"""One-kernel serving (rcmarl_tpu.ops.pallas_serve): the fused
forward + key-derivation + sample Pallas program vs the XLA serve arm.

The bitwise contract (interpret mode on this CPU host): probabilities
AND action streams from ONE fused launch are pinned BITWISE against
the XLA :func:`~rcmarl_tpu.serve.engine.serve_block` /
:func:`~rcmarl_tpu.serve.fleet.fleet_block` chains across the
{sample, greedy} x {f32, bf16-dot} x {solo, fleet} matrix, including
batch sizes that do NOT divide the kernel's tile height (the exact-grid
rule) and an odd action fan-out (the threefry odd-counter padding
path). The heavier cells (bf16, the 96-row batch) ride the slow marker
with the rest of the interpret-mode kernel matrix; real lowerings ride
the queued TPU session (scripts/tpu_session.sh step 12), and the
HBM-traffic claim is carried by the AUDIT.jsonl ``serve_path`` rows
(lint --cost), whose BlockSpec arithmetic is pinned here.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.ops.pallas_serve import (
    SERVE_IMPLS,
    _tile_rows,
    fused_fleet_block,
    fused_serve_block,
    fused_serve_dma_bytes,
    resolve_serve_impl,
)
from rcmarl_tpu.serve.engine import (
    ServeEngine,
    serve_block,
    stack_actor_rows,
)
from rcmarl_tpu.serve.fleet import fleet_block, fleet_stack
from rcmarl_tpu.training.trainer import init_train_state
from rcmarl_tpu.utils.checkpoint import save_checkpoint


def tiny_cfg(**overrides):
    base = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        n_episodes=4,
        n_ep_fixed=2,
        max_ep_len=4,
        n_epochs=2,
        H=1,
    )
    base.update(overrides)
    return Config(**base)


CFG = tiny_cfg()
BLOCK = stack_actor_rows(init_train_state(CFG, jax.random.PRNGKey(0)).params, CFG)
KEY = jax.random.PRNGKey(9)


def _obs(cfg, batch, seed=5):
    return jax.random.normal(
        jax.random.PRNGKey(seed), (batch, cfg.n_agents, cfg.obs_dim)
    )


def _assert_bitwise(cfg, block, obs, key, mode, block_b=128):
    fused_a, fused_p = fused_serve_block(
        cfg, block, obs, key, mode=mode, block_b=block_b, interpret=True
    )
    ref_a, ref_p = serve_block(cfg, block, obs, key, mode=mode)
    np.testing.assert_array_equal(np.asarray(fused_a), np.asarray(ref_a))
    np.testing.assert_array_equal(np.asarray(fused_p), np.asarray(ref_p))


class TestFusedSoloParity:
    @pytest.mark.parametrize("mode", ["sample", "greedy"])
    def test_bitwise_vs_xla_serve_block(self, mode):
        """The headline contract: actions AND probs from ONE fused
        launch are bitwise the XLA chain's, on the default f32 arm."""
        _assert_bitwise(CFG, BLOCK, _obs(CFG, 6), KEY, mode)

    def test_batch_not_dividing_tile_stays_bitwise(self):
        """A prime batch (7) forces a 1-row tile via the exact-grid
        rule — per-request keys must still use the GLOBAL request
        index, so every row stays bitwise across grid steps."""
        _assert_bitwise(CFG, BLOCK, _obs(CFG, 7), KEY, "sample", block_b=4)

    # ~8s — tier-1 870s wall-budget shed; the default odd-fanout parity
    # pins stay fast
    @pytest.mark.slow
    def test_even_action_fanout_stays_bitwise(self):
        """n_actions=4 exercises the even threefry counter split (the
        default 5 covers the odd zero-padded path)."""
        cfg = tiny_cfg(n_actions=4)
        block = stack_actor_rows(
            init_train_state(cfg, jax.random.PRNGKey(0)).params, cfg
        )
        _assert_bitwise(cfg, block, _obs(cfg, 6), KEY, "sample")

    @pytest.mark.slow
    @pytest.mark.parametrize("mode", ["sample", "greedy"])
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_matrix_dtype_by_mode_b96(self, mode, dtype):
        """The full interpret-mode matrix cell: a multi-tile batch (96
        rows, 32-row tiles) on both compute dtypes. bf16 parity holds
        BITWISE because both arms run the identical op sequence (one
        ``batch_probs`` core) — there is no second implementation to
        round differently."""
        cfg = tiny_cfg(compute_dtype=dtype)
        block = stack_actor_rows(
            init_train_state(cfg, jax.random.PRNGKey(0)).params, cfg
        )
        _assert_bitwise(cfg, block, _obs(cfg, 96), KEY, mode, block_b=32)


class TestFusedFleetParity:
    def _fleet(self, cfg, n=2):
        return fleet_stack(
            [
                stack_actor_rows(
                    init_train_state(cfg, jax.random.PRNGKey(f)).params, cfg
                )
                for f in range(n)
            ]
        )

    def test_bitwise_vs_xla_fleet_block(self):
        fleet = self._fleet(CFG)
        obs = _obs(CFG, 6)
        route = jnp.array([0, 1, 1, 0, 1, 0], jnp.int32)
        fused_a, fused_p = fused_fleet_block(
            CFG, fleet, obs, KEY, route, interpret=True
        )
        ref_a, ref_p = fleet_block(CFG, fleet, obs, KEY, route)
        np.testing.assert_array_equal(np.asarray(fused_a), np.asarray(ref_a))
        np.testing.assert_array_equal(np.asarray(fused_p), np.asarray(ref_p))

    def test_routed_member_bitwise_vs_its_solo_serve(self):
        """The transitive pin: a request routed to member f samples
        exactly what f would serve SOLO through the XLA arm — fleet
        serving of one member is indistinguishable from solo serving
        it, fused or not."""
        fleet = self._fleet(CFG)
        obs = _obs(CFG, 6)
        route = jnp.arange(6, dtype=jnp.int32) % 2
        fused_a, fused_p = fused_fleet_block(
            CFG, fleet, obs, KEY, route, interpret=True
        )
        for f in range(2):
            solo = stack_actor_rows(
                init_train_state(CFG, jax.random.PRNGKey(f)).params, CFG
            )
            ref_a, ref_p = serve_block(CFG, solo, obs, KEY)
            idx = np.nonzero(np.asarray(route) == f)[0]
            np.testing.assert_array_equal(
                np.asarray(fused_a)[idx], np.asarray(ref_a)[idx]
            )
            np.testing.assert_array_equal(
                np.asarray(fused_p)[idx], np.asarray(ref_p)[idx]
            )


class TestServeImplPolicy:
    def test_auto_resolves_by_platform(self):
        assert resolve_serve_impl("auto", platform="tpu") == "pallas"
        assert resolve_serve_impl("auto", platform="cpu") == "xla"

    def test_explicit_arms_pass_through(self):
        for impl in SERVE_IMPLS[1:]:
            assert resolve_serve_impl(impl, platform="tpu") == impl

    def test_unknown_impl_is_loud(self):
        with pytest.raises(ValueError, match="serve_impl"):
            resolve_serve_impl("vectorized")

    def test_engine_fused_arm_serves_xla_actions(self, tmp_path):
        """ServeEngine(serve_impl='pallas_interpret') is bitwise the
        default XLA engine on the same checkpoint — the arm is a
        program choice, never a behavior choice."""
        path = tmp_path / "checkpoint.npz"
        save_checkpoint(path, init_train_state(CFG, jax.random.PRNGKey(0)), CFG)
        obs = _obs(CFG, 6)
        a_ref, p_ref = ServeEngine(path).serve(obs, step=0)
        a_fused, p_fused = ServeEngine(
            path, serve_impl="pallas_interpret"
        ).serve(obs, step=0)
        np.testing.assert_array_equal(np.asarray(a_fused), np.asarray(a_ref))
        np.testing.assert_array_equal(np.asarray(p_fused), np.asarray(p_ref))


class TestDmaLedgerArithmetic:
    def test_tile_rows_exact_grid(self):
        assert _tile_rows(96, 128) == 96
        assert _tile_rows(96, 32) == 32
        assert _tile_rows(7, 4) == 1  # prime batch: 1-row tiles
        assert _tile_rows(12, 5) == 4  # largest divisor <= block_b

    def test_bytes_are_exact_blockspec_sums(self):
        """The ledger row's bytes are deterministic arithmetic over the
        kernel's BlockSpecs — recompute one cell by hand."""
        cfg = CFG
        N, A = cfg.n_agents, cfg.n_actions
        dims = [cfg.obs_dim, *cfg.hidden, A]
        B, bb = 96, 32
        params = sum(
            (i * o + o) * 4.0 for i, o in zip(dims[:-1], dims[1:])
        ) * N
        expect = (
            B * N * dims[0] * 4.0  # obs read once
            + params * (B // bb)  # block re-DMAd per tile
            + B * N * 4.0  # actions
            + B * N * A * 4.0  # probs
            + 8.0 * (B // bb)  # key words per tile
        )
        got = fused_serve_dma_bytes(cfg, B, mode="sample", block_b=bb)
        assert got == expect

    def test_greedy_drops_key_traffic_and_fleet_adds_route(self):
        base = fused_serve_dma_bytes(CFG, 96, mode="sample", block_b=32)
        greedy = fused_serve_dma_bytes(CFG, 96, mode="greedy", block_b=32)
        assert base - greedy == 8.0 * 3  # key words per tile, 3 tiles
        fleet = fused_serve_dma_bytes(
            CFG, 96, mode="sample", n_members=2, block_b=32
        )
        assert fleet > base  # F x the param stack + the route read
