"""Pipelined gossip fleets (rcmarl_tpu.parallel.gala).

Four contracts:

1. **Degenerate pins** — the composed trainer IS its pieces at the
   degenerate corners, leaf-for-leaf bitwise: ``pipeline_depth=0`` +
   ``gossip_every=0`` is the independent seed-axis run
   (``train_parallel``), ``replicas=1`` is the solo pipelined trainer
   (``train_pipelined``). Delegation makes these hold by construction;
   the pins here are the regression net against that delegation ever
   being replaced by a drifting twin loop.
2. **Composed guards, exact counters** — a scripted window fault on ONE
   replica's actor tier burns that replica's redraw/skip budget alone
   (per-replica counters exact), and a skipping replica sits out the
   next mix (exclusion) or enters sticky quarantine with
   streak-counted readmission — the solo pipeline's and the gossip
   trainer's fault machinery composing without interference.
3. **Merged surface** — one ``df.attrs`` carries pipeline + guard +
   gossip + canary counter families and :func:`gala_summary` renders
   the ONE line the CI smoke cell greps.
4. **Config contract** — the composed knobs validate loudly
   (tests/test_pipeline.py pins the depth<=gossip_every rule) and
   round-trip through the checkpoint JSON.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rcmarl_tpu.config import Config
from rcmarl_tpu.lint.configs import tiny_cfg
from rcmarl_tpu.parallel.gala import gala_summary, train_gala
from rcmarl_tpu.parallel.gossip import replica_seeds
from rcmarl_tpu.parallel.seeds import train_parallel
from rcmarl_tpu.pipeline.trainer import train_pipelined


def _assert_trees_bitwise(a, b, unstack: bool = False):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        la = np.asarray(la)
        if unstack:
            la = la[0]
        np.testing.assert_array_equal(la, np.asarray(lb))


def _bomb_replica(target_r: int, target_b: int, persistent: bool):
    """A scripted composed-seam fault: NaN-bomb replica ``target_r``'s
    rollout window at global block ``target_b`` (every attempt when
    persistent, only the first draw when transient)."""

    def window_fault(r, b, attempt, fresh, m):
        if r == target_r and b == target_b and (persistent or attempt == 0):
            fresh = jax.tree.map(
                lambda l: (
                    jnp.full_like(l, jnp.nan)
                    if jnp.issubdtype(jnp.asarray(l).dtype, jnp.floating)
                    else l
                ),
                fresh,
            )
        return fresh, m

    return window_fault


class TestDegeneratePins:
    def test_depth0_every0_is_bitwise_train_parallel(self):
        """R replicas, no pipeline, no mixing ≡ the independent
        seed-axis run, leaf for leaf (params AND the delegated
        degenerate pipeline attrs)."""
        cfg = tiny_cfg(replicas=2, pipeline_depth=0, gossip_every=0,
                       gossip_H=0, gossip_degree=2)
        states, df = train_gala(cfg, n_episodes=4)
        ref_states, _ = train_parallel(
            tiny_cfg(), seeds=list(replica_seeds(cfg)), n_blocks=2
        )
        _assert_trees_bitwise(states, ref_states)
        p = df.attrs["pipeline"]
        assert p["depth"] == 0 and p["staleness"] == [0, 0]
        assert p["publishes"] == 2 and p["rejects"] == 0

    def test_depth2_R1_is_bitwise_train_pipelined(self):
        """A one-replica fleet ≡ the solo pipelined trainer with the
        replica axis prepended (a self-mix is an identity)."""
        cfg = tiny_cfg(replicas=1, pipeline_depth=2, gossip_every=2,
                       gossip_degree=1, gossip_H=0)
        g_states, g_df = train_gala(cfg)
        p_states, p_df = train_pipelined(tiny_cfg(pipeline_depth=2))
        _assert_trees_bitwise(g_states, p_states, unstack=True)
        assert (
            g_df.attrs["pipeline"]["staleness"]
            == p_df.attrs["pipeline"]["staleness"]
        )
        g = g_df.attrs["gossip"]
        assert g["replicas"] == 1 and g["rounds"] == 0

    def test_window_fault_rejected_at_depth0(self):
        with pytest.raises(ValueError, match="window_fault"):
            train_gala(
                tiny_cfg(replicas=2, pipeline_depth=0, gossip_H=0,
                         gossip_degree=2),
                window_fault=lambda r, b, a, f, m: (f, m),
            )

    def test_replicas_zero_rejected(self):
        with pytest.raises(ValueError, match="replicas"):
            train_gala(tiny_cfg(pipeline_depth=2))


class TestComposedGuards:
    def test_transient_bomb_burns_one_replicas_redraw_only(self):
        """A transient NaN window on replica 1 costs exactly ONE redraw
        on replica 1 and nothing anywhere else — no skip, no learner
        retry, no exclusion, every block published; and the merged
        attrs surface + summary line carry all four counter families."""
        cfg = tiny_cfg(
            replicas=2, pipeline_depth=2, gossip_every=2, gossip_H=0,
            gossip_graph="full", canary_band=0.5,
        )
        states, df = train_gala(
            cfg, guard=True, max_retries=2,
            window_fault=_bomb_replica(1, 1, persistent=False),
        )
        g = df.attrs["guard"]
        assert g["replica_redraws"] == [0, 1]
        assert g["replica_skipped"] == [0, 0]
        assert g["replica_retries"] == [0, 0]
        go = df.attrs["gossip"]
        assert go["rounds"] == 1  # 3 blocks, mix after block 2
        assert go["excluded"] == 0 and go["rollbacks"] == 0
        p = df.attrs["pipeline"]
        # every replica publishes every block + one force republish
        # at the mix round
        assert p["rejects"] == 0
        assert p["publishes"] == 2 * p["blocks"] + 2
        c = df.attrs["canary"]
        assert c["deploys"] >= 1 and c["deploy_healthy"]
        line = gala_summary(df.attrs)
        assert "gala: 2 replicas" in line
        assert "gossip: 1 rounds" in line and "canary:" in line
        assert jax.tree.leaves(states.params)[0].shape[0] == 2

    def test_persistent_bomb_skips_and_excludes_one_replica(self):
        """A persistent NaN window on replica 0 terminates in bounded
        redraws then a SKIP on replica 0 alone (block-level containment,
        params rolled back, nothing published for that block), and the
        skipping replica sits out the next mix — one exclusion, zero
        gossip rollbacks (the pipeline guard already owned the fault)."""
        cfg = tiny_cfg(
            replicas=2, pipeline_depth=2, gossip_every=2, gossip_H=0,
            gossip_graph="full",
        )
        _, df = train_gala(
            cfg, guard=True, max_retries=2,
            window_fault=_bomb_replica(0, 1, persistent=True),
        )
        g = df.attrs["guard"]
        assert g["replica_redraws"] == [2, 0]
        assert g["replica_skipped"] == [1, 0]
        go = df.attrs["gossip"]
        assert go["excluded"] == 1 and go["rollbacks"] == 0
        assert go["replica_healthy"] == [True, True]  # params stay finite
        p = df.attrs["pipeline"]
        # replica 0's skipped block published nothing
        assert p["publishes"] == 2 * p["blocks"] + 2 - 1

    @pytest.mark.slow
    def test_sticky_quarantine_and_streak_readmission(self):
        """With ``readmit_after=1`` a skipping replica enters sticky
        quarantine (out of EVERY later mix, not just the next), then
        re-enters after one consecutive healthy segment — counters
        exact, end state fully readmitted."""
        cfg = tiny_cfg(
            replicas=2, pipeline_depth=2, gossip_every=2, gossip_H=0,
            gossip_graph="full", n_episodes=12,
        )
        _, df = train_gala(
            cfg, guard=True, max_retries=1, readmit_after=1,
            window_fault=_bomb_replica(1, 0, persistent=True),
        )
        go = df.attrs["gossip"]
        # segment 1: replica 1 skips -> quarantined (1 exclusion at the
        # round-1 mix); segment 2: healthy streak hits readmit_after
        # BEFORE the round-2 mix -> readmitted, mixes again
        assert df.attrs["guard"]["replica_skipped"] == [0, 1]
        assert go["readmitted"] == 1
        assert go["quarantined"] == [0, 0]
        assert go["excluded"] == 1
        assert go["rounds"] == 3  # 6 blocks / gossip_every=2


class TestConfigContract:
    def test_composed_config_json_roundtrip(self):
        from rcmarl_tpu.utils.checkpoint import (
            _config_to_json,
            config_from_json,
        )

        cfg = tiny_cfg(
            replicas=2, pipeline_depth=2, gossip_every=2, gossip_H=0,
            gossip_graph="full", canary_band=0.25, canary_blocks=2,
        )
        assert config_from_json(_config_to_json(cfg)) == cfg
