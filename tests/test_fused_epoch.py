"""One-kernel epoch (ISSUE 13): the fused phase-II Pallas consensus and
the fit-scan kernel vs their XLA reference arms.

The bitwise contract (interpret mode on this CPU host): the SANITIZE
matrix — {regular, ragged} x {clean, drop/NaN/stale/flip/inf faulted} x
{H=0, H>0, traced H} x mixed casts — is pinned leaf-for-leaf BITWISE
against ``consensus_impl='xla'``; plain (sanitize-off) cells keep the
leaf kernel's historical allclose-at-f32-rounding contract (the
``jnp.mean`` epilogue's bits are XLA-fusion-context-dependent — see
ops/pallas_consensus.py). ``corrupt_p > 0`` plans are the documented
fallback to the stacked XLA arm and must be bitwise trivially. The
fit-scan kernel's fitted rows are pinned bitwise against the XLA scan
for every schedule shape. Real lowerings ride the queued TPU session;
the HBM-traffic claim is carried by the AUDIT.jsonl
``consensus_trunk``/``fit_scan`` rows (tests below + ``lint --cost``).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.faults import FaultPlan, apply_link_faults_flat
from rcmarl_tpu.models.mlp import init_stacked_mlp
from rcmarl_tpu.ops.aggregation import resilient_aggregate
from rcmarl_tpu.ops.pallas_consensus import (
    draw_fault_fields,
    fused_pair_consensus,
    kernel_compatible_plan,
)
from rcmarl_tpu.training.update import (
    _pair_block,
    _pair_segments,
    _pair_trunk_split,
)

RAGGED = ((0, 1, 2, 3), (1, 2, 3, 0), (2, 3, 0), (3, 0, 1))


def _setup(n_agents=3, in_nodes=None, hidden=(4, 4)):
    cfg = Config(
        n_agents=n_agents,
        agent_roles=(Roles.COOPERATIVE,) * n_agents,
        in_nodes=in_nodes or circulant_in_nodes(n_agents, n_agents),
        nrow=3,
        ncol=3,
        H=0,
    )
    critic = init_stacked_mlp(
        jax.random.PRNGKey(0), n_agents, cfg.obs_dim, hidden, 1
    )
    tr = init_stacked_mlp(
        jax.random.PRNGKey(1), n_agents, cfg.sa_dim, hidden, 1
    )
    return cfg, critic, tr


def _run_pair(cfg, critic, tr, plan, sanitize, H):
    """(xla reference, fused kernel) trunk aggregates, both jitted —
    the pin target is jitted-program vs jitted-program (the epoch's
    real comparison), not eager dispatch."""
    segs = _pair_segments(critic, tr)
    n_trunk, split = _pair_trunk_split(segs)
    pair = _pair_block(critic, tr)
    carry = _pair_block(
        jax.tree.map(lambda l: l * 0.7, critic),
        jax.tree.map(lambda l: l * 0.7, tr),
    )
    in_arr, valid = cfg.padded_in_nodes()
    in_np = jnp.asarray(np.asarray(in_arr))
    valid_np = None if valid is None else jnp.asarray(np.asarray(valid))
    fkey = jax.random.PRNGKey(99)
    N, n_in = cfg.n_agents, cfg.n_in
    active = plan is not None and plan.active
    stale_live = active and float(plan.stale_p) > 0.0

    @jax.jit
    def ref(pair, carry, fkey):
        nbr = pair[in_np][:, :, :n_trunk]
        if active:
            snbr = carry[in_np][:, :, :n_trunk] if stale_live else nbr
            tsegs = tuple(s for s in segs if s[2] < n_trunk)
            nbr = apply_link_faults_flat(fkey, nbr, snbr, plan, tsegs)
        if valid_np is None:
            return jax.vmap(
                lambda v: resilient_aggregate(
                    v, H, "xla", n_agents=N, sanitize=sanitize
                )
            )(nbr)
        return jax.vmap(
            lambda v, va: resilient_aggregate(
                v, H, "xla", valid=va, n_agents=N, sanitize=sanitize
            )
        )(nbr, valid_np)

    @jax.jit
    def fused(pair, carry, fkey):
        fields = (
            draw_fault_fields(fkey, plan, N, n_in, segs) if active else None
        )
        return fused_pair_consensus(
            pair[:, :n_trunk],
            H,
            in_nodes=in_arr,
            tree_split=split,
            valid=valid,
            sanitize=sanitize,
            plan=plan if active else None,
            stale=carry[:, :n_trunk] if stale_live else None,
            fields=fields,
            interpret=True,
        )

    return np.asarray(ref(pair, carry, fkey)), np.asarray(
        fused(pair, carry, fkey)
    )


FAULTED = FaultPlan(drop_p=0.3, nan_p=0.2, stale_p=0.2, flip_p=0.2, inf_p=0.2)


class TestFusedConsensusKernel:
    @pytest.mark.parametrize(
        "plan,H",
        [
            (None, 0),
            (None, 1),
            (FAULTED, 1),
            (FaultPlan(stale_p=0.5), 0),
        ],
    )
    def test_sanitize_matrix_bitwise_regular(self, plan, H):
        cfg, critic, tr = _setup()
        want, got = _run_pair(cfg, critic, tr, plan, True, H)
        np.testing.assert_array_equal(got, want)

    @pytest.mark.parametrize("plan", [None, FAULTED])
    def test_sanitize_matrix_bitwise_ragged(self, plan):
        cfg, critic, tr = _setup(4, RAGGED)
        want, got = _run_pair(cfg, critic, tr, plan, True, 1)
        np.testing.assert_array_equal(got, want)

    def test_traced_h_bitwise(self):
        cfg, critic, tr = _setup()
        want, got = _run_pair(
            cfg, critic, tr, FaultPlan(drop_p=0.3), True,
            jnp.asarray(1, jnp.int32),
        )
        np.testing.assert_array_equal(got, want)
        want, got = _run_pair(
            cfg, critic, tr, None, False, jnp.asarray(1, jnp.int32)
        )
        np.testing.assert_allclose(got, want, atol=1e-6)

    @pytest.mark.parametrize("H", [0, 1])
    def test_plain_cells_allclose(self, H):
        """The sanitize-off contract is the leaf kernel's historical
        one: allclose at f32 rounding (the jnp.mean epilogue's bits are
        fusion-context-dependent), never bitwise-required."""
        cfg, critic, tr = _setup()
        want, got = _run_pair(cfg, critic, tr, None, False, H)
        np.testing.assert_allclose(got, want, atol=1e-6)

    def test_masked_plain_allclose(self):
        cfg, critic, tr = _setup(4, RAGGED)
        want, got = _run_pair(cfg, critic, tr, None, False, 1)
        np.testing.assert_allclose(got, want, atol=1e-6)

    @pytest.mark.slow
    def test_multi_tile_wide_block(self):
        """> 1 grid tile (wide trunks) under faults + sanitize + H=2."""
        cfg, critic, tr = _setup(5, circulant_in_nodes(5, 5), hidden=(32, 32))
        want, got = _run_pair(
            cfg, critic, tr, FaultPlan(drop_p=0.2, stale_p=0.3, inf_p=0.1),
            True, 2,
        )
        np.testing.assert_array_equal(got, want)

    def test_corrupt_plan_rejected_by_kernel(self):
        cfg, critic, tr = _setup()
        with pytest.raises(ValueError, match="corrupt_p"):
            _run_pair(cfg, critic, tr, FaultPlan(corrupt_p=0.5), True, 1)
        assert not kernel_compatible_plan(FaultPlan(corrupt_p=0.5))
        assert kernel_compatible_plan(FAULTED)
        assert kernel_compatible_plan(None)


class TestFusedEpoch:
    """Epoch-level pins: consensus_impl='pallas_fused_interpret' vs
    'xla' through the REAL epoch program (phase I + II), leaf for
    leaf."""

    KW = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE, Roles.COOPERATIVE, Roles.GREEDY),
        in_nodes=circulant_in_nodes(3, 3),
        H=1,
        nrow=3,
        ncol=3,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=2,
        buffer_size=16,
        hidden=(8, 8),
        coop_fit_steps=1,
        adv_fit_epochs=2,
        adv_fit_batch=4,
        batch_size=4,
        n_episodes=4,
        netstack=True,
        consensus_sanitize=True,
        fault_plan=FaultPlan(drop_p=0.2, nan_p=0.1, stale_p=0.2),
    )

    @staticmethod
    def _epoch_inputs(cfg):
        from rcmarl_tpu.training.buffer import update_batch
        from rcmarl_tpu.training.rollout import rollout_block
        from rcmarl_tpu.training.trainer import init_train_state, make_env
        from rcmarl_tpu.training.update import team_average_reward

        state = init_train_state(cfg, jax.random.PRNGKey(0))
        env = make_env(cfg)
        key = jax.random.PRNGKey(3)
        fresh, _ = jax.jit(
            lambda s, k: rollout_block(
                cfg, env, s.params, s.desired, k, s.initial
            )
        )(state, key)
        batch = jax.jit(update_batch)(state.buffer, fresh)
        return state, batch, team_average_reward(cfg, batch.r), key

    def _pin_epoch(self, kw, spec_from=None):
        from rcmarl_tpu.training.update import critic_tr_epoch, spec_from_config

        cfg_x = Config(**kw, consensus_impl="xla")
        cfg_f = Config(**kw, consensus_impl="pallas_fused_interpret")
        state, batch, r_coop, key = self._epoch_inputs(cfg_x)
        carry = (
            state.params.critic,
            state.params.tr,
            state.params.critic_local,
        )
        outs = []
        for cfg in (cfg_x, cfg_f):
            spec = spec_from_config(cfg) if spec_from else None
            outs.append(
                jax.jit(
                    lambda c, b, rc, k, cfg=cfg, spec=spec: critic_tr_epoch(
                        cfg, c, b, rc, k, spec
                    )
                )(carry, batch, r_coop, key)
            )
        for a, b in zip(jax.tree.leaves(outs[0]), jax.tree.leaves(outs[1])):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_epoch_bitwise_faulted_sanitize_mixed(self):
        self._pin_epoch(self.KW)

    # ~15s — tier-1 870s wall-budget shed; the non-ragged epoch pins
    # above stay fast and ci_tier1.sh's smoke cell covers the wire-up
    @pytest.mark.slow
    def test_epoch_bitwise_ragged(self):
        kw = dict(self.KW)
        kw.update(
            n_agents=4,
            agent_roles=(Roles.COOPERATIVE,) * 2
            + (Roles.GREEDY, Roles.MALICIOUS),
            in_nodes=RAGGED,
        )
        self._pin_epoch(kw)

    @pytest.mark.slow
    def test_epoch_bitwise_traced_spec(self):
        self._pin_epoch(self.KW, spec_from=True)

    @pytest.mark.slow
    def test_epoch_bitwise_h0(self):
        kw = dict(self.KW)
        kw["H"] = 0
        self._pin_epoch(kw)

    @pytest.mark.slow
    def test_corrupt_plan_falls_back_to_stacked_xla_bitwise(self):
        kw = dict(self.KW)
        kw["fault_plan"] = FaultPlan(corrupt_p=0.5, drop_p=0.2)
        self._pin_epoch(kw)

    def test_consensus_block_entry_bitwise(self):
        from rcmarl_tpu.training.update import consensus_block

        cfg_x = Config(**self.KW, consensus_impl="xla")
        cfg_f = Config(**self.KW, consensus_impl="pallas_fused_interpret")
        state, batch, _, key = self._epoch_inputs(cfg_x)
        carry = (
            state.params.critic,
            state.params.tr,
            state.params.critic_local,
        )
        a = consensus_block(cfg_x, carry, batch, key)
        b = consensus_block(cfg_f, carry, batch, key)
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    @pytest.mark.slow
    def test_train_block_bitwise_and_guarded_diag(self):
        """Whole train blocks (rollout + epochs + actor + buffer) on
        the fused arm, including the guarded with_diag path whose fault
        counters come from the diagnostics-only gathered view."""
        from rcmarl_tpu.training.trainer import init_train_state, train_block

        cfg_x = Config(**self.KW, consensus_impl="xla")
        cfg_f = Config(**self.KW, consensus_impl="pallas_fused_interpret")
        s0 = init_train_state(cfg_x, jax.random.PRNGKey(0))
        sx, mx = train_block(cfg_x, s0)
        sf, mf = train_block(cfg_f, s0)
        for a, b in zip(
            jax.tree.leaves(sx.params), jax.tree.leaves(sf.params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            np.asarray(mx.true_team_returns), np.asarray(mf.true_team_returns)
        )
        from rcmarl_tpu.training.update import update_block

        _, batch, _, key = self._epoch_inputs(cfg_x)
        px, dx = update_block(
            cfg_x, s0.params, batch, batch, key, with_diag=True
        )
        pf, df = update_block(
            cfg_f, s0.params, batch, batch, key, with_diag=True
        )
        assert int(dx.nonfinite) == int(df.nonfinite)
        assert int(dx.deficit) == int(df.deficit)


class TestPallasFitScan:
    def _rows(self, B=48):
        from rcmarl_tpu.models.mlp import netstack_stack

        W = 9
        critic = init_stacked_mlp(jax.random.PRNGKey(0), 3, W, (6, 6), 1)
        tr = init_stacked_mlp(jax.random.PRNGKey(1), 3, W, (6, 6), 1)
        rows = netstack_stack(critic, tr)
        keys = jnp.stack(
            [
                jax.random.split(jax.random.PRNGKey(5), 3),
                jax.random.split(jax.random.PRNGKey(6), 3),
            ]
        )
        x_rows = jax.random.normal(jax.random.PRNGKey(2), (2, B, W))
        tgt = jax.random.normal(jax.random.PRNGKey(3), (2, 3, B, 1))
        mask = (jnp.arange(B) < B - 10).astype(jnp.float32)
        return rows, keys, x_rows, tgt, mask

    @pytest.mark.parametrize(
        "epochs,bs,shuffle,assume_valid",
        [(3, 16, True, False), (4, 48, False, False), (2, 16, True, True)],
    )
    def test_fitted_rows_bitwise_vs_xla_scan(
        self, epochs, bs, shuffle, assume_valid
    ):
        from rcmarl_tpu.models.mlp import mlp_forward
        from rcmarl_tpu.ops.fit import FitSchedule, fused_fit_scan
        from rcmarl_tpu.ops.pallas_fit import pallas_fit_scan

        rows, keys, x_rows, tgt, mask = self._rows()
        if assume_valid:
            mask = jnp.ones_like(mask)
        sched = FitSchedule(
            epochs=epochs,
            batch_size=bs,
            shuffle=shuffle,
            assume_valid=assume_valid,
        )
        fwd = lambda p, x: mlp_forward(p, x)
        w_p, w_l = jax.jit(
            lambda k, p, x, t, m: fused_fit_scan(
                k, p, fwd, x, t, m, sched, 0.01
            )
        )(keys, rows, x_rows, tgt, mask)
        g_p, g_l = jax.jit(
            lambda k, p, x, t, m: pallas_fit_scan(
                k, p, fwd, x, t, m, sched, 0.01, interpret=True
            )
        )(keys, rows, x_rows, tgt, mask)
        for a, b in zip(jax.tree.leaves(w_p), jax.tree.leaves(g_p)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # the first-epoch loss is a logging value: allclose contract
        np.testing.assert_allclose(
            np.asarray(g_l), np.asarray(w_l), atol=1e-6
        )

    @pytest.mark.slow
    def test_fitstack_pallas_epoch_bitwise(self):
        """Config.fitstack='pallas_interpret' through the real trainer
        (every adversary flavor live) vs the XLA fused scan."""
        from rcmarl_tpu.training.trainer import init_train_state, train_block

        kw = dict(TestFusedEpoch.KW)
        kw.pop("fault_plan")
        kw.pop("consensus_sanitize")
        kw.update(
            n_agents=4,
            agent_roles=(Roles.COOPERATIVE,) * 2
            + (Roles.GREEDY, Roles.MALICIOUS),
            in_nodes=RAGGED,
        )
        cfg_x = Config(**kw, fitstack=True)
        cfg_p = Config(**kw, fitstack="pallas_interpret")
        s0 = init_train_state(cfg_x, jax.random.PRNGKey(1))
        sx, _ = train_block(cfg_x, s0)
        sp, _ = train_block(cfg_p, s0)
        for a, b in zip(
            jax.tree.leaves(sx.params), jax.tree.leaves(sp.params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestOneRavelPath:
    """Satellite: the pallas tree aggregation rides the ONE shared
    ravel path of resilient_aggregate_tree (apply/one_block), so
    per_leaf is an honest kernel comparison arm and mixed dtypes fall
    back instead of crashing."""

    def _tree(self, n_in=5):
        ks = jax.random.split(jax.random.PRNGKey(3), 4)
        return (
            (
                jax.random.normal(ks[0], (n_in, 6, 8)),
                jax.random.normal(ks[1], (n_in, 8)),
            ),
            (
                jax.random.normal(ks[2], (n_in, 8, 8)),
                jax.random.normal(ks[3], (n_in, 8)),
            ),
        )

    def test_flat_vs_per_leaf_bitwise_on_kernel(self):
        from rcmarl_tpu.ops.aggregation import resilient_aggregate_tree

        tree = self._tree()
        flat = resilient_aggregate_tree(
            tree, 1, impl="pallas_interpret", layout="flat"
        )
        per_leaf = resilient_aggregate_tree(
            tree, 1, impl="pallas_interpret", layout="per_leaf"
        )
        for a, b in zip(jax.tree.leaves(flat), jax.tree.leaves(per_leaf)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_tree_entry_matches_shared_path(self):
        from rcmarl_tpu.ops.aggregation import resilient_aggregate_tree
        from rcmarl_tpu.ops.pallas_aggregation import (
            fused_resilient_aggregate_tree,
        )

        tree = self._tree()
        a = fused_resilient_aggregate_tree(tree, 1, interpret=True)
        b = resilient_aggregate_tree(tree, 1, impl="pallas_interpret")
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))

    def test_fused_alias_impls_accepted_at_leaf_level(self):
        vals = jax.random.normal(jax.random.PRNGKey(0), (5, 40))
        a = resilient_aggregate(vals, 1, impl="pallas_fused_interpret")
        b = resilient_aggregate(vals, 1, impl="pallas_interpret")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestConfigSurface:
    def test_fused_rejects_netstack_off(self):
        with pytest.raises(ValueError, match="netstack"):
            Config(consensus_impl="pallas_fused", netstack=False)

    def test_fused_accepts_time_varying_graph(self):
        """Lifted PR-13 rejection: time-varying schedules now ride the
        SPARSE one-kernel epoch (the graph is a scalar-prefetch
        operand), so the config surface accepts the combination."""
        cfg = Config(
            consensus_impl="pallas_fused",
            graph_schedule="random_geometric",
            graph_degree=3,
        )
        assert cfg.consensus_impl == "pallas_fused"
        assert cfg.graph_schedule == "random_geometric"

    def test_fitstack_kernel_values_accepted(self):
        for v in ("pallas", "pallas_interpret"):
            assert Config(fitstack=v).fitstack == v
        with pytest.raises(ValueError, match="fitstack"):
            Config(fitstack="pallas_nope")

    def test_cli_fitstack_passthrough(self):
        from rcmarl_tpu.cli import _netstack_value

        assert _netstack_value("pallas") == "pallas"
        assert _netstack_value("pallas_interpret") == "pallas_interpret"
        assert _netstack_value("on") is True
        assert _netstack_value("auto") == "auto"

    def test_corrupt_plan_resolves_to_fallback(self):
        from rcmarl_tpu.training.update import consensus_fused_impl

        cfg = Config(
            consensus_impl="pallas_fused_interpret",
            fault_plan=FaultPlan(corrupt_p=0.5),
        )
        assert consensus_fused_impl(cfg) is None
        assert (
            consensus_fused_impl(
                cfg.replace(fault_plan=FaultPlan(drop_p=0.5))
            )
            == "pallas_fused_interpret"
        )


@pytest.mark.slow
class TestHBMLedgerGate:
    """The ISSUE-13 acceptance invariant, runnable standalone: the
    fused consensus entry's bytes_accessed strictly below the
    two-launch arm's sum at equal (±1%) FLOPs (lint --cost re-derives
    and gates this in CI every run)."""

    def test_fused_gate_holds(self):
        from rcmarl_tpu.lint.cost import (
            FUSED_GATE_PAIRS,
            fused_consensus_cost_rows,
            fused_gate_findings,
        )

        rows, notes, skipped = fused_consensus_cost_rows()
        assert fused_gate_findings(rows, skipped) == []
        by = {r["entry"]: r for r in rows}
        fused = by["consensus_trunk[pallas_fused]"]["metrics"]
        two = by["consensus_trunk[two_launch]"]["metrics"]
        assert fused["bytes_accessed"] < two["bytes_accessed"]
        assert abs(fused["flops"] - two["flops"]) <= 0.01 * two["flops"]
        assert by["consensus_trunk[pallas_fused]"]["bytes_model"] == (
            "pallas-blockspec-dma"
        )

    def test_gate_fires_on_planted_regression(self):
        from rcmarl_tpu.lint.cost import (
            fused_consensus_cost_rows,
            fused_gate_findings,
        )

        rows, _, skipped = fused_consensus_cost_rows()
        for r in rows:
            if r["entry"] == "consensus_trunk[pallas_fused]":
                r["metrics"]["bytes_accessed"] = (
                    1e12  # the kernel "lost" its traffic claim
                )
        findings = fused_gate_findings(rows, skipped)
        assert any(f.rule == "cost-fused-gate" for f in findings)


# ---------------------------------------------------------------------------
# Hypothesis twins for the in-kernel trim/sanitize chain
# ---------------------------------------------------------------------------

try:
    import hypothesis  # noqa: F401

    _HAS_HYPOTHESIS = True
except Exception:  # pragma: no cover
    _HAS_HYPOTHESIS = False

if _HAS_HYPOTHESIS:
    from hypothesis import given, settings
    from hypothesis import strategies as st
    from hypothesis.extra.numpy import arrays

    finite = st.floats(-1e6, 1e6, allow_nan=False, width=32)

    @st.composite
    def poisoned_block_and_h(draw, n=3, m=24):
        """A (N=3, P) message block + a per-link poison pattern applied
        POST-GATHER semantics via an inf/nan fault... here: poison the
        senders' columns directly (arbitrary NaN/±Inf payload content —
        the diverged-neighbor case the sanitize kernel must absorb)."""
        vals = draw(arrays(np.float32, (n, m), elements=finite))
        poison = draw(arrays(np.int8, (n, m), elements=st.integers(0, 3)))
        bombs = np.asarray([0.0, np.nan, np.inf, -np.inf], np.float32)
        vals = np.where(poison > 0, bombs[poison], vals).astype(np.float32)
        H = draw(st.integers(0, 1))
        return vals, H

    @settings(max_examples=25, deadline=None)
    @given(poisoned_block_and_h())
    def test_in_kernel_sanitize_chain_bitwise(case):
        """±Inf sentinels, NaN payloads, and the degree-deficit
        fallback: arbitrary non-finite message content through the
        in-kernel gather + sanitize chain agrees BITWISE with the XLA
        reference composition, and deficits keep the own value."""
        vals, H = case
        n, m = vals.shape
        cfg = Config(
            n_agents=n,
            agent_roles=(Roles.COOPERATIVE,) * n,
            in_nodes=circulant_in_nodes(n, n),
            nrow=3,
            ncol=3,
            H=0,
        )
        in_arr, _ = cfg.padded_in_nodes()
        in_np = jnp.asarray(np.asarray(in_arr))
        msgs = jnp.asarray(vals)

        @jax.jit
        def ref(msgs):
            nbr = msgs[in_np]
            return jax.vmap(
                lambda v: resilient_aggregate(
                    v, H, "xla", n_agents=n, sanitize=True
                )
            )(nbr)

        @jax.jit
        def fused(msgs):
            return fused_pair_consensus(
                msgs,
                H,
                in_nodes=in_arr,
                tree_split=m,
                sanitize=True,
                interpret=True,
            )

        want, got = np.asarray(ref(msgs)), np.asarray(fused(msgs))
        np.testing.assert_array_equal(got, want)
        # degree-deficit: where fewer than 2H+1 finite survive, the
        # aggregate must BE the agent's own value (bit for bit)
        gathered = vals[np.asarray(in_np)]
        survivors = np.isfinite(gathered).sum(axis=1)
        deficit = survivors < 2 * H + 1
        own = vals
        np.testing.assert_array_equal(got[deficit], own[deficit])
