"""Hypothesis twins for the time-varying communication-graph builder.

``random_geometric_in_nodes`` feeds BOTH levels of the resilience
stack — the static replica gossip graph and the per-block agent-level
schedule (``scheduled_in_nodes``) — so its invariants are the safety
preconditions of the trimmed mean everywhere: self-first rows (slot 0
is the only positional slot the aggregation treats specially), exact
regular degree (every neighborhood keeps ``n_in >= 2H+1`` whenever the
degree does), valid distinct indices, and bit-level determinism in the
seed (resumed runs must replay their exact graph sequence).

Pure numpy — no jax import, so these cost the tier-1 budget nothing.
"""

import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import given, settings, strategies as st  # noqa: E402

from rcmarl_tpu.config import (  # noqa: E402
    Config,
    random_geometric_in_nodes,
    scheduled_in_nodes,
)


@st.composite
def graph_case(draw):
    n = draw(st.integers(min_value=1, max_value=24))
    degree = draw(st.integers(min_value=1, max_value=n))
    seed = draw(st.integers(min_value=0, max_value=2**31 - 1))
    return n, degree, seed


@given(graph_case())
@settings(max_examples=60, deadline=None)
def test_rows_are_self_first_regular_and_valid(case):
    n, degree, seed = case
    g = random_geometric_in_nodes(n, degree, seed)
    assert len(g) == n
    for i, row in enumerate(g):
        assert len(row) == degree  # regular: no padding/masking needed
        assert row[0] == i  # self first (the aggregation's own slot)
        assert len(set(row)) == degree  # distinct neighbors
        assert all(0 <= j < n for j in row)


@given(graph_case())
@settings(max_examples=40, deadline=None)
def test_deterministic_in_seed(case):
    n, degree, seed = case
    assert random_geometric_in_nodes(n, degree, seed) == (
        random_geometric_in_nodes(n, degree, seed)
    )
    # tuple seeds (the per-round namespace) are deterministic too
    assert random_geometric_in_nodes(n, degree, (seed, 3)) == (
        random_geometric_in_nodes(n, degree, (seed, 3))
    )


@given(
    st.integers(min_value=0, max_value=3),  # H
    st.integers(min_value=0, max_value=2**20),  # graph_seed
    st.integers(min_value=0, max_value=50),  # block
    st.integers(min_value=1, max_value=5),  # graph_every
)
@settings(max_examples=40, deadline=None)
def test_every_neighborhood_keeps_trim_precondition(H, seed, block, every):
    """For any legal (H, degree) config, EVERY resampled neighborhood
    satisfies n_in >= 2H+1 — the trimmed mean's safety precondition —
    and the self-first layout the consensus kernel keys on survives
    resampling at every block."""
    n = 8
    degree = 2 * H + 1  # the tightest legal degree
    cfg = Config(
        n_agents=n,
        in_nodes=tuple(
            tuple((i + k) % n for k in range(max(degree, 1)))
            for i in range(n)
        ),
        H=H,
        graph_schedule="random_geometric",
        graph_degree=degree,
        graph_seed=seed,
        graph_every=every,
    )
    g = scheduled_in_nodes(cfg, block)
    assert g.shape == (n, degree)
    assert (g[:, 0] == np.arange(n)).all()  # self-first preserved
    for row in g:
        assert len(set(row.tolist())) >= 2 * H + 1
    # cadence: blocks in the same round share the graph bit-for-bit
    same = scheduled_in_nodes(cfg, (block // every) * every)
    np.testing.assert_array_equal(g, same)
