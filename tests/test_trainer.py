"""Trainer-layer tests: buffer semantics, rollout, update block, end-to-end.

Covers the reference behaviors of ``training/train_agents.py`` (SURVEY.md
§3.2-3.3): buffer growth 1000->2000->3000, update-before-trim, block
scheduling, metric definitions, and heterogeneous role updates.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rcmarl_tpu.agents.updates import Batch
from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.training import (
    buffer_init,
    buffer_push_block,
    init_agent_params,
    init_train_state,
    make_env,
    rollout_block,
    train,
    train_scanned,
    update_batch,
    update_block,
)
from rcmarl_tpu.training.update import team_average_reward

SMALL = Config(
    n_episodes=4,
    max_ep_len=5,
    n_ep_fixed=2,
    n_epochs=2,
    buffer_size=20,
    coop_fit_steps=2,
    adv_fit_epochs=2,
    adv_fit_batch=4,
    batch_size=5,
)


def _fresh(cfg, offset=0.0):
    B, N = cfg.block_steps, cfg.n_agents
    return Batch(
        s=jnp.full((B, N, cfg.n_states), offset, jnp.float32),
        ns=jnp.full((B, N, cfg.n_states), offset + 0.5, jnp.float32),
        a=jnp.zeros((B, N, 1), jnp.float32),
        r=jnp.full((B, N, 1), offset, jnp.float32),
        mask=jnp.ones((B,), jnp.float32),
    )


class TestBuffer:
    def test_growth_and_trim(self):
        """Reference growth: batch sees 1000 -> 2000 -> 3000 valid rows
        (scaled down); kept buffer FIFO-overwrites once full."""
        cfg = SMALL  # block=10, buffer=20
        buf = buffer_init(cfg.buffer_size, cfg.n_agents, cfg.n_states)
        seen = []
        for k in range(3):
            fresh = _fresh(cfg, float(k))
            batch = update_batch(buf, fresh)
            seen.append(int(jnp.sum(batch.mask)))
            buf = buffer_push_block(buf, fresh)
        assert seen == [10, 20, 30]
        assert int(buf.count) == 20
        # After 3 pushes into capacity 20, rows from block 0 are overwritten
        vals = np.unique(np.asarray(buf.r))
        assert 0.0 not in vals and {1.0, 2.0} <= set(vals.tolist())

    def test_push_block_larger_than_capacity(self):
        """A block bigger than the ring keeps its newest rows (reference
        trim semantics), not an unspecified duplicate-scatter result."""
        cfg = SMALL
        buf = buffer_init(4, cfg.n_agents, cfg.n_states)  # cap 4 < block 10
        fresh = _fresh(cfg)
        fresh = fresh._replace(
            r=jnp.arange(cfg.block_steps, dtype=jnp.float32)[:, None, None]
            * jnp.ones((1, cfg.n_agents, 1))
        )
        buf = buffer_push_block(buf, fresh)
        assert int(buf.count) == 4
        np.testing.assert_array_equal(
            np.asarray(buf.r[:, 0, 0]), np.array([6.0, 7.0, 8.0, 9.0])
        )

    def test_update_batch_masks_empty_rows(self):
        cfg = SMALL
        buf = buffer_init(cfg.buffer_size, cfg.n_agents, cfg.n_states)
        batch = update_batch(buf, _fresh(cfg))
        # kept region invalid, fresh region valid
        assert np.array_equal(
            np.asarray(batch.mask),
            np.concatenate([np.zeros(20), np.ones(10)]),
        )


class TestRollout:
    def test_shapes_and_bounds(self):
        cfg = SMALL
        env = make_env(cfg)
        params = init_agent_params(jax.random.PRNGKey(0), cfg)
        desired = jnp.zeros((cfg.n_agents, 2), jnp.int32)
        fresh, metrics = jax.jit(
            lambda p, d, k: rollout_block(cfg, env, p, d, k)
        )(params, desired, jax.random.PRNGKey(1))
        assert fresh.s.shape == (cfg.block_steps, cfg.n_agents, 2)
        assert fresh.a.shape == (cfg.block_steps, cfg.n_agents, 1)
        acts = np.asarray(fresh.a)
        assert acts.min() >= 0 and acts.max() < cfg.n_actions
        assert metrics.true_team_returns.shape == (cfg.n_ep_fixed,)
        # scaled rewards are in [-2, 0]: raw in [-(8)-1, 0] / 5 on 5x5
        r = np.asarray(fresh.r)
        assert r.max() <= 0.0 and r.min() >= -2.0

    def test_returns_are_discounted_sums(self):
        """true_team_returns == mean over coop agents of sum gamma^j r_j."""
        cfg = SMALL
        env = make_env(cfg)
        params = init_agent_params(jax.random.PRNGKey(0), cfg)
        desired = jnp.zeros((cfg.n_agents, 2), jnp.int32)
        fresh, metrics = rollout_block(
            cfg, env, params, desired, jax.random.PRNGKey(1)
        )
        r = np.asarray(fresh.r).reshape(
            cfg.n_ep_fixed, cfg.max_ep_len, cfg.n_agents
        )
        disc = cfg.gamma ** np.arange(cfg.max_ep_len)
        expect = (r * disc[None, :, None]).sum(1).mean(-1)  # all coop
        np.testing.assert_allclose(
            np.asarray(metrics.true_team_returns), expect, rtol=1e-5
        )

    def test_fixed_initial_state(self):
        """randomize_state=False resets every episode to the fixed initial
        layout drawn at startup (reference grid_world.py:39-43,
        main.py:49)."""
        cfg = SMALL.replace(randomize_state=False)
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        env = make_env(cfg)
        fresh, _ = rollout_block(
            cfg, env, state.params, state.desired, jax.random.PRNGKey(1),
            state.initial,
        )
        s = np.asarray(fresh.s).reshape(
            cfg.n_ep_fixed, cfg.max_ep_len, cfg.n_agents, 2
        )
        from rcmarl_tpu.envs.grid_world import scale_state

        expect = np.asarray(scale_state(env, state.initial))
        for ep in range(cfg.n_ep_fixed):
            np.testing.assert_allclose(s[ep, 0], expect, rtol=1e-6)

    def test_greedy_actions_reach_goal(self):
        """With a strongly biased actor the policy is usable end-to-end:
        agents at the goal that pick 'stay' earn reward 0."""
        cfg = SMALL
        env = make_env(cfg)
        params = init_agent_params(jax.random.PRNGKey(0), cfg)

        # bias every actor's head to always pick action 0 (stay)
        def bias_stay(params):
            W, b = params.actor[-1]
            b = b.at[..., 0].set(50.0)
            return params._replace(actor=params.actor[:-1] + ((W, b),))

        params = bias_stay(params)
        cfg0 = cfg.replace(eps_explore=0.0)
        desired = jnp.zeros((cfg.n_agents, 2), jnp.int32)
        fresh, _ = rollout_block(cfg0, env, params, desired, jax.random.PRNGKey(3))
        acts = np.asarray(fresh.a)
        assert np.all(acts == 0.0)


class TestUpdateBlock:
    def _setup(self, roles):
        cfg = SMALL.replace(
            agent_roles=roles, H=1 if Roles.COOPERATIVE in roles else 0
        )
        params = init_agent_params(jax.random.PRNGKey(0), cfg)
        fresh = _fresh(cfg, 1.0)
        key = jax.random.PRNGKey(7)
        fresh = fresh._replace(
            r=jax.random.uniform(key, fresh.r.shape) - 1.0,
            s=jax.random.normal(key, fresh.s.shape),
            ns=jax.random.normal(jax.random.PRNGKey(8), fresh.ns.shape),
            a=jnp.floor(
                jax.random.uniform(key, fresh.a.shape) * SMALL.n_actions
            ),
        )
        buf = buffer_init(cfg.buffer_size, cfg.n_agents, cfg.n_states)
        batch = update_batch(buf, fresh)
        return cfg, params, batch, fresh

    def test_r_coop(self):
        cfg = SMALL.replace(
            agent_roles=(Roles.COOPERATIVE,) * 4 + (Roles.GREEDY,)
        )
        r = jnp.arange(5, dtype=jnp.float32)[None, :, None]
        r = jnp.broadcast_to(r, (3, 5, 1))
        np.testing.assert_allclose(
            np.asarray(team_average_reward(cfg, r)),
            np.full((3, 1), (0 + 1 + 2 + 3) / 4.0),
        )

    @pytest.mark.slow
    def test_all_roles_update(self):
        """Every role's parameters move as the behavior matrix mandates
        (SURVEY.md §2): faulty critic/TR frozen; all actors train."""
        roles = (
            Roles.COOPERATIVE,
            Roles.COOPERATIVE,
            Roles.GREEDY,
            Roles.FAULTY,
            Roles.MALICIOUS,
        )
        cfg, params, batch, fresh = self._setup(roles)
        out = update_block(cfg, params, batch, fresh, jax.random.PRNGKey(1))

        def moved(tree, i):
            a = jax.tree.leaves(jax.tree.map(lambda l: l[i], tree))
            b = jax.tree.leaves(jax.tree.map(lambda l: l[i], tree2))
            return any(not np.allclose(x, y) for x, y in zip(a, b))

        tree2 = out.critic
        assert moved(params.critic, 0)  # coop: consensus moved it
        assert moved(params.critic, 2)  # greedy: local fit persists
        assert not moved(params.critic, 3)  # faulty: frozen
        assert moved(params.critic, 4)  # malicious: compromised fit
        tree2 = out.tr
        assert not moved(params.tr, 3)
        tree2 = out.actor
        for i in range(5):
            assert moved(params.actor, i), f"actor {i} did not train"
        tree2 = out.critic_local
        assert moved(params.critic_local, 4)  # malicious private critic
        assert not moved(params.critic_local, 0)

    @pytest.mark.slow
    def test_adam_counts_per_role(self):
        """Coop actor: 1 Adam step/block. Adversary: ceil(B/batch) steps."""
        roles = (Roles.COOPERATIVE,) * 4 + (Roles.GREEDY,)
        cfg, params, batch, fresh = self._setup(roles)
        out = update_block(cfg, params, batch, fresh, jax.random.PRNGKey(1))
        counts = np.asarray(out.actor_opt.count)
        assert counts[0] == 1
        assert counts[4] == int(np.ceil(cfg.block_steps / cfg.batch_size))

    @pytest.mark.slow
    def test_coop_critic_restore_semantics(self):
        """With consensus effectively disabled (self-only graph, H=0), the
        local fit must still NOT persist into the agent's own critic trunk:
        consensus of one neighbor (itself) = its own message, but the team
        step only touches the head. We verify the trunk equals the MESSAGE
        trunk (aggregated over {self} = the local-fit result), i.e. restore
        + consensus ordering is honored rather than plain persistence."""
        cfg = SMALL.replace(
            agent_roles=(Roles.COOPERATIVE,),
            n_agents=1,
            in_nodes=((0,),),
            H=0,
        )
        params = init_agent_params(jax.random.PRNGKey(0), cfg)
        fresh = _fresh(cfg, 1.0)
        buf = buffer_init(cfg.buffer_size, cfg.n_agents, cfg.n_states)
        batch = update_batch(buf, fresh)
        out = update_block(cfg, params, batch, fresh, jax.random.PRNGKey(1))
        # 2 epochs ran; check params changed but are finite and the head
        # changed too (team update applied)
        assert np.all(np.isfinite(np.asarray(out.critic[0][0])))
        assert not np.allclose(
            np.asarray(out.critic[-1][0]), np.asarray(params.critic[-1][0])
        )


class TestEndToEnd:
    @pytest.mark.slow
    def test_train_runs_and_returns_frame(self):
        cfg = SMALL
        state, df = train(cfg)
        assert list(df.columns) == [
            "True_team_returns",
            "True_adv_returns",
            "Estimated_team_returns",
        ]
        assert len(df) == cfg.n_episodes
        assert int(state.block) == cfg.n_episodes // cfg.n_ep_fixed
        assert np.all(np.isfinite(df.values))

    @pytest.mark.slow
    def test_train_scanned_matches_host_loop(self):
        """Device-scanned trainer is step-identical to the host loop."""
        cfg = SMALL
        s0 = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
        host_state, df = train(cfg, state=s0)
        dev_state, metrics = jax.jit(
            lambda s: train_scanned(cfg, s, cfg.n_episodes // cfg.n_ep_fixed)
        )(s0)
        np.testing.assert_allclose(
            df["True_team_returns"].values,
            np.asarray(metrics.true_team_returns),
            rtol=1e-5,
        )
        for a, b in zip(
            jax.tree.leaves(host_state.params), jax.tree.leaves(dev_state.params)
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4)

    @pytest.mark.slow
    def test_heterogeneous_train(self):
        cfg = SMALL.replace(
            agent_roles=(
                Roles.COOPERATIVE,
                Roles.COOPERATIVE,
                Roles.COOPERATIVE,
                Roles.COOPERATIVE,
                Roles.MALICIOUS,
            ),
            H=1,
        )
        state, df = train(cfg)
        assert np.all(np.isfinite(df.values))
        assert (df["True_adv_returns"] != 0).any()

    def test_rejects_partial_block(self):
        with pytest.raises(ValueError):
            train(SMALL, n_episodes=3)


class TestHeterogeneousGraph:
    """Irregular in-degree topologies (reference main.py:28 accepts any
    adjacency list; VERDICT.md round-1 weakness 5)."""

    def test_config_accepts_ragged_in_nodes(self):
        cfg = SMALL.replace(
            in_nodes=((0, 1, 2, 3), (1, 2, 3), (2, 3, 4, 0), (3, 4, 0), (4, 0, 1)),
            H=1,
        )
        assert not cfg.regular_graph
        assert cfg.n_in == 4
        assert cfg.in_degrees == (4, 3, 4, 3, 3)
        in_arr, valid = cfg.padded_in_nodes()
        assert in_arr[1] == (1, 2, 3, 1)  # padded with self
        assert valid[1] == (1.0, 1.0, 1.0, 0.0)
        assert valid[0] == (1.0,) * 4

    def test_h_checked_per_agent(self):
        with pytest.raises(ValueError, match="H=1 too large"):
            SMALL.replace(
                in_nodes=((0, 1, 2, 3), (1, 2), (2, 3, 4, 0), (3, 4, 0), (4, 0, 1)),
                H=1,
            )

    @pytest.mark.slow
    def test_train_runs_on_ragged_graph(self):
        cfg = SMALL.replace(
            in_nodes=((0, 1, 2, 3), (1, 2, 3), (2, 3, 4, 0), (3, 4, 0), (4, 0, 1)),
            H=1,
        )
        state, df = train(cfg)
        assert np.all(np.isfinite(df.values))

    def test_padded_equals_unpadded_on_regular_graph(self):
        """Forcing the masked path on a regular graph must reproduce the
        fast path bit-for-bit semantics (same math, different plumbing)."""
        from rcmarl_tpu.agents.updates import consensus_update_one
        from rcmarl_tpu.models.mlp import init_stacked_mlp

        cfg = SMALL
        key = jax.random.PRNGKey(0)
        msgs = init_stacked_mlp(key, cfg.n_in, cfg.obs_dim, cfg.hidden, 1)
        own = jax.tree.map(lambda l: l[0], msgs)
        x = jax.random.normal(jax.random.PRNGKey(1), (7, cfg.n_agents, cfg.n_states))
        mask = jnp.ones((7,))
        fast = consensus_update_one(own, msgs, x, mask, cfg.replace(H=1))
        masked = consensus_update_one(
            own, msgs, x, mask, cfg.replace(H=1), valid=jnp.ones((cfg.n_in,))
        )
        for a, b in zip(jax.tree.leaves(fast), jax.tree.leaves(masked)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)


@pytest.mark.slow
def test_training_actually_learns():
    """End-to-end learning check (not semantics — those are golden-pinned
    elsewhere): on an easy 3-agent 3x3 cooperative task, 300 episodes of
    the fused trainer must lift the mean team return materially.
    Margin calibrated at ~1/3 of the observed improvement (+1.0 to +1.4
    across seeds) so seed noise cannot flip it."""
    cfg = Config(
        n_agents=3,
        agent_roles=(0, 0, 0),
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        slow_lr=0.01,
        n_episodes=300,
        n_ep_fixed=25,
        seed=3,
    )
    _, sim = train(cfg, verbose=False)
    r = sim["True_team_returns"]
    assert r[-50:].mean() - r[:50].mean() > 0.4
