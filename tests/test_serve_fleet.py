"""Fleet-serving contracts (rcmarl_tpu.serve.fleet).

The pins that make a fleet row trustworthy:

- PER-MEMBER BITWISE PARITY: member f's probabilities inside the fleet
  launch equal the solo ``serve_block`` probabilities on the same
  checkpoint bitwise, and a request routed to f samples the exact
  action it would get solo (shared fold_in keys);
- ROUTING IS DATA: re-routing between launches re-dispatches the same
  compiled executable — zero recompiles across route changes and
  member hot-swaps (the compile-count pin; the lint --retrace fleet
  case drives the full matrix);
- MEMBER-ISOLATED DEGRADATION: a corrupt/poisoned member candidate
  degrades only that member to its last-good slice — the fleet keeps
  serving and the other members keep swapping;
- config homogeneity is loud.

Tiny 3-agent configs, states built directly by ``init_train_state``
(no training) — the tier-1 budget discipline of tests/test_serve.py.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.serve.engine import (
    serve_block,
    serve_request_keys,
    stack_actor_rows,
)
from rcmarl_tpu.serve.fleet import (
    FleetEngine,
    fleet_block,
    fleet_set_member,
    fleet_stack,
)
from rcmarl_tpu.training.trainer import init_train_state
from rcmarl_tpu.utils.checkpoint import save_checkpoint


def tiny_cfg(**overrides):
    base = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        n_episodes=4,
        n_ep_fixed=2,
        max_ep_len=4,
        n_epochs=2,
        H=1,
    )
    base.update(overrides)
    return Config(**base)


CFG = tiny_cfg()
STATES = [init_train_state(CFG, jax.random.PRNGKey(s)) for s in range(3)]
BLOCKS = [stack_actor_rows(s.params, CFG) for s in STATES]
B = 6
OBS = jax.random.normal(jax.random.PRNGKey(5), (B, CFG.n_agents, CFG.obs_dim))
KEY = jax.random.PRNGKey(9)
ROUTE = jnp.arange(B, dtype=jnp.int32) % 2


def _leaves_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def _fleet_engine(tmp_path, n=2, **kw):
    paths = []
    for f in range(n):
        p = tmp_path / f"member{f}.npz"
        save_checkpoint(p, STATES[f], CFG)
        paths.append(p)
    return FleetEngine(paths, **kw), paths


class TestFleetBlock:
    def test_fleet_stack_adds_leading_member_axis(self):
        fleet = fleet_stack(BLOCKS[:2])
        for fl, b0 in zip(jax.tree.leaves(fleet), jax.tree.leaves(BLOCKS[0])):
            assert fl.shape == (2,) + b0.shape
        # row f IS member f, bitwise
        for f in range(2):
            _leaves_equal(
                jax.tree.map(lambda l: l[f], fleet), BLOCKS[f]
            )

    def test_per_member_probs_bitwise_vs_solo(self):
        """THE fleet acceptance pin: every request's probability row is
        BITWISE the routed member's solo serve_block row."""
        fleet = fleet_stack(BLOCKS[:2])
        _, fleet_probs = fleet_block(CFG, fleet, OBS, KEY, ROUTE)
        solo = [
            np.asarray(serve_block(CFG, blk, OBS, KEY)[1])
            for blk in BLOCKS[:2]
        ]
        r = np.asarray(ROUTE)
        for b in range(B):
            np.testing.assert_array_equal(
                np.asarray(fleet_probs)[b], solo[r[b]][b]
            )

    def test_routed_actions_bitwise_vs_solo(self):
        """A request routed to member f samples the EXACT action it
        would get from solo serving f — the fold_in key discipline is
        member-independent, so routing cannot change a draw."""
        fleet = fleet_stack(BLOCKS[:2])
        fleet_actions, _ = fleet_block(CFG, fleet, OBS, KEY, ROUTE)
        solo = [
            np.asarray(serve_block(CFG, blk, OBS, KEY)[0])
            for blk in BLOCKS[:2]
        ]
        r = np.asarray(ROUTE)
        for b in range(B):
            np.testing.assert_array_equal(
                np.asarray(fleet_actions)[b], solo[r[b]][b]
            )

    def test_greedy_routes_argmax(self):
        fleet = fleet_stack(BLOCKS[:2])
        actions, probs = fleet_block(
            CFG, fleet, OBS, KEY, ROUTE, mode="greedy"
        )
        np.testing.assert_array_equal(
            np.asarray(actions), np.asarray(jnp.argmax(probs, axis=-1))
        )

    def test_sample_keys_are_the_solo_keys(self):
        """Fleet sampling consumes serve_request_keys(key, B, N) —
        verified by replaying the categorical draw per (request,
        agent)."""
        fleet = fleet_stack(BLOCKS[:2])
        actions, probs = fleet_block(CFG, fleet, OBS, KEY, ROUTE)
        keys = serve_request_keys(KEY, B, CFG.n_agents)
        for b in range(B):
            for n in range(CFG.n_agents):
                a = jax.random.categorical(keys[b, n], jnp.log(probs[b, n]))
                assert int(a) == int(actions[b, n]), (b, n)

    def test_route_changes_and_member_swaps_share_one_program(self):
        """Routing and the fleet tree are DATA: re-routes, member
        hot-swaps, and repeated batches reuse the compiled executable —
        the jit cache must not grow after warmup."""
        fleet = fleet_stack(BLOCKS[:2])
        swapped = fleet_set_member(fleet, 1, BLOCKS[2])
        routes = [
            jnp.zeros((B,), jnp.int32),
            ROUTE,
            jnp.ones((B,), jnp.int32),
        ]
        fleet_block(CFG, fleet, OBS, KEY, routes[0])  # warmup (this cfg)
        before = int(fleet_block._cache_size())
        for fl in (fleet, swapped):
            for route in routes:
                fleet_block(CFG, fl, OBS, KEY, route)
        assert int(fleet_block._cache_size()) == before

    def test_bad_mode_loud(self):
        with pytest.raises(ValueError, match="mode"):
            fleet_block(
                CFG, fleet_stack(BLOCKS[:2]), OBS, KEY, ROUTE, mode="nope"
            )


class TestFleetSetMember:
    def test_replaces_exactly_one_slice(self):
        fleet = fleet_stack(BLOCKS[:2])
        out = fleet_set_member(fleet, 1, BLOCKS[2])
        _leaves_equal(jax.tree.map(lambda l: l[0], out), BLOCKS[0])
        _leaves_equal(jax.tree.map(lambda l: l[1], out), BLOCKS[2])
        # the original fleet is untouched (functional update)
        _leaves_equal(jax.tree.map(lambda l: l[1], fleet), BLOCKS[1])


class TestFleetEngine:
    def test_serve_round_robin_matches_fleet_block(self, tmp_path):
        eng, _ = _fleet_engine(tmp_path)
        a, p = eng.serve(OBS, key=KEY)
        ref_a, ref_p = fleet_block(
            CFG, eng.fleet, OBS, KEY, eng.round_robin_route(B)
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(ref_a))
        np.testing.assert_array_equal(np.asarray(p), np.asarray(ref_p))
        assert eng.counters["launches"] == 1
        assert eng.counters["actions"] == B * CFG.n_agents

    def test_member_swap_updates_only_that_slice(self, tmp_path):
        eng, paths = _fleet_engine(tmp_path)
        save_checkpoint(paths[1], STATES[2], CFG)
        assert eng.poll() == [1]
        _leaves_equal(
            jax.tree.map(lambda l: l[0], eng.fleet), BLOCKS[0]
        )
        _leaves_equal(
            jax.tree.map(lambda l: l[1], eng.fleet), BLOCKS[2]
        )
        assert eng.members[1].counters["swaps"] == 1

    def test_corrupt_member_degrades_alone(self, tmp_path):
        """One member's primary AND .prev corrupted: that member is
        rejected to its last-good slice, the OTHER member still swaps —
        the fleet never degrades past the bad member."""
        eng, paths = _fleet_engine(tmp_path)
        # member 1 gets a real update first (so .prev exists), then
        # both its files are corrupted
        save_checkpoint(paths[1], STATES[2], CFG)
        assert eng.poll() == [1]
        for suffix in ("", ".prev"):
            with open(str(paths[1]) + suffix, "r+b") as f:
                f.seek(100)
                f.write(b"\xde\xad\xbe\xef" * 16)
        # member 0 publishes a healthy update in the same poll round
        save_checkpoint(paths[0], STATES[2], CFG)
        assert eng.poll() == [0]
        assert eng.members[1].counters["rejects"] == 1
        assert eng.members[1].degraded is True
        assert eng.members[0].degraded is False
        # fleet: member 0 fresh, member 1 last-good (its prior swap)
        _leaves_equal(
            jax.tree.map(lambda l: l[0], eng.fleet), BLOCKS[2]
        )
        _leaves_equal(
            jax.tree.map(lambda l: l[1], eng.fleet), BLOCKS[2]
        )
        assert eng.summary()["degraded_members"] == [1]
        assert "m1:last-good" in eng.summary_line()
        assert "m0:fresh" in eng.summary_line()

    def test_poisoned_member_candidate_rejected_alone(self, tmp_path):
        eng, paths = _fleet_engine(tmp_path)
        poisoned = STATES[2]._replace(
            params=STATES[2].params._replace(
                actor=jax.tree.map(
                    lambda l: l.at[0].set(jnp.nan), STATES[2].params.actor
                )
            )
        )
        save_checkpoint(paths[0], poisoned, CFG)
        assert eng.poll() == []
        assert eng.members[0].counters["rejects"] == 1
        _leaves_equal(
            jax.tree.map(lambda l: l[0], eng.fleet), BLOCKS[0]
        )

    def test_mixed_config_members_fail_loudly(self, tmp_path):
        p0 = tmp_path / "m0.npz"
        save_checkpoint(p0, STATES[0], CFG)
        other_cfg = tiny_cfg(hidden=(16, 16))
        p1 = tmp_path / "m1.npz"
        save_checkpoint(
            p1, init_train_state(other_cfg, jax.random.PRNGKey(0)), other_cfg
        )
        with pytest.raises(ValueError, match="share ONE serving config"):
            FleetEngine([p0, p1])

    def test_empty_fleet_loud(self):
        with pytest.raises(ValueError, match="at least one"):
            FleetEngine([])


class TestFleetCLI:
    @pytest.mark.slow
    def test_serve_fleet_cli_emits_parity_certified_row(
        self, tmp_path, capsys
    ):
        # slow marker: the CLI wire-up is also CI-enforced end to end by
        # the ci_tier1.sh production-serving smoke cell (the PR-8/PR-9
        # budget-shedding pattern); the bitwise parity pin itself stays
        # tier-1 (TestFleetBlock above)
        import json

        from rcmarl_tpu.cli import main

        paths = []
        for f in range(2):
            p = tmp_path / f"m{f}.npz"
            save_checkpoint(p, STATES[f], CFG)
            paths.append(str(p))
        assert main([
            "serve", "--fleet", *paths,
            "--batch", "8", "--steps", "2", "--reps", "1",
            "--obs_buffers", "2",
        ]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        row = json.loads(out[0])
        assert row["kind"] == "serve"
        assert row["fleet"] == 2
        assert row["member_parity"] == "bitwise"
        assert row["actions_per_sec"] > 0
        assert row["headline"] is False  # CPU row discipline
        assert row["degradation"]["degraded_members"] == []
        assert "fleet: 2 members" in out[-1]

    def test_fleet_with_canary_band_rejected(self, tmp_path):
        from rcmarl_tpu.cli import main

        p = tmp_path / "m.npz"
        save_checkpoint(p, STATES[0], CFG)
        with pytest.raises(SystemExit, match="SOLO"):
            main([
                "serve", "--fleet", str(p), "--canary_band", "0.05",
                "--watch_every", "1",
            ])
