"""Async actor-learner pipeline (rcmarl_tpu.pipeline).

Tier-1 pins the cheap contracts: the queue/publisher units, the Config
validation, staleness accounting (exact per-block counts at several
depth x publish_every cells), the depth-0 synchronous-handoff arm
BITWISE against the reference trainer on one tiny cell, and a depth-2
finite end-to-end run. The heavier depth-0 equivalence matrix
(mixed / faulted+guarded / netstack cells) rides the slow marker per
the tier-1 budget discipline; ci_tier1.sh re-proves the depth-0 pin
through the real CLI every run.
"""

import jax
import numpy as np
import pytest

from rcmarl_tpu.config import Config
from rcmarl_tpu.lint.configs import tiny_cfg, tiny_faulted_cfg, tiny_mixed_cfg
from rcmarl_tpu.pipeline.publish import PolicyPublisher
from rcmarl_tpu.pipeline.queue import BlockQueue
from rcmarl_tpu.pipeline.trainer import pipeline_summary, train_pipelined
from rcmarl_tpu.training.trainer import train


def _assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------------
# units: queue / publisher / config validation
# --------------------------------------------------------------------------


class TestBlockQueue:
    def test_fifo_and_bounds(self):
        q = BlockQueue(2)
        q.put((0, "f0", "m0"))
        q.put((1, "f1", "m1"))
        assert q.full and len(q) == 2
        with pytest.raises(RuntimeError, match="overflow"):
            q.put((2, "f2", "m2"))
        assert q.get() == (0, "f0", "m0")
        q.put((2, "f2", "m2"))
        assert [q.get()[0] for _ in range(2)] == [1, 2]
        with pytest.raises(RuntimeError, match="underflow"):
            q.get()

    def test_depth_validation(self):
        with pytest.raises(ValueError):
            BlockQueue(0)


class TestPolicyPublisher:
    def test_publish_boundary_and_staleness_bookkeeping(self):
        params = {"w": np.ones(3)}
        pub = PolicyPublisher(params, publish_every=2)
        assert pub.offer({"w": np.full(3, 2.0)}, 1) is False  # not a boundary
        assert pub.published_block == 0
        assert pub.offer({"w": np.full(3, 2.0)}, 2) is True
        assert pub.published_block == 2
        assert pub.counters == {
            "publishes": 1, "rejects": 0, "canary_rejects": 0,
        }

    def test_validate_rejects_nonfinite_keeps_last_good(self):
        good = {"w": np.ones(3, np.float32)}
        pub = PolicyPublisher(good, validate=True)
        bad = {"w": np.array([1.0, np.nan, 1.0], np.float32)}
        assert pub.offer(bad, 1) is False
        assert pub.acting is good  # last good kept, wholesale
        assert pub.counters == {
            "publishes": 0, "rejects": 1, "canary_rejects": 0,
        }
        fresh = {"w": np.full(3, 2.0, np.float32)}
        assert pub.offer(fresh, 2) is True
        assert pub.acting is fresh and pub.published_block == 2

    def test_copy_mode_snapshots_the_tree(self):
        src = {"w": np.ones(3, np.float32)}
        pub = PolicyPublisher(src, copy=True)
        assert pub.acting is not src
        np.testing.assert_array_equal(np.asarray(pub.acting["w"]), src["w"])


class TestConfigValidation:
    def test_negative_depth_rejected(self):
        with pytest.raises(ValueError, match="pipeline_depth"):
            Config(pipeline_depth=-1)

    def test_publish_every_zero_rejected(self):
        with pytest.raises(ValueError, match="publish_every"):
            Config(publish_every=0)

    def test_replica_pipeline_combination_validated(self):
        """The composed topology replaced the old loud rejection: the
        combination is legal iff each gossip segment is at least as
        long as the pipeline depth (the actor tier drains at every mix
        boundary), and the composed canary knobs validate."""
        cfg = Config(
            replicas=2, pipeline_depth=2, gossip_every=2, gossip_H=0,
            gossip_degree=2,
        )
        assert cfg.replicas == 2 and cfg.pipeline_depth == 2
        with pytest.raises(ValueError, match="gossip_every"):
            Config(replicas=2, pipeline_depth=3, gossip_every=2,
                   gossip_H=0, gossip_degree=2)
        with pytest.raises(ValueError, match="canary_band"):
            Config(canary_band=0.1)  # composed-only knob
        with pytest.raises(ValueError, match="canary_band"):
            Config(canary_band=-0.1, replicas=2, pipeline_depth=2,
                   gossip_every=2, gossip_H=0, gossip_degree=2)
        with pytest.raises(ValueError, match="canary_blocks"):
            Config(canary_blocks=0)


# --------------------------------------------------------------------------
# staleness accounting (exact, per block)
# --------------------------------------------------------------------------


class TestStalenessAccounting:
    def test_depth2_ramp_then_steady(self):
        cfg = tiny_cfg(pipeline_depth=2, n_episodes=12)
        _, df = train_pipelined(cfg)
        p = df.attrs["pipeline"]
        assert p["staleness"] == [0, 1, 1, 1, 1, 1]
        assert p["staleness_max"] == 1 and p["publishes"] == 6
        assert np.isfinite(df["True_team_returns"].values).all()
        assert "staleness mean" in pipeline_summary(p)

    def test_publish_every_adds_publish_lag(self):
        cfg = tiny_cfg(pipeline_depth=1, publish_every=2, n_episodes=12)
        _, df = train_pipelined(cfg)
        p = df.attrs["pipeline"]
        # depth 1 dispatches block j right after learner block j, but
        # the publisher only swaps at even blocks: odd-block rollouts
        # act one block stale
        assert p["staleness"] == [0, 1, 0, 1, 0, 1]
        assert p["publishes"] == 3

    def test_depth0_counts_zero_staleness(self):
        cfg = tiny_cfg(pipeline_depth=0)
        _, df = train_pipelined(cfg)
        p = df.attrs["pipeline"]
        assert p["staleness"] == [0] * p["blocks"]
        assert p["depth"] == 0


# --------------------------------------------------------------------------
# the depth-0 synchronous-handoff pin (the reference arm)
# --------------------------------------------------------------------------


class TestDepth0Bitwise:
    def test_depth0_bitwise_vs_train_tiny(self):
        cfg = tiny_cfg()
        s_ref, df_ref = train(cfg)
        s_pipe, df_pipe = train_pipelined(cfg)
        _assert_trees_equal(s_ref, s_pipe)
        np.testing.assert_array_equal(
            df_ref["True_team_returns"].values,
            df_pipe["True_team_returns"].values,
        )
        np.testing.assert_array_equal(
            df_ref["Estimated_team_returns"].values,
            df_pipe["Estimated_team_returns"].values,
        )

    @pytest.mark.slow
    @pytest.mark.parametrize(
        "label,cfg",
        [
            ("mixed", tiny_mixed_cfg()),
            ("faulted+guarded", tiny_faulted_cfg(False)),
            ("netstack", tiny_faulted_cfg(True)),
            ("netstack+fitstack", tiny_mixed_cfg(netstack=True, fitstack=True)),
        ],
    )
    def test_depth0_bitwise_matrix(self, label, cfg):
        s_ref, df_ref = train(cfg)
        s_pipe, df_pipe = train_pipelined(cfg)
        _assert_trees_equal(s_ref, s_pipe)
        for col in df_ref.columns:
            np.testing.assert_array_equal(
                df_ref[col].values, df_pipe[col].values
            )
        if "guard" in df_ref.attrs:
            assert df_ref.attrs["guard"] == df_pipe.attrs["guard"]


# --------------------------------------------------------------------------
# the decoupled pipeline (depth >= 1)
# --------------------------------------------------------------------------


class TestPipelined:
    def test_depth1_matches_sync_key_chain_rollouts(self):
        # depth 1, publish_every 1 is the staleness-0 decoupled arm:
        # every rollout acts on the params the sync trainer would act
        # on, drawn with the sync key chain — returns match the sync
        # run EXACTLY only if rollout and update numerics are
        # unchanged by the program split, which is not guaranteed
        # across fusion boundaries; what IS contractual is staleness 0
        # and a healthy finite run.
        cfg = tiny_cfg(pipeline_depth=1, n_episodes=8)
        _, df = train_pipelined(cfg)
        p = df.attrs["pipeline"]
        assert p["staleness"] == [0, 0, 0, 0]
        assert np.isfinite(df["True_team_returns"].values).all()

    def test_guarded_faulted_pipeline_counts_and_stays_finite(self):
        cfg = tiny_faulted_cfg(False, pipeline_depth=2)
        state, df = train_pipelined(cfg)
        assert bool(np.all([np.isfinite(np.asarray(l)).all()
                            for l in jax.tree.leaves(state.params)]))
        g = df.attrs["guard"]
        assert g["nonfinite"] > 0  # the plan injected, the diag counted
        assert df.attrs["pipeline"]["publishes"] >= 1

    def test_skipped_blocks_publish_nothing_and_fold_the_stored_key(self):
        # an unconditional NaN bomb without sanitize poisons EVERY
        # learner block: all blocks skip, the publisher must never
        # advance (staleness keeps growing against the initial params,
        # publishes stays 0), and the stored key must fold per skip so
        # a checkpoint-resume cannot replay the failing draws forever
        from rcmarl_tpu.faults import FaultPlan

        cfg = tiny_cfg(
            pipeline_depth=2,
            n_episodes=6,
            fault_plan=FaultPlan(nan_p=1.0),
        )
        state, df = train_pipelined(cfg, max_retries=0)
        p = df.attrs["pipeline"]
        assert df.attrs["guard"]["skipped"] == 3
        assert p["publishes"] == 0 and p["rejects"] == 0
        assert p["staleness"] == [0, 1, 2]
        # params rolled back to the (finite) initial tree every block
        assert all(
            np.isfinite(np.asarray(l)).all()
            for l in jax.tree.leaves(state.params)
        )
        # the stored key is the per-skip fold of the synchronous
        # protocol, not the untouched chain key
        key = jax.random.PRNGKey(cfg.seed)
        _, _, _, key = jax.random.split(key, 4)  # init_train_state split
        for b in range(3):
            key = jax.random.fold_in(key, 0x5C1B + b)
        np.testing.assert_array_equal(np.asarray(state.key), np.asarray(key))

    def test_poisoned_rollout_window_redraws_then_skips(self):
        """The skip-and-redraw regression net (the chaos campaign's
        pipeline_window cells): an all-NaN actor window must never be
        retried against (the learner retry is structurally futile with
        the batch kept) — PERSISTENT poisoning terminates in bounded
        REDRAWS then a skip with nothing published, the stored key
        folded like the synchronous skip, and the staleness lengthened;
        TRANSIENT poisoning is healed by one redraw with zero learner
        retries burned."""
        import jax.numpy as jnp

        def bomb_block1(persistent):
            def window_fault(b, attempt, fresh, m):
                if b == 1 and (persistent or attempt == 0):
                    fresh = jax.tree.map(
                        lambda l: (
                            jnp.full_like(l, jnp.nan)
                            if jnp.issubdtype(
                                jnp.asarray(l).dtype, jnp.floating
                            )
                            else l
                        ),
                        fresh,
                    )
                return fresh, m
            return window_fault

        cfg = tiny_cfg(pipeline_depth=2, n_episodes=8)
        seen_keys = {}
        state, df = train_pipelined(
            cfg, guard=True, max_retries=2,
            window_fault=bomb_block1(True),
            block_callback=lambda s, b: seen_keys.update(
                {b: np.asarray(s.key)}
            ),
        )
        g, p = df.attrs["guard"], df.attrs["pipeline"]
        assert g["redraws"] == 2 and g["skipped"] == 1
        assert g["retries"] == 0  # no learner launch paid for the window
        assert p["publishes"] == p["blocks"] - 1  # skip published NOTHING
        # staleness lengthened: block 3's dispatch (fired after block
        # 1's skip) still acts on block-1-old params
        assert p["staleness"] == [0, 1, 1, 2]
        assert all(
            np.isfinite(np.asarray(l)).all()
            for l in jax.tree.leaves(state.params)
        )
        # the STORED key at the skipped block is the per-skip fold of
        # the synchronous protocol on top of the walked chain — a
        # checkpoint taken there never replays the failing draws
        key = jax.random.PRNGKey(cfg.seed)
        _, _, _, key = jax.random.split(key, 4)  # init_train_state split
        key, _, _ = jax.random.split(key, 3)  # block 0's chain step
        key = jax.random.fold_in(key, 0x5C1B + 1)
        np.testing.assert_array_equal(seen_keys[1], np.asarray(key))

        # transient: one redraw heals the window — nothing skipped
        state2, df2 = train_pipelined(
            cfg, guard=True, max_retries=2,
            window_fault=bomb_block1(False),
        )
        g2, p2 = df2.attrs["guard"], df2.attrs["pipeline"]
        assert g2["redraws"] == 1 and g2["skipped"] == 0
        assert g2["retries"] == 0
        assert p2["publishes"] == p2["blocks"]

    def test_window_fault_rejected_at_depth0(self):
        with pytest.raises(ValueError, match="window_fault"):
            train_pipelined(
                tiny_cfg(), window_fault=lambda b, a, f, m: (f, m)
            )

    def test_resume_continues_block_counter(self):
        cfg = tiny_cfg(pipeline_depth=2, n_episodes=4)
        state, _ = train_pipelined(cfg)
        state2, df2 = train_pipelined(cfg, n_episodes=4, state=state)
        assert int(np.asarray(state2.block)) == 4
        assert df2.attrs["pipeline"]["blocks"] == 2

    def test_verbose_and_callback_fire_per_block(self, capsys):
        seen = []
        cfg = tiny_cfg(pipeline_depth=2, n_episodes=6)
        train_pipelined(
            cfg, verbose=True,
            block_callback=lambda s, b: seen.append(b),
        )
        assert seen == [0, 1, 2]
        out = capsys.readouterr().out
        assert out.count("| Block ") == 3
