"""Property-based sanitize-mode contracts (hypothesis).

Randomized twin of tests/test_faults.py's deterministic matrix: over
arbitrary f32 inputs with arbitrary NaN/±Inf poisoning patterns, the
sanitized aggregate must (a) bitwise-agree across every backend,
(b) stay inside the surviving finite values' range whenever enough of
them exist, and (c) fall back to the own value under a degree deficit.
Guarded like the other property modules: a missing hypothesis (the
`test` extra) is a skip, never a collection error.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

from rcmarl_tpu.ops.aggregation import resilient_aggregate
from rcmarl_tpu.ops.pallas_aggregation import fused_resilient_aggregate

finite = st.floats(-1e6, 1e6, allow_nan=False, width=32)


@st.composite
def poisoned_vals_and_h(draw, min_n=3, max_n=8, m=4):
    """(values, H) with a random subset of elements replaced by a random
    choice of NaN/+Inf/-Inf (possibly none, possibly all non-self)."""
    n = draw(st.integers(min_n, max_n))
    H = draw(st.integers(0, (n - 1) // 2))
    vals = draw(arrays(np.float32, (n, m), elements=finite))
    poison = draw(arrays(np.int8, (n, m), elements=st.integers(0, 3)))
    bombs = np.asarray([0.0, np.nan, np.inf, -np.inf], np.float32)
    vals = np.where(poison > 0, bombs[poison], vals)
    return vals, H


@settings(max_examples=40, deadline=None)
@given(poisoned_vals_and_h())
def test_sanitized_backends_agree_bitwise(case):
    vals, H = case
    v = jnp.asarray(vals)
    outs = [
        resilient_aggregate(v, H, impl="xla", sanitize=True),
        resilient_aggregate(v, H, impl="xla_sort", sanitize=True),
        resilient_aggregate(
            v, H, impl="xla", valid=jnp.ones(v.shape[0]), sanitize=True
        ),
        jax.jit(
            lambda x, h: resilient_aggregate(x, h, impl="xla", sanitize=True)
        )(v, jnp.int32(H)),
        fused_resilient_aggregate(
            v, H, variant="select", interpret=True, sanitize=True
        ),
        fused_resilient_aggregate(
            v, H, variant="sort", interpret=True, sanitize=True
        ),
    ]
    base = np.asarray(outs[0])
    for out in outs[1:]:
        np.testing.assert_array_equal(base, np.asarray(out), err_msg=f"H={H}")


@settings(max_examples=40, deadline=None)
@given(poisoned_vals_and_h())
def test_sanitized_output_bounded_or_own(case):
    """Elementwise: with >= 2H+1 finite survivors the aggregate is
    finite and inside their range; otherwise it IS the own value
    (bitwise, including a non-finite own value)."""
    vals, H = case
    out = np.asarray(resilient_aggregate(jnp.asarray(vals), H, sanitize=True))
    fin = np.isfinite(vals)
    count = fin.sum(axis=0)
    for c in range(vals.shape[1]):
        if count[c] >= 2 * H + 1:
            col = vals[fin[:, c], c]
            assert np.isfinite(out[c])
            assert col.min() - 1e-4 <= out[c] <= col.max() + 1e-4
        else:
            np.testing.assert_array_equal(out[c], vals[0, c])


@settings(max_examples=25, deadline=None)
@given(poisoned_vals_and_h(), st.integers(1, 3))
def test_masked_sanitize_ignores_pad_garbage(case, pad):
    """Appending pad slots full of garbage (finite or not) to a
    sanitized masked aggregate changes nothing."""
    vals, H = case
    n = vals.shape[0]
    padded = np.concatenate(
        [vals, np.full((pad, vals.shape[1]), np.inf, np.float32)], axis=0
    )
    valid = jnp.asarray([1.0] * n + [0.0] * pad)
    a = resilient_aggregate(
        jnp.asarray(padded), H, valid=valid, sanitize=True
    )
    b = resilient_aggregate(
        jnp.asarray(vals), H, valid=jnp.ones(n), sanitize=True
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@settings(max_examples=25, deadline=None)
@given(arrays(np.float32, (5, 3), elements=finite))
def test_clean_inputs_unchanged_by_sanitize(vals):
    """On all-finite inputs sanitize is semantically the plain kernel."""
    v = jnp.asarray(vals)
    np.testing.assert_allclose(
        np.asarray(resilient_aggregate(v, 1, sanitize=True)),
        np.asarray(resilient_aggregate(v, 1)),
        rtol=1e-6,
        atol=1e-6,
    )
