"""Composite golden tests: our update primitives vs the reference's
ACTUAL TF classes, under identical fixed weights and batches.

The primitive pieces (forward pass, Adam, SGD, losses, Keras fit
semantics) are golden-pinned in test_models_ops.py; the aggregation
kernel in test_aggregation.py. These tests pin the COMPOSITES — the four
RPBCAC update primitives (SURVEY.md §2 C4) end to end:

  - critic/TR local fit message (resilient_CAC_agents.py:103-140):
    TD target with pre-fit weights, 5 full-batch SGD steps, restore.
  - full Phase II (train_agents.py:125-145 ordering): hidden trunk
    consensus -> head projection on the NEW trunk -> normalized team
    head update (resilient_CAC_agents.py:60-84,142-206).
  - cooperative actor step (resilient_CAC_agents.py:86-101): global-TD
    sample-weighted sparse CE, one Adam train_on_batch.

Keras 3 compatibility shim for the REFERENCE side (not ours): the
reference reuses one SGD instance across models and trainable-set
changes, which Keras 3 rejects. Plain SGD is stateless, so a fresh
instance per compile reproduces the Keras-2 behavior exactly (same shim
the DRIFT.md snapshot runs use).
"""

import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rcmarl_tpu.agents.updates import (
    Batch,
    adv_actor_update,
    adv_critic_fit,
    adv_tr_fit,
    consensus_update_one,
    coop_actor_update,
    coop_local_critic_fit,
    coop_local_tr_fit,
)
from rcmarl_tpu.config import Config
from rcmarl_tpu.ops.optim import adam_init

tf = pytest.importorskip("tensorflow")
keras = tf.keras


def _load_reference_agents():
    """Import each reference module independently so a broken adversarial
    module only skips the adversary tests, not the cooperative ones."""
    sys.path.insert(0, "/root/reference")
    coop = greedy = malicious = None
    try:
        from agents.resilient_CAC_agents import RPBCAC_agent  # type: ignore

        coop = RPBCAC_agent
    except Exception:
        pass
    try:
        from agents.adversarial_CAC_agents import (  # type: ignore
            Greedy_CAC_agent,
            Malicious_CAC_agent,
        )

        greedy, malicious = Greedy_CAC_agent, Malicious_CAC_agent
    except Exception:
        pass
    finally:
        sys.path.remove("/root/reference")
    return coop, greedy, malicious


REF_AGENT, REF_GREEDY, REF_MALICIOUS = _load_reference_agents()

pytestmark = pytest.mark.skipif(
    REF_AGENT is None, reason="reference agent not importable"
)

N_AGENTS, N_STATES, N_ACTIONS, HIDDEN = 5, 2, 5, (20, 20)
GAMMA, FAST_LR, SLOW_LR = 0.9, 0.01, 0.002


def _keras_model(in_feats, out_dim, softmax):
    """The reference's model family (main.py:60-82)."""
    return keras.Sequential(
        [
            keras.Input(shape=(N_AGENTS, in_feats)),
            keras.layers.Flatten(),
            keras.layers.Dense(20, activation=keras.layers.LeakyReLU(alpha=0.1)),
            keras.layers.Dense(20, activation=keras.layers.LeakyReLU(alpha=0.1)),
            keras.layers.Dense(out_dim, activation="softmax" if softmax else None),
        ]
    )


def _stateless_sgd(cls):
    """Keras-2-equivalent shim (see module docstring)."""
    cls.optimizer_fast = property(
        lambda self: keras.optimizers.SGD(learning_rate=self.fast_lr),
        lambda self, v: None,
    )


if REF_AGENT is not None:
    _stateless_sgd(REF_AGENT)


def _models(seed):
    """The reference's model family (main.py:60-82) at seeded weights."""
    keras.utils.set_random_seed(seed)
    return (
        _keras_model(N_STATES, N_ACTIONS, softmax=True),
        _keras_model(N_STATES, 1, softmax=False),
        _keras_model(N_STATES + 1, 1, softmax=False),
    )


def _make_agent(H=1, seed=0):
    return REF_AGENT(*_models(seed), slow_lr=SLOW_LR, fast_lr=FAST_LR,
                     gamma=GAMMA, H=H)


def _make_adversary(cls, seed):
    return cls(*_models(seed), slow_lr=SLOW_LR, fast_lr=FAST_LR, gamma=GAMMA)


def _to_params(keras_weights):
    """Keras [W1,b1,W2,b2,W3,b3] -> our ((W,b), (W,b), (W,b))."""
    w = [jnp.asarray(a) for a in keras_weights]
    return tuple((w[2 * i], w[2 * i + 1]) for i in range(len(w) // 2))


def _to_keras(params):
    return [np.asarray(a) for wb in params for a in wb]


def _stack_msgs(msgs):
    """List of per-neighbor param tuples -> leaves with leading n_in."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *msgs)


def _batch(rng, B=16):
    s = rng.normal(size=(B, N_AGENTS, N_STATES)).astype(np.float32)
    ns = rng.normal(size=(B, N_AGENTS, N_STATES)).astype(np.float32)
    a = rng.integers(0, N_ACTIONS, size=(B, N_AGENTS, 1)).astype(np.float32)
    r = rng.normal(size=(B, 1)).astype(np.float32)
    return s, ns, a, r


def _cfg(H=1):
    return Config(H=H, fast_lr=FAST_LR, slow_lr=SLOW_LR, gamma=GAMMA)


def test_local_critic_fit_message_golden():
    """The transmitted message of critic_update_local, and its restore."""
    rng = np.random.default_rng(0)
    agent = _make_agent()
    s, ns, _, r = _batch(rng)
    before = agent.critic.get_weights()

    msg_ref, ref_loss = agent.critic_update_local(
        tf.constant(s), tf.constant(ns), tf.constant(r)
    )
    # restore semantics: the agent's own net is unchanged
    for a, b in zip(agent.critic.get_weights(), before):
        np.testing.assert_array_equal(a, b)

    mine, my_loss = coop_local_critic_fit(
        _to_params(before),
        jnp.asarray(s),
        jnp.asarray(ns),
        jnp.asarray(r),
        jnp.ones((len(s),), jnp.float32),
        _cfg(),
    )
    for ref_a, my_a in zip(msg_ref, _to_keras(mine)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(my_loss), float(ref_loss), rtol=1e-4)


def test_local_tr_fit_message_golden():
    rng = np.random.default_rng(1)
    agent = _make_agent()
    s, _, a, r = _batch(rng)
    sa = np.concatenate([s, a], axis=-1)
    before = agent.TR.get_weights()

    msg_ref, ref_loss = agent.TR_update_local(tf.constant(sa), tf.constant(r))

    mine, my_loss = coop_local_tr_fit(
        _to_params(before),
        jnp.asarray(sa),
        jnp.asarray(r),
        jnp.ones((len(s),), jnp.float32),
        _cfg(),
    )
    for ref_a, my_a in zip(msg_ref, _to_keras(mine)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(my_loss), float(ref_loss), rtol=1e-4)


@pytest.mark.parametrize("H", [0, 1])
def test_phase2_consensus_golden(H):
    """Hidden consensus + projection + team update, trainer ordering
    (train_agents.py:125-145), against the reference agent end to end."""
    rng = np.random.default_rng(2 + H)
    agent = _make_agent(H=H)
    s, _, _, _ = _batch(rng)
    own_weights = agent.critic.get_weights()

    # Four messages: own (index 0) + three perturbed neighbors.
    msgs = [own_weights]
    for k in range(3):
        msgs.append([a + rng.normal(scale=0.05, size=a.shape).astype(np.float32)
                     for a in own_weights])

    agent.resilient_consensus_critic_hidden(msgs)
    agg = agent.resilient_consensus_critic(tf.constant(s), msgs)
    agent.critic_update_team(tf.constant(s), agg)
    ref_final = agent.critic.get_weights()

    mine = consensus_update_one(
        _to_params(own_weights),
        _stack_msgs([_to_params(m) for m in msgs]),
        jnp.asarray(s),
        jnp.ones((len(s),), jnp.float32),
        _cfg(H=H),
    )
    for ref_a, my_a in zip(ref_final, _to_keras(mine)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)


# ----------------------------------------------------------------------
# Adversary composites (C5-C7). The reference's adversary fits are
# SHUFFLED minibatch runs (fit(epochs=10, batch_size=32)) whose exact
# trajectory depends on Keras's private shuffle RNG — but with B <=
# batch_size every epoch is one full batch, the shuffle is a no-op, and
# the composite becomes exactly comparable. B=16 below.
# ----------------------------------------------------------------------


adversarial = pytest.mark.skipif(
    REF_GREEDY is None, reason="reference adversarial agents not importable"
)


@adversarial
def test_greedy_critic_and_tr_fit_golden():
    """Greedy local fits PERSIST and are transmitted
    (adversarial_CAC_agents.py:228-253): 10 single-batch epochs here."""
    rng = np.random.default_rng(5)
    agent = _make_adversary(REF_GREEDY, seed=10)
    s, ns, a, r = _batch(rng)
    sa = np.concatenate([s, a], axis=-1)
    critic_before = agent.critic.get_weights()
    tr_before = agent.TR.get_weights()

    ref_critic, _ = agent.critic_update_local(
        tf.constant(s), tf.constant(ns), tf.constant(r)
    )
    ref_tr, _ = agent.TR_update_local(tf.constant(sa), tf.constant(r))

    cfg = _cfg()
    mask = jnp.ones((len(s),), jnp.float32)
    mine_critic, _ = adv_critic_fit(
        jax.random.PRNGKey(0), _to_params(critic_before),
        jnp.asarray(s), jnp.asarray(ns), jnp.asarray(r), mask, cfg,
    )
    mine_tr, _ = adv_tr_fit(
        jax.random.PRNGKey(1), _to_params(tr_before),
        jnp.asarray(sa), jnp.asarray(r), mask, cfg,
    )
    for ref_a, my_a in zip(ref_critic, _to_keras(mine_critic)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)
    for ref_a, my_a in zip(ref_tr, _to_keras(mine_tr)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)


@adversarial
def test_malicious_compromised_fits_golden():
    """The Byzantine poisoning path (adversarial_CAC_agents.py:121-165):
    compromised critic/TR trained toward the NEGATED cooperative reward."""
    rng = np.random.default_rng(6)
    agent = _make_adversary(REF_MALICIOUS, seed=11)
    s, ns, a, r_coop = _batch(rng)
    sa = np.concatenate([s, a], axis=-1)
    r_comp = -r_coop
    critic_before = agent.critic.get_weights()
    tr_before = agent.TR.get_weights()

    ref_critic, _ = agent.critic_update_compromised(
        tf.constant(s), tf.constant(ns), tf.constant(r_comp)
    )
    ref_tr, _ = agent.TR_update_compromised(tf.constant(sa), tf.constant(r_comp))

    cfg = _cfg()
    mask = jnp.ones((len(s),), jnp.float32)
    mine_critic, _ = adv_critic_fit(
        jax.random.PRNGKey(0), _to_params(critic_before),
        jnp.asarray(s), jnp.asarray(ns), jnp.asarray(r_comp), mask, cfg,
    )
    mine_tr, _ = adv_tr_fit(
        jax.random.PRNGKey(1), _to_params(tr_before),
        jnp.asarray(sa), jnp.asarray(r_comp), mask, cfg,
    )
    for ref_a, my_a in zip(ref_critic, _to_keras(mine_critic)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)
    for ref_a, my_a in zip(ref_tr, _to_keras(mine_tr)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)


@adversarial
def test_malicious_private_critic_fit_golden():
    """The malicious agent's PRIVATE local critic (adversarial_CAC_agents
    .py:137-152): trained on its own reward via a weight swap, persisted
    to critic_local_weights, compromised critic untouched."""
    rng = np.random.default_rng(7)
    agent = _make_adversary(REF_MALICIOUS, seed=11)
    s, ns, _, r = _batch(rng)
    local_before = [np.array(a) for a in agent.critic_local_weights]
    compromised_before = agent.critic.get_weights()

    agent.critic_update_local(tf.constant(s), tf.constant(ns), tf.constant(r))
    # compromised critic restored after the swap
    for a, b in zip(agent.critic.get_weights(), compromised_before):
        np.testing.assert_array_equal(a, b)

    mine, _ = adv_critic_fit(
        jax.random.PRNGKey(0), _to_params(local_before),
        jnp.asarray(s), jnp.asarray(ns), jnp.asarray(r),
        jnp.ones((len(s),), jnp.float32), _cfg(),
    )
    for ref_a, my_a in zip(agent.critic_local_weights, _to_keras(mine)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)


@adversarial
def test_adversary_actor_update_golden():
    """Adversary actor: local-TD sample weights, fit(batch_size=200,
    epochs=1) — a single Adam batch at B=16 (adversarial_CAC_agents.py:
    211-226; the malicious variant drives it off the private critic)."""
    rng = np.random.default_rng(8)
    agent = _make_adversary(REF_GREEDY, seed=10)
    s, ns, a, r = _batch(rng)
    a_own = a[:, 0, :]
    actor_before = agent.actor.get_weights()
    critic_w = agent.critic.get_weights()

    agent.actor_update(
        tf.constant(s), tf.constant(ns), tf.constant(r), tf.constant(a_own)
    )
    ref_final = agent.actor.get_weights()

    actor_p = _to_params(actor_before)
    new_actor, _, _ = adv_actor_update(
        jax.random.PRNGKey(0),
        actor_p,
        adam_init(actor_p),
        _to_params(critic_w),
        jnp.asarray(s),
        jnp.asarray(ns),
        jnp.asarray(r),
        jnp.asarray(a_own[:, 0]),
        _cfg(),
    )
    for ref_a, my_a in zip(ref_final, _to_keras(new_actor)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)


def test_full_update_block_composition_golden():
    """The ENTIRE update block — n_epochs x (phase I local fits -> phase
    II consensus, in the trainer's exact per-node order,
    train_agents.py:100-145) followed by the phase III actor step — for a
    5-agent all-cooperative network on the reference topology, reference
    objects vs our single fused ``update_block``. Pins the composition
    (message wiring, epoch chaining, actor window), not just the
    per-primitive math."""
    from rcmarl_tpu.config import Roles, circulant_in_nodes
    from rcmarl_tpu.training.update import init_agent_params, update_block

    rng = np.random.default_rng(9)
    n_epochs, B, B_fresh = 3, 50, 20
    in_nodes = circulant_in_nodes(N_AGENTS, 4)
    cfg = Config(
        n_agents=N_AGENTS,
        agent_roles=(Roles.COOPERATIVE,) * N_AGENTS,
        in_nodes=in_nodes,
        H=1,
        n_epochs=n_epochs,
        fast_lr=FAST_LR,
        slow_lr=SLOW_LR,
        gamma=GAMMA,
    )
    agents = [_make_agent(H=1, seed=20 + i) for i in range(N_AGENTS)]
    init_ws = [
        (ag.actor.get_weights(), ag.critic.get_weights(), ag.TR.get_weights())
        for ag in agents
    ]

    s = rng.normal(size=(B, N_AGENTS, N_STATES)).astype(np.float32)
    ns = rng.normal(size=(B, N_AGENTS, N_STATES)).astype(np.float32)
    a = rng.integers(0, N_ACTIONS, size=(B, N_AGENTS, 1)).astype(np.float32)
    r = rng.normal(size=(B, N_AGENTS, 1)).astype(np.float32)
    sa = np.concatenate([s, a], axis=-1)
    ts, tns, tsa = tf.constant(s), tf.constant(ns), tf.constant(sa)

    # ---- reference side: the trainer's exact loop ----
    for _ in range(n_epochs):
        critic_ws, tr_ws = [], []
        for node in range(N_AGENTS):
            r_node = tf.constant(r[:, node])
            x, _ = agents[node].TR_update_local(tsa, r_node)
            y, _ = agents[node].critic_update_local(ts, tns, r_node)
            tr_ws.append(x)
            critic_ws.append(y)
        for node in range(N_AGENTS):
            c_in = [critic_ws[i] for i in in_nodes[node]]
            t_in = [tr_ws[i] for i in in_nodes[node]]
            agents[node].resilient_consensus_critic_hidden(c_in)
            agents[node].resilient_consensus_TR_hidden(t_in)
            c_agg = agents[node].resilient_consensus_critic(ts, c_in)
            t_agg = agents[node].resilient_consensus_TR(tsa, t_in)
            agents[node].critic_update_team(ts, c_agg)
            agents[node].TR_update_team(tsa, t_agg)
    fs, fns, fsa = s[-B_fresh:], ns[-B_fresh:], sa[-B_fresh:]
    for node in range(N_AGENTS):
        agents[node].actor_update(
            tf.constant(fs),
            tf.constant(fns),
            tf.constant(fsa),
            tf.constant(a[-B_fresh:, node]),
        )

    # ---- our side: one fused block over the pre-loop weights ----
    stack = lambda ws: _stack_msgs([_to_params(w) for w in ws])
    actor0 = stack([w[0] for w in init_ws])
    critic0 = stack([w[1] for w in init_ws])
    tr0 = stack([w[2] for w in init_ws])
    params = init_agent_params(jax.random.PRNGKey(0), cfg)._replace(
        actor=actor0, critic=critic0, tr=tr0, critic_local=critic0
    )
    params = params._replace(actor_opt=jax.vmap(adam_init)(params.actor))

    mk = lambda lo: Batch(
        s=jnp.asarray(s[lo:]),
        ns=jnp.asarray(ns[lo:]),
        a=jnp.asarray(a[lo:]),
        r=jnp.asarray(r[lo:]),
        mask=jnp.ones((B - lo,), jnp.float32),
    )
    out = update_block(cfg, params, mk(0), mk(B - B_fresh), jax.random.PRNGKey(1))

    for node in range(N_AGENTS):
        for ref_a, my_a in zip(
            agents[node].critic.get_weights(),
            _to_keras(jax.tree.map(lambda l: l[node], out.critic)),
        ):
            np.testing.assert_allclose(my_a, ref_a, rtol=2e-3, atol=2e-5)
        for ref_a, my_a in zip(
            agents[node].TR.get_weights(),
            _to_keras(jax.tree.map(lambda l: l[node], out.tr)),
        ):
            np.testing.assert_allclose(my_a, ref_a, rtol=2e-3, atol=2e-5)
        for ref_a, my_a in zip(
            agents[node].actor.get_weights(),
            _to_keras(jax.tree.map(lambda l: l[node], out.actor)),
        ):
            np.testing.assert_allclose(my_a, ref_a, rtol=2e-3, atol=2e-5)


def test_coop_actor_update_golden():
    """Sample-weighted sparse-CE Adam step with the global TD error."""
    rng = np.random.default_rng(4)
    agent = _make_agent()
    s, ns, a, _ = _batch(rng)
    sa = np.concatenate([s, a], axis=-1)
    a_own = a[:, 0, :]  # this agent's own actions, (B, 1)
    actor_before = agent.actor.get_weights()
    critic_w = agent.critic.get_weights()
    tr_w = agent.TR.get_weights()

    agent.actor_update(
        tf.constant(s), tf.constant(ns), tf.constant(sa), tf.constant(a_own)
    )
    ref_final = agent.actor.get_weights()

    actor_p = _to_params(actor_before)
    new_actor, _, _ = coop_actor_update(
        actor_p,
        adam_init(actor_p),
        _to_params(critic_w),
        _to_params(tr_w),
        jnp.asarray(s),
        jnp.asarray(ns),
        jnp.asarray(sa),
        jnp.asarray(a_own[:, 0]),
        _cfg(),
    )
    for ref_a, my_a in zip(ref_final, _to_keras(new_actor)):
        np.testing.assert_allclose(my_a, ref_a, rtol=1e-4, atol=1e-5)
