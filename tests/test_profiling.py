"""Profiling utilities (SURVEY.md §5 tracing/profiling subsystem)."""

from pathlib import Path

import pytest
import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.utils.profiling import (
    Timer,
    consensus_tags,
    profile_consensus,
    profile_phases,
    trace,
)


def tiny_cfg():
    return Config(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE, Roles.COOPERATIVE, Roles.GREEDY),
        in_nodes=circulant_in_nodes(3, 2),
        nrow=3,
        ncol=3,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=1,
        buffer_size=16,
        hidden=(8, 8),
        coop_fit_steps=1,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
        n_episodes=2,
    )


def test_timer_forces_completion():
    t = Timer().start()
    x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
    dt = t.stop(x)
    assert dt > 0 and t.elapsed == dt


@pytest.mark.slow
def test_profile_phases_covers_training_subprograms():
    times = profile_phases(tiny_cfg(), reps=1)
    assert set(times) == {
        "rollout_block",
        "critic_tr_epoch",
        "actor_phase",
        "full_block",
    }
    assert all(v > 0 for v in times.values())


def test_profile_consensus_covers_components_and_tags():
    """The consensus micro-breakdown: one timing per component the
    crossover policies tune, plus the (n_in, H, volume) tags refits key
    on — for both trim strategies and both netstack arms. epoch_other is
    a signed residual (epoch - consensus - phase1_fits) and may be
    slightly negative on tiny configs, so only the true timings are
    required positive."""
    for impl, netstack in (
        ("xla", True),
        ("xla", False),
        ("xla_sort", True),
    ):
        cfg = tiny_cfg().replace(consensus_impl=impl, netstack=netstack)
        times = profile_consensus(cfg, reps=1)
        assert set(times) == {
            "gather",
            "trim_bounds",
            "clip_mean",
            "consensus",
            "phase1_fits",
            "epoch",
            "epoch_other",
        }
        assert all(v > 0 for k, v in times.items() if k != "epoch_other")
    tags = consensus_tags(tiny_cfg())
    assert tags["n_in"] == 2 and tags["H"] == 0 and tags["n_agents"] == 3
    assert tags["volume"] == 6
    # gathered volume = N * n_in * per-agent critic params
    # ((8x6 + 8) + (8x8 + 8) + (8x1 + 1) = 137 params for hidden=(8,8))
    assert tags["gathered_numel"] == 3 * 2 * 137


def test_trace_writes_artifacts(tmp_path):
    logdir = tmp_path / "trace"
    with trace(str(logdir)):
        jax.block_until_ready(jnp.ones((32, 32)) @ jnp.ones((32, 32)))
    files = list(Path(logdir).rglob("*"))
    assert any(f.is_file() for f in files)
