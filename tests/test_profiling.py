"""Profiling utilities (SURVEY.md §5 tracing/profiling subsystem)."""

from pathlib import Path

import pytest
import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.utils.profiling import (
    Timer,
    consensus_tags,
    profile_consensus,
    profile_phases,
    trace,
)


def tiny_cfg():
    return Config(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE, Roles.COOPERATIVE, Roles.GREEDY),
        in_nodes=circulant_in_nodes(3, 2),
        nrow=3,
        ncol=3,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=1,
        buffer_size=16,
        hidden=(8, 8),
        coop_fit_steps=1,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
        n_episodes=2,
    )


def test_timer_forces_completion():
    t = Timer().start()
    x = jnp.ones((64, 64)) @ jnp.ones((64, 64))
    dt = t.stop(x)
    assert dt > 0 and t.elapsed == dt


def test_mesh_fingerprint_is_device_count_plus_axis_sizes():
    """The id sharded bench/profile/AUDIT rows carry next to
    cost_fingerprint: device count + named axis sizes, so MULTICHIP
    evidence is tied to the exact mesh that produced it."""
    from rcmarl_tpu.utils.profiling import mesh_fingerprint

    if len(jax.devices()) >= 8:
        from rcmarl_tpu.parallel.seeds import make_mesh

        assert mesh_fingerprint(make_mesh(8, seed_axis=2)) == (
            "8d:seed=2,agent=4"
        )
        assert mesh_fingerprint(make_mesh(2, seed_axis=1)) == (
            "2d:seed=1,agent=2"
        )
    else:  # pragma: no cover - single-device CI fallback
        from rcmarl_tpu.parallel.seeds import make_mesh

        assert mesh_fingerprint(make_mesh(1, seed_axis=1)) == (
            "1d:seed=1,agent=1"
        )


@pytest.mark.slow
def test_profile_phases_covers_training_subprograms():
    times = profile_phases(tiny_cfg(), reps=1)
    assert set(times) == {
        "rollout_block",
        "critic_tr_epoch",
        "actor_phase",
        "full_block",
    }
    assert all(v > 0 for v in times.values())


# ~22s — tier-1 870s wall-budget shed; still runs under
# `pytest tests/` (no -m filter)
@pytest.mark.slow
def test_profile_consensus_covers_components_and_tags():
    """The consensus micro-breakdown: one timing per component the
    crossover policies tune, plus the (n_in, H, volume) tags refits key
    on — for both trim strategies, both netstack arms, and the fused
    fitstack arm. Phase-I fits are split per flavor family (fit_coop /
    fit_adv, the keys the fused-scan A/B attributes wins by;
    phase1_fits stays their sum), and epoch_other is a signed TRUE
    residual (epoch - gather - consensus - fit_coop - fit_adv) that may
    be slightly negative on tiny configs, so only the true timings are
    required positive."""
    coop_only = (Roles.COOPERATIVE,) * 3
    for impl, netstack, roles in (
        # the production dual arm with a greedy cast: full key set,
        # fit_adv measured through the per-flavor scans
        ("xla", False, None),
        # the netstack-pair and sort-strategy micro paths on the
        # cheaper all-coop cast (fit_adv keyed out)
        ("xla", True, coop_only),
        ("xla_sort", True, coop_only),
    ):
        cfg = tiny_cfg().replace(consensus_impl=impl, netstack=netstack)
        if roles is not None:
            cfg = cfg.replace(agent_roles=roles)
        _check_micro_keys(profile_consensus(cfg, reps=1), adv=roles is None)


@pytest.mark.slow
def test_profile_consensus_fitstack_arm():
    """The same micro-breakdown on the fused cross-flavor fit arm
    (fit_coop/fit_adv measured through the fused scans)."""
    cfg = tiny_cfg().replace(fitstack=True)
    _check_micro_keys(profile_consensus(cfg, reps=1), adv=True)


def _check_micro_keys(times, adv):
    # fit_adv appears exactly when the config casts adversary roles
    assert set(times) == {
        "gather",
        "trim_bounds",
        "clip_mean",
        "consensus",
        "fit_coop",
        "phase1_fits",
        "epoch",
        "epoch_other",
    } | ({"fit_adv"} if adv else set())
    assert times["phase1_fits"] == times["fit_coop"] + times.get(
        "fit_adv", 0.0
    )
    assert all(v > 0 for k, v in times.items() if k != "epoch_other")
    tags = consensus_tags(tiny_cfg())
    assert tags["n_in"] == 2 and tags["H"] == 0 and tags["n_agents"] == 3
    assert tags["volume"] == 6
    # gathered volume = N * n_in * per-agent critic params
    # ((8x6 + 8) + (8x8 + 8) + (8x1 + 1) = 137 params for hidden=(8,8))
    assert tags["gathered_numel"] == 3 * 2 * 137


# ~37s — tier-1 870s wall-budget shed
@pytest.mark.slow
def test_trace_writes_artifacts(tmp_path):
    logdir = tmp_path / "trace"
    with trace(str(logdir)):
        jax.block_until_ready(jnp.ones((32, 32)) @ jnp.ones((32, 32)))
    files = list(Path(logdir).rglob("*"))
    assert any(f.is_file() for f in files)
