"""Property-based sort-vs-selection equivalence (hypothesis).

Randomized twin of tests/test_selection.py's deterministic matrix: over
arbitrary f32 inputs (including duplicates and adversarial magnitudes),
the selection-based trim bounds must reproduce the sort-based
aggregation BITWISE for every legal (H, n_in), masked and unmasked,
static and traced H. Guarded like the other property modules: a missing
hypothesis (the `test` extra) is a skip, never a collection error.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

from rcmarl_tpu.ops.aggregation import resilient_aggregate

finite = st.floats(-1e6, 1e6, allow_nan=False, width=32)


@st.composite
def vals_and_h(draw, min_n=3, max_n=9, m=5):
    n = draw(st.integers(min_n, max_n))
    H = draw(st.integers(0, (n - 1) // 2))
    vals = draw(arrays(np.float32, (n, m), elements=finite))
    return vals, H


@settings(max_examples=40, deadline=None)
@given(vals_and_h())
def test_select_matches_sort_bitwise(case):
    vals, H = case
    a = resilient_aggregate(jnp.asarray(vals), H, impl="xla_sort")
    b = resilient_aggregate(jnp.asarray(vals), H, impl="xla")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@settings(max_examples=25, deadline=None)
@given(vals_and_h())
def test_traced_h_select_matches_sort_bitwise(case):
    vals, H = case
    v = jnp.asarray(vals)
    want = resilient_aggregate(v, H, impl="xla_sort")
    sel = jax.jit(lambda x, h: resilient_aggregate(x, h, impl="xla"))(
        v, jnp.int32(H)
    )
    np.testing.assert_array_equal(np.asarray(want), np.asarray(sel))


@settings(max_examples=25, deadline=None)
@given(vals_and_h(min_n=3, max_n=7), st.integers(1, 3))
def test_masked_select_matches_sort_bitwise(case, pad):
    vals, H = case
    d = vals.shape[0]
    padded = np.concatenate(
        [vals, np.full((pad, vals.shape[1]), np.inf, np.float32)], axis=0
    )
    valid = jnp.asarray([1.0] * d + [0.0] * pad)
    a = resilient_aggregate(
        jnp.asarray(padded), H, impl="xla_sort", valid=valid
    )
    b = resilient_aggregate(jnp.asarray(padded), H, impl="xla", valid=valid)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
