"""Property-based gossip-mix contracts (hypothesis).

Randomized twin of tests/test_gossip.py's deterministic matrix, run
EAGERLY through the unjitted mix body (no per-example compile churn)
over a fixed tiny parameter template whose values hypothesis replaces:

- **Envelope**: with any ≤ gossip_H Byzantine replicas (any mode), every
  healthy replica's post-mix parameters are finite and inside the
  healthy replicas' elementwise min/max envelope — the paper's
  trimmed-mean projection guarantee, lifted to the replica level. The
  guarantee survives NaN byzantine counts that trigger the
  degree-deficit fallback (the receiver keeps its own value, which is
  itself inside the envelope).
- **Finiteness**: under ANY replica fault plan (arbitrary probabilistic
  drop/stale/corrupt/flip/NaN/Inf rates plus Byzantine members), the
  sanitized trimmed mix of finite own-parameters stays finite for every
  replica — non-finite payloads can only be excluded, never averaged in.

Guarded like the other property modules: a missing hypothesis (the
`test` extra) is a skip, never a collection error.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

from rcmarl_tpu.config import Config
from rcmarl_tpu.faults import BYZANTINE_MODES, ReplicaFaultPlan
from rcmarl_tpu.ops.aggregation import ravel_neighbor_tree
from rcmarl_tpu.parallel.gossip import (
    _gossip_mix_block,
    _mix_tree,
    replica_in_nodes,
    replica_seeds,
)
from rcmarl_tpu.parallel.seeds import init_states

R = 5

#: head-only (hidden=()) nets keep P_total tiny so each hypothesis
#: example moves a (5, P) block, not a model
_BASE = dict(
    n_agents=3,
    agent_roles=(0, 0, 0),
    in_nodes=((0, 1, 2), (1, 2, 0), (2, 0, 1)),
    nrow=3,
    ncol=3,
    hidden=(),
    replicas=R,
    gossip_graph="full",
    gossip_every=1,
)


def _cfg(**kw):
    return Config(**{**_BASE, **kw})


_TEMPLATE = init_states(_cfg(gossip_H=1), replica_seeds(_cfg(gossip_H=1)))
_FLAT0, _UNRAVEL = ravel_neighbor_tree(_mix_tree(_TEMPLATE.params))
P = int(_FLAT0.shape[1])


def params_from(vals: np.ndarray):
    """Replica-stacked AgentParams whose mixable families hold ``vals``
    ((R, P) rows) — the template supplies structure and Adam state."""
    trees = jax.vmap(_UNRAVEL)(jnp.asarray(vals))
    actor, critic, tr, critic_local = trees
    return _TEMPLATE.params._replace(
        actor=actor, critic=critic, tr=tr, critic_local=critic_local
    )


def mix_flat(cfg, vals: np.ndarray, rnd: int = 0) -> np.ndarray:
    """(R, P) post-mix values via the UNJITTED mix body (eager)."""
    mixed, _ = _gossip_mix_block(
        cfg,
        params_from(vals),
        params_from(vals),
        jnp.asarray(rnd, jnp.int32),
        jnp.zeros(R, bool),
    )
    flat, _ = ravel_neighbor_tree(_mix_tree(mixed))
    return np.asarray(flat)


finite_vals = arrays(
    np.float32,
    (R, P),
    elements=st.floats(-1e4, 1e4, allow_nan=False, width=32),
)


@st.composite
def byzantine_case(draw):
    H = draw(st.integers(1, 2))  # full R=5 graph: 2H <= 4
    n_byz = draw(st.integers(1, H))
    byz = draw(
        st.lists(
            st.integers(0, R - 1), min_size=n_byz, max_size=n_byz, unique=True
        )
    )
    mode = draw(st.sampled_from(BYZANTINE_MODES))
    return H, tuple(sorted(byz)), mode


@given(vals=finite_vals, case=byzantine_case())
@settings(max_examples=25, deadline=None)
def test_healthy_replicas_stay_in_healthy_envelope(vals, case):
    H, byz, mode = case
    cfg = _cfg(
        gossip_H=H,
        replica_fault_plan=ReplicaFaultPlan(
            byzantine_replicas=byz, byzantine_mode=mode
        ),
    )
    post = mix_flat(cfg, vals)
    healthy = [r for r in range(R) if r not in byz]
    lo = vals[healthy].min(axis=0)
    hi = vals[healthy].max(axis=0)
    tol = 1e-4 * np.maximum(1.0, np.abs(hi) + np.abs(lo))
    for r in healthy:
        assert np.isfinite(post[r]).all()
        assert (post[r] >= lo - tol).all()
        assert (post[r] <= hi + tol).all()


@st.composite
def arbitrary_plan(draw):
    p = lambda: draw(st.floats(0.0, 1.0))
    n_byz = draw(st.integers(0, R - 1))
    byz = draw(
        st.lists(
            st.integers(0, R - 1), min_size=n_byz, max_size=n_byz, unique=True
        )
    )
    return ReplicaFaultPlan(
        drop_p=p(),
        stale_p=p(),
        corrupt_p=p(),
        corrupt_scale=draw(st.floats(0.0, 10.0)),
        flip_p=p(),
        nan_p=p(),
        inf_p=p(),
        byzantine_replicas=tuple(sorted(byz)),
        byzantine_mode=draw(st.sampled_from(BYZANTINE_MODES)),
        seed=draw(st.integers(0, 7)),
    )


@given(vals=finite_vals, plan=arbitrary_plan(), rnd=st.integers(0, 3))
@settings(max_examples=25, deadline=None)
def test_trimmed_mix_never_goes_nonfinite(vals, plan, rnd):
    """Whatever the links deliver, sanitized trimming of finite own
    parameters yields finite mixes for EVERY replica (non-finite
    payloads become exclusions; the deficit fallback keeps own)."""
    post = mix_flat(_cfg(gossip_H=2, replica_fault_plan=plan), vals, rnd=rnd)
    assert np.isfinite(post).all()


def test_random_geometric_graph_feeds_the_same_guarantee():
    """One deterministic spot-check off the full graph: the envelope
    holds on a random-geometric topology when the Byzantine count per
    neighborhood cannot exceed gossip_H (here: 1 bomber, H=1)."""
    cfg = _cfg(
        gossip_graph="random_geometric",
        gossip_degree=3,
        gossip_H=1,
        replica_fault_plan=ReplicaFaultPlan(
            byzantine_replicas=(4,), byzantine_mode="nan"
        ),
    )
    rng = np.random.default_rng(0)
    vals = rng.normal(size=(R, P)).astype(np.float32)
    post = mix_flat(cfg, vals)
    healthy = [0, 1, 2, 3]
    lo, hi = vals[healthy].min(axis=0), vals[healthy].max(axis=0)
    in_nodes = replica_in_nodes(cfg)
    assert all(sum(j == 4 for j in row[1:]) <= 1 for row in in_nodes)
    tol = 1e-5
    for r in healthy:
        assert np.isfinite(post[r]).all()
        assert (post[r] >= lo - tol).all() and (post[r] <= hi + tol).all()
