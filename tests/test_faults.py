"""Transport-fault injection + non-finite-hardened consensus + guard rails.

Covers ISSUE 2's robustness surface end to end:

- sanitize mode: NaN/±Inf-poisoned neighbor blocks produce BITWISE-
  identical finite aggregates across all six impls (xla, xla_sort,
  masked, traced-H, pallas select, pallas sort) and equal the
  mask-excluded reference; degree deficits fall back to the own value.
- the unguarded seed behavior — one NaN bomb poisons every backend —
  is pinned as a regression test (the failure mode the subsystem
  defends against must stay reproducible).
- FaultPlan semantics: per-link draws shared across leaves, self slot
  exempt, stage composition, determinism, inactive-plan identity.
- trainer guard rails: injected-fault runs complete with finite params
  via rollback/retry/skip; sanitize keeps the run healthy with
  degradation counters instead.
- checkpoint integrity: payload checksum, corruption/truncation
  detection, rotation + fallback resume.
- sweep per-cell fault isolation (one failing cell is retried, then
  recorded and skipped).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.faults import (
    FaultPlan,
    apply_link_faults,
    fault_diagnostics,
    tree_all_finite,
)
from rcmarl_tpu.ops.aggregation import resilient_aggregate
from rcmarl_tpu.ops.pallas_aggregation import fused_resilient_aggregate


def tiny_cfg(**kw):
    base = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=2,
        buffer_size=16,
        hidden=(8, 8),
        coop_fit_steps=2,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
        n_episodes=4,
        H=1,
    )
    base.update(kw)
    return Config(**base)


def params_finite(state) -> bool:
    return all(
        np.all(np.isfinite(np.asarray(l)))
        for l in jax.tree.leaves(state.params)
    )


def poisoned_block(seed=0, n_in=7, m=23):
    """A neighbor block with two whole-row bombs and scattered
    element-level non-finites; returns (values, finite_row_indices,
    clean_column_indices)."""
    rng = np.random.default_rng(seed)
    vals = rng.normal(size=(n_in, m)).astype(np.float32)
    r_nan, r_inf = 2, n_in - 2
    vals[r_nan] = np.nan
    vals[r_inf] = np.inf
    c1, c2 = min(3, m - 1), m - 1
    vals[1, c1] = -np.inf
    vals[n_in - 1, c2] = np.nan
    keep = [i for i in range(n_in) if i not in (r_nan, r_inf)]
    clean = [c for c in range(m) if c not in (c1, c2)]
    return jnp.asarray(vals), keep, clean


def six_impl_outputs(v, H):
    """The sanitized aggregate by every backend (static, masked,
    traced-H, and both Pallas kernel variants in interpret mode)."""
    n_in = v.shape[0]
    ones = jnp.ones((n_in,))
    return {
        "xla": resilient_aggregate(v, H, impl="xla", sanitize=True),
        "xla_sort": resilient_aggregate(v, H, impl="xla_sort", sanitize=True),
        "masked": resilient_aggregate(
            v, H, impl="xla", valid=ones, sanitize=True
        ),
        "masked_sort": resilient_aggregate(
            v, H, impl="xla_sort", valid=ones, sanitize=True
        ),
        "traced": jax.jit(
            lambda x, h: resilient_aggregate(x, h, impl="xla", sanitize=True)
        )(v, jnp.int32(H)),
        "traced_sort": jax.jit(
            lambda x, h: resilient_aggregate(
                x, h, impl="xla_sort", sanitize=True
            )
        )(v, jnp.int32(H)),
        "pallas": fused_resilient_aggregate(
            v, H, variant="select", interpret=True, sanitize=True
        ),
        "pallas_sort": fused_resilient_aggregate(
            v, H, variant="sort", interpret=True, sanitize=True
        ),
    }


class TestSanitizedAggregation:
    def test_unsanitized_nan_poisons_every_backend(self):
        """The seed behavior this subsystem exists for: WITHOUT sanitize,
        a single NaN payload poisons the trim bounds and the clipped
        mean of every backend (regression pin)."""
        rng = np.random.default_rng(3)
        vals = rng.normal(size=(5, 4)).astype(np.float32)
        vals[2, 1] = np.nan
        v = jnp.asarray(vals)
        for out in [
            resilient_aggregate(v, 1, impl="xla"),
            resilient_aggregate(v, 1, impl="xla_sort"),
            fused_resilient_aggregate(v, 1, variant="select", interpret=True),
            fused_resilient_aggregate(v, 1, variant="sort", interpret=True),
        ]:
            assert not np.isfinite(np.asarray(out)[1])

    @pytest.mark.parametrize("H", [0, 1, 2])
    def test_bitwise_cross_backend_agreement(self, H):
        """Acceptance criterion: with NaN/Inf payloads active, all
        sanitized backends produce IDENTICAL finite aggregates."""
        v, _, _ = poisoned_block(seed=10 + H)
        outs = six_impl_outputs(v, H)
        base = np.asarray(outs["xla"])
        assert np.all(np.isfinite(base))
        for name, out in outs.items():
            np.testing.assert_array_equal(
                base, np.asarray(out), err_msg=f"impl {name} diverges"
            )

    def test_whole_row_bombs_equal_mask_excluded_reference(self):
        """Sanitizing whole-row bombs == aggregating only the surviving
        rows with the plain kernel (the semantics contract)."""
        v, keep, clean = poisoned_block(seed=2)
        for H in (0, 1, 2):
            out = np.asarray(resilient_aggregate(v, H, sanitize=True))
            # columns with element-level poison differ from the row-level
            # reference; compare on the clean columns only
            ref = resilient_aggregate(v[jnp.asarray(keep)], H)
            np.testing.assert_allclose(
                out[np.asarray(clean)],
                np.asarray(ref)[np.asarray(clean)],
                rtol=1e-5,
                atol=1e-6,
            )

    def test_elementwise_exclusion(self):
        """A single poisoned ELEMENT only affects its own column, which
        then equals the reference over that column's finite entries."""
        rng = np.random.default_rng(7)
        vals = rng.normal(size=(6, 5)).astype(np.float32)
        vals[3, 2] = np.inf
        v = jnp.asarray(vals)
        out = np.asarray(resilient_aggregate(v, 1, sanitize=True))
        clean = np.asarray(resilient_aggregate(jnp.asarray(vals), 1, sanitize=False))
        # unpoisoned columns: sanitize == plain kernel up to mean-order
        for c in (0, 1, 3, 4):
            np.testing.assert_allclose(out[c], clean[c], rtol=1e-5, atol=1e-6)
        # poisoned column: equals the 5-surviving-entry reference
        keep = jnp.asarray([0, 1, 2, 4, 5])
        ref = resilient_aggregate(v[keep][:, 2:3], 1)
        np.testing.assert_allclose(out[2], np.asarray(ref)[0], rtol=1e-5, atol=1e-6)

    def test_degree_deficit_keeps_own_value(self):
        """Fewer than 2H+1 finite survivors -> the agent keeps its own
        value instead of undefined clipping."""
        vals = np.full((4, 3), np.nan, np.float32)
        vals[0] = [1.0, 2.0, 3.0]
        vals[1] = [5.0, 6.0, 7.0]  # 2 finite < 2H+1 = 3
        out = resilient_aggregate(jnp.asarray(vals), 1, sanitize=True)
        np.testing.assert_array_equal(np.asarray(out), vals[0])

    def test_all_neighbors_poisoned_keeps_own_value(self):
        vals = np.full((5, 2), np.inf, np.float32)
        vals[0] = [3.0, -4.0]
        out = resilient_aggregate(jnp.asarray(vals), 2, sanitize=True)
        np.testing.assert_array_equal(np.asarray(out), vals[0])

    def test_h0_sanitize_is_finite_mean(self):
        rng = np.random.default_rng(11)
        vals = rng.normal(size=(5, 6)).astype(np.float32)
        vals[2, 0] = np.nan
        vals[4] = np.inf
        out = resilient_aggregate(jnp.asarray(vals), 0, sanitize=True)
        np.testing.assert_allclose(
            np.asarray(out), np.nanmean(np.where(np.isfinite(vals), vals, np.nan), axis=0),
            rtol=1e-5,
            atol=1e-6,
        )

    def test_masked_sanitize_excludes_pads_and_bombs(self):
        """valid-mask exclusion (padded ragged graphs) composes with
        finite exclusion: pad garbage AND bombs both drop out."""
        rng = np.random.default_rng(13)
        vals = rng.normal(size=(7, 4)).astype(np.float32)
        vals[2] = np.nan  # bomb inside the valid region
        vals[5] = 1e9  # pad garbage (finite but invalid)
        vals[6] = -np.inf  # pad garbage (non-finite)
        valid = jnp.asarray([1.0, 1.0, 1.0, 1.0, 1.0, 0.0, 0.0])
        out = resilient_aggregate(
            jnp.asarray(vals), 1, valid=valid, sanitize=True
        )
        ref = resilient_aggregate(jnp.asarray(vals[[0, 1, 3, 4]]), 1)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-6
        )

    def test_clean_inputs_sanitize_matches_plain(self):
        """On all-finite inputs the sanitized aggregate equals the plain
        kernel (same bounds, mean over all n_in entries)."""
        rng = np.random.default_rng(17)
        v = jnp.asarray(rng.normal(size=(5, 9)).astype(np.float32))
        for H in (0, 1, 2):
            np.testing.assert_allclose(
                np.asarray(resilient_aggregate(v, H, sanitize=True)),
                np.asarray(resilient_aggregate(v, H)),
                rtol=1e-5,
                atol=1e-6,
            )

    def test_own_row_poisoned_recovers_from_neighbors(self):
        """A non-finite OWN value is excluded like any other entry: with
        enough finite neighbors the aggregate is their trimmed mean —
        the agent can recover from its own divergence."""
        rng = np.random.default_rng(19)
        vals = rng.normal(size=(6, 4)).astype(np.float32)
        vals[0] = np.nan
        out = np.asarray(resilient_aggregate(jnp.asarray(vals), 1, sanitize=True))
        assert np.all(np.isfinite(out))
        fin = vals[1:]
        assert (out >= fin.min(0) - 1e-6).all() and (out <= fin.max(0) + 1e-6).all()

    def test_vmap_over_agents(self):
        v1, _, _ = poisoned_block(seed=23, n_in=5, m=8)
        v2, _, _ = poisoned_block(seed=29, n_in=5, m=8)
        stacked = jnp.stack([v1, v2])
        out = jax.vmap(
            lambda v: resilient_aggregate(v, 1, sanitize=True)
        )(stacked)
        for i, v in enumerate([v1, v2]):
            np.testing.assert_array_equal(
                np.asarray(out[i]),
                np.asarray(resilient_aggregate(v, 1, sanitize=True)),
            )

    def test_tree_version_sanitized(self):
        from rcmarl_tpu.ops.aggregation import resilient_aggregate_tree

        v1, _, _ = poisoned_block(seed=31, n_in=5, m=6)
        v2, _, _ = poisoned_block(seed=37, n_in=5, m=4)
        tree = {"a": v1, "b": v2}
        out = resilient_aggregate_tree(tree, 1, sanitize=True)
        for k in tree:
            np.testing.assert_array_equal(
                np.asarray(out[k]),
                np.asarray(resilient_aggregate(tree[k], 1, sanitize=True)),
            )


class TestFaultPlan:
    def test_validation(self):
        with pytest.raises(ValueError, match="drop_p"):
            FaultPlan(drop_p=1.5)
        with pytest.raises(ValueError, match="corrupt_scale"):
            FaultPlan(corrupt_scale=-1.0)

    def test_hashable_and_active(self):
        assert hash(FaultPlan(drop_p=0.1)) != hash(FaultPlan(drop_p=0.2))
        assert not FaultPlan().active
        assert FaultPlan(nan_p=0.01).active
        # corrupt_scale alone does not activate (no probability set)
        assert not FaultPlan(corrupt_scale=5.0).active

    def test_config_rejects_non_faultplan(self):
        with pytest.raises(ValueError, match="FaultPlan"):
            tiny_cfg(fault_plan={"drop_p": 0.1})

    def test_config_hashable_with_plan(self):
        cfg = tiny_cfg(fault_plan=FaultPlan(drop_p=0.1))
        hash(cfg)  # jit-staticness requirement


class TestPlanRoundTripEveryField:
    """The finite contract behind every chaos knob: BOTH plan classes
    must survive ``to_dict`` -> strict JSON -> rebuild with EVERY field
    at a non-default value, through the raw dict AND the checkpoint-
    header Config path (``config_from_json``). Field-introspective: a
    NEW knob added to either plan fails here until this test (and the
    checkpoint header it stands for) knows how to give it a non-default
    — a knob that silently drops from headers can't ship."""

    #: Non-default values per known non-probability field; every field
    #: not listed here must be a [0,1] probability (asserted below).
    _SPECIAL = {
        "corrupt_scale": 2.5,
        "seed": 7,
        "byzantine_replicas": (1, 3),
        "byzantine_mode": "sign_flip",
    }
    _PROBS = ("drop_p", "stale_p", "corrupt_p", "flip_p", "nan_p", "inf_p")

    def _nondefault(self, cls):
        import dataclasses

        kw = {}
        for i, f in enumerate(dataclasses.fields(cls)):
            if f.name in self._SPECIAL:
                kw[f.name] = self._SPECIAL[f.name]
            elif f.name in self._PROBS:
                kw[f.name] = round(0.01 * (i + 1), 3)
            else:
                pytest.fail(
                    f"{cls.__name__}.{f.name} is a NEW chaos knob this "
                    "round-trip test does not know: give it a "
                    "non-default here AND make sure config_from_json "
                    "rebuilds it (the checkpoint-header contract)"
                )
        return kw

    @pytest.mark.parametrize(
        "cls", [FaultPlan, None], ids=["FaultPlan", "ReplicaFaultPlan"]
    )
    def test_to_dict_json_rebuild_is_lossless(self, cls):
        import dataclasses
        import json as _json

        from rcmarl_tpu.faults import ReplicaFaultPlan

        cls = cls or ReplicaFaultPlan
        plan = cls(**self._nondefault(cls))
        d = _json.loads(_json.dumps(plan.to_dict()))  # strict JSON trip
        if "byzantine_replicas" in d:
            d["byzantine_replicas"] = tuple(d["byzantine_replicas"])
        rebuilt = cls(**d)
        assert rebuilt == plan
        assert dataclasses.asdict(rebuilt) == dataclasses.asdict(plan)
        # a DROPPED field would rebuild to its default and break
        # equality — prove the probe values are all non-default
        defaults = cls()
        for f in dataclasses.fields(cls):
            assert getattr(plan, f.name) != getattr(defaults, f.name), (
                f"{cls.__name__}.{f.name} probe value equals the "
                "default — the drop-detection has no teeth for it"
            )

    def test_config_header_roundtrip_both_plans(self):
        from rcmarl_tpu.faults import ReplicaFaultPlan
        from rcmarl_tpu.utils.checkpoint import (
            _config_to_json,
            config_from_json,
        )

        cfg = Config(
            replicas=4,
            gossip_every=1,
            gossip_graph="full",
            gossip_H=1,
            n_agents=3,
            agent_roles=(Roles.COOPERATIVE,) * 3,
            in_nodes=circulant_in_nodes(3, 3),
            nrow=3,
            ncol=3,
            fault_plan=FaultPlan(**self._nondefault(FaultPlan)),
            replica_fault_plan=ReplicaFaultPlan(
                **self._nondefault(ReplicaFaultPlan)
            ),
        )
        assert config_from_json(_config_to_json(cfg)) == cfg


class TestApplyLinkFaults:
    def _trees(self, key):
        N, n_in = 4, 3
        fresh = {
            "W": jax.random.normal(key, (N, n_in, 2, 5)),
            "b": jax.random.normal(jax.random.fold_in(key, 1), (N, n_in, 5)),
        }
        stale = jax.tree.map(lambda l: l * 100.0, fresh)
        return fresh, stale

    def test_inactive_plan_is_identity(self):
        key = jax.random.PRNGKey(0)
        fresh, stale = self._trees(key)
        out = apply_link_faults(key, fresh, stale, FaultPlan())
        for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(fresh)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_self_slot_never_faulted(self):
        key = jax.random.PRNGKey(1)
        fresh, stale = self._trees(key)
        plan = FaultPlan(
            drop_p=1.0, stale_p=1.0, corrupt_p=1.0, flip_p=1.0,
            nan_p=1.0, inf_p=1.0,
        )
        out = apply_link_faults(key, fresh, stale, plan)
        for o, f in zip(jax.tree.leaves(out), jax.tree.leaves(fresh)):
            np.testing.assert_array_equal(
                np.asarray(o)[:, 0], np.asarray(f)[:, 0]
            )
            # every non-self link carries the bomb
            assert not np.isfinite(np.asarray(o)[:, 1:]).any()

    def test_link_masks_shared_across_leaves(self):
        key = jax.random.PRNGKey(2)
        fresh, stale = self._trees(key)
        out = apply_link_faults(key, fresh, stale, FaultPlan(nan_p=0.5))
        bad_W = ~np.isfinite(np.asarray(out["W"])).all(axis=(2, 3))
        bad_b = ~np.isfinite(np.asarray(out["b"])).all(axis=2)
        assert np.array_equal(bad_W, bad_b)
        assert bad_W.any()

    def test_stale_replay_uses_stale_payload(self):
        key = jax.random.PRNGKey(3)
        fresh, stale = self._trees(key)
        out = apply_link_faults(key, fresh, stale, FaultPlan(stale_p=0.6))
        W, Wf, Ws = (np.asarray(t["W"]) for t in (out, fresh, stale))
        is_stale = np.isclose(W, Ws).all(axis=(2, 3))
        is_fresh = np.isclose(W, Wf).all(axis=(2, 3))
        assert (is_stale | is_fresh).all()
        assert is_stale.any() and is_fresh[:, 0].all()

    def test_deterministic_and_seed_namespaced(self):
        key = jax.random.PRNGKey(4)
        fresh, stale = self._trees(key)

        def leaves(plan):
            return jax.tree.leaves(apply_link_faults(key, fresh, stale, plan))

        a1 = leaves(FaultPlan(nan_p=0.5))
        a2 = leaves(FaultPlan(nan_p=0.5))
        b = leaves(FaultPlan(nan_p=0.5, seed=1))
        assert all(
            np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)
            for x, y in zip(a1, a2)
        )
        assert not all(
            np.array_equal(np.asarray(x), np.asarray(y), equal_nan=True)
            for x, y in zip(a1, b)
        )

    def test_diagnostics_count_nonfinite_and_deficit(self):
        vals = np.ones((2, 4, 3), np.float32)  # (N, n_in, P)
        vals[0, 1] = np.nan  # 3 entries; 3 finite left per element >= 2H+1=3
        vals[1, 1:] = np.inf  # 9 entries; 1 finite left < 3 -> 3 deficits
        diag = fault_diagnostics({"x": jnp.asarray(vals)}, H=1)
        assert int(diag.nonfinite) == 12
        assert int(diag.deficit) == 3

    def test_tree_all_finite(self):
        assert bool(tree_all_finite({"a": jnp.ones(3)}))
        assert not bool(tree_all_finite({"a": jnp.asarray([1.0, np.nan])}))
        # int leaves don't participate
        assert bool(tree_all_finite({"a": jnp.arange(3)}))


class TestGuardedTraining:
    PLAN = FaultPlan(nan_p=0.4, drop_p=0.2)

    def test_unguarded_seed_behavior_poisons_params(self):
        """Regression pin for the acceptance criterion: without sanitize
        and without the guard, an injected NaN/drop plan destroys the
        run's parameters."""
        from rcmarl_tpu.training.trainer import train

        cfg = tiny_cfg(fault_plan=self.PLAN)
        state, df = train(cfg, guard=False)
        assert not params_finite(state)

    def test_guard_rolls_back_to_finite_params(self):
        """Same plan, guard auto-on: the run completes, parameters stay
        finite via rollback/retry/skip, and the stats record it."""
        from rcmarl_tpu.training.trainer import train

        cfg = tiny_cfg(fault_plan=self.PLAN)
        state, df = train(cfg)
        assert params_finite(state)
        g = df.attrs["guard"]
        assert g["retries"] + g["skipped"] > 0
        assert g["nonfinite"] > 0
        assert len(df) == cfg.n_episodes  # degraded rows recorded, not lost

    def test_sanitize_absorbs_faults_without_rollback(self):
        """With the hardened kernel the same plan degrades gracefully:
        finite params, no skipped blocks, non-zero degradation counters."""
        from rcmarl_tpu.training.trainer import train

        cfg = tiny_cfg(fault_plan=self.PLAN, consensus_sanitize=True)
        state, df = train(cfg)
        assert params_finite(state)
        g = df.attrs["guard"]
        assert g["skipped"] == 0
        assert g["nonfinite"] > 0

    def test_clean_run_has_no_guard_overhead_and_identical_stream(self):
        """fault_plan=None keeps the exact seed behavior: no guard attrs,
        and bit-identical params to a run with sanitize knobs absent."""
        from rcmarl_tpu.training.trainer import train

        cfg = tiny_cfg()
        state_a, df = train(cfg)
        assert "guard" not in df.attrs
        state_b, _ = train(tiny_cfg())
        for a, b in zip(
            jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_fused_matrix_with_faults(self):
        """The fault transform traces under the fused-matrix path
        (traced CellSpec, heterogeneous H) with sanitize on."""
        from rcmarl_tpu.parallel.matrix import train_matrix

        base = tiny_cfg(fault_plan=self.PLAN, consensus_sanitize=True)
        cells = [base, base.replace(agent_roles=(0, 0, 3)), base.replace(H=0)]
        states, metrics = train_matrix(base, cells, seeds=[0, 1], n_blocks=2)
        assert np.asarray(metrics.true_team_returns).shape == (6, 4)


class TestCheckpointIntegrity:
    def _state(self, cfg):
        from rcmarl_tpu.training.trainer import init_train_state

        return init_train_state(cfg, jax.random.PRNGKey(0))

    def test_checksum_roundtrip(self, tmp_path):
        from rcmarl_tpu.utils.checkpoint import load_checkpoint, save_checkpoint

        cfg = tiny_cfg(fault_plan=FaultPlan(drop_p=0.1), consensus_sanitize=True)
        state = self._state(cfg)
        p = tmp_path / "ck.npz"
        save_checkpoint(p, state, cfg)
        restored, rcfg = load_checkpoint(p)
        assert rcfg == cfg  # incl. the nested FaultPlan JSON roundtrip
        for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_corruption_detected(self, tmp_path):
        from rcmarl_tpu.utils.checkpoint import (
            CheckpointError,
            load_checkpoint,
            save_checkpoint,
        )

        cfg = tiny_cfg()
        p = tmp_path / "ck.npz"
        save_checkpoint(p, self._state(cfg), cfg)
        data = bytearray(p.read_bytes())
        # flip a byte every 512 across the whole file: a SINGLE
        # mid-file flip is layout-brittle — depending on the Config
        # header size it can land in dead npy-header padding that no
        # integrity layer can (or should) see — while a stride is
        # guaranteed to hit checksummed payload or zip structure
        for i in range(256, len(data), 512):
            data[i] ^= 0xFF
        p.write_bytes(bytes(data))
        with pytest.raises(CheckpointError):
            load_checkpoint(p)

    def test_truncation_detected(self, tmp_path):
        from rcmarl_tpu.utils.checkpoint import (
            CheckpointError,
            load_checkpoint,
            save_checkpoint,
        )

        cfg = tiny_cfg()
        p = tmp_path / "ck.npz"
        save_checkpoint(p, self._state(cfg), cfg)
        p.write_bytes(p.read_bytes()[:200])
        with pytest.raises(CheckpointError, match="unreadable"):
            load_checkpoint(p)

    def test_rotation_and_fallback(self, tmp_path):
        from rcmarl_tpu.utils.checkpoint import (
            load_checkpoint_with_fallback,
            save_checkpoint,
        )

        cfg = tiny_cfg()
        s1 = self._state(cfg)
        s2 = jax.tree.map(lambda l: l, s1)._replace(block=s1.block + 1)
        p = tmp_path / "ck.npz"
        save_checkpoint(p, s1, cfg)
        save_checkpoint(p, s2, cfg)  # rotates s1 -> ck.npz.prev
        assert (tmp_path / "ck.npz.prev").exists()
        data = bytearray(p.read_bytes())
        # strided flips, not a single mid-file one (see
        # test_corruption_detected): corruption must be detected
        # wherever the npz layout puts the payload bytes
        for i in range(256, len(data), 512):
            data[i] ^= 0xFF
        p.write_bytes(bytes(data))
        state, _, loaded = load_checkpoint_with_fallback(p)
        assert loaded == tmp_path / "ck.npz.prev"
        assert int(state.block) == int(s1.block)

    def test_fallback_reraises_without_prev(self, tmp_path):
        from rcmarl_tpu.utils.checkpoint import (
            CheckpointError,
            load_checkpoint_with_fallback,
        )

        p = tmp_path / "nope.npz"
        p.write_bytes(b"not a zip at all")
        with pytest.raises(CheckpointError):
            load_checkpoint_with_fallback(p)


class TestSweepIsolation:
    # ~11s — tier-1 870s wall-budget shed; the nonfinite-cell isolation
    # twin below stays fast
    @pytest.mark.slow
    def test_one_failing_cell_does_not_abort_matrix(self, tmp_path, monkeypatch):
        """`sweep` retries a failing cell once, records it, skips it, and
        still completes (and writes) every other cell; rc is nonzero so
        drivers see the matrix is incomplete."""
        import rcmarl_tpu.parallel.seeds as seeds_mod
        from rcmarl_tpu.cli import main

        real = seeds_mod.train_parallel
        calls = []

        def flaky(cfg, *a, **kw):
            roles = set(cfg.agent_roles)
            calls.append(tuple(cfg.agent_roles))
            if Roles.GREEDY in roles:
                raise RuntimeError("injected cell failure")
            return real(cfg, *a, **kw)

        monkeypatch.setattr(seeds_mod, "train_parallel", flaky)
        rc = main(
            [
                "sweep",
                "--scenarios", "coop", "greedy",
                "--H", "0",
                "--seeds", "0",
                "--n_episodes", "2",
                "--n_ep_fixed", "2",
                "--max_ep_len", "4",
                "--n_epochs", "1",
                "--buffer_size", "8",
                "--out", str(tmp_path),
            ]
        )
        assert rc == 1
        # the healthy cell's artifact exists; the failed one was retried
        assert (tmp_path / "coop" / "H=0" / "seed=0" / "sim_data1.pkl").exists()
        assert not (tmp_path / "greedy" / "H=0" / "seed=0" / "sim_data1.pkl").exists()
        greedy_calls = [c for c in calls if Roles.GREEDY in set(c)]
        assert len(greedy_calls) == 2  # initial + one retry

    def test_nonfinite_cell_recorded_not_written(self, tmp_path, monkeypatch):
        """The sweep-side guard rail: a cell whose metrics go non-finite
        (fault plan without --sanitize — no host loop to roll back in)
        is recorded and skipped WITHOUT retry (deterministic in its
        seeds) and its corrupt sim_data is never written; rc=1."""
        import rcmarl_tpu.parallel.seeds as seeds_mod
        from rcmarl_tpu.cli import main

        calls = []
        real = seeds_mod.train_parallel

        def counting(cfg, *a, **kw):
            calls.append(1)
            return real(cfg, *a, **kw)

        monkeypatch.setattr(seeds_mod, "train_parallel", counting)
        rc = main(
            [
                "sweep",
                "--scenarios", "coop",
                "--H", "0",
                "--seeds", "0",
                "--n_episodes", "2",
                "--n_ep_fixed", "2",
                "--max_ep_len", "4",
                "--n_epochs", "1",
                "--buffer_size", "8",
                "--fault_nan_p", "0.9",  # no --sanitize: poisons params
                "--out", str(tmp_path),
            ]
        )
        assert rc == 1
        assert not (tmp_path / "coop" / "H=0" / "seed=0" / "sim_data1.pkl").exists()
        assert len(calls) == 1  # _CellUnhealthy skips the crash-retry
