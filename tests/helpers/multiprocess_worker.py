"""Worker process for the TRUE two-process distributed test.

Launched (twice) by ``tests/test_distributed.py::test_true_two_process_
training`` with the standard cluster env vars (JAX_COORDINATOR_ADDRESS /
JAX_NUM_PROCESSES / JAX_PROCESS_ID) and 2 virtual CPU devices per
process. Exercises the REAL multi-process path end-to-end — cluster
join via :func:`rcmarl_tpu.parallel.initialize` (which selects the gloo
CPU collectives backend), a cross-process ``multihost_mesh``, sharded
``train_parallel``, and the ``gather_metrics`` DCN all-gather — the
parts the in-process virtual-mesh tests cannot reach.

Process 0 writes the gathered metrics to ``sys.argv[1]`` (.npz); the
parent test compares them against a single-process run of the same
config and seeds.
"""

import os
import sys

import numpy as np

#: Replica seeds, shared with the parent test's single-process reference.
SEEDS = [5, 6, 7, 8]


def worker_config():
    """The one config BOTH the workers and the parent's single-process
    reference run (imported by the test, so the two sides cannot drift).
    Import is deferred so loading this module never touches jax."""
    from rcmarl_tpu.config import Config

    return Config(
        n_agents=3,
        agent_roles=(0, 0, 0),
        in_nodes=((0, 1, 2), (1, 2, 0), (2, 0, 1)),
        n_episodes=2,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=1,
        buffer_size=16,
        batch_size=4,
        H=1,
    )


def main() -> int:
    out_path = sys.argv[1]

    from rcmarl_tpu.parallel import (
        gather_metrics,
        initialize,
        multihost_mesh,
        train_parallel,
    )

    initialize()  # env-driven cluster join; must precede any device query

    import jax

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2 * jax.local_device_count()

    cfg = worker_config()
    mesh = multihost_mesh(agent_axis=1)  # (4, 1): seed axis spans processes
    _, metrics = train_parallel(cfg, seeds=SEEDS, mesh=mesh, n_blocks=1)
    gathered = gather_metrics(metrics)

    if jax.process_index() == 0:
        np.savez(out_path, **gathered._asdict())
    return 0


if __name__ == "__main__":
    sys.exit(main())
