"""Grid-world env tests.

Includes a golden comparison against the actual reference environment
(/root/reference/environments/grid_world.py) when it is importable (gym is
stubbed out if missing — the reference env only uses it for inheritance).
"""

import sys
import types

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rcmarl_tpu.envs import GridWorld, env_reset, env_step, scale_reward, scale_state


def _load_reference_env():
    """Import the reference Grid_World, stubbing the gym dependency."""
    if "gym" not in sys.modules:
        gym_stub = types.ModuleType("gym")

        class _Env:
            pass

        gym_stub.Env = _Env
        gym_stub.spaces = types.ModuleType("gym.spaces")
        sys.modules["gym"] = gym_stub
        sys.modules["gym.spaces"] = gym_stub.spaces
    sys.path.insert(0, "/root/reference")
    try:
        from environments.grid_world import Grid_World  # type: ignore

        return Grid_World
    except Exception:
        return None
    finally:
        sys.path.remove("/root/reference")


REF_ENV = _load_reference_env()


def test_reset_in_bounds():
    env = GridWorld(nrow=5, ncol=5, n_agents=7)
    pos = env_reset(env, jax.random.PRNGKey(0))
    assert pos.shape == (7, 2)
    assert (np.asarray(pos) >= 0).all() and (np.asarray(pos) <= 4).all()


def test_stay_at_goal_zero_reward():
    env = GridWorld(n_agents=2)
    desired = jnp.array([[1, 1], [3, 3]], dtype=jnp.int32)
    pos = desired
    npos, r = env_step(env, pos, desired, jnp.array([0, 0]))
    np.testing.assert_array_equal(np.asarray(npos), np.asarray(pos))
    np.testing.assert_array_equal(np.asarray(r), [0.0, 0.0])


def test_move_reward_uses_premove_distance():
    # Agent at L1 distance 2 moving toward the goal still pays -(2)-1.
    env = GridWorld(n_agents=1)
    desired = jnp.array([[2, 2]], dtype=jnp.int32)
    pos = jnp.array([[0, 2]], dtype=jnp.int32)
    npos, r = env_step(env, pos, desired, jnp.array([2]))  # move +row
    np.testing.assert_array_equal(np.asarray(npos), [[1, 2]])
    assert float(r[0]) == -3.0


def test_moves_clip_to_grid():
    env = GridWorld(n_agents=1, nrow=5, ncol=5)
    desired = jnp.array([[4, 4]], dtype=jnp.int32)
    pos = jnp.array([[0, 0]], dtype=jnp.int32)
    npos, _ = env_step(env, pos, desired, jnp.array([1]))  # -row off the edge
    np.testing.assert_array_equal(np.asarray(npos), [[0, 0]])


def test_scaling_matches_reference_formula():
    env = GridWorld(nrow=5, ncol=5, n_agents=1)
    pos = jnp.array([[4, 0]], dtype=jnp.int32)
    s = np.asarray(scale_state(env, pos))
    std = np.std(np.arange(5))
    np.testing.assert_allclose(s, [[(4 - 2) / std, (0 - 2) / std]], rtol=1e-6)
    np.testing.assert_allclose(float(scale_reward(env, jnp.array(-3.0))), -0.6)


@pytest.mark.skipif(REF_ENV is None, reason="reference env not importable")
def test_golden_vs_reference_trajectories():
    """Step-for-step parity with the reference env under identical actions."""
    rng = np.random.default_rng(0)
    for trial in range(5):
        n_agents = int(rng.integers(1, 8))
        desired = rng.integers(0, 5, size=(n_agents, 2))
        initial = rng.integers(0, 5, size=(n_agents, 2))
        ref = REF_ENV(
            nrow=5,
            ncol=5,
            n_agents=n_agents,
            desired_state=desired,
            initial_state=initial,
            randomize_state=False,
            scaling=True,
        )
        ref.reset()
        env = GridWorld(nrow=5, ncol=5, n_agents=n_agents)
        pos = jnp.asarray(initial, dtype=jnp.int32)
        des = jnp.asarray(desired, dtype=jnp.int32)
        for step in range(30):
            actions = rng.integers(0, 5, size=n_agents)
            ref.step(actions)
            ref_state, ref_reward = ref.get_data()
            pos, r = env_step(env, pos, des, jnp.asarray(actions, dtype=jnp.int32))
            np.testing.assert_allclose(
                np.asarray(scale_state(env, pos)), ref_state, rtol=1e-6
            )
            np.testing.assert_allclose(
                np.asarray(scale_reward(env, r)), ref_reward, rtol=1e-6
            )


@pytest.mark.skipif(REF_ENV is None, reason="reference env not importable")
def test_golden_nonsquare_reference_clip():
    """The divergent clip branch, pinned against the reference.

    On non-square grids the reference clips BOTH coordinates by nrow-1
    (grid_world.py:55). ``reference_clip=True`` must reproduce that
    trajectory exactly; the default per-axis clip must differ from it
    precisely where a column move crosses the nrow bound.
    """
    rng = np.random.default_rng(7)
    nrow, ncol = 3, 7  # ncol > nrow so the reference bound truncates cols
    for trial in range(5):
        n_agents = int(rng.integers(1, 6))
        desired = rng.integers(0, [nrow, ncol], size=(n_agents, 2))
        initial = rng.integers(0, [nrow, ncol], size=(n_agents, 2))
        ref = REF_ENV(
            nrow=nrow,
            ncol=ncol,
            n_agents=n_agents,
            desired_state=desired,
            initial_state=initial,
            randomize_state=False,
            scaling=True,
        )
        ref.reset()
        env = GridWorld(nrow=nrow, ncol=ncol, n_agents=n_agents, reference_clip=True)
        pos = jnp.asarray(initial, dtype=jnp.int32)
        des = jnp.asarray(desired, dtype=jnp.int32)
        for step in range(30):
            actions = rng.integers(0, 5, size=n_agents)
            ref.step(actions)
            ref_state, ref_reward = ref.get_data()
            pos, r = env_step(env, pos, des, jnp.asarray(actions, dtype=jnp.int32))
            np.testing.assert_allclose(
                np.asarray(scale_state(env, pos)), ref_state, rtol=1e-6
            )
            np.testing.assert_allclose(
                np.asarray(scale_reward(env, r)), ref_reward, rtol=1e-6
            )


def test_nonsquare_default_clip_is_per_axis():
    # Default (reference_clip=False): a +col move from col nrow-1 on a wide
    # grid proceeds; the reference bound would have frozen it at nrow-1.
    env = GridWorld(nrow=3, ncol=7, n_agents=1)
    desired = jnp.array([[0, 6]], dtype=jnp.int32)
    pos = jnp.array([[0, 2]], dtype=jnp.int32)
    npos, _ = env_step(env, pos, desired, jnp.array([4]))  # +col
    np.testing.assert_array_equal(np.asarray(npos), [[0, 3]])
    ref_env = GridWorld(nrow=3, ncol=7, n_agents=1, reference_clip=True)
    npos_ref, _ = env_step(ref_env, pos, desired, jnp.array([4]))
    np.testing.assert_array_equal(np.asarray(npos_ref), [[0, 2]])


def test_collision_physics_optin():
    # Two agents colliding on the same cell: with collision_physics the
    # lander is NOT rewarded with -dist_next; the lone agent is.
    env = GridWorld(n_agents=2, collision_physics=True)
    desired = jnp.array([[4, 4], [0, 0]], dtype=jnp.int32)
    pos = jnp.array([[2, 2], [2, 3]], dtype=jnp.int32)
    # agent0 moves +col onto (2,3)... agent1 stays at (2,3) -> collision
    npos, r = env_step(env, pos, desired, jnp.array([4, 0]))
    np.testing.assert_array_equal(np.asarray(npos), [[2, 3], [2, 3]])
    # agent0: collided -> fallback penalty -(|2-4|+|2-4|)-1 = -5
    assert float(r[0]) == -5.0
    # agent1: also on shared cell -> penalty -( |2-0|+|3-0| )-1 = -6
    assert float(r[1]) == -6.0


def test_reference_clip_plumbed_through_config():
    from rcmarl_tpu.config import Config
    from rcmarl_tpu.training.trainer import make_env

    cfg = Config(nrow=3, ncol=7, reference_clip=True)
    env = make_env(cfg)
    assert env.reference_clip and env.nrow == 3 and env.ncol == 7
    np.testing.assert_array_equal(env.clip_hi, [2, 2])
    assert not make_env(Config()).reference_clip


def test_vmap_over_batch():
    env = GridWorld(n_agents=3)
    keys = jax.random.split(jax.random.PRNGKey(1), 4)
    pos = jax.vmap(lambda k: env_reset(env, k))(keys)
    desired = jnp.zeros((4, 3, 2), dtype=jnp.int32)
    actions = jnp.zeros((4, 3), dtype=jnp.int32)
    npos, r = jax.vmap(lambda p, a: env_step(env, p, desired[0], a))(pos, actions)
    assert npos.shape == (4, 3, 2) and r.shape == (4, 3)


class TestReferenceAPIAdapter:
    """ReferenceGridWorld: the drop-in stateful twin of the reference's
    Grid_World object protocol, golden-diffed against the real thing."""

    @pytest.mark.skipif(REF_ENV is None, reason="reference env unavailable")
    def test_golden_trajectory_vs_reference(self):
        from rcmarl_tpu.envs import ReferenceGridWorld

        desired = np.array([[0, 1], [2, 2], [4, 0]])
        rng_actions = np.random.default_rng(7)
        for scaling in (False, True):
            # identical global-RNG draws for both resets
            np.random.seed(123)
            ref = REF_ENV(
                nrow=4, ncol=6, n_agents=3, desired_state=desired,
                randomize_state=True, scaling=scaling,
            )
            np.random.seed(123)
            ours = ReferenceGridWorld(
                nrow=4, ncol=6, n_agents=3, desired_state=desired,
                randomize_state=True, scaling=scaling,
            )
            np.testing.assert_array_equal(ours.state, ref.state)
            for _ in range(25):
                a = rng_actions.integers(0, 5, size=3)
                ref.step(a)
                ours.step(a)
                np.testing.assert_array_equal(ours.state, ref.state)
                np.testing.assert_allclose(ours.reward, ref.reward)
                rs, rr = ref.get_data()
                os_, or_ = ours.get_data()
                np.testing.assert_allclose(os_, rs)
                np.testing.assert_allclose(or_, rr)

    def test_step_mutates_in_place_like_reference(self):
        """Scripts may alias env.state/env.reward once and read them after
        every step — the reference mutates in place, so must we."""
        from rcmarl_tpu.envs import ReferenceGridWorld

        np.random.seed(5)
        env = ReferenceGridWorld(
            nrow=5, ncol=5, n_agents=2,
            desired_state=np.array([[0, 0], [4, 4]]),
        )
        state_alias, reward_alias = env.state, env.reward
        env.step([2, 2])
        assert state_alias is env.state and reward_alias is env.reward
        np.testing.assert_array_equal(state_alias, env.state)
        assert (reward_alias != 0).any()  # alias sees the new rewards

    def test_fixed_initial_state_and_close(self):
        from rcmarl_tpu.envs import ReferenceGridWorld

        init = np.array([[1, 1], [2, 3]])
        env = ReferenceGridWorld(
            nrow=5, ncol=5, n_agents=2,
            desired_state=np.array([[0, 0], [4, 4]]),
            initial_state=init, randomize_state=False,
        )
        np.testing.assert_array_equal(env.state, init)
        env.step([0, 0])
        assert env.reward.shape == (2,)
        env.close()  # reference no-op protocol
