"""Pragma-escape fixture: every would-be finding below carries a
``# lint: disable=<rule>`` escape, so the suite must stay SILENT on
this file (tests/test_lint.py pins it). Never imported."""

import jax


def waived(key, grads):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))  # lint: disable=prng-reuse
    k1, _ = jax.random.split(key)  # lint: disable=prng-split-discard,prng-reuse
    s = float(jax.numpy.mean(grads))  # lint: disable=host-sync
    return a, b, k1, s
