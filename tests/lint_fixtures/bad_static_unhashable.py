"""Seeded-bad fixture: unhashable/mutable values in jit-static
positions (rcmarl_tpu.lint rule ``static-unhashable``): a frozen
dataclass (jit-static config contract) with mutable fields, and a list
display passed where the jitted callee declared the slot static. Never
imported — AST-parsed only."""

from dataclasses import dataclass
from functools import partial
from typing import List

import jax


@dataclass(frozen=True)
class BadConfig:
    n_agents: int = 5
    in_nodes: List[int] = None  # RULE: static-unhashable (mutable anno)
    weights: dict = None  # RULE: static-unhashable (mutable anno)
    topology: tuple = (0, 1)  # clean: hashable


def _step(cfg, x):
    return x * cfg.n_agents


step = jax.jit(_step, static_argnums=(0,))
step_p = partial(jax.jit, static_argnums=(0,))(_step)


def run(x):
    a = step([1, 2, 3], x)  # RULE: static-unhashable (list in static slot)
    b = step_p({"n": 3}, x)  # RULE: static-unhashable (dict in static slot)
    c = step((1, 2, 3), x)  # clean: tuple hashes
    return a, b, c
