"""Seeded-bad fixture: a raw-int seed minted inside jitted hot-path
code (rcmarl_tpu.lint rule ``prng-int-seed``; the test forces the
hot-path scope). Never imported — AST-parsed only."""

import jax


def traced_update(params, cfg):
    key = jax.random.PRNGKey(0)  # RULE: prng-int-seed (constant stream)
    noise = jax.random.normal(key, (3,))
    return params, noise


def also_new_style(params):
    key = jax.random.key(42)  # RULE: prng-int-seed
    return jax.random.normal(key, (3,))
