"""Seeded-bad fixture: split() entropy thrown away (rcmarl_tpu.lint
rule ``prng-split-discard``). Never imported — AST-parsed only."""

import jax


def underscore_unpack(key):
    k1, _ = jax.random.split(key)  # RULE: prng-split-discard
    return jax.random.normal(k1, (3,))


def subscript_split(key):
    k = jax.random.split(key, 4)[0]  # RULE: prng-split-discard
    return jax.random.normal(k, (3,))


def discarded_entirely(key):
    jax.random.split(key)  # RULE: prng-split-discard (no effect)
    return key


def clean_twin(key):
    k1, k2 = jax.random.split(key)
    return jax.random.normal(k1, (3,)) + jax.random.normal(k2, (3,))
