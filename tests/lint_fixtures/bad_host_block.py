"""Seeded-bad fixture: completion barriers inside hot-path code
(rcmarl_tpu.lint rule ``host-block``). Never imported — AST-parsed
only."""

import jax


def synced_step(params, grads):
    out = params
    out = jax.block_until_ready(out)  # RULE: host-block
    grads.block_until_ready()  # RULE: host-block
    return out
