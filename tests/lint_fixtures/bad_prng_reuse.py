"""Seeded-bad fixture: the same key consumed twice, and a parent key
sampled after being split (rcmarl_tpu.lint rule ``prng-reuse``). Never
imported — tests/test_lint.py parses it only."""

import jax


def double_consume(key):
    a = jax.random.normal(key, (3,))
    b = jax.random.uniform(key, (3,))  # RULE: prng-reuse (second consume)
    return a + b


def sample_split_parent(key):
    k1, k2 = jax.random.split(key)
    noise = jax.random.normal(key, (3,))  # RULE: prng-reuse (parent key)
    return k1, k2, noise


def duplicate_fold_stream(key):
    a = jax.random.fold_in(key, 7)
    b = jax.random.fold_in(key, 7)  # RULE: prng-reuse (same derived stream)
    return a, b


def clean_twin(key):
    k1, k2 = jax.random.split(key)
    a = jax.random.normal(k1, (3,))
    b = jax.random.uniform(k2, (3,))
    return a + b
