"""Seeded-bad fixture: fold_in with a magic-number stream tag in
hot-path code (rcmarl_tpu.lint rule ``prng-fold-tag``; the dedicated-
stream pattern wants named constants like faults.py's _FAULT_STREAM).
Never imported — AST-parsed only."""

import jax

_MY_STREAM = 0xBEEF


def derive_streams(ekey):
    fkey = jax.random.fold_in(ekey, 3)  # RULE: prng-fold-tag (magic int)
    ok = jax.random.fold_in(ekey, _MY_STREAM)  # named constant: clean
    return fkey, ok
