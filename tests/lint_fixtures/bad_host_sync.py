"""Seeded-bad fixture: device->host pulls on traced values in hot-path
code (rcmarl_tpu.lint rule ``host-sync``). Static config/shape pulls
are legal and must NOT fire. Never imported — AST-parsed only."""

import numpy as np

import jax
import jax.numpy as jnp


def leaky_update(params, grads, cfg, plan):
    loss = jnp.mean(grads)
    scale = float(loss)  # RULE: host-sync (traced value)
    host = np.asarray(grads)  # RULE: host-sync (traced value)
    stop = bool(loss > 0)  # RULE: host-sync (traced compare)
    item = loss.item()  # RULE: host-sync (.item())
    fetched = jax.device_get(params)  # RULE: host-sync (transfer)

    # the static pulls the real hot path performs — all clean:
    lr = float(plan.stale_p) if plan is not None else float(cfg.slow_lr)
    n = int(np.prod(grads.shape[1:], dtype=np.int64))
    roles = np.array(cfg.agent_roles)
    return scale, host, stop, item, fetched, lr, n, roles
