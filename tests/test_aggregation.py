"""Resilient aggregation kernel: property tests + golden vs the actual
reference TF implementation (SURVEY.md §4 test strategy)."""

import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rcmarl_tpu.ops import resilient_aggregate, resilient_aggregate_tree


def _reference_aggregator():
    """Load the reference RPBCAC agent class and expose its
    _resilient_aggregation without constructing Keras models."""
    try:
        sys.path.insert(0, "/root/reference")
        from agents.resilient_CAC_agents import RPBCAC_agent  # type: ignore

        def agg(values, H):
            obj = RPBCAC_agent.__new__(RPBCAC_agent)
            obj.H = H
            return np.asarray(obj._resilient_aggregation(values))

        return agg
    except Exception:
        return None
    finally:
        sys.path.remove("/root/reference")


REF_AGG = _reference_aggregator()


def test_hand_computed_example():
    # own=5, neighbors 1, 9, 3; H=1: sorted [1,3,5,9], lower=min(3,5)=3,
    # upper=max(5,5)=5; clip -> [5,3,5,3]; mean 4.
    vals = jnp.array([[5.0], [1.0], [9.0], [3.0]])
    out = resilient_aggregate(vals, H=1)
    np.testing.assert_allclose(np.asarray(out), [4.0])


def test_h0_is_plain_mean():
    vals = jax.random.normal(jax.random.PRNGKey(0), (4, 7, 3))
    np.testing.assert_allclose(
        np.asarray(resilient_aggregate(vals, H=0)),
        np.asarray(vals.mean(axis=0)),
        rtol=1e-6,
    )


def test_permutation_invariance_of_nonself_neighbors():
    key = jax.random.PRNGKey(1)
    vals = jax.random.normal(key, (5, 11))
    out = resilient_aggregate(vals, H=2)
    perm = jnp.concatenate([vals[:1], vals[jnp.array([3, 1, 4, 2])]])
    out_p = resilient_aggregate(perm, H=2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_p), rtol=1e-6)


def test_output_bounded_by_own_range():
    # The aggregate lies within [min(lower, own), max(upper, own)] and,
    # since own is always inside the clip bounds, within the clip range.
    key = jax.random.PRNGKey(2)
    for H in (1, 2):
        vals = jax.random.normal(key, (6, 50)) * 10
        out = np.asarray(resilient_aggregate(vals, H=H))
        v = np.asarray(vals)
        own = v[0]
        sv = np.sort(v, axis=0)
        lower = np.minimum(sv[H], own)
        upper = np.maximum(sv[-H - 1], own)
        assert (out >= lower - 1e-6).all() and (out <= upper + 1e-6).all()


def test_adversary_cannot_drag_outside_cooperative_range():
    # With <=H adversaries sending arbitrarily extreme values, the bounds
    # are set by cooperative values and own value.
    coop = jnp.array([[1.0], [2.0], [3.0]])
    for extreme in (1e9, -1e9):
        vals = jnp.concatenate([coop, jnp.array([[extreme]])])
        out = float(resilient_aggregate(vals, H=1)[0])
        assert 1.0 - 1e-6 <= out <= 3.0 + 1e-6


def test_invalid_H_raises():
    vals = jnp.zeros((4, 2))
    with pytest.raises(ValueError):
        resilient_aggregate(vals, H=2)  # need 2H <= n_in-1 = 3


def test_tree_version_matches_leafwise():
    key = jax.random.PRNGKey(3)
    k1, k2 = jax.random.split(key)
    tree = {"W": jax.random.normal(k1, (4, 3, 5)), "b": jax.random.normal(k2, (4, 5))}
    out = resilient_aggregate_tree(tree, H=1)
    np.testing.assert_allclose(
        np.asarray(out["W"]), np.asarray(resilient_aggregate(tree["W"], 1)), rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(out["b"]), np.asarray(resilient_aggregate(tree["b"], 1)), rtol=1e-6
    )


@pytest.mark.skipif(REF_AGG is None, reason="reference agent not importable")
def test_golden_vs_reference_tf_implementation():
    rng = np.random.default_rng(0)
    for trial in range(10):
        n_in = int(rng.integers(3, 8))
        H = int(rng.integers(0, (n_in - 1) // 2 + 1))
        shape = (n_in,) + tuple(rng.integers(1, 6, size=int(rng.integers(1, 3))))
        vals = rng.normal(size=shape).astype(np.float32)
        ref = REF_AGG(vals, H)
        mine = np.asarray(resilient_aggregate(jnp.asarray(vals), H))
        np.testing.assert_allclose(mine, ref, rtol=1e-5, atol=1e-6)


def test_vmap_over_agents():
    # Batched over an agent axis: (N, n_in, P)
    vals = jax.random.normal(jax.random.PRNGKey(4), (6, 5, 13))
    out = jax.vmap(lambda v: resilient_aggregate(v, H=1))(vals)
    for i in range(6):
        np.testing.assert_allclose(
            np.asarray(out[i]),
            np.asarray(resilient_aggregate(vals[i], H=1)),
            rtol=1e-5,
            atol=1e-6,
        )


class TestMaskedAggregation:
    """Padded-neighborhood (heterogeneous in-degree) semantics: the masked
    aggregate over a padded block must equal the unmasked aggregate over
    just the valid prefix (reference accepts arbitrary adjacency lists,
    main.py:28)."""

    # ~7s (10-trial compile sweep) — tier-1 870s wall-budget shed
    @pytest.mark.slow
    def test_matches_unpadded_prefix(self):
        rng = np.random.default_rng(5)
        for trial in range(10):
            d = int(rng.integers(3, 7))  # true degree
            pad = int(rng.integers(1, 4))
            H = int(rng.integers(0, (d - 1) // 2 + 1))
            shape = (d,) + tuple(rng.integers(1, 6, size=2))
            vals = rng.normal(size=shape).astype(np.float32)
            padded = np.concatenate(
                [vals, np.repeat(vals[:1], pad, axis=0) * 7.7], axis=0
            )  # garbage in padded slots must not matter
            valid = jnp.asarray([1.0] * d + [0.0] * pad)
            out = resilient_aggregate(jnp.asarray(padded), H, valid=valid)
            expect = resilient_aggregate(jnp.asarray(vals), H)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(expect), rtol=1e-5, atol=1e-6
            )

    def test_padding_value_irrelevant(self):
        vals = jnp.array([[5.0], [1.0], [9.0], [3.0]])
        valid = jnp.array([1.0, 1.0, 1.0, 1.0, 0.0, 0.0])
        for junk in (0.0, 1e9, -1e9, jnp.nan):
            padded = jnp.concatenate(
                [vals, jnp.full((2, 1), junk)], axis=0
            )
            out = resilient_aggregate(padded, H=1, valid=valid)
            np.testing.assert_allclose(np.asarray(out), [4.0])

    def test_vmap_heterogeneous_degrees(self):
        # Two agents, degrees 4 and 3, padded to 4: vmapped masked call
        # matches per-agent unmasked calls.
        a0 = jnp.array([[5.0], [1.0], [9.0], [3.0]])
        a1 = jnp.array([[2.0], [8.0], [4.0], [2.0]])  # last row = pad
        vals = jnp.stack([a0, a1])
        valid = jnp.array([[1.0, 1.0, 1.0, 1.0], [1.0, 1.0, 1.0, 0.0]])
        out = jax.vmap(
            lambda v, m: resilient_aggregate(v, H=1, valid=m)
        )(vals, valid)
        np.testing.assert_allclose(np.asarray(out[0]), [4.0])
        expect1 = resilient_aggregate(a1[:3], H=1)
        np.testing.assert_allclose(
            np.asarray(out[1]), np.asarray(expect1), rtol=1e-6
        )

    def test_tree_version_masked(self):
        key = jax.random.PRNGKey(6)
        k1, k2 = jax.random.split(key)
        tree = {
            "W": jax.random.normal(k1, (5, 3, 4)),
            "b": jax.random.normal(k2, (5, 4)),
        }
        valid = jnp.array([1.0, 1.0, 1.0, 1.0, 0.0])
        out = resilient_aggregate_tree(tree, H=1, valid=valid)
        expect = resilient_aggregate_tree(
            jax.tree.map(lambda l: l[:4], tree), H=1
        )
        for k in ("W", "b"):
            np.testing.assert_allclose(
                np.asarray(out[k]), np.asarray(expect[k]), rtol=1e-6
            )


def test_unknown_impl_rejected():
    vals = jnp.zeros((4, 2))
    with pytest.raises(ValueError, match="unknown consensus impl"):
        resilient_aggregate(vals, H=1, impl="Pallas")
    with pytest.raises(ValueError, match="unknown consensus impl"):
        resilient_aggregate_tree({"w": vals}, H=1, impl="palas")


class TestAutoImpl:
    """'auto' = the measured-crossover choice (BENCH_SCALING.jsonl)."""

    def test_resolution_rules(self, monkeypatch):
        from rcmarl_tpu.ops import aggregation as agg

        # non-TPU backend: the XLA family — with the tournament strategy
        # the measured rows favor selection at every n_in
        # (SELECT_MAX_N_IN=None; tests/test_selection.py pins the full
        # 3-way policy)
        monkeypatch.setattr(agg.jax, "default_backend", lambda: "cpu")
        assert agg.resolve_impl("auto", 4) == "xla"
        assert agg.resolve_impl("auto", 64, n_agents=64) == "xla"
        # TPU backend: pallas from the measured volume crossover up
        # (n_in * n_agents is the key, so hold n_in at a selection-
        # friendly size and scale the agent axis)
        monkeypatch.setattr(agg.jax, "default_backend", lambda: "tpu")
        v = agg.PALLAS_CROSSOVER_VOLUME
        assert agg.resolve_impl("auto", 16, n_agents=v // 16 - 1) == "xla"
        assert agg.resolve_impl("auto", 16, n_agents=v // 16) == "pallas"
        # f64 never routes to the f32-computing kernel
        assert (
            agg.resolve_impl("auto", 64, np.float64, n_agents=64)
            == "xla"
        )
        assert agg.resolve_impl("auto", 16, np.float64, n_agents=64) == "xla"
        # explicit impls pass through untouched on every backend
        assert agg.resolve_impl("xla", 64) == "xla"
        assert agg.resolve_impl("xla_sort", 4) == "xla_sort"
        assert agg.resolve_impl("pallas", 4) == "pallas"
        assert agg.resolve_impl("pallas_sort", 4) == "pallas_sort"

    def test_crossover_matches_measured_rows(self, monkeypatch):
        """Pin 'auto' to every measured TPU row in BENCH_SCALING.jsonl.

        The round-4 rows REFUTED an n_in-only rule: at identical n_in=5
        the winner flips with the agent count (n16_ring xla 1.67x faster
        vs n64_ring pallas 1.64x faster), so 'auto' keys on the volume
        n_in * n_agents. Each (config -> winner) below is a measured
        2026-07-30/2026-08-02 row, not a projection.
        """
        from rcmarl_tpu.ops import aggregation as agg

        monkeypatch.setattr(agg.jax, "default_backend", lambda: "tpu")
        measured = [
            ("ref5_ring", 4, 5, "xla"),  # 11580 vs 6943
            ("n16_ring", 5, 16, "xla"),  # 8494 vs 5085
            ("n16_full", 16, 16, "pallas"),  # 9146 vs 8387
            ("n64_ring", 5, 64, "pallas"),  # 5039 vs 3077
            ("n64_full", 64, 64, "pallas"),  # 1980 vs 1470
        ]
        for config, n_in, n_agents, winner in measured:
            got = agg.resolve_impl("auto", n_in, n_agents=n_agents)
            assert got == winner, (
                f"{config}: auto resolved to {got}, measured winner is "
                f"{winner} (n_in={n_in}, n_agents={n_agents})"
            )

    def test_auto_matches_xla_on_cpu(self):
        vals = jnp.asarray(np.random.default_rng(0).normal(size=(5, 3, 7)))
        np.testing.assert_allclose(
            np.asarray(resilient_aggregate(vals, H=1, impl="auto")),
            np.asarray(resilient_aggregate(vals, H=1, impl="xla")),
            rtol=1e-12,
        )

    def test_auto_trains_end_to_end(self):
        from rcmarl_tpu.config import Config
        from rcmarl_tpu.training.trainer import init_train_state, train_block

        cfg = Config(
            n_agents=3,
            agent_roles=(0, 0, 0),
            in_nodes=((0, 1, 2), (1, 2, 0), (2, 0, 1)),
            n_episodes=2,
            max_ep_len=4,
            n_ep_fixed=2,
            n_epochs=1,
            buffer_size=16,
            batch_size=4,
            H=1,
            consensus_impl="auto",
        )
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        state, metrics = train_block(cfg, state)
        assert np.isfinite(np.asarray(metrics.true_team_returns)).all()


class TestTracedH:
    """Traced-H path (the heterogeneous-cell matrix program): must match
    the static specialization bit-for-bit for every legal H, including
    the H=0 plain-mean shortcut, and must compose with vmap so replicas
    with DIFFERENT H values share one program."""

    @pytest.mark.parametrize("H", [0, 1, 2])
    def test_matches_static(self, H):
        rng = np.random.default_rng(7 + H)
        values = jnp.asarray(rng.normal(size=(6, 4, 3)), jnp.float32)
        static = resilient_aggregate(values, H)
        traced = jax.jit(
            lambda v, h: resilient_aggregate(v, h)
        )(values, jnp.int32(H))
        np.testing.assert_array_equal(np.asarray(static), np.asarray(traced))

    def test_tree_matches_static(self):
        rng = np.random.default_rng(11)
        tree = {
            "W": jnp.asarray(rng.normal(size=(5, 3, 2)), jnp.float32),
            "b": jnp.asarray(rng.normal(size=(5, 2)), jnp.float32),
        }
        static = resilient_aggregate_tree(tree, 1)
        traced = jax.jit(
            lambda t, h: resilient_aggregate_tree(t, h)
        )(tree, jnp.int32(1))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            static,
            traced,
        )

    def test_vmap_heterogeneous_h(self):
        """One program, three replicas with H = 0, 1, 2 — each replica's
        row equals the corresponding static-H call."""
        rng = np.random.default_rng(13)
        values = jnp.asarray(rng.normal(size=(3, 7, 10)), jnp.float32)
        hs = jnp.asarray([0, 1, 2], jnp.int32)
        out = jax.jit(
            jax.vmap(lambda v, h: resilient_aggregate(v, h))
        )(values, hs)
        for i, H in enumerate([0, 1, 2]):
            np.testing.assert_array_equal(
                np.asarray(out[i]),
                np.asarray(resilient_aggregate(values[i], H)),
            )

    def test_traced_h_rejects_pallas(self):
        values = jnp.zeros((4, 2), jnp.float32)
        with pytest.raises(ValueError, match="traced H"):
            resilient_aggregate(values, jnp.int32(1), impl="pallas")

    def test_traced_h_rejects_valid_mask(self):
        values = jnp.zeros((4, 2), jnp.float32)
        with pytest.raises(ValueError, match="uniform graph"):
            resilient_aggregate(
                values, jnp.int32(1), valid=jnp.asarray([1, 1, 1, 0])
            )

    @pytest.mark.skipif(REF_AGG is None, reason="reference import failed")
    def test_traced_h_golden_vs_reference(self):
        rng = np.random.default_rng(17)
        values = rng.normal(size=(5, 8)).astype(np.float32)
        for H in (0, 1, 2):
            ours = jax.jit(lambda v, h: resilient_aggregate(v, h))(
                jnp.asarray(values), jnp.int32(H)
            )
            np.testing.assert_allclose(
                np.asarray(ours), REF_AGG(values, H), rtol=1e-6
            )

    def test_traced_h_auto_resolves_to_xla(self):
        """impl='auto' must lower with a traced H on ANY backend (auto
        picks an impl that can lower; only explicit pallas errors)."""
        rng = np.random.default_rng(19)
        # regardless of volume, a traced H forces the xla path — the
        # Pallas kernel fixes its trim indices at lowering time
        values = jnp.asarray(rng.normal(size=(16, 6)), jnp.float32)
        out = jax.jit(
            lambda v, h: resilient_aggregate(v, h, impl="auto")
        )(values, jnp.int32(2))
        np.testing.assert_array_equal(
            np.asarray(out), np.asarray(resilient_aggregate(values, 2))
        )
        tree_out = resilient_aggregate_tree(
            {"w": values}, jnp.int32(2), impl="auto"
        )
        np.testing.assert_array_equal(
            np.asarray(tree_out["w"]), np.asarray(resilient_aggregate(values, 2))
        )
