"""Checkpoint/resume, reference-format interop, and the CLI surface.

Covers SURVEY.md C1 (CLI), C9 (checkpoint I/O), C13 (plotting/analysis),
C15 (sweep orchestration). Reference-format tests load REAL artifacts
shipped with the reference (``raw_data/coop/H=1/seed=100/``) to pin the
interop layout, not a synthetic imitation of it.
"""

import json
from pathlib import Path

import jax
import numpy as np
import pandas as pd
import pytest

from rcmarl_tpu.cli import main, scenario_labels
from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.training.trainer import init_train_state, train_block
from rcmarl_tpu.utils.checkpoint import (
    export_reference_weights,
    import_reference_weights,
    load_checkpoint,
    save_checkpoint,
)

REF_RUN = Path("/root/reference/simulation_results/raw_data/coop/H=1/seed=100")


def tiny_cfg(**kw):
    base = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 2),
        nrow=3,
        ncol=3,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=2,
        buffer_size=16,
        hidden=(8, 8),
        coop_fit_steps=2,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
        n_episodes=4,
    )
    base.update(kw)
    return Config(**base)


def leaves_equal(a, b):
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


class TestCheckpoint:
    @pytest.mark.slow
    def test_roundtrip_and_deterministic_resume(self, tmp_path):
        cfg = tiny_cfg()
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        state, _ = train_block(cfg, state)
        path = tmp_path / "ck.npz"
        save_checkpoint(path, state, cfg)
        restored, r_cfg = load_checkpoint(path)
        assert r_cfg == cfg
        assert leaves_equal(state, restored)
        # resuming from the restore reproduces the original continuation
        cont_a, _ = train_block(cfg, state)
        cont_b, _ = train_block(cfg, restored)
        assert leaves_equal(cont_a, cont_b)

    def test_structure_mismatch_rejected(self, tmp_path):
        cfg = tiny_cfg()
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        path = tmp_path / "ck.npz"
        save_checkpoint(path, state, cfg)
        other = tiny_cfg(hidden=(4, 4))
        with pytest.raises(ValueError, match="shape"):
            load_checkpoint(path, other)

    def test_reference_export_import_roundtrip(self):
        cfg = tiny_cfg()
        state = init_train_state(cfg, jax.random.PRNGKey(1))
        exported = export_reference_weights(state.params, cfg)
        assert exported.shape == (3,)
        assert len(exported[0]) == 4  # actor, critic, TR, critic_local
        # import into a differently-initialized template -> exact restore
        blank = init_train_state(cfg, jax.random.PRNGKey(2))
        restored = import_reference_weights(exported, cfg, blank.params)
        for field in ("actor", "critic", "tr", "critic_local"):
            assert leaves_equal(getattr(restored, field), getattr(state.params, field))

    def test_save_is_atomic_no_tmp_left_behind(self, tmp_path):
        cfg = tiny_cfg()
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        path = tmp_path / "ck.npz"
        save_checkpoint(path, state, cfg)
        save_checkpoint(path, state, cfg)  # overwrite goes through rename
        assert path.exists()
        assert not list(tmp_path.glob("*.tmp"))
        load_checkpoint(path)  # still a valid archive

    def test_import_rejects_layer_count_mismatch(self):
        cfg = tiny_cfg()
        state = init_train_state(cfg, jax.random.PRNGKey(1))
        exported = export_reference_weights(state.params, cfg)
        deeper = tiny_cfg(hidden=(8, 8, 8))
        deep_state = init_train_state(deeper, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="layer-count mismatch"):
            import_reference_weights(exported, deeper, deep_state.params)

    @pytest.mark.slow
    def test_loads_real_reference_artifacts(self):
        """Real reference checkpoint (Keras get_weights layout, main.py:83-92)
        imports into the default Config's shapes."""
        if not REF_RUN.exists():
            pytest.skip("reference artifacts unavailable")
        weights = np.load(REF_RUN / "pretrained_weights.npy", allow_pickle=True)
        cfg = Config()  # default 5-agent published architecture
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        params = import_reference_weights(weights, cfg, state.params)
        # agent 0's actor W1 must equal the reference array bit-for-bit
        ref_w1 = np.asarray(weights[0][0][0])
        assert ref_w1.shape == (10, 20)
        assert np.array_equal(np.asarray(params.actor[0][0][0]), ref_w1)
        # imported desired state matches grid bounds
        desired = np.load(REF_RUN / "desired_state.npy", allow_pickle=True)
        assert desired.shape == (5, 2) and desired.max() < 5


class TestCLI:
    def test_scenario_presets(self):
        labels, g = scenario_labels("malicious_global")
        assert labels[-1] == "Malicious" and g
        labels, g = scenario_labels("coop")
        assert set(labels) == {"Cooperative"} and not g
        with pytest.raises(SystemExit):
            scenario_labels("nonsense")

    @pytest.mark.slow
    def test_train_artifacts_and_resume(self, tmp_path, capsys):
        out = tmp_path / "run"
        flags = [
            "train",
            "--n_agents", "3", "--in_degree", "2",
            "--n_episodes", "4", "--max_ep_len", "4", "--n_ep_fixed", "2",
            "--n_epochs", "1", "--buffer_size", "16", "--batch_size", "4",
            "--random_seed", "7", "--summary_dir", str(out), "--quiet",
        ]
        assert main(flags) == 0
        for artifact in (
            "sim_data1.pkl", "checkpoint.npz",
            "pretrained_weights.npy", "desired_state.npy",
        ):
            assert (out / artifact).exists(), artifact
        df = pd.read_pickle(out / "sim_data1.pkl")
        assert list(df.columns) == [
            "True_team_returns", "True_adv_returns", "Estimated_team_returns",
        ]
        assert len(df) == 4  # one row per episode
        # resume from our checkpoint; phase auto-numbers, no clobber
        assert main(flags + ["--pretrained_agents", str(out / "checkpoint.npz")]) == 0
        assert (out / "sim_data2.pkl").exists()
        assert len(pd.read_pickle(out / "sim_data1.pkl")) == 4  # untouched
        # warm-start from the reference-format artifacts we just wrote
        assert main(flags + ["--pretrained_agents", str(out)]) == 0
        assert (out / "sim_data3.pkl").exists()

    def test_scenario_conflicts_with_explicit_labels(self):
        with pytest.raises(SystemExit, match="conflict"):
            main([
                "train", "--scenario", "coop",
                "--agent_label", "Cooperative", "Cooperative", "Cooperative",
                "Cooperative", "Greedy",
            ])

    def test_missing_pretrained_path_is_clear_error(self, tmp_path):
        with pytest.raises(SystemExit, match="does not exist"):
            main([
                "train", "--summary_dir", str(tmp_path), "--quiet",
                "--pretrained_agents", str(tmp_path / "no_such.npz"),
            ])

    @pytest.mark.slow
    def test_resume_warns_on_config_drift(self, tmp_path, capsys):
        out = tmp_path / "run"
        flags = [
            "train",
            "--n_agents", "3", "--in_degree", "2",
            "--n_episodes", "2", "--max_ep_len", "4", "--n_ep_fixed", "2",
            "--n_epochs", "1", "--buffer_size", "16", "--batch_size", "4",
            "--random_seed", "7", "--summary_dir", str(out), "--quiet",
            "--gamma", "0.95",
        ]
        assert main(flags) == 0
        capsys.readouterr()
        # resume WITHOUT --gamma: shape-compatible but hyperparam drift
        resume = [f for f in flags if f not in ("--gamma", "0.95")]
        assert main(resume + ["--pretrained_agents", str(out / "checkpoint.npz")]) == 0
        msg = capsys.readouterr().out
        assert "WARNING" in msg and "gamma" in msg

    @pytest.mark.slow
    def test_bench_reports_scaling_configs(self, capsys):
        import json as _json

        assert main([
            "bench", "--configs", "ref5_ring", "--impl", "xla",
            "--n_ep_fixed", "2", "--blocks", "1", "--reps", "1",
        ]) == 0
        rows = [
            _json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert rows[0]["config"] == "ref5_ring"
        assert rows[0]["n_in"] == 4  # reference topology incl. self
        assert rows[0]["env_steps_per_sec"] > 0

    @pytest.mark.slow
    def test_bench_dtype_axis_rows_self_describing(self, capsys):
        import json as _json

        assert main([
            "bench", "--configs", "ref5_ring", "--impl", "xla",
            "--compute_dtype", "float32", "bfloat16",
            "--n_ep_fixed", "2", "--blocks", "1", "--reps", "1",
        ]) == 0
        rows = [
            _json.loads(line)
            for line in capsys.readouterr().out.strip().splitlines()
        ]
        assert [r["compute_dtype"] for r in rows] == ["float32", "bfloat16"]
        assert all(
            r["impl_resolved"] == "xla" and r["env_steps_per_sec"] > 0
            for r in rows
        )

    @pytest.mark.slow
    def test_profile_reports_phase_breakdown(self, tmp_path, capsys):
        import json as _json

        out = tmp_path / "perf.jsonl"
        assert main([
            "profile", "--configs", "ref5_ring", "--impl", "xla",
            "--n_ep_fixed", "2", "--reps", "1", "--out", str(out),
        ]) == 0
        row = _json.loads(capsys.readouterr().out.strip().splitlines()[0])
        assert set(row["ms"]) == {
            "rollout_block", "critic_tr_epoch", "actor_phase", "full_block",
        }
        assert all(v > 0 for v in row["ms"].values())
        # the appended artifact parses back to the same row
        assert _json.loads(out.read_text().strip()) == row

    @pytest.mark.slow
    def test_sweep_plot_summary(self, tmp_path, capsys):
        raw = tmp_path / "raw_data"
        assert main([
            "sweep", "--scenarios", "greedy", "--H", "0",
            "--seeds", "5", "6", "--n_episodes", "4", "--max_ep_len", "4",
            "--n_ep_fixed", "2", "--n_epochs", "1", "--buffer_size", "16",
            "--out", str(raw),
        ]) == 0
        cell = raw / "greedy" / "H=0"
        assert (cell / "seed=5" / "sim_data1.pkl").exists()
        assert (cell / "seed=6" / "sim_data1.pkl").exists()
        figs = tmp_path / "figures"
        assert main([
            "plot", "--raw_data", str(raw), "--out", str(figs),
            "--drop", "0", "--rolling", "2", "--summary",
        ]) == 0
        assert (figs / "greedy_h0.png").exists()
        out = capsys.readouterr().out
        assert "greedy" in out and "team_return" in out


class TestAnalysis:
    def test_aggregate_matches_reference_pipeline(self, tmp_path):
        """Seed-mean + rolling aggregation over a synthetic two-phase run."""
        from rcmarl_tpu.analysis.plots import aggregate_scenario, final_returns

        rng = np.random.default_rng(0)
        for seed in (1, 2):
            d = tmp_path / "toy" / "H=0" / f"seed={seed}"
            d.mkdir(parents=True)
            for phase in (1, 2):
                df = pd.DataFrame({
                    "True_team_returns": rng.normal(-5, 0.1, 40),
                    "True_adv_returns": np.zeros(40),
                    "Estimated_team_returns": rng.normal(-5, 0.1, 40),
                })
                df.to_pickle(d / f"sim_data{phase}.pkl")
        agg = aggregate_scenario(tmp_path / "toy", 0, drop=10, rolling=5)
        # two phases x (40 - 10) rows each survive the per-phase drop
        assert len(agg) == 60
        assert abs(agg["True_team_returns"].mean() + 5) < 0.2
        table = final_returns(tmp_path, window=20)
        assert table.iloc[0]["scenario"] == "toy"
        assert abs(table.iloc[0]["team_return"] + 5) < 0.2

    def test_drift_comparison_marks_actual_phase_boundaries(self, tmp_path):
        """The DRIFT.md overlay figure: boundaries come from each tree's
        own phase files, and asymmetric protocols don't invent one."""
        from rcmarl_tpu.analysis.plots import (
            _phase_boundaries,
            plot_drift_comparison,
        )

        rng = np.random.default_rng(1)

        def write(root, phases):
            d = root / "coop" / "H=0" / "seed=1"
            d.mkdir(parents=True)
            for i, n in enumerate(phases, 1):
                pd.DataFrame({
                    "True_team_returns": rng.normal(-5, 0.1, n),
                    "True_adv_returns": np.zeros(n),
                    "Estimated_team_returns": rng.normal(-5, 0.1, n),
                }).to_pickle(d / f"sim_data{i}.pkl")

        mine, ref = tmp_path / "mine", tmp_path / "ref"
        write(mine, [30])          # single phase: no boundary
        write(ref, [30, 30])       # two-phase: boundary at 30
        assert _phase_boundaries(mine / "coop", 0) == []
        assert _phase_boundaries(ref / "coop", 0) == [30]
        out = plot_drift_comparison(
            mine, ref, tmp_path / "fig.png", scenario="coop", H=0, rolling=2
        )
        assert Path(out).exists()

    def test_parity_verdicts_and_support_separation(self, tmp_path):
        """Verdict ladder: within / noise-compatible / outside — and
        fully-disjoint per-seed supports refute the seed-noise label no
        matter what the std-overlap heuristic says."""
        from rcmarl_tpu.analysis.plots import parity_table

        def write(root, scen, seed, level, jitter):
            d = root / scen / "H=0" / f"seed={seed}"
            d.mkdir(parents=True)
            pd.DataFrame({
                "True_team_returns": np.full(40, level + jitter),
                "True_adv_returns": np.zeros(40),
                "Estimated_team_returns": np.full(40, level),
            }).to_pickle(d / "sim_data1.pkl")

        mine, ref = tmp_path / "mine", tmp_path / "ref"
        # within: identical
        for i, seed in enumerate((1, 2, 3)):
            write(mine, "within", seed, -5.0, 0.01 * i)
            write(ref, "within", seed, -5.0, 0.01 * i)
        # separated: ours clusters at -4.6, ref at -5.2, wide stds would
        # let the 2*(std+std) heuristic call it noise — supports disjoint
        for i, seed in enumerate((1, 2, 3)):
            write(mine, "drift", seed, -4.6, 0.2 * i)
            write(ref, "drift", seed, -5.2, 0.2 * i)
        # noise-compatible: overlapping supports, means 8% apart
        for i, seed in enumerate((1, 2, 3)):
            write(mine, "noisy", seed, -5.0, -0.3 * i)
            write(ref, "noisy", seed, -5.4, -0.3 * i)
        table = parity_table(mine, ref, window=40, tolerance=0.05)
        t = {r.scenario: r for _, r in table.iterrows()}
        assert t["within"].verdict == "within"
        assert not t["within"].supports_separated
        assert t["drift"].supports_separated
        assert t["drift"].verdict == "outside"
        # std heuristic alone would have said noise-compatible
        assert abs(t["drift"].delta) <= 2 * (
            t["drift"].mine_std + t["drift"].ref_std
        )
        assert t["noisy"].verdict == "outside (seed-noise-compatible)"
        assert not t["noisy"].supports_separated

    def test_support_separation_needs_three_seeds_per_side(self, tmp_path):
        """With n=2 on either side (some reference _global cells ship
        only 2 seeds), disjoint supports are weak evidence: the column
        still records the disjointness, but the hard 'outside' override
        is gated on >= 3 seeds per side and the cell falls through to
        the std-overlap heuristic."""
        from rcmarl_tpu.analysis.plots import parity_table

        def write(root, scen, seed, level):
            d = root / scen / "H=0" / f"seed={seed}"
            d.mkdir(parents=True)
            pd.DataFrame({
                "True_team_returns": np.full(40, level),
                "True_adv_returns": np.zeros(40),
                "Estimated_team_returns": np.full(40, level),
            }).to_pickle(d / "sim_data1.pkl")

        mine, ref = tmp_path / "mine", tmp_path / "ref"
        # disjoint supports, but only 2 reference seeds; the wide spread
        # keeps the delta within 2*(mine_std + ref_std)
        for seed, level in ((1, -4.3), (2, -4.9), (3, -4.6)):
            write(mine, "lown", seed, level)
        for seed, level in ((1, -5.1), (2, -5.7)):
            write(ref, "lown", seed, level)
        table = parity_table(mine, ref, window=40, tolerance=0.05)
        row = table[table.scenario == "lown"].iloc[0]
        assert row.supports_separated  # still recorded for the reader
        assert row.ref_seeds == 2
        assert row.verdict == "outside (seed-noise-compatible)"

    def test_parity_cli_pools_multiple_trees(self, tmp_path, capsys):
        """`parity --raw_data A B` folds per-seed rows from both trees
        (the n=6 PARITY.md), and a missing tree contributes nothing."""
        from rcmarl_tpu.cli import main

        def write(root, seed, level):
            d = root / "coop" / "H=0" / f"seed={seed}"
            d.mkdir(parents=True)
            pd.DataFrame({
                "True_team_returns": np.full(40, level),
                "True_adv_returns": np.zeros(40),
                "Estimated_team_returns": np.full(40, level),
            }).to_pickle(d / "sim_data1.pkl")

        ref, t1, t2 = tmp_path / "ref", tmp_path / "t1", tmp_path / "t2"
        for seed in (100, 200, 300):
            write(ref, seed, -5.0)
            write(t1, seed, -5.0)
        for seed in (400, 500, 600):
            write(t2, seed, -5.1)
        out, summary = tmp_path / "P.md", tmp_path / "s.json"
        rc = main([
            "parity", "--raw_data", str(t1), str(t2),
            str(tmp_path / "missing_tree"),
            "--ref_raw_data", str(ref), "--out", str(out),
            "--summary_out", str(summary), "--window", "40",
        ])
        assert rc == 0
        text = out.read_text()
        assert "(n=6)" in text and "(n=3)" in text
        data = json.loads(summary.read_text())
        assert len(data["per_seed"]["mine"]) == 6
        assert [r["seed"] for r in data["per_seed"]["mine"]] == [
            "100", "200", "300", "400", "500", "600"
        ]
        assert data["raw_data"] == [
            str(t1), str(t2), str(tmp_path / "missing_tree")
        ]
        # a seed present in two pooled trees must raise, not silently
        # double-count (the cross-tree guard applies to the CLI's pooled
        # call, not only to direct per_seed_final_returns list input)
        write(t2, 100, -5.2)
        with pytest.raises(ValueError, match="duplicate"):
            main([
                "parity", "--raw_data", str(t1), str(t2),
                "--ref_raw_data", str(ref), "--out", str(out),
                "--summary_out", str(summary), "--window", "40",
            ])

    def test_qualitative_claims_section_verdicts(self):
        """Measured verdicts, not asserted ones: holds / FAILS / missing,
        and NaN cells render as dashes, never 'nan'."""
        from rcmarl_tpu.analysis.plots import qualitative_claims_section

        def row(scen, H, ref, mine):
            return {"scenario": scen, "H": H, "ref_mean": ref, "mine_mean": mine}

        table = pd.DataFrame([
            row("coop", 0, -5.0, -5.0),
            row("coop", 1, -5.2, -5.2),
            # greedy: degrades at H=0, trimming recovers 90% -> holds twice
            row("greedy", 0, -7.0, -7.0),
            row("greedy", 1, -5.4, -5.4),
            # faulty: H=1 impact as bad as H=0 -> recovery claim FAILS
            row("faulty", 0, -7.0, -7.0),
            row("faulty", 1, -5.4, -7.2),
            # malicious: our cells absent -> missing (ref NaN must not
            # print as 'nan')
            row("malicious", 0, np.nan, np.nan),
        ])
        md = qualitative_claims_section(table)
        lines = {l.split("|")[1].strip() + l.split("|")[2].strip(): l
                 for l in md.splitlines() if l.startswith("| ")}
        assert "holds" in lines["greedy0"] and "holds" in lines["greedy1"]
        assert "FAILS" in lines["faulty1"] and "holds" in lines["faulty0"]
        assert "missing" in lines["malicious0"] and "missing" in lines["malicious1"]
        assert "nan" not in md
        assert "—" in lines["malicious0"]

        # A negligible H=0 degradation makes the H=1 recovery claim
        # untestable, never FAILS.
        table2 = pd.DataFrame([
            row("coop", 0, -5.0, -5.0),
            row("coop", 1, -5.2, -5.2),
            row("greedy", 0, -7.0, -5.1),
            row("greedy", 1, -5.4, -5.3),
        ])
        md2 = qualitative_claims_section(table2)
        g1 = [l for l in md2.splitlines() if l.startswith("| greedy | 1")][0]
        assert "untestable" in g1 and "FAILS" not in g1

    def test_reads_real_reference_sim_data(self):
        """Our loader consumes the reference's shipped pickles unchanged."""
        from rcmarl_tpu.analysis.plots import load_run

        if not REF_RUN.exists():
            pytest.skip("reference artifacts unavailable")
        phases = load_run(REF_RUN)
        assert len(phases) == 2  # 4000 + 4000 two-phase run
        assert all(len(p) == 4000 for p in phases)
        assert "True_team_returns" in phases[0].columns
