"""Property-based tests (hypothesis) for the consensus kernel and env.

The reference validates these components only empirically (SURVEY.md §4);
here the algebraic contracts that make the H-trimming defense work are
pinned as properties over randomized inputs:

- resilient aggregation: H=0 degenerates to the mean; output always lies
  within [min, max] of the inputs; invariant to permutations of the
  non-self neighbors; affine-equivariant; and — the Byzantine-resilience
  contract — with at most H adversarial inputs the output stays within
  the cooperative inputs' range no matter what the adversaries send.
- grid world: positions stay in the grid under arbitrary action
  sequences; rewards have the documented sign/zero structure.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# Optional dependency (the `test` extra — `pip install -e .[test]`):
# without the guard a missing hypothesis is a COLLECTION ERROR that
# fails the whole suite, not a skip.
pytest.importorskip("hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

from rcmarl_tpu.envs.grid_world import GridWorld, env_step
from rcmarl_tpu.ops.aggregation import resilient_aggregate

# Bounded to ±1e3: the contracts are algebraic, and at larger magnitudes
# f32 catastrophic cancellation (e.g. {1e6, -1e6, ...}) swamps any fixed
# tolerance with pure summation-order noise.
finite = st.floats(-1e3, 1e3, allow_nan=False, width=32)


def vals_strategy(min_n=3, max_n=9, m=5):
    return st.integers(min_n, max_n).flatmap(
        lambda n: arrays(np.float32, (n, m), elements=finite)
    )


@settings(max_examples=25, deadline=None)
@given(vals_strategy())
def test_h0_is_mean(vals):
    out = resilient_aggregate(jnp.asarray(vals), 0)
    np.testing.assert_allclose(
        np.asarray(out), vals.mean(axis=0), rtol=1e-4, atol=1e-4
    )


@settings(max_examples=25, deadline=None)
@given(vals_strategy(), st.integers(0, 3))
def test_output_within_input_range(vals, H):
    n = vals.shape[0]
    if 2 * H > n - 1:
        H = (n - 1) // 2
    out = np.asarray(resilient_aggregate(jnp.asarray(vals), H))
    tol = 1e-4 + 1e-5 * np.abs(vals).max(axis=0)  # f32 summation rounding
    assert (out <= vals.max(axis=0) + tol).all()
    assert (out >= vals.min(axis=0) - tol).all()


@settings(max_examples=25, deadline=None)
@given(vals_strategy(min_n=4), st.randoms(use_true_random=False))
def test_permutation_invariance_of_neighbors(vals, rng):
    """Aggregation must not depend on the order neighbors arrive in —
    only index 0 (own value) is special."""
    n = vals.shape[0]
    perm = list(range(1, n))
    rng.shuffle(perm)
    permuted = vals[[0] + perm]
    a = np.asarray(resilient_aggregate(jnp.asarray(vals), 1))
    b = np.asarray(resilient_aggregate(jnp.asarray(permuted), 1))
    # atol covers f32 summation-order noise at the strategy's magnitudes
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-3)


@settings(max_examples=25, deadline=None)
@given(
    vals_strategy(),
    st.floats(0.1, 10.0, allow_nan=False),
    st.floats(-100.0, 100.0, allow_nan=False),
)
def test_affine_equivariance(vals, a, b):
    """agg(a*x + b) == a*agg(x) + b for a > 0 (sort/clip/mean are all
    affine-equivariant), so consensus is unit-independent."""
    x = jnp.asarray(vals)
    lhs = np.asarray(resilient_aggregate(a * x + b, 1))
    rhs = a * np.asarray(resilient_aggregate(x, 1)) + b
    np.testing.assert_allclose(lhs, rhs, rtol=1e-3, atol=0.1)


@settings(max_examples=25, deadline=None)
@given(
    arrays(np.float32, (4, 5), elements=st.floats(-10, 10, allow_nan=False, width=32)),
    # adversaries deliberately get the FULL f32-friendly range (±1e6, far
    # beyond `finite`): the defense clips them into the cooperative range
    # before any summation, so magnitude must not matter here
    arrays(
        np.float32,
        (1, 5),
        elements=st.floats(-1e6, 1e6, allow_nan=False, width=32),
    ),
)
def test_byzantine_bound(coop, adv):
    """With own value cooperative and <= H adversarial neighbors, the
    aggregate stays within the cooperative range REGARDLESS of what the
    adversary transmits — the defense's core guarantee."""
    vals = jnp.concatenate([jnp.asarray(coop), jnp.asarray(adv)], axis=0)
    out = np.asarray(resilient_aggregate(vals, 1))
    assert (out <= coop.max(axis=0) + 1e-4).all()
    assert (out >= coop.min(axis=0) - 1e-4).all()


# ---------------------------------------------------------------------------
# Environment invariants
# ---------------------------------------------------------------------------


@settings(max_examples=20, deadline=None)
@given(
    st.integers(0, 10**6),
    st.lists(st.integers(0, 4), min_size=1, max_size=30),
    st.booleans(),
)
def test_positions_stay_in_grid(seed, action_seq, collision):
    env = GridWorld(nrow=4, ncol=6, n_agents=3, collision_physics=collision)
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    pos = jax.random.randint(k1, (3, 2), 0, jnp.array([4, 6]), dtype=jnp.int32)
    desired = jax.random.randint(k2, (3, 2), 0, jnp.array([4, 6]), dtype=jnp.int32)
    for a in action_seq:
        actions = jnp.full((3,), a, jnp.int32)
        pos, reward = env_step(env, pos, desired, actions)
        assert bool((pos[:, 0] >= 0).all() and (pos[:, 0] < 4).all())
        assert bool((pos[:, 1] >= 0).all() and (pos[:, 1] < 6).all())
        assert bool((np.asarray(reward) <= 0).all())


@settings(max_examples=20, deadline=None)
@given(st.integers(0, 10**6))
def test_zero_reward_iff_stay_at_goal(seed):
    env = GridWorld(nrow=5, ncol=5, n_agents=4)
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    pos = jax.random.randint(k1, (4, 2), 0, 5, dtype=jnp.int32)
    # random goals, but agent 0 pinned exactly at its goal
    desired = jax.random.randint(k2, (4, 2), 0, 5, dtype=jnp.int32)
    desired = desired.at[0].set(pos[0])
    actions = jnp.zeros((4,), jnp.int32)  # everyone stays
    _, reward = env_step(env, pos, desired, actions)
    at_goal = np.asarray(jnp.sum(jnp.abs(pos - desired), axis=1) == 0)
    r = np.asarray(reward)
    assert (r[at_goal] == 0).all()
    assert (r[~at_goal] < 0).all()
