"""Fitstack contracts: the cross-flavor fused fit scan
(``Config.fitstack``) pinned leaf-for-leaf BITWISE against the PR-4
phase-I arms, and the bf16 compute arm's cache hygiene.

Three layers:

1. Primitive twins (hypothesis): the unified minibatch step body of
   ``ops/fit.py`` reproduces ``fit_mse_full_batch`` bitwise under the
   identity plan (a full batch IS one minibatch covering the buffer)
   and ``fit_mse_minibatch`` bitwise under the shuffle plan, across
   ragged masks and partial final batches; the stacked
   ``fused_fit_scan`` reproduces its per-row fits bitwise (batching
   rows is value-neutral); ``assume_valid`` never changes a plan.
2. Block equivalence (deterministic): ``update_block`` with
   ``fitstack=True`` equals ``fitstack=False`` leaf for leaf across
   mixed adversary casts, ragged+faulted graphs, both netstack arms,
   and the traced-spec (fused-matrix) path.
3. The bf16 arm: compiling/running ``compute_dtype='bfloat16'``
   programs in the same process leaves the f32 arm's outputs BITWISE
   unchanged (compute_dtype is jit-static — distinct caches, no dtype
   leakage), while the bf16 outputs themselves are finite and really
   do come from a narrowed program (they differ from f32).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rcmarl_tpu.agents.updates import Batch
from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.faults import FaultPlan
from rcmarl_tpu.models.mlp import (
    init_mlp,
    mlp_forward,
    netstack_split_rows,
    netstack_stack_rows,
)
from rcmarl_tpu.ops.fit import (
    FitSchedule,
    fit_mse_full_batch,
    fit_mse_minibatch,
    fit_mse_sched,
    fused_fit_scan,
    valid_first_shuffle,
)
from rcmarl_tpu.training.update import (
    fitstack_enabled,
    init_agent_params,
    spec_from_config,
    update_block,
)

BASE = dict(
    n_agents=5,
    agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.GREEDY, Roles.MALICIOUS),
    in_nodes=circulant_in_nodes(5, 4),
    H=1,
    n_epochs=2,
    hidden=(8, 8),
    coop_fit_steps=3,
    adv_fit_epochs=2,
    adv_fit_batch=8,
    batch_size=8,
)

RAGGED = ((0, 1, 2, 3), (1, 2, 3), (2, 3, 4, 0), (3, 4, 0), (4, 0, 1))

PLAN = FaultPlan(
    drop_p=0.1, stale_p=0.2, corrupt_p=0.2, flip_p=0.1, nan_p=0.05, inf_p=0.05
)


def _mk_batch(key, cfg, B, full=False):
    ks = jax.random.split(key, 4)
    return Batch(
        s=jax.random.normal(ks[0], (B, cfg.n_agents, cfg.n_states)),
        ns=jax.random.normal(ks[1], (B, cfg.n_agents, cfg.n_states)),
        a=jax.random.randint(
            ks[2], (B, cfg.n_agents, 1), 0, cfg.n_actions
        ).astype(jnp.float32),
        r=jax.random.normal(ks[3], (B, cfg.n_agents, 1)),
        mask=jnp.ones((B,), jnp.float32)
        if full
        else (jnp.arange(B) < B - 3).astype(jnp.float32),
    )


def _run_block(cfg, spec=None):
    params = init_agent_params(jax.random.PRNGKey(0), cfg)
    batch = _mk_batch(jax.random.PRNGKey(1), cfg, 40)
    fresh = _mk_batch(jax.random.PRNGKey(2), cfg, 16, full=True)
    return update_block(cfg, params, batch, fresh, jax.random.PRNGKey(3), spec)


def _assert_tree_equal(a, b):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# --------------------------------------------------------------------------
# 1. Primitive twins
# --------------------------------------------------------------------------

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st
    from hypothesis.extra.numpy import arrays

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - exercised by bare environments
    HAVE_HYPOTHESIS = False

if HAVE_HYPOTHESIS:
    finite = st.floats(-10.0, 10.0, allow_nan=False, width=32)

    @st.composite
    def fit_case(draw):
        """(in_dim, hidden, B, x, target, mask, seed) with a RAGGED
        validity tail (0..B-1 invalid trailing rows; at least one row
        valid)."""
        in_dim = draw(st.integers(1, 5))
        hidden = tuple(
            draw(st.lists(st.integers(1, 5), min_size=0, max_size=2))
        )
        B = draw(st.integers(2, 12))
        x = draw(arrays(np.float32, (B, in_dim), elements=finite))
        target = draw(arrays(np.float32, (B, 1), elements=finite))
        n_valid = draw(st.integers(1, B))
        mask = (np.arange(B) < n_valid).astype(np.float32)
        seed = draw(st.integers(0, 2**16))
        return in_dim, hidden, B, x, target, mask, seed

    @settings(deadline=None, max_examples=10)
    @given(fit_case(), st.integers(1, 3))
    def test_identity_plan_fit_is_bitwise_full_batch(case, n_steps):
        """The unified minibatch body under the identity plan (one
        batch covering the buffer) == fit_mse_full_batch, params AND
        loss bitwise — the contract that lets the fused scan run the
        cooperative flavor through the shared step body."""
        in_dim, hidden, B, x, target, mask, seed = case
        params = init_mlp(jax.random.PRNGKey(seed), in_dim, hidden, 1)
        x, target, mask = jnp.asarray(x), jnp.asarray(target), jnp.asarray(mask)
        fwd = lambda p, xx: mlp_forward(p, xx)
        ref_p, ref_loss = fit_mse_full_batch(
            params, fwd, x, target, mask, n_steps, 0.05
        )
        sched = FitSchedule(epochs=n_steps, batch_size=B, shuffle=False)
        got_p, got_loss = fit_mse_sched(
            jnp.zeros((2,), jnp.uint32),  # never consumed
            params, fwd, x, target, mask, sched, 0.05,
        )
        _assert_tree_equal(ref_p, got_p)
        np.testing.assert_array_equal(np.asarray(ref_loss), np.asarray(got_loss))

    @settings(deadline=None, max_examples=10)
    @given(fit_case(), st.integers(1, 3), st.integers(1, 7))
    def test_sched_fit_is_bitwise_minibatch(case, epochs, batch_size):
        """The schedule form of the minibatch fit == fit_mse_minibatch
        for arbitrary ragged masks and partial final batches."""
        in_dim, hidden, B, x, target, mask, seed = case
        params = init_mlp(jax.random.PRNGKey(seed), in_dim, hidden, 1)
        x, target, mask = jnp.asarray(x), jnp.asarray(target), jnp.asarray(mask)
        key = jax.random.PRNGKey(seed + 1)
        fwd = lambda p, xx: mlp_forward(p, xx)
        ref_p, ref_loss = fit_mse_minibatch(
            key, params, fwd, x, target, mask, epochs, batch_size, 0.05
        )
        got_p, got_loss = fit_mse_sched(
            key, params, fwd, x, target, mask,
            FitSchedule(epochs=epochs, batch_size=batch_size, shuffle=True),
            0.05,
        )
        _assert_tree_equal(ref_p, got_p)
        np.testing.assert_array_equal(np.asarray(ref_loss), np.asarray(got_loss))

    @settings(deadline=None, max_examples=6)
    @given(fit_case(), st.integers(2, 4), st.booleans())
    def test_fused_rows_match_per_row_fits(case, n_rows, shuffle):
        """Stacking R rows into one fused scan is value-neutral: every
        row's fitted params == the same fit run alone (mixed input
        widths exercise the first-layer zero-padding)."""
        in_dim, hidden, B, x, target, mask, seed = case
        wide = in_dim + 2
        keys = jax.random.split(jax.random.PRNGKey(seed), n_rows + 1)
        # alternate narrow (padded) and wide rows — the critic/TR mix
        dims = [in_dim if r % 2 == 0 else wide for r in range(n_rows)]
        nets = [
            jax.vmap(lambda k: init_mlp(k, d, hidden, 1))(
                jax.random.split(keys[r], 2)  # N=2 agents
            )
            for r, d in enumerate(dims)
        ]
        x = jnp.asarray(x)
        xw = jnp.pad(x, ((0, 0), (0, 2)), constant_values=0.5)
        xs = jnp.stack([
            jnp.pad(x, ((0, 0), (0, 2))) if d == in_dim else xw for d in dims
        ])
        tgt = jnp.broadcast_to(jnp.asarray(target), (n_rows, 2, B, 1))
        mask = jnp.asarray(mask)
        rkeys = jax.vmap(lambda k: jax.random.split(k, 2))(
            jax.random.split(keys[-1], n_rows)
        )
        sched = FitSchedule(
            epochs=2, batch_size=(5 if shuffle else B), shuffle=shuffle
        )
        fwd = lambda p, xx: mlp_forward(p, xx)
        fused, losses = fused_fit_scan(
            rkeys, netstack_stack_rows(nets), fwd, xs, tgt, mask, sched, 0.05
        )
        parts = netstack_split_rows(fused, dims)
        for r, d in enumerate(dims):
            ref, ref_loss = jax.vmap(
                lambda k, p, t: fit_mse_sched(
                    k, p, fwd, xs[r][:, :d] if d == in_dim else xs[r],
                    t, mask, sched, 0.05,
                )
            )(rkeys[r], nets[r], tgt[r])
            # the narrow rows ran PADDED inside the fused scan; trim is
            # the lossless inverse (pad rows carry exact zeros)
            _assert_tree_equal(parts[r], ref)
            np.testing.assert_array_equal(
                np.asarray(losses[r]), np.asarray(ref_loss)
            )


def test_assume_valid_shuffle_is_bitwise():
    """The assume_valid fast path (rows with no invalid tail skip the
    valid-first penalty work) returns the IDENTICAL plan."""
    for cap, n_b, bs in ((13, 4, 4), (8, 1, 8), (20, 3, 7)):
        key = jax.random.PRNGKey(cap)
        mask = jnp.ones((cap,), jnp.float32)
        idx_a, val_a = valid_first_shuffle(key, mask, n_b, bs)
        idx_b, val_b = valid_first_shuffle(
            key, mask, n_b, bs, assume_valid=True
        )
        np.testing.assert_array_equal(np.asarray(idx_a), np.asarray(idx_b))
        np.testing.assert_array_equal(np.asarray(val_a), np.asarray(val_b))


# --------------------------------------------------------------------------
# 2. Block equivalence
# --------------------------------------------------------------------------


class TestBlockEquivalence:
    """update_block(fitstack=True) == update_block(fitstack=False),
    leaf for leaf — the PR-4 arms stay the bitwise reference."""

    #: the full matrix (5-agent mixed cast, ragged+faulted, netstack-on,
    #: H=0, sort arm) rides the slow marker to keep the 870s tier-1
    #: wall budget; tier-1 keeps a TINY all-flavor pin below, and the
    #: 3-agent mixed + ragged+faulted fused pins ALSO run end-to-end in
    #: ci_tier1.sh's fused-fit smoke cell, so they stay CI-enforced
    SLOW_MODES = {
        "mixed_cast": {},
        "ragged_sanitize_faults": dict(
            in_nodes=RAGGED, consensus_sanitize=True, fault_plan=PLAN
        ),
        "netstack_on": dict(netstack=True),
        "h0": dict(H=0),
        "xla_sort": dict(consensus_impl="xla_sort"),
    }

    @pytest.mark.slow
    def test_pinned_leaf_for_leaf_tiny_all_flavors(self):
        """The fused-vs-PR-4 block pin on a 3-agent cast with one agent
        of EVERY adversarial role, so both fused groups (full-batch
        coop pair + all 5 minibatch flavor rows) are live. Slow-marked
        for the tier-1 wall budget; the SAME pin runs end-to-end in
        ci_tier1.sh's fused-fit smoke cell on every CI run."""
        kw = dict(
            BASE,
            n_agents=3,
            agent_roles=(Roles.COOPERATIVE, Roles.GREEDY, Roles.MALICIOUS),
            in_nodes=circulant_in_nodes(3, 3),
            hidden=(4,),
        )
        on = _run_block(Config(**kw, fitstack=True))
        off = _run_block(Config(**kw, fitstack=False))
        _assert_tree_equal(on, off)

    @pytest.mark.slow
    @pytest.mark.parametrize("mode", sorted(SLOW_MODES))
    def test_pinned_leaf_for_leaf_extended(self, mode):
        kw = dict(BASE)
        kw.update(self.SLOW_MODES[mode])
        on = _run_block(Config(**kw, fitstack=True))
        off = _run_block(Config(**kw, fitstack=False))
        _assert_tree_equal(on, off)

    @pytest.mark.slow
    def test_traced_spec(self):
        """The fused-matrix path: fused fits under a traced CellSpec ==
        the PR-4 arm under the same spec."""
        cfg_on = Config(**BASE, fitstack=True)
        cfg_off = Config(**BASE, fitstack=False)
        on = _run_block(cfg_on, spec_from_config(cfg_on))
        off = _run_block(cfg_off, spec_from_config(cfg_off))
        _assert_tree_equal(on, off)

    def test_auto_policy_resolves_by_backend(self):
        """fitstack='auto' (the Config default) mirrors the
        netstack='auto' measured backend policy."""
        cfg = Config(**BASE)
        assert cfg.fitstack == "auto"
        expected = jax.default_backend() == "tpu"
        assert fitstack_enabled(cfg) == expected
        assert fitstack_enabled(cfg.replace(fitstack=True)) is True
        assert fitstack_enabled(cfg.replace(fitstack=False)) is False
        with pytest.raises(ValueError, match="fitstack"):
            Config(**BASE, fitstack="sideways")


# --------------------------------------------------------------------------
# 3. The bf16 arm: no dtype leakage across jit caches
# --------------------------------------------------------------------------


# ~9s — tier-1 870s wall-budget shed; the bf16 kernel/dtype pins in
# tests/test_models_ops.py stay fast
@pytest.mark.slow
def test_bf16_rows_do_not_perturb_f32_outputs():
    """f32 reference outputs are BITWISE unchanged when bfloat16
    programs compile and run in the same process (compute_dtype is
    jit-static: distinct caches, zero cross-contamination), and the
    bf16 arm itself is live (finite outputs that differ from f32)."""
    kw = dict(
        BASE,
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        hidden=(4,),
    )
    cfg32 = Config(**kw, fitstack=True)
    cfg16 = Config(**kw, fitstack=True, compute_dtype="bfloat16")
    first = _run_block(cfg32)
    bf16 = _run_block(cfg16)
    again = _run_block(cfg32)
    _assert_tree_equal(first, again)
    leaves16 = jax.tree.leaves(bf16)
    assert all(bool(jnp.all(jnp.isfinite(l))) for l in leaves16)
    # params/optimizer state stay f32 — only the matmul INPUTS narrow
    # (integer leaves, e.g. Adam's step counter, are exempt)
    assert all(
        l.dtype == jnp.float32
        for l in leaves16
        if jnp.issubdtype(l.dtype, jnp.floating)
    )
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(first), leaves16)
    ), "bfloat16 arm produced bitwise-f32 results: the dtype is not threaded"
