"""Latency-harness contracts (rcmarl_tpu.serve.load).

The pins that make a latency-vs-load row trustworthy:

- arrival plans are DETERMINISTIC in their seed (replaying a sweep
  replays the exact queueing), with the configured mean load;
- the micro-batching queue's close rule is exact: a batch closes when
  it FILLS (max_batch) or when the oldest request has waited max_wait,
  never before the server frees — verified against hand-computed
  latencies on crafted arrival plans;
- saturation is accounted, not hidden: past the capacity
  max_batch/service the utilization pins near 1, the queue depth grows,
  and the knee extraction flags the crossing;
- the whole report is replayable: same arrivals + same service model =
  identical report.

The queue units run on an injected constant service model (no jax at
all); one tiny cell drives the REAL serve_block service model end to
end at the padded shape.
"""

from __future__ import annotations

import numpy as np
import pytest

from rcmarl_tpu.serve.load import (
    KNEE_FACTOR,
    bursty_arrivals,
    poisson_arrivals,
    run_load,
    saturation_knee,
    sweep_load,
)


class TestArrivalPlans:
    def test_poisson_deterministic_in_seed(self):
        a = poisson_arrivals(7, 500, 1000.0)
        b = poisson_arrivals(7, 500, 1000.0)
        np.testing.assert_array_equal(a, b)
        assert not np.array_equal(a, poisson_arrivals(8, 500, 1000.0))

    def test_poisson_mean_rate(self):
        a = poisson_arrivals(0, 20000, 1000.0)
        # mean inter-arrival gap ~ 1/rate (law of large numbers slack)
        assert np.diff(a).mean() == pytest.approx(1e-3, rel=0.05)
        assert np.all(np.diff(a) >= 0)  # sorted by construction

    def test_bursty_same_long_run_load_in_spikes(self):
        burst = 8
        a = bursty_arrivals(0, 8000, 1000.0, burst=burst)
        assert a.shape == (8000,)
        # bursts are simultaneous: every run of `burst` shares one time
        assert np.all(a[:burst] == a[0])
        # long-run load matches the configured rate (~1000 req/s)
        rate = len(a) / (a[-1] - a[0])
        assert rate == pytest.approx(1000.0, rel=0.1)
        np.testing.assert_array_equal(
            a, bursty_arrivals(0, 8000, 1000.0, burst=burst)
        )

    def test_invalid_args_loud(self):
        with pytest.raises(ValueError):
            poisson_arrivals(0, 0, 1000.0)
        with pytest.raises(ValueError):
            poisson_arrivals(0, 10, 0.0)
        with pytest.raises(ValueError):
            bursty_arrivals(0, 10, 100.0, burst=0)


class TestMicroBatchQueue:
    def test_max_wait_flush_exact(self):
        """A lone request closes at arrival + max_wait; its latency is
        exactly max_wait + service."""
        rep = run_load(
            lambda fill: 0.002, np.array([1.0]), max_batch=64,
            max_wait=0.005,
        )
        assert rep["launches"] == 1
        assert rep["fill_mean"] == 1.0
        assert rep["p50"] == pytest.approx(0.007)
        assert rep["p99"] == pytest.approx(0.007)

    def test_max_batch_closes_immediately(self):
        """max_batch simultaneous arrivals close at the arrival instant:
        latency is pure service, the max_wait budget untouched."""
        arr = np.full(8, 2.0)
        rep = run_load(lambda fill: 0.003, arr, max_batch=8, max_wait=1.0)
        assert rep["launches"] == 1
        assert rep["fill_mean"] == 8.0
        assert rep["p99"] == pytest.approx(0.003)

    def test_close_rule_hand_computed(self):
        """Crafted plan, constant service 2ms, max_batch 2, max_wait
        10ms: [0.0, 0.001] fill a batch at t=0.001 (latencies 3ms/2ms);
        [0.1] rides its max_wait alone (12ms); [0.2, 0.2005] fill at
        0.2005 (2.5ms/2ms)."""
        arr = np.array([0.0, 0.001, 0.1, 0.2, 0.2005])
        lat = {}

        def service(fill):
            return 0.002

        rep = run_load(service, arr, max_batch=2, max_wait=0.010)
        assert rep["launches"] == 3
        # reconstruct the exact latencies: close times 0.001, 0.110,
        # 0.2005; completions 0.003, 0.112, 0.2025
        expect = np.array(
            [0.003, 0.002, 0.012, 0.0025, 0.002]
        )
        assert rep["mean_latency"] == pytest.approx(expect.mean())
        assert rep["p99"] == pytest.approx(
            np.percentile(expect, 99.0)
        )
        del lat

    def test_backlog_launches_without_extra_wait(self):
        """With the server busy and >= max_batch waiting, the next batch
        closes the instant the server frees (no max_wait added)."""
        # 6 simultaneous arrivals, max_batch 2, service 1ms: three
        # back-to-back launches at t=0, 0.001, 0.002
        arr = np.zeros(6)
        rep = run_load(lambda fill: 0.001, arr, max_batch=2, max_wait=0.5)
        assert rep["launches"] == 3
        assert rep["p99"] == pytest.approx(0.003)
        assert rep["utilization"] == pytest.approx(1.0)

    def test_saturation_accounting(self):
        """Offered load past max_batch/service: utilization pins ~1,
        queue depth grows, and latency is backlog-dominated (far above
        the underloaded max_wait+service bound)."""
        arr = poisson_arrivals(0, 4000, 10000.0)  # 10k req/s offered
        # capacity = 16 / 0.004 = 4k req/s << offered
        rep = run_load(lambda fill: 0.004, arr, max_batch=16, max_wait=0.002)
        assert rep["utilization"] > 0.99
        assert rep["fill_mean"] == pytest.approx(16.0, rel=0.05)
        assert rep["queue_depth_max"] > 100
        assert rep["p99"] > 10 * (0.002 + 0.004)

    def test_report_replayable(self):
        arr = poisson_arrivals(3, 1000, 5000.0)
        a = run_load(lambda fill: 0.001, arr, 32, 0.004)
        b = run_load(lambda fill: 0.001, arr, 32, 0.004)
        assert a == b

    def test_bad_service_model_loud(self):
        with pytest.raises(ValueError):
            run_load(lambda fill: 0.0, np.array([0.0]), 4, 0.01)
        with pytest.raises(ValueError):
            run_load(lambda fill: 0.001, np.array([0.0]), 0, 0.01)
        with pytest.raises(ValueError):
            run_load(lambda fill: 0.001, np.array([0.0]), 4, -1.0)


class TestDeadlineShedding:
    def test_shed_off_reproduces_the_shed_free_queue(self):
        """``shed_after=inf`` (and the default) is bitwise the
        historical queue: identical latency numbers, with the shedding
        ledger present at zero on every row (the PR-14-row
        reproduction pin)."""
        import math

        arr = poisson_arrivals(0, 2000, 10000.0)
        base = run_load(lambda f: 0.004, arr, 16, 0.002)
        explicit = run_load(lambda f: 0.004, arr, 16, 0.002, math.inf)
        assert base == explicit
        assert base["shed"] == 0 and base["shed_fraction"] == 0.0
        assert base["served"] == base["requests"]

    def test_shed_accounting_hand_computed(self):
        """Service 10ms, max_batch 1, shed_after 5ms, arrivals at 0 /
        1ms / 2ms: request 0 serves (10ms), requests 1 and 2 have
        waited 9ms/8ms when the server frees — both past the deadline,
        both shed."""
        rep = run_load(
            lambda f: 0.010, np.array([0.0, 0.001, 0.002]),
            max_batch=1, max_wait=0.0, shed_after=0.005,
        )
        assert rep["launches"] == 1
        assert rep["served"] == 1 and rep["shed"] == 2
        assert rep["shed_fraction"] == pytest.approx(2.0 / 3.0)
        assert rep["p99"] == pytest.approx(0.010)

    def test_shed_bounds_p99_past_the_knee(self):
        """The acceptance criterion: past the saturation knee, deadline
        shedding keeps p99 within 2x the knee-point p99 (the shed-free
        twin explodes into backlog), with the cost ledgered as the shed
        fraction. This is the same contract the chaos campaign's
        serve_overload cells gate in RESILIENCE.jsonl."""
        service = lambda f: 0.001  # noqa: E731 — injected model
        max_batch, max_wait = 16, 0.002
        capacity = max_batch / 0.001
        knee = run_load(
            service, poisson_arrivals(0, 4000, 0.8 * capacity),
            max_batch, max_wait,
        )
        overload = poisson_arrivals(0, 4000, 4.0 * capacity)
        noshed = run_load(service, overload, max_batch, max_wait)
        shed = run_load(
            service, overload, max_batch, max_wait, shed_after=0.002
        )
        assert noshed["p99"] > 2.0 * knee["p99"]  # the documented cliff
        assert shed["p99"] <= 2.0 * knee["p99"]  # bounded past the knee
        assert shed["shed_fraction"] > 0.5  # the cost is explicit
        # and the bound is the analytical one: shed_after+max_wait+svc
        assert shed["p99"] <= 0.002 + max_wait + 0.001 + 1e-9

    def test_sweep_rows_carry_shed_fraction(self):
        pts = sweep_load(
            lambda f: 0.001, [1000.0, 200000.0], n_requests=2000,
            max_batch=16, max_wait=0.002, seed=0, shed_after=0.004,
        )
        assert all("shed_fraction" in p for p in pts)
        assert pts[0]["shed_fraction"] == 0.0  # light load sheds nothing
        assert pts[-1]["shed_fraction"] > 0.0  # saturated load sheds

    def test_bad_deadline_loud_and_head_always_serves(self):
        with pytest.raises(ValueError, match="shed_after"):
            run_load(lambda f: 0.001, np.array([0.0]), 4, 0.01,
                     shed_after=0.0)
        # a deadline far below one service time sheds everything BEHIND
        # the head-of-line request, but the head itself always serves
        # (its wait is zero when the server first considers it)
        rep = run_load(
            lambda f: 1.0, np.zeros(64), max_batch=1, max_wait=0.0,
            shed_after=1e-6,
        )
        assert rep["served"] == 1 and rep["shed"] == 63


class TestSweepAndKnee:
    def test_sweep_points_tagged_and_knee_found(self):
        """Constant service 1ms, max_batch 32 -> capacity 32k req/s:
        loads below stay under the knee, loads far above saturate."""
        pts = sweep_load(
            lambda fill: 0.001, [1000.0, 8000.0, 200000.0],
            n_requests=3000, max_batch=32, max_wait=0.005, seed=0,
        )
        assert [p["offered_load"] for p in pts] == [1e3, 8e3, 2e5]
        assert all(p["arrival"] == "poisson" for p in pts)
        knee = saturation_knee(pts)
        assert knee == 8000.0  # 200k is past capacity: p99 explodes
        sat = pts[-1]
        assert sat["utilization"] > 0.99
        assert sat["p99"] > KNEE_FACTOR * pts[0]["p99"]

    def test_knee_none_when_sweep_starts_saturated(self):
        pts = sweep_load(
            lambda fill: 0.01, [100000.0], n_requests=2000,
            max_batch=8, max_wait=0.001, seed=0,
        )
        assert saturation_knee(pts) is None

    def test_bursty_sweep_waits_less_than_poisson_at_light_load(self):
        """Bursts fill batches instantly, so at light load the bursty
        arrival pattern SHORTENS p50 vs the same offered Poisson load
        (the batching-friendly spike) — the two processes are genuinely
        different inputs, not a relabel."""
        kw = dict(
            n_requests=2000, max_batch=16, max_wait=0.01, seed=0,
        )
        poisson = sweep_load(lambda f: 0.001, [500.0], **kw)[0]
        bursty = sweep_load(
            lambda f: 0.001, [500.0], arrival="bursty", burst=16, **kw
        )[0]
        assert bursty["p50"] < poisson["p50"]
        assert bursty["fill_mean"] > poisson["fill_mean"]

    def test_unknown_arrival_loud(self):
        with pytest.raises(ValueError):
            sweep_load(lambda f: 0.001, [1.0], 10, 4, 0.01, arrival="nope")


class TestRealServiceModel:
    def test_serve_service_fn_measures_real_launches(self):
        """The real service model: a compiled serve_block launch at the
        padded max_batch shape, positive finite seconds per call, and
        the queue runs on it end to end."""
        import jax

        from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
        from rcmarl_tpu.serve.engine import stack_actor_rows
        from rcmarl_tpu.serve.load import serve_service_fn
        from rcmarl_tpu.training.trainer import init_train_state

        cfg = Config(
            n_agents=3,
            agent_roles=(Roles.COOPERATIVE,) * 3,
            in_nodes=circulant_in_nodes(3, 3),
            nrow=3, ncol=3, n_episodes=4, n_ep_fixed=2, max_ep_len=4,
            n_epochs=2, H=1,
        )
        block = stack_actor_rows(
            init_train_state(cfg, jax.random.PRNGKey(0)).params, cfg
        )
        service = serve_service_fn(cfg, block, max_batch=8)
        s = service(5)  # partial fill, same padded shape
        assert s > 0.0 and np.isfinite(s)
        rep = run_load(
            service, poisson_arrivals(0, 40, 2000.0), max_batch=8,
            max_wait=0.002,
        )
        assert rep["requests"] == 40
        assert np.isfinite(rep["p99"]) and rep["p99"] > 0
