"""Serving subsystem contracts (rcmarl_tpu.serve).

The pins that make the serve path trustworthy:

- batched-vs-per-agent PARITY: the one-launch ``serve_block`` computes
  probabilities BITWISE equal to the per-agent ``actor_probs`` path
  (the reference get_action's policy computation), and samples
  IDENTICAL actions when a per-agent per-request loop is handed the
  same fold_in keys;
- hot-swap ATOMICITY: a swap mid-loop replaces the whole block or
  nothing — no launch ever observes a torn tree;
- guarded DEGRADATION: corrupted/truncated/non-finite candidates are
  rejected with counters incremented while the engine keeps serving the
  last good params; a replica-world checkpoint fails loudly;
- the bf16 serve arm stays finite.

Everything runs on a tiny 3-agent config with states built directly by
``init_train_state`` (no training) to stay inside the tier-1 budget.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.models.mlp import actor_probs, agent_slice
from rcmarl_tpu.serve.engine import (
    ServeEngine,
    serve_block,
    serve_keys,
    serve_request_keys,
    stack_actor_rows,
)
from rcmarl_tpu.serve.swap import CheckpointWatcher
from rcmarl_tpu.training.trainer import init_train_state
from rcmarl_tpu.utils.checkpoint import save_checkpoint


def tiny_cfg(**overrides):
    base = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        n_episodes=4,
        n_ep_fixed=2,
        max_ep_len=4,
        n_epochs=2,
        H=1,
    )
    base.update(overrides)
    return Config(**base)


CFG = tiny_cfg()
STATE = init_train_state(CFG, jax.random.PRNGKey(0))
STATE_B = init_train_state(CFG, jax.random.PRNGKey(1))
OBS = jax.random.normal(
    jax.random.PRNGKey(5), (6, CFG.n_agents, CFG.obs_dim)
)
KEY = jax.random.PRNGKey(9)


def _leaves_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


def _engine(tmp_path, state=STATE, cfg=CFG, **kw):
    path = tmp_path / "checkpoint.npz"
    save_checkpoint(path, state, cfg)
    return ServeEngine(path, **kw)


class TestServeBlock:
    def test_stacked_block_is_the_checkpoint_actor_layout(self):
        """netstack_stack_rows over the homogeneous actor family is
        bitwise the checkpoint's stacked actor leaves (the padding is
        a provable no-op here)."""
        _leaves_equal(stack_actor_rows(STATE.params, CFG), STATE.params.actor)

    def test_probs_bitwise_vs_per_agent_path(self):
        """The batched launch's probabilities == the per-agent eager
        actor_probs path, bitwise, for every (request, agent)."""
        _, probs = serve_block(CFG, stack_actor_rows(STATE.params, CFG), OBS, KEY)
        for n in range(CFG.n_agents):
            ref = actor_probs(
                agent_slice(STATE.params.actor, n),
                OBS[:, n, :],
                CFG.leaky_alpha,
                CFG.dot_dtype,
            )
            np.testing.assert_array_equal(
                np.asarray(probs[:, n]), np.asarray(ref)
            )

    def test_actions_identical_under_shared_keys(self):
        """A per-agent per-request loop handed the same fold_in keys
        samples the exact actions the batched launch emitted."""
        block = stack_actor_rows(STATE.params, CFG)
        actions, probs = serve_block(CFG, block, OBS, KEY)
        keys = serve_request_keys(KEY, OBS.shape[0], CFG.n_agents)
        for b in range(OBS.shape[0]):
            for n in range(CFG.n_agents):
                a = jax.random.categorical(keys[b, n], jnp.log(probs[b, n]))
                assert int(a) == int(actions[b, n]), (b, n)

    def test_greedy_is_argmax(self):
        block = stack_actor_rows(STATE.params, CFG)
        actions, probs = serve_block(CFG, block, OBS, KEY, mode="greedy")
        np.testing.assert_array_equal(
            np.asarray(actions), np.asarray(jnp.argmax(probs, axis=-1))
        )

    def test_eval_arm_replays_fixed_seeds(self, tmp_path):
        """The deterministic eval stream: the same (eval_seed, step)
        pair replays the exact action stream across engines."""
        e1 = _engine(tmp_path, eval_seed=7)
        a1, p1 = e1.serve(OBS, step=3)
        e2 = ServeEngine(tmp_path / "checkpoint.npz", eval_seed=7)
        a2, p2 = e2.serve(OBS, step=3)
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a2))
        np.testing.assert_array_equal(np.asarray(p1), np.asarray(p2))
        # and the explicit-key form agrees with the stream form
        a3, _ = serve_block(
            CFG, e1.block, OBS, serve_keys(7, 3)
        )
        np.testing.assert_array_equal(np.asarray(a1), np.asarray(a3))

    def test_bf16_serve_arm_finite(self, tmp_path):
        """The bfloat16 compute arm serves finite, normalized policies
        (a distinct jit cache entry — compute_dtype is part of the
        static config, the PR-8 no-dtype-leak discipline)."""
        cfg16 = tiny_cfg(compute_dtype="bfloat16")
        state = init_train_state(cfg16, jax.random.PRNGKey(0))
        path = tmp_path / "c16.npz"
        save_checkpoint(path, state, cfg16)
        eng = ServeEngine(path)
        actions, probs = eng.serve(OBS)
        assert np.isfinite(np.asarray(probs)).all()
        np.testing.assert_allclose(
            np.asarray(probs).sum(-1), 1.0, rtol=1e-5
        )
        assert np.asarray(actions).shape == (OBS.shape[0], CFG.n_agents)


class TestHotSwap:
    def test_swap_applies_new_params_atomically(self, tmp_path):
        """Swap mid-loop: every launch is either pure-A or pure-B —
        the engine's single block reference is replaced wholesale, so
        the post-swap launch equals a pure-B engine's output bitwise."""
        eng = _engine(tmp_path)
        watcher = CheckpointWatcher(eng)
        a_before, p_before = eng.serve(OBS, key=KEY)
        assert watcher.poll() is False  # unchanged file: no-op
        save_checkpoint(tmp_path / "checkpoint.npz", STATE_B, CFG)
        assert watcher.poll() is True
        _leaves_equal(eng.block, STATE_B.params.actor)  # the WHOLE tree
        a_after, p_after = eng.serve(OBS, key=KEY)
        # pure-B reference output (fresh block, same key)
        ref_a, ref_p = serve_block(
            CFG, stack_actor_rows(STATE_B.params, CFG), OBS, KEY
        )
        np.testing.assert_array_equal(np.asarray(a_after), np.asarray(ref_a))
        np.testing.assert_array_equal(np.asarray(p_after), np.asarray(ref_p))
        # and the pre-swap launch was pure-A
        ref_a0, _ = serve_block(
            CFG, stack_actor_rows(STATE.params, CFG), OBS, KEY
        )
        np.testing.assert_array_equal(np.asarray(a_before), np.asarray(ref_a0))
        assert eng.counters["swaps"] == 1
        assert eng.counters["rejects"] == 0

    def test_corrupted_candidate_serves_last_good(self, tmp_path):
        """Corrupting BOTH the primary and its .prev rotation must be
        rejected (counter incremented) with the engine still serving
        the pre-corruption block bitwise."""
        eng = _engine(tmp_path)
        watcher = CheckpointWatcher(eng)
        save_checkpoint(tmp_path / "checkpoint.npz", STATE_B, CFG)
        assert watcher.poll() is True
        for name in ("checkpoint.npz", "checkpoint.npz.prev"):
            with open(tmp_path / name, "r+b") as f:
                f.seek(100)
                f.write(b"\xde\xad\xbe\xef" * 16)
        assert watcher.poll() is False
        assert eng.counters["rejects"] == 1
        _leaves_equal(eng.block, STATE_B.params.actor)  # last good kept
        assert "served: last-good" in eng.summary_line()

    def test_double_corruption_within_one_poll_cycle(self, tmp_path):
        """Primary AND .prev both corrupted BETWEEN polls (one poll
        cycle sees the whole double fault): exactly one reject, ZERO
        fallbacks (a fallback counter that moved would claim the .prev
        served, which it never did), serving stays bitwise the last
        good block — and a healthy re-publish recovers completely.
        Extends the single-corruption cells above; the chaos campaign's
        ckpt_bitflip@both cell gates the same contract in
        RESILIENCE.jsonl."""
        eng = _engine(tmp_path)
        watcher = CheckpointWatcher(eng)
        ref_a, ref_p = eng.serve(OBS, key=KEY)
        fallbacks_before = eng.counters["fallbacks"]
        # a new publish lands, then BOTH files rot before the next poll
        save_checkpoint(tmp_path / "checkpoint.npz", STATE_B, CFG)
        for name in ("checkpoint.npz", "checkpoint.npz.prev"):
            with open(tmp_path / name, "r+b") as f:
                f.seek(100)
                f.write(b"\xde\xad\xbe\xef" * 16)
        assert watcher.poll() is False
        assert eng.counters["rejects"] == 1
        assert eng.counters["fallbacks"] == fallbacks_before  # never served
        assert eng.counters["swaps"] == 0
        _leaves_equal(eng.block, STATE.params.actor)  # last good kept
        a, p = eng.serve(OBS, key=KEY)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(ref_a))
        np.testing.assert_array_equal(np.asarray(p), np.asarray(ref_p))
        assert "served: last-good" in eng.summary_line()
        # recovery: a healthy re-publish swaps in and clears the status
        save_checkpoint(tmp_path / "checkpoint.npz", STATE_B, CFG)
        assert watcher.poll() is True
        _leaves_equal(eng.block, STATE_B.params.actor)
        assert eng.counters["swaps"] == 1 and eng.counters["rejects"] == 1
        assert "served: fresh" in eng.summary_line()

    def test_corrupt_primary_falls_back_to_prev(self, tmp_path):
        """A corrupted primary with a good .prev swaps the PREVIOUS
        params in (the discovery chain's fallback), counted as a
        fallback, not a reject."""
        eng = _engine(tmp_path)
        watcher = CheckpointWatcher(eng)
        save_checkpoint(tmp_path / "checkpoint.npz", STATE_B, CFG)
        # primary = B, .prev = A; corrupt only the primary
        with open(tmp_path / "checkpoint.npz", "r+b") as f:
            f.seek(100)
            f.write(b"\xde\xad\xbe\xef" * 16)
        assert watcher.poll() is True
        _leaves_equal(eng.block, STATE.params.actor)  # .prev holds A
        assert eng.counters["fallbacks"] == 1
        assert eng.counters["rejects"] == 0

    def test_status_recovers_after_successful_swap(self, tmp_path):
        """'served: last-good' reflects the CURRENT block: a rejected
        candidate degrades the status, the next applied swap restores
        'served: fresh' (the counters keep the full history)."""
        eng = _engine(tmp_path)
        watcher = CheckpointWatcher(eng)
        save_checkpoint(tmp_path / "checkpoint.npz", STATE_B, CFG)
        assert watcher.poll() is True
        for name in ("checkpoint.npz", "checkpoint.npz.prev"):
            with open(tmp_path / name, "r+b") as f:
                f.seek(100)
                f.write(b"\xde\xad\xbe\xef" * 16)
        assert watcher.poll() is False
        assert "served: last-good" in eng.summary_line()
        save_checkpoint(tmp_path / "checkpoint.npz", STATE, CFG)  # fixed deploy
        assert watcher.poll() is True
        assert "served: fresh" in eng.summary_line()
        assert eng.counters["rejects"] == 1  # history preserved

    def test_nonfinite_candidate_rejected(self, tmp_path):
        """A checksum-valid file carrying NaN params is refused by the
        fault guard in front of the swap."""
        eng = _engine(tmp_path)
        watcher = CheckpointWatcher(eng)
        poisoned = STATE_B._replace(
            params=STATE_B.params._replace(
                actor=jax.tree.map(
                    lambda l: l.at[0].set(jnp.nan), STATE_B.params.actor
                )
            )
        )
        save_checkpoint(tmp_path / "checkpoint.npz", poisoned, CFG)
        assert watcher.poll() is False
        assert eng.counters["rejects"] == 1
        _leaves_equal(eng.block, STATE.params.actor)

    def test_replica_world_fails_loudly(self, tmp_path):
        """A replica-stacked gossip checkpoint must raise at engine
        construction AND at hot-swap — never silently serve replica 0."""
        states = jax.vmap(lambda k: init_train_state(CFG, k))(
            jax.random.split(jax.random.PRNGKey(0), 2)
        )
        rpath = tmp_path / "replica.npz"
        save_checkpoint(
            rpath, states, CFG,
            meta={"replicas": 2, "gossip_round": 0, "excluded": [False] * 2},
        )
        with pytest.raises(ValueError, match="replica"):
            ServeEngine(rpath)
        eng = _engine(tmp_path)
        watcher = CheckpointWatcher(eng)
        save_checkpoint(
            tmp_path / "checkpoint.npz", states, CFG,
            meta={"replicas": 2, "gossip_round": 0, "excluded": [False] * 2},
        )
        with pytest.raises(ValueError, match="replica"):
            watcher.poll()

    def test_nonfinite_initial_checkpoint_refused(self, tmp_path):
        """At construction there is no last-good block to degrade to:
        a poisoned initial checkpoint is a loud error."""
        poisoned = STATE._replace(
            params=STATE.params._replace(
                actor=jax.tree.map(
                    lambda l: l.at[0].set(jnp.inf), STATE.params.actor
                )
            )
        )
        path = tmp_path / "bad.npz"
        save_checkpoint(path, poisoned, CFG)
        with pytest.raises(ValueError, match="non-finite"):
            ServeEngine(path)


class TestServeCLI:
    def test_serve_cli_emits_actions_per_sec_row(self, tmp_path, capsys):
        import json

        from rcmarl_tpu.cli import main

        path = tmp_path / "checkpoint.npz"
        save_checkpoint(path, STATE, CFG)
        assert main([
            "serve", "--checkpoint", str(path),
            "--batch", "8", "--steps", "2", "--reps", "1",
            "--obs_buffers", "2",
        ]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        row = json.loads(out[0])
        assert row["kind"] == "serve"
        assert row["actions_per_sec"] > 0
        assert row["cost_fingerprint"]
        assert row["headline"] is False  # CPU row discipline
        assert row["degradation"]["rejects"] == 0
        assert "served: fresh" in out[-1]

    def test_evaluate_cli_emits_stats_row(self, tmp_path, capsys):
        import json

        from rcmarl_tpu.cli import main

        path = tmp_path / "checkpoint.npz"
        save_checkpoint(path, STATE, CFG)
        assert main([
            "evaluate", "--checkpoint", str(path), "--episodes", "2",
        ]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        row = json.loads(out[0])
        assert row["kind"] == "evaluate"
        assert row["episodes"] == CFG.n_ep_fixed  # rounded up to a block
        assert len(row["per_agent_returns"]) == CFG.n_agents
        assert np.isfinite(row["team_return_mean"])

    def test_evaluate_rejects_replica_checkpoint(self, tmp_path):
        from rcmarl_tpu.cli import main

        states = jax.vmap(lambda k: init_train_state(CFG, k))(
            jax.random.split(jax.random.PRNGKey(0), 2)
        )
        path = tmp_path / "replica.npz"
        save_checkpoint(
            path, states, CFG,
            meta={"replicas": 2, "gossip_round": 0, "excluded": [False] * 2},
        )
        with pytest.raises(SystemExit, match="replica"):
            main(["evaluate", "--checkpoint", str(path)])


class TestEvalBlock:
    def test_eval_block_shapes_and_finiteness(self):
        from rcmarl_tpu.serve.engine import eval_block

        metrics, agent_returns = eval_block(
            CFG, STATE.params, STATE.desired, KEY, STATE.initial
        )
        assert np.asarray(metrics.true_team_returns).shape == (CFG.n_ep_fixed,)
        assert np.asarray(agent_returns).shape == (CFG.n_agents,)
        assert np.isfinite(np.asarray(agent_returns)).all()
        # per-agent returns are consistent with the team metric: the
        # cooperative mean of per-agent discounted returns equals the
        # mean over episodes of true_team_returns (all-coop cast)
        np.testing.assert_allclose(
            np.asarray(agent_returns).mean(),
            np.asarray(metrics.true_team_returns).mean(),
            rtol=1e-5,
        )
