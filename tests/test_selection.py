"""Selection-based trim bounds vs the full sort: BITWISE equivalence.

The selection impls (``xla``, and the registers inside the Pallas
kernel) compute the same two order statistics the sort-based paths read
— ``sorted[H]`` and ``sorted[n_in-H-1]`` — by dual top-(H+1) running
min/max registers (``ops/aggregation.py:_running_extrema``). Both
strategies pick exact input values, so the contract pinned here is
bitwise equality (``==``, not allclose) of the full aggregation output
across every (H, n_in, masked, traced-H) combination the training paths
exercise. tests/test_selection_properties.py covers the same contract
over randomized hypothesis inputs; this module is the deterministic,
dependency-free matrix that always runs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rcmarl_tpu.ops.aggregation import (
    PALLAS_CROSSOVER_VOLUME,
    _running_extrema,
    resilient_aggregate,
    resilient_aggregate_tree,
    resolve_impl,
)

N_INS = [3, 5, 9, 64]
HS = [0, 1, 2]


def _vals(n_in, m=23, seed=0, ties=True):
    rng = np.random.default_rng(seed + 100 * n_in)
    v = jnp.asarray(rng.normal(size=(n_in, m)).astype(np.float32))
    if ties and n_in > 1:
        # duplicated entries stress tie-handling: selection and sort
        # must still pick identical representatives
        v = v.at[1].set(v[0])
    return v


class TestRunningExtrema:
    """The register helper itself: small == sorted[:k], large ==
    sorted[-k:], bitwise, for every k up to the legal maximum."""

    @pytest.mark.parametrize("n", [1, 2, 3, 5, 9, 16])
    def test_matches_sorted_prefix_suffix(self, n):
        vals = _vals(n, m=17)
        ref = np.sort(np.asarray(vals), axis=0)
        for k in range(1, n + 1):
            small, large = _running_extrema([vals[i] for i in range(n)], k)
            np.testing.assert_array_equal(
                np.stack([np.asarray(s) for s in small]), ref[:k]
            )
            np.testing.assert_array_equal(
                np.stack([np.asarray(l) for l in large]), ref[n - k:]
            )


@pytest.mark.parametrize("n_in", N_INS)
@pytest.mark.parametrize("H", HS)
class TestSelectMatchesSortBitwise:
    def test_static_h(self, n_in, H):
        if 2 * H > n_in - 1:
            pytest.skip("H invalid for this n_in")
        vals = _vals(n_in)
        a = resilient_aggregate(vals, H, impl="xla_sort")
        b = resilient_aggregate(vals, H, impl="xla")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_masked(self, n_in, H):
        if n_in < 4:
            pytest.skip("needs padding room")
        d = n_in - 2  # true degree; 2 padded slots
        if 2 * H > d - 1:
            pytest.skip("H invalid for the valid count")
        vals = _vals(n_in, seed=1)
        # non-finite garbage in the padded slots must not matter
        vals = vals.at[d:].set(jnp.nan)
        valid = jnp.asarray([1.0] * d + [0.0] * (n_in - d))
        a = resilient_aggregate(vals, H, impl="xla_sort", valid=valid)
        b = resilient_aggregate(vals, H, impl="xla", valid=valid)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # and both equal the unpadded prefix aggregation
        want = resilient_aggregate(_vals(n_in, seed=1)[:d], H, impl="xla_sort")
        np.testing.assert_allclose(
            np.asarray(b), np.asarray(want), rtol=1e-6, atol=1e-6
        )

    def test_traced_h(self, n_in, H):
        if 2 * H > n_in - 1:
            pytest.skip("H invalid for this n_in")
        # n_in=64 included: the tournament's k_max selection is ⌈log₂n⌉
        # merge levels of block ops, so the compile-time blowup that made
        # the PR-1 register chain skip large n (a 4096-op unroll) is gone
        vals = _vals(n_in, seed=2)
        want = resilient_aggregate(vals, H, impl="xla_sort")
        sel = jax.jit(
            lambda v, h: resilient_aggregate(v, h, impl="xla")
        )(vals, jnp.int32(H))
        srt = jax.jit(
            lambda v, h: resilient_aggregate(v, h, impl="xla_sort")
        )(vals, jnp.int32(H))
        np.testing.assert_array_equal(np.asarray(want), np.asarray(sel))
        np.testing.assert_array_equal(np.asarray(want), np.asarray(srt))


def test_traced_h_auto_large_n_stays_selection():
    """'auto' with a traced H keys on the STATIC worst-case trim
    k_max = (n_in-1)//2+1: with the tournament that selection compiles
    and wins even at n_in=64 (the register-chain era routed this to the
    sort), and the result matches the static sort path bitwise."""
    vals = _vals(64, seed=3)
    out = jax.jit(
        lambda v, h: resilient_aggregate(v, h, impl="auto")
    )(vals, jnp.int32(2))
    want = resilient_aggregate(vals, 2, impl="xla_sort")
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


def test_tree_select_matches_sort_bitwise():
    rng = np.random.default_rng(9)
    tree = {
        "W": jnp.asarray(rng.normal(size=(5, 3, 4)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(size=(5, 4)).astype(np.float32)),
    }
    a = resilient_aggregate_tree(tree, 2, impl="xla_sort")
    b = resilient_aggregate_tree(tree, 2, impl="xla")
    for k in tree:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_select_under_vmap_matches_sort():
    """The consensus layer's shape: vmapped over agents."""
    rng = np.random.default_rng(11)
    vals = jnp.asarray(rng.normal(size=(6, 5, 13)).astype(np.float32))
    a = jax.vmap(lambda v: resilient_aggregate(v, 2, impl="xla_sort"))(vals)
    b = jax.vmap(lambda v: resilient_aggregate(v, 2, impl="xla"))(vals)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


class TestThreeWayAutoPolicy:
    """resolve_impl's 3-way (H, n_in, volume) crossover policy."""

    def test_cpu_selection_vs_sort_by_measured_rule(self, monkeypatch):
        from rcmarl_tpu.ops import aggregation as agg

        monkeypatch.setattr(agg.jax, "default_backend", lambda: "cpu")
        # measured tournament epoch rows (PERF.md "sort vs select"):
        # selection wins at EVERY measured n_in, including the dense
        # n_in=64 shape the register chain lost — SELECT_MAX_N_IN=None
        # means no sort crossover exists on this host
        assert agg.SELECT_MAX_N_IN is None
        assert agg.resolve_impl("auto", 4, H=1) == "xla"
        assert agg.resolve_impl("auto", 16, H=7) == "xla"
        assert agg.resolve_impl("auto", 64, H=1) == "xla"
        assert agg.resolve_impl("auto", 64, H=31) == "xla"
        assert agg.resolve_impl("auto", 64) == "xla"
        # a future refit to a finite threshold re-introduces the sort arm
        monkeypatch.setattr(agg, "SELECT_MAX_N_IN", 16)
        assert agg.resolve_impl("auto", 16, H=1) == "xla"
        assert agg.resolve_impl("auto", 64, H=1) == "xla_sort"

    def test_tpu_volume_beats_xla_family(self, monkeypatch):
        from rcmarl_tpu.ops import aggregation as agg

        monkeypatch.setattr(agg.jax, "default_backend", lambda: "tpu")
        v = PALLAS_CROSSOVER_VOLUME
        assert agg.resolve_impl("auto", v, H=1) == "pallas"
        # below the volume crossover the CPU rule applies on TPU too
        assert agg.resolve_impl("auto", 5, H=1) == "xla"
        # f64 never routes to the f32-computing kernel, any volume
        assert (
            agg.resolve_impl("auto", 16, np.float64, n_agents=64, H=1)
            == "xla"
        )
        assert (
            agg.resolve_impl("auto", 64, np.float64, n_agents=64, H=5)
            == "xla"
        )

    def test_explicit_impls_stick(self):
        for impl in ("xla", "xla_sort", "pallas", "pallas_sort"):
            assert resolve_impl(impl, 64, H=5) == impl

    def test_masked_path_resolution_is_xla_only(self, monkeypatch):
        """Padded graphs never lower the Pallas kernel: 'auto' on the
        masked path applies the n_in crossover (never the TPU volume
        rule), pallas-family impls map to their XLA strategy twin, and
        every combination still aggregates correctly."""
        from rcmarl_tpu.ops import aggregation as agg

        assert agg._resolve_masked("auto", 5, 1) == "xla"
        assert agg._resolve_masked("auto", 64, 1) == "xla"
        assert agg._resolve_masked("pallas", 5, 1) == "xla"
        assert agg._resolve_masked("pallas_interpret", 5, 1) == "xla"
        assert agg._resolve_masked("pallas_sort", 5, 1) == "xla_sort"
        assert agg._resolve_masked("xla_sort", 5, 1) == "xla_sort"
        # behavioral: a volume that resolves to pallas unmasked must
        # still aggregate (XLA-only) on the masked path, identically
        monkeypatch.setattr(agg.jax, "default_backend", lambda: "tpu")
        vals = _vals(5, seed=7)
        valid = jnp.asarray([1.0, 1.0, 1.0, 1.0, 0.0])
        want = resilient_aggregate(vals, 1, impl="xla_sort", valid=valid)
        for impl in ("auto", "pallas", "pallas_sort"):
            got = resilient_aggregate(
                vals, 1, impl=impl, valid=valid, n_agents=1000
            )
            np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_unknown_impl_rejected(self):
        with pytest.raises(ValueError, match="unknown consensus impl"):
            resolve_impl("topk", 4, H=1)


# ~16s — tier-1 870s wall-budget shed; the primitive select-vs-sort
# pins above stay fast
@pytest.mark.slow
def test_end_to_end_block_select_vs_sort():
    """One full update block: consensus_impl='xla' (selection) must
    reproduce consensus_impl='xla_sort' exactly — the bounds are
    bitwise-equal, so the whole training trajectory is."""
    from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
    from rcmarl_tpu.training.trainer import init_train_state, train_block

    kw = dict(
        n_agents=4,
        agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.GREEDY,),
        in_nodes=circulant_in_nodes(4, 4),
        H=1,
        nrow=3,
        ncol=3,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=2,
        buffer_size=16,
        hidden=(8, 8),
        coop_fit_steps=2,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
        n_episodes=2,
    )
    cfg_sel = Config(**kw, consensus_impl="xla")
    cfg_srt = Config(**kw, consensus_impl="xla_sort")
    s0 = init_train_state(cfg_sel, jax.random.PRNGKey(0))
    s_sel, m_sel = train_block(cfg_sel, s0)
    s_srt, m_srt = train_block(cfg_srt, s0)
    for a, b in zip(jax.tree.leaves(s_sel.params), jax.tree.leaves(s_srt.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    np.testing.assert_allclose(
        np.asarray(m_sel.true_team_returns),
        np.asarray(m_srt.true_team_returns),
        atol=1e-6,
    )
