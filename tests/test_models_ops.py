"""Models / optimizer / loss / fit-emulation tests, including golden
numerical comparisons against TF/Keras (the reference's substrate)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rcmarl_tpu.models import (
    actor_probs,
    head,
    init_mlp,
    init_stacked_mlp,
    leaky_relu,
    mlp_forward,
    trunk,
    trunk_forward,
)
from rcmarl_tpu.ops import (
    adam_init,
    adam_update,
    fit_full_batch,
    fit_minibatch,
    sgd_update,
    valid_first_shuffle,
    weighted_mse,
    weighted_sparse_ce,
)

tf = pytest.importorskip("tensorflow")
keras = tf.keras


# ---------------------------------------------------------------- models


def test_init_shapes_and_glorot_bounds():
    p = init_mlp(jax.random.PRNGKey(0), 10, (20, 20), 5)
    shapes = [(w.shape, b.shape) for w, b in p]
    assert shapes == [((10, 20), (20,)), ((20, 20), (20,)), ((20, 5), (5,))]
    for (w, b), fan_in in zip(p, (10, 20, 20)):
        limit = np.sqrt(6.0 / (fan_in + w.shape[1]))
        assert np.abs(np.asarray(w)).max() <= limit
        assert (np.asarray(b) == 0).all()
    sp = init_stacked_mlp(jax.random.PRNGKey(1), 5, 10, (20, 20), 1)
    assert sp[0][0].shape == (5, 10, 20)
    # agents get different draws
    assert not np.allclose(np.asarray(sp[0][0][0]), np.asarray(sp[0][0][1]))


def _keras_model(in_shape, out_dim, softmax):
    return keras.Sequential(
        [
            keras.Input(shape=in_shape),
            keras.layers.Flatten(),
            keras.layers.Dense(20, activation=keras.layers.LeakyReLU(alpha=0.1)),
            keras.layers.Dense(20, activation=keras.layers.LeakyReLU(alpha=0.1)),
            keras.layers.Dense(out_dim, activation="softmax" if softmax else None),
        ]
    )


def test_forward_golden_vs_keras():
    rng = np.random.default_rng(0)
    p = init_mlp(jax.random.PRNGKey(2), 10, (20, 20), 5)
    x = rng.normal(size=(7, 5, 2)).astype(np.float32)

    model = _keras_model((5, 2), 5, softmax=True)
    model.set_weights([np.asarray(a) for wb in p for a in wb])
    ref = model(x).numpy()
    mine = np.asarray(actor_probs(p, jnp.asarray(x)))
    np.testing.assert_allclose(mine, ref, rtol=1e-5, atol=1e-6)

    critic = init_mlp(jax.random.PRNGKey(3), 10, (20, 20), 1)
    cmodel = _keras_model((5, 2), 1, softmax=False)
    cmodel.set_weights([np.asarray(a) for wb in critic for a in wb])
    np.testing.assert_allclose(
        np.asarray(mlp_forward(critic, jnp.asarray(x))),
        cmodel(x).numpy(),
        rtol=1e-5,
        atol=1e-6,
    )
    # trunk_forward matches the keras sub-model cut at layers[-2].output
    features = keras.Model(cmodel.inputs, cmodel.layers[-2].output)
    np.testing.assert_allclose(
        np.asarray(trunk_forward(critic, jnp.asarray(x))),
        features(x).numpy(),
        rtol=1e-5,
        atol=1e-6,
    )


def test_trunk_head_split():
    p = init_mlp(jax.random.PRNGKey(4), 10, (20, 20), 1)
    assert len(trunk(p)) == 2 and head(p)[0].shape == (20, 1)


def test_leaky_relu_alpha():
    x = jnp.array([-2.0, 3.0])
    np.testing.assert_allclose(np.asarray(leaky_relu(x, 0.1)), [-0.2, 3.0])


# ------------------------------------------------------------- optimizers


def test_adam_golden_vs_tf():
    rng = np.random.default_rng(1)
    w0 = rng.normal(size=(4, 3)).astype(np.float32)
    grads = [rng.normal(size=(4, 3)).astype(np.float32) for _ in range(7)]

    var = tf.Variable(w0)
    opt = keras.optimizers.Adam(learning_rate=0.01)
    for g in grads:
        opt.apply_gradients([(tf.constant(g), var)])
    ref = var.numpy()

    p = {"w": jnp.asarray(w0)}
    state = adam_init(p)
    for g in grads:
        p, state = adam_update(p, {"w": jnp.asarray(g)}, state, lr=0.01)
    # float32 accumulation-order differences over 7 steps: atol 1e-5
    np.testing.assert_allclose(np.asarray(p["w"]), ref, rtol=1e-3, atol=1e-5)


def test_sgd_update():
    p = {"w": jnp.ones((2,))}
    out = sgd_update(p, {"w": jnp.array([1.0, 2.0])}, lr=0.1)
    np.testing.assert_allclose(np.asarray(out["w"]), [0.9, 0.8])


# ------------------------------------------------------------------ losses


def test_mse_golden_vs_keras_with_sample_weight():
    rng = np.random.default_rng(2)
    pred = rng.normal(size=(9, 1)).astype(np.float32)
    target = rng.normal(size=(9, 1)).astype(np.float32)
    w = rng.uniform(0.1, 2.0, size=(9,)).astype(np.float32)
    ref = float(keras.losses.MeanSquaredError()(target, pred, sample_weight=w))
    mine = float(weighted_mse(jnp.asarray(pred), jnp.asarray(target), jnp.asarray(w)))
    np.testing.assert_allclose(mine, ref, rtol=1e-5)


def test_sparse_ce_golden_vs_keras_with_sample_weight():
    rng = np.random.default_rng(3)
    logits = rng.normal(size=(11, 5)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    labels = rng.integers(0, 5, size=(11,))
    w = rng.normal(size=(11,)).astype(np.float32)  # TD errors can be negative
    ref = float(
        keras.losses.SparseCategoricalCrossentropy()(labels, probs, sample_weight=w)
    )
    mine = float(
        weighted_sparse_ce(jnp.asarray(probs), jnp.asarray(labels), jnp.asarray(w))
    )
    np.testing.assert_allclose(mine, ref, rtol=1e-4, atol=1e-6)


def test_masked_loss_equals_dense_subset():
    rng = np.random.default_rng(4)
    pred = rng.normal(size=(8, 1)).astype(np.float32)
    target = rng.normal(size=(8, 1)).astype(np.float32)
    mask = jnp.asarray([1, 1, 1, 1, 1, 0, 0, 0], jnp.float32)
    # garbage in masked rows must not leak
    pred_poisoned = pred.copy()
    pred_poisoned[5:] = np.nan
    dense = float(weighted_mse(jnp.asarray(pred[:5]), jnp.asarray(target[:5])))
    masked = float(
        weighted_mse(jnp.asarray(pred_poisoned), jnp.asarray(target), mask=mask)
    )
    np.testing.assert_allclose(masked, dense, rtol=1e-6)


# --------------------------------------------------------------- fit utils


def test_valid_first_shuffle_plan():
    mask = jnp.asarray([1] * 10 + [0] * 6, jnp.float32)  # capacity 16
    idx, bvalid = valid_first_shuffle(jax.random.PRNGKey(0), mask, 4, 5)
    assert idx.shape == (4, 5) and bvalid.shape == (4, 5)
    flat_idx, flat_val = np.asarray(idx).ravel(), np.asarray(bvalid).ravel()
    # the 10 valid rows appear exactly once each, in the first 10 slots
    assert sorted(flat_idx[flat_val == 1]) == list(range(10))
    # Keras batch structure: two full batches of 5, then ceil: batch 2 has
    # 0 valid? 10 valid / bs 5 -> batches 0,1 full, batches 2,3 empty
    np.testing.assert_array_equal(np.asarray(bvalid).sum(axis=1), [5, 5, 0, 0])


def test_fit_full_batch_matches_manual_sgd():
    rng = np.random.default_rng(5)
    X = jnp.asarray(rng.normal(size=(6, 3)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(6, 1)).astype(np.float32))
    p0 = {"w": jnp.zeros((3, 1))}

    def loss(p):
        return weighted_mse(X @ p["w"], y)

    p1, first_loss = fit_full_batch(p0, loss, n_steps=2, lr=0.1)
    # manual
    g0 = jax.grad(loss)(p0)
    m1 = {"w": p0["w"] - 0.1 * g0["w"]}
    g1 = jax.grad(loss)(m1)
    m2 = {"w": m1["w"] - 0.1 * g1["w"]}
    np.testing.assert_allclose(np.asarray(p1["w"]), np.asarray(m2["w"]), rtol=1e-6)
    np.testing.assert_allclose(float(first_loss), float(loss(p0)), rtol=1e-6)


def test_fit_minibatch_golden_vs_keras_fit():
    """Full golden comparison against keras model.fit with shuffle=False
    equivalent: we use batch_size=capacity so shuffling is irrelevant,
    multiple epochs of full-batch SGD on a linear model."""
    rng = np.random.default_rng(6)
    X = rng.normal(size=(12, 4)).astype(np.float32)
    y = rng.normal(size=(12, 1)).astype(np.float32)
    w0 = rng.normal(size=(4, 1)).astype(np.float32)

    model = keras.Sequential(
        [keras.Input(shape=(4,)), keras.layers.Dense(1, use_bias=False)]
    )
    model.set_weights([w0])
    model.compile(
        optimizer=keras.optimizers.SGD(learning_rate=0.05),
        loss=keras.losses.MeanSquaredError(),
    )
    model.fit(X, y, batch_size=12, epochs=4, verbose=0, shuffle=False)
    ref = model.get_weights()[0]

    Xj, yj = jnp.asarray(X), jnp.asarray(y)
    mask = jnp.ones((12,), jnp.float32)

    def batch_loss(p, idx, bval):
        return weighted_mse(Xj[idx] @ p["w"], yj[idx], mask=bval)

    p, _, _ = fit_minibatch(
        jax.random.PRNGKey(0),
        {"w": jnp.asarray(w0)},
        batch_loss,
        capacity=12,
        mask=mask,
        epochs=4,
        batch_size=12,
        lr=0.05,
    )
    np.testing.assert_allclose(np.asarray(p["w"]), ref, rtol=1e-4, atol=1e-6)


def test_fit_minibatch_partial_batch_and_padding():
    """9 valid rows in a capacity-16 buffer, batch 4: Keras would run
    batches [4,4,1]; verify our masked version gives identical results to
    a dense 9-row run when the permutation is forced to identity."""
    rng = np.random.default_rng(7)
    X = np.zeros((16, 3), np.float32)
    y = np.zeros((16, 1), np.float32)
    X[:9] = rng.normal(size=(9, 3))
    y[:9] = rng.normal(size=(9, 1))
    # poison with huge-but-finite garbage: masked rows may hold stale
    # buffer contents (always finite), and must contribute exactly zero
    X[9:] = 1e30
    y[9:] = -1e30
    mask = jnp.asarray([1.0] * 9 + [0.0] * 7)
    Xj, yj = jnp.asarray(X), jnp.asarray(y)

    def batch_loss(p, idx, bval):
        return weighted_mse(Xj[idx] @ p["w"], yj[idx], mask=bval)

    import rcmarl_tpu.ops.fit as fit_mod

    orig = fit_mod.valid_first_shuffle

    def identity_shuffle(key, m, nb, bs):
        idx = jnp.arange(nb * bs, dtype=jnp.int32) % m.shape[0]
        bval = (jnp.arange(nb * bs) < jnp.sum(m)).astype(jnp.float32)
        return idx.reshape(nb, bs), bval.reshape(nb, bs)

    fit_mod.valid_first_shuffle = identity_shuffle
    try:
        p, _, _ = fit_mod.fit_minibatch(
            jax.random.PRNGKey(0),
            {"w": jnp.zeros((3, 1))},
            batch_loss,
            capacity=16,
            mask=mask,
            epochs=2,
            batch_size=4,
            lr=0.05,
        )
    finally:
        fit_mod.valid_first_shuffle = orig

    # dense manual: batches [0:4],[4:8],[8:9] twice
    w = jnp.zeros((3, 1))
    Xd, yd = jnp.asarray(X[:9]), jnp.asarray(y[:9])
    for _ in range(2):
        for lo, hi in ((0, 4), (4, 8), (8, 9)):
            g = jax.grad(lambda w: weighted_mse(Xd[lo:hi] @ w, yd[lo:hi]))(w)
            w = w - 0.05 * g
    assert np.isfinite(np.asarray(p["w"])).all()
    np.testing.assert_allclose(np.asarray(p["w"]), np.asarray(w), rtol=1e-5)


def test_fit_minibatch_with_adam_state_advances_once_per_real_batch():
    rng = np.random.default_rng(8)
    X = jnp.asarray(rng.normal(size=(8, 2)).astype(np.float32))
    y = jnp.asarray(rng.normal(size=(8, 1)).astype(np.float32))
    mask = jnp.asarray([1.0] * 8)
    p0 = {"w": jnp.zeros((2, 1))}

    def batch_loss(p, idx, bval):
        return weighted_mse(X[idx] @ p["w"], y[idx], mask=bval)

    state = adam_init(p0)
    p, state, _ = fit_minibatch(
        jax.random.PRNGKey(1),
        p0,
        batch_loss,
        capacity=8,
        mask=mask,
        epochs=3,
        batch_size=4,
        opt_state=state,
        opt_update=lambda p, g, s: adam_update(p, g, s, lr=0.01),
    )
    assert int(state.count) == 6  # 2 batches x 3 epochs


class TestBF16Compute:
    """compute_dtype='bfloat16': MXU-native matmul inputs, f32 accumulation
    (models/mlp.py:dot). Opt-in only — the f32 default stays golden-pinned
    by the tests above."""

    def test_dot_bf16_output_is_f32_and_close(self):
        from rcmarl_tpu.models.mlp import dot

        rng = np.random.default_rng(0)
        a = jnp.asarray(rng.normal(size=(16, 32)).astype(np.float32))
        b = jnp.asarray(rng.normal(size=(32, 8)).astype(np.float32))
        out = dot(a, b, "bfloat16")
        assert out.dtype == jnp.float32  # accumulation/output stays f32
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(dot(a, b)), rtol=2e-2, atol=2e-2
        )

    def test_forward_bf16_close_to_f32(self):
        from rcmarl_tpu.models.mlp import init_mlp, mlp_forward

        params = init_mlp(jax.random.PRNGKey(0), 10, (20, 20), 1)
        x = jnp.asarray(
            np.random.default_rng(1).normal(size=(32, 10)).astype(np.float32)
        )
        f32 = np.asarray(mlp_forward(params, x))
        bf16 = np.asarray(mlp_forward(params, x, dtype="bfloat16"))
        assert bf16.dtype == np.float32
        np.testing.assert_allclose(bf16, f32, rtol=5e-2, atol=5e-2)

    def test_config_rejects_unknown_dtype(self):
        from rcmarl_tpu.config import Config

        with pytest.raises(ValueError, match="compute_dtype"):
            Config(compute_dtype="float16")

    # ~17s — tier-1 870s wall-budget shed; the bf16 kernel/dtype pins
    # above stay fast
    @pytest.mark.slow
    def test_bf16_trains_end_to_end(self):
        from rcmarl_tpu.config import Config
        from rcmarl_tpu.training.trainer import init_train_state, train_block

        cfg = Config(
            n_agents=3,
            agent_roles=(0, 1, 3),  # include adversary branches
            in_nodes=((0, 1, 2), (1, 2, 0), (2, 0, 1)),
            n_episodes=2,
            max_ep_len=4,
            n_ep_fixed=2,
            n_epochs=1,
            buffer_size=16,
            batch_size=4,
            H=1,
            compute_dtype="bfloat16",
        )
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        state, metrics = train_block(cfg, state)
        # model weights stay f32 end-to-end (opt state holds an int count)
        for tree in (
            state.params.actor,
            state.params.critic,
            state.params.tr,
            state.params.critic_local,
        ):
            assert all(l.dtype == jnp.float32 for l in jax.tree.leaves(tree))
        assert np.isfinite(np.asarray(metrics.true_team_returns)).all()
