"""Tournament selection + flattened one-launch layout: BITWISE contracts.

Two tentpole mechanisms are pinned here:

1. **Log-depth tournament selection** (``ops/aggregation.py:_k_smallest``
   / ``_k_largest``): chunk the stacked neighbor axis, bitonic-sort
   within chunks, pairwise-merge sorted k-prefixes/suffixes up a binary
   tree — whole-block min/max only, no unstacked row slices. Selection
   returns exact input values, so every aggregate it feeds must equal
   the ``xla_sort`` arm bitwise across (n_in, H, masked, sanitize,
   traced-H) — including odd / non-power-of-two n_in (the tournament
   pads with ±inf sentinels) and inputs that already carry ±inf
   sentinels (sanitize sinks, masked slots), where a pad and a real
   sentinel share one bit pattern.

2. **Flattened one-launch tree layout**
   (``resilient_aggregate_tree(layout='flat')``): every leaf raveled
   into one (n_in, P_total) block. Raveling is elementwise-neutral, so
   the flat path must match the historical per-leaf path LEAF-FOR-LEAF,
   in every mode.

tests/test_selection.py keeps the register-chain-era deterministic
matrix (the helpers still back the Pallas kernel); this module is the
tournament-specific coverage, with hypothesis twins at the bottom.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rcmarl_tpu.ops.aggregation import (
    _k_largest,
    _k_smallest,
    ravel_neighbor_tree,
    resilient_aggregate,
    resilient_aggregate_tree,
)

# deliberately odd / non-power-of-two heavy: the tournament's chunk
# padding and odd-node-count carry paths must all be exercised. The
# primitive test sweeps the full list; the aggregate-mode matrix runs a
# trimmed grid (each extra cell is 2+ jit compiles) that still covers
# odd, even-non-pow2, pow2, and the dense-64 shape.
N_INS = [2, 3, 5, 6, 7, 9, 12, 13, 16, 17, 33, 64]
N_INS_MODES = [3, 5, 6, 9, 12, 64]
HS = [0, 1, 3]


def _vals(n_in, m=19, seed=0, ties=False, infs=False):
    rng = np.random.default_rng(seed + 1000 * n_in)
    v = rng.normal(size=(n_in, m)).astype(np.float32)
    if ties and n_in > 2:
        v[1] = v[0]
        v[n_in // 2] = v[0]
    if infs:
        v = np.where(rng.random(v.shape) < 0.3, np.inf, v)
        v = np.where(rng.random(v.shape) < 0.15, -np.inf, v)
        v = v.astype(np.float32)
    return jnp.asarray(v)


class TestTournamentPrimitive:
    """_k_smallest / _k_largest == the sort prefix/suffix, bitwise, for
    every k up to n — the raw selection contract everything else rides."""

    @pytest.mark.parametrize(
        "n",
        # tier-1 870s wall-budget shed: the two priciest sizes (~6-7s
        # each, every-k sweeps) ride the slow marker; the remaining ten
        # sizes keep the ties/±inf/pad contract fast
        [n if n not in (17, 33) else pytest.param(n, marks=pytest.mark.slow)
         for n in N_INS],
    )
    def test_matches_sort_prefix_suffix(self, n):
        # ties + ±inf payloads in one input: both tie-handling and the
        # sentinel/pad interplay are always exercised
        for variant in ({"ties": True}, {"infs": True}):
            vals = _vals(n, seed=1, **variant)
            ref = np.sort(np.asarray(vals), axis=0)
            ks = sorted({1, 2, (n - 1) // 2 + 1, n})
            for k in ks:
                if k > n:
                    continue
                np.testing.assert_array_equal(
                    np.asarray(_k_smallest(vals, k)), ref[:k]
                )
                np.testing.assert_array_equal(
                    np.asarray(_k_largest(vals, k)), ref[n - k :]
                )

    def test_under_vmap_and_jit(self):
        vals = _vals(7, seed=2)
        batched = jnp.stack([vals + i for i in range(5)])  # (5, 7, m)
        out = jax.jit(jax.vmap(lambda v: _k_smallest(v, 3)))(batched)
        ref = np.sort(np.asarray(batched), axis=1)[:, :3]
        np.testing.assert_array_equal(np.asarray(out), ref)


@pytest.mark.parametrize("n_in", N_INS_MODES)
@pytest.mark.parametrize("H", HS)
class TestTournamentAggregateMatchesSort:
    """Full aggregation, tournament ('xla') vs full sort ('xla_sort'),
    across the mode matrix."""

    def _skip_invalid(self, n_in, H):
        if 2 * H > n_in - 1:
            pytest.skip("H invalid for this n_in")

    def test_static_h(self, n_in, H):
        self._skip_invalid(n_in, H)
        vals = _vals(n_in, ties=True)
        a = resilient_aggregate(vals, H, impl="xla_sort")
        b = resilient_aggregate(vals, H, impl="xla")
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_sanitize_with_nonfinite_payloads(self, n_in, H):
        """±inf/NaN bombs + the sanitize sinks: tournament pads (±inf)
        meet real ±inf sentinels and the aggregate must still be
        bitwise-equal to the sort arm."""
        self._skip_invalid(n_in, H)
        vals = np.asarray(_vals(n_in, seed=3, infs=True))
        rng = np.random.default_rng(7 + n_in)
        vals = np.where(rng.random(vals.shape) < 0.1, np.nan, vals).astype(
            np.float32
        )
        vals = jnp.asarray(vals)
        a = resilient_aggregate(vals, H, impl="xla_sort", sanitize=True)
        b = resilient_aggregate(vals, H, impl="xla", sanitize=True)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_masked(self, n_in, H):
        if n_in < 4:
            pytest.skip("needs padding room")
        d = n_in - 2
        if 2 * H > d - 1:
            pytest.skip("H invalid for the valid count")
        vals = _vals(n_in, seed=4)
        vals = vals.at[d:].set(jnp.nan)  # garbage in padded slots
        valid = jnp.asarray([1.0] * d + [0.0] * (n_in - d))
        a = resilient_aggregate(vals, H, impl="xla_sort", valid=valid)
        b = resilient_aggregate(vals, H, impl="xla", valid=valid)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_traced_h(self, n_in, H):
        self._skip_invalid(n_in, H)
        vals = _vals(n_in, seed=5, ties=True)
        want = resilient_aggregate(vals, H, impl="xla_sort")
        got = jax.jit(lambda v, h: resilient_aggregate(v, h, impl="xla"))(
            vals, jnp.int32(H)
        )
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    def test_traced_h_sanitized(self, n_in, H):
        self._skip_invalid(n_in, H)
        vals = _vals(n_in, seed=6, infs=True)
        want = jax.jit(
            lambda v, h: resilient_aggregate(
                v, h, impl="xla_sort", sanitize=True
            )
        )(vals, jnp.int32(H))
        got = jax.jit(
            lambda v, h: resilient_aggregate(v, h, impl="xla", sanitize=True)
        )(vals, jnp.int32(H))
        np.testing.assert_array_equal(np.asarray(want), np.asarray(got))


# --------------------------------------------------------------------------
# Flattened one-launch layout
# --------------------------------------------------------------------------


def _tree(n_in, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "W1": jnp.asarray(rng.normal(size=(n_in, 4, 6)).astype(np.float32)),
        "b1": jnp.asarray(rng.normal(size=(n_in, 6)).astype(np.float32)),
        "W2": jnp.asarray(rng.normal(size=(n_in, 6, 3)).astype(np.float32)),
        "b2": jnp.asarray(rng.normal(size=(n_in, 3)).astype(np.float32)),
    }


def test_ravel_neighbor_tree_roundtrip():
    tree = _tree(5)
    flat, unravel = ravel_neighbor_tree(tree)
    assert flat.shape == (5, 4 * 6 + 6 + 6 * 3 + 3)
    back = unravel(flat[0])
    for k in tree:
        np.testing.assert_array_equal(
            np.asarray(back[k]), np.asarray(tree[k][0])
        )


def test_ravel_rejects_mismatched_neighbor_dim():
    tree = {"a": jnp.zeros((4, 2)), "b": jnp.zeros((5, 2))}
    with pytest.raises(ValueError, match="leading neighbor dim"):
        ravel_neighbor_tree(tree)


class TestFlatLayoutMatchesPerLeaf:
    """layout='flat' vs layout='per_leaf', leaf for leaf, bitwise, in
    every mode — the regression pin for the one-launch restructuring."""

    def _check(self, n_in=5, H=2, **kw):
        tree = _tree(n_in, seed=n_in)
        for impl in ("xla", "xla_sort"):
            a = resilient_aggregate_tree(
                tree, H, impl=impl, layout="flat", **kw
            )
            b = resilient_aggregate_tree(
                tree, H, impl=impl, layout="per_leaf", **kw
            )
            for k in tree:
                np.testing.assert_array_equal(
                    np.asarray(a[k]), np.asarray(b[k])
                )

    # ~9s each — tier-1 870s wall-budget shed; the slow end-to-end
    # flat-layout block pin below already covers both paths
    @pytest.mark.slow
    def test_static_h(self):
        self._check()

    def test_h0_short_circuit(self):
        self._check(H=0)

    def test_sanitize(self):
        self._check(sanitize=True)

    def test_masked(self):
        self._check(H=1, valid=jnp.asarray([1.0, 1.0, 1.0, 0.0, 0.0]))

    def test_masked_sanitize(self):
        self._check(
            H=1,
            valid=jnp.asarray([1.0, 1.0, 1.0, 0.0, 0.0]),
            sanitize=True,
        )

    def test_traced_h(self):
        tree = _tree(7, seed=7)
        a = jax.jit(
            lambda t, h: resilient_aggregate_tree(t, h, layout="flat")
        )(tree, jnp.int32(2))
        b = jax.jit(
            lambda t, h: resilient_aggregate_tree(t, h, layout="per_leaf")
        )(tree, jnp.int32(2))
        for k in tree:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))

    @pytest.mark.slow
    def test_under_agent_vmap(self):
        """The consensus layer's actual shape: (N, n_in, ...) leaves,
        vmapped over agents."""
        base = _tree(5, seed=11)
        stacked = jax.tree.map(
            lambda l: jnp.stack([l * (i + 1) for i in range(4)]), base
        )  # (4, 5, ...) leaves
        a = jax.vmap(
            lambda t: resilient_aggregate_tree(t, 1, layout="flat")
        )(stacked)
        b = jax.vmap(
            lambda t: resilient_aggregate_tree(t, 1, layout="per_leaf")
        )(stacked)
        for k in base:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))

    def test_mixed_dtype_falls_back_to_per_leaf(self):
        tree = {
            "a": jnp.asarray(
                np.random.default_rng(0).normal(size=(5, 3)), jnp.float32
            ),
            "b": jnp.ones((5, 2), jnp.bfloat16),
        }
        out = resilient_aggregate_tree(tree, 1, layout="flat")
        assert out["a"].dtype == jnp.float32
        assert out["b"].dtype == jnp.bfloat16

    def test_unknown_layout_rejected(self):
        with pytest.raises(ValueError, match="unknown layout"):
            resilient_aggregate_tree(_tree(5), 1, layout="stacked")


# ~19s — tier-1 870s wall-budget shed; the per-primitive flat-layout
# pins above stay fast
@pytest.mark.slow
def test_flat_layout_end_to_end_block_matches_per_leaf():
    """One full training block under consensus_layout='flat' must
    reproduce 'per_leaf' bit-for-bit (raveling is elementwise-neutral,
    so the whole trajectory is identical). The layout knob only exists
    on the dual-launch arm, so both configs pin netstack=False (the
    netstack-vs-dual pin is tests/test_netstack.py)."""
    from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
    from rcmarl_tpu.training.trainer import init_train_state, train_block

    kw = dict(
        n_agents=4,
        agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.GREEDY,),
        in_nodes=circulant_in_nodes(4, 4),
        H=1,
        nrow=3,
        ncol=3,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=2,
        buffer_size=16,
        hidden=(8, 8),
        coop_fit_steps=2,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
        n_episodes=2,
    )
    cfg_flat = Config(**kw, consensus_layout="flat", netstack=False)
    cfg_leaf = Config(**kw, consensus_layout="per_leaf", netstack=False)
    s0 = init_train_state(cfg_flat, jax.random.PRNGKey(0))
    s_flat, m_flat = train_block(cfg_flat, s0)
    s_leaf, m_leaf = train_block(cfg_leaf, s0)
    for a, b in zip(
        jax.tree.leaves(s_flat.params), jax.tree.leaves(s_leaf.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        np.asarray(m_flat.true_team_returns),
        np.asarray(m_leaf.true_team_returns),
    )


# Hypothesis twins live in tests/test_tournament_properties.py, guarded
# by importorskip — this module is the deterministic matrix that always
# runs (same split as test_selection.py / test_selection_properties.py).
