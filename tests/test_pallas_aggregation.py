"""Pallas consensus kernel vs the XLA reference implementation.

Runs the fused kernel in interpreter mode (CPU test platform; the real
lowering is exercised on TPU via ``Config.consensus_impl='pallas'``).
Equivalence to :func:`rcmarl_tpu.ops.aggregation.resilient_aggregate`
is the whole correctness contract: the XLA path is itself pinned to the
reference's ``_resilient_aggregation`` by tests/test_aggregation.py.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rcmarl_tpu.ops.aggregation import (
    resilient_aggregate,
    resilient_aggregate_tree,
)
from rcmarl_tpu.ops.pallas_aggregation import (
    fused_resilient_aggregate,
    fused_resilient_aggregate_tree,
)


@pytest.mark.parametrize("variant", ["select", "sort"])
@pytest.mark.parametrize("n_in", [3, 4, 5, 8])
@pytest.mark.parametrize("H", [0, 1])
@pytest.mark.parametrize(
    "shape", [(7,), (10, 20), (33, 5, 2), (3000, 1)]
)
def test_matches_xla_reference(variant, n_in, H, shape):
    if 2 * H > n_in - 1:
        pytest.skip("H invalid for this n_in")
    vals = jax.random.normal(jax.random.PRNGKey(n_in * 10 + H), (n_in, *shape))
    want = resilient_aggregate(vals, H)
    got = fused_resilient_aggregate(vals, H, variant=variant, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


@pytest.mark.parametrize("n_in,H", [(4, 1), (5, 2), (8, 3)])
def test_select_kernel_bitwise_vs_sort_kernel(n_in, H):
    """The two kernel variants pick identical order statistics, so their
    outputs agree BITWISE (both compute in f32), including under ties."""
    vals = jax.random.normal(jax.random.PRNGKey(3 * n_in + H), (n_in, 200))
    vals = vals.at[1].set(vals[0])  # tie stress
    a = fused_resilient_aggregate(vals, H, variant="sort", interpret=True)
    b = fused_resilient_aggregate(vals, H, variant="select", interpret=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_select_kernel_vs_xla_select_path():
    """Selection kernel pinned against the XLA selection path. The trim
    bounds are bitwise-identical (same registers); only the mean
    epilogue differs (the kernel's sequential accumulate * 1/n vs XLA's
    reduce + divide), hence the f32-rounding tolerance — the same
    contract the sort kernel has always had against the XLA sort."""
    vals = jax.random.normal(jax.random.PRNGKey(21), (5, 77, 3))
    want = resilient_aggregate(vals, 2, impl="xla")
    got = fused_resilient_aggregate(vals, 2, variant="select", interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_unknown_variant_rejected():
    vals = jnp.zeros((4, 8))
    with pytest.raises(ValueError, match="unknown kernel variant"):
        fused_resilient_aggregate(vals, 1, variant="topk", interpret=True)


def test_multi_tile_grid():
    """Payload larger than one block (block_rows*128) exercises the
    BlockSpec index_map across several grid steps — the path taken at
    the kernel's target scale (N=64 agents, 256-wide trunks)."""
    vals = jax.random.normal(jax.random.PRNGKey(11), (5, 300, 41))  # 12300 el
    want = resilient_aggregate(vals, 2)
    got = fused_resilient_aggregate(vals, 2, block_rows=8, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_h2_wide_neighborhood():
    vals = jax.random.normal(jax.random.PRNGKey(0), (7, 129))  # pad path
    want = resilient_aggregate(vals, 2)
    got = fused_resilient_aggregate(vals, 2, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_adversary_bound_property():
    """Output stays within [min, max] of cooperative inputs when at most H
    neighbors are adversarial (the Byzantine-resilience contract)."""
    key = jax.random.PRNGKey(7)
    coop = jax.random.normal(key, (4, 256))
    adv = jnp.full((1, 256), 1e6)  # one outlier transmitter
    vals = jnp.concatenate([coop, adv], axis=0)  # own (idx 0) cooperative
    out = fused_resilient_aggregate(vals, 1, interpret=True)
    assert bool(jnp.all(out <= coop.max(axis=0) + 1e-5))
    assert bool(jnp.all(out >= coop.min(axis=0) - 1e-5))


def test_tree_single_launch_matches_per_leaf():
    key = jax.random.PRNGKey(3)
    ks = jax.random.split(key, 4)
    n_in = 5
    tree = (
        (jax.random.normal(ks[0], (n_in, 10, 20)), jax.random.normal(ks[1], (n_in, 20))),
        (jax.random.normal(ks[2], (n_in, 20, 20)), jax.random.normal(ks[3], (n_in, 20))),
    )
    want = resilient_aggregate_tree(tree, 1)
    got = fused_resilient_aggregate_tree(tree, 1, interpret=True)
    for w, g in zip(jax.tree.leaves(want), jax.tree.leaves(got)):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w), atol=1e-6)


def test_vmap_over_agents():
    """The consensus layer vmaps aggregation over the agent axis."""
    vals = jax.random.normal(jax.random.PRNGKey(9), (6, 4, 50))  # (N, n_in, M)
    want = jax.vmap(lambda v: resilient_aggregate(v, 1))(vals)
    got = jax.vmap(
        lambda v: fused_resilient_aggregate(v, 1, interpret=True)
    )(vals)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_invalid_h_rejected():
    vals = jnp.zeros((3, 8))
    with pytest.raises(ValueError, match="H=2"):
        fused_resilient_aggregate(vals, 2, interpret=True)


@pytest.mark.slow
def test_training_block_with_pallas_consensus():
    """End-to-end: one update block with consensus_impl='pallas_interpret'
    produces the same trajectory as the XLA implementation."""
    from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
    from rcmarl_tpu.training.trainer import init_train_state, train_block

    kw = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE, Roles.COOPERATIVE, Roles.GREEDY),
        in_nodes=circulant_in_nodes(3, 3),
        H=1,
        nrow=3,
        ncol=3,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=1,
        buffer_size=16,
        hidden=(8, 8),
        coop_fit_steps=1,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
        n_episodes=2,
    )
    cfg_x = Config(**kw)
    cfg_p = Config(**kw, consensus_impl="pallas_interpret")
    s0 = init_train_state(cfg_x, jax.random.PRNGKey(0))
    sx, mx = train_block(cfg_x, s0)
    sp, mp = train_block(cfg_p, s0)
    for a, b in zip(jax.tree.leaves(sx.params), jax.tree.leaves(sp.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(mx.true_team_returns), np.asarray(mp.true_team_returns), atol=1e-5
    )
