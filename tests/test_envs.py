"""Env-zoo tests: the protocol/registry, per-env invariant suites, the
adaptive colluding adversary's payload, and the graph-as-data gather.

The expensive cross-env train cells ride the slow marker (the PR-8/PR-9
tier-1 budget pattern); the ci_tier1.sh env-zoo smoke cell trains every
new env through the real CLI on every run.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from rcmarl_tpu.config import (
    ENV_NAMES,
    Config,
    Roles,
    circulant_in_nodes,
    scheduled_in_nodes,
)
from rcmarl_tpu.envs import (
    ENV_REGISTRY,
    CongestionWorld,
    CoverageWorld,
    GridWorld,
    PursuitWorld,
    env_obs,
    env_reset,
    env_reward_scaled,
    env_task,
    env_transition,
    make_env,
)

ALL_ENVS = list(ENV_NAMES)
NEW_ENVS = [n for n in ALL_ENVS if n != "grid_world"]


def _cfg(env_name, n_agents=5, **kw):
    """Config helper: keeps roles/topology consistent with n_agents."""
    base = dict(
        env=env_name,
        n_agents=n_agents,
        agent_roles=(Roles.COOPERATIVE,) * n_agents,
        in_nodes=circulant_in_nodes(n_agents, min(n_agents, 4)),
    )
    base.update(kw)
    return Config(**base)


# ---------------------------------------------------------------------------
# registry / protocol
# ---------------------------------------------------------------------------


def test_registry_keys_pinned_to_config():
    """The registry and the jax-free ENV_NAMES tuple may never drift."""
    assert tuple(ENV_REGISTRY) == ENV_NAMES


def test_grid_only_knobs_rejected_on_other_envs():
    """collision_physics/reference_clip are grid_world semantics;
    silently ignoring them on another env would lie to the user."""
    with pytest.raises(ValueError, match="grid_world-only"):
        Config(env="pursuit", collision_physics=True)
    with pytest.raises(ValueError, match="grid_world-only"):
        Config(env="coverage", reference_clip=True)
    Config(env="congestion")  # defaults stay legal


def test_make_env_dispatch_types():
    types = {
        "grid_world": GridWorld,
        "pursuit": PursuitWorld,
        "coverage": CoverageWorld,
        "congestion": CongestionWorld,
    }
    for name, t in types.items():
        env = make_env(Config(env=name, nrow=4, ncol=4))
        assert isinstance(env, t)
        assert env.nrow == 4 and env.n_agents == 5


def test_default_env_is_the_pinned_grid_world():
    """Config.env='grid_world' (the default) builds EXACTLY the world
    the trainer always built — the bitwise env pin's static half (the
    dynamic half is the golden-trajectory suite, which runs the same
    compiled rollout this world keys)."""
    cfg = Config()
    assert cfg.env == "grid_world"
    assert make_env(cfg) == GridWorld(
        nrow=cfg.nrow,
        ncol=cfg.ncol,
        n_agents=cfg.n_agents,
        scaling=cfg.scaling,
        collision_physics=cfg.collision_physics,
        reference_clip=cfg.reference_clip,
    )


@pytest.mark.parametrize("name", ALL_ENVS)
def test_protocol_shapes_and_dtypes(name):
    cfg = _cfg(name, n_agents=4)
    env = make_env(cfg)
    pos = env_reset(env, jax.random.PRNGKey(0))
    task = env_task(env, jax.random.PRNGKey(1))
    assert pos.shape == (4, 2) and pos.dtype == jnp.int32
    assert task.shape == (4, 2) and task.dtype == jnp.int32
    a = jnp.array([0, 1, 2, 4], jnp.int32)
    npos, ntask, r = env_transition(env, pos, task, a)
    assert npos.shape == (4, 2) and npos.dtype == jnp.int32
    assert ntask.shape == (4, 2) and ntask.dtype == jnp.int32
    assert r.shape == (4,)
    # positions stay on the grid
    hi = np.array([env.nrow - 1, env.ncol - 1])
    assert (np.asarray(npos) >= 0).all() and (np.asarray(npos) <= hi).all()
    assert (np.asarray(ntask) >= 0).all() and (np.asarray(ntask) <= hi).all()


@pytest.mark.parametrize("name", ALL_ENVS)
def test_dynamics_deterministic(name):
    """The step is a pure function: same (pos, task, actions) -> bitwise
    the same (new_pos, new_task, reward), jitted or not."""
    env = make_env(Config(env=name))
    pos = env_reset(env, jax.random.PRNGKey(2))
    task = env_task(env, jax.random.PRNGKey(3))
    a = jnp.array([1, 2, 3, 4, 0], jnp.int32)
    out1 = env_transition(env, pos, task, a)
    out2 = env_transition(env, pos, task, a)
    out3 = jax.jit(lambda p, t, x: env_transition(env, p, t, x))(pos, task, a)
    for x, y, z in zip(out1, out2, out3):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        np.testing.assert_array_equal(np.asarray(x), np.asarray(z))


@pytest.mark.parametrize("name", ALL_ENVS)
def test_reward_bounds(name):
    """Rewards are finite and bounded by each env's documented range
    (scaled rewards bounded by range/5) over random rollouts."""
    n = 5
    env = make_env(Config(env=name))
    lo = -(env.nrow + env.ncol - 1) - (
        1.0 if name == "coverage" else float(n - 1) if name == "congestion" else 0.0
    )
    key = jax.random.PRNGKey(0)
    pos = env_reset(env, jax.random.fold_in(key, 1))
    task = env_task(env, jax.random.fold_in(key, 2))
    for t in range(12):
        a = jax.random.randint(jax.random.fold_in(key, 10 + t), (n,), 0, 5)
        pos, task, r = env_transition(env, pos, task, a.astype(jnp.int32))
        r = np.asarray(r)
        assert np.isfinite(r).all()
        assert (r <= 0.0).all() and (r >= lo).all(), (name, t, r, lo)
        rs = np.asarray(env_reward_scaled(env, jnp.asarray(r)))
        np.testing.assert_allclose(rs, r / 5.0)


@pytest.mark.parametrize("name", ALL_ENVS)
def test_obs_standardization(name):
    """env_obs is the shared grid standardization: per-axis
    (pos - mean(arange)) / std(arange); scaling=False is a plain cast."""
    cfg = Config(env=name, nrow=4, ncol=6)
    env = make_env(cfg)
    pos = env_reset(env, jax.random.PRNGKey(5))
    obs = np.asarray(env_obs(env, pos))
    x, y = np.arange(4), np.arange(6)
    mean = np.array([x.mean(), y.mean()], np.float32)
    std = np.array([x.std(), y.std()], np.float32)
    np.testing.assert_allclose(
        obs, (np.asarray(pos).astype(np.float32) - mean) / std, rtol=1e-6
    )
    env_raw = make_env(cfg.replace(scaling=False))
    np.testing.assert_array_equal(
        np.asarray(env_obs(env_raw, pos)),
        np.asarray(pos).astype(np.float32),
    )


# ---------------------------------------------------------------------------
# per-env dynamics
# ---------------------------------------------------------------------------


def test_pursuit_task_rows_identical_and_evader_moves_one_step():
    env = make_env(Config(env="pursuit"))
    task = env_task(env, jax.random.PRNGKey(7))
    t = np.asarray(task)
    assert (t == t[0]).all()
    pos = env_reset(env, jax.random.PRNGKey(8))
    _, ntask, _ = env_transition(
        env, pos, task, jnp.zeros((5,), jnp.int32)
    )
    nt = np.asarray(ntask)
    assert (nt == nt[0]).all()  # still one broadcast evader
    assert np.abs(nt[0] - t[0]).sum() <= 1  # at most one L1 step


def test_pursuit_capture_pins_evader_and_zeroes_reward():
    env = make_env(_cfg("pursuit", n_agents=3, nrow=3, ncol=3))
    # agent 0 stands ON the evader and stays; everyone stays
    pos = jnp.array([[1, 1], [0, 0], [2, 2]], jnp.int32)
    task = jnp.broadcast_to(jnp.array([1, 1], jnp.int32), (3, 2))
    npos, ntask, r = env_transition(
        env, pos, task, jnp.zeros((3,), jnp.int32)
    )
    np.testing.assert_array_equal(np.asarray(ntask), np.asarray(task))
    np.testing.assert_array_equal(np.asarray(r), np.zeros(3))


def test_pursuit_evader_flees_to_max_min_distance():
    env = make_env(_cfg("pursuit", n_agents=2, nrow=5, ncol=5))
    # both pursuers at the left edge; evader at center must flee right
    pos = jnp.array([[0, 2], [0, 1]], jnp.int32)
    task = jnp.broadcast_to(jnp.array([2, 2], jnp.int32), (2, 2))
    _, ntask, _ = env_transition(env, pos, task, jnp.zeros((2,), jnp.int32))
    assert np.asarray(ntask)[0, 0] == 3  # moved away along the row axis


def test_coverage_static_task_and_collision_penalty():
    env = make_env(_cfg("coverage", n_agents=2, nrow=3, ncol=3))
    task = jnp.array([[0, 0], [2, 2]], jnp.int32)
    # both agents on the SAME cell: each covers landmark 0 at distance
    # d, and both pay the collide penalty
    pos = jnp.array([[0, 0], [0, 0]], jnp.int32)
    npos, ntask, r = env_transition(
        env, pos, task, jnp.zeros((2,), jnp.int32)
    )
    np.testing.assert_array_equal(np.asarray(ntask), np.asarray(task))
    np.testing.assert_allclose(np.asarray(r), [0.0 - 1.0, -4.0 - 1.0])
    # spread out: no penalty, both landmarks covered exactly
    pos = jnp.array([[0, 0], [2, 2]], jnp.int32)
    _, _, r = env_transition(env, pos, task, jnp.zeros((2,), jnp.int32))
    np.testing.assert_allclose(np.asarray(r), [0.0, 0.0])


def test_congestion_shaping_and_load_toll():
    env = make_env(_cfg("congestion", n_agents=3, nrow=3, ncol=3))
    task = jnp.array([[0, 0], [2, 2], [1, 1]], jnp.int32)
    # agent 0 at its goal staying and ALONE: reward 0 (the grid-world
    # shaping rule, bitwise)
    pos = jnp.array([[0, 0], [2, 0], [0, 2]], jnp.int32)
    _, ntask, r = env_transition(env, pos, task, jnp.zeros((3,), jnp.int32))
    np.testing.assert_array_equal(np.asarray(ntask), np.asarray(task))
    assert np.asarray(r)[0] == 0.0
    # all three stacked on one cell: everyone pays 2 others' load
    pos = jnp.array([[1, 1], [1, 1], [1, 1]], jnp.int32)
    _, _, r = env_transition(env, pos, task, jnp.zeros((3,), jnp.int32))
    shaping = np.array([-3.0, -3.0, 0.0])  # agent 2 at-goal-stay
    np.testing.assert_allclose(np.asarray(r), shaping - 2.0 * 1.0)


# ---------------------------------------------------------------------------
# adaptive colluding adversary
# ---------------------------------------------------------------------------


def test_adaptive_payload_formula_and_untouched_rows():
    from rcmarl_tpu.faults import adaptive_payload_tree

    leaf = jnp.array(
        [[1.0, 2.0], [3.0, 6.0], [2.0, 4.0], [99.0, -99.0]], jnp.float32
    )
    coop = jnp.array([True, True, True, False])
    adaptive = jnp.array([False, False, False, True])
    out = np.asarray(
        adaptive_payload_tree((leaf,), coop, adaptive, 2.0)[0]
    )
    # cooperative rows bitwise untouched
    np.testing.assert_array_equal(out[:3], np.asarray(leaf)[:3])
    # payload = mean_coop + scale * (max_coop - min_coop), per coordinate
    np.testing.assert_allclose(out[3], [2.0 + 2.0 * 2.0, 4.0 + 2.0 * 4.0])


def test_adaptive_colluders_send_identical_payloads():
    from rcmarl_tpu.faults import adaptive_payload_tree

    key = jax.random.PRNGKey(0)
    leaf = jax.random.normal(key, (6, 3, 2))
    coop = jnp.array([True, True, True, True, False, False])
    adaptive = ~coop
    out = np.asarray(adaptive_payload_tree(leaf, coop, adaptive, 0.5))
    np.testing.assert_array_equal(out[4], out[5])
    np.testing.assert_array_equal(out[:4], np.asarray(leaf)[:4])


def test_adaptive_role_rejected_by_fused_matrix_spec():
    from rcmarl_tpu.training.update import spec_from_config

    cfg = Config(
        n_agents=4,
        in_nodes=circulant_in_nodes(4, 4),
        agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.ADAPTIVE,),
        H=1,
    )
    with pytest.raises(ValueError, match="ADAPTIVE"):
        spec_from_config(cfg)


# ---------------------------------------------------------------------------
# graph-as-data gather
# ---------------------------------------------------------------------------


def test_gather_with_data_indices_matches_static_gather():
    """Feeding the STATIC topology's indices in as data must reproduce
    the compiled static gather bitwise (rolls vs advanced indexing are
    value-equal; this is what makes the time-varying schedule a pure
    superset of the static path)."""
    from rcmarl_tpu.training.update import gather_neighbor_messages

    cfg = Config(n_agents=5, in_nodes=circulant_in_nodes(5, 4), H=1)
    tree = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (5, 3, 2)),
        "b": jax.random.normal(jax.random.PRNGKey(1), (5, 4)),
    }
    static = gather_neighbor_messages(cfg, tree)
    in_arr = jnp.asarray(np.array(cfg.in_nodes), jnp.int32)
    dynamic = gather_neighbor_messages(cfg, tree, in_arr)
    for a, b in zip(jax.tree.leaves(static), jax.tree.leaves(dynamic)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_scheduled_in_nodes_cadence_and_determinism():
    cfg = Config(
        graph_schedule="random_geometric", graph_degree=3, graph_seed=4, H=1
    )
    g0 = scheduled_in_nodes(cfg, 0)
    assert g0.shape == (5, 3) and g0.dtype == np.int32
    np.testing.assert_array_equal(g0, scheduled_in_nodes(cfg, 0))
    # graph_every groups consecutive blocks onto one graph
    cfg2 = cfg.replace(graph_every=3)
    np.testing.assert_array_equal(
        scheduled_in_nodes(cfg2, 0), scheduled_in_nodes(cfg2, 2)
    )
    assert not np.array_equal(
        scheduled_in_nodes(cfg2, 2), scheduled_in_nodes(cfg2, 3)
    )
    # self-first rows
    np.testing.assert_array_equal(g0[:, 0], np.arange(5))


def test_parallel_trainers_reject_dynamic_graphs():
    from rcmarl_tpu.parallel.seeds import train_parallel
    from rcmarl_tpu.training.trainer import (
        init_train_state,
        train_scanned,
    )

    cfg = _cfg(
        "grid_world",
        n_agents=3,
        nrow=3,
        ncol=3,
        n_episodes=2,
        n_ep_fixed=2,
        max_ep_len=2,
        n_epochs=1,
        graph_schedule="random_geometric",
        graph_degree=3,
        H=1,
    )
    with pytest.raises(ValueError, match="graph_schedule"):
        train_parallel(cfg, seeds=[0], n_blocks=1)
    state = init_train_state(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="graph_schedule"):
        train_scanned(cfg, state, 1)
    with pytest.raises(ValueError, match="solo-trainer"):
        cfg.replace(pipeline_depth=2)


# ---------------------------------------------------------------------------
# slow integration cells (the CI env-zoo smoke cell covers the CLI wire-up
# every run; these are the in-suite twins)
# ---------------------------------------------------------------------------


def _tiny(env_name, **kw):
    base = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        n_episodes=4,
        n_ep_fixed=2,
        max_ep_len=4,
        n_epochs=2,
        H=1,
        env=env_name,
    )
    base.update(kw)
    return Config(**base)


@pytest.mark.slow
@pytest.mark.parametrize("name", NEW_ENVS)
def test_new_envs_train_end_to_end(name):
    from rcmarl_tpu.training.trainer import train

    state, df = train(_tiny(name))
    assert np.isfinite(df["True_team_returns"].values).all()
    for l in jax.tree.leaves(state.params):
        assert np.isfinite(np.asarray(l)).all()


@pytest.mark.slow
def test_dynamic_graph_train_finite_and_resume_deterministic():
    """A time-varying-graph run is finite, and resuming from a
    checkpointed state replays the SAME graph sequence (blocks are keyed
    on the global block number): 2+2 resumed blocks == 4 straight."""
    from rcmarl_tpu.training.trainer import train

    cfg = _tiny(
        "grid_world", graph_schedule="random_geometric", graph_degree=3,
        n_episodes=8,
    )
    s_full, df_full = train(cfg)
    s_half, _ = train(cfg, n_episodes=4)
    s_res, df_res = train(cfg, n_episodes=4, state=s_half)
    for a, b in zip(jax.tree.leaves(s_full.params), jax.tree.leaves(s_res.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        df_full["True_team_returns"].values[4:],
        df_res["True_team_returns"].values,
    )


@pytest.mark.slow
def test_adaptive_netstack_dual_arms_bitwise():
    """The adaptive payload is applied per tree identically on both
    epoch arms — the netstack-vs-dual leaf-for-leaf pin extended to the
    new role."""
    from rcmarl_tpu.training.trainer import train

    cfg = _tiny(
        "grid_world",
        n_agents=4,
        agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.ADAPTIVE,),
        in_nodes=circulant_in_nodes(4, 4),
        adaptive_scale=2.0,
    )
    s_dual, _ = train(cfg.replace(netstack=False))
    s_stack, _ = train(cfg.replace(netstack=True))
    for a, b in zip(
        jax.tree.leaves(s_dual.params), jax.tree.leaves(s_stack.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_adaptive_trim_bounds_containment():
    """One update block under a huge adaptive payload: H=1 trimming
    keeps the cooperative parameters finite and within a sane envelope,
    while H=0 (no trimming) lets the colluding payload through — the
    unit-scale twin of the committed QUALITY.md experiment."""
    from rcmarl_tpu.training.buffer import update_batch
    from rcmarl_tpu.training.rollout import rollout_block
    from rcmarl_tpu.training.trainer import init_train_state, make_env
    from rcmarl_tpu.training.update import update_block

    def run(H, scale):
        cfg = _tiny(
            "grid_world",
            n_agents=5,
            agent_roles=(Roles.COOPERATIVE,) * 4 + (Roles.ADAPTIVE,),
            in_nodes=circulant_in_nodes(5, 4),
            H=H,
            adaptive_scale=scale,
        )
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        key, k_roll, k_upd = jax.random.split(state.key, 3)
        fresh, _ = rollout_block(
            cfg, make_env(cfg), state.params, state.desired, k_roll,
            state.initial,
        )
        batch = update_batch(state.buffer, fresh)
        params = update_block(cfg, state.params, batch, fresh, k_upd)
        coop_norm = max(
            float(np.abs(np.asarray(l)[:4]).max())
            for l in jax.tree.leaves((params.critic, params.tr))
        )
        return coop_norm

    poisoned_h0 = run(0, 1e6)
    contained_h1 = run(1, 1e6)
    # the H=0 clip bounds are the gathered min/max, which the adversary
    # itself sets: the payload lands in the cooperative nets (and the
    # next epoch's fits on the poisoned values overflow to non-finite)
    assert not np.isfinite(poisoned_h0) or poisoned_h0 > 1e3
    # H=1 trims the single colluding payload back to the healthy range
    assert np.isfinite(contained_h1) and contained_h1 < 1e2
