"""Canary-gated deployment contracts (rcmarl_tpu.serve.canary +
the pipeline publisher's canary hook).

The pins that close the deployment loop:

- the GATE's decision rule is exact: candidate frozen return below
  ``incumbent - band * |incumbent|`` -> rejected; at/above -> promoted
  with the incumbent reference advanced; non-finite params -> rejected
  WITHOUT paying an eval; non-finite measured return -> rejected;
- gate measurements are DETERMINISTIC: the same candidate measures the
  same frozen return (the eval stream is seeded), so a decision is
  replayable;
- the WATCHER splices the gate between candidate validation and the
  atomic swap: a gate-rejected file candidate leaves the engine
  serving the incumbent bitwise with the degradation counters
  incremented ('served: last-good'), a promoted one swaps atomically;
- the PUBLISHER's canary hook gives the in-memory pipeline chain the
  same protection (canary_rejects counted, acting tree untouched).

Band-logic cells run on a scripted ``frozen_return`` (deterministic,
no rollouts); a small number of real eval_block measurements pin the
measurement path itself.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.pipeline.publish import PolicyPublisher
from rcmarl_tpu.serve.canary import CanaryGate, CanaryWatcher
from rcmarl_tpu.serve.engine import ServeEngine, stack_actor_rows
from rcmarl_tpu.training.trainer import init_train_state
from rcmarl_tpu.utils.checkpoint import save_checkpoint


def tiny_cfg(**overrides):
    base = dict(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        n_episodes=4,
        n_ep_fixed=2,
        max_ep_len=4,
        n_epochs=2,
        H=1,
    )
    base.update(overrides)
    return Config(**base)


CFG = tiny_cfg()
STATE = init_train_state(CFG, jax.random.PRNGKey(0))
STATE_B = init_train_state(CFG, jax.random.PRNGKey(1))


def _poison(state):
    return state._replace(
        params=state.params._replace(
            actor=jax.tree.map(
                lambda l: l.at[0].set(jnp.nan), state.params.actor
            )
        )
    )


class ScriptedGate(CanaryGate):
    """The band-logic test vehicle: frozen_return reads a scripted
    queue instead of rolling out, so each decision's inputs are exact
    and the band arithmetic is the only thing under test."""

    def __init__(self, returns, **kw):
        super().__init__(CFG, STATE.desired, STATE.initial, **kw)
        self._returns = list(returns)

    def frozen_return(self, params):
        return self._returns.pop(0)


class TestCanaryGateDecision:
    def test_band_floor_arithmetic(self):
        g = ScriptedGate([-5.0], band=0.05)
        g.incumbent_return = -5.0
        assert g.floor() == pytest.approx(-5.25)

    def test_candidate_below_floor_rejected(self):
        g = ScriptedGate([-5.0, -5.26], band=0.05)
        g.set_incumbent(STATE.params)  # scripted -5.0
        assert g.admit(STATE_B.params) is False
        assert g.counters == {"evals": 1, "rejects": 1, "accepts": 0}
        assert g.last["accepted"] is False
        assert g.last["reason"] == "frozen return below the band floor"
        assert g.last["floor"] == pytest.approx(-5.25)
        assert g.last["degradation"] == pytest.approx(0.26)
        # the incumbent reference is untouched: it keeps serving
        assert g.incumbent_return == pytest.approx(-5.0)

    def test_candidate_within_band_promoted_and_becomes_incumbent(self):
        g = ScriptedGate([-5.0, -5.2], band=0.05)
        g.set_incumbent(STATE.params)
        assert g.admit(STATE_B.params) is True
        assert g.counters["accepts"] == 1
        # the promoted candidate IS the new incumbent reference
        assert g.incumbent_return == pytest.approx(-5.2)

    def test_improving_candidate_promoted(self):
        g = ScriptedGate([-5.0, -4.0], band=0.05)
        g.set_incumbent(STATE.params)
        assert g.admit(STATE_B.params) is True
        assert g.incumbent_return == pytest.approx(-4.0)

    def test_nan_poisoned_candidate_rejected_without_eval(self):
        """Non-finite params short-circuit BEFORE the frozen-return
        measurement (the scripted queue holds only the incumbent's
        value — an eval would pop from an empty list and fail)."""
        g = ScriptedGate([-5.0], band=0.05)
        g.set_incumbent(STATE.params)
        assert g.admit(_poison(STATE_B).params) is False
        assert g.counters == {"evals": 0, "rejects": 1, "accepts": 0}
        assert g.last["reason"] == "non-finite candidate params"

    def test_nonfinite_frozen_return_rejected(self):
        g = ScriptedGate([-5.0, float("nan")], band=0.05)
        g.set_incumbent(STATE.params)
        assert g.admit(STATE_B.params) is False
        assert g.last["reason"] == "non-finite frozen return"

    def test_no_incumbent_is_loud(self):
        g = ScriptedGate([-5.0])
        with pytest.raises(RuntimeError, match="incumbent"):
            g.admit(STATE.params)

    def test_invalid_knobs_loud(self):
        with pytest.raises(ValueError, match="band"):
            CanaryGate(CFG, STATE.desired, STATE.initial, band=-0.1)
        with pytest.raises(ValueError, match="blocks"):
            CanaryGate(CFG, STATE.desired, STATE.initial, blocks=0)

    def test_summary_line_reads_the_last_decision(self):
        g = ScriptedGate([-5.0, -9.0], band=0.05)
        g.set_incumbent(STATE.params)
        g.admit(STATE_B.params)
        line = g.summary_line()
        assert "0 accepted, 1 rejected" in line
        assert "rejected (frozen return below the band floor)" in line


class TestCanaryGateMeasurement:
    def test_frozen_return_deterministic(self):
        """The real measurement path: the same params measure the same
        return (seeded eval stream) — a gate decision is replayable."""
        g = CanaryGate(CFG, STATE.desired, STATE.initial, blocks=1)
        r1 = g.frozen_return(STATE.params)
        r2 = g.frozen_return(STATE.params)
        assert np.isfinite(r1)
        assert r1 == r2

    def test_identical_candidate_always_promotes(self):
        """A republish of the serving params can never be rejected:
        its frozen return IS the incumbent's (same seeds, same
        policy)."""
        g = CanaryGate(CFG, STATE.desired, STATE.initial, blocks=1)
        g.set_incumbent(STATE.params)
        assert g.admit(STATE.params) is True


class TestCanaryWatcher:
    def _watcher(self, tmp_path, gate=None):
        path = tmp_path / "checkpoint.npz"
        save_checkpoint(path, STATE, CFG)
        eng = ServeEngine(path)
        if gate is None:
            gate = ScriptedGate([-5.0], band=0.05)
        return eng, CanaryWatcher(eng, gate), path

    def test_incumbent_pinned_at_construction(self, tmp_path):
        _, w, _ = self._watcher(tmp_path)
        assert w.gate.incumbent_return == pytest.approx(-5.0)

    def test_band_violating_candidate_keeps_incumbent(self, tmp_path):
        """A checksum-valid, fully finite candidate whose frozen return
        fell out of the band: rejected on BOTH ledgers, the engine
        serving the incumbent bitwise — 'bad policy' behaves exactly
        like 'corrupt file'."""
        eng, w, path = self._watcher(
            tmp_path, ScriptedGate([-5.0, -9.0], band=0.05)
        )
        save_checkpoint(path, STATE_B, CFG)
        assert w.poll() is False
        assert w.gate.counters["rejects"] == 1
        assert eng.counters["rejects"] == 1
        assert eng.counters["swaps"] == 0
        for a, b in zip(
            jax.tree.leaves(eng.block),
            jax.tree.leaves(stack_actor_rows(STATE.params, CFG)),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert "served: last-good" in eng.summary_line()

    def test_healthy_candidate_promotes_atomically(self, tmp_path):
        eng, w, path = self._watcher(
            tmp_path, ScriptedGate([-5.0, -4.9], band=0.05)
        )
        save_checkpoint(path, STATE_B, CFG)
        assert w.poll() is True
        assert eng.counters["swaps"] == 1
        for a, b in zip(
            jax.tree.leaves(eng.block),
            jax.tree.leaves(stack_actor_rows(STATE_B.params, CFG)),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert w.gate.incumbent_return == pytest.approx(-4.9)
        assert "served: fresh" in eng.summary_line()

    def test_poisoned_candidate_rejected_before_the_gate(self, tmp_path):
        """A NaN candidate is the FILE chain's reject (params_finite in
        _load_candidate): the gate pays no eval and its counters stay
        clean — the scripted queue holds only the incumbent value."""
        eng, w, path = self._watcher(tmp_path)
        poisoned = _poison(STATE_B)
        save_checkpoint(path, poisoned, CFG)
        save_checkpoint(path, poisoned, CFG)  # poison .prev too
        assert w.poll() is False
        assert eng.counters["rejects"] == 1
        assert w.gate.counters["evals"] == 0

    def test_reject_then_promote_sequence(self, tmp_path):
        """The committed-experiment shape: a degraded publish is caught,
        the next healthy publish still promotes (the gate does not
        wedge)."""
        eng, w, path = self._watcher(
            tmp_path, ScriptedGate([-5.0, -9.0, -5.01], band=0.05)
        )
        save_checkpoint(path, STATE_B, CFG)
        assert w.poll() is False
        save_checkpoint(path, STATE_B, CFG)
        assert w.poll() is True
        assert w.gate.counters == {"evals": 2, "accepts": 1, "rejects": 1}
        assert eng.counters["swaps"] == 1 and eng.counters["rejects"] == 1


class TestPublisherCanaryHook:
    def test_canary_reject_keeps_acting_tree(self):
        pub = PolicyPublisher(
            STATE.params, 1, canary=lambda params: False
        )
        assert pub.offer(STATE_B.params, 1) is False
        assert pub.counters["canary_rejects"] == 1
        assert pub.counters["publishes"] == 0
        assert pub.acting is STATE.params  # untouched reference

    def test_canary_accept_publishes(self):
        seen = []

        def canary(params):
            seen.append(params)
            return True

        pub = PolicyPublisher(STATE.params, 1, canary=canary)
        assert pub.offer(STATE_B.params, 1) is True
        assert seen == [STATE_B.params]
        assert pub.acting is STATE_B.params
        assert pub.counters["publishes"] == 1

    def test_finiteness_guard_runs_before_the_canary(self):
        """validate=True rejects a NaN candidate BEFORE the canary
        callable sees it — the eval never pays for a tree the cheap
        guard already condemned."""
        calls = []
        pub = PolicyPublisher(
            STATE.params, 1, validate=True,
            canary=lambda p: calls.append(p) or True,
        )
        assert pub.offer(_poison(STATE_B).params, 1) is False
        assert pub.counters["rejects"] == 1
        assert pub.counters["canary_rejects"] == 0
        assert calls == []

    def test_canary_respects_publish_cadence(self):
        calls = []
        pub = PolicyPublisher(
            STATE.params, 2, canary=lambda p: calls.append(p) or True
        )
        assert pub.offer(STATE_B.params, 1) is False  # not a boundary
        assert calls == []  # the gate is not consulted off-boundary
        assert pub.offer(STATE_B.params, 2) is True
        assert len(calls) == 1

    def test_real_gate_bound_to_publisher(self):
        """The intended composition: PolicyPublisher(canary=gate.admit)
        with the REAL gate — a republish of the incumbent promotes
        (identical frozen return), and the gate counters land."""
        gate = CanaryGate(CFG, STATE.desired, STATE.initial, blocks=1)
        gate.set_incumbent(STATE.params)
        pub = PolicyPublisher(STATE.params, 1, canary=gate.admit)
        assert pub.offer(STATE.params, 1) is True
        assert gate.counters["accepts"] == 1


class TestCanarySection:
    def test_renders_from_the_committed_artifact(self):
        """QUALITY.md's canary section renders from the committed
        experiment artifact (render-from-evidence, never hand-typed);
        absent artifact renders empty."""
        from pathlib import Path

        from rcmarl_tpu.analysis.quality import canary_section

        artifact = (
            Path(__file__).resolve().parent.parent
            / "simulation_results/canary_gate.json"
        )
        if not artifact.exists():
            pytest.skip("committed canary artifact not present")
        lines = canary_section(artifact)
        text = "\n".join(lines)
        assert "## Canary-gated deployment" in text
        assert "**REJECTED**" in text
        assert "promoted" in text
        assert canary_section("/nonexistent/canary.json") == []


class TestCanaryCLI:
    def test_serve_canary_band_requires_watch(self, tmp_path):
        from rcmarl_tpu.cli import main

        path = tmp_path / "checkpoint.npz"
        save_checkpoint(path, STATE, CFG)
        with pytest.raises(SystemExit, match="watch_every"):
            main([
                "serve", "--checkpoint", str(path), "--canary_band", "0.05",
            ])

    @pytest.mark.slow
    def test_serve_cli_canary_row(self, tmp_path, capsys):
        """The CLI wire-up: a canary-gated serve run emits the gate
        counters on the row and the canary summary line (an identical
        checkpoint republished mid-loop promotes). Slow marker: the
        ci_tier1.sh smoke cell drives the same chain through the real
        CLI outside the pytest budget."""
        import json

        from rcmarl_tpu.cli import main

        path = tmp_path / "checkpoint.npz"
        save_checkpoint(path, STATE, CFG)
        assert main([
            "serve", "--checkpoint", str(path),
            "--batch", "4", "--steps", "2", "--reps", "1",
            "--obs_buffers", "1", "--watch_every", "1",
            "--canary_band", "0.05",
        ]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        row = json.loads(out[0])
        assert row["canary"]["band"] == 0.05
        assert np.isfinite(row["canary"]["incumbent_return"])
        assert out[-1].startswith("canary:")
