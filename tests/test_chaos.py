"""Chaos campaign contracts (rcmarl_tpu.chaos).

Tier-1 pins the cheap layers: registry integrity (every point named,
cells unique, the acceptance floor of >= 15 cells across >= 4
subsystems), the --cells selector, the ledger's canonical byte-stable
IO, the compare gate's full finding matrix on synthetic rows
(regression / envelope / unbaselined / stale / improvement-note /
subset semantics), per-cell fault isolation, and the REAL numpy-only
cells (overload + publish poisoning) through the actual CLI check.

The planted-regression run (disable the sanitize fallback + guard, a
survived transport cell must flip to failed and the check to rc != 0 —
the lint-suite discipline) and the committed-ledger spot check ride the
slow marker: they pay real tiny trains.
"""

from __future__ import annotations

import json
from pathlib import Path

import numpy as np
import pytest

from rcmarl_tpu.chaos.campaign import (
    _select_cells,
    check_campaign,
    compare_rows,
    read_resilience,
    run_cell,
    write_resilience,
)
from rcmarl_tpu.chaos.registry import (
    CHAOS_POINTS,
    OUTCOMES,
    CellFailed,
    point_by_name,
    registry_cells,
)

REPO_LEDGER = Path(__file__).resolve().parent.parent / "RESILIENCE.jsonl"


def _row(point="link_nan", intensity="0.5", outcome="survived",
         expected=None, delta=None, **over):
    pt = point_by_name(point)
    base = {
        "kind": "chaos",
        "point": point,
        "subsystem": pt.subsystem if pt else "transport",
        "intensity": intensity,
        "expected": expected
        if expected is not None
        else (dict(pt.cells).get(intensity, "survived") if pt else "survived"),
        "outcome": outcome,
        "counters": {},
        "final_return": None,
        "clean_return": None,
        "return_delta": delta,
        "detail": "synthetic",
    }
    base.update(over)
    return base


class TestRegistry:
    def test_points_named_and_unique(self):
        names = [p.name for p in CHAOS_POINTS]
        assert len(names) == len(set(names))
        for p in CHAOS_POINTS:
            assert p.cells, p.name
            assert p.guard and p.test_pin and p.injector, p.name
            for _, expected in p.cells:
                assert expected in OUTCOMES, (p.name, expected)

    def test_acceptance_floor_cells_and_subsystems(self):
        """The acceptance criteria's floor: >= 15 campaign cells
        spanning >= 4 of the named subsystems."""
        cells = registry_cells()
        assert len(cells) == len(set(cells))
        assert len(cells) >= 15
        subsystems = {p.subsystem for p in CHAOS_POINTS}
        named = {"transport", "gossip", "checkpoint", "publish",
                 "pipeline", "serving"}
        assert len(subsystems & named) >= 4

    def test_selector_resolves_points_and_cells(self):
        assert _select_cells(None) == list(registry_cells())
        assert _select_cells(["link_nan@0.5"]) == [("link_nan", "0.5")]
        both = _select_cells(["serve_overload"])
        assert set(both) == {("serve_overload", "noshed"),
                             ("serve_overload", "shed"),
                             ("serve_overload", "autoscale")}
        with pytest.raises(ValueError, match="matches no registry cell"):
            _select_cells(["no_such_point"])
        with pytest.raises(ValueError, match="matches no registry cell"):
            _select_cells(["link_nan@0.99"])

    def test_run_cell_rejects_unknown(self):
        with pytest.raises(ValueError, match="unknown chaos point"):
            run_cell("no_such_point", "x")
        with pytest.raises(ValueError, match="no intensity"):
            run_cell("link_nan", "0.99")


class TestLedgerIO:
    def test_roundtrip_and_byte_stability(self, tmp_path):
        rows = [_row(), _row("serve_overload", "shed", "survived")]
        p = tmp_path / "RESILIENCE.jsonl"
        write_resilience(p, rows)
        first = p.read_bytes()
        loaded = read_resilience(p)
        assert len(loaded) == 2
        write_resilience(p, loaded)
        assert p.read_bytes() == first  # canonical: rewrite is a no-op
        # canonical order: sorted by (subsystem, point, intensity)
        assert [r["subsystem"] for r in loaded] == sorted(
            r["subsystem"] for r in loaded
        )

    def test_missing_ledger_reads_empty(self, tmp_path):
        assert read_resilience(tmp_path / "absent.jsonl") == []


class TestCompareGate:
    def test_outcome_regression_is_a_finding(self):
        for before, after in (("survived", "degraded"),
                              ("survived", "failed"),
                              ("degraded", "failed")):
            findings, _ = compare_rows(
                [_row(outcome=before)], [_row(outcome=after)]
            )
            assert len(findings) == 1 and "chaos-regression" in findings[0]

    def test_improvement_is_a_note_not_a_finding(self):
        findings, notes = compare_rows(
            [_row(outcome="degraded")], [_row(outcome="survived")]
        )
        assert findings == []
        assert any("unclaimed win" in n for n in notes)

    def test_envelope_widening_is_a_finding(self):
        findings, _ = compare_rows(
            [_row(delta=-0.1)], [_row(delta=-0.5)]
        )
        assert len(findings) == 1 and "chaos-envelope" in findings[0]
        # within tolerance: clean
        findings, _ = compare_rows(
            [_row(delta=-0.1)], [_row(delta=-0.2)]
        )
        assert findings == []
        # NARROWING is never a finding
        findings, _ = compare_rows(
            [_row(delta=-0.5)], [_row(delta=-0.1)]
        )
        assert findings == []

    def test_unbaselined_and_stale(self):
        findings, _ = compare_rows([], [_row()])
        assert len(findings) == 1 and "chaos-unbaselined" in findings[0]
        # a committed row naming no registry cell is stale on FULL checks
        ghost = _row(point="retired_point", expected="survived")
        findings, _ = compare_rows([ghost], [])
        assert len(findings) == 1 and "chaos-stale" in findings[0]
        # ...but a --cells subset judges only what it ran
        findings, _ = compare_rows(
            [ghost, _row()], [_row()], checked=[("link_nan", "0.5")]
        )
        assert findings == []

    def test_expectation_drift_is_unbaselined(self):
        findings, _ = compare_rows(
            [_row(expected="degraded")], [_row(expected="survived")]
        )
        assert len(findings) == 1 and "chaos-unbaselined" in findings[0]

    def test_cell_isolation_records_failed(self):
        def boom(intensity):
            raise RuntimeError("injected crash")

        row = run_cell("link_nan", "0.5", runner=boom)
        assert row["outcome"] == "failed"
        assert "injected crash" in row["detail"]

        def contract(intensity):
            raise CellFailed("guard contract broke")

        row = run_cell("link_nan", "0.5", runner=contract)
        assert row["outcome"] == "failed"
        assert "containment contract violated" in row["detail"]


class TestRealCellsThroughCLI:
    """The numpy-only cells (micro-batching overload, publisher
    poisoning) through the REAL `chaos` CLI — cheap enough for tier-1,
    and they pin the deadline-shedding acceptance criterion (p99 within
    2x the knee-point p99 with the shed fraction ledgered)."""

    CELLS = ["serve_overload", "publish_poison"]

    def test_run_then_check_rc0_then_planted_ledger_flip(self, tmp_path):
        from rcmarl_tpu.cli import main

        ledger = tmp_path / "RESILIENCE.jsonl"
        assert main(
            ["chaos", "--run", "--baseline", str(ledger), "--cells"]
            + self.CELLS
        ) == 0
        rows = read_resilience(ledger)
        assert {(r["point"], r["intensity"]) for r in rows} == {
            ("serve_overload", "noshed"), ("serve_overload", "shed"),
            ("serve_overload", "autoscale"), ("publish_poison", "nan"),
        }
        autoscale = next(r for r in rows if r["intensity"] == "autoscale")
        assert autoscale["outcome"] == "survived"
        assert autoscale["counters"]["max_scale_used"] > 1
        # the scaled fleet undercuts the static arm's shed cost
        assert (
            autoscale["counters"]["shed_fraction"]
            < autoscale["counters"]["static_shed_fraction"]
        )
        shed = next(r for r in rows if r["intensity"] == "shed")
        assert shed["outcome"] == "survived"
        assert shed["counters"]["shed_fraction"] > 0
        assert (
            shed["counters"]["p99_ms"] <= 2.0 * shed["counters"]["knee_p99_ms"]
        )
        noshed = next(r for r in rows if r["intensity"] == "noshed")
        assert noshed["outcome"] == "degraded"
        assert (
            noshed["counters"]["p99_ms"]
            > 2.0 * noshed["counters"]["knee_p99_ms"]
        )
        # a fresh check against what we just wrote is clean
        assert main(
            ["chaos", "--check", "--baseline", str(ledger), "--cells"]
            + self.CELLS
        ) == 0
        # plant a ledger that claims the no-shed arm survived: the real
        # (degraded) outcome is now a regression and the check fails
        doctored = [
            dict(r, outcome="survived") if r["intensity"] == "noshed" else r
            for r in rows
        ]
        write_resilience(ledger, doctored)
        assert main(
            ["chaos", "--check", "--baseline", str(ledger), "--cells"]
            + self.CELLS
        ) == 1
        # the fresh rows landed next to the baseline for the diff
        assert (tmp_path / "RESILIENCE.jsonl.new").exists()

    def test_run_drops_rows_of_retired_registry_cells(self, tmp_path):
        """`chaos --run` is the documented remedy for chaos-stale: a
        committed row naming no registry cell must be DROPPED by the
        regenerate (keeping it would leave the check permanently red),
        while rows of real cells outside the --cells subset are kept."""
        from rcmarl_tpu.cli import main

        ledger = tmp_path / "RESILIENCE.jsonl"
        ghost = _row(point="retired_point", expected="survived")
        kept_real = _row()  # link_nan@0.5: a registry cell, not re-run
        write_resilience(ledger, [ghost, kept_real])
        assert main(
            ["chaos", "--run", "--baseline", str(ledger), "--cells",
             "publish_poison"]
        ) == 0
        cells = {(r["point"], r["intensity"])
                 for r in read_resilience(ledger)}
        assert ("retired_point", "0.5") not in cells
        assert ("link_nan", "0.5") in cells
        assert ("publish_poison", "nan") in cells

    def test_check_without_ledger_is_unbaselined(self, tmp_path, capsys):
        from rcmarl_tpu.cli import main

        rc = main(
            ["chaos", "--check", "--baseline",
             str(tmp_path / "absent.jsonl"), "--cells", "publish_poison"]
        )
        assert rc == 1
        assert "chaos-unbaselined" in capsys.readouterr().out


@pytest.mark.slow
class TestPlantedRegression:
    """The lint-suite discipline on the resilience gate: sabotage the
    defense for real (sanitize fallback AND guard rails disabled), and
    the survived transport cell must flip to FAILED with the check
    flipping to rc != 0."""

    def test_disabling_sanitize_flips_cell_to_failed(self):
        from rcmarl_tpu.chaos import registry
        from rcmarl_tpu.faults import FaultPlan
        from rcmarl_tpu.training.trainer import train

        def sabotaged(intensity):
            # the planted regression: the NaN-bomb plan runs WITHOUT
            # the sanitize fallback and WITHOUT the guard rails —
            # exactly the containment the survived cell certifies
            cfg = registry._tiny(
                n_episodes=registry._TRAIN_EPS,
                fault_plan=FaultPlan(nan_p=float(intensity)),
                consensus_sanitize=False,
            )
            state, df = train(
                cfg, n_episodes=registry._TRAIN_EPS, guard=False
            )
            final = registry._final_return(df)
            import math

            return {
                "outcome": (
                    "survived" if registry._params_ok(state) else "failed"
                ),
                "counters": {},
                "final_return": final if math.isfinite(final) else None,
                "clean_return": registry._clean_train_return(
                    cfg, registry._TRAIN_EPS
                ),
                "detail": "sabotaged: sanitize fallback + guard disabled",
            }

        fresh = run_cell("link_nan", "0.5", runner=sabotaged)
        assert fresh["outcome"] == "failed"
        committed = _row(outcome="survived")
        findings, _ = compare_rows(
            [committed], [fresh], checked=[("link_nan", "0.5")]
        )
        assert len(findings) == 1 and "chaos-regression" in findings[0]

    def test_committed_ledger_spot_check(self, tmp_path):
        """Two real cells re-run against the COMMITTED RESILIENCE.jsonl
        must produce zero findings (the TestCommittedLedger pattern)."""
        if not REPO_LEDGER.exists():
            pytest.skip("no committed RESILIENCE.jsonl in this checkout")
        findings, notes, fresh = check_campaign(
            REPO_LEDGER, cells=["ckpt_bitflip@both", "serve_overload"]
        )
        assert findings == [], findings
        assert len(fresh) == 3


class TestCommittedLedgerShape:
    def test_committed_rows_meet_the_acceptance_floor(self):
        """The committed artifact itself: >= 15 cells, >= 4 subsystems,
        every row canonical with a known outcome/expectation."""
        if not REPO_LEDGER.exists():
            pytest.skip("no committed RESILIENCE.jsonl in this checkout")
        rows = read_resilience(REPO_LEDGER)
        assert len(rows) >= 15
        assert len({r["subsystem"] for r in rows}) >= 4
        known = set(registry_cells())
        for r in rows:
            assert (r["point"], r["intensity"]) in known
            assert r["outcome"] in OUTCOMES
            assert r["expected"] in OUTCOMES
            assert json.dumps(r, sort_keys=True)  # strict JSON
