"""Sparse neighbor exchange + mega-population rails (ops/exchange.py).

Contracts:

1. **Sparse-vs-dense bitwise pin** — :func:`sparse_gather` with the
   static topology's own indices passed as TRACED data is bitwise the
   compiled static gather, and a whole ``train_block`` on the scheduled
   config (graph = the static circulant, as data) matches the static
   config leaf-for-leaf across the arm matrix: dual/netstack, clean /
   faulted+sanitize, and the fused-consensus config (which routes a
   data graph onto the stacked XLA arm — pinned against the static
   fused kernel, i.e. kernel-vs-data-graph).
2. **Guard rails** — every graph :func:`rcmarl_tpu.config.scheduled_in_nodes`
   can emit passes :func:`validate_graph` (hypothesis twin), and every
   corruption class (shape, dtype, range, self-slot, duplicates, trim
   headroom) is rejected loudly before it can reach the device gather.
3. **Cost model** — the analytic exchange cost is linear in
   ``n·degree`` and strictly below the dense ``n·n`` exchange for any
   ``degree < n`` (the AUDIT.jsonl ``consensus_exchange`` row's
   invariant, checked here without compiling anything).
4. **fit_clip rail** — ``clip=0`` (the default) and an unreachable
   ceiling are BITWISE the reference fit (IEEE: ``g * 1.0 == g``), an
   active clip bounds the step norm by ``lr * clip``, and the clip
   threads through the fitstack XLA/Pallas twins leaf-for-leaf.
5. **Diff-DAC task axis** — ``env_step_scaled`` at ``task_scale=1.0``
   is bitwise the plain congestion step; the task-axis gossip program
   trains finite and records its levels.

Heavy cells (two trainer compiles or a replica program) are
slow-marked; the tier-1 residents are the gather/validator/cost/fit
units plus ONE tiny block-level pin.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rcmarl_tpu.config import (
    Config,
    circulant_in_nodes,
    scheduled_in_nodes,
)
from rcmarl_tpu.faults import FaultPlan
from rcmarl_tpu.ops.exchange import (
    exchange_cost_model,
    sparse_gather,
    validate_graph,
)
from rcmarl_tpu.training.trainer import init_train_state, train_block

N = 6
DEG = 3  # incl. self: 2H <= DEG-1 holds with H=1

#: miniature trainer shape (the tier-1 compile budget is tight)
TINY = dict(
    n_agents=N,
    agent_roles=(0,) * N,
    in_nodes=circulant_in_nodes(N, DEG),
    nrow=3,
    ncol=3,
    n_episodes=2,
    max_ep_len=4,
    n_ep_fixed=2,
    n_epochs=1,
    buffer_size=16,
    coop_fit_steps=2,
    adv_fit_epochs=1,
    adv_fit_batch=4,
    batch_size=4,
    H=1,
)


def static_cfg(**kw):
    base = dict(TINY)
    base.update(kw)
    return Config(**base)


def sched_cfg(**kw):
    """The same topology, but consensus rides the data-graph path."""
    return static_cfg(
        graph_schedule="random_geometric", graph_degree=DEG, **kw
    )


#: the static circulant's own rows, as the traced-data operand
CIRC = jnp.asarray(np.array(circulant_in_nodes(N, DEG)), jnp.int32)


def assert_trees_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def random_tree(key, n):
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "w": jax.random.normal(k1, (n, 4, 3)),
        "b": jax.random.normal(k2, (n,)),
        "v": jax.random.normal(k3, (n, 2)),
    }


class TestSparseGather:
    def test_matches_static_fancy_index_bitwise(self):
        """Same indices, data vs literal: the exact same gather op."""
        tree = random_tree(jax.random.PRNGKey(0), N)
        idx = np.array(circulant_in_nodes(N, DEG))
        sparse = sparse_gather(tree, jnp.asarray(idx, jnp.int32))
        static = jax.tree.map(lambda l: l[idx], tree)
        assert_trees_equal(sparse, static)

    def test_own_message_at_slot_zero(self):
        tree = random_tree(jax.random.PRNGKey(1), N)
        out = sparse_gather(tree, CIRC)
        for name in tree:
            np.testing.assert_array_equal(
                np.asarray(out[name][:, 0]), np.asarray(tree[name])
            )

    def test_one_compiled_program_across_resamples(self):
        """Indices are DATA: one jitted program serves every graph."""
        tree = random_tree(jax.random.PRNGKey(2), N)
        compiles = []
        cfg = sched_cfg()

        @jax.jit
        def gather(t, g):
            compiles.append(1)
            return sparse_gather(t, g)

        for block in range(3):
            g = validate_graph(
                scheduled_in_nodes(cfg, block), N, degree=DEG, H=1
            )
            out = gather(tree, jnp.asarray(g))
            assert_trees_equal(out, jax.tree.map(lambda l: l[g], tree))
        assert len(compiles) == 1  # traced once, re-dispatched twice

    def test_ragged_padded_rows_match_dense_gather(self):
        """The dense arm's padded fancy-index gather IS sparse_gather on
        the padded index array — the ragged-graph pin."""
        from rcmarl_tpu.training.update import gather_neighbor_messages

        ragged = ((0, 1), (1, 0, 2), (2, 0))
        cfg = static_cfg(
            n_agents=3, agent_roles=(0,) * 3, in_nodes=ragged, H=0
        )
        in_pad, valid = cfg.padded_in_nodes()
        assert any(v != valid[0] for v in valid)  # genuinely ragged
        tree = random_tree(jax.random.PRNGKey(3), 3)
        dense = gather_neighbor_messages(cfg, tree)
        sparse = sparse_gather(tree, jnp.asarray(np.array(in_pad)))
        assert_trees_equal(dense, sparse)


def _block_pin(cfg_sched, cfg_static):
    """train_block on the scheduled config, fed the STATIC topology as
    data, must match the static program leaf-for-leaf."""
    state = init_train_state(cfg_static, jax.random.PRNGKey(0))
    out_d, m_d = train_block(cfg_static, state)
    out_s, m_s = train_block(cfg_sched, state, graph=CIRC)
    assert_trees_equal(out_s.params, out_d.params)
    np.testing.assert_array_equal(
        np.asarray(m_s.true_team_returns), np.asarray(m_d.true_team_returns)
    )


class TestBlockLevelPins:
    def test_dual_arm_clean(self):
        _block_pin(sched_cfg(netstack=False), static_cfg(netstack=False))

    @pytest.mark.slow
    def test_netstack_arm_clean(self):
        _block_pin(sched_cfg(netstack=True), static_cfg(netstack=True))

    @pytest.mark.slow
    @pytest.mark.parametrize("netstack", [False, True])
    def test_faulted_sanitized(self, netstack):
        """Transport faults act on the GATHERED block, so the sparse
        block passes through the same fault/trim/clip/mean chain."""
        plan = FaultPlan(nan_p=0.3, drop_p=0.2, seed=11)
        kw = dict(
            netstack=netstack, fault_plan=plan, consensus_sanitize=True
        )
        _block_pin(sched_cfg(**kw), static_cfg(**kw))

    @pytest.mark.slow
    def test_fused_kernel_vs_data_graph(self):
        """Kernel-vs-data-graph equivalence: the scheduled XLA arm fed
        the static topology as traced data matches the STATIC
        fused-consensus kernel (which unrolls in_nodes inside the
        Pallas program) to kernel tolerance — the fused kernel itself
        is only allclose to the XLA arm in this fusion context, so the
        pin is allclose, not bitwise (the bitwise sparse-vs-dense pins
        live on the XLA arms above)."""
        cfg_f = static_cfg(
            netstack=True, consensus_impl="pallas_fused_interpret"
        )
        cfg_s = sched_cfg(netstack=True)
        state = init_train_state(cfg_f, jax.random.PRNGKey(0))
        out_f, _ = train_block(cfg_f, state)
        out_s, _ = train_block(cfg_s, state, graph=CIRC)
        for a, b in zip(
            jax.tree.leaves(out_f.params), jax.tree.leaves(out_s.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6
            )

    @pytest.mark.slow
    def test_sparse_fused_block_bitwise_vs_xla_arm(self):
        """The SPARSE one-kernel epoch at block level: the scheduled
        config on the fused impl (graph as a scalar-prefetch operand,
        in-register gather) must match the scheduled XLA arm
        (sparse_gather chain) BITWISE, leaf-for-leaf, on the same
        traced graph under the sanitize contract — the ISSUE-19 lift
        of the old time-varying rejection. (Sanitize-off cells keep
        the kernel's historical PLAIN allclose contract — the
        ``jnp.mean`` epilogue's bits are fusion-context-dependent,
        tests/test_fused_epoch.py.)"""
        kw = dict(
            netstack=True,
            consensus_sanitize=True,
            fault_plan=FaultPlan(nan_p=0.3, drop_p=0.2, seed=11),
        )
        cfg_x = sched_cfg(**kw)
        cfg_p = sched_cfg(consensus_impl="pallas_fused_interpret", **kw)
        state = init_train_state(cfg_x, jax.random.PRNGKey(0))
        out_x, m_x = train_block(cfg_x, state, graph=CIRC)
        out_p, m_p = train_block(cfg_p, state, graph=CIRC)
        assert_trees_equal(out_p.params, out_x.params)
        np.testing.assert_array_equal(
            np.asarray(m_p.true_team_returns),
            np.asarray(m_x.true_team_returns),
        )

    @pytest.mark.slow
    def test_scheduled_host_loop_trains_finite(self):
        """The real host-looped train() path: per-block resamples flow
        through validate_graph + sparse_gather and training stays
        finite."""
        from rcmarl_tpu.training.trainer import train

        cfg = sched_cfg(n_episodes=4, fit_clip=1.0)
        state, df = train(cfg, n_episodes=4)
        assert all(
            bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(state.params)
        )
        assert np.isfinite(df["True_team_returns"].to_numpy()).all()


class TestValidateGraph:
    def valid(self):
        return np.asarray(
            validate_graph(scheduled_in_nodes(sched_cfg(), 0), N, DEG, 1)
        )

    def test_accepts_scheduled_output(self):
        g = self.valid()
        assert g.dtype == np.int32 and g.shape == (N, DEG)

    def test_rejects_wrong_shape(self):
        with pytest.raises(ValueError, match="must be"):
            validate_graph(self.valid()[: N - 1], N)
        with pytest.raises(ValueError, match="degree"):
            validate_graph(self.valid(), N, degree=DEG + 1)

    def test_rejects_float_dtype(self):
        with pytest.raises(ValueError, match="integer"):
            validate_graph(self.valid().astype(np.float32), N)

    def test_rejects_out_of_range(self):
        g = self.valid()
        g[2, 1] = N  # one past the end
        with pytest.raises(ValueError, match="out of range"):
            validate_graph(g, N)
        g = self.valid()
        g[0, 2] = -1
        with pytest.raises(ValueError, match="out of range"):
            validate_graph(g, N)

    def test_rejects_non_self_first(self):
        g = self.valid()
        g[3, 0], g[3, 1] = g[3, 1], g[3, 0]
        with pytest.raises(ValueError, match="itself"):
            validate_graph(g, N)

    def test_rejects_duplicate_senders(self):
        g = self.valid()
        g[1, 2] = g[1, 1]  # a sender voting twice
        with pytest.raises(ValueError, match="duplicate"):
            validate_graph(g, N)

    def test_rejects_insufficient_trim_headroom(self):
        with pytest.raises(ValueError, match="2H"):
            validate_graph(self.valid(), N, H=2)  # needs degree >= 5


@pytest.mark.parametrize("n,deg", [(16, 4), (256, 9), (1024, 8)])
def test_cost_model_linear_and_below_dense(n, deg):
    sparse = exchange_cost_model(n, deg, p_total=100)
    dense = exchange_cost_model(n, n, p_total=100)
    assert sparse["total"] < dense["total"]
    # the dominant written-block term is exactly linear in degree
    double = exchange_cost_model(n, 2 * deg, p_total=100)
    assert double["write_gathered"] == 2 * sparse["write_gathered"]


# Property twin: EVERY graph the schedule can emit passes the guard.
# The deterministic sweep always runs (hypothesis is an optional dep —
# tests/test_graph_properties.py covers the builder when it exists);
# with hypothesis present the same property also fuzzes broadly.
def _check_schedule_validates(H, seed, block, n=8):
    degree = 2 * H + 1
    cfg = Config(
        n_agents=n,
        agent_roles=(0,) * n,
        in_nodes=tuple(
            tuple((i + k) % n for k in range(degree)) for i in range(n)
        ),
        H=H,
        graph_schedule="random_geometric",
        graph_degree=degree,
        graph_seed=seed,
    )
    g = validate_graph(
        scheduled_in_nodes(cfg, block), n, degree=degree, H=H
    )
    assert g.shape == (n, degree)


@pytest.mark.parametrize("H", [0, 1, 2])
@pytest.mark.parametrize("seed", [0, 17, 2**19 + 3])
@pytest.mark.parametrize("block", [0, 1, 37])
def test_scheduled_graphs_always_validate(H, seed, block):
    _check_schedule_validates(H, seed, block)


try:  # the fuzzing twin, when the optional dep exists
    from hypothesis import given, settings, strategies as st

    @given(
        st.integers(min_value=0, max_value=2),  # H
        st.integers(min_value=0, max_value=2**20),  # graph_seed
        st.integers(min_value=0, max_value=40),  # block
    )
    @settings(max_examples=40, deadline=None)
    def test_scheduled_graphs_always_validate_fuzzed(H, seed, block):
        _check_schedule_validates(H, seed, block)

except ImportError:  # pragma: no cover - hypothesis not installed
    pass


class TestFitClip:
    def _fit(self, clip, minibatch=False):
        from rcmarl_tpu.ops.fit import fit_mse_full_batch, fit_mse_minibatch

        key = jax.random.PRNGKey(5)
        k1, k2, k3 = jax.random.split(key, 3)
        params = {
            "w": jax.random.normal(k1, (3, 1)),
            "b": jnp.zeros((1,)),
        }
        x = jax.random.normal(k2, (16, 3)) * 8.0  # hot gradients
        t = jax.random.normal(k3, (16, 1))
        mask = jnp.ones((16,))
        fwd = lambda p, xx: xx @ p["w"] + p["b"]
        if minibatch:
            out, _ = fit_mse_minibatch(
                key, params, fwd, x, t, mask, epochs=2, batch_size=8,
                lr=0.05, clip=clip,
            )
        else:
            out, _ = fit_mse_full_batch(
                params, fwd, x, t, mask, n_steps=3, lr=0.05, clip=clip
            )
        return params, out

    @pytest.mark.parametrize("minibatch", [False, True])
    def test_off_and_unreachable_ceiling_bitwise(self, minibatch):
        """clip=0 traces NO clip ops; an unreachable ceiling multiplies
        by exactly 1.0 — both are the reference fit, bit-for-bit."""
        _, off = self._fit(0.0, minibatch)
        _, huge = self._fit(1e12, minibatch)
        assert_trees_equal(off, huge)

    def test_active_clip_bounds_first_step(self):
        from rcmarl_tpu.ops.fit import fit_mse_full_batch

        clip, lr = 0.25, 0.05
        params, _ = self._fit(0.0)
        fwd = lambda p, xx: xx @ p["w"] + p["b"]
        key = jax.random.PRNGKey(5)
        _, k2, k3 = jax.random.split(key, 3)
        x = jax.random.normal(k2, (16, 3)) * 8.0
        t = jax.random.normal(k3, (16, 1))
        out, _ = fit_mse_full_batch(
            params, fwd, x, t, jnp.ones((16,)), n_steps=1, lr=lr, clip=clip
        )
        delta = jax.tree.map(lambda a, b: a - b, out, params)
        norm = float(
            jnp.sqrt(
                sum(jnp.sum(jnp.square(l)) for l in jax.tree.leaves(delta))
            )
        )
        assert norm <= lr * clip * (1 + 1e-5)
        # and the raw fit genuinely exceeds the ceiling (clip is active)
        raw, _ = fit_mse_full_batch(
            params, fwd, x, t, jnp.ones((16,)), n_steps=1, lr=lr
        )
        draw = jax.tree.map(lambda a, b: a - b, raw, params)
        assert (
            float(
                jnp.sqrt(
                    sum(
                        jnp.sum(jnp.square(l))
                        for l in jax.tree.leaves(draw)
                    )
                )
            )
            > lr * clip
        )

    def test_clip_threads_through_fitstack_twins(self):
        """XLA fused scan vs Pallas fit kernel (interpret), clip ON:
        the clip lives in the shared step body, so the leaf-for-leaf
        pin carries any clip value."""
        from rcmarl_tpu.ops.fit import FitSchedule, fused_fit_scan
        from rcmarl_tpu.ops.pallas_fit import pallas_fit_scan

        R, n, B, W = 2, 2, 8, 4
        key = jax.random.PRNGKey(9)
        ks = jax.random.split(key, 6)
        keys = jax.random.split(ks[0], R * n).reshape(R, n, -1)
        params = {
            "w": jax.random.normal(ks[1], (R, n, W, 1)),
            "b": jnp.zeros((R, n, 1)),
        }
        x = jax.random.normal(ks[2], (R, B, W)) * 5.0
        t = jax.random.normal(ks[3], (R, n, B, 1))
        mask = jnp.ones((B,))
        fwd = lambda p, xx: xx @ p["w"] + p["b"]
        sched = FitSchedule(epochs=2, batch_size=4)
        xla_out, xla_loss = fused_fit_scan(
            keys, params, fwd, x, t, mask, sched, 0.05, 0.3
        )
        pl_out, pl_loss = pallas_fit_scan(
            keys, params, fwd, x, t, mask, sched, 0.05, 0.3,
            interpret=True,
        )
        assert_trees_equal(xla_out, pl_out)
        np.testing.assert_allclose(
            np.asarray(pl_loss), np.asarray(xla_loss), rtol=1e-6
        )

    def test_config_validation(self):
        with pytest.raises(ValueError, match="fit_clip"):
            static_cfg(fit_clip=-0.5)
        assert static_cfg(fit_clip=1.0).fit_clip == 1.0


class TestTaskAxis:
    def _world(self, weight=1.0):
        from rcmarl_tpu.envs.api import make_env

        cfg = static_cfg(env="congestion", congestion_weight=weight)
        return cfg, make_env(cfg)

    def test_unit_scale_bitwise(self):
        from rcmarl_tpu.envs.congestion import env_step, env_step_scaled

        cfg, env = self._world()
        key = jax.random.PRNGKey(4)
        pos = jax.random.randint(key, (N, 2), 0, 3)
        task = jnp.zeros((N, 2), jnp.int32)
        acts = jax.random.randint(key, (N,), 0, 5)
        base = env_step(env, pos, task, acts)
        scaled = env_step_scaled(env, pos, task, acts, jnp.float32(1.0))
        for a, b in zip(base, scaled):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_scale_amplifies_the_toll_only(self):
        from rcmarl_tpu.envs.congestion import env_step, env_step_scaled

        cfg, env = self._world()
        pos = jnp.zeros((N, 2), jnp.int32)  # everyone on one cell
        task = jnp.zeros((N, 2), jnp.int32)
        acts = jnp.zeros((N,), jnp.int32)  # all stay: shaping = 0
        _, _, r1 = env_step(env, pos, task, acts)
        _, _, r2 = env_step_scaled(env, pos, task, acts, jnp.float32(2.0))
        np.testing.assert_allclose(np.asarray(r2), 2.0 * np.asarray(r1))

    @pytest.mark.slow
    def test_task_axis_gossip_trains_finite(self):
        """The Diff-DAC arm end to end: two replicas train the
        congestion world at different load levels through ONE compiled
        program, the gossip mix doubling as cross-task consensus."""
        from rcmarl_tpu.parallel.gossip import train_gossip

        cfg = static_cfg(
            env="congestion",
            replicas=2,
            task_axis=True,
            task_levels=(0.5, 2.0),
            gossip_every=1,
            gossip_graph="full",
            gossip_H=0,
        )
        states, df = train_gossip(cfg)
        g = df.attrs["gossip"]
        assert g["task_axis"] is True
        assert g["task_levels"] == [0.5, 2.0]
        assert all(
            bool(jnp.isfinite(l).all())
            for l in jax.tree.leaves(states.params)
        )
