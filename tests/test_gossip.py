"""Gossip-replicated learners (rcmarl_tpu.parallel.gossip).

Four contracts:

1. **Independence pin** — ``ReplicaFaultPlan=None`` + no mixing
   (``gossip_every=0``) is leaf-for-leaf BITWISE the independent
   seed-axis run (`parallel/seeds.py:train_parallel` over the same
   seeds): the replica layer adds nothing until replicas actually talk.
2. **Projection guarantee, lifted to replicas** — with ≤ ``gossip_H``
   Byzantine replicas (NaN-bomb or sign-flip), every healthy replica's
   post-mix parameters stay finite and inside the healthy replicas'
   elementwise min/max envelope (the trimmed-mean clip bound, exactly
   the paper's in-graph guarantee one level up).
3. **Mean-mix poisoning regression** — the plain-mean comparison arm is
   poisoned by ONE NaN-bombing replica (the motivation for trimming).
4. **Replica checkpointing** — the replica-stacked TrainState + gossip
   round counter round-trip through the checksummed ``.prev``-rotated
   format, and a corrupted primary falls back via
   ``load_checkpoint_with_fallback``.
"""

import jax
import numpy as np
import pytest

from rcmarl_tpu.config import Config
from rcmarl_tpu.faults import ReplicaFaultPlan
from rcmarl_tpu.parallel.gossip import (
    _mix_tree,
    gossip_mix_block,
    replica_in_nodes,
    replica_seeds,
    train_gossip,
)
from rcmarl_tpu.parallel.seeds import init_states, train_parallel
from rcmarl_tpu.ops.aggregation import ravel_neighbor_tree

#: 3-agent miniature (the lint-config scale) so every jitted program in
#: this module compiles in O(seconds) — the tier-1 budget is tight.
TINY3 = dict(
    n_agents=3,
    agent_roles=(0, 0, 0),
    in_nodes=((0, 1, 2), (1, 2, 0), (2, 0, 1)),
    nrow=3,
    ncol=3,
    n_episodes=2,
    max_ep_len=4,
    n_ep_fixed=2,
    n_epochs=1,
    buffer_size=16,
    coop_fit_steps=2,
    adv_fit_epochs=1,
    adv_fit_batch=4,
    batch_size=4,
)


def gossip_cfg(**kw):
    base = dict(replicas=4, gossip_graph="full", gossip_H=1, gossip_every=1)
    base.update(kw)
    return Config(**TINY3, **base)


_PARAMS_CACHE = {}


def init_params(cfg):
    """Replica-stacked init params, shared across the mix tests: the
    gossip knobs don't touch the parameter shapes, so one vmapped init
    (one compile) serves every mix variant."""
    key = cfg.replicas
    if key not in _PARAMS_CACHE:
        _PARAMS_CACHE[key] = init_states(cfg, replica_seeds(cfg)).params
    return _PARAMS_CACHE[key]


def params_flat(params):
    """(R, P_total) view of the mixable families."""
    flat, _ = ravel_neighbor_tree(_mix_tree(params))
    return np.asarray(flat)


class TestReplicaGraphs:
    def test_ring_and_full_shapes(self):
        cfg = gossip_cfg(replicas=5, gossip_graph="ring", gossip_degree=3)
        g = replica_in_nodes(cfg)
        assert g == tuple(
            tuple((i + k) % 5 for k in range(3)) for i in range(5)
        )
        full = replica_in_nodes(gossip_cfg(replicas=4))
        assert all(len(row) == 4 and row[0] == i for i, row in enumerate(full))

    def test_random_geometric_is_deterministic_and_self_first(self):
        cfg = gossip_cfg(
            replicas=6, gossip_graph="random_geometric", gossip_degree=3
        )
        g1, g2 = replica_in_nodes(cfg), replica_in_nodes(cfg)
        assert g1 == g2
        assert all(len(row) == 3 and row[0] == i for i, row in enumerate(g1))
        assert all(len(set(row)) == 3 for row in g1)

    def test_validation(self):
        with pytest.raises(ValueError, match="gossip_H"):
            gossip_cfg(replicas=2, gossip_H=1)  # n_in=2: need 2H <= 1
        with pytest.raises(ValueError, match="gossip_degree"):
            gossip_cfg(replicas=2, gossip_graph="ring", gossip_degree=3)
        with pytest.raises(ValueError, match="gossip_graph"):
            gossip_cfg(gossip_graph="torus")
        with pytest.raises(ValueError, match="gossip_mix"):
            gossip_cfg(gossip_mix="median")
        with pytest.raises(ValueError, match="out of range"):
            gossip_cfg(
                replica_fault_plan=ReplicaFaultPlan(byzantine_replicas=(9,))
            )


class TestReplicaFaultPlan:
    def test_field_validation(self):
        with pytest.raises(ValueError, match="drop_p"):
            ReplicaFaultPlan(drop_p=1.5)
        with pytest.raises(ValueError, match="byzantine_mode"):
            ReplicaFaultPlan(byzantine_mode="gaussian")
        with pytest.raises(ValueError, match="duplicate"):
            ReplicaFaultPlan(byzantine_replicas=(1, 1))
        with pytest.raises(ValueError, match="non-negative"):
            ReplicaFaultPlan(byzantine_replicas=(-1,))

    def test_active_and_normalization(self):
        assert not ReplicaFaultPlan().active
        assert ReplicaFaultPlan(nan_p=0.1).active
        plan = ReplicaFaultPlan(byzantine_replicas=(2, 0))
        assert plan.active
        assert plan.byzantine_replicas == (0, 2)  # sorted: order-stable hash


class TestGossipMix:
    """Direct mix-block contracts on real (init-time) parameter trees."""

    def mix(self, cfg, params, exclude=None, rnd=0):
        import jax.numpy as jnp

        R = cfg.replicas
        excl = jnp.zeros(R, bool) if exclude is None else jnp.asarray(exclude)
        return gossip_mix_block(
            cfg, params, params, jnp.asarray(rnd, jnp.int32), excl
        )

    @pytest.mark.parametrize("mode", ["nan", "sign_flip"])
    def test_healthy_replicas_stay_in_healthy_envelope(self, mode):
        cfg = gossip_cfg(
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(3,), byzantine_mode=mode
            )
        )
        params = init_params(cfg)
        pre = params_flat(params)
        mixed, diag = self.mix(cfg, params)
        post = params_flat(mixed)
        healthy = [0, 1, 2]
        lo = pre[healthy].min(axis=0)
        hi = pre[healthy].max(axis=0)
        for r in healthy:
            assert np.isfinite(post[r]).all()
            tol = 1e-6 * np.maximum(1.0, np.abs(hi))
            assert (post[r] >= lo - tol).all() and (post[r] <= hi + tol).all()
        if mode == "nan":
            assert int(diag.nonfinite) > 0

    def test_mean_mix_poisoned_by_one_nan_replica(self):
        """The comparison arm's regression: plain-mean gossip is
        destroyed by a single NaN-bombing replica."""
        cfg = gossip_cfg(
            gossip_mix="mean",
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(3,), byzantine_mode="nan"
            ),
        )
        params = init_params(cfg)
        mixed, _ = self.mix(cfg, params)
        post = params_flat(mixed)
        for r in (0, 1, 2):  # every in-neighbor of the bomber is poisoned
            assert np.isnan(post[r]).any()

    def test_guard_exclusion_drops_replica_from_mix(self):
        """An excluded replica's payload must not leak into the mix:
        with gossip_H=0 (sanitized mean over survivors) the healthy
        replicas' mix equals the mean over the non-excluded ones."""
        cfg = gossip_cfg(gossip_H=0)
        params = init_params(cfg)
        # replica 3's params are absurd; exclusion must hide them
        big = jax.tree.map(
            lambda l: l.at[3].set(1e9)
            if np.issubdtype(l.dtype, np.floating)
            else l,
            params,
        )
        mixed, _ = self.mix(cfg, big, exclude=[False, False, False, True])
        post = params_flat(mixed)
        pre = params_flat(big)
        expected = pre[[0, 1, 2]].mean(axis=0)
        for r in (0, 1, 2):
            # per-receiver slot order permutes the accumulation, so the
            # sum association differs from numpy's by last-ulp noise
            np.testing.assert_allclose(post[r], expected, rtol=1e-5, atol=1e-7)
        assert (np.abs(post[:3]) < 1e6).all()

    def test_inactive_plan_is_bitwise_no_plan(self):
        """The fault machinery must be invisible when no fault can fire
        (the dedicated-stream discipline, replica level)."""
        cfg_none = gossip_cfg(replica_fault_plan=None)
        cfg_zero = gossip_cfg(replica_fault_plan=ReplicaFaultPlan())
        params = init_states(cfg_none, replica_seeds(cfg_none)).params
        a, _ = self.mix(cfg_none, params)
        b, _ = self.mix(cfg_zero, params)
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


class TestGossipTrain:
    # ~14s — tier-1 870s wall-budget shed; the gala depth-0 delegation
    # pin (tests/test_gala.py) re-proves the gossip_every=0 corner fast
    @pytest.mark.slow
    def test_no_mix_is_bitwise_independent_seed_axis(self):
        """ReplicaFaultPlan=None + gossip_every=0 ≡ parallel/seeds.py,
        leaf for leaf (params AND metrics)."""
        cfg = gossip_cfg(replicas=2, gossip_every=0, gossip_H=0)
        states, df = train_gossip(cfg, n_episodes=4)
        ref_states, ref_m = train_parallel(
            Config(**TINY3), seeds=list(replica_seeds(cfg)), n_blocks=2
        )
        for a, b in zip(
            jax.tree.leaves(states), jax.tree.leaves(ref_states)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert df.attrs["gossip"]["rounds"] == 0

    @pytest.mark.slow
    def test_byzantine_chaos_keeps_healthy_replicas_training(self):
        """R=4, H=1, one NaN-bombing replica, trimmed mix under guard:
        rc-equivalent of the CI chaos cell — every replica's params stay
        finite, blocks advance, counters land in df.attrs['gossip']."""
        cfg = gossip_cfg(
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(3,), byzantine_mode="nan"
            )
        )
        states, df = train_gossip(cfg, n_episodes=4)
        g = df.attrs["gossip"]
        assert g["rounds"] == 2 and g["byzantine"] == [3]
        assert all(g["replica_healthy"])
        assert g["nonfinite"] > 0
        assert np.all(np.asarray(states.block) == 2)

    @pytest.mark.slow
    def test_mean_mix_training_run_is_poisoned(self):
        """End-to-end regression: the same chaos under the mean arm
        (guard off) leaves the healthy replicas non-finite."""
        cfg = gossip_cfg(
            gossip_mix="mean",
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(3,), byzantine_mode="nan"
            ),
        )
        _, df = train_gossip(cfg, n_episodes=4, guard=False)
        assert not any(df.attrs["gossip"]["replica_healthy"][:3])


class TestReadmission:
    def test_negative_readmit_after_rejected(self):
        with pytest.raises(ValueError, match="readmit_after"):
            train_gossip(gossip_cfg(), n_episodes=2, readmit_after=-1)

    @pytest.mark.slow
    def test_readmit_zero_is_bitwise_the_legacy_path(self):
        """readmit_after=0 (the default) pins bit-for-bit to the PR-7
        one-round exclusion: on a CLEAN config the whole readmission
        machinery must also be inert at any K (no guard events, so
        quarantine/streak never move)."""
        cfg = gossip_cfg()
        s0, df0 = train_gossip(cfg, n_episodes=4, readmit_after=0)
        s2, df2 = train_gossip(cfg, n_episodes=4, readmit_after=2)
        for a, b in zip(jax.tree.leaves(s0), jax.tree.leaves(s2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        g0, g2 = df0.attrs["gossip"], df2.attrs["gossip"]
        assert g0["readmitted"] == 0 and g2["readmitted"] == 0
        assert g0["quarantined"] == [0] * 4
        assert g0["readmit_after"] == 0 and g2["readmit_after"] == 2
        np.testing.assert_array_equal(
            df0["True_team_returns"].values, df2["True_team_returns"].values
        )

    @pytest.mark.slow
    def test_flapping_replica_quarantined_then_readmitted(self, monkeypatch):
        """Scripted health flapping (replica 3 unhealthy in segment 0
        only): under readmit_after=2 it must sit out TWO mixes (the
        quarantine is sticky across its first healthy probe round) and
        re-enter at the third, with the readmission counted; under the
        legacy readmit_after=0 the same script excludes it from exactly
        ONE mix. The real-fault twin of this cell is the committed
        gossip_flapping row in RESILIENCE.jsonl and the
        gossip_readmission.json experiment."""
        import rcmarl_tpu.training.trainer as trainer_mod

        def scripted_health(calls):
            healths = iter(calls)

            def fake(states, metrics):
                return np.asarray(next(healths), bool)

            return fake

        cfg = gossip_cfg()
        script = [
            [True, True, True, False],
            [True, True, True, True],
            [True, True, True, True],
            [True, True, True, True],
        ]
        monkeypatch.setattr(
            trainer_mod, "_replica_block_healthy", scripted_health(script)
        )
        _, df = train_gossip(cfg, n_episodes=8, guard=True, readmit_after=2)
        g = df.attrs["gossip"]
        # seg0: quarantined (excluded from mix 0); seg1: probe 1
        # (still excluded from mix 1); seg2: probe 2 -> READMITTED
        # before mix 2; seg3: fully back
        assert g["rollbacks"] == 1
        assert g["readmitted"] == 1
        assert g["excluded"] == 2  # replica-rounds spent excluded
        assert g["quarantined"] == [0] * 4

        monkeypatch.setattr(
            trainer_mod, "_replica_block_healthy", scripted_health(script)
        )
        _, df0 = train_gossip(cfg, n_episodes=8, guard=True, readmit_after=0)
        g0 = df0.attrs["gossip"]
        assert g0["rollbacks"] == 1
        assert g0["readmitted"] == 0
        assert g0["excluded"] == 1  # legacy: one mix, then back in

    @pytest.mark.slow
    def test_flap_resets_the_probe_streak(self, monkeypatch):
        """A replica that flaps unhealthy again mid-probe must restart
        its streak — the exact hole one-round exclusion leaves open."""
        import rcmarl_tpu.training.trainer as trainer_mod

        script = [
            [True, True, True, False],  # quarantined
            [True, True, True, True],   # probe 1
            [True, True, True, False],  # flaps: streak resets
            [True, True, True, True],   # probe 1 again — NOT readmitted
        ]
        healths = iter(script)
        monkeypatch.setattr(
            trainer_mod,
            "_replica_block_healthy",
            lambda s, m: np.asarray(next(healths), bool),
        )
        cfg = gossip_cfg()
        _, df = train_gossip(cfg, n_episodes=8, guard=True, readmit_after=2)
        g = df.attrs["gossip"]
        assert g["readmitted"] == 0
        assert g["quarantined"] == [0, 0, 0, 1]  # still serving probation
        assert g["excluded"] == 4  # excluded from every mix
        assert g["rollbacks"] == 2


class TestReplicaCheckpoint:
    def test_replica_world_roundtrip_and_fallback(self, tmp_path):
        from rcmarl_tpu.utils.checkpoint import (
            load_checkpoint,
            load_checkpoint_with_fallback,
            read_checkpoint_meta,
            save_checkpoint,
        )

        cfg = gossip_cfg(
            replicas=2,
            gossip_H=0,
            replica_fault_plan=ReplicaFaultPlan(
                byzantine_replicas=(1,), stale_p=0.1
            ),
        )
        states = init_states(cfg, replica_seeds(cfg))
        meta = {"replicas": 2, "gossip_round": 3, "excluded": [0, 1]}
        path = tmp_path / "ckpt.npz"
        save_checkpoint(path, states, cfg, meta=meta)
        loaded, stored_cfg = load_checkpoint(path, cfg)
        assert stored_cfg == cfg  # incl. the nested ReplicaFaultPlan JSON
        assert read_checkpoint_meta(path) == meta
        for a, b in zip(jax.tree.leaves(loaded), jax.tree.leaves(states)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

        # rotate a second save, corrupt the primary, resume via .prev
        meta2 = dict(meta, gossip_round=4)
        save_checkpoint(path, states, cfg, meta=meta2)
        path.write_bytes(path.read_bytes()[: path.stat().st_size // 2])
        state_fb, _, loaded_path = load_checkpoint_with_fallback(path, cfg)
        assert str(loaded_path).endswith(".prev")
        assert read_checkpoint_meta(loaded_path)["gossip_round"] == 3
        for a, b in zip(jax.tree.leaves(state_fb), jax.tree.leaves(states)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_solo_checkpoint_still_loads_without_meta(self, tmp_path):
        from rcmarl_tpu.training.trainer import init_train_state
        from rcmarl_tpu.utils.checkpoint import (
            load_checkpoint,
            read_checkpoint_meta,
            save_checkpoint,
        )

        cfg = Config(**TINY3)
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        path = tmp_path / "solo.npz"
        save_checkpoint(path, state, cfg)
        assert read_checkpoint_meta(path) == {}
        loaded, _ = load_checkpoint(path, cfg)
        np.testing.assert_array_equal(
            np.asarray(loaded.block), np.asarray(state.block)
        )
