"""Test harness: force JAX onto a virtual 8-device CPU platform.

This is the idiomatic way to test pjit/shard_map/mesh code without real
TPU slices (SURVEY.md §4). Must run before jax is imported anywhere.

Environment gotchas (see .claude/skills/verify/SKILL.md):
- The machine presets JAX_PLATFORMS=axon (a real-TPU tunnel whose PJRT
  plugin is registered by a sitecustomize at interpreter start). We must
  both force JAX_PLATFORMS=cpu AND deregister the axon backend factory:
  initializing the axon plugin dials the tunnel and can block the whole
  process if the tunnel is unhealthy — tests must never depend on it.
- Single-core hosts: tests that EXECUTE cross-device collectives on the
  8-device virtual mesh are skipped there (``needs_multicore`` in
  tests/test_parallel.py) — XLA's in-process collective rendezvous can
  starve when the host cannot run the participants concurrently, and
  its AwaitAndLogIfStuck watchdog then CHECK-aborts the whole pytest
  process (reproduced solo: InProcessCommunicator::AllGather).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Belt and suspenders for subprocesses spawned by tests.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

try:  # deregister the axon PJRT plugin installed by sitecustomize
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # sitecustomize's register() may have snapshotted jax_platforms=axon
    # before this conftest ran; force it back.
    jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: OPT-IN via RCMARL_TEST_CACHE=1.
    # Caching the trainer compiles cuts repeat wall-clock ~3x, but
    # jaxlib 0.9.0's native executable serialize/deserialize SEGFAULTED
    # twice in full-suite runs (round 3: put_executable_and_time and,
    # after a timeout-killed run truncated an entry,
    # get_executable_and_time — rc=139), and a randomly-crashing suite
    # is worse than a slower deterministic one. Default is therefore no
    # persistent cache; developers iterating on one test file can export
    # RCMARL_TEST_CACHE=1 for fast warm reruns.
    if os.environ.get("RCMARL_TEST_CACHE") == "1":
        _repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        jax.config.update(
            "jax_compilation_cache_dir", os.path.join(_repo_root, ".jax_cache")
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:  # pragma: no cover - jax internals moved; env vars still apply
    pass


def host_cores() -> int:
    """Cores actually available to this process (affinity-aware on
    Linux; portable fallback elsewhere)."""
    if hasattr(os, "sched_getaffinity"):
        return len(os.sched_getaffinity(0))
    return os.cpu_count() or 1


import pytest  # noqa: E402

#: Tests that EXECUTE cross-device collectives on the virtual mesh need
#: real host parallelism: on a single core, XLA's in-process communicator
#: rendezvous can starve (all participants must arrive concurrently),
#: trip AwaitAndLogIfStuck, and CHECK-abort the whole pytest process
#: (reproduced solo: xla::cpu::InProcessCommunicator::AllGather).
#: Seed-axis-only sharding has zero collectives and is unaffected;
#: compiled-HLO collective tests only inspect lowering, never execute it.
needs_multicore = pytest.mark.skipif(
    host_cores() < 2,
    reason="multi-device collective EXECUTION deadlocks XLA's rendezvous "
    "watchdog on a single-core host",
)
