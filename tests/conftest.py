"""Test harness: force JAX onto a virtual 8-device CPU platform.

This is the idiomatic way to test pjit/shard_map/mesh code without real
TPU slices (SURVEY.md §4). Must run before jax is imported anywhere.

Environment gotchas (see .claude/skills/verify/SKILL.md):
- The machine presets JAX_PLATFORMS=axon (a real-TPU tunnel whose PJRT
  plugin is registered by a sitecustomize at interpreter start). We must
  both force JAX_PLATFORMS=cpu AND deregister the axon backend factory:
  initializing the axon plugin dials the tunnel and can block the whole
  process if the tunnel is unhealthy — tests must never depend on it.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Belt and suspenders for subprocesses spawned by tests.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

try:  # deregister the axon PJRT plugin installed by sitecustomize
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # sitecustomize's register() may have snapshotted jax_platforms=axon
    # before this conftest ran; force it back.
    jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: the suite is dominated by XLA compiles
    # of the jitted trainer programs (identical across runs), so caching
    # them cuts repeat wall-clock dramatically (VERDICT.md round-1
    # weakness 3). Keyed on HLO + flags; safe across processes.
    _repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jax.config.update(
        "jax_compilation_cache_dir", os.path.join(_repo_root, ".jax_cache")
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
except Exception:  # pragma: no cover - jax internals moved; env vars still apply
    pass
