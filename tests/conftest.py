"""Test harness: force JAX onto a virtual 8-device CPU platform.

This is the idiomatic way to test pjit/shard_map/mesh code without real
TPU slices (SURVEY.md §4). Must run before jax is imported anywhere.

Environment gotchas (see .claude/skills/verify/SKILL.md):
- The machine presets JAX_PLATFORMS=axon (a real-TPU tunnel whose PJRT
  plugin is registered by a sitecustomize at interpreter start). We must
  both force JAX_PLATFORMS=cpu AND deregister the axon backend factory:
  initializing the axon plugin dials the tunnel and can block the whole
  process if the tunnel is unhealthy — tests must never depend on it.
- Single-core hosts: tests that EXECUTE cross-device collectives on the
  8-device virtual mesh are skipped there (``needs_multicore`` in
  tests/test_parallel.py) — XLA's in-process collective rendezvous can
  starve when the host cannot run the participants concurrently, and
  its AwaitAndLogIfStuck watchdog then CHECK-aborts the whole pytest
  process (reproduced solo: InProcessCommunicator::AllGather).
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
# Belt and suspenders for subprocesses spawned by tests.
os.environ["PALLAS_AXON_POOL_IPS"] = ""
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

try:  # deregister the axon PJRT plugin installed by sitecustomize
    import jax
    import jax._src.xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    # sitecustomize's register() may have snapshotted jax_platforms=axon
    # before this conftest ran; force it back.
    jax.config.update("jax_platforms", "cpu")
    # Persistent compilation cache: OPT-IN via RCMARL_TEST_CACHE=1.
    # Caching the trainer compiles cuts repeat wall-clock ~3x, but
    # jaxlib 0.9.0's native executable serialize/deserialize SEGFAULTED
    # twice in full-suite runs (round 3: put_executable_and_time and,
    # after a timeout-killed run truncated an entry,
    # get_executable_and_time — rc=139), and a randomly-crashing suite
    # is worse than a slower deterministic one. Default is therefore no
    # persistent cache; developers iterating on one test file can export
    # RCMARL_TEST_CACHE=1 for fast warm reruns.
    if os.environ.get("RCMARL_TEST_CACHE") == "1":
        _repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get(
                "RCMARL_TEST_CACHE_DIR",
                os.path.join(_repo_root, ".jax_cache"),
            ),
        )
        # Persist everything: tier-1 is dominated by many sub-second
        # trainer compiles, and the default 1s floor would never cache
        # them (observed: 42 requests, 0 entries written).
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        # Hit/miss accounting for the CI wall-budget line: jax emits a
        # monitoring event per cache-eligible compile request and per
        # hit (jax._src.compiler); misses = requests - hits. Printed by
        # pytest_sessionfinish below as a greppable RCMARL_CACHE line.
        import jax.monitoring as _monitoring

        _CACHE_EVENTS = {
            "/jax/compilation_cache/cache_hits": 0,
            "/jax/compilation_cache/compile_requests_use_cache": 0,
        }

        def _count_cache_event(event: str, **kw) -> None:
            if event in _CACHE_EVENTS:
                _CACHE_EVENTS[event] += 1

        _monitoring.register_event_listener(_count_cache_event)
except Exception:  # pragma: no cover - jax internals moved; env vars still apply
    _CACHE_EVENTS = None
else:
    if os.environ.get("RCMARL_TEST_CACHE") != "1":
        _CACHE_EVENTS = None


def pytest_sessionfinish(session, exitstatus):
    """Print the persistent-compilation-cache tally when the cache is
    on (RCMARL_TEST_CACHE=1) — ci_tier1.sh greps this line into its
    tier-1 wall-budget report."""
    if _CACHE_EVENTS is None:
        return
    hits = _CACHE_EVENTS["/jax/compilation_cache/cache_hits"]
    reqs = _CACHE_EVENTS["/jax/compilation_cache/compile_requests_use_cache"]
    print(f"\nRCMARL_CACHE hits={hits} misses={max(reqs - hits, 0)}")


def host_cores() -> int:
    """Cores actually available to this process (affinity-aware on
    Linux; portable fallback elsewhere)."""
    if hasattr(os, "sched_getaffinity"):
        return len(os.sched_getaffinity(0))
    return os.cpu_count() or 1


import pytest  # noqa: E402

#: Tests that EXECUTE cross-device collectives on the virtual mesh need
#: real host parallelism: on a single core, XLA's in-process communicator
#: rendezvous can starve (all participants must arrive concurrently),
#: trip AwaitAndLogIfStuck, and CHECK-abort the whole pytest process
#: (reproduced solo: xla::cpu::InProcessCommunicator::AllGather).
#: Seed-axis-only sharding has zero collectives and is unaffected;
#: compiled-HLO collective tests only inspect lowering, never execute it.
needs_multicore = pytest.mark.skipif(
    host_cores() < 2,
    reason="multi-device collective EXECUTION deadlocks XLA's rendezvous "
    "watchdog on a single-core host",
)
