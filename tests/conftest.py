"""Test harness: force JAX onto a virtual 8-device CPU platform.

This is the idiomatic way to test pjit/shard_map/mesh code without real
TPU slices (SURVEY.md §4). Must run before jax is imported anywhere.
"""

import os

# Force, don't setdefault: the machine environment presets
# JAX_PLATFORMS=axon (the real-TPU tunnel) and tests must be
# deterministic on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
