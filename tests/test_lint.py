"""graftlint contract tests (rcmarl_tpu.lint).

Three pins:

1. **Fixture corpus** — every AST rule fires on its seeded-bad file
   under ``tests/lint_fixtures/``, on EXACTLY the lines the fixture
   marks with ``# RULE: <rule>`` (so false positives on the adjacent
   clean twins fail too), and the pragma escape silences a marked file.
2. **Package silence** — the installed package lints clean: the suite's
   own acceptance bar, which forced the real violations it found during
   development (training/update.py's magic fold_in tags) to be fixed.
3. **Runtime audits** — the retrace auditor proves exactly-once
   compilation for a guarded+faulted tiny run on both netstack arms
   (and catches a planted retrace); the donation audit proves the
   donated entry points' input->output aliasing survived to the
   compiled executable (xfail where the platform exposes no aliasing
   metadata); the backend purity/dtype audit passes over all six
   aggregation backends and both netstack epoch arms.
4. **Cost ledger + collective census** — AUDIT.jsonl round-trips
   canonically and byte-stably; a planted hidden-width regression and
   a planted host callback each trip their gate (`cost-regression` /
   `host-transfer`) at exactly the offending entry; sharded-program
   collective counts gate exactly and stay inside the enumerated
   pod-readiness set; and (slow) the full audits report zero findings
   against the COMMITTED ledger.
5. **Sharding arm + determinism census + contract** — compiled-SPMD
   sharding annotations parse per operand (planted replicated big
   operand → `sharding-replicated`; collective-feeds-collective →
   `sharding-reshard-chain`); the per-device memory ladder gates
   growth and failure-to-shrink (`device-memory-regression`); the
   nondeterministic-HLO walker fires on a planted float scatter-add
   and non-threefry RNG and stays silent on the committed programs
   (`nondeterminism`); and the Config⇄CLI⇄docs contract pass fires
   `contract-drift` at the exact config.py field line when a flag is
   removed, a field goes undocumented, or the JSON round-trip breaks.
6. **Kernel budget arm** — pure plan arithmetic (no backend): the
   committed DMA models re-derive EXACTLY from the BlockSpec grid
   arithmetic (fit within its documented 4·R·N loss-output residual);
   residency is exact on hand-computed tiny grids and monotone in
   every shape axis (hypothesis); planted cells — an oversized block,
   a 7-row f32 tile, a 1.5× drifted model — trip their rules at
   exactly the planted entry through the REAL `kernel_rows` pipeline;
   and the `kernel_budget` ledger rows round-trip byte-stably with the
   full cost-arm compare semantics (growth/fingerprint/stale fire,
   skipped is exempt, a feasible→infeasible flip fires the budget rule
   itself).
"""

from __future__ import annotations

import re
from pathlib import Path

import pytest

from rcmarl_tpu.lint import SOURCE_RULES, lint_file, run_source_lint

FIXTURES = Path(__file__).parent / "lint_fixtures"

_RULE_MARK = re.compile(r"#\s*RULE:\s*([\w\-]+)")


def _marked_lines(path: Path, rule: str) -> set:
    """Line numbers the fixture marks as violations of ``rule``."""
    return {
        lineno
        for lineno, text in enumerate(
            path.read_text().splitlines(), start=1
        )
        if (m := _RULE_MARK.search(text)) and m.group(1) == rule
    }


class TestSourceRules:
    """Each AST rule fires on its fixture — exactly where marked."""

    CASES = [
        ("bad_prng_reuse.py", False, "prng-reuse"),
        ("bad_prng_split_discard.py", False, "prng-split-discard"),
        ("bad_prng_int_seed.py", True, "prng-int-seed"),
        ("bad_prng_fold_tag.py", True, "prng-fold-tag"),
        ("bad_host_sync.py", True, "host-sync"),
        ("bad_host_block.py", True, "host-block"),
        ("bad_static_unhashable.py", False, "static-unhashable"),
    ]

    @pytest.mark.parametrize("fixture,hot,rule", CASES)
    def test_rule_fires_exactly_on_marked_lines(self, fixture, hot, rule):
        path = FIXTURES / fixture
        expected = _marked_lines(path, rule)
        assert expected, f"fixture {fixture} carries no # RULE: marks"
        findings = lint_file(path, hot_path=hot)
        got = {f.line for f in findings if f.rule == rule}
        assert got == expected, (
            f"{rule} fired on lines {sorted(got)}, fixture marks "
            f"{sorted(expected)} — a mismatch is a false "
            "positive/negative on the seeded corpus"
        )

    @pytest.mark.parametrize("fixture,hot,rule", CASES)
    def test_no_offrule_noise(self, fixture, hot, rule):
        """A fixture only demonstrates ITS rules: everything the file
        fires must be marked (some files legitimately mark several)."""
        path = FIXTURES / fixture
        findings = lint_file(path, hot_path=hot)
        for f in findings:
            assert f.line in _marked_lines(path, f.rule), (
                f"unmarked finding {f} — either mark the fixture line "
                "or fix the false positive"
            )

    def test_rule_ids_are_registered(self):
        for _, _, rule in self.CASES:
            assert rule in SOURCE_RULES

    def test_pragma_escape_silences(self):
        assert lint_file(FIXTURES / "pragma_ok.py", hot_path=True) == []

    def test_hot_path_rules_stay_out_of_host_modules(self):
        """The traced-code rules (host-sync, prng-int-seed) must NOT
        fire outside the hot-path scope — host orchestration fetches
        and mints keys legitimately."""
        findings = lint_file(FIXTURES / "bad_host_sync.py", hot_path=False)
        assert [f for f in findings if f.rule == "host-sync"] == []
        findings = lint_file(
            FIXTURES / "bad_prng_int_seed.py", hot_path=False
        )
        assert [f for f in findings if f.rule == "prng-int-seed"] == []


class TestPackageClean:
    def test_package_reports_zero_findings(self):
        findings = run_source_lint()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_cli_lint_exits_zero(self):
        from rcmarl_tpu.cli import main

        assert main(["lint"]) == 0


class TestRetraceAuditor:
    @pytest.mark.slow
    def test_exactly_once_compilation_both_arms(self):
        """The `lint --retrace` mode: guarded+faulted tiny runs on the
        dual and stacked (netstack+fitstack) arms plus a clean donated
        run compile nothing after their warmup block. The alternating
        f32/bf16 fused-fit case, the one-kernel-epoch case, AND the
        fused-serving/autoscale-resize cases ride the slow twin below
        and the CI graftlint cell.

        Rides the slow marker (46s; tier-1 870s wall budget): the
        round-16 shed compensating tests/test_pallas_serve.py +
        tests/test_autoscale.py joining tier-1 — ci_tier1.sh's
        graftlint cell runs the REAL `lint --retrace` audit (every
        case, fresh process) on every CI run, which subsumes this
        reduced-arm twin; the full suite (no -m filter) still runs
        both."""
        from rcmarl_tpu.lint.retrace import audit_retrace

        findings = audit_retrace(
            fitstack_dtypes=False, fused_epoch=False, fused_serve=False,
            gala=False, scanned_window=False,
        )
        assert findings == [], "\n".join(str(f) for f in findings)

    @pytest.mark.slow
    def test_exactly_once_compilation_alternating_dtypes(self):
        """The full audit incl. the alternating f32/bf16 fused-fit
        case (exactly one compile per compute_dtype, zero steady-state
        recompiles across alternation), the one-kernel-epoch case
        (the fused Pallas phase II + fit-scan kernel compile exactly
        once), and the fused-serving cases (hot-swaps/re-routes under
        the ONE-kernel serve program, autoscale resizes across
        already-seen batch shapes)."""
        from rcmarl_tpu.lint.retrace import audit_retrace

        findings = audit_retrace()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_planted_retrace_is_caught_and_named(self):
        from rcmarl_tpu.lint.retrace import RetraceAuditor, _tiny_cfg
        from rcmarl_tpu.training.trainer import train

        cfg = _tiny_cfg(False, False)
        train(cfg, n_episodes=cfg.n_ep_fixed)  # warm THIS config
        auditor = RetraceAuditor()
        with auditor.expect_no_compiles(context="planted H change"):
            # a different static config inside the steady-state window
            # is exactly the drift class the auditor exists for
            train(cfg.replace(H=0), n_episodes=cfg.n_ep_fixed)
        rules = {f.rule for f in auditor.findings}
        assert rules == {"retrace"}
        names = " ".join(f.message for f in auditor.findings)
        assert "train_block_donated" in names


class TestDonationAudit:
    """PR 3's donation can never silently rot: the compiled executables
    must keep the declared input->output buffer aliasing."""

    @pytest.fixture(scope="class")
    def report(self):
        from rcmarl_tpu.lint.donation import donation_report

        return donation_report()

    @pytest.mark.parametrize(
        "entry", ["update_block_donated", "train_block_donated"]
    )
    def test_donated_state_buffers_alias(self, report, entry):
        row = report[entry]
        if not row["has_metadata"]:
            pytest.xfail(
                "platform exposes no input_output_alias metadata in "
                "compiled HLO text; aliasing unverifiable here"
            )
        assert row["warnings"] == [], (
            f"{entry}: XLA warned donated buffers went unused: "
            f"{row['warnings']}"
        )
        assert row["alias_pairs"] >= row["expected_min"], (
            f"{entry}: {row['alias_pairs']} aliased pairs < "
            f"{row['expected_min']} parameter/optimizer leaves — the "
            "donation was dropped and the state is being copied"
        )

    def test_audit_donation_is_clean(self):
        from rcmarl_tpu.lint.donation import audit_donation

        findings, _notes = audit_donation()
        assert findings == [], "\n".join(str(f) for f in findings)


class TestCostLedger:
    """The compiled-cost gate (lint --cost): AUDIT.jsonl round-trips
    canonically, clean self-comparison, and the planted regressions
    trip at exactly the offending entry with a stable rule id."""

    @pytest.fixture(scope="class")
    def base_rows(self):
        from rcmarl_tpu.lint.configs import tiny_cfg
        from rcmarl_tpu.lint.cost import entry_cost_rows

        arms = {"dual": (tiny_cfg(netstack=False), False, ("update_block",))}
        rows, notes, skipped = entry_cost_rows(arms)
        assert notes == [] and skipped == set() and len(rows) == 1
        return rows

    def test_ledger_roundtrip_and_canonical_order(self, tmp_path):
        from rcmarl_tpu.lint.cost import (
            canonical_rows,
            read_ledger,
            write_ledger,
        )

        rows = [
            {"v": 1, "kind": "cost", "entry": "z", "metrics": {"b": 2, "a": 1}},
            {"kind": "collectives", "entry": "a", "v": 1},
            {"v": 1, "kind": "cost", "entry": "a", "metrics": {}},
        ]
        path = tmp_path / "AUDIT.jsonl"
        write_ledger(path, rows)
        back = read_ledger(path)
        assert back == canonical_rows(rows)
        assert [(r["kind"], r["entry"]) for r in back] == [
            ("collectives", "a"), ("cost", "a"), ("cost", "z"),
        ]
        # byte-stable: rewriting the read-back rows (any order) changes
        # nothing — the committed artifact never churns spuriously
        first = path.read_bytes()
        write_ledger(path, list(reversed(back)))
        assert path.read_bytes() == first
        assert read_ledger(tmp_path / "missing.jsonl") == []

    def test_self_comparison_is_clean(self, base_rows):
        from rcmarl_tpu.lint.cost import compare_cost

        findings, notes = compare_cost(base_rows, base_rows)
        assert findings == [] and notes == []

    def test_metric_growth_trips_exactly_the_entry(self, base_rows):
        import copy

        from rcmarl_tpu.lint.cost import compare_cost

        other = {
            "v": 1, "kind": "cost", "entry": "aggregation[xla]",
            "fingerprint": "f", "program": "p", "platform": "cpu",
            "jax": "x", "metrics": {"flops": 100.0},
        }
        fresh = copy.deepcopy(base_rows) + [copy.deepcopy(other)]
        fresh[0]["metrics"]["flops"] *= 1.10
        findings, _ = compare_cost(base_rows + [other], fresh)
        assert {f.rule for f in findings} == {"cost-regression"}
        assert len(findings) == 1 and "update_block@dual" in findings[0].message
        assert "flops" in findings[0].message

    def test_planted_hidden_width_regression(self, base_rows):
        """Widen one hidden layer and recompile: FLOPs/bytes grow, and
        the gate trips cost-regression at exactly the offending entry.
        (The fresh rows reuse the baseline fingerprint: a program-side
        cost change at the fixed canonical config — the drift class the
        metric gate owns; config-side changes are covered below.)"""
        from rcmarl_tpu.lint.configs import tiny_cfg
        from rcmarl_tpu.lint.cost import compare_cost, entry_cost_rows

        arms = {
            "dual": (
                tiny_cfg(netstack=False, hidden=(40, 20)),
                False,
                ("update_block",),
            )
        }
        fresh, _, _ = entry_cost_rows(arms)
        assert fresh[0]["metrics"]["flops"] > base_rows[0]["metrics"]["flops"]
        fresh[0]["fingerprint"] = base_rows[0]["fingerprint"]
        findings, _ = compare_cost(base_rows, fresh)
        assert findings, "widened hidden layer did not trip the cost gate"
        assert {f.rule for f in findings} == {"cost-regression"}
        assert all("update_block@dual" in f.message for f in findings)
        assert any("flops" in f.message for f in findings)

    def test_config_change_reports_unbaselined(self, base_rows):
        import copy

        from rcmarl_tpu.lint.cost import compare_cost

        fresh = copy.deepcopy(base_rows)
        fresh[0]["fingerprint"] = "somethingelse"
        findings, _ = compare_cost(base_rows, fresh)
        assert {f.rule for f in findings} == {"cost-unbaselined"}

    def test_missing_and_stale_rows_are_findings(self, base_rows):
        from rcmarl_tpu.lint.cost import compare_cost

        findings, _ = compare_cost([], base_rows)  # no baseline row
        assert {f.rule for f in findings} == {"cost-unbaselined"}
        findings, _ = compare_cost(base_rows, [])  # stale baseline row
        assert {f.rule for f in findings} == {"cost-unbaselined"}
        # ...but a row this host could not MEASURE is a note, not stale
        findings, _ = compare_cost(
            base_rows, [], skipped={base_rows[0]["entry"]}
        )
        assert findings == []


class TestCollectiveCensus:
    """lint --collectives: the sharded programs' communication stays
    the bounded enumerated set, counts gate exactly, and a planted
    host transfer trips with a stable rule id. Compile/inspect only —
    no collective ever executes, so single-core hosts are safe."""

    @pytest.fixture(scope="class")
    def rows(self):
        import jax

        from rcmarl_tpu.lint.collectives import _census_programs, census_rows

        if len(jax.devices()) < 4:
            pytest.skip("census needs >= 4 (virtual) devices")
        # the base seeds programs; the matrix program AND the
        # seeds@sharded+fitstack variant ride the slow committed-ledger
        # test and the CI graftlint cell (tier-1 wall budget)
        programs = {
            k: v
            for k, v in _census_programs().items()
            if k in ("seeds@unsharded", "seeds@sharded")
        }
        rows, findings, notes, skipped = census_rows(programs)
        assert findings == [] and notes == [] and skipped == set()
        return rows

    def test_seed_axis_has_zero_collectives(self, rows):
        by_entry = {r["entry"]: r for r in rows}
        assert by_entry["seeds@unsharded"]["collectives"] == {}

    def test_sharded_set_is_bounded_and_enumerated(self, rows):
        from rcmarl_tpu.lint.collectives import ALLOWED_COLLECTIVES

        sharded = {r["entry"]: r for r in rows}["seeds@sharded"]
        assert sharded["collectives"], "agent sharding produced no collectives"
        assert set(sharded["collectives"]) <= ALLOWED_COLLECTIVES
        assert sharded["host_transfers"] == 0

    def test_self_comparison_is_clean(self, rows):
        from rcmarl_tpu.lint.collectives import compare_census

        findings, notes = compare_census(rows, rows)
        assert findings == [] and notes == []

    def test_census_counts_async_tuple_typed_ops(self):
        """TPU lowers collectives to async pairs whose -start op (and
        infeed) carry TUPLE result types with internal whitespace; the
        census must count the -start exactly once and must not count
        the -done or operand references."""
        from rcmarl_tpu.lint.collectives import (
            collective_census,
            host_transfer_ops,
        )

        hlo = "\n".join([
            "  %ags = (f32[2]{0}, f32[8]{0}) all-gather-start(f32[2]{0}"
            " %p), replica_groups={}, dimensions={0}",
            "  %agd = f32[8]{0} all-gather-done((f32[2]{0}, f32[8]{0})"
            " %ags)",
            "  %ar = f32[4]{0} all-reduce(f32[4]{0} %q), to_apply=%add",
            "  %if = ((f32[4]{0}), token[]) infeed(token[] %tok)",
        ])
        assert collective_census(hlo) == {"all-gather": 1, "all-reduce": 1}
        assert len(host_transfer_ops(hlo)) == 1

    def test_count_drift_trips_exactly_the_entry(self, rows):
        import copy

        from rcmarl_tpu.lint.collectives import compare_census

        fresh = copy.deepcopy(rows)
        for r in fresh:
            if r["entry"] == "seeds@sharded":
                r["collectives"]["all-reduce"] = (
                    r["collectives"].get("all-reduce", 0) + 1
                )
        findings, _ = compare_census(rows, fresh)
        assert {f.rule for f in findings} == {"collective-census"}
        assert len(findings) == 1 and "seeds@sharded" in findings[0].message

    def test_planted_host_callback_trips_host_transfer(self):
        """The runtime twin of the AST host-sync rule: a spurious
        device->host pull (a host callback, the only way one survives
        into a compiled program) must trip `host-transfer` at exactly
        the planted entry."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from rcmarl_tpu.lint.collectives import census_rows, host_transfer_ops

        def planted(x):
            y = jax.pure_callback(
                lambda v: np.asarray(v), jax.ShapeDtypeStruct((4,), jnp.float32), x
            )
            return y + 1.0

        lowered = jax.jit(planted).lower(jnp.ones(4, jnp.float32))
        assert host_transfer_ops(lowered.compile().as_text())
        programs = {
            "seeds@planted": (lambda: lowered, 1, {"seed": 1, "agent": 1}, True)
        }
        rows, findings, notes, _skipped = census_rows(programs)
        assert notes == []
        assert any(f.rule == "host-transfer" for f in findings)
        assert all("seeds@planted" in f.message for f in findings)
        assert rows[0]["host_transfers"] >= 1


class TestShardingAudit:
    """lint --sharding (ledger half): big-operand sharding annotations
    parse off compiled SPMD text, planted replication/reshard-chain/
    memory regressions each trip their rule, and the real sharded
    compiles ride the slow committed-ledger test + the CI graftlint
    cell (tier-1 wall budget)."""

    HLO = "\n".join([
        '  %p0 = f32[2,2000,2,2]{3,2,1,0} parameter(0), '
        'sharding={replicated}, metadata={op_name="s.buffer.s"}',
        '  %p1 = f32[2,2,20,20]{3,2,1,0} parameter(1), '
        'sharding={devices=[1,2,1,1]<=[2]}, '
        'metadata={op_name="s.params.critic[1][0]"}',
        '  %p2 = s32[2]{0} parameter(2), sharding={replicated}, '
        'metadata={op_name="s.buffer.ptr"}',
        '  %p3 = f32[8,1024]{1,0} parameter(3), '
        'sharding={maximal device=0}, metadata={op_name="s.desired"}',
    ])

    def test_sharded_parameter_parsing(self):
        from rcmarl_tpu.lint.sharding import sharded_parameters

        params = {p["path"]: p for p in sharded_parameters(self.HLO)}
        assert params["s.buffer.s"]["kind"] == "replicated"
        assert params["s.buffer.s"]["bytes"] == 2 * 2000 * 2 * 2 * 4
        assert params["s.params.critic[1][0]"]["kind"] == "sharded"
        assert params["s.desired"]["kind"] == "maximal"

    def test_replicated_big_operands_respect_threshold(self):
        """Big replicated + big maximal flagged; the small replicated
        ring pointer and the properly sharded leaf are not."""
        from rcmarl_tpu.lint.sharding import replicated_big_operands

        flagged = {p["path"] for p in replicated_big_operands(self.HLO)}
        assert flagged == {"s.buffer.s", "s.desired"}

    def test_reshard_chain_detector(self):
        """A collective fed (through a -done alias and a copy) by
        another collective's result is a chain; independent collectives
        and plain -start/-done pairs are not."""
        from rcmarl_tpu.lint.sharding import reshard_chains

        clean = "\n".join([
            "  %ags = (f32[2]{0}, f32[8]{0}) all-gather-start(f32[2]{0}"
            " %p), dimensions={0}",
            "  %agd = f32[8]{0} all-gather-done((f32[2]{0}, f32[8]{0})"
            " %ags)",
            "  %ar = f32[4]{0} all-reduce(f32[4]{0} %q), to_apply=%add",
        ])
        assert reshard_chains(clean) == []
        chained = clean + "\n" + "\n".join([
            "  %cp = f32[8]{0} copy(f32[8]{0} %agd)",
            "  %ar2 = f32[8]{0} all-reduce(f32[8]{0} %cp), to_apply=%add",
        ])
        hits = reshard_chains(chained)
        assert len(hits) == 1 and "all-reduce" in hits[0]

    def test_planted_replicated_program_fires(self):
        """A big operand deliberately lowered with a fully-replicated
        in_sharding under a 2-device mesh must trip sharding-replicated
        at exactly the planted entry."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from rcmarl_tpu.lint.configs import tiny_cfg
        from rcmarl_tpu.lint.sharding import sharding_rows

        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 (virtual) devices")
        from rcmarl_tpu.parallel.seeds import make_mesh

        build = lambda mesh: jax.jit(
            lambda x: x * 2.0,
            in_shardings=(NamedSharding(mesh, P()),),
        ).lower(jnp.ones((64, 64), jnp.float32))
        programs = {
            "seeds@planted": (
                tiny_cfg(), lambda n: make_mesh(n, seed_axis=1), build,
            )
        }
        rows, findings, notes, _skipped = sharding_rows(
            programs, mesh_points=(2,)
        )
        assert notes == []
        assert {f.rule for f in findings} == {"sharding-replicated"}
        assert all("seeds@planted" in f.message for f in findings)
        assert rows[0]["mesh"] == {"seed": 1, "agent": 2}
        assert rows[0]["mesh_fingerprint"] == "2d:seed=1,agent=2"

    @staticmethod
    def _row(entry, mesh, peak, arg=1000.0):
        return {
            "v": 1, "kind": "device_memory", "entry": f"{entry}@mesh{mesh}",
            "fingerprint": "f", "program": "p",
            "mesh_fingerprint": f"{mesh}d:seed=1,agent={mesh}",
            "mesh": {"seed": 1, "agent": mesh}, "platform": "cpu",
            "jax": "x",
            "metrics": {
                "argument_bytes": arg / mesh, "output_bytes": 10.0,
                "temp_bytes": 10.0, "alias_bytes": 0.0,
                "peak_bytes": peak,
            },
        }

    def test_planted_per_device_growth_fires_shrink_invariant(self):
        """Per-device peak that FAILS to shrink across the mesh ladder
        (the replication signature) trips device-memory-regression with
        no baseline involved; a shrinking ladder stays clean."""
        from rcmarl_tpu.lint.sharding import shrink_findings

        good = [
            self._row("seeds@sharded", 1, 8000.0),
            self._row("seeds@sharded", 2, 4600.0),
            self._row("seeds@sharded", 8, 1500.0),
        ]
        assert shrink_findings(good) == []
        flat = [
            self._row("seeds@sharded", 1, 8000.0),
            self._row("seeds@sharded", 2, 8000.0),
            self._row("seeds@sharded", 8, 8100.0),
        ]
        findings = shrink_findings(flat)
        assert findings and {f.rule for f in findings} == {
            "device-memory-regression"
        }
        assert any("fails to shrink" in f.message for f in findings)

    def test_compare_device_memory_gate(self):
        """The ledger gate: self-comparison clean; planted per-device
        peak growth trips device-memory-regression at exactly the
        entry; a missing row is cost-unbaselined; a row this host
        skipped is exempt from the stale check."""
        import copy

        from rcmarl_tpu.lint.sharding import compare_device_memory

        base = [self._row("seeds@sharded", 8, 1500.0)]
        findings, notes = compare_device_memory(base, base)
        assert findings == [] and notes == []
        fresh = copy.deepcopy(base)
        fresh[0]["metrics"]["peak_bytes"] *= 1.10
        findings, _ = compare_device_memory(base, fresh)
        assert {f.rule for f in findings} == {"device-memory-regression"}
        assert len(findings) == 1
        assert "seeds@sharded@mesh8" in findings[0].message
        findings, _ = compare_device_memory([], fresh)
        assert {f.rule for f in findings} == {"cost-unbaselined"}
        findings, _ = compare_device_memory(base, [])
        assert {f.rule for f in findings} == {"cost-unbaselined"}
        findings, _ = compare_device_memory(
            base, [], skipped={base[0]["entry"]}
        )
        assert findings == []


class TestDeterminismCensus:
    """lint --sharding (census half): the nondeterministic-HLO walker
    fires on planted hazards and stays silent on the deterministic
    committed programs."""

    def test_planted_nondeterministic_scatter_fires(self):
        """A float scatter-add with duplicate-capable indices
        (unique_indices=false) — the accumulation-order hazard the
        bitwise pinning discipline cannot survive — must fire."""
        import jax
        import jax.numpy as jnp

        from rcmarl_tpu.lint.sharding import nondeterministic_ops

        low = jax.jit(lambda x, i, v: x.at[i].add(v)).lower(
            jnp.ones(8, jnp.float32),
            jnp.array([1, 1, 2]),
            jnp.ones(3, jnp.float32),
        )
        hits = nondeterministic_ops(low.as_text(), compiled=False)
        assert hits and all("scatter" in h for h in hits)

    def test_overwrite_scatter_is_clean(self):
        """The replay-ring writes (.at[idx].set) carry no float
        accumulation — order-safe, must NOT fire."""
        import jax
        import jax.numpy as jnp

        from rcmarl_tpu.lint.sharding import nondeterministic_ops

        low = jax.jit(lambda x, v: x.at[jnp.arange(3)].set(v)).lower(
            jnp.ones((8, 4), jnp.float32), jnp.ones((3, 4), jnp.float32)
        )
        assert nondeterministic_ops(low.as_text(), compiled=False) == []

    def test_rng_and_collective_text_rules(self):
        from rcmarl_tpu.lint.sharding import nondeterministic_ops

        fires = (
            "%o, %s = stablehlo.rng_bit_generator %k, algorithm = "
            " DEFAULT : (tensor<2xui64>) -> (tensor<2xui64>, "
            "tensor<4xui32>)"
        )
        assert nondeterministic_ops(fires, compiled=False)
        threefry = fires.replace("DEFAULT", "THREE_FRY")
        assert nondeterministic_ops(threefry, compiled=False) == []
        legacy = "  %r = f32[4]{0} rng(f32[] %a, f32[] %b), distribution=rng_uniform"
        assert nondeterministic_ops(legacy, compiled=True)
        bad_coll = (
            "  %cb = f32[4]{0} collective-broadcast(f32[4]{0} %x), "
            "replica_groups={}"
        )
        assert nondeterministic_ops(bad_coll, compiled=True)
        ok_coll = (
            "  %ar = f32[4]{0} all-reduce(f32[4]{0} %x), to_apply=%add"
        )
        assert nondeterministic_ops(ok_coll, compiled=True) == []

    def test_update_block_lowering_is_clean(self):
        """The actor phase's label gather keeps a deterministic
        backward (ops/losses.py one-hot custom_vjp): the dual
        update-block lowering carries zero hazards. The full walk
        (every arm + aggregation backends + compiled sharded modules)
        rides the slow committed-ledger test and the CI cell."""
        from rcmarl_tpu.lint.configs import tiny_cfg
        from rcmarl_tpu.lint.sharding import nondeterministic_ops
        from rcmarl_tpu.utils.profiling import lowered_entry_points

        low = lowered_entry_points(
            tiny_cfg(netstack=False), False, ("update_block",)
        )["update_block"]
        assert nondeterministic_ops(low.as_text(), compiled=False) == []


class TestContract:
    """lint --contract: the Config⇄CLI⇄docs regression net."""

    def test_committed_tree_is_clean(self):
        from rcmarl_tpu.lint.contract import audit_contract

        findings, notes = audit_contract()
        assert findings == [], "\n".join(str(f) for f in findings)
        assert notes == []

    def test_roundtrip_is_clean(self):
        from rcmarl_tpu.lint.contract import roundtrip_drift

        assert roundtrip_drift() == []

    def test_removed_cli_flag_fires_at_the_field_line(self):
        """Hard-coding a Config keyword (the residue of a deleted flag)
        must fire contract-drift anchored at that field's config.py
        declaration line."""
        from pathlib import Path

        import rcmarl_tpu.cli as cli_mod
        from rcmarl_tpu.lint.contract import (
            audit_contract,
            config_field_lines,
        )

        source = Path(cli_mod.__file__).read_text()
        assert "gamma=args.gamma," in source, "fixture went stale"
        doctored = source.replace("gamma=args.gamma,", "gamma=0.9,")
        findings, _ = audit_contract(cli_source=doctored)
        hits = [f for f in findings if "Config.gamma" in f.message]
        assert len(hits) == 1 and hits[0].rule == "contract-drift"
        assert hits[0].line == config_field_lines()["gamma"]
        assert hits[0].path == "rcmarl_tpu/config.py"

    def test_undocumented_field_fires(self):
        """A docs table naming only one field flags every other field
        (an EMPTY/missing docs file is a note, not a finding storm)."""
        from rcmarl_tpu.lint.contract import audit_contract

        findings, _ = audit_contract(
            api_md_text="only `n_agents` is documented here"
        )
        assert findings and {f.rule for f in findings} == {"contract-drift"}
        assert any(
            "Config.H does not appear" in f.message for f in findings
        )
        assert not any("Config.n_agents" in f.message for f in findings)
        _, notes = audit_contract(api_md_text="no backticks at all")
        assert any("unverifiable" in n for n in notes)

    def test_stale_exemption_fires(self):
        """An exemption naming no current field is itself drift."""
        from unittest import mock

        import rcmarl_tpu.lint.contract as contract

        with mock.patch.dict(
            contract.CLI_EXEMPT, {"no_such_field": "ghost"}
        ):
            findings, _ = contract.audit_contract()
        assert any(
            f.rule == "contract-drift" and "no_such_field" in f.message
            for f in findings
        )


def _planted_plan(
    name="planted",
    grid=(3,),
    block=(8, 128),
    dtype="float32",
    tiled_dims=(0, 1),
    smem_shape=None,
    scratch_shape=None,
):
    """A hand-built KernelPlan for the planted-regression cells: one
    pipelined in/out pair, optional scalar-prefetch + scratch."""
    from rcmarl_tpu.ops.dma_model import BlockOperand, KernelPlan

    inputs = [
        BlockOperand("x", block, dtype, (True,), tiled_dims=tiled_dims)
    ]
    if smem_shape is not None:
        inputs.append(
            BlockOperand(
                "sched", smem_shape, "int32", (False,), memory="smem"
            )
        )
    scratch = (
        (BlockOperand("acc", scratch_shape, "float32", (False,)),)
        if scratch_shape is not None
        else ()
    )
    return KernelPlan(
        name=name,
        grid=grid,
        inputs=tuple(inputs),
        outputs=(BlockOperand("o", block, dtype, (True,)),),
        scratch=scratch,
    )


def _planted_cell(entry, plan, model=None, must_fit=True, steps=()):
    from rcmarl_tpu.lint.kernels import KernelCell

    return KernelCell(entry, tuple(steps), must_fit, lambda: (plan, model))


class TestKernelPlans:
    """The committed ``*_dma_bytes`` models are DERIVED, not asserted:
    each one re-derives from its kernel's ``kernel_plan()`` BlockSpec
    grid arithmetic — exactly for consensus (dense + sparse) and
    serve (solo + fleet), and within the documented 4·R·N loss-output
    residual for the fit scan."""

    def test_consensus_models_rederive_exactly(self):
        from rcmarl_tpu.ops import pallas_consensus
        from rcmarl_tpu.ops.dma_model import (
            consensus_model_bytes,
            plan_dma_bytes,
            sparse_consensus_model_bytes,
        )

        for n, n_in, trunk, faulted in [
            (5, 3, 100, False),
            (16, 16, 840, True),
            (64, 8, 3200, True),
        ]:
            plan = pallas_consensus.kernel_plan(
                n, n_in, trunk,
                active=faulted, has_stale=faulted, sanitize=faulted,
            )
            model = consensus_model_bytes(
                n, n_in, trunk, active=faulted, has_stale=faulted
            )
            assert plan_dma_bytes(plan) == model, (n, n_in, trunk, faulted)
        for n, deg, trunk in [(8, 3, 200), (256, 9, 5000)]:
            plan = pallas_consensus.kernel_plan(
                n, deg, trunk, sparse=True
            )
            model = sparse_consensus_model_bytes(n, deg, trunk)
            assert plan_dma_bytes(plan) == model, (n, deg, trunk)

    def test_serve_models_rederive_exactly(self):
        from rcmarl_tpu.lint.kernels import kernel_cells
        from rcmarl_tpu.ops.dma_model import plan_dma_bytes

        cells = {
            c.entry: c
            for c in kernel_cells()
            if c.entry.startswith(("fused_serve", "fused_fleet"))
        }
        assert len(cells) == 4  # tiny solo, tiny fleet, ref5 solo+fleet
        for entry, cell in cells.items():
            plan, model = cell.build()
            assert plan_dma_bytes(plan) == model, entry

    def test_fit_model_residual_is_the_loss_output(self):
        """The fit model's only gap from the derivation is the
        ``(R, N)`` first-epoch-loss output — 4·R·N bytes exactly, well
        under the drift rule's absolute slack."""
        from rcmarl_tpu.lint.kernels import KERNEL_DRIFT_ABS_SLACK
        from rcmarl_tpu.ops import pallas_fit
        from rcmarl_tpu.ops.dma_model import plan_dma_bytes
        from rcmarl_tpu.utils.profiling import (
            coop_fit_row_structs,
            fit_row_structs,
        )
        from rcmarl_tpu.lint.configs import tiny_cfg, tiny_mixed_cfg

        for structs in (
            fit_row_structs(tiny_mixed_cfg()),
            coop_fit_row_structs(tiny_cfg()),
        ):
            _, params_rows, x_rows, targets_rows, schedule = structs
            plan = pallas_fit.kernel_plan(
                params_rows, x_rows, targets_rows, schedule
            )
            model = pallas_fit.fit_scan_hbm_bytes(
                params_rows, x_rows, targets_rows, schedule, resident=True
            )
            import jax

            rows, n_agents = jax.tree.leaves(params_rows)[0].shape[:2]
            gap = plan_dma_bytes(plan) - model
            assert gap == 4.0 * rows * n_agents
            assert gap < KERNEL_DRIFT_ABS_SLACK


class TestKernelResidency:
    """The residency arithmetic itself: exact on hand-computed tiny
    grids, monotone in every shape axis (hypothesis twin)."""

    def test_hand_computed_dense_consensus(self):
        """n=2 agents, n_in=3, trunk=100, H=1, block_rows=8: one
        1024-column tile → grid (1,), no double-buffer. Blocks are
        (2, 8, 128) f32 = 8192 B each; scratch live set is
        n_in + 2·(H+1) + 1 = 8 rows of (8, 128) f32 = 32768 B."""
        from rcmarl_tpu.lint.kernels import (
            plan_smem_bytes,
            plan_vmem_bytes,
        )
        from rcmarl_tpu.ops import pallas_consensus
        from rcmarl_tpu.ops.dma_model import plan_dma_bytes

        plan = pallas_consensus.kernel_plan(2, 3, 100, trim_h=1)
        assert plan.grid == (1,)
        assert plan_vmem_bytes(plan) == 8192 + 8192 + 32768
        assert plan_smem_bytes(plan) == 0
        assert plan_dma_bytes(plan) == 8192 + 8192

    def test_hand_computed_multi_tile_double_buffers(self):
        """trunk=3000 pads to 3072 → grid (3,): the pipelined blocks
        double (Mosaic overlaps tile i compute with tile i+1 DMA),
        scratch stays single; traffic is per-step. The sparse twin adds
        one (N, degree) int32 scalar-prefetch block, resident in SMEM
        and DMAd once."""
        from rcmarl_tpu.lint.kernels import (
            plan_smem_bytes,
            plan_vmem_bytes,
        )
        from rcmarl_tpu.ops import pallas_consensus
        from rcmarl_tpu.ops.dma_model import plan_dma_bytes

        plan = pallas_consensus.kernel_plan(2, 3, 3000, trim_h=1)
        assert plan.grid == (3,)
        assert plan_vmem_bytes(plan) == 2 * (8192 + 8192) + 32768
        assert plan_dma_bytes(plan) == 3 * (8192 + 8192)
        sparse = pallas_consensus.kernel_plan(
            2, 3, 3000, sparse=True, trim_h=1
        )
        assert plan_smem_bytes(sparse) == 2 * 3 * 4
        assert plan_dma_bytes(sparse) == 3 * (8192 + 8192) + 2 * 3 * 4

    def test_residency_monotone_deterministic_sweep(self):
        """The hypothesis property's always-on twin: a fixed lattice of
        shapes, each axis bumped in turn — residency never shrinks."""
        import itertools

        from rcmarl_tpu.lint.kernels import plan_vmem_bytes
        from rcmarl_tpu.ops import pallas_consensus

        def vmem(n, n_in, trunk, h):
            return plan_vmem_bytes(
                pallas_consensus.kernel_plan(
                    n, n_in, trunk,
                    active=True, has_stale=True, sanitize=True, trim_h=h,
                )
            )

        lattice = itertools.product(
            (2, 16, 64), (3, 9), (100, 1024, 5000), (0, 1, 4)
        )
        for n, n_in, trunk, h in lattice:
            if 2 * h + 1 > n_in:
                continue
            base = vmem(n, n_in, trunk, h)
            assert vmem(n + 1, n_in, trunk, h) >= base
            assert vmem(n, n_in + 1, trunk, h) >= base
            assert vmem(n, n_in, trunk + 1, h) >= base
            if 2 * (h + 1) + 1 <= n_in:
                assert vmem(n, n_in, trunk, h + 1) >= base

    def test_residency_monotone_in_every_axis(self):
        """Growing any shape axis — agents, fan-in, trunk columns, the
        trim parameter — never SHRINKS per-grid-step residency."""
        hypothesis = pytest.importorskip("hypothesis")
        from hypothesis import given, settings
        from hypothesis import strategies as st

        from rcmarl_tpu.lint.kernels import plan_vmem_bytes
        from rcmarl_tpu.ops import pallas_consensus

        def vmem(n, n_in, trunk, h):
            return plan_vmem_bytes(
                pallas_consensus.kernel_plan(
                    n, n_in, trunk,
                    active=True, has_stale=True, sanitize=True, trim_h=h,
                )
            )

        @settings(max_examples=80, deadline=None)
        @given(
            n=st.integers(2, 64),
            n_in=st.integers(3, 16),
            trunk=st.integers(1, 8000),
            h=st.integers(0, 4),
            bump=st.sampled_from(["n", "n_in", "trunk", "h"]),
        )
        def check(n, n_in, trunk, h, bump):
            hypothesis.assume(2 * h + 1 <= n_in)
            base = vmem(n, n_in, trunk, h)
            grown = dict(n=n, n_in=n_in, trunk=trunk, h=h)
            grown[bump] += 1
            if bump == "h":
                hypothesis.assume(2 * grown["h"] + 1 <= n_in)
            assert (
                vmem(grown["n"], grown["n_in"], grown["trunk"], grown["h"])
                >= base
            )

        check()


class TestKernelBudgetAudit:
    """Planted kernel regressions through the REAL ``kernel_rows``
    pipeline (the ``cells`` override), plus the full ledger-compare
    semantics on ``kernel_budget`` rows."""

    @pytest.fixture(scope="class")
    def base_rows(self):
        from rcmarl_tpu.lint.kernels import kernel_rows

        cells = [
            _planted_cell(
                "planted[ok]",
                _planted_plan(scratch_shape=(16, 128)),
                model=None,
                must_fit=True,
            ),
            _planted_cell(
                "planted[session]",
                _planted_plan(grid=(5,), smem_shape=(4, 2)),
                model=None,
                must_fit=False,
                steps=("99",),
            ),
        ]
        rows, findings, notes, skipped = kernel_rows(cells=cells)
        assert findings == [] and notes == [] and skipped == set()
        assert len(rows) == 6  # 2 cells x 3 generations
        return rows

    def test_rows_are_feasible_and_tagged(self, base_rows):
        by_entry = {r["entry"]: r for r in base_rows}
        assert all(r["verdict"] == "feasible" for r in base_rows)
        assert by_entry["planted[session]@v4"]["steps"] == ["99"]
        assert by_entry["planted[ok]@v4"]["must_fit"] is True
        # one fingerprint per CELL, shared across its generation rows
        assert len({r["fingerprint"] for r in base_rows}) == 2

    def test_oversized_block_fires_vmem_budget(self):
        """A (4200, 8, 128) f32 block double-buffers past the v4
        16 MiB VMEM budget on a must-fit cell — `kernel-vmem-budget`
        at exactly the planted entry, and an honest `infeasible`
        verdict in the v4 row."""
        from rcmarl_tpu.lint.kernels import kernel_rows

        cell = _planted_cell(
            "planted[oversized]",
            _planted_plan(block=(4200, 8, 128), tiled_dims=(1, 2)),
        )
        rows, findings, notes, _ = kernel_rows(cells=[cell])
        assert {f.rule for f in findings} == {"kernel-vmem-budget"}
        assert len(findings) == 1
        assert "planted[oversized]" in findings[0].message
        by_entry = {r["entry"]: r for r in rows}
        assert by_entry["planted[oversized]@v4"]["verdict"] == "infeasible"
        assert by_entry["planted[oversized]@v5e"]["verdict"] == "feasible"

    def test_oversized_smem_fires_smem_budget(self):
        from rcmarl_tpu.lint.kernels import kernel_rows

        cell = _planted_cell(
            "planted[smem]",
            _planted_plan(smem_shape=(600, 600)),  # 1.37 MiB > 1 MiB
        )
        _, findings, _, _ = kernel_rows(cells=[cell])
        assert {f.rule for f in findings} == {"kernel-smem-budget"}
        assert "planted[smem]" in findings[0].message

    def test_session_cell_infeasibility_is_a_note_not_a_finding(self):
        """The verdict-vs-finding split: a SESSION shape over budget is
        an honest ledger verdict + a note naming its step tags (the
        preflight's abort signal) — not a lint failure."""
        from rcmarl_tpu.lint.kernels import kernel_rows

        cell = _planted_cell(
            "planted[bigsession]",
            _planted_plan(block=(4200, 8, 128), tiled_dims=(1, 2)),
            must_fit=False,
            steps=("14",),
        )
        rows, findings, notes, _ = kernel_rows(cells=[cell])
        assert findings == []
        assert len(notes) == 1 and "14" in notes[0]
        assert {r["entry"]: r["verdict"] for r in rows}[
            "planted[bigsession]@v4"
        ] == "infeasible"

    def test_seven_row_tile_fires_misaligned(self):
        """A chosen 7-row f32 tile violates the (8, 128) packing
        quantum at the sublane position; a problem-determined 7-wide
        dim (not in tiled_dims) must NOT fire."""
        from rcmarl_tpu.lint.kernels import kernel_rows

        bad = _planted_cell(
            "planted[badtile]", _planted_plan(block=(7, 128))
        )
        _, findings, _, _ = kernel_rows(cells=[bad])
        assert {f.rule for f in findings} == {"kernel-tile-misaligned"}
        assert "sublane" in findings[0].message
        ok = _planted_cell(
            "planted[problemdim]",
            _planted_plan(block=(7, 128), tiled_dims=(1,)),
        )
        _, findings, _, _ = kernel_rows(cells=[ok])
        assert findings == []

    def test_bf16_tile_quantum_is_sixteen(self):
        from rcmarl_tpu.lint.kernels import kernel_rows

        cell = _planted_cell(
            "planted[bf16]", _planted_plan(block=(8, 128), dtype="bfloat16")
        )
        _, findings, _, _ = kernel_rows(cells=[cell])
        assert {f.rule for f in findings} == {"kernel-tile-misaligned"}
        ok = _planted_cell(
            "planted[bf16ok]",
            _planted_plan(block=(16, 128), dtype="bfloat16"),
        )
        _, findings, _, _ = kernel_rows(cells=[ok])
        assert findings == []

    def test_drifted_model_fires_drift(self):
        """Scale the committed model 1.5× off the derivation:
        `kernel-dma-model-drift` at exactly the planted entry, both
        directions."""
        from rcmarl_tpu.ops.dma_model import plan_dma_bytes
        from rcmarl_tpu.lint.kernels import kernel_rows

        plan = _planted_plan(grid=(64,))
        derived = plan_dma_bytes(plan)
        assert derived * 0.5 > 4096  # clear of the absolute slack
        for factor in (1.5, 0.5):
            cell = _planted_cell(
                "planted[drift]", plan, model=derived * factor
            )
            _, findings, _, _ = kernel_rows(cells=[cell])
            assert {f.rule for f in findings} == {
                "kernel-dma-model-drift"
            }, factor
            assert "planted[drift]" in findings[0].message
        exact = _planted_cell("planted[exact]", plan, model=derived)
        _, findings, _, _ = kernel_rows(cells=[exact])
        assert findings == []

    def test_underivable_cell_is_note_plus_skip_never_pass(self):
        from rcmarl_tpu.lint.kernels import KernelCell, kernel_rows

        def boom():
            raise ValueError("no such shape")

        cell = KernelCell("planted[broken]", (), True, boom)
        rows, findings, notes, skipped = kernel_rows(cells=[cell])
        assert rows == [] and findings == []
        assert len(notes) == 1 and "planted[broken]" in notes[0]
        assert skipped == {
            "planted[broken]@v4",
            "planted[broken]@v5e",
            "planted[broken]@v5p",
        }

    def test_ledger_roundtrip_is_byte_stable(self, base_rows, tmp_path):
        from rcmarl_tpu.lint.cost import (
            canonical_rows,
            read_ledger,
            write_ledger,
        )

        path = tmp_path / "AUDIT.jsonl"
        write_ledger(path, base_rows)
        back = read_ledger(path)
        assert back == canonical_rows(base_rows)
        first = path.read_bytes()
        write_ledger(path, list(reversed(back)))
        assert path.read_bytes() == first

    def test_self_comparison_is_clean(self, base_rows):
        from rcmarl_tpu.lint.kernels import compare_kernels

        findings, notes = compare_kernels(base_rows, base_rows)
        assert findings == [] and notes == []

    def test_metric_growth_trips_exactly_the_entry(self, base_rows):
        import copy

        from rcmarl_tpu.lint.kernels import compare_kernels

        fresh = copy.deepcopy(base_rows)
        for r in fresh:
            if r["entry"] == "planted[ok]@v4":
                r["metrics"]["vmem_bytes"] *= 1.10
        findings, _ = compare_kernels(base_rows, fresh)
        assert {f.rule for f in findings} == {"kernel-budget-regression"}
        assert len(findings) == 1
        assert "planted[ok]@v4" in findings[0].message
        assert "vmem_bytes" in findings[0].message
        # ...and a SHRINK is a note, not a finding
        fresh = copy.deepcopy(base_rows)
        fresh[0]["metrics"]["dma_derived_bytes"] *= 0.5
        findings, notes = compare_kernels(base_rows, fresh)
        assert findings == [] and len(notes) == 1

    def test_fingerprint_change_reports_regression(self, base_rows):
        import copy

        from rcmarl_tpu.lint.kernels import compare_kernels

        fresh = copy.deepcopy(base_rows)
        fresh[0]["fingerprint"] = "somethingelse"
        findings, _ = compare_kernels(base_rows, fresh)
        assert {f.rule for f in findings} == {"kernel-budget-regression"}
        assert "fingerprint" in findings[0].message

    def test_missing_stale_and_skipped_rows(self, base_rows):
        from rcmarl_tpu.lint.kernels import compare_kernels

        findings, _ = compare_kernels([], base_rows)  # unbaselined
        assert {f.rule for f in findings} == {"kernel-budget-regression"}
        assert len(findings) == len(base_rows)
        findings, _ = compare_kernels(base_rows, [])  # stale
        assert {f.rule for f in findings} == {"kernel-budget-regression"}
        # ...but rows this host could not DERIVE are exempt, not stale
        findings, _ = compare_kernels(
            base_rows, [], skipped={r["entry"] for r in base_rows}
        )
        assert findings == []

    def test_feasibility_flip_fires_the_budget_rule(self, base_rows):
        """A committed `feasible` verdict regressing to `infeasible`
        is the regression the budget table exists to catch — it fires
        kernel-vmem-budget itself, not the generic regression rule;
        the improving flip is a note."""
        import copy

        from rcmarl_tpu.lint.kernels import TPU_GENERATIONS, compare_kernels

        fresh = copy.deepcopy(base_rows)
        for r in fresh:
            if r["entry"] == "planted[ok]@v4":
                r["verdict"] = "infeasible"
                r["metrics"]["vmem_bytes"] = (
                    TPU_GENERATIONS["v4"]["vmem"] + 1.0
                )
        findings, _ = compare_kernels(base_rows, fresh)
        assert {f.rule for f in findings} == {"kernel-vmem-budget"}
        assert "regressed" in findings[0].message
        baseline = copy.deepcopy(fresh)
        findings, notes = compare_kernels(baseline, base_rows)
        assert findings == []
        assert any("improved" in n for n in notes)

    def test_feasibility_lines_cover_every_queued_step(self):
        """The session-preflight feed: every line is machine-parseable,
        the queued sparse mega-cells report honestly infeasible at v4
        and feasible at v5e — pure arithmetic, identical on any
        host."""
        import re as _re

        from rcmarl_tpu.lint.kernels import feasibility_lines

        lines = feasibility_lines()
        fmt = _re.compile(
            r"^step:\S+ kernel=\w+ shape=\S+ gen=v4 "
            r"verdict=(feasible|infeasible|unverified) "
            r"vmem_mib=(\d+\.\d\d|nan)$"
        )
        assert lines and all(fmt.match(ln) for ln in lines), lines
        steps = {ln.split()[0].removeprefix("step:") for ln in lines}
        assert {"1", "2", "9", "9b", "10b", "12", "14", "15b"} <= steps
        n1024 = [ln for ln in lines if "shape=n1024_sparse" in ln]
        assert n1024 and all("verdict=infeasible" in ln for ln in n1024)
        assert any(
            "verdict=feasible" in ln
            for ln in feasibility_lines("v5e")
            if "shape=n1024_sparse" in ln
        )


@pytest.mark.slow
class TestCommittedLedger:
    """The acceptance bar: the full cost + collective + sharding audits
    report zero findings against the COMMITTED AUDIT.jsonl on this host
    (the same gate ci_tier1.sh runs through the real CLI)."""

    BASELINE = Path(__file__).parent.parent / "AUDIT.jsonl"

    def test_cost_gate_is_clean(self):
        from rcmarl_tpu.lint.cost import audit_cost

        findings, _notes, _rows = audit_cost(self.BASELINE)
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_collective_census_is_clean(self):
        from rcmarl_tpu.lint.collectives import audit_collectives

        findings, _notes, _rows = audit_collectives(self.BASELINE)
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_sharding_gate_is_clean(self):
        """Sharding annotations, reshard chains, the per-device shrink
        invariant, and the device_memory ledger rows — all green on the
        committed tree at every mesh rung this host can build."""
        from rcmarl_tpu.lint.sharding import audit_sharding

        findings, _notes, _rows = audit_sharding(self.BASELINE)
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_determinism_census_is_clean(self):
        from rcmarl_tpu.lint.sharding import audit_determinism

        findings, _notes = audit_determinism()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_kernel_budget_gate_is_clean(self):
        """The full (kernel x shape) matrix — every Pallas entry at
        every tiny lint shape, bench cell, and tpu_session.sh queued
        shape — derives, re-derives its committed DMA model, and
        matches the committed kernel_budget rows at every
        generation."""
        from rcmarl_tpu.lint.kernels import audit_kernels, kernel_cells

        findings, notes, rows = audit_kernels(self.BASELINE)
        assert findings == [], "\n".join(str(f) for f in findings)
        # every cell derived (no skips hid behind notes) at all 3 gens
        assert len(rows) == 3 * len(kernel_cells())


class TestBackendAudit:
    def test_all_six_backends_and_netstack_arms_pass(self):
        from rcmarl_tpu.lint.backends import audit_backends

        findings = audit_backends()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_audit_table_is_the_contract(self):
        """The audit iterates ops.aggregation.AUDIT_BACKEND_MODES —
        pin the backend-table shape so a new backend must register."""
        from rcmarl_tpu.ops.aggregation import AUDIT_BACKEND_MODES

        names = [name for name, _ in AUDIT_BACKEND_MODES]
        assert names == [
            "xla", "xla_sort", "masked", "traced_h",
            "pallas_select", "pallas_sort", "pallas_fused",
        ]
