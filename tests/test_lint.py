"""graftlint contract tests (rcmarl_tpu.lint).

Three pins:

1. **Fixture corpus** — every AST rule fires on its seeded-bad file
   under ``tests/lint_fixtures/``, on EXACTLY the lines the fixture
   marks with ``# RULE: <rule>`` (so false positives on the adjacent
   clean twins fail too), and the pragma escape silences a marked file.
2. **Package silence** — the installed package lints clean: the suite's
   own acceptance bar, which forced the real violations it found during
   development (training/update.py's magic fold_in tags) to be fixed.
3. **Runtime audits** — the retrace auditor proves exactly-once
   compilation for a guarded+faulted tiny run on both netstack arms
   (and catches a planted retrace); the donation audit proves the
   donated entry points' input->output aliasing survived to the
   compiled executable (xfail where the platform exposes no aliasing
   metadata); the backend purity/dtype audit passes over all six
   aggregation backends and both netstack epoch arms.
"""

from __future__ import annotations

import re
from pathlib import Path

import pytest

from rcmarl_tpu.lint import SOURCE_RULES, lint_file, run_source_lint

FIXTURES = Path(__file__).parent / "lint_fixtures"

_RULE_MARK = re.compile(r"#\s*RULE:\s*([\w\-]+)")


def _marked_lines(path: Path, rule: str) -> set:
    """Line numbers the fixture marks as violations of ``rule``."""
    return {
        lineno
        for lineno, text in enumerate(
            path.read_text().splitlines(), start=1
        )
        if (m := _RULE_MARK.search(text)) and m.group(1) == rule
    }


class TestSourceRules:
    """Each AST rule fires on its fixture — exactly where marked."""

    CASES = [
        ("bad_prng_reuse.py", False, "prng-reuse"),
        ("bad_prng_split_discard.py", False, "prng-split-discard"),
        ("bad_prng_int_seed.py", True, "prng-int-seed"),
        ("bad_prng_fold_tag.py", True, "prng-fold-tag"),
        ("bad_host_sync.py", True, "host-sync"),
        ("bad_host_block.py", True, "host-block"),
        ("bad_static_unhashable.py", False, "static-unhashable"),
    ]

    @pytest.mark.parametrize("fixture,hot,rule", CASES)
    def test_rule_fires_exactly_on_marked_lines(self, fixture, hot, rule):
        path = FIXTURES / fixture
        expected = _marked_lines(path, rule)
        assert expected, f"fixture {fixture} carries no # RULE: marks"
        findings = lint_file(path, hot_path=hot)
        got = {f.line for f in findings if f.rule == rule}
        assert got == expected, (
            f"{rule} fired on lines {sorted(got)}, fixture marks "
            f"{sorted(expected)} — a mismatch is a false "
            "positive/negative on the seeded corpus"
        )

    @pytest.mark.parametrize("fixture,hot,rule", CASES)
    def test_no_offrule_noise(self, fixture, hot, rule):
        """A fixture only demonstrates ITS rules: everything the file
        fires must be marked (some files legitimately mark several)."""
        path = FIXTURES / fixture
        findings = lint_file(path, hot_path=hot)
        for f in findings:
            assert f.line in _marked_lines(path, f.rule), (
                f"unmarked finding {f} — either mark the fixture line "
                "or fix the false positive"
            )

    def test_rule_ids_are_registered(self):
        for _, _, rule in self.CASES:
            assert rule in SOURCE_RULES

    def test_pragma_escape_silences(self):
        assert lint_file(FIXTURES / "pragma_ok.py", hot_path=True) == []

    def test_hot_path_rules_stay_out_of_host_modules(self):
        """The traced-code rules (host-sync, prng-int-seed) must NOT
        fire outside the hot-path scope — host orchestration fetches
        and mints keys legitimately."""
        findings = lint_file(FIXTURES / "bad_host_sync.py", hot_path=False)
        assert [f for f in findings if f.rule == "host-sync"] == []
        findings = lint_file(
            FIXTURES / "bad_prng_int_seed.py", hot_path=False
        )
        assert [f for f in findings if f.rule == "prng-int-seed"] == []


class TestPackageClean:
    def test_package_reports_zero_findings(self):
        findings = run_source_lint()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_cli_lint_exits_zero(self):
        from rcmarl_tpu.cli import main

        assert main(["lint"]) == 0


class TestRetraceAuditor:
    def test_exactly_once_compilation_both_arms(self):
        """The `lint --retrace` mode: guarded+faulted tiny runs on both
        netstack arms plus a clean donated run compile nothing after
        their warmup block."""
        from rcmarl_tpu.lint.retrace import audit_retrace

        findings = audit_retrace()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_planted_retrace_is_caught_and_named(self):
        from rcmarl_tpu.lint.retrace import RetraceAuditor, _tiny_cfg
        from rcmarl_tpu.training.trainer import train

        cfg = _tiny_cfg(False, False)
        train(cfg, n_episodes=cfg.n_ep_fixed)  # warm THIS config
        auditor = RetraceAuditor()
        with auditor.expect_no_compiles(context="planted H change"):
            # a different static config inside the steady-state window
            # is exactly the drift class the auditor exists for
            train(cfg.replace(H=0), n_episodes=cfg.n_ep_fixed)
        rules = {f.rule for f in auditor.findings}
        assert rules == {"retrace"}
        names = " ".join(f.message for f in auditor.findings)
        assert "train_block_donated" in names


class TestDonationAudit:
    """PR 3's donation can never silently rot: the compiled executables
    must keep the declared input->output buffer aliasing."""

    @pytest.fixture(scope="class")
    def report(self):
        from rcmarl_tpu.lint.donation import donation_report

        return donation_report()

    @pytest.mark.parametrize(
        "entry", ["update_block_donated", "train_block_donated"]
    )
    def test_donated_state_buffers_alias(self, report, entry):
        row = report[entry]
        if not row["has_metadata"]:
            pytest.xfail(
                "platform exposes no input_output_alias metadata in "
                "compiled HLO text; aliasing unverifiable here"
            )
        assert row["warnings"] == [], (
            f"{entry}: XLA warned donated buffers went unused: "
            f"{row['warnings']}"
        )
        assert row["alias_pairs"] >= row["expected_min"], (
            f"{entry}: {row['alias_pairs']} aliased pairs < "
            f"{row['expected_min']} parameter/optimizer leaves — the "
            "donation was dropped and the state is being copied"
        )

    def test_audit_donation_is_clean(self):
        from rcmarl_tpu.lint.donation import audit_donation

        findings, _notes = audit_donation()
        assert findings == [], "\n".join(str(f) for f in findings)


class TestBackendAudit:
    def test_all_six_backends_and_netstack_arms_pass(self):
        from rcmarl_tpu.lint.backends import audit_backends

        findings = audit_backends()
        assert findings == [], "\n".join(str(f) for f in findings)

    def test_audit_table_is_the_contract(self):
        """The audit iterates ops.aggregation.AUDIT_BACKEND_MODES —
        pin the six-backend shape so a new backend must register."""
        from rcmarl_tpu.ops.aggregation import AUDIT_BACKEND_MODES

        names = [name for name, _ in AUDIT_BACKEND_MODES]
        assert names == [
            "xla", "xla_sort", "masked", "traced_h",
            "pallas_select", "pallas_sort",
        ]
