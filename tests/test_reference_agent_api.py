"""Golden tests for the reference-protocol agent adapter.

`ReferenceRPBCACAgent` claims drop-in fidelity to the reference's
`RPBCAC_agent` object; these tests drive BOTH through a full reference
trainer epoch — local fits, the synchronous weight exchange, hidden +
projection consensus, team updates, and the actor step — and compare
weights and returned values at every boundary. Reuses the Keras setup
conventions of ``test_golden_updates.py`` (TF optional: skipped when
unavailable).
"""

import sys

import numpy as np
import pytest

from rcmarl_tpu.agents import ReferenceRPBCACAgent

tf = pytest.importorskip("tensorflow")
keras = tf.keras


def _load_reference_agent():
    sys.path.insert(0, "/root/reference")
    try:
        from agents.resilient_CAC_agents import RPBCAC_agent  # type: ignore

        return RPBCAC_agent
    except Exception:
        return None
    finally:
        sys.path.remove("/root/reference")


REF_AGENT = _load_reference_agent()

pytestmark = pytest.mark.skipif(
    REF_AGENT is None, reason="reference agent not importable"
)

N_AGENTS, N_STATES, N_ACTIONS = 5, 2, 5
GAMMA, FAST_LR, SLOW_LR, H = 0.9, 0.01, 0.002, 1
N_IN = 4  # reference default neighborhood incl. self (main.py:28)


def _keras_model(in_feats, out_dim, softmax):
    return keras.Sequential(
        [
            keras.Input(shape=(N_AGENTS, in_feats)),
            keras.layers.Flatten(),
            keras.layers.Dense(20, activation=keras.layers.LeakyReLU(alpha=0.1)),
            keras.layers.Dense(20, activation=keras.layers.LeakyReLU(alpha=0.1)),
            keras.layers.Dense(out_dim, activation="softmax" if softmax else None),
        ]
    )


if REF_AGENT is not None:
    # Keras-3 compat shim, as in test_golden_updates.py: the reference
    # reuses one stateless SGD across models/trainable-set changes.
    REF_AGENT.optimizer_fast = property(
        lambda self: keras.optimizers.SGD(learning_rate=self.fast_lr),
        lambda self, v: None,
    )


def _pair(seed=0):
    """(reference agent, adapter) from IDENTICAL initial weights."""
    keras.utils.set_random_seed(seed)
    models = (
        _keras_model(N_STATES, N_ACTIONS, softmax=True),
        _keras_model(N_STATES, 1, softmax=False),
        _keras_model(N_STATES + 1, 1, softmax=False),
    )
    ref = REF_AGENT(*models, slow_lr=SLOW_LR, fast_lr=FAST_LR, gamma=GAMMA, H=H)
    ours = ReferenceRPBCACAgent(
        models[0].get_weights(),
        models[1].get_weights(),
        models[2].get_weights(),
        slow_lr=SLOW_LR,
        fast_lr=FAST_LR,
        gamma=GAMMA,
        H=H,
    )
    return ref, ours


def _batch(rng, B=32):
    s = rng.normal(size=(B, N_AGENTS, N_STATES)).astype(np.float32)
    ns = rng.normal(size=(B, N_AGENTS, N_STATES)).astype(np.float32)
    a = rng.integers(0, N_ACTIONS, size=(B, N_AGENTS, 1)).astype(np.float32)
    r = rng.normal(size=(B, 1)).astype(np.float32) * 0.3 - 0.5
    return s, ns, a, r


def _neighbor_messages(rng, own_weights):
    """own message first + 3 perturbed copies (the exchange's shape)."""
    msgs = [own_weights]
    for k in range(1, N_IN):
        msgs.append(
            [w + rng.normal(size=w.shape).astype(np.float32) * 0.05 for w in own_weights]
        )
    return msgs


def _assert_weights_close(ours_flat, ref_weights, rtol=1e-4, atol=1e-5):
    for mine, ref in zip(ours_flat, ref_weights):
        np.testing.assert_allclose(np.asarray(mine), ref, rtol=rtol, atol=atol)


class TestFullEpochGolden:
    def test_local_fit_messages_and_losses(self):
        ref, ours = _pair()
        rng = np.random.default_rng(0)
        s, ns, a, r = _batch(rng)
        sa = np.concatenate([s, a], axis=-1)

        w_ref, l_ref = ref.critic_update_local(
            tf.constant(s), tf.constant(ns), tf.constant(r)
        )
        w_my, l_my = ours.critic_update_local(s, ns, r)
        _assert_weights_close(w_my, w_ref)
        np.testing.assert_allclose(l_my, l_ref, rtol=1e-4)

        w_ref, l_ref = ref.TR_update_local(tf.constant(sa), tf.constant(r))
        w_my, l_my = ours.TR_update_local(sa, r)
        _assert_weights_close(w_my, w_ref)
        np.testing.assert_allclose(l_my, l_ref, rtol=1e-4)

    def test_consensus_and_team_update_golden(self):
        ref, ours = _pair()
        rng = np.random.default_rng(1)
        s, ns, a, r = _batch(rng)
        sa = np.concatenate([s, a], axis=-1)

        c_msgs = _neighbor_messages(rng, ref.critic.get_weights())
        t_msgs = _neighbor_messages(rng, ref.TR.get_weights())

        # hidden consensus writes the trunk on both sides
        ref.resilient_consensus_critic_hidden(c_msgs)
        ref.resilient_consensus_TR_hidden(t_msgs)
        ours.resilient_consensus_critic_hidden(c_msgs)
        ours.resilient_consensus_TR_hidden(t_msgs)
        _assert_weights_close(
            [w for pair in ours.critic for w in pair], ref.critic.get_weights()
        )

        # projection targets over the full batch
        agg_ref = np.asarray(ref.resilient_consensus_critic(tf.constant(s), c_msgs))
        agg_my = ours.resilient_consensus_critic(s, c_msgs)
        np.testing.assert_allclose(agg_my, agg_ref, rtol=1e-4, atol=1e-5)
        tr_agg_ref = np.asarray(ref.resilient_consensus_TR(tf.constant(sa), t_msgs))
        tr_agg_my = ours.resilient_consensus_TR(sa, t_msgs)
        np.testing.assert_allclose(tr_agg_my, tr_agg_ref, rtol=1e-4, atol=1e-5)

        # team head updates
        ref.critic_update_team(tf.constant(s), tf.constant(agg_ref))
        ours.critic_update_team(s, agg_my)
        _assert_weights_close(
            [w for pair in ours.critic for w in pair], ref.critic.get_weights()
        )
        ref.TR_update_team(tf.constant(sa), tf.constant(tr_agg_ref))
        ours.TR_update_team(sa, tr_agg_my)
        _assert_weights_close(
            [w for pair in ours.TR for w in pair], ref.TR.get_weights()
        )

    def test_actor_update_golden(self):
        ref, ours = _pair()
        rng = np.random.default_rng(2)
        s, ns, a, r = _batch(rng)
        sa = np.concatenate([s, a], axis=-1)
        a_local = a[:, 0, 0]

        ref.actor_update(
            tf.constant(s), tf.constant(ns), tf.constant(sa), tf.constant(a_local)
        )
        ours.actor_update(s, ns, sa, a_local)
        _assert_weights_close(
            [w for pair in ours.actor for w in pair],
            ref.actor.get_weights(),
            rtol=2e-4,
            atol=2e-5,
        )

    def test_get_action_stream_and_parameters(self):
        ref, ours = _pair()
        state = np.zeros((1, N_AGENTS, N_STATES), np.float32)
        # identical global-RNG streams => identical ε-mixed action choices
        np.random.seed(42)
        a_ref = [int(ref.get_action(state)) for _ in range(10)]
        np.random.seed(42)
        a_my = [int(ours.get_action(state)) for _ in range(10)]
        assert a_my == a_ref

        for mine, ref_w in zip(ours.get_parameters(), ref.get_parameters()):
            _assert_weights_close(mine, ref_w)


def _load_reference_adversaries():
    sys.path.insert(0, "/root/reference")
    try:
        from agents.adversarial_CAC_agents import (  # type: ignore
            Faulty_CAC_agent,
            Greedy_CAC_agent,
            Malicious_CAC_agent,
        )

        return Faulty_CAC_agent, Greedy_CAC_agent, Malicious_CAC_agent
    except Exception:
        return None, None, None
    finally:
        sys.path.remove("/root/reference")


REF_FAULTY, REF_GREEDY, REF_MALICIOUS = _load_reference_adversaries()

adversarial = pytest.mark.skipif(
    REF_GREEDY is None, reason="reference adversarial agents not importable"
)


def _adv_pair(ours_cls, ref_cls, seed=3, **extra):
    from rcmarl_tpu.agents import reference_api  # noqa: F401

    keras.utils.set_random_seed(seed)
    models = (
        _keras_model(N_STATES, N_ACTIONS, softmax=True),
        _keras_model(N_STATES, 1, softmax=False),
        _keras_model(N_STATES + 1, 1, softmax=False),
    )
    ref = ref_cls(*models, slow_lr=SLOW_LR, gamma=GAMMA, **extra)
    ours = ours_cls(
        models[0].get_weights(),
        models[1].get_weights(),
        models[2].get_weights(),
        slow_lr=SLOW_LR,
        gamma=GAMMA,
        **extra,
    )
    return ref, ours


@adversarial
class TestAdversaryTwinsGolden:
    """B=32 with fit batch_size=32 (and actor batch_size=200 > B) makes
    every reference fit single-batch, so shuffle order is irrelevant and
    the twins must match bit-for-bit within float tolerance."""

    def test_faulty_frozen_messages_and_actor(self):
        from rcmarl_tpu.agents import ReferenceFaultyAgent

        ref, ours = _adv_pair(ReferenceFaultyAgent, REF_FAULTY)
        rng = np.random.default_rng(4)
        s, ns, a, r = _batch(rng)
        a_local = a[:, 0, :]

        _assert_weights_close(ours.get_critic_weights(), ref.get_critic_weights())
        _assert_weights_close(ours.get_TR_weights(), ref.get_TR_weights())

        ref.actor_update(
            tf.constant(s), tf.constant(ns), tf.constant(r), tf.constant(a_local)
        )
        ours.actor_update(s, ns, r, a_local)
        _assert_weights_close(
            [w for pair in ours.actor for w in pair],
            ref.actor.get_weights(),
            rtol=2e-4,
            atol=2e-5,
        )
        # messages stay frozen through actor training
        _assert_weights_close(ours.get_critic_weights(), ref.get_critic_weights())

    def test_greedy_persisting_fits(self):
        from rcmarl_tpu.agents import ReferenceGreedyAgent

        ref, ours = _adv_pair(
            ReferenceGreedyAgent, REF_GREEDY, fast_lr=FAST_LR
        )
        rng = np.random.default_rng(5)
        s, ns, a, r = _batch(rng)
        sa = np.concatenate([s, a], axis=-1)

        w_ref, l_ref = ref.critic_update_local(
            tf.constant(s), tf.constant(ns), tf.constant(r)
        )
        w_my, l_my = ours.critic_update_local(s, ns, r)
        _assert_weights_close(w_my, w_ref)
        np.testing.assert_allclose(l_my, l_ref, rtol=1e-3)

        w_ref, l_ref = ref.TR_update_local(tf.constant(sa), tf.constant(r))
        w_my, l_my = ours.TR_update_local(sa, r)
        _assert_weights_close(w_my, w_ref)
        np.testing.assert_allclose(l_my, l_ref, rtol=1e-3)

        # fits PERSISTED on both sides
        _assert_weights_close(
            [w for pair in ours.critic for w in pair], ref.critic.get_weights()
        )

    def test_malicious_private_and_compromised(self):
        from rcmarl_tpu.agents import ReferenceMaliciousAgent

        ref, ours = _adv_pair(
            ReferenceMaliciousAgent, REF_MALICIOUS, fast_lr=FAST_LR
        )
        rng = np.random.default_rng(6)
        s, ns, a, r_coop = _batch(rng)
        sa = np.concatenate([s, a], axis=-1)

        # private critic fit persists to critic_local_weights only
        ref.critic_update_local(tf.constant(s), tf.constant(ns), tf.constant(r_coop))
        ours.critic_update_local(s, ns, r_coop)
        _assert_weights_close(ours.critic_local_weights, ref.critic_local_weights)
        _assert_weights_close(
            [w for pair in ours.critic for w in pair], ref.critic.get_weights()
        )

        # compromised fits toward -r_coop persist and are transmitted
        w_ref, l_ref = ref.critic_update_compromised(
            tf.constant(s), tf.constant(ns), tf.constant(-r_coop)
        )
        w_my, l_my = ours.critic_update_compromised(s, ns, -r_coop)
        _assert_weights_close(w_my, w_ref)
        np.testing.assert_allclose(l_my, l_ref, rtol=1e-3)

        w_ref, l_ref = ref.TR_update_compromised(tf.constant(sa), tf.constant(-r_coop))
        w_my, l_my = ours.TR_update_compromised(sa, -r_coop)
        _assert_weights_close(w_my, w_ref)
        np.testing.assert_allclose(l_my, l_ref, rtol=1e-3)

        # 4-entry parameter export incl. the private critic
        assert len(ours.get_parameters()) == len(ref.get_parameters()) == 4

    def test_malicious_actor_uses_private_critic(self):
        from rcmarl_tpu.agents import ReferenceMaliciousAgent

        ref, ours = _adv_pair(
            ReferenceMaliciousAgent, REF_MALICIOUS, seed=7, fast_lr=FAST_LR
        )
        rng = np.random.default_rng(7)
        s, ns, a, r = _batch(rng)
        a_local = a[:, 0, :]
        # diverge the private critic from the compromised one first
        ref.critic_update_local(tf.constant(s), tf.constant(ns), tf.constant(r))
        ours.critic_update_local(s, ns, r)

        ref.actor_update(
            tf.constant(s), tf.constant(ns), tf.constant(r), tf.constant(a_local)
        )
        ours.actor_update(s, ns, r, a_local)
        _assert_weights_close(
            [w for pair in ours.actor for w in pair],
            ref.actor.get_weights(),
            rtol=2e-4,
            atol=2e-5,
        )


def test_twin_construction_consumes_no_global_numpy_draws():
    """The reference constructors draw nothing from np.random; the twins
    must not either, or seeded scripts' get_action streams would shift."""
    from rcmarl_tpu.agents import (
        ReferenceFaultyAgent,
        ReferenceGreedyAgent,
        ReferenceMaliciousAgent,
        ReferenceRPBCACAgent,
    )

    def flat(out_dim):
        return [
            np.zeros((N_AGENTS * N_STATES, 20), np.float32), np.zeros(20, np.float32),
            np.zeros((20, 20), np.float32), np.zeros(20, np.float32),
            np.zeros((20, out_dim), np.float32), np.zeros(out_dim, np.float32),
        ]

    np.random.seed(9)
    expected = np.random.randint(0, 10**6)
    np.random.seed(9)
    ReferenceRPBCACAgent(flat(N_ACTIONS), flat(1), flat(1), SLOW_LR, FAST_LR)
    ReferenceFaultyAgent(flat(N_ACTIONS), flat(1), flat(1), SLOW_LR)
    ReferenceGreedyAgent(flat(N_ACTIONS), flat(1), flat(1), SLOW_LR, FAST_LR)
    ReferenceMaliciousAgent(flat(N_ACTIONS), flat(1), flat(1), SLOW_LR, FAST_LR)
    assert np.random.randint(0, 10**6) == expected


def _load_reference_trainer():
    """Import the reference train_RPBCAC with gym stubbed (it only
    imports gym for unused symbols)."""
    import types

    if "gym" not in sys.modules:
        gym_stub = types.ModuleType("gym")
        gym_stub.Env = type("Env", (), {})
        gym_stub.spaces = types.ModuleType("gym.spaces")
        sys.modules["gym"] = gym_stub
        sys.modules["gym.spaces"] = gym_stub.spaces
    sys.path.insert(0, "/root/reference")
    try:
        from training.train_agents import train_RPBCAC  # type: ignore

        return train_RPBCAC
    except Exception:
        return None
    finally:
        sys.path.remove("/root/reference")


REF_TRAIN = _load_reference_trainer()


@pytest.mark.slow
@pytest.mark.skipif(
    REF_TRAIN is None or REF_GREEDY is None,
    reason="reference trainer/agents not importable",
)
def test_full_program_golden_vs_reference_trainer(capsys):
    """The capstone: the reference's ENTIRE training program — env,
    agents, trainer — run twice from identical seeds and weights, once on
    the reference TF stack and once on this framework's compat twins.
    Identical global-RNG streams drive resets and action sampling, so the
    two runs must produce matching sim_data and near-identical weights."""
    from rcmarl_tpu.agents import ReferenceGreedyAgent
    from rcmarl_tpu.envs import ReferenceGridWorld
    from rcmarl_tpu.training import train_RPBCAC as my_train

    sys.path.insert(0, "/root/reference")
    try:
        from environments.grid_world import Grid_World  # type: ignore
    finally:
        sys.path.remove("/root/reference")

    labels = ["Cooperative"] * 4 + ["Greedy"]
    args = {
        "agent_label": labels,
        "n_states": 2,
        "gamma": GAMMA,
        "in_nodes": [[0, 1, 2, 3], [1, 2, 3, 4], [2, 3, 4, 0], [3, 4, 0, 1], [4, 0, 1, 2]],
        "max_ep_len": 4,
        "n_episodes": 4,
        "n_ep_fixed": 2,
        "n_epochs": 1,
        "batch_size": 200,
        "buffer_size": 16,
        "common_reward": False,
        "verbose": False,
    }
    desired = np.array([[0, 1], [2, 2], [4, 0], [1, 3], [3, 4]])

    def build_agents(twin: bool):
        keras.utils.set_random_seed(0)
        out = []
        for node, lab in enumerate(labels):
            models = (
                _keras_model(N_STATES, N_ACTIONS, softmax=True),
                _keras_model(N_STATES, 1, softmax=False),
                _keras_model(N_STATES + 1, 1, softmax=False),
            )
            if lab == "Cooperative":
                if twin:
                    out.append(ReferenceRPBCACAgent(
                        *(m.get_weights() for m in models),
                        slow_lr=SLOW_LR, fast_lr=FAST_LR, gamma=GAMMA, H=H,
                    ))
                else:
                    out.append(REF_AGENT(*models, slow_lr=SLOW_LR,
                                         fast_lr=FAST_LR, gamma=GAMMA, H=H))
            else:
                if twin:
                    out.append(ReferenceGreedyAgent(
                        *(m.get_weights() for m in models),
                        slow_lr=SLOW_LR, fast_lr=FAST_LR, gamma=GAMMA,
                    ))
                else:
                    out.append(REF_GREEDY(*models, slow_lr=SLOW_LR,
                                          fast_lr=FAST_LR, gamma=GAMMA))
        return out

    # reference run
    np.random.seed(77)
    ref_env = Grid_World(nrow=5, ncol=5, n_agents=5,
                         desired_state=desired, scaling=True)
    ref_w, ref_data = REF_TRAIN(ref_env, build_agents(twin=False), args)
    capsys.readouterr()  # swallow the reference's per-episode prints

    # twin run, identical streams
    np.random.seed(77)
    my_env = ReferenceGridWorld(nrow=5, ncol=5, n_agents=5,
                                desired_state=desired, scaling=True)
    my_w, my_data = my_train(my_env, build_agents(twin=True), args)

    np.testing.assert_allclose(
        my_data["True_team_returns"], ref_data["True_team_returns"],
        rtol=1e-4, atol=1e-5,
    )
    np.testing.assert_allclose(
        my_data["Estimated_team_returns"], ref_data["Estimated_team_returns"],
        rtol=1e-3, atol=1e-4,
    )
    # final weights: every agent, every network, every array
    for mine_agent, ref_agent in zip(my_w, ref_w):
        for mine_net, ref_net in zip(mine_agent, ref_agent):
            _assert_weights_close(mine_net, ref_net, rtol=5e-3, atol=5e-4)


def test_trainer_twin_exp_buffer_warm_start():
    """train_RPBCAC's exp_buffer warm-start (reference train_agents.py:
    36-40): pre-seeded experience participates in the first update window
    and is FIFO-trimmed after it."""
    from rcmarl_tpu.agents import ReferenceRPBCACAgent
    from rcmarl_tpu.envs import ReferenceGridWorld
    from rcmarl_tpu.models.mlp import init_mlp
    from rcmarl_tpu.training import train_RPBCAC
    import jax

    def flat_init(key, in_dim, out):
        params = init_mlp(key, in_dim, (20, 20), out)
        return [np.asarray(x) for wb in params for x in wb]

    n, keys = 3, jax.random.split(jax.random.PRNGKey(0), 9)
    agents = [
        ReferenceRPBCACAgent(
            flat_init(keys[3 * i], n * 2, 5),
            flat_init(keys[3 * i + 1], n * 2, 1),
            flat_init(keys[3 * i + 2], n * 3, 1),
            slow_lr=SLOW_LR, fast_lr=FAST_LR, gamma=GAMMA, H=1,
        )
        for i in range(n)
    ]
    args = {
        "agent_label": ["Cooperative"] * n,
        "n_states": 2,
        "gamma": GAMMA,
        "in_nodes": [[0, 1, 2], [1, 2, 0], [2, 0, 1]],
        "max_ep_len": 3,
        "n_episodes": 2,
        "n_ep_fixed": 2,
        "n_epochs": 1,
        "batch_size": 200,
        "buffer_size": 8,
        "common_reward": False,
        "verbose": False,
    }
    desired = np.array([[0, 1], [2, 2], [4, 0]])
    np.random.seed(1)
    env = ReferenceGridWorld(nrow=5, ncol=5, n_agents=n,
                             desired_state=desired, scaling=True)
    # warm-start with 4 synthetic steps; the lists are mutated in place
    pre = 4
    rng = np.random.default_rng(2)
    buf = (
        [rng.normal(size=(n, 2)).astype(np.float32) for _ in range(pre)],
        [rng.normal(size=(n, 2)).astype(np.float32) for _ in range(pre)],
        [rng.integers(0, 5, size=(n, 1)).astype(np.float32) for _ in range(pre)],
        [rng.normal(size=(n, 1)).astype(np.float32) for _ in range(pre)],
    )
    _, sim_data = train_RPBCAC(env, agents, args, exp_buffer=buf)
    assert len(sim_data) == 2
    # 4 warm + 6 new = 10 > buffer_size 8 -> trimmed to 8 after the update
    assert len(buf[0]) == 8


def test_twin_exports_roundtrip_into_fused_trainer():
    """The compat boundary closes a full circle: weights exported by the
    twins (reference pretrained_weights format) import losslessly into
    the fused stacked-trainer's parameters via the same path that loads
    the reference's real artifacts."""
    from rcmarl_tpu.agents import ReferenceRPBCACAgent
    from rcmarl_tpu.config import Config
    from rcmarl_tpu.models.mlp import init_mlp
    from rcmarl_tpu.training.trainer import init_train_state
    from rcmarl_tpu.utils.checkpoint import import_reference_weights
    import jax

    n = 3
    cfg = Config(
        n_agents=n, agent_roles=(0,) * n,
        in_nodes=((0, 1, 2), (1, 2, 0), (2, 0, 1)), H=1,
    )

    def flat_init(key, in_dim, out):
        return [np.asarray(x) for wb in init_mlp(key, in_dim, (20, 20), out)
                for x in wb]

    keys = jax.random.split(jax.random.PRNGKey(5), 3 * n)
    twins = [
        ReferenceRPBCACAgent(
            flat_init(keys[3 * i], cfg.obs_dim, cfg.n_actions),
            flat_init(keys[3 * i + 1], cfg.obs_dim, 1),
            flat_init(keys[3 * i + 2], cfg.sa_dim, 1),
            slow_lr=0.002, fast_lr=0.01, gamma=0.9, H=1,
        )
        for i in range(n)
    ]
    exported = np.asarray([t.get_parameters() for t in twins], dtype=object)

    state = init_train_state(cfg, jax.random.PRNGKey(99))  # different init
    params = import_reference_weights(exported, cfg, state.params)
    # agent 1's critic W1 in the stacked pytree == twin 1's export
    np.testing.assert_array_equal(
        np.asarray(params.critic[0][0][1]), twins[1].get_parameters()[1][0]
    )
    np.testing.assert_array_equal(
        np.asarray(params.actor[-1][1][2]), twins[2].get_parameters()[0][-1]
    )
