"""SLO-driven autoscaling contracts (rcmarl_tpu.serve.autoscale).

The pins that make the control loop trustworthy:

- the headline evidence claim: under the seeded 1x->10x->1x offered-load
  swing the autoscaled fleet holds the p99 SLO in EVERY window while the
  static scale-1 baseline saturates on the same plan;
- scale-down HYSTERESIS: down moves wait out consecutive low-demand
  windows and project the smaller fleet's demand first — no flapping;
- never-resizes-mid-batch: scale changes land exactly at window
  boundaries (every resize's ``after_window`` accounting) and no request
  is lost across a resize;
- the chaos ``serve_overload@autoscale`` cell: sustained 4x-capacity
  overload is survived by scaling out, with a shed cost strictly under
  the static deadline-shedding arm's.

Everything runs on injected deterministic service models — replays are
bit-for-bit reproducible from ``(seed, plan, controller)`` alone, no
wall clock anywhere.
"""

from __future__ import annotations

import numpy as np
import pytest

from rcmarl_tpu.serve.autoscale import (
    HYSTERESIS,
    SLOController,
    autoscale_replay,
    summary_line,
    swing_arrivals,
)

SERVICE_S = 0.001
MAX_BATCH = 16
MAX_WAIT = 0.002
SLO = 0.006
#: half a scale-1 member's batch capacity — the swing's 10x peak then
#: offers 5x what the static fleet can serve
BASE_RATE = 0.5 * MAX_BATCH / SERVICE_S


def _swing(seg=4000, seed=0):
    # 4000 requests/segment = 10 control windows per 1x segment — the
    # committed autoscale_slo.json plan (a faster ramp outruns the
    # one-window control lag by construction, not by a controller bug)
    return swing_arrivals(seed, BASE_RATE, seg)


def _replay(controller, arrivals=None, **kw):
    arrivals = _swing() if arrivals is None else arrivals
    kw.setdefault("window", 0.05)
    kw.setdefault("max_batch", MAX_BATCH)
    kw.setdefault("max_wait", MAX_WAIT)
    kw.setdefault("slo_p99", SLO)
    return autoscale_replay(
        lambda fill: SERVICE_S, arrivals, controller, **kw
    )


class TestSwingEvidence:
    def test_autoscaled_holds_slo_static_saturates(self):
        """The committed autoscale_slo.json claim, as a pinned test:
        same seeded plan, same service model — the controller-driven
        fleet keeps every window's p99 under the SLO shed-free while
        the static scale-1 arm blows through it at the peak."""
        auto = _replay(SLOController(slo_p99=SLO, max_scale=16))
        static = _replay(None)
        assert auto["slo_held"]
        assert auto["shed"] == 0
        assert auto["max_scale_used"] >= 5  # the 10x peak needs >= 5x
        assert not static["slo_held"]
        static_peak = max(w["p99"] for w in static["windows"])
        assert static_peak > 10 * SLO  # saturation, not a near miss
        assert summary_line(auto).startswith("autoscale: SLO held")
        assert "SLO violated" in summary_line(static)

    def test_replay_is_deterministic(self):
        a = _replay(SLOController(slo_p99=SLO, max_scale=16))
        b = _replay(SLOController(slo_p99=SLO, max_scale=16))
        assert a == b

    def test_scale_comes_back_down_after_the_peak(self):
        """The trough after the swing releases capacity: hysteresis
        steps the fleet back down once demand stays low."""
        auto = _replay(SLOController(slo_p99=SLO, max_scale=16))
        assert auto["final_scale"] < auto["max_scale_used"]
        assert any(r["reason"] == "scale-down" for r in auto["resizes"])


class TestControllerDecisions:
    def _report(self, p99=0.001, demand=0.5, shed=0):
        return {"p99": p99, "demand": demand, "shed": shed}

    def test_breach_doubles_and_shed_doubles(self):
        c = SLOController(slo_p99=SLO, max_scale=8)
        c.scale = 2
        assert c.decide(self._report(p99=2 * SLO)) == "p99-breach"
        assert c.scale == 4
        assert c.decide(self._report(shed=3)) == "shed"
        assert c.scale == 8

    def test_demand_scale_up_is_proportional(self):
        """A ramp that doubles offered load gets a resized fleet, not
        one more member: the next scale lands demand back at the
        low-water mark."""
        c = SLOController(slo_p99=SLO, max_scale=16)
        c.scale = 2
        assert c.decide(self._report(demand=0.9)) == "high-demand"
        # ceil(0.9 * 2 / 0.35) = 6 — not 3
        assert c.scale == 6

    def test_scale_down_waits_out_hysteresis(self):
        c = SLOController(slo_p99=SLO, max_scale=8)
        c.scale = 4
        low = self._report(demand=0.1)
        for _ in range(HYSTERESIS - 1):
            assert c.decide(low) is None
            assert c.scale == 4
        assert c.decide(low) == "scale-down"
        assert c.scale == 3  # ONE step, not a collapse

    def test_hysteresis_resets_on_a_hot_window(self):
        c = SLOController(slo_p99=SLO, max_scale=8)
        c.scale = 4
        low = self._report(demand=0.1)
        for _ in range(HYSTERESIS - 1):
            c.decide(low)
        c.decide(self._report(demand=0.7))  # resets the healthy streak
        for _ in range(HYSTERESIS - 1):
            assert c.decide(low) is None
        assert c.decide(low) == "scale-down"

    def test_no_step_down_when_projection_would_overload(self):
        """Demand under the low mark but the SMALLER fleet's projected
        demand over it: hold — the anti-flap projection gate."""
        c = SLOController(slo_p99=SLO, max_scale=8)
        c.scale = 2
        # projected = 0.3 * 2 / 1 = 0.6 >= low mark 0.35 -> hold
        for _ in range(HYSTERESIS + 2):
            assert c.decide(self._report(demand=0.3)) is None
        assert c.scale == 2

    def test_envelope_and_validation(self):
        c = SLOController(slo_p99=SLO, min_scale=1, max_scale=2)
        assert c.decide(self._report(p99=2 * SLO)) == "p99-breach"
        assert c.decide(self._report(p99=2 * SLO)) is None  # at ceiling
        assert c.scale == 2
        with pytest.raises(ValueError, match="slo_p99"):
            SLOController(slo_p99=0.0)
        with pytest.raises(ValueError, match="min_scale"):
            SLOController(slo_p99=1.0, min_scale=3, max_scale=2)
        with pytest.raises(ValueError, match="hysteresis"):
            SLOController(slo_p99=1.0, hysteresis=0)


class TestResizeBoundaries:
    def test_never_resizes_mid_window_and_no_request_lost(self):
        """Structural pin of never-resizes-mid-batch: every window row
        reports exactly ONE scale, that scale equals the trajectory
        implied by the ``after_window`` resize log (a resize after
        window w is first visible in window w+1), and served + shed
        covers every arrival — no request can vanish at a boundary."""
        auto = _replay(SLOController(slo_p99=SLO, max_scale=16))
        scale = auto["windows"][0]["scale"]
        resized_at = {r["after_window"]: r["to"] for r in auto["resizes"]}
        prev_w = None
        for row in auto["windows"]:
            if prev_w is not None:
                for w in range(prev_w, row["window"]):
                    scale = resized_at.get(w, scale)
            assert row["scale"] == scale
            prev_w = row["window"]
        assert auto["served"] + auto["shed"] == auto["requests"]

    def test_windowed_static_percentiles_match_unwindowed_run(self):
        """A static scale-1 windowed replay is the SAME queue as one
        un-windowed :func:`run_load` pass over the plan — windowing is
        accounting, never simulation drift."""
        from rcmarl_tpu.serve.load import run_load

        arrivals = _swing(seg=200)
        windowed = _replay(None, arrivals=arrivals)
        flat = run_load(
            lambda fill: SERVICE_S, arrivals, MAX_BATCH, MAX_WAIT
        )
        lat = np.concatenate(
            [[w["p99"]] for w in windowed["windows"]]
        )
        # the flat run's p99 must sit inside the windowed envelope
        assert lat.min() - 1e-9 <= flat["p99"] <= lat.max() + 1e-9
        assert windowed["served"] == flat["served"]


class TestChaosAutoscaleCell:
    def test_serve_overload_autoscale_survives(self):
        """The registry cell end to end: sustained 4x-capacity overload
        is SURVIVED by scaling out — SLO restored by the final window,
        shed cost strictly under the static deadline-shedding arm."""
        from rcmarl_tpu.chaos.campaign import run_cell

        row = run_cell("serve_overload", "autoscale")
        assert row["outcome"] == "survived"
        c = row["counters"]
        assert c["max_scale_used"] > 1
        assert c["shed_fraction"] < c["static_shed_fraction"]
        assert c["final_p99_ms"] <= c["slo_ms"]
