"""Fused-matrix path: traced CellSpec must reproduce the static path.

The solo trainer specializes its program on Config at trace time; the
fused-matrix path (one program for the whole heterogeneous scenario x H
experiment matrix) carries roles/H/common_reward as traced data
(:class:`rcmarl_tpu.agents.updates.CellSpec`). These tests pin the load-
bearing contract: a spec-mode replica is NUMERICALLY IDENTICAL to its
statically-specialized solo twin — per update block, per full training
block, and under vmap across replicas with DIFFERENT scenarios.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rcmarl_tpu.config import Roles, circulant_in_nodes
from rcmarl_tpu.training import (
    init_agent_params,
    init_train_state,
    update_block,
)
from rcmarl_tpu.training.trainer import train_block, train_scanned
from rcmarl_tpu.training.update import spec_from_config
from tests.conftest import needs_multicore
from tests.test_trainer import SMALL, _fresh


def _cell_cfg(roles=None, H=0, common_reward=False):
    return SMALL.replace(
        agent_roles=roles or (Roles.COOPERATIVE,) * SMALL.n_agents,
        H=H,
        common_reward=common_reward,
    )


CELLS = {
    "coop_h0": _cell_cfg(),
    "coop_h1_common": _cell_cfg(H=1, common_reward=True),
    "greedy_h1": _cell_cfg(
        roles=(Roles.COOPERATIVE,) * 4 + (Roles.GREEDY,), H=1
    ),
    "faulty_h0": _cell_cfg(
        roles=(Roles.COOPERATIVE,) * 4 + (Roles.FAULTY,), H=0
    ),
    "malicious_h1": _cell_cfg(
        roles=(Roles.COOPERATIVE,) * 4 + (Roles.MALICIOUS,), H=1
    ),
}


def _assert_trees_equal(a, b, **kw):
    jax.tree.map(
        lambda x, y: np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), **kw
        ),
        a,
        b,
    )


#: Tier-1 870s wall-budget shed (the PR-8 fitstack / netstack
#: _FAST_EQUIVALENCE_MODES pattern): two representative cells stay in
#: tier-1 (one H=1/common-reward, one adversarial-role H=0), the rest
#: of the role × H matrix rides the slow marker. The full matrix still
#: runs under `pytest tests/` (no -m filter), and ci_tier1.sh's smoke
#: cells drive the traced-spec wire-up through the real trainer every
#: CI run.
_FAST_SPEC_CELLS = ("coop_h1_common", "faulty_h0")

_SPEC_CELL_PARAMS = [
    n
    if n in _FAST_SPEC_CELLS
    else pytest.param(n, marks=pytest.mark.slow)
    for n in sorted(CELLS)
]


class TestSpecEquivalence:
    @pytest.mark.parametrize("name", _SPEC_CELL_PARAMS)
    def test_update_block(self, name):
        """update_block(cfg) == update_block(cfg, spec=spec_from_config(cfg))
        — same RNG stream structure, compute-all-then-mask selects the
        same values the static path computes.

        The H>0 cells are pinned bitwise. The H=0 cells compare at
        float32-rounding tolerance: their static program short-circuits
        consensus to a plain mean while the traced-H program runs the
        general clip/mean with dynamic trim indices — the aggregation
        outputs themselves are bitwise-equal (tests/test_selection.py),
        but the structurally different consensus graphs fuse the
        SURROUNDING epoch ops (projection einsum, fits) differently,
        the same ~1e-8 fusion-order effect documented on
        TestSpecEquivalenceProperty and test_train_block."""
        cfg = CELLS[name]
        params = init_agent_params(jax.random.PRNGKey(3), cfg)
        batch, fresh = _fresh(cfg, 0.1), _fresh(cfg, 0.2)
        key = jax.random.PRNGKey(7)
        static = update_block(cfg, params, batch, fresh, key)
        traced = update_block(
            cfg, params, batch, fresh, key, spec_from_config(cfg)
        )
        if cfg.H > 0:
            _assert_trees_equal(static, traced, rtol=0, atol=0)
        else:
            _assert_trees_equal(static, traced, rtol=1e-5, atol=1e-7)

    @pytest.mark.parametrize(
        "name",
        # same tier-1 shed as _SPEC_CELL_PARAMS: one cell stays fast
        ["coop_h1_common",
         pytest.param("malicious_h1", marks=pytest.mark.slow)],
    )
    def test_train_block(self, name):
        """Full block (rollout + update + buffer push): state AND metrics
        identical between the two modes."""
        cfg = CELLS[name]
        state = init_train_state(cfg, jax.random.PRNGKey(0))
        s_static, m_static = train_block(cfg, state)
        s_traced, m_traced = train_block(cfg, state, spec_from_config(cfg))
        # composite programs fuse differently between the two modes, so
        # equality here is to float32 rounding (update_block alone is
        # bitwise — TestSpecEquivalence.test_update_block)
        _assert_trees_equal(s_static, s_traced, rtol=1e-5, atol=1e-7)
        _assert_trees_equal(m_static, m_traced, rtol=1e-5, atol=1e-7)


class TestHeterogeneousVmap:
    # ~56s cell — tier-1 870s wall-budget shed; the fused-matrix
    # contract still runs under `pytest tests/` and the sweep CLI
    # smoke in ci_tier1.sh exercises the vmapped matrix program.
    @pytest.mark.slow
    def test_matrix_of_cells_matches_solo_runs(self):
        """THE fused-matrix contract: one vmapped program over replicas
        with different scenarios == each scenario's solo scanned run."""
        names = sorted(CELLS)
        cfgs = [CELLS[n] for n in names]
        base = cfgs[0]
        n_blocks = 2

        # identical state init across cells (roles/H don't touch init)
        state = init_train_state(base, jax.random.PRNGKey(1))
        states = jax.tree.map(
            lambda x: jnp.broadcast_to(x, (len(cfgs), *x.shape)), state
        )
        specs = jax.tree.map(
            lambda *xs: jnp.stack(xs), *[spec_from_config(c) for c in cfgs]
        )

        fused_states, fused_metrics = jax.jit(
            jax.vmap(
                lambda st, sp: train_scanned(base, st, n_blocks, sp)
            )
        )(states, specs)

        for i, cfg in enumerate(cfgs):
            solo_state, solo_metrics = train_scanned(cfg, state, n_blocks)
            # float32-rounding tolerance: the vmapped fused program and
            # each solo program fuse differently (see test_train_block)
            _assert_trees_equal(
                jax.tree.map(lambda x: x[i], fused_states),
                solo_state,
                rtol=1e-4,
                atol=1e-6,
            )
            _assert_trees_equal(
                jax.tree.map(lambda x: x[i], fused_metrics),
                solo_metrics,
                rtol=1e-4,
                atol=1e-6,
            )


class TestFusedSweepCLI:
    @pytest.mark.slow
    def test_fused_matches_sequential_sweep(self, tmp_path):
        """`sweep --fused` writes the same artifact tree as the per-cell
        sweep, to float32 rounding, including the two-phase protocol."""
        import pandas as pd

        from rcmarl_tpu.cli import main

        common = [
            "sweep", "--scenarios", "coop", "malicious", "--H", "0", "1",
            "--seeds", "100", "200", "--n_episodes", "100",
            "--n_ep_fixed", "50", "--n_epochs", "2", "--buffer_size", "100",
            "--phases", "2",
        ]
        seq, fused = tmp_path / "seq", tmp_path / "fused"
        assert main(common + ["--out", str(seq)]) == 0
        assert main(common + ["--out", str(fused), "--fused"]) == 0
        pkls = sorted(p.relative_to(seq) for p in seq.rglob("*.pkl"))
        assert len(pkls) == 2 * 2 * 2 * 2  # scen x H x seed x phase
        assert pkls == sorted(p.relative_to(fused) for p in fused.rglob("*.pkl"))
        for rel in pkls:
            a = pd.read_pickle(seq / rel)
            b = pd.read_pickle(fused / rel)
            np.testing.assert_allclose(
                a.to_numpy(), b.to_numpy(), rtol=1e-4, atol=1e-6,
                err_msg=str(rel),
            )

    def test_fused_unfusable_config_exits_cleanly(self, tmp_path):
        """Fusability violations (e.g. --consensus_impl pallas, which the
        traced heterogeneous matrix cannot fuse) surface as SystemExit
        with a message, like cmd_sweep's other argument validation — not
        as a raw ValueError traceback."""
        from rcmarl_tpu.cli import main

        with pytest.raises(SystemExit) as exc:
            main([
                "sweep", "--fused", "--scenarios", "coop", "--H", "0",
                "--seeds", "100", "--n_episodes", "50", "--n_ep_fixed",
                "50", "--n_epochs", "1", "--buffer_size", "50",
                "--consensus_impl", "pallas", "--out", str(tmp_path),
            ])
        assert "sweep --fused" in str(exc.value)

    # ~19s CLI cell — tier-1 870s wall-budget shed (slow twin of the
    # fused-sweep cells above; skip-existing is also exercised by the
    # sweep smoke in ci_tier1.sh)
    @pytest.mark.slow
    def test_fused_skip_existing_complete(self, tmp_path, capsys):
        from rcmarl_tpu.cli import main

        args = [
            "sweep", "--fused", "--skip_existing", "--scenarios", "coop",
            "--H", "0", "--seeds", "100", "--n_episodes", "50",
            "--n_ep_fixed", "50", "--n_epochs", "1", "--buffer_size", "50",
            "--out", str(tmp_path),
        ]
        assert main(args) == 0
        assert (tmp_path / "coop" / "H=0" / "seed=100" / "sim_data1.pkl").exists()
        before = (tmp_path / "coop" / "H=0" / "seed=100" / "sim_data1.pkl").stat().st_mtime
        assert main(args) == 0
        assert "skipping" in capsys.readouterr().out
        after = (tmp_path / "coop" / "H=0" / "seed=100" / "sim_data1.pkl").stat().st_mtime
        assert before == after


class TestShardedMatrix:
    @pytest.mark.slow
    @needs_multicore
    def test_fused_matrix_on_mesh_matches_solo(self):
        """Cell fusion composes with mesh sharding (seed axis) AND
        agent-axis sharding: the sharded fused matrix equals each cell's
        unsharded solo run."""
        from rcmarl_tpu.parallel import make_mesh, train_matrix
        from rcmarl_tpu.training import init_train_state

        n = 8
        base = SMALL.replace(
            n_agents=n,
            agent_roles=(Roles.COOPERATIVE,) * n,
            in_nodes=circulant_in_nodes(n, 4),
        )
        cfgs = [
            base,
            base.replace(H=1),
            base.replace(
                agent_roles=(Roles.COOPERATIVE,) * 7 + (Roles.MALICIOUS,),
                H=1,
            ),
            base.replace(
                agent_roles=(Roles.COOPERATIVE,) * 7 + (Roles.GREEDY,),
                common_reward=True,
            ),
        ]
        seeds = [3, 4]
        mesh = make_mesh(8, seed_axis=4)  # ('seed', 'agent') = (4, 2)
        states, metrics = train_matrix(
            base, cfgs, seeds, n_blocks=2, mesh=mesh, shard_agents=True
        )
        for c, cfg in enumerate(cfgs):
            for s, seed in enumerate(seeds):
                i = c * len(seeds) + s
                solo = init_train_state(cfg, jax.random.PRNGKey(seed))
                solo_state, solo_metrics = train_scanned(cfg, solo, 2)
                np.testing.assert_allclose(
                    np.asarray(metrics.true_team_returns[i]),
                    np.asarray(solo_metrics.true_team_returns),
                    rtol=1e-4,
                    atol=1e-6,
                )
                for a, b in zip(
                    jax.tree.leaves(
                        jax.tree.map(lambda x: x[i], states.params)
                    ),
                    jax.tree.leaves(solo_state.params),
                ):
                    np.testing.assert_allclose(
                        np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5
                    )


# ~10s (compiles the spec path before reaching the raise) — tier-1
# 870s wall-budget shed
@pytest.mark.slow
def test_spec_with_explicit_pallas_raises():
    """An explicit consensus_impl='pallas' must NOT be silently
    downgraded on the traced-H path — the aggregation layer raises
    (auto still resolves to xla and works)."""
    from rcmarl_tpu.training import init_agent_params, update_block

    cfg = CELLS["coop_h1_common"].replace(consensus_impl="pallas")
    params = init_agent_params(jax.random.PRNGKey(0), cfg)
    batch = _fresh(cfg, 0.1)
    with pytest.raises(ValueError, match="traced H requires the xla"):
        update_block(
            cfg, params, batch, batch, jax.random.PRNGKey(1),
            spec_from_config(cfg),
        )
    auto = cfg.replace(consensus_impl="auto")
    out = update_block(
        auto, params, batch, batch, jax.random.PRNGKey(1),
        spec_from_config(auto),
    )
    assert all(
        bool(jnp.all(jnp.isfinite(l))) for l in jax.tree.leaves(out)
    )


@pytest.mark.slow
class TestCompileOnly:
    def test_sharded_matrix_compiles_on_any_host(self):
        """compile_only validates the agent-sharded fused program's
        shardings and collective lowering WITHOUT executing collectives,
        so it is safe even where needs_multicore skips execution.

        Rides the slow marker (25s; tier-1 870s wall budget): the CI
        graftlint cell now compiles matrix@sharded at mesh sizes
        {1,2,8} on every run (`lint --sharding`,
        rcmarl_tpu.lint.sharding), which subsumes this lowering check —
        the full suite (no -m filter) still runs it."""
        from rcmarl_tpu.parallel import make_mesh, train_matrix

        n = 8
        base = SMALL.replace(
            n_agents=n,
            agent_roles=(Roles.COOPERATIVE,) * n,
            in_nodes=circulant_in_nodes(n, 4),
        )
        cells = [base, base.replace(H=1)]
        mesh = make_mesh(8, seed_axis=4)
        out = train_matrix(
            base, cells, [3, 4], n_blocks=1, mesh=mesh,
            shard_agents=True, compile_only=True,
        )
        assert out is None


class TestFusableChecks:
    def test_rejects_shape_divergence(self):
        """Cells may differ ONLY in roles/H/common_reward."""
        from rcmarl_tpu.parallel import train_matrix

        base = CELLS["coop_h0"]
        widened = base.replace(hidden=(30, 30))
        with pytest.raises(ValueError, match="beyond"):
            train_matrix(base, [base, widened], [0], n_blocks=1)

    def test_rejects_pallas_impl(self):
        from rcmarl_tpu.parallel import train_matrix

        base = CELLS["coop_h0"].replace(consensus_impl="pallas")
        with pytest.raises(ValueError, match="XLA path"):
            train_matrix(base, [base], [0], n_blocks=1)

    def test_rejects_ragged_graph(self):
        from rcmarl_tpu.parallel import train_matrix

        base = CELLS["coop_h0"].replace(
            in_nodes=((0, 1, 2, 3), (1, 2, 3), (2, 3, 4), (3, 4, 0), (4, 0, 1)),
            H=0,
        )
        with pytest.raises(ValueError, match="uniform-degree"):
            train_matrix(base, [base], [0], n_blocks=1)


# The randomized spec-equivalence property test lives in
# tests/test_matrix_properties.py: it needs hypothesis (the `test`
# extra), and keeping the optional import out of THIS module means a
# missing hypothesis skips only the property test instead of taking the
# whole fused-matrix suite down as a collection error.
