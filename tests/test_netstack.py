"""Netstack equivalence matrix: the one-block critic+TR epoch
(``Config.netstack``, the default) pinned leaf-for-leaf against the
dual-launch comparison arm (``netstack=False``) — the contract that lets
the stacked path replace the historical one without renumbering any
golden trajectory.

The stacking is engineered to be exactly neutral: critic inputs and
first-layer rows are zero-padded to the TR width (padded columns are
exact zeros, so padded rows get bitwise-zero gradients —
tests/test_netstack_properties.py), phase-II aggregation of the combined
(n_in, P_critic + P_tr) block is elementwise along columns, and every
RNG stream (adversary fit shuffles, fault masks, corruption noise) is
drawn with the dual arm's exact key structure. On this backend the whole
update block is bitwise-identical between the arms for every mode with
hidden layers; the degenerate head-only (hidden=()) nets compare at
float32 rounding (their stacked projection contracts over a padded
feature axis).
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from rcmarl_tpu.agents.updates import (
    Batch,
    adv_critic_fit,
    adv_pair_fit,
    adv_tr_fit,
    consensus_update_one,
    consensus_update_pair,
    coop_local_critic_fit,
    coop_local_tr_fit,
    coop_pair_fit,
    netstack_pair_inputs,
    pair_bootstrap_targets,
)
from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.faults import FaultPlan, apply_link_faults, apply_link_faults_flat
from rcmarl_tpu.models.mlp import init_stacked_mlp, netstack_split, netstack_stack
from rcmarl_tpu.training.update import (
    _pair_block,
    _pair_segments,
    gather_neighbor_messages,
    init_agent_params,
    spec_from_config,
    update_block,
)

BASE = dict(
    n_agents=5,
    agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.GREEDY, Roles.MALICIOUS),
    in_nodes=circulant_in_nodes(5, 4),
    H=1,
    n_epochs=2,
    hidden=(8, 8),
    coop_fit_steps=3,
    adv_fit_epochs=2,
    adv_fit_batch=8,
    batch_size=8,
)

RAGGED = ((0, 1, 2, 3), (1, 2, 3), (2, 3, 4, 0), (3, 4, 0), (4, 0, 1))

PLAN = FaultPlan(
    drop_p=0.1, stale_p=0.2, corrupt_p=0.2, flip_p=0.1, nan_p=0.05, inf_p=0.05
)


def _mk_batch(key, cfg, B, full=False):
    ks = jax.random.split(key, 4)
    b = Batch(
        s=jax.random.normal(ks[0], (B, cfg.n_agents, cfg.n_states)),
        ns=jax.random.normal(ks[1], (B, cfg.n_agents, cfg.n_states)),
        a=jax.random.randint(ks[2], (B, cfg.n_agents, 1), 0, cfg.n_actions).astype(
            jnp.float32
        ),
        r=jax.random.normal(ks[3], (B, cfg.n_agents, 1)),
        mask=jnp.ones((B,), jnp.float32)
        if full
        else (jnp.arange(B) < B - 3).astype(jnp.float32),
    )
    return b


def _run_block(cfg, spec=None):
    params = init_agent_params(jax.random.PRNGKey(0), cfg)
    batch = _mk_batch(jax.random.PRNGKey(1), cfg, 40)
    fresh = _mk_batch(jax.random.PRNGKey(2), cfg, 16, full=True)
    return update_block(cfg, params, batch, fresh, jax.random.PRNGKey(3), spec)


def _assert_tree_equal(a, b, exact=True):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        if exact:
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        else:
            np.testing.assert_allclose(
                np.asarray(x), np.asarray(y), rtol=1e-5, atol=1e-7
            )


_EQUIVALENCE_MODES = {
    "static_h1": {},
    "h0": dict(H=0),
    "sanitize": dict(consensus_sanitize=True),
    "faults": dict(fault_plan=PLAN, consensus_sanitize=True),
    "ragged_masked": dict(in_nodes=RAGGED),
    "ragged_sanitize_faults": dict(
        in_nodes=RAGGED, consensus_sanitize=True, fault_plan=PLAN
    ),
    "xla_sort": dict(consensus_impl="xla_sort"),
    "pallas_interpret": dict(consensus_impl="pallas_interpret"),
    "pallas_interpret_sort_sanitize": dict(
        consensus_impl="pallas_interpret", consensus_sanitize=True
    ),
}

#: The cells that stay in tier-1: the clean static-H representative and
#: the sanitize arm. The expensive fault/ragged/pallas/h0/xla_sort
#: cells (13-29s each) ride the slow marker — the tier-1 870s wall
#: budget shed PR 8 applied to the fitstack matrix, with the same CI
#: compensation: ci_tier1.sh's netstack smoke cell drives the
#: ragged+sanitize+faults stacked-vs-dual wire-up through the real
#: trainer on every CI run, and the full matrix still runs under
#: `pytest tests/` (no -m filter).
_FAST_EQUIVALENCE_MODES = ("sanitize",)

_EQUIVALENCE_PARAMS = [
    m
    if m in _FAST_EQUIVALENCE_MODES
    else pytest.param(m, marks=pytest.mark.slow)
    for m in sorted(_EQUIVALENCE_MODES)
]


class TestBlockEquivalence:
    """update_block(netstack=True) == update_block(netstack=False),
    leaf for leaf, across every consensus mode."""

    MODES = _EQUIVALENCE_MODES

    @pytest.mark.parametrize("mode", _EQUIVALENCE_PARAMS)
    def test_pinned_leaf_for_leaf(self, mode):
        kw = dict(BASE)
        kw.update(self.MODES[mode])
        on = _run_block(Config(**kw, netstack=True))
        off = _run_block(Config(**kw, netstack=False))
        _assert_tree_equal(on, off)

    # ~20s — tier-1 870s wall-budget shed (same CI compensation as the
    # slow _EQUIVALENCE_PARAMS cells)
    @pytest.mark.slow
    def test_traced_spec(self):
        """The fused-matrix path: netstack spec-mode == dual spec-mode
        (same traced-H trim and compute-all-then-mask role plumbing)."""
        cfg_on = Config(**BASE, netstack=True)
        cfg_off = Config(**BASE, netstack=False)
        on = _run_block(cfg_on, spec_from_config(cfg_on))
        off = _run_block(cfg_off, spec_from_config(cfg_off))
        _assert_tree_equal(on, off)

    @pytest.mark.slow
    def test_head_only_nets(self):
        """hidden=() makes the two families' feature widths differ, so
        the stacked projection contracts over a padded axis — equal to
        float32 rounding rather than bitwise."""
        kw = dict(BASE, hidden=())
        on = _run_block(Config(**kw, netstack=True))
        off = _run_block(Config(**kw, netstack=False))
        _assert_tree_equal(on, off, exact=False)

    # ~42s — the heaviest netstack cell; ci_tier1.sh's netstack smoke
    # cell drives the sanitize+faults wire-up every CI run
    @pytest.mark.slow
    def test_with_diag_counters_match(self):
        """Degradation counters from the combined block == the sum the
        dual arm computes over its two per-tree blocks."""
        kw = dict(BASE, fault_plan=PLAN, consensus_sanitize=True)
        args = lambda cfg: (
            cfg,
            init_agent_params(jax.random.PRNGKey(0), cfg),
            _mk_batch(jax.random.PRNGKey(1), cfg, 40),
            _mk_batch(jax.random.PRNGKey(2), cfg, 16, full=True),
            jax.random.PRNGKey(3),
        )
        _, diag_on = update_block(*args(Config(**kw, netstack=True)), with_diag=True)
        _, diag_off = update_block(*args(Config(**kw, netstack=False)), with_diag=True)
        assert int(diag_on.nonfinite) == int(diag_off.nonfinite)
        assert int(diag_on.deficit) == int(diag_off.deficit)


class TestPairPrimitives:
    """The netstack building blocks against their dual-arm twins."""

    def _cfg(self, **kw):
        return Config(**dict(BASE, **kw))

    def _nets(self, cfg, key=0):
        k1, k2 = jax.random.split(jax.random.PRNGKey(key))
        critic = init_stacked_mlp(k1, cfg.n_agents, cfg.obs_dim, cfg.hidden, 1)
        tr = init_stacked_mlp(k2, cfg.n_agents, cfg.sa_dim, cfg.hidden, 1)
        return critic, tr

    def test_netstack_roundtrip(self):
        cfg = self._cfg()
        critic, tr = self._nets(cfg)
        c2, t2 = netstack_split(
            netstack_stack(critic, tr), (cfg.obs_dim, cfg.sa_dim)
        )
        _assert_tree_equal(critic, c2)
        _assert_tree_equal(tr, t2)

    def test_coop_pair_fit_matches_separate_fits(self):
        cfg = self._cfg()
        critic, tr = self._nets(cfg)
        batch = _mk_batch(jax.random.PRNGKey(1), cfg, 24)
        r = jnp.moveaxis(batch.r, 1, 0)
        x2 = netstack_pair_inputs(cfg, batch.s, batch.sa)
        stack2 = netstack_stack(critic, tr)
        pair, _ = jax.jit(
            lambda p2, cp, rr: coop_pair_fit(
                p2, x2, pair_bootstrap_targets(cfg, cp, batch.ns, rr),
                batch.mask, cfg,
            )
        )(stack2, critic, r)
        c_pair, t_pair = netstack_split(pair, (cfg.obs_dim, cfg.sa_dim))
        c_ref, _ = jax.jit(
            jax.vmap(
                lambda p, rr: coop_local_critic_fit(
                    p, batch.s, batch.ns, rr, batch.mask, cfg
                )
            )
        )(critic, r)
        t_ref, _ = jax.jit(
            jax.vmap(
                lambda p, rr: coop_local_tr_fit(p, batch.sa, rr, batch.mask, cfg)
            )
        )(tr, r)
        _assert_tree_equal(c_pair, c_ref)
        _assert_tree_equal(t_pair, t_ref)

    def test_adv_pair_fit_matches_separate_fits(self):
        """Same keys -> same shuffles -> identical minibatch trajectories."""
        cfg = self._cfg()
        critic, tr = self._nets(cfg)
        batch = _mk_batch(jax.random.PRNGKey(1), cfg, 24)
        r = jnp.moveaxis(batch.r, 1, 0)
        x2 = netstack_pair_inputs(cfg, batch.s, batch.sa)
        kc, kt = jax.random.PRNGKey(10), jax.random.PRNGKey(11)
        keys_c = jax.random.split(kc, cfg.n_agents)
        keys_t = jax.random.split(kt, cfg.n_agents)
        pair, _ = jax.jit(
            lambda p2, cp, rr: adv_pair_fit(
                jnp.stack([keys_c, keys_t]),
                p2, x2, pair_bootstrap_targets(cfg, cp, batch.ns, rr),
                batch.mask, cfg,
            )
        )(netstack_stack(critic, tr), critic, r)
        c_pair, t_pair = netstack_split(pair, (cfg.obs_dim, cfg.sa_dim))
        c_ref, _ = jax.jit(
            jax.vmap(
                lambda k, p, rr: adv_critic_fit(
                    k, p, batch.s, batch.ns, rr, batch.mask, cfg
                )
            )
        )(keys_c, critic, r)
        t_ref, _ = jax.jit(
            jax.vmap(
                lambda k, p, rr: adv_tr_fit(k, p, batch.sa, rr, batch.mask, cfg)
            )
        )(keys_t, tr, r)
        _assert_tree_equal(c_pair, c_ref)
        _assert_tree_equal(t_pair, t_ref)

    @pytest.mark.parametrize("valid", [None, (1.0, 1.0, 1.0, 0.0)])
    def test_consensus_pair_matches_two_single_updates(self, valid):
        cfg = self._cfg()
        msg_c, msg_t = self._nets(cfg, key=1)  # n_in == n_agents messages
        own_c = jax.tree.map(lambda l: l[0], msg_c)
        own_t = jax.tree.map(lambda l: l[0], msg_t)
        batch = _mk_batch(jax.random.PRNGKey(2), cfg, 16)
        v = None if valid is None else jnp.asarray(valid)
        blk = _pair_block(msg_c, msg_t)  # (n_in, P) — message stack as block
        x2 = netstack_pair_inputs(cfg, batch.s, batch.sa)
        pc, pt = jax.jit(
            lambda oc, ot, b: consensus_update_pair(
                oc, ot, b, x2, batch.mask, cfg, valid=v
            )
        )(own_c, own_t, blk[: cfg.n_in])
        rc = jax.jit(
            lambda own, nb, x: consensus_update_one(
                own, nb, x, batch.mask, cfg, valid=v
            )
        )(own_c, jax.tree.map(lambda l: l[: cfg.n_in], msg_c), batch.s)
        rt = jax.jit(
            lambda own, nb, x: consensus_update_one(
                own, nb, x, batch.mask, cfg, valid=v
            )
        )(own_t, jax.tree.map(lambda l: l[: cfg.n_in], msg_t), batch.sa)
        _assert_tree_equal(pc, rc)
        _assert_tree_equal(pt, rt)

    def test_flat_faults_match_tree_faults(self):
        """apply_link_faults_flat on the combined block == the two
        per-tree apply_link_faults calls, raveled — masks, noise, and
        stale replay all drawn from the dual arm's exact streams."""
        cfg = self._cfg()
        msg_c, msg_t = self._nets(cfg, key=3)
        carry_c, carry_t = self._nets(cfg, key=4)
        key = jax.random.PRNGKey(7)
        nbr_c = gather_neighbor_messages(cfg, msg_c)
        nbr_t = gather_neighbor_messages(cfg, msg_t)
        stale_c = gather_neighbor_messages(cfg, carry_c)
        stale_t = gather_neighbor_messages(cfg, carry_t)
        ref_c = apply_link_faults(jax.random.fold_in(key, 0), nbr_c, stale_c, PLAN)
        ref_t = apply_link_faults(jax.random.fold_in(key, 1), nbr_t, stale_t, PLAN)
        flat = apply_link_faults_flat(
            key,
            gather_neighbor_messages(cfg, _pair_block(msg_c, msg_t)),
            gather_neighbor_messages(cfg, _pair_block(carry_c, carry_t)),
            PLAN,
            _pair_segments(msg_c, msg_t),
        )
        # re-ravel the reference trees in the pair order and compare
        ref_pair = (
            (ref_c[:-1], ref_t[:-1]),
            (ref_c[-1], ref_t[-1]),
        )
        N, n_in = cfg.n_agents, cfg.n_in
        ref_flat = jnp.concatenate(
            [l.reshape(N, n_in, -1) for l in jax.tree.leaves(ref_pair)], axis=-1
        )
        np.testing.assert_array_equal(
            np.asarray(flat), np.asarray(ref_flat)
        )

    def test_auto_policy_resolves_by_backend(self):
        """netstack='auto' (the Config default) is the measured backend
        policy: dual-launch off-TPU, stacked on TPU — mirroring the
        consensus_impl='auto' precedent."""
        from rcmarl_tpu.training.update import netstack_enabled

        cfg = Config(**BASE)  # default netstack='auto'
        assert cfg.netstack == "auto"
        expected = jax.default_backend() == "tpu"
        assert netstack_enabled(cfg) == expected
        assert netstack_enabled(cfg.replace(netstack=True)) is True
        assert netstack_enabled(cfg.replace(netstack=False)) is False
        with pytest.raises(ValueError, match="netstack"):
            Config(**BASE, netstack="sideways")

    def test_segments_cover_block(self):
        cfg = self._cfg()
        msg_c, msg_t = self._nets(cfg)
        segs = _pair_segments(msg_c, msg_t)
        P = _pair_block(msg_c, msg_t).shape[-1]
        assert sum(s[3] for s in segs) == P
        assert sorted({t for t, *_ in segs}) == [0, 1]
        # offsets are contiguous and ordered
        off = 0
        for _, _, o, sz in segs:
            assert o == off
            off += sz
