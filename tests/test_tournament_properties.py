"""Property-based tournament-vs-sort equivalence (hypothesis).

Randomized twin of tests/test_tournament.py's deterministic matrix:
over arbitrary f32 inputs (duplicates, adversarial magnitudes, ±inf
payloads, NaN injections), the log-depth tournament selection and the
flattened one-launch tree layout must reproduce the sort-based
aggregation BITWISE. Guarded like the other property modules: a missing
hypothesis (the `test` extra) is a skip, never a collection error.
"""

import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

from rcmarl_tpu.ops.aggregation import (
    _k_largest,
    _k_smallest,
    resilient_aggregate,
    resilient_aggregate_tree,
)

finite = st.floats(-1e6, 1e6, allow_nan=False, width=32)
with_infs = st.floats(
    -1e6, 1e6, allow_nan=False, allow_infinity=True, width=32
)


@st.composite
def vals_k(draw, min_n=1, max_n=17, m=5, elements=finite):
    n = draw(st.integers(min_n, max_n))
    k = draw(st.integers(1, n))
    vals = draw(arrays(np.float32, (n, m), elements=elements))
    return vals, k


@settings(max_examples=60, deadline=None)
@given(vals_k(elements=with_infs))
def test_tournament_primitive_matches_sort(case):
    vals, k = case
    ref = np.sort(vals, axis=0)
    np.testing.assert_array_equal(
        np.asarray(_k_smallest(jnp.asarray(vals), k)), ref[:k]
    )
    np.testing.assert_array_equal(
        np.asarray(_k_largest(jnp.asarray(vals), k)), ref[vals.shape[0] - k :]
    )


@st.composite
def vals_and_h(draw, min_n=3, max_n=13, m=5, elements=finite):
    n = draw(st.integers(min_n, max_n))
    H = draw(st.integers(0, (n - 1) // 2))
    vals = draw(arrays(np.float32, (n, m), elements=elements))
    return vals, H


@settings(max_examples=40, deadline=None)
@given(vals_and_h())
def test_tournament_aggregate_matches_sort(case):
    vals, H = case
    a = resilient_aggregate(jnp.asarray(vals), H, impl="xla_sort")
    b = resilient_aggregate(jnp.asarray(vals), H, impl="xla")
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@settings(max_examples=30, deadline=None)
@given(vals_and_h(elements=with_infs), st.integers(0, 2**31 - 1))
def test_sanitized_tournament_matches_sort(case, nan_seed):
    """Random ±inf payloads plus random NaN injection: the sanitize
    sinks and the tournament's ±inf pads must coexist bitwise."""
    vals, H = case
    rng = np.random.default_rng(nan_seed)
    vals = np.where(rng.random(vals.shape) < 0.15, np.nan, vals).astype(
        np.float32
    )
    a = resilient_aggregate(jnp.asarray(vals), H, impl="xla_sort", sanitize=True)
    b = resilient_aggregate(jnp.asarray(vals), H, impl="xla", sanitize=True)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@settings(max_examples=25, deadline=None)
@given(vals_and_h(min_n=4, max_n=9))
def test_flat_tree_matches_per_leaf(case):
    vals, H = case
    tree = {
        "a": jnp.asarray(vals),
        "b": jnp.asarray(vals[:, :3] * 2.0 + 1.0),
    }
    a = resilient_aggregate_tree(tree, H, layout="flat")
    b = resilient_aggregate_tree(tree, H, layout="per_leaf")
    for k in tree:
        np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))
