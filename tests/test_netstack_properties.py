"""Property-based netstack contracts (hypothesis).

Randomized twin of tests/test_netstack.py's deterministic matrix. The
load-bearing property is the one the whole stacking trick rests on:
zero-padded input columns contribute BITWISE-ZERO gradient to the
padded first-layer rows — for arbitrary widths, batch contents,
targets, and step counts — so a padded critic inside the netstack walks
exactly the trajectory the unpadded critic walks, and the padded rows
never drift from zero. Guarded like the other property modules: a
missing hypothesis (the `test` extra) is a skip, never a collection
error.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st
from hypothesis.extra.numpy import arrays

from rcmarl_tpu.models.mlp import (
    init_mlp,
    mlp_forward,
    netstack_split,
    netstack_stack,
    pad_features,
)
from rcmarl_tpu.ops.fit import fit_mse_full_batch
from rcmarl_tpu.ops.losses import weighted_mse

finite = st.floats(-10.0, 10.0, allow_nan=False, width=32)


@st.composite
def fit_case(draw):
    """(in_dim, pad_to, hidden, B, x, target, n_steps, seed)."""
    in_dim = draw(st.integers(1, 6))
    pad_to = in_dim + draw(st.integers(1, 5))
    hidden = tuple(
        draw(st.lists(st.integers(1, 6), min_size=0, max_size=2))
    )
    B = draw(st.integers(1, 8))
    x = draw(arrays(np.float32, (B, in_dim), elements=finite))
    target = draw(arrays(np.float32, (B, 1), elements=finite))
    n_steps = draw(st.integers(1, 4))
    seed = draw(st.integers(0, 2**16))
    return in_dim, pad_to, hidden, B, x, target, n_steps, seed


@settings(deadline=None, max_examples=25)
@given(fit_case())
def test_padded_columns_contribute_bitwise_zero_gradient(case):
    """One gradient of the padded regression loss: the padded first-layer
    rows' entries are EXACTLY 0.0 — not small, zero."""
    in_dim, pad_to, hidden, B, x, target, _, seed = case
    params = init_mlp(jax.random.PRNGKey(seed), in_dim, hidden, 1)
    W1, b1 = params[0]
    padded = ((jnp.pad(W1, ((0, pad_to - in_dim), (0, 0))), b1),) + params[1:]
    xp = pad_features(jnp.asarray(x), pad_to)

    g = jax.grad(
        lambda p: weighted_mse(mlp_forward(p, xp), jnp.asarray(target))
    )(padded)
    pad_rows = np.asarray(g[0][0][in_dim:])
    np.testing.assert_array_equal(pad_rows, np.zeros_like(pad_rows))


@settings(deadline=None, max_examples=15)
@given(fit_case())
def test_padded_fit_rows_stay_zero_and_trim_to_unpadded_fit(case):
    """Across a whole multi-step fit: padded rows stay exactly zero, and
    the trimmed padded params equal the unpadded fit leaf for leaf."""
    in_dim, pad_to, hidden, B, x, target, n_steps, seed = case
    params = init_mlp(jax.random.PRNGKey(seed), in_dim, hidden, 1)
    W1, b1 = params[0]
    padded = ((jnp.pad(W1, ((0, pad_to - in_dim), (0, 0))), b1),) + params[1:]
    x = jnp.asarray(x)
    xp = pad_features(x, pad_to)
    target = jnp.asarray(target)
    mask = jnp.ones((B,), jnp.float32)
    fwd = lambda p, xx: mlp_forward(p, xx)

    fit_pad, _ = fit_mse_full_batch(padded, fwd, xp, target, mask, n_steps, 0.05)
    fit_ref, _ = fit_mse_full_batch(params, fwd, x, target, mask, n_steps, 0.05)

    pad_rows = np.asarray(fit_pad[0][0][in_dim:])
    np.testing.assert_array_equal(pad_rows, np.zeros_like(pad_rows))
    trimmed = ((fit_pad[0][0][:in_dim], fit_pad[0][1]),) + fit_pad[1:]
    for a, b in zip(jax.tree.leaves(trimmed), jax.tree.leaves(fit_ref)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )


@settings(deadline=None, max_examples=10)
@given(st.integers(1, 8), st.integers(1, 8), st.integers(1, 6), st.integers(0, 2**16))
def test_netstack_roundtrip_property(d_a, extra, h, seed):
    """stack -> split is the identity for arbitrary width pairs."""
    d_b = d_a + extra
    k1, k2 = jax.random.split(jax.random.PRNGKey(seed))
    a = init_mlp(k1, d_a, (h,), 1)
    b = init_mlp(k2, d_b, (h,), 1)
    a2, b2 = netstack_split(netstack_stack(a, b), (d_a, d_b))
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(a2)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(jax.tree.leaves(b), jax.tree.leaves(b2)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
