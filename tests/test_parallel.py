"""Multi-device tests on the virtual 8-device CPU mesh (conftest forces
``xla_force_host_platform_device_count=8`` — SURVEY.md §4's test story)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.parallel import (
    init_states,
    make_mesh,
    train_block_parallel,
    train_parallel,
)
from rcmarl_tpu.training import init_train_state, train_scanned
from tests.conftest import needs_multicore

TINY = Config(
    n_episodes=2,
    max_ep_len=4,
    n_ep_fixed=2,
    n_epochs=1,
    buffer_size=16,
    coop_fit_steps=2,
    adv_fit_epochs=1,
    adv_fit_batch=4,
    batch_size=4,
)


def test_has_8_devices():
    assert jax.device_count() == 8


class TestSeedParallel:
    @pytest.mark.slow
    def test_matches_single_replica(self):
        """Sharded multi-seed training must be bitwise-equivalent in
        structure and numerically equivalent to running each seed alone."""
        cfg = TINY
        mesh = make_mesh(4)
        seeds = [100, 200, 300, 400]
        states, metrics = train_parallel(cfg, seeds, n_blocks=2, mesh=mesh)
        assert metrics.true_team_returns.shape == (4, 4)

        # replica 1 alone
        solo = init_train_state(cfg, jax.random.PRNGKey(200))
        solo, solo_m = jax.jit(lambda s: train_scanned(cfg, s, 2))(solo)
        np.testing.assert_allclose(
            np.asarray(metrics.true_team_returns[1]),
            np.asarray(solo_m.true_team_returns),
            rtol=1e-4,
        )
        for a, b in zip(
            jax.tree.leaves(jax.tree.map(lambda l: l[1], states.params)),
            jax.tree.leaves(solo.params),
        ):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5)

    @pytest.mark.slow
    def test_block_parallel_resume(self):
        cfg = TINY
        mesh = make_mesh(2)
        states = init_states(cfg, [1, 2])
        states, m1 = train_block_parallel(cfg, states, mesh)
        states, m2 = train_block_parallel(cfg, states, mesh)
        assert np.all(np.asarray(states.block) == 2)
        assert np.all(np.isfinite(np.asarray(m2.true_team_returns)))

    def test_rejects_bad_mesh_split(self):
        with pytest.raises(ValueError):
            make_mesh(8, seed_axis=3)

    @pytest.mark.slow
    def test_repeated_calls_reuse_compiled_program(self):
        """Resume calls (sweep phase 2, timed bench reps) must hit the
        compiled-program cache instead of re-tracing a fresh closure, and
        resumed execution must stay correct (blocks advance, finite)."""
        from rcmarl_tpu.parallel import seeds as seeds_mod

        cfg = TINY
        mesh = make_mesh(2)
        seeds_mod._JIT_CACHE.clear()
        states, _ = train_parallel(cfg, seeds=[1, 2], n_blocks=1, mesh=mesh)
        assert len(seeds_mod._JIT_CACHE) == 1
        fn_first = next(iter(seeds_mod._JIT_CACHE.values()))
        states, m = train_parallel(cfg, states=states, n_blocks=1, mesh=mesh)
        assert len(seeds_mod._JIT_CACHE) == 1
        assert next(iter(seeds_mod._JIT_CACHE.values())) is fn_first
        assert np.all(np.asarray(states.block) == 2)
        assert np.all(np.isfinite(np.asarray(m.true_team_returns)))


class TestAgentSharding:
    @pytest.mark.slow
    @needs_multicore
    def test_agent_axis_sharded_consensus(self):
        """8 agents sharded 2-way over the 'agent' mesh axis: the consensus
        gather lowers to cross-device collectives and still matches the
        unsharded result."""
        n = 8
        cfg = TINY.replace(
            n_agents=n,
            agent_roles=(Roles.COOPERATIVE,) * 7 + (Roles.GREEDY,),
            in_nodes=circulant_in_nodes(n, 4),
            H=1,
        )
        mesh = make_mesh(8, seed_axis=4)  # ('seed', 'agent') = (4, 2)
        seeds = [7, 8, 9, 10]
        states, metrics = train_parallel(
            cfg, seeds, n_blocks=1, mesh=mesh, shard_agents=True
        )
        states_r, metrics_r = train_parallel(
            cfg, seeds, n_blocks=1, mesh=make_mesh(4), shard_agents=False
        )
        np.testing.assert_allclose(
            np.asarray(metrics.true_team_returns),
            np.asarray(metrics_r.true_team_returns),
            rtol=1e-4,
        )
        for a, b in zip(
            jax.tree.leaves(states.params), jax.tree.leaves(states_r.params)
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5
            )


class TestPhaseReset:
    """Reference two-phase protocol boundary (SURVEY.md §5): weights and
    goal layout carry over; Adam moments, buffer, block counter, and RNG
    reset exactly as a phase-1 init from the same seed."""

    # ~12s — tier-1 870s wall-budget shed
    @pytest.mark.slow
    def test_reset_semantics(self):
        from rcmarl_tpu.parallel.seeds import (
            init_states,
            reset_states_for_phase,
            train_parallel,
        )

        cfg = TINY
        seeds = [7, 8]
        states, _ = train_parallel(cfg, seeds=seeds, n_blocks=2)
        reset = reset_states_for_phase(cfg, states, seeds)
        fresh = init_states(cfg, seeds)

        # weights + goal kept from the trained state
        for a, b in zip(
            jax.tree.leaves(reset.params.actor), jax.tree.leaves(states.params.actor)
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(
            np.asarray(reset.desired), np.asarray(states.desired)
        )
        # Adam moments zeroed, step count zeroed
        assert np.all(np.asarray(reset.params.actor_opt.count) == 0)
        for m in jax.tree.leaves(reset.params.actor_opt.m):
            assert np.all(np.asarray(m) == 0)
        # buffer, block, and RNG match a fresh phase-1 init from the seed
        assert np.all(np.asarray(reset.buffer.count) == 0)
        np.testing.assert_array_equal(np.asarray(reset.block), np.zeros(2))
        np.testing.assert_array_equal(
            np.asarray(reset.key), np.asarray(fresh.key)
        )
        np.testing.assert_array_equal(
            np.asarray(reset.initial), np.asarray(fresh.initial)
        )
