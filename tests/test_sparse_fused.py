"""Sparse one-kernel epoch (ISSUE 19): the fused consensus kernel with
the scheduled graph as a TRACED scalar-prefetch operand, and the
stacked-schedule multi-block scan.

Four contracts:

1. **Kernel sanitize-matrix parity** — the sparse fused kernel
   (``fused_pair_consensus`` with an ``(N, deg)`` int32 graph, gather
   via in-register dynamic row selects) is pinned leaf-for-leaf BITWISE
   against the XLA sparse chain (``sparse_gather`` ->
   ``apply_link_faults_flat`` -> vmapped ``resilient_aggregate``)
   across {clean, faulted} x {H=0, H>0, traced H} x sanitize — except
   the PLAIN cells (sanitize off), which keep the kernel's historical
   allclose-at-f32 contract (the ``jnp.mean`` epilogue's bits are
   XLA-fusion-context-dependent — tests/test_fused_epoch.py).
2. **Stacked-schedule operand** — ``schedule_window(cfg, start, S)``
   slices are BITWISE the per-block ``scheduled_in_nodes`` sequence for
   arbitrary ``graph_every``/seed/offset, and a mid-window resume
   replays the tail bitwise (``window(start+k, S-k) ==
   window(start, S)[k:]``) — deterministic sweep always; hypothesis
   fuzz twin when the optional dep exists.
3. **Scanned window == host loop** — ``train_scanned`` over a
   ``schedule_window`` operand is bitwise the S host-looped
   ``train_block(..., graph=w[b])`` dispatches, and the donated
   windowed entry (``train_window_donated``) matches too.
4. **Mega-population fused arm** — ``megapop_consensus_block`` on a
   fused impl (kernel, sanitized) is bitwise its XLA sparse arm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from functools import partial

from rcmarl_tpu.config import (
    Config,
    circulant_in_nodes,
    schedule_window,
    scheduled_in_nodes,
)
from rcmarl_tpu.faults import FaultPlan, apply_link_faults_flat
from rcmarl_tpu.ops.aggregation import resilient_aggregate
from rcmarl_tpu.ops.exchange import sparse_gather, validate_graph
from rcmarl_tpu.ops.pallas_consensus import (
    draw_fault_fields,
    fused_pair_consensus,
)

N = 4
DEG = 3
P = 260
SPLIT = 130
#: fake 2-segment layout: critic columns then TR columns
SEGS = ((0, 0, 0, SPLIT), (1, 0, SPLIT, P - SPLIT))
PLAN = FaultPlan(drop_p=0.3, nan_p=0.2, stale_p=0.2, flip_p=0.2, inf_p=0.2)
GRAPH = jnp.asarray(
    [[0, 1, 2], [1, 3, 0], [2, 0, 3], [3, 2, 1]], jnp.int32
)


def _msgs(seed=0):
    return jax.random.normal(jax.random.PRNGKey(seed), (N, P), jnp.float32)


def _arms(H, sanitize, faulted):
    """(xla_chain, fused) closures over (msgs, graph) — the two arms of
    the ``sparse_consensus`` ledger pair at test scale."""
    carry = _msgs(7)
    fkey = jax.random.PRNGKey(3)

    def xla_arm(msgs, graph):
        nbr = sparse_gather(msgs, graph)
        if faulted:
            stale = sparse_gather(carry, graph)
            nbr = apply_link_faults_flat(fkey, nbr, stale, PLAN, SEGS)
        return jax.vmap(
            lambda v: resilient_aggregate(v, H, "xla", sanitize=sanitize)
        )(nbr)

    def fused_arm(msgs, graph):
        fields = (
            draw_fault_fields(fkey, PLAN, N, DEG, SEGS) if faulted else None
        )
        return fused_pair_consensus(
            msgs,
            H,
            in_nodes=graph,
            tree_split=SPLIT,
            sanitize=sanitize,
            plan=PLAN if faulted else None,
            stale=carry if faulted else None,
            fields=fields,
            interpret=True,
        )

    return jax.jit(xla_arm), jax.jit(fused_arm)


def _assert_bitwise(a, b):
    np.testing.assert_array_equal(
        np.asarray(a).view(np.uint32), np.asarray(b).view(np.uint32)
    )


class TestSparseKernelMatrix:
    def test_sanitize_clean_bitwise(self):
        """The fast tier-1 representative: sanitized clean cell, H=1."""
        xla, fused = _arms(1, True, False)
        _assert_bitwise(xla(_msgs(), GRAPH), fused(_msgs(), GRAPH))

    @pytest.mark.slow
    @pytest.mark.parametrize("H", [0, 1])
    @pytest.mark.parametrize("faulted", [False, True])
    def test_sanitize_matrix_bitwise(self, H, faulted):
        xla, fused = _arms(H, True, faulted)
        _assert_bitwise(xla(_msgs(), GRAPH), fused(_msgs(), GRAPH))

    @pytest.mark.slow
    @pytest.mark.parametrize("faulted", [False, True])
    def test_faulted_unsanitized_bitwise(self, faulted):
        """Sanitize-off FAULTED cells stay bitwise: the fault chain is
        threshold compares + selects, no reassociable reduction."""
        if not faulted:
            pytest.skip("clean plain cells are the allclose contract")
        xla, fused = _arms(1, False, True)
        _assert_bitwise(xla(_msgs(), GRAPH), fused(_msgs(), GRAPH))

    @pytest.mark.slow
    @pytest.mark.parametrize("H", [0, 1])
    def test_plain_cells_allclose(self, H):
        """The sanitize-off clean contract is the kernel's historical
        PLAIN one: allclose at f32 rounding, never bitwise-required."""
        xla, fused = _arms(H, False, False)
        np.testing.assert_allclose(
            np.asarray(xla(_msgs(), GRAPH)),
            np.asarray(fused(_msgs(), GRAPH)),
            atol=1e-6,
        )

    @pytest.mark.slow
    @pytest.mark.parametrize("sanitize", [False, True])
    def test_traced_h_bitwise(self, sanitize):
        xla, fused = _arms(jnp.asarray(1, jnp.int32), sanitize, True)
        _assert_bitwise(xla(_msgs(), GRAPH), fused(_msgs(), GRAPH))

    @pytest.mark.slow
    def test_resample_is_data_not_program(self):
        """A fresh graph re-dispatches the SAME compiled sparse kernel
        (scalar-prefetch operand = data) and stays bitwise."""
        xla, fused = _arms(1, True, True)
        g2 = jnp.asarray(
            [[0, 2, 3], [1, 0, 2], [2, 3, 1], [3, 1, 0]], jnp.int32
        )
        fused(_msgs(), GRAPH)
        _assert_bitwise(xla(_msgs(), g2), fused(_msgs(), g2))
        assert int(fused._cache_size()) == 1

    def test_sparse_rejects_validity_mask(self):
        """Scheduled graphs are regular by construction — a validity
        mask on the sparse path is a caller bug, rejected loudly."""
        with pytest.raises(ValueError, match="valid"):
            fused_pair_consensus(
                _msgs(),
                1,
                in_nodes=GRAPH,
                tree_split=SPLIT,
                valid=((True,) * DEG,) * N,
                interpret=True,
            )


# --------------------------------------------------------------------------
# The stacked-schedule operand
# --------------------------------------------------------------------------


def _sched_cfg(graph_every=2, seed=0, n=8, degree=3, **kw):
    base = dict(
        n_agents=n,
        agent_roles=(0,) * n,
        in_nodes=circulant_in_nodes(n, degree),
        H=1,
        graph_schedule="random_geometric",
        graph_degree=degree,
        graph_every=graph_every,
        graph_seed=seed,
    )
    base.update(kw)
    return Config(**base)


def _check_window_matches_blocks(graph_every, seed, start, S):
    cfg = _sched_cfg(graph_every=graph_every, seed=seed)
    w = schedule_window(cfg, start, S)
    assert w.shape == (S, cfg.n_agents, DEG) and w.dtype == np.int32
    for b in range(S):
        np.testing.assert_array_equal(
            w[b],
            np.asarray(
                validate_graph(
                    scheduled_in_nodes(cfg, start + b), cfg.n_agents
                )
            ),
        )


def _check_mid_window_resume(graph_every, seed, start, S, k):
    cfg = _sched_cfg(graph_every=graph_every, seed=seed)
    full = schedule_window(cfg, start, S)
    tail = schedule_window(cfg, start + k, S - k)
    np.testing.assert_array_equal(full[k:], tail)


class TestScheduleWindow:
    def test_window_matches_per_block_sequence(self):
        for graph_every in (1, 2, 3):
            for seed in (0, 7):
                for start in (0, 1, 5):
                    _check_window_matches_blocks(graph_every, seed, start, 4)

    def test_mid_window_resume_bitwise(self):
        """Resuming at block ``start+k`` replays the remaining window
        bitwise — a checkpoint mid-window loses nothing."""
        for graph_every in (1, 2, 3):
            for k in (1, 2, 3):
                _check_mid_window_resume(2, 11, 3, 4, k)
                _check_mid_window_resume(graph_every, 5, 0, 4, k)

    def test_window_spans_resample_boundary(self):
        """graph_every=2, S=4 from an odd start: the window must change
        content exactly at the resample boundaries."""
        cfg = _sched_cfg(graph_every=2, seed=3)
        w = schedule_window(cfg, 1, 4)  # blocks 1,2,3,4 -> rounds 0,1,1,2
        assert (w[1] == w[2]).all()  # same round
        assert not (w[0] == w[1]).all()  # round 0 -> 1
        assert not (w[2] == w[3]).all()  # round 1 -> 2

    def test_window_rejections(self):
        cfg = _sched_cfg()
        with pytest.raises(ValueError):
            schedule_window(cfg, 0, 0)
        with pytest.raises(ValueError):
            schedule_window(cfg, -1, 2)

    def test_train_scanned_rejections(self):
        from rcmarl_tpu.lint.configs import tiny_cfg
        from rcmarl_tpu.training.trainer import (
            init_train_state,
            train_scanned,
        )

        scfg = tiny_cfg().replace(
            graph_schedule="random_geometric", graph_degree=3
        )
        state = init_train_state(scfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="schedule_window"):
            train_scanned(scfg, state, 2)
        stat = tiny_cfg()
        sstate = init_train_state(stat, jax.random.PRNGKey(0))
        w = schedule_window(scfg, 0, 2)
        with pytest.raises(ValueError, match="static"):
            train_scanned(stat, sstate, 2, graphs=w)
        with pytest.raises(ValueError, match="n_blocks"):
            train_scanned(scfg, state, 3, graphs=w)


try:  # the fuzzing twins, when the optional dep exists
    from hypothesis import given, settings, strategies as st

    @given(
        st.integers(min_value=1, max_value=4),  # graph_every
        st.integers(min_value=0, max_value=2**20),  # graph_seed
        st.integers(min_value=0, max_value=17),  # start block
        st.integers(min_value=1, max_value=5),  # window length
    )
    @settings(max_examples=25, deadline=None)
    def test_schedule_window_fuzzed(graph_every, seed, start, S):
        _check_window_matches_blocks(graph_every, seed, start, S)

    @given(
        st.integers(min_value=1, max_value=4),  # graph_every
        st.integers(min_value=0, max_value=2**20),  # graph_seed
        st.integers(min_value=0, max_value=9),  # start block
        st.integers(min_value=2, max_value=5),  # window length
        st.data(),
    )
    @settings(max_examples=25, deadline=None)
    def test_mid_window_resume_fuzzed(graph_every, seed, start, S, data):
        k = data.draw(st.integers(min_value=1, max_value=S - 1))
        _check_mid_window_resume(graph_every, seed, start, S, k)

except ImportError:  # pragma: no cover - hypothesis not installed
    pass


# --------------------------------------------------------------------------
# Scanned window vs host loop
# --------------------------------------------------------------------------


def _tiny_train_cfg(**kw):
    base = dict(
        n_agents=6,
        agent_roles=(0,) * 6,
        in_nodes=circulant_in_nodes(6, 3),
        nrow=3,
        ncol=3,
        n_episodes=2,
        max_ep_len=4,
        n_ep_fixed=2,
        n_epochs=1,
        buffer_size=16,
        coop_fit_steps=2,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
        H=1,
        graph_schedule="random_geometric",
        graph_degree=3,
        graph_every=2,
        consensus_sanitize=True,
        fault_plan=FaultPlan(nan_p=0.2, drop_p=0.2, seed=5),
    )
    base.update(kw)
    return Config(**base)


def _leaves_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@pytest.mark.slow
class TestScannedWindow:
    S = 3  # odd window over graph_every=2: straddles a resample

    def test_scanned_bitwise_vs_host_loop(self):
        from rcmarl_tpu.training.trainer import (
            init_train_state,
            train_block,
            train_scanned,
        )

        cfg = _tiny_train_cfg()
        w = schedule_window(cfg, 0, self.S)
        state_h = init_train_state(cfg, jax.random.PRNGKey(0))
        state_s = init_train_state(cfg, jax.random.PRNGKey(0))
        for b in range(self.S):
            state_h, _ = train_block(cfg, state_h, graph=jnp.asarray(w[b]))
        state_s, metrics = train_scanned(cfg, state_s, self.S, graphs=w)
        _leaves_equal(state_s.params, state_h.params)
        assert int(state_s.block) == int(state_h.block)
        # one metrics row per episode, flattened in episode order
        assert jax.tree.leaves(metrics)[0].shape[0] == self.S * cfg.n_ep_fixed

    def test_donated_window_entry_matches(self):
        from rcmarl_tpu.training.trainer import (
            init_train_state,
            train_scanned,
            train_window_donated,
        )

        cfg = _tiny_train_cfg()
        w = schedule_window(cfg, 0, self.S)
        ref, _ = train_scanned(
            cfg, init_train_state(cfg, jax.random.PRNGKey(0)), self.S,
            graphs=w,
        )
        don, _ = train_window_donated(
            cfg, init_train_state(cfg, jax.random.PRNGKey(0)), self.S,
            jnp.asarray(w),
        )
        _leaves_equal(don.params, ref.params)

    def test_scanned_fused_impl_bitwise(self):
        """The composed tentpole: the SPARSE one-kernel epoch under the
        stacked-schedule scan matches the XLA sparse chain's scan."""
        from rcmarl_tpu.training.trainer import (
            init_train_state,
            train_scanned,
        )

        cfg_x = _tiny_train_cfg()
        cfg_p = _tiny_train_cfg(consensus_impl="pallas_fused_interpret")
        w = schedule_window(cfg_x, 0, 2)
        out_x, _ = train_scanned(
            cfg_x, init_train_state(cfg_x, jax.random.PRNGKey(0)), 2,
            graphs=w,
        )
        out_p, _ = train_scanned(
            cfg_p, init_train_state(cfg_p, jax.random.PRNGKey(0)), 2,
            graphs=w,
        )
        _leaves_equal(out_p.params, out_x.params)


# --------------------------------------------------------------------------
# Mega-population fused arm
# --------------------------------------------------------------------------


class TestMegapopFusedArm:
    def _run(self, impl):
        from rcmarl_tpu.parallel.megapop import megapop_consensus_block

        cfg = _sched_cfg(n=8, degree=3, consensus_impl=impl)
        block = jax.random.normal(
            jax.random.PRNGKey(2), (8, 40), jnp.float32
        )
        graph = jnp.asarray(
            validate_graph(scheduled_in_nodes(cfg, 0), 8, 3, cfg.H)
        )
        return megapop_consensus_block(cfg, block, graph)

    def test_fused_arm_bitwise_vs_xla(self):
        _assert_bitwise(self._run("xla"), self._run("pallas_fused_interpret"))
