"""Property-based fused-matrix equivalence (hypothesis).

Split out of tests/test_matrix.py so the optional hypothesis dependency
(the `test` extra — `pip install -e .[test]`) can be guarded with a
module-level importorskip without skipping the deterministic matrix
tests alongside it: a missing hypothesis must be a SKIP, never a
collection error.
"""

import jax
import pytest

pytest.importorskip("hypothesis")

from hypothesis import given, settings
from hypothesis import strategies as st

from rcmarl_tpu.config import Roles
from rcmarl_tpu.training import init_agent_params, update_block
from rcmarl_tpu.training.update import spec_from_config
from tests.test_matrix import _assert_trees_equal, _cell_cfg
from tests.test_trainer import _fresh


class TestSpecEquivalenceProperty:
    """Random scenario knobs, not just the five hand-picked cells: ANY
    role composition x H x reward mode must match the static path
    (cfg-specialized, compiled per composition) to float32 rounding.

    Tolerance note: the hand-picked cells in TestSpecEquivalence are
    bitwise-equal, but that is not guaranteed in general — e.g. the
    traced ``jnp.where(common_reward, r_team, r_agents)`` select and the
    static broadcast compile to differently-fused programs, which can
    differ by ~1e-8 under common_reward with adversaries present
    (hypothesis found roles=[C,C,C,G,G], H=0, common=True). Semantics
    are identical; only XLA fusion order differs."""

    @pytest.mark.slow
    @settings(max_examples=6, deadline=None)
    @given(
        roles=st.lists(
            st.sampled_from(
                [Roles.COOPERATIVE, Roles.GREEDY, Roles.FAULTY,
                 Roles.MALICIOUS]
            ),
            min_size=5,
            max_size=5,
        ),
        H=st.integers(min_value=0, max_value=1),
        common=st.booleans(),
        seed=st.integers(min_value=0, max_value=2**16),
    )
    def test_random_cell_matches_static(self, roles, H, common, seed):
        cfg = _cell_cfg(roles=tuple(roles), H=H, common_reward=common)
        base = _cell_cfg()  # all-cooperative, H=0, private reward
        params = init_agent_params(jax.random.PRNGKey(seed), cfg)
        batch, fresh = _fresh(cfg, 0.1), _fresh(cfg, 0.3)
        key = jax.random.PRNGKey(seed + 1)
        static = update_block(cfg, params, batch, fresh, key)
        traced = update_block(
            base, params, batch, fresh, key, spec_from_config(cfg)
        )
        _assert_trees_equal(static, traced, rtol=1e-5, atol=1e-7)
