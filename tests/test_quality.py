"""Tests for the episodes-to-quality analysis (analysis/quality.py).

BASELINE.json's second metric ("episodes-to-return-threshold") must be
computed, not asserted: these tests pin the crossing-detection math on
synthetic curves with known crossings, the threshold convention
(within-tol of a NEGATIVE converged return), the bench-row selection
behind the wall-clock columns, and the QUALITY.md generator end-to-end
on a synthetic two-tree layout.
"""

import json

import numpy as np
import pandas as pd
import pytest

from rcmarl_tpu.analysis.quality import (
    episode_throughput_from_bench,
    episodes_to_threshold,
    quality_table,
    write_quality_md,
)


def _write_run(run_dir, curve, phases: int = 1):
    """Write a sim_data phase tree for one seed with the given team curve."""
    run_dir.mkdir(parents=True, exist_ok=True)
    splits = np.array_split(np.asarray(curve, np.float64), phases)
    for i, part in enumerate(splits, start=1):
        pd.DataFrame(
            {
                "True_team_returns": part,
                "True_adv_returns": np.zeros_like(part),
                "Estimated_team_returns": part,
            }
        ).to_pickle(run_dir / f"sim_data{i}.pkl")


class TestEpisodesToThreshold:
    def test_known_crossing(self):
        curve = pd.Series(np.linspace(-10.0, 0.0, 101))  # hits -5 at idx 50
        assert episodes_to_threshold(curve, -5.0) == 50

    def test_never_reached(self):
        curve = pd.Series(np.full(100, -8.0))
        assert np.isnan(episodes_to_threshold(curve, -5.0))

    def test_first_crossing_wins(self):
        # noisy dip back below the threshold after the first touch does
        # not move the crossing
        curve = pd.Series([-9.0, -4.0, -6.0, -4.0])
        assert episodes_to_threshold(curve, -5.0) == 1


class TestQualityTable:
    @pytest.fixture()
    def trees(self, tmp_path):
        """Reference converges to -5.0 slowly; ours reaches it earlier."""
        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        n = 1000
        # linear approach to the plateau, then flat
        ref_curve = np.concatenate(
            [np.linspace(-9.0, -5.0, 800), np.full(200, -5.0)]
        )
        mine_curve = np.concatenate(
            [np.linspace(-9.0, -5.0, 400), np.full(600, -5.0)]
        )
        for seed in (100, 200):
            _write_run(ref / "coop" / "H=0" / f"seed={seed}", ref_curve, 2)
            _write_run(mine / "coop" / "H=0" / f"seed={seed}", mine_curve, 2)
        assert len(ref_curve) == len(mine_curve) == n
        return mine, ref

    def test_crossing_order_and_threshold(self, trees):
        mine, ref = trees
        table = quality_table(mine, ref, window=200, tol=0.05, rolling=1)
        assert list(table.scenario) == ["coop"] and list(table.H) == [0]
        row = table.iloc[0]
        # converged ref mean = -5.0, threshold 5% below: -5.25
        assert row.ref_final == pytest.approx(-5.0)
        assert row.threshold == pytest.approx(-5.25)
        # ours crosses -5.25 at 400 * (9-5.25)/(9-5) = 375; ref at 750
        assert row.ep_mine == pytest.approx(375, abs=2)
        assert row.ep_ref == pytest.approx(750, abs=2)
        assert row.ep_ratio == pytest.approx(2.0, rel=0.02)

    def test_missing_mine_cell_is_nan(self, trees, tmp_path):
        _, ref = trees
        empty = tmp_path / "empty"
        empty.mkdir()
        table = quality_table(empty, ref, window=200, tol=0.05, rolling=1)
        assert np.isnan(table.iloc[0].ep_mine)
        assert np.isnan(table.iloc[0].ep_ratio)
        assert np.isfinite(table.iloc[0].ep_ref)

    def test_rolling_smoothing_applied(self, tmp_path):
        """A single-episode spike must not count as reaching quality
        under a rolling window larger than the spike."""
        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        base = np.full(600, -8.0)
        ref_curve = base.copy()
        ref_curve[-200:] = -5.0  # genuine convergence
        spike = base.copy()
        spike[100] = 0.0  # one-episode outlier
        spike[-200:] = -5.0
        _write_run(ref / "coop" / "H=0" / "seed=100", ref_curve)
        _write_run(mine / "coop" / "H=0" / "seed=100", spike)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=50)
        # the spike averages to -7.84 over 50 episodes: no early crossing
        assert table.iloc[0].ep_mine > 300
        assert not table.iloc[0].degenerate

    def test_full_window_required(self, tmp_path):
        """The first `rolling` episodes cannot cross — a crossing needs a
        fully-populated smoothing window (min_periods=rolling)."""
        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        curve = np.full(300, -5.0)  # at threshold from episode 0
        _write_run(ref / "coop" / "H=0" / "seed=100", curve)
        _write_run(mine / "coop" / "H=0" / "seed=100", curve)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=50)
        row = table.iloc[0]
        # earliest possible crossing is the first full window (index 49)
        assert row.ep_ref == 49
        assert row.ep_mine == 49

    def test_mine_only_cell_appears_as_nan_row(self, trees, tmp_path):
        """A cell swept locally with no reference counterpart must still
        appear (all-NaN), not be silently dropped — and must render as
        'no data', not as a sample-efficiency verdict."""
        mine, ref = trees
        _write_run(
            mine / "newscen" / "H=1" / "seed=100", np.full(400, -5.0)
        )
        table = quality_table(mine, ref, window=200, tol=0.05, rolling=1)
        row = table[(table.scenario == "newscen")]
        assert len(row) == 1
        assert np.isnan(row.iloc[0].threshold)
        assert np.isnan(row.iloc[0].ep_mine)
        assert not row.iloc[0].degenerate
        assert row.iloc[0].ref_seeds == 0 and row.iloc[0].mine_seeds == 1

        out = tmp_path / "Q.md"
        write_quality_md(
            table, out, {}, window=200, tol=0.05, rolling=1,
            mine_dir=mine, ref_dir=ref, bench_jsonl="none.jsonl",
        )
        text = out.read_text()
        newscen_line = next(l for l in text.splitlines() if "newscen" in l)
        assert "no data" in newscen_line
        assert "nan" not in newscen_line
        # the summary denominator counts only cells WITH a threshold
        assert "Of the 1 cells with a real learning signal" in text

    def test_absent_mine_tree_renders_no_data(self, trees, tmp_path):
        """A wrong --raw_data path must yield 'no data', never a false
        'not reached' claim about sample efficiency."""
        _, ref = trees
        table = quality_table(
            tmp_path / "typo_path", ref, window=200, tol=0.05, rolling=1
        )
        out = tmp_path / "Q.md"
        write_quality_md(
            table, out, {}, window=200, tol=0.05, rolling=1,
            mine_dir="typo", ref_dir=ref, bench_jsonl="none.jsonl",
        )
        text = out.read_text()
        table_rows = [l for l in text.splitlines() if l.startswith("| ")]
        assert any("no data" in l for l in table_rows)
        # the footnote legitimately mentions 'not reached'; no DATA row
        # may claim it for an absent tree
        assert not any("not reached" in l for l in table_rows)

    def test_degenerate_boundary_is_exclusive(self, tmp_path):
        """A reference crossing at smoothed index == rolling (one step
        after the earliest possible) is genuine learning, NOT degenerate;
        only index rolling-1 (at threshold from the start) is."""
        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        rolling = 50
        # at threshold from episode 1 onward: the full-window mean first
        # clears the threshold at index `rolling`, not rolling-1
        curve = np.full(300, -5.0)
        curve[0] = -5.0 - 50 * (0.05 * 5.0 + 0.01)
        _write_run(ref / "coop" / "H=0" / "seed=100", curve)
        _write_run(mine / "coop" / "H=0" / "seed=100", curve)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=rolling)
        row = table.iloc[0]
        assert row.ep_ref == rolling
        assert not row.degenerate

    def test_degenerate_cell_flagged(self, tmp_path):
        """A cell where BOTH curves start at their converged level (the
        undefended H=0 attack cells) is flagged degenerate — the rule is
        two-sided, so one side alone never qualifies."""
        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        flat = np.full(400, -7.0)  # no learning progress at all
        learn = np.concatenate(
            [np.linspace(-9.0, -7.0, 200), np.full(200, -7.0)]
        )
        _write_run(ref / "faulty" / "H=0" / "seed=100", flat)
        _write_run(mine / "faulty" / "H=0" / "seed=100", flat)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=50)
        row = table.iloc[0]
        assert row.degenerate and not row.asymmetric
        # a cell with genuine learning on both sides is NOT flagged
        _write_run(ref / "coop" / "H=0" / "seed=100", learn)
        _write_run(mine / "coop" / "H=0" / "seed=100", learn)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=50)
        coop = table[table.scenario == "coop"].iloc[0]
        assert not coop.degenerate and not coop.asymmetric
        assert coop.ep_ref > 50

    def test_one_sided_at_start_is_asymmetric_not_degenerate(self, tmp_path):
        """Reference at threshold from the start while ours climbs for
        hundreds of episodes (the malicious_global H=0 shape): the old
        one-sided rule hid this as 'degenerate'; it must surface as an
        asymmetric finding. Same for the mirror orientation, and for an
        at-start reference whose counterpart never arrives at all."""
        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        flat = np.full(400, -7.0)
        learn = np.concatenate(
            [np.linspace(-9.0, -7.0, 200), np.full(200, -7.0)]
        )
        never = np.full(400, -9.0)
        _write_run(ref / "malg" / "H=0" / "seed=100", flat)
        _write_run(mine / "malg" / "H=0" / "seed=100", learn)
        _write_run(ref / "mirror" / "H=0" / "seed=100", learn)
        _write_run(mine / "mirror" / "H=0" / "seed=100", flat)
        _write_run(ref / "greedy" / "H=0" / "seed=100", flat)
        _write_run(mine / "greedy" / "H=0" / "seed=100", never)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=50)
        malg = table[table.scenario == "malg"].iloc[0]
        assert malg.asymmetric and not malg.degenerate
        assert malg.degenerate_ref and not malg.degenerate_mine
        mirror = table[table.scenario == "mirror"].iloc[0]
        assert mirror.asymmetric and not mirror.degenerate
        assert mirror.degenerate_mine and not mirror.degenerate_ref
        greedy = table[table.scenario == "greedy"].iloc[0]
        assert greedy.asymmetric and np.isnan(greedy.ep_mine)
        # the insufficient-data boundary: a curve SHORTER than one
        # rolling window smooths to all-NaN exactly like a genuine
        # never-crossing, but it is an in-progress run, not a behavioral
        # finding — the cell must not be flagged asymmetric (the genuine
        # never-arrives orientation is `greedy` above, full-length).
        _write_run(ref / "refshort" / "H=0" / "seed=100", never[:30])
        _write_run(mine / "refshort" / "H=0" / "seed=100", flat)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=50)
        refshort = table[table.scenario == "refshort"].iloc[0]
        assert np.isnan(refshort.ep_ref) and refshort.degenerate_mine
        assert not refshort.asymmetric and not refshort.degenerate
        # same truncation on OUR side: a 30-episode in-progress run must
        # not be reported as 'never reaches the reference quality'
        _write_run(ref / "mineshort" / "H=0" / "seed=100", flat)
        _write_run(mine / "mineshort" / "H=0" / "seed=100", never[:30])
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=50)
        mineshort = table[table.scenario == "mineshort"].iloc[0]
        assert np.isnan(mineshort.ep_mine)
        assert not mineshort.asymmetric and not mineshort.degenerate
        # a mine-only cell (no reference curves) is NOT asymmetric —
        # that's missing data, not a behavioral finding
        _write_run(mine / "mineonly" / "H=1" / "seed=100", learn)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=50)
        only = table[table.scenario == "mineonly"].iloc[0]
        assert not only.asymmetric and not only.degenerate

    def test_minority_spanning_seeds_do_not_take_hard_label(self, tmp_path):
        """One full-length seed among truncated ones must NOT be enough
        for the hard asymmetric label: the smoothed seed-mean averages
        every curve, so its tail rests on partial data when most seeds
        are in-progress. A MAJORITY of the side's curves must span the
        rolling window (ADVICE round-5 finding, quality.py)."""
        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        rolling = 50
        flat = np.full(400, -7.0)  # at threshold from the start
        never = np.full(400, -9.0)  # full-length, never crosses
        _write_run(ref / "part" / "H=0" / "seed=100", flat)
        # mine: ONE spanning seed, two truncated in-progress seeds
        _write_run(mine / "part" / "H=0" / "seed=100", never)
        _write_run(mine / "part" / "H=0" / "seed=200", never[:30])
        _write_run(mine / "part" / "H=0" / "seed=300", never[:30])
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=rolling)
        row = table[table.scenario == "part"].iloc[0]
        assert np.isnan(row.ep_mine)
        assert not row.asymmetric and not row.degenerate
        # with a majority spanning (2 of 3), the finding DOES surface
        _write_run(mine / "maj" / "H=0" / "seed=100", never)
        _write_run(mine / "maj" / "H=0" / "seed=200", never)
        _write_run(mine / "maj" / "H=0" / "seed=300", never[:30])
        _write_run(ref / "maj" / "H=0" / "seed=100", flat)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=rolling)
        maj = table[table.scenario == "maj"].iloc[0]
        assert maj.asymmetric and not maj.degenerate


    def test_index_zero_crossing_ratio(self, tmp_path):
        """With rolling=1 a legitimate crossing at index 0 is possible;
        the ratio must be inf (ref needed episodes, we needed none), not
        NaN via a falsy-zero guard."""
        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        learn = np.concatenate(
            [np.linspace(-9.0, -5.0, 200), np.full(200, -5.0)]
        )
        at_start = np.full(400, -5.0)
        _write_run(ref / "coop" / "H=1" / "seed=100", learn)
        _write_run(mine / "coop" / "H=1" / "seed=100", at_start)
        table = quality_table(mine, ref, window=100, tol=0.05, rolling=1)
        row = table.iloc[0]
        assert row.ep_mine == 0
        assert np.isposinf(row.ep_ratio)


class TestThroughputRows:
    def test_best_row_per_platform(self, tmp_path):
        rows = [
            {"config": "ref5_ring", "impl": "xla", "env_steps_per_sec": 11580.0,
             "platform": "tpu", "timestamp": "t1"},
            {"config": "ref5_ring", "impl": "pallas", "env_steps_per_sec": 6943.0,
             "platform": "tpu", "timestamp": "t2"},
            {"config": "ref5_ring", "impl": "xla", "env_steps_per_sec": 803.0,
             "platform": "cpu", "timestamp": "t3"},
            # different config, sharded-A/B, and reduced-precision rows
            # must all be ignored (mixed-provenance wall-clock numbers)
            {"config": "n64_ring", "impl": "xla", "env_steps_per_sec": 99999.0,
             "platform": "tpu", "timestamp": "t4"},
            {"config": "ref5_ring", "impl": "xla", "env_steps_per_sec": 99999.0,
             "platform": "cpu", "shard_agents": True, "timestamp": "t5"},
            {"config": "ref5_ring", "impl": "xla", "env_steps_per_sec": 99999.0,
             "platform": "tpu", "compute_dtype": "bfloat16", "timestamp": "t6"},
        ]
        path = tmp_path / "bench.jsonl"
        path.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
        best = episode_throughput_from_bench(path)
        assert set(best) == {"tpu", "cpu"}
        assert best["tpu"]["episodes_per_sec"] == pytest.approx(11580 / 20)
        assert best["tpu"]["impl"] == "xla"
        assert best["cpu"]["episodes_per_sec"] == pytest.approx(803 / 20)

    def test_missing_file_is_empty(self, tmp_path):
        assert episode_throughput_from_bench(tmp_path / "nope.jsonl") == {}


class TestWriteQualityMd:
    def test_artifact_renders(self, tmp_path):
        table = pd.DataFrame(
            [
                {"scenario": "coop", "H": 0, "ref_final": -5.0,
                 "threshold": -5.25, "ep_ref": 750.0, "ep_mine": 375.0,
                 "ep_ratio": 2.0, "degenerate": False},
                {"scenario": "greedy", "H": 0, "ref_final": -6.67,
                 "threshold": -7.0, "ep_ref": 900.0,
                 "ep_mine": float("nan"), "ep_ratio": float("nan"),
                 "degenerate": False},
                {"scenario": "malicious", "H": 0, "ref_final": -7.2,
                 "threshold": -7.56, "ep_ref": 199.0, "ep_mine": 300.0,
                 "ep_ratio": 0.66, "degenerate": True},
            ]
        )
        throughput = {
            "tpu": {"episodes_per_sec": 579.0, "impl": "xla",
                    "timestamp": "t1"},
        }
        out = tmp_path / "QUALITY.md"
        write_quality_md(
            table, out, throughput, window=500, tol=0.05, rolling=200,
            mine_dir="mine", ref_dir="ref", bench_jsonl="bench.jsonl",
        )
        text = out.read_text()
        assert "do not edit result rows by hand" in text
        # 750 episodes at 0.125 eps/s = 6000 s = 1.7 h
        assert "1.7 h" in text
        # 375 episodes at 579 eps/s < 1 s
        assert "0.6 s" in text
        assert "not reached" in text
        # degenerate rows are marked and excluded from the summary line
        assert "degenerate†" in text
        assert "Of the 2 cells with a real learning signal, 1 are reached" in text
        assert "median episode ratio 2.00" in text

    def test_asymmetric_rendering_and_findings(self, tmp_path):
        """Asymmetric cells are marked in the table, excluded from the
        median, and spelled out in a findings paragraph."""
        table = pd.DataFrame(
            [
                {"scenario": "coop", "H": 1, "ref_final": -5.0,
                 "threshold": -5.25, "ep_ref": 750.0, "ep_mine": 375.0,
                 "ep_ratio": 2.0, "degenerate": False,
                 "degenerate_ref": False, "degenerate_mine": False,
                 "asymmetric": False},
                {"scenario": "malicious_global", "H": 0, "ref_final": -7.2,
                 "threshold": -7.56, "ep_ref": 199.0, "ep_mine": 5777.0,
                 "ep_ratio": 0.03, "degenerate": False,
                 "degenerate_ref": True, "degenerate_mine": False,
                 "asymmetric": True},
                {"scenario": "greedy", "H": 0, "ref_final": -6.67,
                 "threshold": -7.0, "ep_ref": 150.0,
                 "ep_mine": float("nan"), "ep_ratio": float("nan"),
                 "degenerate": False, "degenerate_ref": True,
                 "degenerate_mine": False, "asymmetric": True},
            ]
        )
        out = tmp_path / "QUALITY.md"
        write_quality_md(
            table, out, {}, window=500, tol=0.05, rolling=200,
            mine_dir="mine", ref_dir="ref", bench_jsonl="bench.jsonl",
        )
        text = out.read_text()
        assert text.count("asymmetric‡") == 2
        assert "Asymmetric cells (2):" in text
        assert (
            "**malicious_global H=0**: the reference is at threshold "
            "from its first fully-smoothed point, but this framework "
            "first reaches it at episode 5777." in text
        )
        assert "never reaches it in the swept budget" in text
        # only coop counts toward the summary ratio
        assert "Of the 1 cells with a real learning signal" in text
        assert "median episode ratio 2.00" in text
        # empty throughput: explicit note, no dangling provenance join
        assert "no measured `ref5_ring`" in text
        assert "`bench.jsonl` ." not in text

    def test_quality_cli_end_to_end(self, tmp_path, capsys):
        """The subcommand wires trees + bench rows into QUALITY.md."""
        from rcmarl_tpu.cli import main

        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        curve = np.concatenate(
            [np.linspace(-9.0, -5.0, 300), np.full(300, -5.0)]
        )
        _write_run(ref / "coop" / "H=1" / "seed=100", curve)
        _write_run(mine / "coop" / "H=1" / "seed=100", curve)
        bench = tmp_path / "b.jsonl"
        bench.write_text(json.dumps(
            {"config": "ref5_ring", "impl": "xla",
             "env_steps_per_sec": 11580.0, "platform": "tpu",
             "timestamp": "t"}) + "\n")
        out = tmp_path / "QUALITY.md"
        rc = main([
            "quality", "--raw_data", str(mine), "--ref_raw_data", str(ref),
            "--out", str(out), "--bench_jsonl", str(bench),
            "--window", "100", "--rolling", "10",
        ])
        assert rc == 0
        text = out.read_text()
        # identical curves: both cross at the same episode, ratio 1.00
        assert "| 1.00 |" in text
        assert "coop" in text


class TestQualityFigure:
    def test_plot_quality_crossing(self, tmp_path):
        from rcmarl_tpu.analysis.quality import plot_quality_crossing

        ref = tmp_path / "ref"
        mine = tmp_path / "mine"
        curve = np.concatenate(
            [np.linspace(-9.0, -5.0, 300), np.full(300, -5.0)]
        )
        _write_run(ref / "coop" / "H=1" / "seed=100", curve, phases=2)
        _write_run(mine / "coop" / "H=1" / "seed=100", curve, phases=2)
        out = plot_quality_crossing(
            mine, ref, tmp_path / "fig.png", scenario="coop", H=1,
            window=100, rolling=20,
        )
        assert (tmp_path / "fig.png").stat().st_size > 0
        assert out.endswith("fig.png")

    def test_plot_quality_missing_cell_raises(self, tmp_path):
        from rcmarl_tpu.analysis.quality import plot_quality_crossing

        ref = tmp_path / "ref"
        _write_run(ref / "coop" / "H=1" / "seed=100", np.full(100, -5.0))
        with pytest.raises(FileNotFoundError, match="missing"):
            plot_quality_crossing(
                tmp_path / "empty", ref, tmp_path / "f.png",
                scenario="coop", H=1,
            )
