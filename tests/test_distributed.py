"""Multi-host layer on the virtual 8-device CPU mesh.

True multi-process runs need separate hosts; what IS testable here — and
what the driver's dryrun validates too — is the mesh construction rule
(agent groups contiguous, never straddling a host boundary), the
single-process fallbacks, and that training actually executes over a
multihost_mesh-shaped mesh.
"""

import jax
import numpy as np
import pytest

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.parallel import (
    gather_metrics,
    initialize,
    multihost_mesh,
    train_parallel,
)


def test_initialize_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    initialize()  # must not raise or try to reach a coordinator
    assert jax.process_count() == 1


def test_multihost_mesh_layout():
    mesh = multihost_mesh(agent_axis=2)
    assert mesh.axis_names == ("seed", "agent")
    assert mesh.devices.shape == (4, 2)
    # agent groups are contiguous device runs (the within-host rule)
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert (ids[:, 1] - ids[:, 0] == 1).all()


def test_multihost_mesh_rejects_straddling():
    with pytest.raises(ValueError, match="divide the local device count"):
        multihost_mesh(agent_axis=3)


def test_gather_metrics_single_process():
    x = {"a": jax.numpy.arange(4.0)}
    out = gather_metrics(x)
    assert isinstance(out["a"], np.ndarray)
    np.testing.assert_array_equal(out["a"], np.arange(4.0))


@pytest.mark.slow
def test_train_parallel_over_multihost_mesh():
    cfg = Config(
        n_agents=4,
        agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.GREEDY,),
        in_nodes=circulant_in_nodes(4, 3),
        H=1,
        nrow=3,
        ncol=3,
        n_episodes=2,
        max_ep_len=2,
        n_ep_fixed=2,
        n_epochs=1,
        buffer_size=8,
        hidden=(8, 8),
        coop_fit_steps=1,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
    )
    mesh = multihost_mesh(agent_axis=2)
    states, metrics = train_parallel(
        cfg, seeds=list(range(4)), n_blocks=1, mesh=mesh, shard_agents=True
    )
    got = gather_metrics(metrics)
    assert got.true_team_returns.shape == (4, 2)  # (seeds, episodes)
    assert np.isfinite(got.true_team_returns).all()
