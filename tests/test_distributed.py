"""Multi-host layer: virtual-mesh tests + a TRUE multi-process run.

The fast tests exercise the mesh construction rule (agent groups
contiguous, never straddling a host boundary), the single-process
fallbacks, and training over a multihost_mesh-shaped mesh on the
virtual 8-device CPU mesh. ``test_true_two_process_training`` then runs
the real thing: two OS processes joined through the coordinator, gloo
cross-process collectives, and the gather_metrics DCN path, checked
numerically against a single-process run.
"""

import os

import jax
import numpy as np
import pytest

from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.parallel import (
    gather_metrics,
    initialize,
    multihost_mesh,
    train_parallel,
)
from tests.conftest import needs_multicore


def test_initialize_single_process_noop(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    initialize()  # must not raise or try to reach a coordinator
    assert jax.process_count() == 1


def test_multihost_mesh_layout():
    mesh = multihost_mesh(agent_axis=2)
    assert mesh.axis_names == ("seed", "agent")
    assert mesh.devices.shape == (4, 2)
    # agent groups are contiguous device runs (the within-host rule)
    ids = np.vectorize(lambda d: d.id)(mesh.devices)
    assert (ids[:, 1] - ids[:, 0] == 1).all()


def test_multihost_mesh_rejects_straddling():
    with pytest.raises(ValueError, match="divide the local device count"):
        multihost_mesh(agent_axis=3)


def test_gather_metrics_single_process():
    x = {"a": jax.numpy.arange(4.0)}
    out = gather_metrics(x)
    assert isinstance(out["a"], np.ndarray)
    np.testing.assert_array_equal(out["a"], np.arange(4.0))


@pytest.mark.slow
@needs_multicore  # executes shard_agents=True collectives in-process
def test_train_parallel_over_multihost_mesh():
    cfg = Config(
        n_agents=4,
        agent_roles=(Roles.COOPERATIVE,) * 3 + (Roles.GREEDY,),
        in_nodes=circulant_in_nodes(4, 3),
        H=1,
        nrow=3,
        ncol=3,
        n_episodes=2,
        max_ep_len=2,
        n_ep_fixed=2,
        n_epochs=1,
        buffer_size=8,
        hidden=(8, 8),
        coop_fit_steps=1,
        adv_fit_epochs=1,
        adv_fit_batch=4,
        batch_size=4,
    )
    mesh = multihost_mesh(agent_axis=2)
    states, metrics = train_parallel(
        cfg, seeds=list(range(4)), n_blocks=1, mesh=mesh, shard_agents=True
    )
    got = gather_metrics(metrics)
    assert got.true_team_returns.shape == (4, 2)  # (seeds, episodes)
    assert np.isfinite(got.true_team_returns).all()


@pytest.mark.slow
def test_true_two_process_training(tmp_path):
    """REAL multi-process run: 2 OS processes x 2 virtual CPU devices form
    one 4-device cluster over gloo collectives; seeds shard across the
    process boundary and the gathered metrics must equal a single-process
    run of the identical config + seeds (replica independence)."""
    import importlib.util
    import socket
    import subprocess
    import sys as _sys

    # free port for the coordinator
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(repo, "tests", "helpers", "multiprocess_worker.py")
    # import the worker module (jax-free at import time) so both sides
    # provably run the SAME config and seeds
    spec = importlib.util.spec_from_file_location("mp_worker", worker)
    mp_worker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mp_worker)

    out_path = str(tmp_path / "metrics.npz")
    env_base = {
        **os.environ,
        "PALLAS_AXON_POOL_IPS": "",  # axon sitecustomize must not register
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
        "PYTHONPATH": repo + os.pathsep + os.environ.get("PYTHONPATH", ""),
    }
    # worker stdout/stderr go to FILES: piped output could fill the pipe
    # buffer and deadlock the barrier-coupled pair
    logs = [tmp_path / f"worker{i}.log" for i in (0, 1)]
    procs = []
    try:
        for i in (0, 1):
            with open(logs[i], "w") as log:
                procs.append(
                    subprocess.Popen(
                        [_sys.executable, worker, out_path],
                        env={**env_base, "JAX_PROCESS_ID": str(i)},
                        stdout=log,
                        stderr=subprocess.STDOUT,
                    )
                )
        for p in procs:
            p.wait(timeout=420)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for i, p in enumerate(procs):
        assert p.returncode == 0, (
            f"worker {i} failed:\n{logs[i].read_text()[-2000:]}"
        )

    got = np.load(out_path)
    # single-process reference: identical cfg + seeds on this process's mesh
    _, ref = train_parallel(
        mp_worker.worker_config(), seeds=mp_worker.SEEDS, n_blocks=1
    )
    np.testing.assert_allclose(
        got["true_team_returns"],
        np.asarray(ref.true_team_returns),
        rtol=1e-5,
        atol=1e-6,
    )
