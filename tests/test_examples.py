"""Smoke tests for the runnable examples.

The examples are user-facing documentation; they must keep executing as
the API evolves. Each runs as a real subprocess (fresh interpreter, CPU
platform forced the same way a user would) with the
``RCMARL_EXAMPLE_FAST`` hook shrinking workloads — same code paths,
smaller episode counts.
"""

import os
import subprocess
import sys
from pathlib import Path

import pytest

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"


def _run(name: str, timeout: int):
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        RCMARL_EXAMPLE_FAST="1",
    )
    proc = subprocess.run(
        [sys.executable, str(EXAMPLES / name)],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )
    assert proc.returncode == 0, (
        f"{name} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    )
    return proc.stdout


def test_env_demo():
    out = _run("env_demo.py", timeout=300)
    assert "goal layout" in out and "t=0:" in out


@pytest.mark.slow
def test_reference_program():
    # stale artifacts from a previous run must not satisfy the assertion
    import shutil

    shutil.rmtree("/tmp/reference_program_out", ignore_errors=True)
    out = _run("reference_program.py", timeout=900)
    assert "compat twins" in out
    # reference-format artifacts written
    assert (Path("/tmp/reference_program_out") / "sim_data.pkl").exists()


@pytest.mark.slow
def test_resilience_demo():
    out = _run("resilience_demo.py", timeout=900)
    assert "attack cost without defense" in out
    # part 2: transport faults — unsanitized poisons, sanitized survives
    assert "unsanitized params finite: False" in out
    assert "sanitized  params finite: True" in out


@pytest.mark.slow
def test_quickstart_api():
    out = _run("quickstart_api.py", timeout=1200)
    assert "team return" in out
    assert "per-seed team returns" in out  # the train_matrix walkthrough
