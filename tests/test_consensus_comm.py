"""Consensus communication backend: neighbor-message gather lowering.

VERDICT.md round-1 weakness 4: the agent-sharded consensus gather
``msgs[in_arr]`` lowered to an all-gather of ALL agents' stacked params on
every epoch. For rotation-symmetric graphs (circulant/full — every
topology the reference and BASELINE.json use) the gather is now expressed
as static rolls, which XLA's SPMD partitioner lowers to ring
collective-permutes of just the halo rows. These tests pin (a) the shift
detection, (b) semantic equivalence of the two gather lowerings, and
(c) the compiled-HLO property itself on a sharded mesh.
"""

import re

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rcmarl_tpu.config import (
    Config,
    Roles,
    circulant_in_nodes,
    full_in_nodes,
)
from rcmarl_tpu.training.update import gather_neighbor_messages


class TestUniformShifts:
    def test_circulant(self):
        cfg = Config()
        assert cfg.uniform_shifts == (0, 1, 2, 3)

    def test_full_graph(self):
        cfg = Config(in_nodes=full_in_nodes(5))
        assert cfg.uniform_shifts == (0, 1, 2, 3, 4)

    def test_ragged_graph_has_none(self):
        cfg = Config(
            in_nodes=((0, 1, 2, 3), (1, 2, 3), (2, 3, 4, 0), (3, 4, 0), (4, 0, 1))
        )
        assert cfg.uniform_shifts is None

    def test_regular_but_asymmetric_has_none(self):
        # degree 2 everywhere, but agent 0 listens to 2 while others
        # listen to their successor: not rotation-symmetric
        cfg = Config(
            in_nodes=((0, 2), (1, 2), (2, 3), (3, 4), (4, 0)),
            H=0,
        )
        assert cfg.regular_graph
        assert cfg.uniform_shifts is None


class TestGatherEquivalence:
    def _stacked(self, key, n, shape=(3, 4)):
        return {
            "W": jax.random.normal(key, (n, *shape)),
            "b": jax.random.normal(jax.random.fold_in(key, 1), (n, shape[-1])),
        }

    def test_roll_path_matches_index_path_as_multiset(self):
        """Roll-gather rows hold the same neighbor multiset as the
        reference in_nodes rows, with self at index 0 in both."""
        cfg = Config()  # circulant(5, 4): roll path
        tree = self._stacked(jax.random.PRNGKey(0), cfg.n_agents)
        rolled = gather_neighbor_messages(cfg, tree)
        in_arr = jnp.asarray(np.array(cfg.in_nodes))
        indexed = jax.tree.map(lambda l: l[in_arr], tree)
        for k in tree:
            r, g = np.asarray(rolled[k]), np.asarray(indexed[k])
            assert r.shape == g.shape
            # self first in both
            np.testing.assert_array_equal(r[:, 0], np.asarray(tree[k]))
            # same multiset of neighbor rows per agent
            for i in range(cfg.n_agents):
                r_sorted = r[i][np.lexsort(r[i].reshape(cfg.n_in, -1).T)]
                g_sorted = g[i][np.lexsort(g[i].reshape(cfg.n_in, -1).T)]
                np.testing.assert_array_equal(r_sorted, g_sorted)

    def test_arbitrary_graph_uses_exact_indexing(self):
        cfg = Config(
            in_nodes=((0, 2), (1, 2), (2, 3), (3, 4), (4, 0)),
            H=0,
        )
        tree = self._stacked(jax.random.PRNGKey(1), cfg.n_agents)
        out = gather_neighbor_messages(cfg, tree)
        in_arr = np.array(cfg.in_nodes)
        for k in tree:
            np.testing.assert_array_equal(
                np.asarray(out[k]), np.asarray(tree[k])[in_arr]
            )

    def test_ragged_graph_pads_with_self(self):
        cfg = Config(
            in_nodes=((0, 1, 2, 3), (1, 2, 3), (2, 3, 4, 0), (3, 4, 0), (4, 0, 1))
        )
        tree = self._stacked(jax.random.PRNGKey(2), cfg.n_agents)
        out = gather_neighbor_messages(cfg, tree)
        # agent 1 has degree 3, padded slot 3 repeats its own row
        np.testing.assert_array_equal(
            np.asarray(out["W"][1, 3]), np.asarray(tree["W"][1])
        )


class TestShardedLowering:
    """The compiled-HLO property on an 8-device agent-sharded mesh."""

    def _collective_lines(self, cfg, n, feat=(192, 64)):
        mesh = Mesh(np.array(jax.devices()[:8]).reshape(8), ("agent",))
        x = jnp.zeros((n, *feat))
        sh = NamedSharding(mesh, P("agent"))

        def f(l):
            out = gather_neighbor_messages(cfg, {"w": l})["w"]
            return out * 2.0  # consumer so the gather isn't DCE'd

        txt = (
            jax.jit(f, in_shardings=sh, out_shardings=sh)
            .lower(jax.device_put(x, sh))
            .compile()
            .as_text()
        )
        return txt

    @pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
    def test_circulant_gather_is_halo_exchange(self):
        n = 64
        cfg = Config(
            n_agents=n,
            agent_roles=(Roles.COOPERATIVE,) * n,
            in_nodes=circulant_in_nodes(n, 4),
            H=1,
        )
        txt = self._collective_lines(cfg, n)
        # no all-gather of the full stacked params
        full_ag = [
            l
            for l in txt.splitlines()
            if re.search(rf"= \S*all-gather", l) and f"[{n}," in l
        ]
        assert not full_ag, full_ag[:2]
        # halo rows move by collective-permute instead
        assert "collective-permute" in txt

    @pytest.mark.skipif(len(jax.devices()) < 8, reason="needs 8 devices")
    def test_arbitrary_gather_all_gathers(self):
        """The general path is expected (and documented) to all-gather —
        this pins the contrast that motivates the roll path."""
        n = 64
        in_nodes = tuple(
            (i,) + tuple(sorted({(i * 7 + k) % n for k in (1, 2, 3)} - {i}))
            for i in range(n)
        )
        # make degrees regular by construction check; fall back: pad
        cfg = Config(
            n_agents=n,
            agent_roles=(Roles.COOPERATIVE,) * n,
            in_nodes=in_nodes,
            H=0,
        )
        assert cfg.uniform_shifts is None
        txt = self._collective_lines(cfg, n)
        assert "all-gather" in txt
