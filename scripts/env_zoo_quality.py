#!/usr/bin/env python
"""Env-zoo training+serving evidence — the committed QUALITY.md cells
(simulation_results/env_zoo.json).

Drives the REAL CLI for every new environment of the registry
(``python -m rcmarl_tpu train --env <name>`` then ``evaluate`` on the
written checkpoint), so the committed artifact proves the whole wire-up
— CLI flag -> Config.env -> registry -> generic rollout -> trainer ->
checksummed checkpoint -> frozen-policy evaluation — not just the
library path. Per env it records the training return curve's first/last
window means (finite, improving) and the `evaluate` CLI's JSONL row
(the frozen-policy serving-side measurement the acceptance criteria
ask for per env).

Usage:  python scripts/env_zoo_quality.py [--episodes 1000]
"""

import argparse
import json
import subprocess
import sys
import tempfile
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--episodes", type=int, default=1000)
    p.add_argument("--eval_episodes", type=int, default=100)
    p.add_argument("--seed", type=int, default=300)
    p.add_argument("--window", type=int, default=200)
    p.add_argument(
        "--out", type=str, default="simulation_results/env_zoo.json"
    )
    args = p.parse_args()

    import pandas as pd

    import jax

    from rcmarl_tpu.config import ENV_NAMES

    envs = [n for n in ENV_NAMES if n != "grid_world"]
    cells = []
    for name in envs:
        with tempfile.TemporaryDirectory() as tmp:
            train_cmd = [
                sys.executable, "-m", "rcmarl_tpu", "train",
                "--env", name,
                "--n_episodes", str(args.episodes),
                "--slow_lr", "0.002",
                "--random_seed", str(args.seed),
                "--summary_dir", tmp,
                "--quiet",
            ]
            subprocess.run(train_cmd, check=True)
            df = pd.read_pickle(Path(tmp) / "sim_data1.pkl")
            r = df["True_team_returns"].values
            assert np.isfinite(r).all(), f"{name}: non-finite return curve"
            eval_out = Path(tmp) / "evaluate.jsonl"
            eval_cmd = [
                sys.executable, "-m", "rcmarl_tpu", "evaluate",
                "--checkpoint", str(Path(tmp) / "checkpoint.npz"),
                "--episodes", str(args.eval_episodes),
                "--out", str(eval_out),
            ]
            subprocess.run(eval_cmd, check=True)
            row = json.loads(eval_out.read_text().strip().splitlines()[-1])
        row.pop("checkpoint", None)  # a temp path is not evidence
        w = min(args.window, len(r) // 2)
        cells.append(
            {
                "env": name,
                "episodes": args.episodes,
                "first_window_return": round(float(np.mean(r[:w])), 4),
                "final_window_return": round(float(np.mean(r[-w:])), 4),
                "improved": bool(np.mean(r[-w:]) > np.mean(r[:w])),
                "evaluate": row,
            }
        )
        print(cells[-1], flush=True)

    out = {
        "generated_by": "python scripts/env_zoo_quality.py",
        "config": {
            "episodes": args.episodes,
            "eval_episodes": args.eval_episodes,
            "seed": args.seed,
            "window": args.window,
            "cast": "5 cooperative, ref ring (in_degree 4), H=0",
        },
        "platform": jax.devices()[0].platform,
        "cells": cells,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
