#!/usr/bin/env bash
# Tunnel watcher: probe the axon TPU backend every INTERVAL seconds and
# run the queued measurement session (scripts/tpu_session.sh) exactly
# once, the moment a window opens. Round-4 post-mortem: windows can be
# minutes long and appear without warning, so banking them must not
# depend on a human (or an agent turn) noticing — start this in the
# background at the top of a working session:
#
#   nohup bash scripts/tpu_watch.sh > /tmp/tpu_watch.log 2>&1 &
#
# A marker file guards against double-running the session; remove it to
# re-arm the watcher after editing the session script. The marker lives
# in the repo root (.tpu_session_done, gitignored), NOT in a fixed
# global /tmp path: two checkouts/branches (or a stale marker from a
# prior machine session) must not silently disarm each other's watcher.
# Override with TPU_SESSION_MARKER if needed.
set -u
cd "$(dirname "$0")/.."

INTERVAL="${TPU_WATCH_INTERVAL:-600}"
MARKER="${TPU_SESSION_MARKER:-$(pwd)/.tpu_session_done}"

while true; do
    if [ -e "$MARKER" ]; then
        echo "$(date -Is) session already ran (rm $MARKER to re-arm); exiting"
        exit 0
    fi
    if timeout 240 python -c \
        "import jax; d = jax.devices(); assert d[0].platform != 'cpu'" \
        2>/dev/null; then
        echo "$(date -Is) tunnel UP - running the queued session"
        bash scripts/tpu_session.sh
        rc=$?
        echo "$(date -Is) session finished rc=$rc"
        if [ "$rc" -eq 2 ]; then
            # the session's own probe failed before any measurement
            # (window closed between our probe and its) — stay armed,
            # but back off first: a flapping tunnel that passes our
            # probe and fails the session's must not re-probe
            # back-to-back in a tight loop
            sleep "$INTERVAL"
            continue
        fi
        # rc 0 (all steps) or 1 (ran with some failures): measurements
        # were attempted/banked; mark done so reruns don't duplicate rows
        touch "$MARKER"
        exit 0
    fi
    echo "$(date -Is) tunnel down"
    sleep "$INTERVAL"
done
