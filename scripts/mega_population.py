#!/usr/bin/env python
"""Mega-population training quality — the committed QUALITY.md
experiment (simulation_results/mega_population.json).

The n=256 congestion-free grid world at population scale, consensus
riding the SPARSE time-varying exchange (random-geometric degree 9,
resampled every block, gather indices as traced data —
ops/exchange.py) with the ``fit_clip`` stability rail on (the raw
reference fit diverges past n~64: Config.fit_clip). Three arms ask the
mega-population acceptance questions directly:

  clean_h2   : 256 cooperative, H=2 — does training IMPROVE at n=256
               on the sparse path (first-window vs last-window mean
               return), and does it stay finite end to end?
  trimmed_h2 : 254 coop + 2 Adaptive colluders, H=2 — the PROVISIONED
               trim: total colluders <= H, so no neighborhood can ever
               contain more than H of them, under ANY schedule —
               containment by construction, and the trimmed mean holds
               both gates.
  trimmed_h1 : same cast, H=1 — the UNDER-provisioned trim: both
               colluders landing in one resampled neighborhood beat a
               1-per-side trim, and each leaked payload (10x the
               healthy spread) widens the next epoch's spread. At 2
               colluders the leak is measurable (consensus magnitude
               elevated over clean) but slow; as the colluder count
               grows it compounds geometrically to non-finite — with 8
               colluders even H=2 falls, since >=3 land in one
               degree-9 neighborhood a handful of times over 60
               resamples, and a handful is enough. The theory's
               assumption is <=H Byzantine PER NEIGHBORHOOD, and a
               global count above H plus schedule mixing is what
               breaks it — not the sparse exchange itself.
  plain_h0   : same cast, H=0 — the undefended comparison arm (the
               clip-and-average bounds are adversary-controlled).

Each arm reports its return windows AND ``consensus_abs_max`` — the
largest |parameter| across the COOPERATIVE agents' consensus critic+TR
rows at the end of the run (the adversaries' own rows are
adversary-controlled by definition and excluded). That second metric is where the poisoning shows first: the policy's
returns are shielded for a while by Adam's scale invariance (blown-up
advantages normalize away in the actor step), so the return band alone
CANNOT separate the under-provisioned arms — the H=2 arm's consensus
nets stay near the clean arm's band while the H=1 and H=0 nets go
non-finite. ``values_sane`` gates it at 100x the clean arm's
magnitude.

The adversary is the omniscient colluding ADAPTIVE role at scale 10
(see scripts/adaptive_adversary.py for the 5-agent original; this is
its n-scale twin over a time-varying sparse graph).

Usage:  python scripts/mega_population.py [--episodes 120]
"""

import argparse
import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--episodes", type=int, default=120)
    p.add_argument("--seed", type=int, default=300)
    p.add_argument("--n_agents", type=int, default=256)
    p.add_argument("--n_adv", type=int, default=2)
    p.add_argument("--degree", type=int, default=9)
    p.add_argument("--scale", type=float, default=10.0)
    p.add_argument("--window", type=int, default=30)
    p.add_argument("--tol", type=float, default=0.05)
    p.add_argument(
        "--out", type=str, default="simulation_results/mega_population.json"
    )
    args = p.parse_args()

    import jax

    from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
    from rcmarl_tpu.training.trainer import train

    n, n_adv = args.n_agents, args.n_adv
    side = max(3, int(round(n**0.5)))
    coop = (Roles.COOPERATIVE,) * n
    adv = (Roles.COOPERATIVE,) * (n - n_adv) + (Roles.ADAPTIVE,) * n_adv
    arms_spec = [
        ("clean_h2", coop, 2),
        ("trimmed_h2", adv, 2),
        ("trimmed_h1", adv, 1),
        ("plain_h0", adv, 0),
    ]

    arms = []
    for label, cast, H in arms_spec:
        cfg = Config(
            n_agents=n,
            agent_roles=cast,
            # tiny static anchor ring: consensus rides the schedule
            in_nodes=circulant_in_nodes(n, 5),
            nrow=side,
            ncol=side,
            hidden=(4,),
            graph_schedule="random_geometric",
            graph_degree=args.degree,
            H=H,
            fit_clip=1.0,
            adaptive_scale=args.scale,
            n_episodes=args.episodes,
            n_ep_fixed=2,
            max_ep_len=8,
            n_epochs=2,
            slow_lr=0.002,
            seed=args.seed,
        )
        state, df = train(cfg, guard=False)
        r = df["True_team_returns"].values
        finite = np.isfinite(r)
        collapsed = None if finite.all() else int(np.argmin(finite))
        rf = r[finite]
        w = min(args.window, max(1, len(rf) // 3))
        # healthy rows only: the adversaries' own rows in the stacked
        # trees are adversary-controlled by definition (their local fits
        # ride their own poisoned estimates) — the poisoning question is
        # what the COOPERATIVE agents' consensus nets absorbed.
        coop_mask = np.array([c == Roles.COOPERATIVE for c in cast])
        cons = max(
            float(np.max(np.abs(np.asarray(leaf)[coop_mask])))
            for tree in (state.params.critic, state.params.tr)
            for leaf in jax.tree_util.tree_leaves(tree)
        )
        arms.append(
            {
                "label": label,
                "H": H,
                "adversaries": int(sum(c == Roles.ADAPTIVE for c in cast)),
                "first_window": round(float(np.mean(rf[:w])), 4),
                "final_return": round(float(np.mean(rf[-w:])), 4),
                "consensus_abs_max": float(f"{cons:.4g}"),
                "collapsed_at_episode": collapsed,
            }
        )
        print(arms[-1], flush=True)

    clean = next(a for a in arms if a["label"] == "clean_h2")
    clean["improved"] = bool(
        clean["collapsed_at_episode"] is None
        and clean["final_return"] > clean["first_window"]
    )
    band = clean["final_return"]
    sane = 100.0 * clean["consensus_abs_max"]
    for a in arms:
        # one-sided: DEGRADATION is what the band polices
        a["within_clean_band"] = bool(
            a["collapsed_at_episode"] is None
            and a["final_return"] >= band - args.tol * abs(band)
        )
        a["values_sane"] = bool(a["consensus_abs_max"] <= sane)

    out = {
        "generated_by": "python scripts/mega_population.py",
        "config": {
            "scenario": (
                f"n={n} grid ({side}x{side}), sparse random-geometric "
                f"degree {args.degree} resampled per block, "
                f"{n_adv} Adaptive colluders, fit_clip 1.0"
            ),
            "episodes": args.episodes,
            "seed": args.seed,
            "adaptive_scale": args.scale,
            "window": args.window,
            "tol": args.tol,
        },
        "platform": jax.devices()[0].platform,
        "clean_final": band,
        "arms": arms,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
