"""Arbitration harness: run the UNMODIFIED reference snapshot in-image.

DRIFT.md's central claim — the phase-2 private-reward divergence is a
TF-substrate difference, not a semantic one — rests on executing the
reference's own TF/Keras algorithm end-to-end in this image and watching
where it lands. Round 3 did that once (coop, H=0, seed 100); this script
is the committed, repeatable form, used in round 4 to extend the
arbitration to n>=3 seeds plus a `_global` control cell (VERDICT r3
item 3).

The algorithm code is imported from /root/reference and executed as-is
(`training.train_agents.train_RPBCAC`, the agent classes, `Grid_World`).
Exactly three strictly semantics-preserving accommodations are applied,
the same three documented in DRIFT.md "Arbitration":

(a) `get_action`'s per-step Keras ``model.predict``
    (resilient_CAC_agents.py:215 — ~100 ms of dispatch per batch-of-1
    call, the reason the reference runs at 2.5 steps/s) is replaced by
    the same model called directly under one ``tf.function`` trace:
    same weights, same float32 graph math, and the same three global
    NumPy draws in the same order.
(b) Keras 3 forbids reusing one optimizer instance across models /
    trainable-set changes; every ``compile`` receives a fresh SGD with
    the same config (resilient_CAC_agents.py:36 shares one). SGD is
    stateless, so this is numerically identical. The per-agent actor
    Adam (stateful) is created once per model and is NOT touched.
(c) ``np.save`` of the ragged per-agent weight list needs an explicit
    object array under numpy >= 1.24.

Everything else — model architecture, hyperparameters, the two-phase
restart protocol, the artifact layout (sim_data{1,2}.pkl,
pretrained_weights.npy, desired_state.npy, out.txt) — mirrors main.py
(/root/reference/main.py:23-122) and the published job scripts
(raw_data/*/job.sh: --slow_lr=0.002, 4000 episodes per phase) so the
resulting tree is directly comparable to both the shipped artifacts and
this framework's sweeps.

Usage (one cell, both phases):

    python scripts/tf_arbitration.py --scenario coop --H 0 --seed 200 \
        --out simulation_results/tf_arbitration

Writes <out>/<scenario>/H=<H>/seed=<seed>/sim_data{phase}.pkl + the
weight/goal files + a config dump per phase, and prints rolling-200
summary means compatible with DRIFT.md's tables.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import sys
import time
import types
from pathlib import Path

import numpy as np

REFERENCE = "/root/reference"

#: node labels per scenario, verified against the published config dumps
#: (raw_data/<scenario>/H=*/seed=*/out.txt): the adversary is node 4.
SCENARIO_LABELS = {
    "coop": ["Cooperative"] * 5,
    "faulty": ["Cooperative"] * 4 + ["Faulty"],
    "greedy": ["Cooperative"] * 4 + ["Greedy"],
    "malicious": ["Cooperative"] * 4 + ["Malicious"],
}


def _install_gym_stub() -> None:
    """The reference imports gym only for the Env base class and the
    spaces module; neither is exercised by training (same stub as
    tests/test_env.py)."""
    if "gym" in sys.modules:
        return
    gym_stub = types.ModuleType("gym")

    class _Env:
        pass

    gym_stub.Env = _Env
    gym_stub.spaces = types.ModuleType("gym.spaces")
    sys.modules["gym"] = gym_stub
    sys.modules["gym.spaces"] = gym_stub.spaces


def _patch_semantics_preserving(tf, keras, agent_classes) -> None:
    """Install accommodations (a) and (b). See module docstring."""

    # (b) fresh stateless SGD per compile, same config
    orig_compile = keras.Model.compile

    def fresh_sgd_compile(self, optimizer="rmsprop", **kwargs):
        if isinstance(optimizer, keras.optimizers.SGD):
            optimizer = keras.optimizers.SGD.from_config(
                optimizer.get_config()
            )
        return orig_compile(self, optimizer=optimizer, **kwargs)

    keras.Model.compile = fresh_sgd_compile

    # (a) direct traced call instead of Model.predict; identical RNG
    # stream: draw 1 (uniform action) before the forward pass, draws 2-3
    # (policy sample, exploration mix) after, exactly like the original
    # (resilient_CAC_agents.py:208-219)
    def fast_get_action(self, state, mu=0.1):
        fn = getattr(self, "_fast_actor", None)
        if fn is None:
            fn = self._fast_actor = tf.function(self.actor)
        random_action = np.random.choice(self.n_actions)
        action_prob = fn(state).numpy().ravel()
        action_from_policy = np.random.choice(self.n_actions, p=action_prob)
        self.action = np.random.choice(
            [action_from_policy, random_action], p=[1 - mu, mu]
        )
        return self.action

    for cls in agent_classes:
        cls.get_action = fast_get_action


def _save_object_array(path, ragged_list) -> None:
    """Accommodation (c): main.py:121's ``np.save(..., agent_weights,
    allow_pickle=True)`` relies on implicit ragged->object coercion that
    numpy >= 1.24 rejects; build the object array explicitly."""
    arr = np.empty(len(ragged_list), dtype=object)
    for i, w in enumerate(ragged_list):
        arr[i] = w
    np.save(path, arr, allow_pickle=True)


def run_phase(scenario: str, H: int, seed: int, phase: int, run_dir: Path,
              n_episodes: int, slow_lr: float, quiet: bool) -> dict:
    """One phase of the published two-phase protocol for one cell.

    Phase 1 trains from scratch; phase 2 re-runs the same entry flow
    with pretrained_agents=True, which (like main.py:46-55) reseeds,
    REDRAWS both layout arrays (consuming the same RNG draws), then
    overwrites the goal layout from disk and loads the weights. The
    replay buffer starts empty each phase (main.py passes no
    exp_buffer).
    """
    _install_gym_stub()
    sys.path.insert(0, REFERENCE)
    try:
        import tensorflow as tf
        from tensorflow import keras

        from agents.adversarial_CAC_agents import (  # type: ignore
            Faulty_CAC_agent,
            Greedy_CAC_agent,
            Malicious_CAC_agent,
        )
        from agents.resilient_CAC_agents import RPBCAC_agent  # type: ignore
        from environments.grid_world import Grid_World  # type: ignore
        import training.train_agents as ref_training  # type: ignore
    finally:
        sys.path.remove(REFERENCE)

    tf.get_logger().setLevel("ERROR")
    _patch_semantics_preserving(
        tf, keras,
        (RPBCAC_agent, Faulty_CAC_agent, Greedy_CAC_agent,
         Malicious_CAC_agent),
    )

    base = scenario.removesuffix("_global")
    labels = SCENARIO_LABELS[base]
    # published run parameters (main.py defaults + job.sh overrides)
    args = {
        "n_agents": 5,
        "agent_label": labels,
        "in_nodes": [[0, 1, 2, 3], [1, 2, 3, 4], [2, 3, 4, 0],
                     [3, 4, 0, 1], [4, 0, 1, 2]],
        "n_actions": 5,
        "n_states": 2,
        "n_episodes": n_episodes,
        "max_ep_len": 20,
        "n_ep_fixed": 50,
        "n_epochs": 10,
        "slow_lr": slow_lr,
        "fast_lr": 0.01,
        "batch_size": 200,
        "buffer_size": 2000,
        "gamma": 0.9,
        "H": H,
        "common_reward": scenario.endswith("_global"),
        "pretrained_agents": phase > 1,
        "random_seed": seed,
    }

    # entry flow, in main.py's exact order (seeding, layout draws,
    # pretrained overrides)
    np.random.seed(seed)
    tf.random.set_seed(seed)
    s_desired = np.random.randint(0, 5, size=(5, args["n_states"]))
    s_initial = np.random.randint(0, 5, size=(5, args["n_states"]))
    pretrained_weights = None
    if args["pretrained_agents"]:
        pretrained_weights = np.load(
            run_dir / "pretrained_weights.npy", allow_pickle=True
        )
        s_desired = np.load(run_dir / "desired_state.npy", allow_pickle=True)

    agents = []
    for node in range(args["n_agents"]):
        # main.py:60-82's architecture, verbatim contract: 20-20 LeakyReLU
        # trunks, softmax / linear heads
        def mlp(out_units, out_activation, in_dim):
            return keras.Sequential([
                keras.Input(shape=(args["n_agents"], in_dim)),
                keras.layers.Flatten(),
                keras.layers.Dense(
                    20, activation=keras.layers.LeakyReLU(negative_slope=0.1)
                ),
                keras.layers.Dense(
                    20, activation=keras.layers.LeakyReLU(negative_slope=0.1)
                ),
                keras.layers.Dense(out_units, activation=out_activation),
            ])

        actor = mlp(args["n_actions"], "softmax", args["n_states"])
        critic = mlp(1, None, args["n_states"])
        team_reward = mlp(1, None, args["n_states"] + 1)
        if pretrained_weights is not None:
            actor.set_weights(pretrained_weights[node][0])
            critic.set_weights(pretrained_weights[node][1])
            team_reward.set_weights(pretrained_weights[node][2])

        label = labels[node]
        if label == "Malicious":
            agent = Malicious_CAC_agent(
                actor, critic, team_reward, slow_lr=args["slow_lr"],
                fast_lr=args["fast_lr"], gamma=args["gamma"],
            )
            if pretrained_weights is not None:
                agent.critic_local_weights = pretrained_weights[node][3]
        elif label == "Faulty":
            agent = Faulty_CAC_agent(
                actor, critic, team_reward, slow_lr=args["slow_lr"],
                gamma=args["gamma"],
            )
        elif label == "Greedy":
            agent = Greedy_CAC_agent(
                actor, critic, team_reward, slow_lr=args["slow_lr"],
                fast_lr=args["fast_lr"], gamma=args["gamma"],
            )
        else:
            agent = RPBCAC_agent(
                actor, critic, team_reward, slow_lr=args["slow_lr"],
                fast_lr=args["fast_lr"], gamma=args["gamma"], H=args["H"],
            )
        agents.append(agent)

    env = Grid_World(
        nrow=5, ncol=5, n_agents=args["n_agents"], desired_state=s_desired,
        initial_state=s_initial, randomize_state=True, scaling=True,
    )

    run_dir.mkdir(parents=True, exist_ok=True)
    t0 = time.perf_counter()
    log_path = run_dir / f"out{phase}.txt"
    with open(log_path, "w") as log:
        print(args, s_desired, file=log)
        target = log if quiet else sys.stdout
        with contextlib.redirect_stdout(target):
            agent_weights, sim_data = ref_training.train_RPBCAC(
                env, agents, args
            )
    dt = time.perf_counter() - t0

    sim_data.to_pickle(run_dir / f"sim_data{phase}.pkl")
    _save_object_array(run_dir / "pretrained_weights.npy", agent_weights)
    np.save(run_dir / "desired_state.npy", s_desired, allow_pickle=True)

    returns = sim_data["True_team_returns"].to_numpy()
    roll = min(200, len(returns))
    summary = {
        "scenario": scenario,
        "H": H,
        "seed": seed,
        "phase": phase,
        "episodes": len(returns),
        "final_500_mean": float(np.mean(returns[-500:])),
        "rolling200_final": float(np.mean(returns[-roll:])),
        "wall_clock_s": round(dt, 1),
        "env_steps_per_sec": round(
            len(returns) * args["max_ep_len"] / dt, 1
        ),
    }
    with open(run_dir / f"summary{phase}.json", "w") as f:
        json.dump(summary, f, indent=1)
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--scenario", default="coop",
                   choices=sorted(SCENARIO_LABELS)
                   + sorted(s + "_global" for s in SCENARIO_LABELS))
    p.add_argument("--H", type=int, default=0)
    p.add_argument("--seed", type=int, default=100)
    p.add_argument("--phases", type=int, default=2)
    p.add_argument("--start_phase", type=int, default=1,
                   help="resume at this phase (earlier phases' weight "
                   "files must exist in the run dir)")
    p.add_argument("--n_episodes", type=int, default=4000,
                   help="episodes PER PHASE (published protocol: 4000)")
    p.add_argument("--slow_lr", type=float, default=0.002,
                   help="published job.sh override")
    p.add_argument("--out", default="simulation_results/tf_arbitration")
    p.add_argument("--verbose", action="store_true",
                   help="stream the reference's per-episode prints to "
                   "stdout instead of out<phase>.txt")
    args = p.parse_args(argv)

    run_dir = (Path(args.out) / args.scenario / f"H={args.H}"
               / f"seed={args.seed}")
    for phase in range(args.start_phase, args.phases + 1):
        summary = run_phase(
            args.scenario, args.H, args.seed, phase, run_dir,
            args.n_episodes, args.slow_lr, quiet=not args.verbose,
        )
        print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
