#!/usr/bin/env bash
# The queued TPU measurement set (BENCH_SCALING.md tunnel-outage
# post-mortem). Run on a host with a healthy axon tunnel:
#
#   bash scripts/tpu_session.sh
#
# Probes first with a hard timeout (a wedged tunnel hangs any backend
# init, including a bare jax.devices()); if the probe fails nothing else
# runs. After that every step runs INDEPENDENTLY — one failing or
# timed-out measurement must not cost the rest of the session — and a
# status summary prints at the end. In order of value:
#   1. the N=64 / N=256 scaling rows x {xla, xla_sort, pallas,
#      pallas_sort} (BENCH_SCALING.jsonl; 'xla' is now the log-depth
#      TOURNAMENT selection — the sort arms are the comparison rows for
#      refitting PALLAS_CROSSOVER_VOLUME and SELECT_MAX_N_IN on-chip)
#   2. per-phase TPU profile rows incl. the dense n16/n64 shapes behind
#      the CPU tournament crossover refit, with the consensus
#      micro-breakdown (gather vs trim-bounds vs clip/mean) enabled
#      (PERF.jsonl; completes PERF.md's table), plus (2b) the netstack
#      on/off A/B — the one-block critic+TR epoch vs the dual-launch
#      arm, the on-chip confirmation of PERF.md's "netstack" CPU table
#   2c. the fitstack x compute_dtype refit arms: the cross-flavor fused
#      fit scan (fitstack on/off) x {f32, bf16} with the per-flavor
#      fit_coop/fit_adv micro split — the on-chip measurement the
#      fitstack='auto' backend policy and the bf16 arm are queued for
#   3. bfloat16 + fused-fit rows for the 256-wide config (the MXU-native
#      compute mode; float32/per-flavor comparator arms included)
#   4. the fused experiment matrix at the published scale - 16 cells x
#      3 seeds x 2x4000 episodes as ONE program per phase (writes a
#      sweep tree under /tmp, we only need the printed wall-clock)
#   5. bench.py headline sanity (the driver runs this at round end too)
#
# Every command appends self-describing JSONL rows; nothing here edits
# narrative docs - update BENCH_SCALING.md / PERF.md from the new rows.
set -u
cd "$(dirname "$0")/.."

echo "== probe =="
if ! timeout 240 python -c "import jax; d = jax.devices(); print(d); assert d[0].platform != 'cpu', 'CPU fallback - tunnel down'"; then
    echo "probe FAILED - tunnel down, aborting before any measurement"
    # distinct exit code: "nothing ran" (watchers keep waiting) vs "ran
    # with failures" (exit 1 below)
    exit 2
fi

# Kernel-feasibility preamble: price every queued Pallas shape's
# per-grid-step VMEM/SMEM residency from its BlockSpec plan (pure host
# arithmetic — no backend touched) BEFORE burning tunnel time on step 1.
# One line per (step, kernel, shape); run_step below vetoes any step
# holding an `infeasible` verdict for the selected generation (override
# with TPU_GEN=v5e/v5p for the bigger-VMEM parts; default v4 is the
# strictest committed budget). A broken preflight must not veto the
# session: derivation failure leaves every step unverified, not aborted.
echo "== kernel_feasibility preflight (TPU_GEN=${TPU_GEN:-v4}) =="
FEAS_FILE=$(mktemp)
if ! timeout 900 python -m rcmarl_tpu lint --feasibility \
        ${TPU_GEN:+--tpu_gen "$TPU_GEN"} | tee "$FEAS_FILE"; then
    echo "preflight derivation FAILED - every step runs unverified"
    : > "$FEAS_FILE"
fi

declare -A status
step_order=()

run_step() {
    local name="$1"; shift
    # the leading "<tag>." of every step name is its preflight key
    local tag="${name%%.*}"
    echo "== ${name} =="
    step_order+=("$name")
    local infeasible
    infeasible=$(grep -E "^step:${tag} .*verdict=infeasible" "$FEAS_FILE" || true)
    if [ -n "$infeasible" ]; then
        echo "step ${tag} ABORTED: kernel_feasibility preflight priced a"
        echo "queued Pallas shape over the ${TPU_GEN:-v4} on-chip budget:"
        printf '%s\n' "$infeasible" | sed 's/^/    /'
        echo "(rerun with TPU_GEN=v5e or v5p on a bigger-VMEM host)"
        status["$name"]="ABORTED (infeasible kernel shape)"
        return
    fi
    if "$@"; then
        status["$name"]=ok
    else
        status["$name"]="FAILED (rc=$?)"
    fi
}

run_step "1. scaling rows (n64/n256 x sort/select x xla/pallas)" \
    timeout 5400 python -m rcmarl_tpu bench \
    --configs n64_ring n64_full n64_large_h2 n256_ring \
    --impl xla xla_sort pallas pallas_sort --out BENCH_SCALING.jsonl

run_step "2. per-phase profile rows (tournament-vs-sort arms + micro)" \
    timeout 3600 python -m rcmarl_tpu profile \
    --configs ref5_ring n16_full n64_full n64_large_h2 \
    --impl xla xla_sort pallas pallas_sort \
    --consensus_micro --out PERF.jsonl

run_step "2b. netstack A/B rows (one-block epoch vs dual-launch arm)" \
    timeout 3600 python -m rcmarl_tpu profile \
    --configs ref5_ring n16_full n64_full \
    --netstack on off \
    --consensus_micro --out PERF.jsonl

run_step "2c. fitstack x compute_dtype refit arms (fused fit scan A/B)" \
    timeout 3600 python -m rcmarl_tpu profile \
    --configs ref5_ring n16_mixed n64_full \
    --fitstack on off --compute_dtype float32 bfloat16 \
    --consensus_micro --out PERF.jsonl

run_step "3. bfloat16 rows (256-wide config + fused-fit arm)" \
    timeout 1800 python -m rcmarl_tpu bench \
    --configs n64_large_h2 --impl xla \
    --fitstack on off \
    --compute_dtype float32 bfloat16 --out BENCH_SCALING.jsonl

run_step "4. fused published matrix, one program per phase" \
    timeout 5400 python -m rcmarl_tpu sweep --fused \
    --scenarios coop coop_global greedy greedy_global \
    faulty faulty_global malicious malicious_global \
    --H 0 1 --seeds 100 200 300 --n_episodes 4000 --phases 2 \
    --out /tmp/fused_tpu_matrix

run_step "5. headline" \
    timeout 3600 python bench.py

# The serving benchmark axis (PR 10): on-chip actions/sec through the
# compiled batched inference launch — the committed BENCH_SERVE.jsonl
# rows are CPU fallbacks (headline:false); this is their TPU refit.
run_step "6. serve actions/sec refit (batched policy serving headline)" \
    bash -c 'set -o pipefail; timeout 1800 python bench.py --serve | tee -a BENCH_SERVE.jsonl'

# The async pipeline (PR 11): the committed sync-vs-pipelined rows are
# CPU fallbacks (headline:false — a serial core executes the two tiers
# back to back, so they measure host-loop overhead, not overlap). This
# is the on-chip refit where the shadow claim is actually decidable:
# rollout cost must disappear into the epoch shadow at depth >= 2.
run_step "7. pipeline shadow refit (sync vs pipelined, on-chip)" \
    timeout 3600 python -m rcmarl_tpu bench \
    --configs n16_full n64_full --pipeline_depth 0 2 4 \
    --n_ep_fixed 10 --blocks 5 --reps 3 --out PERF.jsonl

run_step "7b. pipeline headline pair (bench.py orchestration)" \
    bash -c 'set -o pipefail; timeout 1800 python bench.py --pipeline | tee -a PERF.jsonl'

# The env zoo (PR 12): the committed per-env rollout/epoch rows are CPU
# fallbacks (headline:false). On-chip bench arms for every new env at
# the n16/n64 shapes — rows tagged with the resolved env name +
# cost_fingerprint, so per-env steps/s claims tie to the exact program.
run_step "8. env-zoo on-chip bench arms (pursuit/coverage/congestion)" \
    timeout 3600 python -m rcmarl_tpu bench \
    --configs n16_ring n64_ring --env pursuit coverage congestion \
    --n_ep_fixed 10 --blocks 3 --reps 3 --out PERF.jsonl

# The one-kernel epoch (PR 13): the committed pins are interpret-mode
# (headline:false) and the AUDIT.jsonl bytes gate is the BlockSpec DMA
# model — this is the REAL-LOWERING refit: (9) fused-vs-two-launch
# epoch A/B (consensus_impl pallas_fused vs xla/pallas at the dense
# shapes, rows tagged with the resolved impl + cost_fingerprint), and
# (9b) the fit-scan kernel arm vs the XLA scan (fitstack pallas vs on).
# These rows are what lets 'auto' adopt the fused arms with a measured
# crossover instead of a CPU guess.
run_step "9. one-kernel epoch refit (pallas_fused vs two-launch, on-chip)" \
    timeout 3600 python -m rcmarl_tpu bench \
    --configs n16_full n64_full n64_large_h2 \
    --impl xla pallas pallas_fused \
    --n_ep_fixed 10 --blocks 3 --reps 3 --out BENCH_SCALING.jsonl

run_step "9b. fit-scan kernel refit (fitstack pallas vs scan, on-chip)" \
    timeout 3600 python -m rcmarl_tpu profile \
    --configs n16_mixed n64_full \
    --fitstack on pallas --consensus_micro --out PERF.jsonl

# The production serving tier (PR 14): the committed latency-vs-load
# sweep and the F=4 fleet row are CPU fallbacks (headline:false). (10)
# re-runs the micro-batching latency sweep on-chip — Poisson + bursty
# arrival twins at max_batch 4096 up to 80M req/s offered, the
# saturation knee is the headline value; (10b) trains a fresh ref5
# checkpoint, snapshots four policy versions, and serves them as ONE
# fleet launch on-chip (per-member bitwise parity verified by the CLI
# before timing). Both tee into BENCH_SERVE.jsonl like step 6.
run_step "10. serving latency knee refit (micro-batching queue, on-chip)" \
    bash -c 'set -o pipefail; timeout 1800 python bench.py --serve_load | tee -a BENCH_SERVE.jsonl'

run_step "10b. fleet serving row (F=4 policy versions, one launch)" \
    bash -c 'set -o pipefail; d=$(mktemp -d); \
      timeout 900 python - "$d" <<'"'"'PY'"'"'
import sys, jax
from pathlib import Path
from rcmarl_tpu.config import Config
from rcmarl_tpu.training.trainer import train
from rcmarl_tpu.utils.checkpoint import save_checkpoint
cfg = Config(slow_lr=0.002, fast_lr=0.01, seed=100)
out = Path(sys.argv[1]); state = None
for v in range(4):
    state, _ = train(cfg, n_episodes=100, state=state)
    save_checkpoint(out / f"policy_v{v + 1}.npz", state, cfg)
PY
      timeout 900 python -m rcmarl_tpu serve \
        --fleet "$d"/policy_v1.npz "$d"/policy_v2.npz \
                "$d"/policy_v3.npz "$d"/policy_v4.npz \
        --batch 4096 --steps 30 --reps 3 --out BENCH_SERVE.jsonl'

# The chaos campaign (PR 15): the committed RESILIENCE.jsonl was
# generated on the CPU host (every cell deterministic there). (11)
# re-runs the FULL campaign on-chip: outcomes must hold — a cell that
# survived on CPU failing on TPU is a real platform finding, and a
# widened degradation envelope is reported with the fresh rows in
# RESILIENCE.jsonl.new. If the on-chip deltas are legitimate (e.g.
# different launch costs moving a tiny return inside the generous
# band), regenerate with `chaos --run` and commit the refreshed ledger
# alongside the session's other artifacts.
run_step "11. chaos campaign on-chip refit (chaos --check)" \
    timeout 1800 python -m rcmarl_tpu chaos --check \
    --baseline RESILIENCE.jsonl

# The one-kernel serving path (PR 16): the committed fused-serve rows
# are interpret-mode (headline:false) and the serve_path bytes gate is
# the BlockSpec DMA model — this is the REAL-LOWERING refit: (12) the
# fused-vs-XLA serve A/B on a fresh checkpoint (the CLI verifies
# actions+probs BITWISE on the real batch before timing, so the rows
# carry fused_parity proven on-chip), (12b) the per-arm serve
# micro-breakdown (forward/key-derivation/sample splits on the XLA arm
# vs the whole-kernel fused time), plus the SLO autoscale replay over
# REAL on-chip launch times riding the last serve invocation (the
# committed autoscale_slo.json is a CPU-measured service model). These
# rows are what lets --serve_impl auto adopt the fused program with a
# measured win.
run_step "12. one-kernel serve refit (fused vs XLA, bitwise-gated)" \
    bash -c 'set -o pipefail; d=$(mktemp -d); \
      timeout 900 python - "$d" <<'"'"'PY'"'"'
import sys
from pathlib import Path
from rcmarl_tpu.config import Config
from rcmarl_tpu.training.trainer import train
from rcmarl_tpu.utils.checkpoint import save_checkpoint
cfg = Config(seed=100)
state, _ = train(cfg, n_episodes=100)
save_checkpoint(Path(sys.argv[1]) / "deployed.npz", state, cfg)
PY
      for impl in xla pallas; do
        timeout 900 python -m rcmarl_tpu serve \
          --checkpoint "$d"/deployed.npz --serve_impl "$impl" \
          --batch 4096 --steps 30 --reps 3 --out BENCH_SERVE.jsonl \
          || exit 1
      done
      timeout 900 python -m rcmarl_tpu serve \
        --checkpoint "$d"/deployed.npz --serve_impl pallas \
        --batch 4096 --steps 20 --reps 3 \
        --autoscale 2000 --max_scale 16 --out BENCH_SERVE.jsonl'

run_step "12b. serve micro-breakdown arms (forward/key/sample splits)" \
    timeout 1800 python -m rcmarl_tpu profile \
    --serve_micro --serve_impl xla pallas \
    --serve_batch 4096 --out PERF.jsonl

# The pipelined gossip fleet (PR 17): the committed gala_composed
# steps/s row is a CPU fallback (headline:false — a serial core runs
# every replica's two tiers back to back, so it measures host-loop
# overhead, not fleet overlap). This is the on-chip refit: the full
# composed experiment (flat vs composed Byzantine bands + the mean
# documented-fail arm + serving containment) at its committed defaults,
# re-appending a headline composed steps/s row to PERF.jsonl.
run_step "13. pipelined-gossip-fleet refit (composed steps/s, on-chip)" \
    timeout 3600 python scripts/gala_experiment.py \
    --json_out simulation_results/gala_composed_tpu.json \
    --perf_out PERF.jsonl

# The mega-population path (PR 18): the committed n256_sparse /
# n1024_sparse epoch rows and the sparse-vs-dense consensus micros are
# CPU fallbacks (headline:false — a serial host loop dominates the
# per-block resample + launch). This is the on-chip refit: (14) the
# sparse bench cells at both scales across the env-zoo scale-up arms
# (congestion + pursuit ride the same cells via --env), re-appending
# headline epoch rows with the resolved cost_fingerprint so the
# O(n·deg·P) claim is priced on the MXU, and (14b) the consensus
# micro split (gather vs trim-bounds vs clip/mean) on the n256 dense
# comparator vs the sparse schedule — the measured crossover the
# AUDIT.jsonl cost arm models statically.
run_step "14. mega-population sparse refit (n256/n1024 epoch rows)" \
    timeout 5400 python -m rcmarl_tpu bench \
    --configs n256_sparse n1024_sparse \
    --env grid_world congestion pursuit \
    --n_ep_fixed 2 --blocks 3 --reps 3 --out PERF.jsonl

run_step "14b. sparse-vs-dense consensus micro (n256, on-chip)" \
    timeout 3600 python -m rcmarl_tpu profile \
    --configs n256_ring n256_sparse \
    --consensus_micro --out PERF.jsonl

# The sparse one-kernel epoch (PR 19): the committed scheduled-graph
# fused rows are interpret-mode (headline:false) and the AUDIT.jsonl
# sparse_consensus bytes gate is the BlockSpec DMA model — this is the
# REAL-LOWERING refit: (15) the sparse-fused vs XLA-sparse consensus
# A/B at n=256 on both schedule harnesses (the host-looped reference
# and the round-19 stacked-schedule scan; rows tagged sched_harness/
# window so the two-axis win — kernel fusion x launch amortisation —
# separates in the ledger), and (15b) the scanned-window n=1024 row,
# the scale where per-block host dispatch dominated the CPU numbers.
run_step "15. sparse-fused refit (scheduled fused vs XLA, both harnesses)" \
    timeout 5400 python -m rcmarl_tpu bench \
    --configs n256_sparse \
    --impl xla pallas_fused --sched_harness both \
    --n_ep_fixed 2 --blocks 3 --reps 3 --out BENCH_SCALING.jsonl

run_step "15b. scanned-window n1024 row (S blocks per launch, on-chip)" \
    timeout 5400 python -m rcmarl_tpu bench \
    --configs n1024_sparse \
    --impl xla pallas_fused --sched_harness scanned \
    --n_ep_fixed 2 --blocks 3 --reps 3 --out PERF.jsonl

echo "== session summary =="
rc=0
for name in "${step_order[@]}"; do
    echo "  ${name}: ${status[$name]}"
    [ "${status[$name]}" = ok ] || rc=1
done
echo "== update BENCH_SCALING.md / PERF.md / PARALLELISM.md from the new rows =="
exit "$rc"
