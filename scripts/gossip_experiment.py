#!/usr/bin/env python
"""Byzantine-replica gossip experiment — the PR's QUALITY evidence.

Runs R gossip-replicated learners (rcmarl_tpu.parallel.gossip) with H
always-adversarial Byzantine replicas under BOTH mixing arms:

- ``trimmed``: the repo's sanitized resilient clip-and-average
  (gossip_H = H) — the healthy R−H replicas must stay finite and keep
  training;
- ``mean``: the plain-mean comparison arm — a single NaN-bombing
  replica must poison it (the motivation for trimming).

plus a clean no-Byzantine control, for each Byzantine mode requested
(``nan`` = all-NaN bombs, ``sign_flip`` = negated parameters). Also
times the warm gossip-mix launch standalone for the PERF.jsonl
gossip-overhead row.

Artifacts:
  --json_out   full per-arm results (committed:
               simulation_results/gossip_byzantine.json — QUALITY.md
               renders its evidence section from this file)
  --perf_out   append the gossip-overhead JSONL row (PERF.jsonl)

Usage (the committed evidence was generated with the defaults):
  JAX_PLATFORMS=cpu python scripts/gossip_experiment.py \
      --json_out simulation_results/gossip_byzantine.json \
      --perf_out PERF.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from datetime import datetime
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_cfg(args, mix: str, byzantine: tuple, mode: str):
    from rcmarl_tpu.config import Config
    from rcmarl_tpu.faults import ReplicaFaultPlan

    plan = (
        ReplicaFaultPlan(byzantine_replicas=byzantine, byzantine_mode=mode)
        if byzantine
        else None
    )
    return Config(
        n_episodes=args.n_episodes,
        n_ep_fixed=args.n_ep_fixed,
        replicas=args.replicas,
        gossip_graph="full",
        gossip_H=args.gossip_H,
        gossip_every=args.gossip_every,
        gossip_mix=mix,
        replica_fault_plan=plan,
        slow_lr=0.002,
    )


def run_arm(args, mix: str, byzantine: tuple, mode: str) -> dict:
    import numpy as np

    from rcmarl_tpu.parallel.gossip import train_gossip

    cfg = build_cfg(args, mix, byzantine, mode)
    t0 = time.perf_counter()
    states, df = train_gossip(cfg, verbose=False)
    dt = time.perf_counter() - t0
    g = df.attrs["gossip"]
    ret = np.asarray(df["True_team_returns"], float)
    w = min(100, len(ret) // 4)
    first = float(np.nanmean(ret[:w]))
    last = float(np.nanmean(ret[-w:]))
    healthy = g["replica_healthy"]
    n_healthy_expected = args.replicas - len(byzantine)
    return {
        "mix": mix,
        "byzantine": list(byzantine),
        "byzantine_mode": mode if byzantine else None,
        "replicas": args.replicas,
        "gossip_H": args.gossip_H,
        "gossip_every": args.gossip_every,
        "rounds": g["rounds"],
        "rollbacks": g["rollbacks"],
        "nonfinite_payload_entries": g["nonfinite"],
        "deficit_fallbacks": g["deficit"],
        "replica_healthy": healthy,
        "healthy_ok": bool(
            all(
                healthy[r]
                for r in range(args.replicas)
                if r not in set(byzantine)
            )
        ),
        "n_healthy_expected": n_healthy_expected,
        "team_return_first": None if np.isnan(first) else round(first, 3),
        "team_return_last": None if np.isnan(last) else round(last, 3),
        "window_episodes": w,
        "wall_seconds": round(dt, 1),
    }


def time_mix_overhead(args) -> dict:
    """Warm per-mix wall time of the gossip launch vs per-block train
    time — the PERF.jsonl gossip-overhead row."""
    import jax
    import jax.numpy as jnp

    from rcmarl_tpu.parallel.gossip import (
        gossip_mix_block,
        replica_seeds,
    )
    from rcmarl_tpu.parallel.seeds import init_states, train_parallel
    from rcmarl_tpu.utils.profiling import Timer

    cfg = build_cfg(args, "trimmed", (), "nan")
    states = init_states(cfg, replica_seeds(cfg))
    rnd = jnp.zeros((), jnp.int32)
    excl = jnp.zeros(cfg.replicas, bool)
    run_mix = lambda: gossip_mix_block(cfg, states.params, states.params, rnd, excl)
    jax.device_get(run_mix()[0].critic)  # compile + warm
    best_mix = float("inf")
    for _ in range(5):
        t = Timer().start()
        out, _ = run_mix()
        best_mix = min(best_mix, t.stop(out.critic))
    # one warm training block for the denominator
    states2, m = train_parallel(cfg, states=states, n_blocks=1)
    t = Timer().start()
    states2, m = train_parallel(cfg, states=states2, n_blocks=1)
    block_s = t.stop(m.true_team_returns)
    return {
        "kind": "gossip_overhead",
        "config": "ref5_gossip",
        "replicas": cfg.replicas,
        "gossip_graph": cfg.gossip_graph,
        "gossip_H": cfg.gossip_H,
        "gossip_every": cfg.gossip_every,
        "n_agents": cfg.n_agents,
        "hidden": list(cfg.hidden),
        "ms_per_mix": round(best_mix * 1e3, 3),
        "sec_per_block": round(block_s, 4),
        "overhead_per_block": round(
            best_mix / (cfg.gossip_every * block_s), 5
        ),
        "platform": jax.devices()[0].platform,
        "timestamp": datetime.now().isoformat(timespec="seconds"),
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=8)
    p.add_argument("--gossip_H", type=int, default=2)
    p.add_argument("--gossip_every", type=int, default=2)
    p.add_argument("--n_episodes", type=int, default=500)
    p.add_argument("--n_ep_fixed", type=int, default=50)
    p.add_argument(
        "--modes", nargs="+", default=["nan", "sign_flip"],
        choices=["nan", "sign_flip", "inf"],
    )
    p.add_argument("--json_out", type=str, default=None)
    p.add_argument("--perf_out", type=str, default=None)
    args = p.parse_args()

    byz = tuple(range(args.replicas - args.gossip_H, args.replicas))
    arms = [("trimmed", (), "nan")]  # clean control
    for mode in args.modes:
        arms.append(("trimmed", byz, mode))
        arms.append(("mean", byz, mode))

    results = []
    for mix, b, mode in arms:
        label = f"{mix} byz={list(b)} mode={mode if b else '-'}"
        print(f"== {label}", file=sys.stderr)
        row = run_arm(args, mix, b, mode)
        results.append(row)
        print(json.dumps(row))

    overhead = time_mix_overhead(args)
    print(json.dumps(overhead))
    if args.perf_out:
        with open(args.perf_out, "a") as f:
            f.write(json.dumps(overhead) + "\n")
    if args.json_out:
        out = Path(args.json_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(
            json.dumps(
                {
                    "generated_by": "python scripts/gossip_experiment.py",
                    "config": {
                        "replicas": args.replicas,
                        "gossip_H": args.gossip_H,
                        "gossip_every": args.gossip_every,
                        "gossip_graph": "full",
                        "n_episodes": args.n_episodes,
                        "byzantine": list(byz),
                    },
                    "arms": results,
                    "overhead": overhead,
                },
                indent=1,
            )
            + "\n"
        )
        print(f"wrote {out}", file=sys.stderr)

    # verdict: trimmed arms keep every healthy replica finite; at least
    # one mean arm must show poisoning (else the experiment is vacuous)
    trimmed_ok = all(
        r["healthy_ok"] for r in results if r["mix"] == "trimmed"
    )
    mean_poisoned = any(
        not r["healthy_ok"] for r in results if r["mix"] == "mean"
    )
    print(
        f"verdict: trimmed_ok={trimmed_ok} mean_poisoned={mean_poisoned}",
        file=sys.stderr,
    )
    return 0 if trimmed_ok and mean_poisoned else 1


if __name__ == "__main__":
    sys.exit(main())
