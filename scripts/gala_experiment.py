#!/usr/bin/env python
"""Pipelined-gossip-fleet experiment — the composed PR's QUALITY evidence.

Runs the COMPOSED topology (rcmarl_tpu.parallel.gala: R gossiping
learner replicas, each fed by its own depth-D actor tier, trimmed-mean
mixed every K blocks, the winner canary-gate-deployed) next to its
PIECES, and proves composition degrades no worse than the pieces:

- ``composed clean`` vs ``composed byz trimmed``: one always-NaN
  Byzantine replica inside the pipelined fleet — the healthy R−1
  replicas must stay finite and the last-window return must stay
  inside the chaos band of the composed clean twin (the same band the
  FLAT gossip Byzantine cell holds);
- ``flat clean`` vs ``flat byz trimmed``: the pipeline_depth=0 pieces,
  for the side-by-side degradation deltas;
- ``composed byz mean``: the plain-mean comparison arm — the same
  single NaN replica must poison it (documented fail), while the
  canary-gated deploy publisher must still reject every poisoned
  winner (serving containment holds even when training is lost).

Also times the warm composed block for the PERF.jsonl composed
steps/s row (headline:false on CPU — a serial core runs the tiers
back to back).

Artifacts:
  --json_out   full per-arm results (committed:
               simulation_results/gala_composed.json — QUALITY.md
               renders its evidence section from this file)
  --perf_out   append the composed steps/s JSONL row (PERF.jsonl)

Usage (the committed evidence was generated with the defaults):
  JAX_PLATFORMS=cpu python scripts/gala_experiment.py \
      --json_out simulation_results/gala_composed.json \
      --perf_out PERF.jsonl
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from datetime import datetime
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: The chaos-cell band (rcmarl_tpu.chaos.registry.RETURN_BAND): a
#: faulted arm within this relative band of its clean twin counts as
#: functionally intact.
BAND = 0.5


def build_cfg(args, mix: str, byzantine: tuple, mode: str, depth: int):
    from rcmarl_tpu.config import Config
    from rcmarl_tpu.faults import ReplicaFaultPlan

    plan = (
        ReplicaFaultPlan(byzantine_replicas=byzantine, byzantine_mode=mode)
        if byzantine
        else None
    )
    return Config(
        n_episodes=args.n_episodes,
        n_ep_fixed=args.n_ep_fixed,
        replicas=args.replicas,
        gossip_graph="full",
        gossip_H=args.gossip_H,
        gossip_every=args.gossip_every,
        gossip_mix=mix,
        replica_fault_plan=plan,
        pipeline_depth=depth,
        canary_band=args.canary_band if depth else 0.0,
        slow_lr=0.002,
    )


def _train(cfg):
    if cfg.pipeline_depth:
        from rcmarl_tpu.parallel.gala import train_gala

        return train_gala(cfg)
    from rcmarl_tpu.parallel.gossip import train_gossip

    return train_gossip(cfg)


def run_arm(args, label: str, mix: str, byzantine: tuple, mode: str,
            depth: int) -> dict:
    import numpy as np

    cfg = build_cfg(args, mix, byzantine, mode, depth)
    t0 = time.perf_counter()
    states, df = _train(cfg)
    dt = time.perf_counter() - t0
    g = df.attrs["gossip"]
    ret = np.asarray(df["True_team_returns"], float)
    w = min(100, len(ret) // 4)
    first = float(np.nanmean(ret[:w]))
    last = float(np.nanmean(ret[-w:]))
    healthy = g["replica_healthy"]
    row = {
        "arm": label,
        "mix": mix,
        "byzantine": list(byzantine),
        "byzantine_mode": mode if byzantine else None,
        "pipeline_depth": depth,
        "replicas": args.replicas,
        "gossip_H": args.gossip_H,
        "gossip_every": args.gossip_every,
        "rounds": g["rounds"],
        "rollbacks": g["rollbacks"],
        "excluded": g["excluded"],
        "replica_healthy": healthy,
        "healthy_ok": bool(
            all(
                healthy[r]
                for r in range(args.replicas)
                if r not in set(byzantine)
            )
        ),
        "team_return_first": None if np.isnan(first) else round(first, 3),
        "team_return_last": None if np.isnan(last) else round(last, 3),
        "window_episodes": w,
        "wall_seconds": round(dt, 1),
    }
    if depth:
        p = df.attrs["pipeline"]
        c = df.attrs["canary"]
        row["staleness_mean"] = p["staleness_mean"]
        row["publishes"] = p["publishes"]
        # the guard family is only present when the guard ran (clean
        # unguarded arms have nothing to count)
        row["skipped"] = sum(
            df.attrs.get("guard", {}).get(
                "replica_skipped", [0] * args.replicas
            )
        )
        row["canary"] = {
            k: c[k]
            for k in ("evals", "accepts", "rejects", "deploys",
                      "deploy_rejects", "deploy_healthy")
        }
    return row


def _within_band(final, clean) -> bool:
    if final is None or clean is None:
        return False
    return abs(final - clean) <= BAND * max(1.0, abs(clean))


def time_composed_block(args) -> dict:
    """Warm composed steps/s — resume a warmed fleet for one more run
    and report env steps per wall second (the PERF.jsonl composed row;
    headline:false on CPU, the serial-core caveat of the pipeline
    rows)."""
    import jax

    from rcmarl_tpu.parallel.gala import gala_fingerprint, train_gala

    cfg = build_cfg(args, "trimmed", (), "nan", args.pipeline_depth)
    warm_eps = 2 * cfg.n_ep_fixed
    states, df = train_gala(cfg, n_episodes=warm_eps)  # compile + warm
    t0 = time.perf_counter()
    states, _ = train_gala(
        cfg, n_episodes=warm_eps, states=states,
        start_round=df.attrs["gossip"]["gossip_round"],
    )
    jax.block_until_ready(states.params)
    dt = time.perf_counter() - t0
    steps = warm_eps * cfg.max_ep_len * cfg.replicas
    return {
        "kind": "gala_composed",
        "config": "ref5_gala",
        "replicas": cfg.replicas,
        "pipeline_depth": cfg.pipeline_depth,
        "gossip_every": cfg.gossip_every,
        "gossip_H": cfg.gossip_H,
        "canary_band": cfg.canary_band,
        "n_agents": cfg.n_agents,
        "hidden": list(cfg.hidden),
        "env_steps_per_sec": round(steps / dt, 1),
        "sec_per_block": round(dt / (warm_eps // cfg.n_ep_fixed), 4),
        "cost_fingerprint": gala_fingerprint(cfg),
        "workload": {"episodes": warm_eps, "block_steps":
                     cfg.n_ep_fixed * cfg.max_ep_len},
        "platform": jax.devices()[0].platform,
        "headline": jax.devices()[0].platform != "cpu",
        "timestamp": datetime.now().isoformat(timespec="seconds"),
    }


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=4)
    p.add_argument("--gossip_H", type=int, default=1)
    p.add_argument("--gossip_every", type=int, default=4)
    p.add_argument("--pipeline_depth", type=int, default=2)
    p.add_argument("--canary_band", type=float, default=0.5)
    p.add_argument("--n_episodes", type=int, default=400)
    p.add_argument("--n_ep_fixed", type=int, default=50)
    p.add_argument("--json_out", type=str, default=None)
    p.add_argument("--perf_out", type=str, default=None)
    args = p.parse_args()

    byz = (args.replicas - 1,)
    d = args.pipeline_depth
    arms = [
        ("flat clean", "trimmed", (), "nan", 0),
        ("flat byz trimmed", "trimmed", byz, "nan", 0),
        ("composed clean", "trimmed", (), "nan", d),
        ("composed byz trimmed", "trimmed", byz, "nan", d),
        ("composed byz mean", "mean", byz, "nan", d),
    ]

    results = []
    for label, mix, b, mode, depth in arms:
        print(f"== {label}", file=sys.stderr)
        row = run_arm(args, label, mix, b, mode, depth)
        results.append(row)
        print(json.dumps(row))

    perf = time_composed_block(args)
    print(json.dumps(perf))
    if args.perf_out:
        with open(args.perf_out, "a") as f:
            f.write(json.dumps(perf) + "\n")

    by = {r["arm"]: r for r in results}
    # verdict: (1) every trimmed arm keeps its healthy replicas finite;
    # (2) the composed Byzantine arm holds the SAME chaos band vs its
    # clean twin that the flat arm holds vs its own — composition
    # degrades no worse than the pieces; (3) the mean arm is poisoned
    # (else the comparison is vacuous) while its canary-gated deploy
    # publisher rejected every poisoned winner (serving containment).
    trimmed_ok = all(
        r["healthy_ok"] for r in results if r["mix"] == "trimmed"
    )
    flat_in_band = _within_band(
        by["flat byz trimmed"]["team_return_last"],
        by["flat clean"]["team_return_last"],
    )
    composed_in_band = _within_band(
        by["composed byz trimmed"]["team_return_last"],
        by["composed clean"]["team_return_last"],
    )
    mean_row = by["composed byz mean"]
    mean_poisoned = (
        not mean_row["healthy_ok"]
        or mean_row["rollbacks"] > 0
        or mean_row["team_return_last"] is None
    )
    serving_contained = (
        mean_row["canary"]["deploy_healthy"]
        and (mean_row["canary"]["deploy_rejects"]
             + mean_row["canary"]["rejects"]) >= 1
    )
    verdict = {
        "trimmed_ok": trimmed_ok,
        "flat_in_band": flat_in_band,
        "composed_in_band": composed_in_band,
        "mean_poisoned": mean_poisoned,
        "serving_contained": serving_contained,
    }
    print(f"verdict: {verdict}", file=sys.stderr)

    if args.json_out:
        out = Path(args.json_out)
        out.parent.mkdir(parents=True, exist_ok=True)
        out.write_text(
            json.dumps(
                {
                    "generated_by": "python scripts/gala_experiment.py",
                    "config": {
                        "replicas": args.replicas,
                        "gossip_H": args.gossip_H,
                        "gossip_every": args.gossip_every,
                        "pipeline_depth": args.pipeline_depth,
                        "canary_band": args.canary_band,
                        "gossip_graph": "full",
                        "n_episodes": args.n_episodes,
                        "byzantine": list(byz),
                    },
                    "arms": results,
                    "perf": perf,
                    "verdict": verdict,
                },
                indent=1,
            )
            + "\n"
        )
        print(f"wrote {out}", file=sys.stderr)

    return 0 if all(verdict.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
