#!/usr/bin/env python
"""The staleness quality cell: measured off-policy staleness vs return.

The async pipeline (``rcmarl_tpu.pipeline``) makes acting-parameter
staleness a configured, counted quantity: ``pipeline_depth`` blocks of
actor lead plus up to ``publish_every - 1`` blocks of publish lag. This
script sweeps ``publish_every`` at a fixed pipelined depth against the
synchronous reference arm (``pipeline_depth=0``, bitwise the historical
trainer), records the MEASURED per-run staleness counters next to each
arm's returns, and scores every arm with the same smoothing/threshold
machinery QUALITY.md uses — the whole-policy, schedule-level twin of
the ``stale_p`` link-replay degradation curves
(:mod:`rcmarl_tpu.faults`). The committed verdict lands in
``simulation_results/staleness_quality.json``, which
``python -m rcmarl_tpu quality`` renders into QUALITY.md's
"Pipeline staleness vs return" section.

    python scripts/staleness_quality.py [--episodes 2000] [--seed 300]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from datetime import datetime
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--episodes", type=int, default=2000)
    p.add_argument("--seed", type=int, default=300)
    p.add_argument("--depth", type=int, default=2,
                   help="pipeline depth of the pipelined arms")
    p.add_argument("--publish_every", nargs="+", type=int,
                   default=[1, 4, 16],
                   help="publish cadences to sweep at --depth")
    p.add_argument("--rolling", type=int, default=200)
    p.add_argument("--window", type=int, default=400,
                   help="final-window size for the converged-return mean")
    p.add_argument("--tol", type=float, default=0.05,
                   help="quality-band tolerance (PARITY.md's 5%% default)")
    p.add_argument(
        "--out", type=str,
        default=str(Path(__file__).resolve().parent.parent
                    / "simulation_results/staleness_quality.json"),
    )
    args = p.parse_args()

    import jax
    import numpy as np
    import pandas as pd

    from rcmarl_tpu.analysis.quality import episodes_to_threshold
    from rcmarl_tpu.config import Config
    from rcmarl_tpu.pipeline.trainer import train_pipelined

    base = Config(seed=args.seed)  # the reference 5-agent cooperative ring

    def curve(df) -> pd.Series:
        return (
            df["True_team_returns"]
            .rolling(args.rolling, min_periods=args.rolling)
            .mean()
        )

    def final(df) -> float:
        return float(df["True_team_returns"].iloc[-args.window:].mean())

    # arm list: the synchronous reference first (the threshold source),
    # then the pipelined publish_every sweep at the fixed depth
    arm_cfgs = [("sync depth=0", base)]
    for k in args.publish_every:
        arm_cfgs.append(
            (
                f"depth={args.depth} publish_every={k}",
                base.replace(pipeline_depth=args.depth, publish_every=k),
            )
        )

    arms = []
    for label, cfg in arm_cfgs:
        t0 = time.perf_counter()
        _, df = train_pipelined(cfg, n_episodes=args.episodes)
        wall = round(time.perf_counter() - t0, 2)
        pipe = df.attrs["pipeline"]
        arms.append(
            {
                "label": label,
                "pipeline_depth": cfg.pipeline_depth,
                "publish_every": cfg.publish_every,
                "staleness_mean": round(pipe["staleness_mean"], 3),
                "staleness_max": pipe["staleness_max"],
                "final_return": round(final(df), 4),
                "wall_s": wall,
                "_curve": curve(df),
            }
        )
        print(f"{label}: final {arms[-1]['final_return']} "
              f"(staleness mean {arms[-1]['staleness_mean']}, {wall}s)")

    # the quality bar is the SYNC arm's own converged return, relaxed by
    # tol of its magnitude — the QUALITY.md threshold recipe with the
    # synchronous trainer standing in for the reference
    sync = arms[0]
    threshold = sync["final_return"] - args.tol * abs(sync["final_return"])
    for arm in arms:
        ep = episodes_to_threshold(arm.pop("_curve"), threshold)
        arm["ep_to_threshold"] = None if np.isnan(ep) else int(ep)
        arm["within_band"] = bool(arm["final_return"] >= threshold)

    result = {
        "config": {
            "scenario": "coop ref5_ring (Config defaults)",
            "n_agents": base.n_agents,
            "hidden": list(base.hidden),
            "episodes": args.episodes,
            "seed": args.seed,
            "depth": args.depth,
            "rolling": args.rolling,
            "window": args.window,
            "tol": args.tol,
        },
        "threshold": round(threshold, 4),
        "arms": arms,
        "platform": jax.devices()[0].platform,
        "timestamp": datetime.now().isoformat(timespec="seconds"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(result, indent=1) + "\n")
    print(json.dumps(result, indent=1))
    print(f"wrote {out}")
    # the gate: every swept cadence must stay inside the sync arm's own
    # quality band, or the artifact says loudly which cadence fell out —
    # rc reflects only that the sweep RAN and was recorded (falling out
    # of band at an aggressive cadence is a finding, not a failure)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
