#!/usr/bin/env bash
# Tier-1 CI gate: install the package WITH the `test` extra (pytest +
# hypothesis) and run the exact ROADMAP.md tier-1 verify command on CPU.
#
# Why the extra matters: the property-test modules import hypothesis.
# They guard it with pytest.importorskip so a bare environment skips
# them instead of dying at collection — but CI must run them, not skip
# them, so this script installs `.[test]` first and then FAILS if any
# module still errors at collection (pytest propagates collection
# errors into a nonzero exit code even under
# --continue-on-collection-errors).
#
#   bash scripts/ci_tier1.sh
set -euo pipefail
cd "$(dirname "$0")/.."

python -m pip install -e '.[test]'

# The exact tier-1 verify command from ROADMAP.md. errexit is lifted
# around the pipeline so a failing run still reaches the DOTS_PASSED
# diagnostic and the collection-error guard below (the captured rc is
# re-raised at the end).
set -o pipefail
rm -f /tmp/_t1.log
set +e
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
set -e
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"

# Belt and braces: a collection error must fail CI loudly even if a
# future pytest version stops reflecting it in the exit code.
if grep -aq "ERROR collecting\|errors during collection" /tmp/_t1.log; then
    echo "collection errors detected" >&2
    exit 1
fi
exit "$rc"
