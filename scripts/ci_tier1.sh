#!/usr/bin/env bash
# Tier-1 CI gate: install the package WITH the `test` extra (pytest +
# hypothesis) and run the exact ROADMAP.md tier-1 verify command on CPU.
#
# Why the extra matters: the property-test modules import hypothesis.
# They guard it with pytest.importorskip so a bare environment skips
# them instead of dying at collection — but CI must run them, not skip
# them, so this script installs `.[test]` first and then FAILS if any
# module still errors at collection (pytest propagates collection
# errors into a nonzero exit code even under
# --continue-on-collection-errors).
#
#   bash scripts/ci_tier1.sh
set -euo pipefail
cd "$(dirname "$0")/.."

python -m pip install -e '.[test]'

# The exact tier-1 verify command from ROADMAP.md. errexit is lifted
# around the pipeline so a failing run still reaches the DOTS_PASSED
# diagnostic and the collection-error guard below (the captured rc is
# re-raised at the end).
set -o pipefail
rm -f /tmp/_t1.log
set +e
t1_start=$(date +%s)
# RCMARL_TEST_CACHE=1 turns on the persistent JAX compilation cache for
# the suite (tests/conftest.py): cold runs pay the same compiles they
# always did; reruns on a warm runner get them back from disk. The
# conftest prints an "RCMARL_CACHE hits=... misses=..." tally at session
# end, folded into the wall-budget line below so cache effectiveness is
# visible next to the number it is supposed to shrink.
timeout -k 10 870 env JAX_PLATFORMS=cpu RCMARL_TEST_CACHE=1 \
    python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
set -e
t1_secs=$(( $(date +%s) - t1_start ))
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
# Tier-1 wall budget, measured every run so the 870s ceiling stops
# being discovered by timeout: warn loudly past 90% — a PR pushing the
# suite over that line should move cells to the slow marker / CI cells
# (the PR-8/PR-9 pattern) BEFORE the budget kills the whole gate.
t1_cache=$(grep -ao 'RCMARL_CACHE hits=[0-9]* misses=[0-9]*' /tmp/_t1.log \
    | tail -1 | sed 's/RCMARL_CACHE //')
echo "tier-1 wall budget: ${t1_secs}s / 870s ($(( t1_secs * 100 / 870 ))%," \
     "compile cache ${t1_cache:-unavailable})"
if [ "$t1_secs" -gt 783 ]; then
    echo "WARNING: tier-1 suite consumed >90% of the 870s wall budget" \
         "(${t1_secs}s); shed load to the slow marker before it times out" >&2
fi

# Belt and braces: a collection error must fail CI loudly even if a
# future pytest version stops reflecting it in the exit code.
if grep -aq "ERROR collecting\|errors during collection" /tmp/_t1.log; then
    echo "collection errors detected" >&2
    exit 1
fi
if [ "$rc" -ne 0 ]; then
    exit "$rc"
fi

# Fault-injection smoke cell (kept tiny to stay inside the tier-1 time
# budget: 3 agents, 3x3 grid, 2 blocks): a drop+NaN transport plan with
# the sanitize kernel and the rollback guard must complete rc=0 with
# finite parameters — the end-to-end wire-up of rcmarl_tpu.faults that
# unit tests can't cover (CLI flag plumbing -> Config -> update block ->
# guard -> checkpoint with a FaultPlan header).
smoke_dir=$(mktemp -d)
trap 'rm -rf "$smoke_dir"' EXIT
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 3 --in_degree 3 --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --fault_drop_p 0.2 --fault_nan_p 0.2 --sanitize \
    --summary_dir "$smoke_dir" --quiet
echo "fault-injection smoke cell OK"

# Flattened-path smoke cell: a RAGGED graph (per-agent degrees 4/4/3/3,
# padded + masked; every degree >= 2H+1) under the default flat
# one-launch layout, with
# sanitize and a tiny drop+NaN fault plan — the flattened XLA masked +
# sanitize + fault-injection wire-up end to end, which the unit tests
# cover only layer by layer. Same tiny budget as the cell above.
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 4 --in_nodes '[[0,1,2,3],[1,2,3,0],[2,3,0],[3,0,1]]' \
    --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --consensus_layout flat --fault_drop_p 0.2 --fault_nan_p 0.2 \
    --sanitize --netstack off --summary_dir "$smoke_dir" --quiet
echo "flattened ragged-graph smoke cell OK"

# Netstack smoke cell: the same ragged + sanitize + fault-plan scenario
# on the STACKED critic+TR path (--netstack on, the default) — the
# combined-block gather + flat fault injection + masked sanitize
# consensus end to end, i.e. the exact wire-up tests pin leaf-for-leaf
# against the dual arm above (tests/test_netstack.py), proven here
# through the full CLI -> Config -> trainer stack.
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 4 --in_nodes '[[0,1,2,3],[1,2,3,0],[2,3,0],[3,0,1]]' \
    --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --netstack on --fault_drop_p 0.2 --fault_nan_p 0.2 --fault_stale_p 0.1 \
    --sanitize --summary_dir "$smoke_dir" --quiet
echo "netstack ragged smoke cell OK"

# Fused-fit + bf16 smoke cell: the cross-flavor fused fit scan
# (Config.fitstack) must stay BITWISE the PR-4 arm through the real
# trainer on a mixed cast (every fit flavor live) — on the clean
# regular graph AND on a ragged+faulted+sanitize cell (the acceptance
# cells; the ragged twin of the pytest pin rides the slow marker to
# keep the tier-1 wall budget, so it is CI-enforced here instead) —
# and the bfloat16 compute arm must train end-to-end with finite
# returns curves. The fitstack/compute_dtype wire-up (Config -> epoch
# -> fused scans -> trainer) beyond what the unit pins cover layer by
# layer.
timeout -k 10 420 env JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np, jax
from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.faults import FaultPlan
from rcmarl_tpu.training.trainer import train

kw = dict(
    n_agents=3,
    agent_roles=(Roles.COOPERATIVE, Roles.GREEDY, Roles.MALICIOUS),
    in_nodes=circulant_in_nodes(3, 3), nrow=3, ncol=3,
    n_episodes=4, n_ep_fixed=2, max_ep_len=4, n_epochs=2, H=1,
)
ragged = dict(
    kw,
    n_agents=4,
    agent_roles=(Roles.COOPERATIVE,) * 2 + (Roles.GREEDY, Roles.MALICIOUS),
    in_nodes=((0, 1, 2, 3), (1, 2, 3, 0), (2, 3, 0), (3, 0, 1)),
    consensus_sanitize=True,
    fault_plan=FaultPlan(drop_p=0.2, nan_p=0.2, stale_p=0.1),
)
for cell, c in (("regular", kw), ("ragged+faulted", ragged)):
    s_on, df_on = train(Config(**c, fitstack=True))
    s_off, df_off = train(Config(**c, fitstack=False))
    for a, b in zip(
        jax.tree.leaves(s_on.params), jax.tree.leaves(s_off.params)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(
        df_on["True_team_returns"].values, df_off["True_team_returns"].values
    )
    print(f"fitstack bitwise pin OK ({cell})")
_, df16 = train(Config(**kw, fitstack=True, compute_dtype="bfloat16"))
assert np.isfinite(df16["True_team_returns"].values).all()
print("finite bf16 curves OK")
PY
echo "fused-fit + bf16 smoke cell OK"

# One-kernel-epoch smoke cell: the fused Pallas phase II
# (consensus_impl=pallas_fused_interpret) + the fit-scan kernel
# (fitstack=pallas_interpret) must stay BITWISE the stacked XLA arm
# through the real trainer on the ragged+faulted+sanitize mixed cell —
# the acceptance wire-up (Config -> epoch -> kernel -> tail ->
# trainer), carried here EVERY CI run while the wider equivalence
# matrix rides the slow marker (tests/test_fused_epoch.py) per the
# tier-1 budget pattern — plus the CLI flag plumbing end to end.
timeout -k 10 420 env JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np, jax
from rcmarl_tpu.config import Config, Roles
from rcmarl_tpu.faults import FaultPlan
from rcmarl_tpu.training.trainer import train

kw = dict(
    n_agents=4,
    agent_roles=(Roles.COOPERATIVE,) * 2 + (Roles.GREEDY, Roles.MALICIOUS),
    in_nodes=((0, 1, 2, 3), (1, 2, 3, 0), (2, 3, 0), (3, 0, 1)),
    nrow=3, ncol=3,
    n_episodes=4, n_ep_fixed=2, max_ep_len=4, n_epochs=2, H=1,
    netstack=True, consensus_sanitize=True,
    fault_plan=FaultPlan(drop_p=0.2, nan_p=0.2, stale_p=0.1),
)
s_x, df_x = train(Config(**kw, consensus_impl="xla", fitstack=True))
s_f, df_f = train(Config(
    **kw, consensus_impl="pallas_fused_interpret",
    fitstack="pallas_interpret",
))
for a, b in zip(jax.tree.leaves(s_x.params), jax.tree.leaves(s_f.params)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
np.testing.assert_array_equal(
    df_x["True_team_returns"].values, df_f["True_team_returns"].values
)
print("one-kernel epoch bitwise pin OK (ragged+faulted+sanitize)")
PY
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 3 --in_degree 3 --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --consensus_impl pallas_fused_interpret --fitstack pallas_interpret \
    --netstack on --fault_drop_p 0.2 --fault_nan_p 0.2 --sanitize \
    --summary_dir "$smoke_dir" --quiet
echo "one-kernel epoch smoke cell OK"

# Gossip chaos cell: 4 learner replicas, one ALWAYS-NaN-bombing
# Byzantine replica (replica 3) under trimmed-mean gossip (gossip_H=1)
# with the per-replica guard — the replica-level resilience wire-up end
# to end (CLI flags -> Config -> train_gossip -> gossip_mix_block ->
# replica checkpoint with gossip meta), which the unit tests cover only
# layer by layer. Must exit rc=0 with every replica's params finite
# ("healthy: 4/4") and the degradation counters landing in
# df.attrs['gossip'] (asserted via the printed summary line).
gossip_log="$smoke_dir/gossip.log"
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 3 --in_degree 3 --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --replicas 4 --gossip_graph full --gossip_H 1 --gossip_every 1 \
    --replica_byzantine 3 --replica_byzantine_mode nan \
    --summary_dir "$smoke_dir" --quiet | tee "$gossip_log"
grep -q "gossip: 4 replicas" "$gossip_log"
grep -q "healthy: 4/4" "$gossip_log"
grep -q "non-finite payload entries" "$gossip_log"
echo "gossip chaos cell OK"

# Serve smoke cell: the serving subsystem end to end through the real
# CLI and engine — train a tiny checkpoint, serve batches (one compiled
# launch per step, actions/sec row emitted), then drive the hot-swap +
# corruption sequence: a NEW checkpoint must swap in atomically, a
# corrupted primary+prev pair must be REJECTED with the engine serving
# the last good params, and the degradation counters must land on the
# "served: last-good" summary line. rc=0 throughout.
serve_dir="$smoke_dir/serve"
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 3 --in_degree 3 --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --checkpoint_every 1 --summary_dir "$serve_dir" --quiet
serve_log="$smoke_dir/serve.log"
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m rcmarl_tpu serve \
    --checkpoint "$serve_dir/checkpoint.npz" \
    --batch 32 --steps 4 --reps 1 | tee "$serve_log"
grep -q '"actions_per_sec"' "$serve_log"
timeout -k 10 240 env JAX_PLATFORMS=cpu python - "$serve_dir" <<'PY' | tee "$serve_log"
import sys
import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.serve.engine import ServeEngine, stack_actor_rows, serve_block
from rcmarl_tpu.serve.swap import CheckpointWatcher
from rcmarl_tpu.utils.checkpoint import load_checkpoint_with_meta, save_checkpoint

path = sys.argv[1] + "/checkpoint.npz"
eng = ServeEngine(path)
watcher = CheckpointWatcher(eng)
obs = jax.random.normal(
    jax.random.PRNGKey(0), (16, eng.cfg.n_agents, eng.cfg.obs_dim)
)
a0, _ = eng.serve(obs)

# hot-swap: a NEW checkpoint (perturbed params) must apply atomically
state, cfg, _, _ = load_checkpoint_with_meta(path)
bumped = state._replace(
    params=state.params._replace(
        actor=jax.tree.map(lambda l: l + 0.01, state.params.actor)
    )
)
save_checkpoint(path, bumped, cfg)
assert watcher.poll() is True, "hot-swap did not apply"
ref, _ = serve_block(
    eng.cfg, stack_actor_rows(bumped.params, eng.cfg), obs,
    jax.random.fold_in(jax.random.PRNGKey(eng.eval_seed), 1),
)
a1, _ = eng.serve(obs)
np.testing.assert_array_equal(np.asarray(a1), np.asarray(ref))
print("hot-swap atomic OK")

# corruption: primary AND .prev unreadable -> reject, serve last good
for suffix in ("", ".prev"):
    with open(path + suffix, "r+b") as f:
        f.seek(100)
        f.write(b"\xde\xad\xbe\xef" * 16)
assert watcher.poll() is False, "corrupted checkpoint was not rejected"
a2, _ = eng.serve(obs, step=1)
np.testing.assert_array_equal(np.asarray(a2), np.asarray(ref))
assert np.isfinite(np.asarray(a2)).all()
assert eng.counters["rejects"] == 1 and eng.counters["swaps"] == 1
print(eng.summary_line())
PY
grep -q "hot-swap atomic OK" "$serve_log"
grep -q "served: last-good" "$serve_log"
echo "serve smoke cell OK"

# Production-serving smoke cell (round 14): the latency/fleet/canary
# tier end to end through the real CLI and engines, outside the pytest
# budget — tiny train -> a fleet of 2 checkpoint versions served in ONE
# launch (the CLI verifies per-member bitwise parity before timing;
# grep the fleet row), one load burst through the micro-batching queue
# (grep a latency point), corrupt one member -> the FLEET keeps serving
# with that member degraded to last-good, and the canary gate: a
# poisoned publish and a band-violating (fresh-init) publish are both
# REJECTED (grep the "rejected" line) while a healthy re-publish
# promotes. rc=0 throughout.
prod_dir="$smoke_dir/prod_serve"
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 3 --in_degree 3 --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --checkpoint_every 1 --summary_dir "$prod_dir" --quiet
prod_log="$smoke_dir/prod_serve.log"
cp "$prod_dir/checkpoint.npz" "$prod_dir/member0.npz"
cp "$prod_dir/checkpoint.npz.prev" "$prod_dir/member1.npz"
timeout -k 10 180 env JAX_PLATFORMS=cpu python -m rcmarl_tpu serve \
    --fleet "$prod_dir/member0.npz" "$prod_dir/member1.npz" \
    --batch 16 --steps 4 --reps 1 | tee "$prod_log"
grep -q '"member_parity": "bitwise"' "$prod_log"
grep -q '"fleet": 2' "$prod_log"
timeout -k 10 420 env JAX_PLATFORMS=cpu python - "$prod_dir" <<'PY' | tee "$prod_log"
import sys
import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.serve.canary import CanaryGate, CanaryWatcher
from rcmarl_tpu.serve.engine import ServeEngine
from rcmarl_tpu.serve.fleet import FleetEngine
from rcmarl_tpu.serve.load import fleet_service_fn, poisson_arrivals, run_load
from rcmarl_tpu.training.trainer import init_train_state
from rcmarl_tpu.utils.checkpoint import load_checkpoint_with_meta, save_checkpoint

root = sys.argv[1]
members = [f"{root}/member0.npz", f"{root}/member1.npz"]
fleet = FleetEngine(members)

# one load burst through the micro-batching queue over the REAL fleet
# program (padded max_batch shape, measured launches)
service = fleet_service_fn(fleet.cfg, fleet.fleet, 2, max_batch=16)
rep = run_load(service, poisson_arrivals(0, 64, 2000.0), 16, 0.005)
assert np.isfinite(rep["p99"]) and rep["p99"] > 0
print(f"load burst: p50 {rep['p50']*1000:.2f} ms, "
      f"p99 {rep['p99']*1000:.2f} ms, {rep['launches']} launches")

# corrupt member 1 (primary, no .prev) -> that member degrades to its
# last-good slice; the fleet keeps serving
with open(members[1], "r+b") as f:
    f.seek(100)
    f.write(b"\xde\xad\xbe\xef" * 16)
assert fleet.poll() == []  # the in-place corruption IS a file change
assert fleet.members[1].degraded is True
obs = jax.random.normal(
    jax.random.PRNGKey(0), (8, fleet.cfg.n_agents, fleet.cfg.obs_dim)
)
actions, _ = fleet.serve(obs)
assert np.isfinite(np.asarray(actions)).all()
print(fleet.summary_line())

# canary gate on the solo path: a poisoned publish is rejected by the
# guard chain, a band-violating publish by the REAL band decision
# (the incumbent reference is pinned above any achievable return, so a
# finite fresh-init candidate is deterministically below the floor —
# the committed canary_gate.json experiment carries the
# trained-vs-stale version of this arm), and a healthy re-publish
# promotes after the rejections
path = f"{root}/checkpoint.npz"
eng = ServeEngine(path)
state, cfg, _, _ = load_checkpoint_with_meta(path)
gate = CanaryGate(cfg, state.desired, state.initial, band=0.05, blocks=1)
watcher = CanaryWatcher(eng, gate)
poisoned = state._replace(params=state.params._replace(
    actor=jax.tree.map(lambda l: jnp.asarray(l).at[0].set(jnp.nan),
                       state.params.actor)))
save_checkpoint(path, poisoned, cfg)
save_checkpoint(path, poisoned, cfg)  # poison the .prev rotation too
assert watcher.poll() is False, "poisoned publish was not rejected"
assert eng.counters["rejects"] == 1 and eng.degraded
print("canary: poisoned publish rejected (guard, no eval paid)")
gate.incumbent_return = 0.0  # floor above any achievable return here
fresh = init_train_state(cfg, jax.random.PRNGKey(123))
save_checkpoint(path, fresh, cfg)
assert watcher.poll() is False, "band-violating publish was not rejected"
assert gate.last["reason"] == "frozen return below the band floor"
print("canary: band-violating publish rejected "
      f"(candidate {gate.last['candidate_return']:.3f} < "
      f"floor {gate.last['floor']:.3f})")
gate.set_incumbent(state.params)  # back to the measured incumbent
save_checkpoint(path, state, cfg)  # healthy re-publish
assert watcher.poll() is True, "healthy re-publish did not promote"
print(gate.summary_line())
print(eng.summary_line())
PY
grep -q "load burst: p50" "$prod_log"
grep -q "m1:last-good" "$prod_log"
grep -q "rejected" "$prod_log"
echo "production-serving smoke cell OK"

# One-kernel serving smoke cell (round 16): the fused serve arm + the
# SLO autoscale replay through the real CLI, outside the pytest budget
# — serve the tier's tiny checkpoint on the fused interpret arm (the
# CLI pins actions AND probs BITWISE vs the XLA serve_block chain on
# the real batch before anything is timed, so the grepped row's
# fused_parity is proven, not asserted), then replay the seeded
# 1x->10x->1x swing through the SLO control loop on the same
# invocation: the autoscaled arm must hold the SLO (the summary-line
# grep) and the serve_autoscale row must land. rc=0 throughout.
fused_log="$smoke_dir/fused_serve.log"
timeout -k 10 420 env JAX_PLATFORMS=cpu python -m rcmarl_tpu serve \
    --checkpoint "$prod_dir/member0.npz" \
    --serve_impl pallas_interpret --batch 16 --steps 2 --reps 1 \
    --autoscale 400 --max_scale 8 | tee "$fused_log"
grep -q '"serve_impl": "pallas_interpret"' "$fused_log"
grep -q '"fused_parity": "bitwise"' "$fused_log"
grep -q '"serve_autoscale"' "$fused_log"
grep -q "autoscale: SLO held" "$fused_log"
echo "one-kernel serving smoke cell OK"

# Pipeline smoke cell: the async actor-learner pipeline end to end
# through the real CLI — a depth-2 pipelined run with a sparse publish
# cadence must exit rc=0 with the staleness counters on the summary
# line (CLI flags -> Config -> train_pipelined -> actor_block/
# learner_block_donated -> publisher), and the depth-0 synchronous-
# handoff arm must stay leaf-for-leaf BITWISE the historical trainer
# on a mixed ragged+faulted+sanitize cell through the real trainer
# (the acceptance pin; the wider equivalence matrix rides the slow
# marker in tests/test_pipeline.py per the tier-1 budget pattern).
pipe_log="$smoke_dir/pipeline.log"
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 3 --in_degree 3 --nrow 3 --ncol 3 \
    --n_episodes 8 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --pipeline_depth 2 --publish_every 2 \
    --summary_dir "$smoke_dir" --quiet | tee "$pipe_log"
grep -q "pipeline: depth 2, publish_every 2" "$pipe_log"
grep -q "staleness mean" "$pipe_log"
timeout -k 10 420 env JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np, jax
from rcmarl_tpu.config import Config, Roles
from rcmarl_tpu.faults import FaultPlan
from rcmarl_tpu.pipeline.trainer import train_pipelined
from rcmarl_tpu.training.trainer import train

cfg = Config(
    n_agents=4,
    agent_roles=(Roles.COOPERATIVE,) * 2 + (Roles.GREEDY, Roles.MALICIOUS),
    in_nodes=((0, 1, 2, 3), (1, 2, 3, 0), (2, 3, 0), (3, 0, 1)),
    nrow=3, ncol=3,
    n_episodes=4, n_ep_fixed=2, max_ep_len=4, n_epochs=2, H=1,
    consensus_sanitize=True,
    fault_plan=FaultPlan(drop_p=0.2, nan_p=0.2, stale_p=0.1),
)
s_ref, df_ref = train(cfg)
s_pipe, df_pipe = train_pipelined(cfg)
for a, b in zip(jax.tree.leaves(s_ref), jax.tree.leaves(s_pipe)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
for col in df_ref.columns:
    np.testing.assert_array_equal(df_ref[col].values, df_pipe[col].values)
assert df_ref.attrs["guard"] == df_pipe.attrs["guard"]
assert df_pipe.attrs["pipeline"]["staleness"] == [0, 0]
print("pipeline depth-0 bitwise pin OK (ragged+faulted+guarded)")
PY
echo "pipeline smoke cell OK"

# Pipelined-gossip-fleet smoke cell (composed topology): a 2-replica
# fleet, each learner fed by its own depth-2 actor tier, trimmed-mean
# mixed every 2 blocks, under agent-level NaN bombs with sanitize and
# the per-replica guard, publishing the winner through the canary-gated
# deploy — the whole composed wire-up end to end (CLI flags -> Config
# -> train_gala -> per-replica BlockQueue/publishers -> gala_mix_block
# -> CanaryGate deploy -> checkpoint with gossip meta), which
# tests/test_gala.py covers only layer by layer. Must exit rc=0 with
# the ONE merged counters line (gala: ... | gossip: ... | canary: ...)
# on the summary. R=2 on the full replica graph has gossip in-degree 2,
# so the replica-level trim rides gossip_H=0 here; the H=1 composed
# Byzantine arm is gated by the RESILIENCE.jsonl gala_byzantine cells.
gala_log="$smoke_dir/gala.log"
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 3 --in_degree 3 --nrow 3 --ncol 3 \
    --n_episodes 8 --n_ep_fixed 2 --max_ep_len 6 --n_epochs 2 --H 1 \
    --replicas 2 --gossip_graph full --gossip_H 0 --gossip_every 2 \
    --pipeline_depth 2 --canary_band 0.5 \
    --fault_nan_p 0.1 --sanitize \
    --summary_dir "$smoke_dir" --quiet | tee "$gala_log"
grep -q "gala: 2 replicas" "$gala_log"
grep -q "canary:" "$gala_log"
grep -q "staleness mean" "$gala_log"
echo "pipelined-gossip-fleet smoke cell OK"

# Env-zoo smoke cell: every NEW environment of the registry trains end
# to end through the real CLI (finite return curves, rc=0 — the
# acceptance wire-up CLI -> Config.env -> registry -> generic rollout
# -> trainer -> checkpoint), each checkpoint round-trips through the
# `evaluate` CLI (an evaluate row per env), and one time-varying-graph
# run under a faulted+sanitize transport plan proves the
# indices-as-data path composes with the fault/sanitize stack outside
# the pytest budget (the per-env invariant suites and the graph
# builder's hypothesis twins stay in tier-1; the expensive train cells
# ride the slow marker per the PR-8/PR-9 pattern).
for zoo_env in pursuit coverage congestion; do
    env_dir="$smoke_dir/env_$zoo_env"
    env_log="$smoke_dir/env_$zoo_env.log"
    timeout -k 10 240 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
        --env "$zoo_env" \
        --n_agents 3 --in_degree 3 --nrow 3 --ncol 3 \
        --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
        --summary_dir "$env_dir" --quiet | tee "$env_log"
    grep -q "done: 4 episodes" "$env_log"
    timeout -k 10 240 env JAX_PLATFORMS=cpu python -m rcmarl_tpu evaluate \
        --checkpoint "$env_dir/checkpoint.npz" --episodes 4 | tee "$env_log"
    grep -q "\"env\": \"$zoo_env\"" "$env_log"
    grep -q "team_return_mean" "$env_log"
    echo "env-zoo $zoo_env train+evaluate OK"
done
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 4 --in_degree 4 --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --graph_schedule random_geometric --graph_degree 3 --graph_every 1 \
    --fault_drop_p 0.2 --fault_nan_p 0.2 --sanitize \
    --summary_dir "$smoke_dir" --quiet
echo "time-varying-graph faulted+sanitize smoke cell OK"
# Adaptive colluding adversary: the scenario preset must train rc=0
# with the trimmed mean (H=1) keeping the cooperative params finite.
timeout -k 10 240 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --scenario adaptive --in_degree 4 --nrow 3 --ncol 3 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 2 --H 1 \
    --adaptive_scale 100 --summary_dir "$smoke_dir" --quiet
echo "adaptive-adversary smoke cell OK"

# Mega-population smoke cell (round 18): a tiny-budget n=256 train
# through the real CLI with consensus riding the SPARSE random-
# geometric schedule as traced data (ops/exchange.py sparse_gather,
# O(n·deg·P) instead of the n² dense gather) and the fit_clip
# stability rail on — the mega-population wire-up end to end (CLI
# flags -> Config -> host-looped train() -> per-block resample ->
# sparse exchange -> checkpoint). The population is what is under
# test, so everything else stays minimal: (4,) hidden, 2 blocks.
# The bitwise sparse-vs-dense pins and n=1024 ladders live in
# tests/test_exchange.py + AUDIT.jsonl; this proves the CLI path.
timeout -k 10 300 env JAX_PLATFORMS=cpu python -m rcmarl_tpu train \
    --n_agents 256 --in_degree 5 --nrow 16 --ncol 16 --hidden 4 \
    --graph_schedule random_geometric --graph_degree 9 --graph_every 1 \
    --fit_clip 1.0 --H 1 \
    --n_episodes 4 --n_ep_fixed 2 --max_ep_len 4 --n_epochs 1 \
    --summary_dir "$smoke_dir" --quiet
echo "mega-population sparse smoke cell OK"

# Sparse-fused smoke cell (round 19): the scheduled-graph fused Pallas
# phase II at mega-population scale — an n=256 degree-9 random-
# geometric schedule, resampled every block, under a drop+NaN transport
# plan with sanitize on the stacked critic+TR path, trained on BOTH
# consensus arms (XLA sparse_gather chain vs pallas_fused_interpret
# with the schedule as a traced scalar-prefetch operand) from the same
# init — the params must come out BITWISE identical. This is the
# acceptance wire-up of the round-19 tentpole (Config -> trainer ->
# scheduled fused kernel -> tail) at the scale the pytest suite cannot
# afford (the interpret-mode kernel alone is ~5 min at n=256; the
# n<=8 twins ride tier-1 in tests/test_sparse_fused.py, the wider
# sanitize matrix rides the slow marker). The fused arm's cost side is
# gated separately by the AUDIT.jsonl sparse_consensus rows.
timeout -k 10 720 env JAX_PLATFORMS=cpu python - <<'PY'
import numpy as np, jax
from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
from rcmarl_tpu.faults import FaultPlan
from rcmarl_tpu.training.trainer import train

N = 256
kw = dict(
    n_agents=N, agent_roles=(Roles.COOPERATIVE,) * N,
    in_nodes=circulant_in_nodes(N, 5),
    nrow=16, ncol=16, hidden=(4,),
    graph_schedule="random_geometric", graph_degree=9, graph_every=1,
    fit_clip=1.0, H=1,
    n_episodes=4, n_ep_fixed=2, max_ep_len=4, n_epochs=1,
    netstack=True, consensus_sanitize=True,
    fault_plan=FaultPlan(drop_p=0.2, nan_p=0.2, seed=7),
)
s_x, _ = train(Config(**kw, consensus_impl="xla"))
s_f, _ = train(Config(**kw, consensus_impl="pallas_fused_interpret"))
for a, b in zip(jax.tree.leaves(s_x.params), jax.tree.leaves(s_f.params)):
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
print("sparse-fused n=256 bitwise pin OK (scheduled deg-9, faulted+sanitize)")
PY
echo "sparse-fused smoke cell OK"

# Chaos smoke cell: a representative slice of the chaos campaign
# through the real CLI, gated against the committed RESILIENCE.jsonl —
# one transport cell (NaN bombs at the high rate, sanitize+guard), the
# double-corruption checkpoint cell (primary AND .prev in one poll
# cycle -> reject+serve-last-good), the poisoned-rollout-window
# pipeline cell (bounded redraws then skip, nothing published), and
# BOTH serving overload arms (the deadline-shedding acceptance
# criterion: shed p99 within 2x the knee-point p99, no-shed past it).
# A cell that previously survived and now fails — or whose degradation
# envelope widened past tolerance — exits nonzero here; the FULL
# campaign rides ci.yml's chaos job (outside the tier-1 wall budget,
# the PR-8/PR-9 shedding pattern).
timeout -k 10 600 env JAX_PLATFORMS=cpu python -m rcmarl_tpu chaos \
    --check --baseline RESILIENCE.jsonl \
    --cells link_nan@0.5 ckpt_bitflip@both pipeline_window serve_overload
echo "chaos smoke cell OK"

# graftlint cell: the AST passes over the installed package (zero
# findings is the contract — rcmarl_tpu.lint) plus the retrace audit
# (tiny guarded+faulted 2-block trains on both netstack arms + a clean
# donated run; any post-warmup compile fails) plus the COST GATE and
# COLLECTIVE CENSUS against the committed AUDIT.jsonl ledger: every
# jitted entry point recompiled and its FLOPs / bytes-accessed / buffer
# bytes compared to the ledger, the seed×agent sharded programs' HLO
# collective counts matched exactly, host transfers forbidden. Since
# the sharding-arm PR the cell also runs --sharding (big-operand
# sharding annotations + reshard chains on the compiled SPMD modules,
# the per-device memory ladder at mesh {1,2,8} vs the ledger's
# device_memory rows, and the nondeterministic-HLO census) and
# --contract (every Config field CLI-reachable, JSON-round-tripping,
# and documented) and --kernels (every Pallas plan's per-grid-step
# VMEM/SMEM residency vs the strictest generation budget, chosen-tile
# packing quanta, the committed *_dma_bytes models re-derived from
# BlockSpec grid arithmetic, and the kernel_budget ledger rows — pure
# shape arithmetic, no backend). The donation + backend-purity audits run inside the
# pytest suite above (tests/test_lint.py); the repeat here proves the
# contracts through the real CLI entry, not just the test harness —
# and carries the sharded compiles the tier-1 pytest budget cannot
# afford (the slow-marker twins). On a cost/census/memory failure the
# CLI writes AUDIT.jsonl.new next to the baseline — ci.yml uploads it
# as an artifact so the ledger diff is one click away.
timeout -k 10 900 env JAX_PLATFORMS=cpu python -m rcmarl_tpu lint \
    --retrace --cost --collectives --sharding --contract --kernels \
    --baseline AUDIT.jsonl
echo "graftlint cell OK"
