#!/usr/bin/env python
"""The committed autoscale-SLO experiment: p99 vs offered load across a
10x swing, autoscaled vs static.

Builds ONE real serving block (a freshly trained policy), measures its
per-launch serve time on the requested arm (100 timed launches of the
real compiled program; the median is the service model — deterministic
replay, so the curve measures QUEUEING, not this host's dispatch
jitter), and replays the SAME seeded 1x -> 10x -> 1x offered-load
swing (``swing_arrivals``) through two fleets:

1. **autoscaled**: :class:`rcmarl_tpu.serve.autoscale.SLOController`
   resizes at window boundaries from the windowed p99/demand/shed
   telemetry — must hold the p99 SLO in EVERY window, shed-free;
2. **static**: the same plan on the pinned scale-1 fleet — must
   saturate (peak p99 far beyond the SLO), proving the swing is a real
   overload and not a soft target.

Both arms shed at the deadline (``shed_after = slo``): the SLO *is* the
deadline, so the static arm's shed fraction is the price of not
scaling. The committed verdict (full per-window p99 curves plus a
per-load-factor summary) lands in
``simulation_results/autoscale_slo.json``; QUALITY.md's "SLO-driven
autoscaling" section renders from it
(:func:`rcmarl_tpu.analysis.quality.autoscale_slo_section`).

    python scripts/autoscale_experiment.py [--seg_requests 2000]
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import time
from datetime import datetime
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--batch", type=int, default=64,
                   help="max member batch (requests per launch)")
    p.add_argument("--seg_requests", type=int, default=2000,
                   help="requests per swing segment (10 segments)")
    p.add_argument("--slo_ms", type=float, default=0.0,
                   help="p99 SLO in ms; 0 = 4x the measured per-launch "
                   "serve time (the cmd_serve --autoscale default)")
    p.add_argument("--max_scale", type=int, default=16)
    p.add_argument("--n_windows", type=int, default=40,
                   help="control windows across the whole plan")
    p.add_argument("--serve_impl", type=str, default="auto",
                   choices=["auto", "xla", "pallas", "pallas_interpret"])
    p.add_argument("--mode", type=str, default="sample",
                   choices=["sample", "greedy"])
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--train_episodes", type=int, default=8,
                   help="episodes behind the served policy (the service "
                   "time, not the policy quality, is what is measured)")
    p.add_argument(
        "--out", type=str,
        default=str(Path(__file__).resolve().parent.parent
                    / "simulation_results/autoscale_slo.json"),
    )
    args = p.parse_args()

    import jax
    import numpy as np

    from rcmarl_tpu.config import Config, Roles, circulant_in_nodes
    from rcmarl_tpu.ops.pallas_serve import resolve_serve_impl
    from rcmarl_tpu.serve.autoscale import (
        SLOController,
        autoscale_replay,
        summary_line,
        swing_arrivals,
    )
    from rcmarl_tpu.serve.engine import stack_actor_rows
    from rcmarl_tpu.serve.load import serve_service_fn
    from rcmarl_tpu.training.trainer import train

    # a small REAL policy: the service model below times its actual
    # compiled serving program, so the block must be a trained pytree,
    # not a stand-in
    cfg = Config(
        n_agents=3,
        agent_roles=(Roles.COOPERATIVE,) * 3,
        in_nodes=circulant_in_nodes(3, 3),
        nrow=3,
        ncol=3,
        n_ep_fixed=2,
        max_ep_len=8,
        H=1,
        seed=args.seed,
    )
    t0 = time.perf_counter()
    state, _ = train(cfg, n_episodes=args.train_episodes)
    block = stack_actor_rows(state.params, cfg)
    impl = resolve_serve_impl(args.serve_impl)
    service = serve_service_fn(
        cfg, block, args.batch, mode=args.mode, seed=args.seed,
        serve_impl=impl,
    )
    # calibrate the service model from REAL launches, then replay it
    # deterministically: this host's dispatch jitter (occasional
    # launches 20x the median) sits ABOVE any honest p99 target, so
    # billing live wall-clock launches makes every window a coin flip
    # on OS noise at ANY scale — the committed curve must isolate
    # QUEUEING (what scaling fixes) from dispatch jitter (what it
    # cannot). The measured median of the real compiled serving
    # program is the service model; live-launch billing rides
    # `serve --autoscale` (same controller, same replay).
    samples = np.array([service(args.batch) for _ in range(100)])
    per_launch = float(np.median(samples))
    svc_p99 = float(np.percentile(samples, 99.0))
    service = lambda fill: per_launch  # noqa: E731
    slo = args.slo_ms / 1e3 if args.slo_ms > 0 else 4.0 * per_launch
    base_rate = 0.5 * args.batch / per_launch  # 10x peak = 5x capacity
    arrivals = swing_arrivals(args.seed, base_rate, args.seg_requests)
    window = (arrivals[-1] - arrivals[0]) / args.n_windows
    replay_kw = dict(
        window=window,
        max_batch=args.batch,
        max_wait=2.0 * per_launch,
        shed_after=slo,  # the deadline IS the SLO, on both arms
        slo_p99=slo,
    )
    auto = autoscale_replay(
        service, arrivals,
        SLOController(slo_p99=slo, max_scale=args.max_scale),
        **replay_kw,
    )
    static = autoscale_replay(service, arrivals, None, **replay_kw)
    wall = round(time.perf_counter() - t0, 2)
    print(summary_line(auto))
    print(summary_line(static))

    # map each control window to its swing load factor (the segment
    # whose arrival span contains the window midpoint) and fold the two
    # arms into one per-factor curve — QUALITY.md renders this table
    factors = (1, 2, 4, 8, 10, 10, 8, 4, 2, 1)
    seg_lo = [arrivals[s * args.seg_requests] for s in range(len(factors))]
    seg_lo.append(arrivals[-1])

    def _factor(t_mid: float) -> int:
        for s in range(len(factors)):
            if t_mid < seg_lo[s + 1]:
                return s
        return len(factors) - 1

    def _p99_ms(rows):
        worst = max(r["p99"] for r in rows)
        return None if not math.isfinite(worst) else round(worst * 1e3, 3)

    curve = []
    for s, factor in enumerate(factors):
        picks = {
            label: [
                r for r in arm["windows"]
                if _factor(r["t0"] + window / 2) == s
            ]
            for label, arm in (("auto", auto), ("static", static))
        }
        if not picks["auto"] or not picks["static"]:
            continue
        scales = sorted({r["scale"] for r in picks["auto"]})
        curve.append({
            "segment": s,
            "factor": factor,
            "offered_rps": round(
                float(np.mean([r["offered_load"] for r in picks["auto"]])),
                1,
            ),
            "auto_p99_ms": _p99_ms(picks["auto"]),
            "auto_scale": (
                f"{scales[0]}-{scales[-1]}"
                if len(scales) > 1 else str(scales[0])
            ),
            "auto_shed": int(sum(r["shed"] for r in picks["auto"])),
            "static_p99_ms": _p99_ms(picks["static"]),
            "static_shed": int(sum(r["shed"] for r in picks["static"])),
        })

    def _arm(label, res, scale_fields):
        worst = max(r["p99"] for r in res["windows"])
        return {
            "label": label,
            "slo_held": bool(res["slo_held"]),
            "requests": int(res["requests"]),
            "served": int(res["served"]),
            "shed": int(res["shed"]),
            "shed_fraction": round(res["shed"] / res["requests"], 4),
            "peak_p99_ms": (
                None if not math.isfinite(worst)
                else round(worst * 1e3, 3)
            ),
            "summary": summary_line(res),
            "windows": [
                {
                    "window": r["window"],
                    "scale": r["scale"],
                    "offered_rps": round(r["offered_load"], 1),
                    "p99_ms": (
                        None if not math.isfinite(r["p99"])
                        else round(r["p99"] * 1e3, 3)
                    ),
                    "shed": r["shed"],
                    "slo_ok": r["slo_ok"],
                }
                for r in res["windows"]
            ],
            **scale_fields,
        }

    result = {
        "generated_by": "python scripts/autoscale_experiment.py",
        "config": {
            "scenario": "coop circ3 (3 agents, circulant in-degree 3)",
            "batch": args.batch,
            "mode": args.mode,
            "serve_impl": args.serve_impl,
            "serve_impl_resolved": impl,
            "service_model": "measured-median-replay",
            "per_launch_ms": round(per_launch * 1e3, 3),
            "service_p99_ms": round(svc_p99 * 1e3, 3),
            "slo_ms": round(slo * 1e3, 3),
            "base_rate_rps": round(base_rate, 1),
            "swing_factors": list(factors),
            "seg_requests": args.seg_requests,
            "n_windows": args.n_windows,
            "window_ms": round(window * 1e3, 3),
            "max_wait_ms": round(2.0 * per_launch * 1e3, 3),
            "max_scale": args.max_scale,
            "seed": args.seed,
            "train_episodes": args.train_episodes,
        },
        "arms": [
            _arm("autoscaled", auto, {
                "max_scale_used": int(auto["max_scale_used"]),
                "final_scale": int(auto["final_scale"]),
                "resizes": auto["resizes"],
            }),
            _arm("static", static, {"scale": 1}),
        ],
        "curve": curve,
        "as_expected": bool(auto["slo_held"]) and not static["slo_held"],
        "wall_s": wall,
        "platform": jax.devices()[0].platform,
        "timestamp": datetime.now().isoformat(timespec="seconds"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(result, indent=1) + "\n")
    print(f"wrote {out}")
    # rc IS the acceptance gate: the autoscaled fleet must hold the SLO
    # on the exact swing that saturates the static fleet
    return 0 if result["as_expected"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
