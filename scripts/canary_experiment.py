#!/usr/bin/env python
"""The committed canary-gate experiment: the deployment loop closed.

Trains ONE real run in three segments — an EARLY snapshot (the "stale
publish" adversary), the INCUMBENT the serving engine deploys, and a
LATER snapshot (the healthy publish) — then drives the full
file-watcher deployment loop (:class:`rcmarl_tpu.serve.canary.
CanaryWatcher` over a real checkpoint path) through four publishes:

1. **healthy**: the later-training checkpoint — must PROMOTE (its
   frozen-policy return sits inside the incumbent's band);
2. **stale**: the early-training checkpoint — checksum-valid, fully
   finite, just a WORSE policy: must be REJECTED by the BAND (the case
   neither the checksum chain nor ``params_finite`` can catch);
3. **poisoned**: NaN-injected params — must be rejected by the guard
   in front of the gate, paying no eval;
4. **re-publish**: the healthy checkpoint again — the gate must not
   wedge after rejections.

After every rejection the engine's serving block is verified BITWISE
against the last promoted policy. The committed verdict lands in
``simulation_results/canary_gate.json``; QUALITY.md's "Canary-gated
deployment" section renders from it
(:func:`rcmarl_tpu.analysis.quality.canary_section`).

    python scripts/canary_experiment.py [--episodes 900] [--seed 300]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from datetime import datetime
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--episodes", type=int, default=900,
                   help="episodes to the INCUMBENT checkpoint")
    p.add_argument("--stale_frac", type=float, default=1 / 6,
                   help="the stale snapshot's training fraction")
    p.add_argument("--healthy_extra", type=int, default=300,
                   help="extra episodes past the incumbent for the "
                   "healthy candidate")
    p.add_argument("--seed", type=int, default=300)
    p.add_argument("--band", type=float, default=0.05,
                   help="canary band (PARITY.md's 5% tolerance)")
    p.add_argument("--blocks", type=int, default=2,
                   help="eval blocks per gate measurement")
    p.add_argument(
        "--out", type=str,
        default=str(Path(__file__).resolve().parent.parent
                    / "simulation_results/canary_gate.json"),
    )
    args = p.parse_args()

    import tempfile

    import jax
    import numpy as np

    from rcmarl_tpu.config import Config
    from rcmarl_tpu.serve.canary import CanaryGate, CanaryWatcher
    from rcmarl_tpu.serve.engine import ServeEngine, stack_actor_rows
    from rcmarl_tpu.training.trainer import train
    from rcmarl_tpu.utils.checkpoint import save_checkpoint

    cfg = Config(seed=args.seed)  # the reference 5-agent cooperative ring
    blk = cfg.n_ep_fixed
    stale_eps = max(blk, int(args.episodes * args.stale_frac) // blk * blk)
    inc_eps = max(stale_eps + blk, args.episodes // blk * blk)
    extra_eps = max(blk, args.healthy_extra // blk * blk)

    t0 = time.perf_counter()
    state, _ = train(cfg, n_episodes=stale_eps)
    stale_state = jax.tree.map(lambda x: x, state)  # snapshot the pytree
    state, _ = train(cfg, n_episodes=inc_eps - stale_eps, state=state)
    incumbent_state = jax.tree.map(lambda x: x, state)
    state, _ = train(cfg, n_episodes=extra_eps, state=state)
    healthy_state = state
    train_wall = round(time.perf_counter() - t0, 2)
    print(f"trained {stale_eps}/{inc_eps}/{inc_eps + extra_eps} episode "
          f"snapshots in {train_wall}s")

    def poisoned(st):
        import jax.numpy as jnp

        return st._replace(
            params=st.params._replace(
                actor=jax.tree.map(
                    lambda l: l.at[0].set(jnp.nan), st.params.actor
                )
            )
        )

    with tempfile.TemporaryDirectory() as tmp:
        path = Path(tmp) / "deployed.npz"
        save_checkpoint(path, incumbent_state, cfg)
        engine = ServeEngine(path)
        gate = CanaryGate(
            cfg, incumbent_state.desired, incumbent_state.initial,
            band=args.band, blocks=args.blocks,
        )
        watcher = CanaryWatcher(engine, gate)
        incumbent_return = gate.incumbent_return
        print(f"incumbent ({inc_eps} eps) frozen return: "
              f"{incumbent_return:.4f}")

        last_promoted = incumbent_state
        arms = []

        def publish(label, st, expect_promoted, kind):
            nonlocal last_promoted
            save_checkpoint(path, st, cfg)
            if kind == "poisoned":
                # poison the rotated fallback too: the chain must not
                # quietly serve the previous file and mask the reject
                save_checkpoint(path, st, cfg)
            floor = gate.floor()
            evals_before = gate.counters["evals"]
            applied = watcher.poll()
            gated = gate.counters["evals"] > evals_before
            if applied:
                last_promoted = st
            # after any rejection the engine must still serve the last
            # promoted policy BITWISE
            for a, b in zip(
                jax.tree.leaves(engine.block),
                jax.tree.leaves(stack_actor_rows(last_promoted.params, cfg)),
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            rec = {
                "label": label,
                "kind": kind,
                "promoted": bool(applied),
                "expected_promoted": expect_promoted,
                "as_expected": bool(applied) == expect_promoted,
                "floor": round(floor, 4),
                "candidate_return": (
                    round(gate.last["candidate_return"], 4)
                    if gated and gate.last["candidate_return"] is not None
                    else None
                ),
                "reason": (
                    gate.last["reason"]
                    if gated
                    else "rejected by the finiteness guard (no eval paid)"
                ),
            }
            arms.append(rec)
            verdict = "promoted" if applied else "REJECTED"
            print(f"{label}: {verdict} (candidate "
                  f"{rec['candidate_return']}, floor {rec['floor']}) — "
                  f"{'as expected' if rec['as_expected'] else 'UNEXPECTED'}")

        publish(
            f"healthy (+{extra_eps} eps)", healthy_state, True, "healthy"
        )
        publish(
            f"stale ({stale_eps} eps snapshot)", stale_state, False, "stale"
        )
        publish("poisoned (NaN actor)", poisoned(healthy_state), False,
                "poisoned")
        publish(
            f"healthy re-publish (+{extra_eps} eps)", healthy_state, True,
            "healthy",
        )

        result = {
            "config": {
                "scenario": "coop ref5_ring (Config defaults)",
                "episodes_stale": stale_eps,
                "episodes_incumbent": inc_eps,
                "episodes_healthy": inc_eps + extra_eps,
                "seed": args.seed,
                "band": args.band,
                "eval_blocks": args.blocks,
            },
            "incumbent_return": round(incumbent_return, 4),
            "arms": arms,
            "gate_counters": dict(gate.counters),
            "engine_counters": engine.summary(),
            "gate_summary": gate.summary_line(),
            "engine_summary": engine.summary_line(),
            "all_as_expected": all(a["as_expected"] for a in arms),
            "train_wall_s": train_wall,
            "platform": jax.devices()[0].platform,
            "timestamp": datetime.now().isoformat(timespec="seconds"),
        }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(result, indent=1) + "\n")
    print(json.dumps(result, indent=1))
    print(f"wrote {out}")
    print(gate.summary_line())
    # rc IS the acceptance gate: this experiment exists to prove the
    # canary catches the degraded publishes and passes the healthy ones
    return 0 if result["all_as_expected"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
