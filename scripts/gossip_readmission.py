#!/usr/bin/env python
"""Gossip readmission experiment — the flapping-sender QUALITY evidence.

A probabilistic agent-level NaN plan WITHOUT sanitize flaps individual
gossip replicas unhealthy segment by segment (each replica draws its
own fault pattern, so some segments poison it and some don't — the
flapping sender the one-round PR-7 exclusion re-admits the moment its
luck turns). Three arms over the SAME fault draws:

- ``readmit0``  — the legacy one-round exclusion (PR-7 behavior): a
  rolled-back replica sits out exactly one mix.
- ``readmitK``  — the sticky quarantine (``train_gossip
  (readmit_after=K)``): an excluded replica must prove K consecutive
  healthy probe rounds before re-entering the mix; the experiment
  demonstrates excluded -> readmitted -> healthy-envelope-holds.
- ``clean``     — the no-fault control pinning the quality band.

Verdict (rc=0): in the readmission arm at least one replica is
quarantined AND later readmitted, every replica ends finite, and the
flapping arms' final returns hold the clean arm's band.

Artifacts:
  --json_out   committed: simulation_results/gossip_readmission.json —
               QUALITY.md's "Gossip readmission" section renders from
               this file (analysis/quality.py:gossip_readmission_section)

Usage (the committed evidence was generated with the defaults):
  JAX_PLATFORMS=cpu python scripts/gossip_readmission.py \
      --json_out simulation_results/gossip_readmission.json
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

#: Quality band vs the clean control (the PARITY.md tolerance).
BAND_TOL = 0.05


def build_cfg(args, faulted: bool):
    from rcmarl_tpu.config import Config
    from rcmarl_tpu.faults import FaultPlan

    return Config(
        n_episodes=args.n_episodes,
        n_ep_fixed=args.n_ep_fixed,
        replicas=args.replicas,
        gossip_graph="full",
        gossip_H=args.gossip_H,
        gossip_every=args.gossip_every,
        fault_plan=FaultPlan(nan_p=args.nan_p) if faulted else None,
        slow_lr=0.002,
    )


def run_arm(args, label: str, faulted: bool, readmit_after: int) -> dict:
    import numpy as np

    from rcmarl_tpu.parallel.gossip import train_gossip

    cfg = build_cfg(args, faulted)
    t0 = time.perf_counter()
    states, df = train_gossip(cfg, readmit_after=readmit_after)
    dt = time.perf_counter() - t0
    g = df.attrs["gossip"]
    ret = np.asarray(df["True_team_returns"], float)
    w = min(100, len(ret) // 4)
    final = float(np.nanmean(ret[-w:]))
    return {
        "label": label,
        "readmit_after": readmit_after,
        "faulted": faulted,
        "rounds": g["rounds"],
        "rollbacks": g["rollbacks"],
        "excluded_replica_rounds": g["excluded"],
        "readmitted": g["readmitted"],
        "quarantined_final": g["quarantined"],
        "replica_healthy": g["replica_healthy"],
        "nonfinite_payload_entries": g["nonfinite"],
        "final_return": None if np.isnan(final) else round(final, 4),
        "window_episodes": w,
        "wall_seconds": round(dt, 1),
    }


def main() -> int:
    import jax

    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--replicas", type=int, default=4)
    p.add_argument("--gossip_H", type=int, default=1)
    p.add_argument("--gossip_every", type=int, default=1)
    p.add_argument("--n_episodes", type=int, default=600)
    p.add_argument("--n_ep_fixed", type=int, default=20)
    p.add_argument(
        "--nan_p", type=float, default=0.002,
        help="agent-level per-link NaN rate (no sanitize): tuned so "
        "replicas FLAP — poisoned some segments, clean others",
    )
    p.add_argument("--readmit_after", type=int, default=2)
    p.add_argument("--json_out", type=str, default=None)
    args = p.parse_args()

    arms = [
        run_arm(args, "clean", False, 0),
        run_arm(args, "readmit0 (legacy one-round)", True, 0),
        run_arm(
            args, f"readmit{args.readmit_after} (sticky quarantine)",
            True, args.readmit_after,
        ),
    ]
    clean = arms[0]["final_return"]
    for a in arms:
        a["within_band"] = (
            a["final_return"] is not None
            and clean is not None
            and abs(a["final_return"] - clean) <= BAND_TOL * abs(clean)
        )
        print(json.dumps(a))

    sticky = arms[2]
    flapped = sticky["rollbacks"] > 0
    readmitted = sticky["readmitted"] > 0
    all_finite = all(a["replica_healthy"] == [True] * args.replicas
                     for a in arms)
    band_holds = all(a["within_band"] for a in arms)
    out = {
        "generated_by": "python scripts/gossip_readmission.py",
        "config": {
            "replicas": args.replicas,
            "gossip_H": args.gossip_H,
            "gossip_every": args.gossip_every,
            "gossip_graph": "full",
            "n_episodes": args.n_episodes,
            "n_ep_fixed": args.n_ep_fixed,
            "nan_p": args.nan_p,
            "readmit_after": args.readmit_after,
            "tol": BAND_TOL,
        },
        "arms": arms,
        "verdict": {
            "flapped": flapped,
            "readmitted": readmitted,
            "all_replicas_finite": all_finite,
            "band_holds": band_holds,
        },
        "platform": jax.devices()[0].platform,
    }
    if args.json_out:
        path = Path(args.json_out)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(out, indent=1) + "\n")
        print(f"wrote {path}", file=sys.stderr)
    print(
        f"verdict: flapped={flapped} readmitted={readmitted} "
        f"finite={all_finite} band={band_holds}",
        file=sys.stderr,
    )
    return 0 if (flapped and readmitted and all_finite and band_holds) else 1


if __name__ == "__main__":
    sys.exit(main())
