#!/usr/bin/env python
"""Adaptive colluding adversary vs the trimmed mean — the committed
QUALITY.md experiment (simulation_results/adaptive_adversary.json).

The ADAPTIVE role (``Roles.ADAPTIVE``, ``Config.adaptive_scale``,
``rcmarl_tpu.faults.adaptive_payload_tree``) is the omniscient
colluding adversary the three scripted labels never were: every epoch
it reads the CURRENT cooperative messages and transmits
``mean_coop + scale * (max_coop - min_coop)`` on every parameter
coordinate — the coordinated-placement attack family against a
clip-and-average consensus. This experiment runs the reference 5-agent
ring with node 4 adaptive and asks the acceptance question directly:

  does the trimmed mean at sufficient H keep cooperative returns in
  the clean band where the plain (untrimmed, H=0) mean degrades?

Arms (all seed 300, slow_lr 0.002, the published-run hyperparameters):

  clean_h1   : 5 cooperative, H=1      — the clean band source
  clean_h0   : 5 cooperative, H=0      — proves H=0 itself learns fine
  trimmed_h1 : 4 coop + adaptive, H=1  — the defense arm
  plain_h0   : 4 coop + adaptive, H=0  — the undefended arm
  inside_h1  : 4 coop + adaptive, H=1, scale=0.3 — the just-inside-
               the-trim-bounds placement (payload BELOW the healthy
               max, so clipping never touches it: the pure residual-
               influence stress test). Note any scale >= ~0.5 lands at
               or past the healthy max and clips to the SAME bound —
               the defense saturates, which is the point of trimming.

Usage:  python scripts/adaptive_adversary.py [--episodes 2000]
"""

import argparse
import json
import sys
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--episodes", type=int, default=2000)
    p.add_argument("--seed", type=int, default=300)
    p.add_argument("--scale", type=float, default=25.0)
    p.add_argument("--window", type=int, default=500)
    p.add_argument("--tol", type=float, default=0.05)
    p.add_argument(
        "--out",
        type=str,
        default="simulation_results/adaptive_adversary.json",
    )
    args = p.parse_args()

    import jax

    from rcmarl_tpu.config import Config, Roles
    from rcmarl_tpu.training.trainer import train

    coop = (Roles.COOPERATIVE,) * 5
    adv = (Roles.COOPERATIVE,) * 4 + (Roles.ADAPTIVE,)
    arms_spec = [
        ("clean_h1", coop, 1, args.scale),
        ("clean_h0", coop, 0, args.scale),
        ("trimmed_h1", adv, 1, args.scale),
        ("plain_h0", adv, 0, args.scale),
        ("inside_h1", adv, 1, 0.3),
    ]

    arms = []
    for label, cast, H, scale in arms_spec:
        cfg = Config(
            agent_roles=cast,
            H=H,
            adaptive_scale=scale,
            n_episodes=args.episodes,
            slow_lr=0.002,
            seed=args.seed,
        )
        _, df = train(cfg)
        r = df["True_team_returns"].values
        finite = np.isfinite(r)
        collapsed = None if finite.all() else int(np.argmin(finite))
        tail = r[finite][-args.window :]
        arms.append(
            {
                "label": label,
                "H": H,
                "adaptive_scale": scale,
                "adversaries": int(sum(c == Roles.ADAPTIVE for c in cast)),
                "final_return": round(float(np.mean(tail)), 4),
                "collapsed_at_episode": collapsed,
            }
        )
        print(arms[-1], flush=True)

    clean = next(a for a in arms if a["label"] == "clean_h1")["final_return"]
    for a in arms:
        # one-sided: DEGRADATION is what the band polices (an arm that
        # converges better than the control is not a defense failure)
        a["within_clean_band"] = bool(
            a["collapsed_at_episode"] is None
            and a["final_return"] >= clean - args.tol * abs(clean)
        )

    out = {
        "generated_by": "python scripts/adaptive_adversary.py",
        "config": {
            "scenario": "ref 5-agent ring (in_degree 4), node 4 Adaptive",
            "episodes": args.episodes,
            "seed": args.seed,
            "adaptive_scale": args.scale,
            "window": args.window,
            "tol": args.tol,
        },
        "platform": jax.devices()[0].platform,
        "clean_final": clean,
        "arms": arms,
    }
    path = Path(args.out)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(out, indent=1) + "\n")
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
