#!/usr/bin/env python
"""The bf16 accuracy/parity cell: measured returns-curve agreement of
``compute_dtype='bfloat16'`` against the bitwise-f32 reference arm.

The bf16 compute arm narrows ONLY the matmul inputs (f32 accumulation,
params/optimizer state stay f32 — ``models/mlp.py:dot``), so the gate
it must pass is behavioral, not bitwise: trained on the same seed and
schedule, the bf16 returns curve must reach the f32 arm's own converged
quality band. This script runs the two arms, scores them with the SAME
smoothing/threshold machinery QUALITY.md uses
(:mod:`rcmarl_tpu.analysis.quality`), and commits the verdict to
``simulation_results/bf16_parity.json`` — which
``python -m rcmarl_tpu quality`` then renders into QUALITY.md's
"Mixed precision (bfloat16)" section.

    python scripts/bf16_parity.py [--episodes 2000] [--seed 300]
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from datetime import datetime
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--episodes", type=int, default=2000)
    p.add_argument("--seed", type=int, default=300)
    p.add_argument("--rolling", type=int, default=200)
    p.add_argument("--window", type=int, default=400,
                   help="final-window size for the converged-return mean")
    p.add_argument("--tol", type=float, default=0.05,
                   help="quality-band tolerance (PARITY.md's 5%% default)")
    p.add_argument(
        "--out", type=str,
        default=str(Path(__file__).resolve().parent.parent
                    / "simulation_results/bf16_parity.json"),
    )
    args = p.parse_args()

    import jax
    import numpy as np
    import pandas as pd

    from rcmarl_tpu.analysis.quality import episodes_to_threshold
    from rcmarl_tpu.config import Config
    from rcmarl_tpu.training.trainer import train

    base = Config(seed=args.seed)  # the reference 5-agent cooperative ring
    arms = {}
    for dtype in ("float32", "bfloat16"):
        cfg = base.replace(compute_dtype=dtype)
        t0 = time.perf_counter()
        _, df = train(cfg, n_episodes=args.episodes)
        arms[dtype] = {
            "df": df,
            "wall_s": round(time.perf_counter() - t0, 2),
        }
        print(f"{dtype}: {args.episodes} eps in {arms[dtype]['wall_s']}s")

    def curve(df) -> pd.Series:
        return (
            df["True_team_returns"]
            .rolling(args.rolling, min_periods=args.rolling)
            .mean()
        )

    def final(df) -> float:
        return float(df["True_team_returns"].iloc[-args.window:].mean())

    f32, b16 = arms["float32"], arms["bfloat16"]
    final32, final16 = final(f32["df"]), final(b16["df"])
    # the quality bar is the f32 arm's OWN converged return, relaxed by
    # tol of its magnitude — exactly the QUALITY.md threshold recipe,
    # with the f32 arm standing in for the reference
    threshold = final32 - args.tol * abs(final32)
    ep32 = episodes_to_threshold(curve(f32["df"]), threshold)
    ep16 = episodes_to_threshold(curve(b16["df"]), threshold)
    tail32 = curve(f32["df"]).iloc[-args.window:]
    tail16 = curve(b16["df"]).iloc[-args.window:]
    tail_dev = float(np.nanmax(np.abs(tail32.values - tail16.values)))

    result = {
        "config": {
            "scenario": "coop ref5_ring (Config defaults)",
            "n_agents": base.n_agents,
            "hidden": list(base.hidden),
            "episodes": args.episodes,
            "seed": args.seed,
            "rolling": args.rolling,
            "window": args.window,
            "tol": args.tol,
        },
        "f32_final": round(final32, 4),
        "bf16_final": round(final16, 4),
        "threshold": round(threshold, 4),
        "ep_to_threshold_f32": None if np.isnan(ep32) else int(ep32),
        "ep_to_threshold_bf16": None if np.isnan(ep16) else int(ep16),
        "tail_max_abs_dev": round(tail_dev, 4),
        "bf16_within_band": bool(final16 >= threshold),
        "wall_s": {k: v["wall_s"] for k, v in arms.items()},
        "platform": jax.devices()[0].platform,
        "timestamp": datetime.now().isoformat(timespec="seconds"),
    }
    out = Path(args.out)
    out.parent.mkdir(parents=True, exist_ok=True)
    out.write_text(json.dumps(result, indent=1) + "\n")
    print(json.dumps(result, indent=1))
    print(f"wrote {out}")
    # the parity GATE: the bf16 arm must land inside the f32 arm's own
    # quality band — a nonzero rc makes this scriptable in CI/sessions
    return 0 if result["bf16_within_band"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
