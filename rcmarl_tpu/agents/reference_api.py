"""Drop-in twin of the reference's cooperative agent object.

The reference's primary plugin boundary is its agent classes — user
code drives ``RPBCAC_agent`` (``agents/resilient_CAC_agents.py:5-223``)
method-by-method: local fits that RETURN transmitted weights, the
hidden/projection consensus pair fed with neighbors' Keras weight
lists, team head updates, the weighted-CE actor step, and ε-mixed
action sampling. This module exposes that exact protocol over this
framework's pure functions (:mod:`rcmarl_tpu.agents.updates`), so
custom training loops written against the reference class migrate
without rewrites.

Weight format at the boundary is the reference's: a flat Keras-style
list ``[W1, b1, W2, b2, ..., Wk, bk]`` per network (what ``np.load`` of
its ``pretrained_weights.npy`` holds), converted internally to this
framework's ``((W, b), ...)`` pytrees by the same helpers the
checkpoint interop uses. ``get_action`` draws from the GLOBAL NumPy
RNG in the reference's exact order (random candidate, policy sample,
ε-mix — ``resilient_CAC_agents.py:214-217``), so seeded scripts
reproduce its action streams modulo actor weights.

Everything runs eagerly (op-by-op) — this shell exists for API
fidelity and interactive use; the fused, vmapped trainer
(:mod:`rcmarl_tpu.training`) is the performance path.
"""

from __future__ import annotations

from types import SimpleNamespace
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.agents.updates import (
    adv_actor_update,
    adv_critic_fit,
    adv_tr_fit,
    coop_actor_update,
    coop_local_critic_fit,
    coop_local_tr_fit,
    team_head_update,
)
from rcmarl_tpu.models.mlp import (
    MLPParams,
    actor_probs,
    einsum,
    trunk_forward,
)
from rcmarl_tpu.ops.aggregation import (
    resilient_aggregate,
    resilient_aggregate_tree,
)
from rcmarl_tpu.ops.optim import adam_init

__all__ = [
    "ReferenceRPBCACAgent",
    "ReferenceFaultyAgent",
    "ReferenceGreedyAgent",
    "ReferenceMaliciousAgent",
]


def _layers(flat: Sequence[np.ndarray]) -> MLPParams:
    """Keras flat [W1,b1,...] -> ((W,b), ...) pytree (float32)."""
    return tuple(
        (jnp.asarray(flat[i], jnp.float32), jnp.asarray(flat[i + 1], jnp.float32))
        for i in range(0, len(flat), 2)
    )


def _flat(params: MLPParams) -> List[np.ndarray]:
    """((W,b), ...) pytree -> Keras flat [W1,b1,...] (numpy)."""
    out: List[np.ndarray] = []
    for W, b in params:
        out.append(np.asarray(W))
        out.append(np.asarray(b))
    return out


def _stack_neighbors(weights_innodes: Sequence[Sequence[np.ndarray]]) -> MLPParams:
    """List of neighbors' flat weight lists (own first) -> one pytree with
    leaves (n_in, ...) — the stacked-message layout the aggregation
    kernels consume."""
    layered = [_layers(w) for w in weights_innodes]
    return tuple(
        (
            jnp.stack([l[i][0] for l in layered]),
            jnp.stack([l[i][1] for l in layered]),
        )
        for i in range(len(layered[0]))
    )


class ReferenceRPBCACAgent:
    """Reference-protocol cooperative RPBCAC agent over pure-JAX internals.

    Constructor mirrors ``RPBCAC_agent.__init__(actor, critic,
    team_reward, slow_lr, fast_lr, gamma, H)``
    (``resilient_CAC_agents.py:28``), taking each network as a Keras-style
    flat weight list instead of a compiled Keras model.
    """

    def __init__(
        self,
        actor: Sequence[np.ndarray],
        critic: Sequence[np.ndarray],
        team_reward: Sequence[np.ndarray],
        slow_lr: float,
        fast_lr: float,
        gamma: float = 0.95,
        H: int = 0,
    ):
        self.actor = _layers(actor)
        self.critic = _layers(critic)
        self.TR = _layers(team_reward)
        self.n_actions = int(self.actor[-1][1].shape[0])
        self.gamma = gamma
        self.H = H
        # the attribute subset the shared update primitives read
        self._cfg = SimpleNamespace(
            gamma=gamma,
            fast_lr=fast_lr,
            slow_lr=slow_lr,
            coop_fit_steps=5,  # reference resilient_CAC_agents.py:118
            leaky_alpha=0.1,
            H=H,
            consensus_impl="xla",
            dot_dtype=None,
        )
        self._actor_opt = adam_init(self.actor)

    # -- helpers -----------------------------------------------------------

    @staticmethod
    def _full_mask(x) -> jnp.ndarray:
        return jnp.ones((np.asarray(x).shape[0],), jnp.float32)

    def _resilient_aggregation(self, values_innodes):
        """The trimmed clip-and-average kernel, own value at index 0
        (``resilient_CAC_agents.py:42-58``)."""
        return np.asarray(
            resilient_aggregate(jnp.asarray(values_innodes), self.H)
        )

    # -- phase I: local fits -> transmitted weights ------------------------

    def critic_update_local(self, s, ns, r_local):
        """5-step full-batch fit toward the pre-fit TD target; own net
        RESTORED — returns (weights_to_transmit, first_step_loss), like
        the reference's ``history['loss'][0]``
        (``resilient_CAC_agents.py:103-122``)."""
        msg, loss = coop_local_critic_fit(
            self.critic,
            jnp.asarray(s),
            jnp.asarray(ns),
            jnp.asarray(r_local),
            self._full_mask(s),
            self._cfg,
        )
        return _flat(msg), float(loss)

    def TR_update_local(self, sa, r_local):
        """Team-reward twin of :meth:`critic_update_local`
        (``resilient_CAC_agents.py:124-140``)."""
        msg, loss = coop_local_tr_fit(
            self.TR,
            jnp.asarray(sa),
            jnp.asarray(r_local),
            self._full_mask(sa),
            self._cfg,
        )
        return _flat(msg), float(loss)

    # -- phase II: resilient consensus ------------------------------------

    def resilient_consensus_critic_hidden(self, critic_weights_innodes):
        """Clip-mean each TRUNK array over neighbors and write it to the
        own net; head untouched (``resilient_CAC_agents.py:142-153``)."""
        self.critic = self._hidden(self.critic, critic_weights_innodes)

    def resilient_consensus_TR_hidden(self, TR_weights_innodes):
        """(``resilient_CAC_agents.py:155-166``)"""
        self.TR = self._hidden(self.TR, TR_weights_innodes)

    def _hidden(self, own: MLPParams, weights_innodes) -> MLPParams:
        stacked = _stack_neighbors(weights_innodes)
        trunk_agg = resilient_aggregate_tree(stacked[:-1], self.H)
        return tuple(trunk_agg) + (own[-1],)

    def resilient_consensus_critic(self, s, critic_weights_innodes):
        """Projection: every neighbor's HEAD evaluated on the own
        (post-hidden-consensus) trunk features, clip-meaned per sample
        (``resilient_CAC_agents.py:168-186``). Returns (B, 1) targets."""
        return self._projection(self.critic, jnp.asarray(s), critic_weights_innodes)

    def resilient_consensus_TR(self, sa, TR_weights_innodes):
        """(``resilient_CAC_agents.py:188-206``)"""
        return self._projection(self.TR, jnp.asarray(sa), TR_weights_innodes)

    def _projection(self, own: MLPParams, x, weights_innodes) -> np.ndarray:
        stacked = _stack_neighbors(weights_innodes)
        phi = trunk_forward(own, x, self._cfg.leaky_alpha)
        W_nbr, b_nbr = stacked[-1]
        vals = einsum("bh,nho->nbo", phi, W_nbr) + b_nbr[:, None, :]
        return np.asarray(resilient_aggregate(vals, self.H))

    def critic_update_team(self, s, critic_agg):
        """Normalized projected head step toward the aggregated targets
        (``resilient_CAC_agents.py:60-71``)."""
        self.critic = self._team(self.critic, jnp.asarray(s), critic_agg)

    def TR_update_team(self, sa, TR_agg):
        """(``resilient_CAC_agents.py:73-84``)"""
        self.TR = self._team(self.TR, jnp.asarray(sa), TR_agg)

    def _team(self, own: MLPParams, x, targets) -> MLPParams:
        phi = trunk_forward(own, x, self._cfg.leaky_alpha)
        new_head = team_head_update(
            own[-1], phi, jnp.asarray(targets), self._cfg
        )
        return own[:-1] + (new_head,)

    # -- phase III: actor ---------------------------------------------------

    def actor_update(self, s, ns, sa, a_local, pretrain=False):
        """One Adam step of TD-error-weighted sparse CE
        (``resilient_CAC_agents.py:86-101``). ``pretrain`` mirrors the
        reference signature, where it is accepted but unused. Returns the
        ``train_on_batch``-style loss: the weighted CE at the PRE-update
        parameters."""
        del pretrain  # dead parameter in the reference too
        s, ns, sa = jnp.asarray(s), jnp.asarray(ns), jnp.asarray(sa)
        a = jnp.asarray(np.asarray(a_local).reshape(-1), jnp.int32)
        self.actor, self._actor_opt, loss = coop_actor_update(
            self.actor, self._actor_opt, self.critic, self.TR,
            s, ns, sa, a, self._cfg,
        )
        return float(loss)

    # -- sampling / introspection ------------------------------------------

    def get_action(self, state, mu: float = 0.1):
        """ε-mixed policy sample with the reference's exact global-NumPy
        draw order (``resilient_CAC_agents.py:208-219``)."""
        random_action = np.random.choice(self.n_actions)
        action_prob = np.asarray(
            actor_probs(self.actor, jnp.asarray(state), self._cfg.leaky_alpha)
        ).ravel()
        action_from_policy = np.random.choice(self.n_actions, p=action_prob)
        self.action = np.random.choice(
            [action_from_policy, random_action], p=[1 - mu, mu]
        )
        return self.action

    def get_parameters(self):
        """[actor, critic, TR] Keras-style weight lists
        (``resilient_CAC_agents.py:221-223``)."""
        return [_flat(self.actor), _flat(self.critic), _flat(self.TR)]


class _ReferenceAdversaryBase:
    """Shared shell for the three adversary twins
    (``adversarial_CAC_agents.py``): nets from Keras weight lists, the
    local-TD actor fit, and the reference's ε-mixed action sampling.

    The adversaries' ``fit(...)`` calls shuffle minibatches; the twins
    shuffle with a JAX PRNG stream (seeded per instance) instead of TF's,
    so multi-batch fits match the reference statistically, exactly as the
    trainer does (SURVEY.md §7 hard part (c)); single-batch regimes
    (B <= batch_size) are bit-faithful.
    """

    def __init__(
        self, actor, critic, team_reward, slow_lr, fast_lr, gamma,
        shuffle_seed: int = 0,
    ):
        self.actor = _layers(actor)
        self.critic = _layers(critic)
        self.TR = _layers(team_reward)
        self.n_actions = int(self.actor[-1][1].shape[0])
        self.gamma = gamma
        self._cfg = SimpleNamespace(
            gamma=gamma,
            fast_lr=fast_lr,
            slow_lr=slow_lr,
            leaky_alpha=0.1,
            dot_dtype=None,
            # fit(epochs=10, batch_size=32): adversarial_CAC_agents.py:133
            adv_fit_epochs=10,
            adv_fit_batch=32,
            # actor fit(batch_size=200, epochs=1): adversarial:41,116,224
            batch_size=200,
        )
        self._actor_opt = adam_init(self.actor)
        # Deterministic, caller-suppliable shuffle stream: construction
        # must consume NO global-NumPy draws (the reference constructors
        # don't), or seeded scripts' get_action streams would shift.
        self._key = jax.random.PRNGKey(shuffle_seed)

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def actor_update(self, s, ns, r_local, a_local):
        """Local-TD actor fit off the agent's own critic
        (``adversarial_CAC_agents.py:28-43,211-226``); the malicious twin
        overrides this to use its private critic."""
        return self._actor_fit(self.critic, s, ns, r_local, a_local)

    def _actor_fit(self, critic: MLPParams, s, ns, r_local, a_local) -> float:
        """Local-TD-weighted actor fit shared by all three adversaries
        (``adversarial_CAC_agents.py:28-43,102-119,211-226``)."""
        s, ns = jnp.asarray(s), jnp.asarray(ns)
        r = jnp.asarray(r_local).reshape(-1, 1)
        a = jnp.asarray(np.asarray(a_local).reshape(-1), jnp.int32)
        self.actor, self._actor_opt, loss = adv_actor_update(
            self._next_key(), self.actor, self._actor_opt, critic,
            s, ns, r, a, self._cfg,
        )
        return float(loss)

    def get_action(self, state, mu: float = 0.1):
        """Identical to the cooperative agent's sampling
        (``adversarial_CAC_agents.py:57-68``)."""
        return ReferenceRPBCACAgent.get_action(self, state, mu)

    def get_parameters(self):
        return [_flat(self.actor), _flat(self.critic), _flat(self.TR)]


class ReferenceFaultyAgent(_ReferenceAdversaryBase):
    """Twin of ``Faulty_CAC_agent`` (``adversarial_CAC_agents.py:5-72``):
    trains only its actor on its own reward and transmits its FROZEN
    critic/TR weights — a crash-like fault."""

    def __init__(self, actor, critic, team_reward, slow_lr, gamma=0.95,
                 shuffle_seed: int = 0):
        # the reference's faulty agent takes no fast_lr: nothing fits
        super().__init__(
            actor, critic, team_reward, slow_lr, 0.0, gamma,
            shuffle_seed=shuffle_seed,
        )

    def get_critic_weights(self):
        """(``adversarial_CAC_agents.py:45-49``)"""
        return _flat(self.critic)

    def get_TR_weights(self):
        """(``adversarial_CAC_agents.py:51-55``)"""
        return _flat(self.TR)


class ReferenceGreedyAgent(_ReferenceAdversaryBase):
    """Twin of ``Greedy_CAC_agent`` (``adversarial_CAC_agents.py:184-275``):
    trains critic/TR on its OWN reward (persisting), transmits them, and
    never applies consensus."""

    def __init__(self, actor, critic, team_reward, slow_lr, fast_lr, gamma=0.95,
                 shuffle_seed: int = 0):
        super().__init__(
            actor, critic, team_reward, slow_lr, fast_lr, gamma,
            shuffle_seed=shuffle_seed,
        )

    def critic_update_local(self, s, ns, r_local):
        """PERSISTING own-reward critic fit; returns (weights, loss)
        (``adversarial_CAC_agents.py:228-241``)."""
        self.critic, loss = adv_critic_fit(
            self._next_key(), self.critic, jnp.asarray(s), jnp.asarray(ns),
            jnp.asarray(r_local), ReferenceRPBCACAgent._full_mask(s), self._cfg,
        )
        return _flat(self.critic), float(loss)

    def TR_update_local(self, sa, r_local):
        """(``adversarial_CAC_agents.py:243-253``)"""
        self.TR, loss = adv_tr_fit(
            self._next_key(), self.TR, jnp.asarray(sa),
            jnp.asarray(r_local), ReferenceRPBCACAgent._full_mask(sa), self._cfg,
        )
        return _flat(self.TR), float(loss)


class ReferenceMaliciousAgent(_ReferenceAdversaryBase):
    """Twin of ``Malicious_CAC_agent`` (``adversarial_CAC_agents.py:
    74-182``): a PRIVATE local critic (trained on its own reward) drives
    its actor, while the transmitted critic/TR are trained toward the
    NEGATED cooperative reward — Byzantine poisoning."""

    def __init__(self, actor, critic, team_reward, slow_lr, fast_lr, gamma=0.95,
                 shuffle_seed: int = 0):
        super().__init__(
            actor, critic, team_reward, slow_lr, fast_lr, gamma,
            shuffle_seed=shuffle_seed,
        )
        # private critic starts as a copy of the compromised one
        # (adversarial_CAC_agents.py:99)
        self.critic_local_weights = _flat(self.critic)

    def actor_update(self, s, ns, r_local, a_local):
        """Actor drives off the PRIVATE critic
        (``adversarial_CAC_agents.py:102-119``)."""
        return self._actor_fit(
            _layers(self.critic_local_weights), s, ns, r_local, a_local
        )

    def critic_update_local(self, s, ns, r_local):
        """Own-reward fit of the PRIVATE critic; persists to
        ``critic_local_weights``, returns nothing — exactly the reference
        (``adversarial_CAC_agents.py:137-152``)."""
        new, _ = adv_critic_fit(
            self._next_key(), _layers(self.critic_local_weights),
            jnp.asarray(s), jnp.asarray(ns), jnp.asarray(r_local),
            ReferenceRPBCACAgent._full_mask(s), self._cfg,
        )
        self.critic_local_weights = _flat(new)

    def critic_update_compromised(self, s, ns, r_compromised):
        """Poisoned-critic fit toward the negated team reward; persists
        and returns (weights, loss) (``adversarial_CAC_agents.py:121-135``)."""
        self.critic, loss = adv_critic_fit(
            self._next_key(), self.critic, jnp.asarray(s), jnp.asarray(ns),
            jnp.asarray(r_compromised), ReferenceRPBCACAgent._full_mask(s),
            self._cfg,
        )
        return _flat(self.critic), float(loss)

    def TR_update_compromised(self, sa, r_compromised):
        """(``adversarial_CAC_agents.py:154-165``)"""
        self.TR, loss = adv_tr_fit(
            self._next_key(), self.TR, jnp.asarray(sa),
            jnp.asarray(r_compromised), ReferenceRPBCACAgent._full_mask(sa),
            self._cfg,
        )
        return _flat(self.TR), float(loss)

    def get_parameters(self):
        """Four entries incl. the private critic
        (``adversarial_CAC_agents.py:180-182``)."""
        return super().get_parameters() + [list(self.critic_local_weights)]
