"""Per-role update rules as pure functions over stacked parameters.

This module is the algorithmic heart: the TPU-native twin of the
reference's four agent classes (``agents/resilient_CAC_agents.py`` and
``agents/adversarial_CAC_agents.py``), re-expressed as pure functions of
``(stacked params, batch, masks)`` so a whole heterogeneous network of
agents updates inside one jitted XLA program (SURVEY.md §7 "Design
stance"). Object-per-agent method dispatch becomes compute-per-role +
masked select; role composition is STATIC (from Config), so absent roles
cost nothing at trace time.

Phase structure per update block (reference ``train_agents.py:100-153``):

  for epoch in range(n_epochs):
    I)  local critic/TR fits, ALL agents -> "messages" (transmitted
        weights); cooperative agents RESTORE their own nets
        (resilient_CAC_agents.py:120,138) — the local step produces the
        message, not a state change.
    II) resilient consensus, cooperative agents only:
        a) gather neighbor messages over in_nodes,
        b) hidden-layer clip-mean consensus -> new trunk,
        c) projection: evaluate every neighbor's HEAD on the agent's own
           (just-aggregated) trunk features, clip-mean over neighbors,
        d) normalized team update of the head toward the aggregate.
  III) actor updates, once per block: cooperative = one weighted
       train_on_batch step; adversaries = 5 shuffled minibatch Adam steps
       (fit(batch_size=200, epochs=1), adversarial_CAC_agents.py:41).

All batch tensors live in fixed-capacity buffers with validity masks so
shapes stay static under jit (see ops/losses.py).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config
from rcmarl_tpu.models.mlp import (
    MLPParams,
    actor_probs,
    einsum,
    flatten_input,
    head_forward,
    mlp_forward,
    netstack_stack,
    netstack_stack_rows,
    pad_features,
    pad_rows,
    trunk_apply,
    trunk_forward,
)
from rcmarl_tpu.ops.aggregation import (
    resilient_aggregate,
    resilient_aggregate_tree,
)
from rcmarl_tpu.ops.fit import (
    FitSchedule,
    fit_minibatch,
    fit_mse_full_batch,
    fit_mse_minibatch,
    fused_fit_scan,
)
from rcmarl_tpu.ops.losses import weighted_mse, weighted_sparse_ce
from rcmarl_tpu.ops.optim import AdamState, adam_update


class AgentParams(NamedTuple):
    """All agents' learnable state, every leaf with leading agent axis N.

    ``critic_local`` is the malicious agent's PRIVATE critic
    (adversarial_CAC_agents.py:99): trained on its own reward and used for
    its actor updates, while ``critic`` holds the compromised critic it
    transmits. For non-malicious agents ``critic_local`` is an unused
    mirror (kept dense for vmap-ability; tiny at these model sizes).
    """

    actor: MLPParams
    critic: MLPParams
    tr: MLPParams
    critic_local: MLPParams
    actor_opt: AdamState


class CellSpec(NamedTuple):
    """One experiment cell's behavioral knobs as TRACED data.

    The solo trainer specializes its program on ``Config`` at trace time
    (roles/H/common_reward are compile-time constants; absent roles cost
    nothing). This pytree is the alternative used by the fused-matrix
    path (:mod:`rcmarl_tpu.parallel.matrix`): every field is an array,
    so replicas with DIFFERENT scenarios — the reference's whole
    scenario x H experiment matrix (``simulation_results/raw_data``
    layout) — share ONE compiled program, vmapped over the cell axis.
    Heterogeneous behavior then costs compute-all-then-mask across roles,
    the trade SURVEY.md §7 endorses at these model sizes.

    coop/greedy/malicious: (N,) bool role masks (faulty = none of the
    three: it transmits frozen nets and needs no branch of its own).
    H: () int32 trim parameter. common_reward: () bool.
    task_scale: () float32 congestion-toll multiplier — the Diff-DAC
    task axis (``Config.task_axis``): each replica trains the
    congestion world at its own load level, all from one compiled
    program. 1.0 multiplies bitwise-exactly, so every non-task cell
    keeps the historical reward stream bit-for-bit.
    """

    coop: jnp.ndarray
    greedy: jnp.ndarray
    malicious: jnp.ndarray
    H: jnp.ndarray
    common_reward: jnp.ndarray
    task_scale: jnp.ndarray


class Batch(NamedTuple):
    """Fixed-capacity update batch (the replay window).

    s/ns: (B, N, n_states) scaled states; a: (B, N, 1) float actions;
    r: (B, N, 1) scaled rewards; mask: (B,) validity.
    """

    s: jnp.ndarray
    ns: jnp.ndarray
    a: jnp.ndarray
    r: jnp.ndarray
    mask: jnp.ndarray

    @property
    def sa(self) -> jnp.ndarray:
        """concat(s, a) on the feature axis (train_agents.py:93)."""
        return jnp.concatenate([self.s, self.a], axis=-1)


# --------------------------------------------------------------------------
# Phase I: local fits
# --------------------------------------------------------------------------


def _fwd(cfg: Config):
    """The forward pass every critic/TR fit regresses with (the nets use
    the reference-hardcoded LeakyReLU alpha=0.1 default)."""
    return lambda p, x: mlp_forward(p, x, dtype=cfg.dot_dtype)


def coop_local_critic_fit(
    critic: MLPParams, s, ns, r, mask, cfg: Config
) -> Tuple[MLPParams, jnp.ndarray]:
    """Cooperative local critic fit -> transmitted message
    (resilient_CAC_agents.py:103-122): TD target computed ONCE with
    current weights, then ``coop_fit_steps`` full-batch SGD steps; the
    caller keeps the agent's own critic unchanged (restore semantics).
    Returns (message_params, first_step_loss) — the loss mirrors the
    reference's ``history['loss'][0]`` second return value."""
    fwd = _fwd(cfg)
    target = r + cfg.gamma * fwd(critic, ns)
    return fit_mse_full_batch(
        critic, fwd, s, target, mask, cfg.coop_fit_steps, cfg.fast_lr,
        clip=cfg.fit_clip,
    )


def coop_local_tr_fit(
    tr: MLPParams, sa, r, mask, cfg: Config
) -> Tuple[MLPParams, jnp.ndarray]:
    """Cooperative local team-reward fit (resilient_CAC_agents.py:124-140):
    same 5-step full-batch SGD, target = local reward (no bootstrap).
    Returns (message_params, first_step_loss)."""
    return fit_mse_full_batch(
        tr, _fwd(cfg), sa, r, mask, cfg.coop_fit_steps, cfg.fast_lr,
        clip=cfg.fit_clip,
    )


def adv_critic_fit(
    key, critic: MLPParams, s, ns, r_target, mask, cfg: Config
) -> Tuple[MLPParams, jnp.ndarray]:
    """Adversary critic fit (greedy local / malicious local+compromised):
    TD target with pre-fit weights, then fit(epochs=10, batch_size=32)
    shuffled minibatch SGD (adversarial_CAC_agents.py:131-133,146-151,
    237-239). The update PERSISTS (no restore). Returns
    (params, first_epoch_mean_loss) — the reference's
    ``history['loss'][0]`` second return value."""
    fwd = _fwd(cfg)
    target = r_target + cfg.gamma * fwd(critic, ns)
    return fit_mse_minibatch(
        key, critic, fwd, s, target, mask,
        cfg.adv_fit_epochs, cfg.adv_fit_batch, cfg.fast_lr,
        clip=cfg.fit_clip,
    )


def adv_tr_fit(
    key, tr: MLPParams, sa, r_target, mask, cfg: Config
) -> Tuple[MLPParams, jnp.ndarray]:
    """Adversary team-reward fit: fit(epochs=10, batch_size=32) toward the
    (possibly compromised) reward (adversarial_CAC_agents.py:154-165,
    243-253). Returns (params, first_epoch_mean_loss)."""
    return fit_mse_minibatch(
        key, tr, _fwd(cfg), sa, r_target, mask,
        cfg.adv_fit_epochs, cfg.adv_fit_batch, cfg.fast_lr,
        clip=cfg.fit_clip,
    )


# --------------------------------------------------------------------------
# Netstack: critic + TR fits as ONE (net, agent)-vmapped program
# --------------------------------------------------------------------------
#
# ``Config.netstack`` stacks the critic and team-reward families along a
# leading net axis (models/mlp.py:netstack_stack — critic inputs/first-
# layer rows zero-padded to the TR width, exactly gradient-neutral), so
# each phase-I fit flavor launches ONE scan over (2, N) stacked nets
# instead of two N-stacked scans, and phase II aggregates both message
# trees as one combined block (:func:`consensus_update_pair`). Net 0 is
# the critic, net 1 the TR net. Both nets regress toward FIXED
# precomputed targets (:func:`pair_bootstrap_targets`: net 0 gets the TD
# bootstrap, net 1 the raw reward), which is how one program serves both
# target rules without the stacked loop paying a per-net branch.


def netstack_pair_inputs(cfg: Config, s, sa) -> jnp.ndarray:
    """The shared stacked fit/feature input for the critic+TR netstack:
    ``(2, B, sa_dim)`` — net 0 the zero-padded flattened critic input
    (s), net 1 the flattened TR input (sa)."""
    return jnp.stack(
        [pad_features(flatten_input(s), cfg.sa_dim), flatten_input(sa)]
    )


def pair_bootstrap_targets(cfg: Config, critic, ns, r, v=None) -> jnp.ndarray:
    """(2, N, B, 1) regression targets for one critic+TR fit pair:
    net 0 = ``r + gamma * V(ns)`` (TD bootstrap with the PRE-FIT critic),
    net 1 = ``r`` (the TR net regresses the raw reward, no bootstrap).

    The bootstrap forward runs ONCE at the critic's unpadded width — the
    dual arm computes the identical ``mlp_forward(critic, ns)`` inside
    each critic fit flavor, so reusing one evaluation across the coop /
    greedy / malicious pairs is a strict flop saving in mixed-role
    configs, and the targets stay bitwise the dual arm's. Pass a
    precomputed ``v`` (the (N, B, 1) bootstrap values) to share it
    across several target calls, as the netstack epoch does.
    """
    if v is None:
        v = jax.vmap(lambda p: mlp_forward(p, ns, dtype=cfg.dot_dtype))(critic)
    return jnp.stack([r + cfg.gamma * v, jnp.broadcast_to(r, v.shape)])


def coop_pair_fit(stack2, x2, targets2, mask, cfg: Config):
    """Phase-I cooperative critic+TR fits as ONE (net, agent)-vmapped
    full-batch scan — the netstack twin of
    :func:`coop_local_critic_fit` + :func:`coop_local_tr_fit`.

    ``stack2``: netstacked params, leaves ``(2, N, ...)``; ``x2``:
    :func:`netstack_pair_inputs`; ``targets2``: ``(2, N, B, 1)``
    precomputed regression targets (:func:`pair_bootstrap_targets`).
    Returns the stacked messages (leaves ``(2, N, ...)``) and ``(2, N)``
    losses.
    """
    fwd = _fwd(cfg)

    def fit_one(p, x, t):
        return fit_mse_full_batch(
            p, fwd, x, t, mask, cfg.coop_fit_steps, cfg.fast_lr,
            clip=cfg.fit_clip,
        )

    per_agent = jax.vmap(fit_one, in_axes=(0, None, 0))
    return jax.vmap(per_agent, in_axes=(0, 0, 0))(stack2, x2, targets2)


def adv_pair_fit(keys2, stack2, x2, targets2, mask, cfg: Config):
    """Phase-I adversary critic+TR fit pair as ONE (net, agent)-vmapped
    minibatch program — the netstack twin of :func:`adv_critic_fit` +
    :func:`adv_tr_fit` (used for both the greedy and the malicious
    compromised pair; the malicious PRIVATE critic fit stays unpaired).

    ``keys2``: ``(2, N)`` PRNG keys — per net the same ``split(key, N)``
    stream the dual-launch arm draws, so shuffles are identical.
    """
    fwd = _fwd(cfg)

    def fit_one(k, p, x, t):
        return fit_mse_minibatch(
            k, p, fwd, x, t, mask,
            cfg.adv_fit_epochs, cfg.adv_fit_batch, cfg.fast_lr,
            clip=cfg.fit_clip,
        )

    per_agent = jax.vmap(fit_one, in_axes=(0, 0, None, 0))
    return jax.vmap(per_agent, in_axes=(0, 0, 0, 0))(
        keys2, stack2, x2, targets2
    )


# --------------------------------------------------------------------------
# Fitstack: ALL fit flavors of one schedule shape as ONE stacked scan
# --------------------------------------------------------------------------
#
# ``Config.fitstack`` goes one rung above the pair stacking: instead of
# one (2, N) scan PER FLAVOR, every flavor sharing a schedule shape —
# full-batch (cooperative critic+TR) vs minibatch (greedy critic+TR,
# malicious compromised critic+TR, malicious private critic) — stacks
# into one (flavor·net, N) row block and launches through the ONE
# unified scan body of :func:`rcmarl_tpu.ops.fit.fused_fit_scan`. The
# two shapes cannot share a launch without ruinous width padding (a
# 32-row minibatch padded to the buffer capacity), so a mixed cast pays
# exactly two fused launches; a homogeneous cast pays ONE.


def coop_fit_schedule(cfg: Config, capacity: int) -> FitSchedule:
    """The cooperative full-batch flavor's schedule shape: one
    identity-plan batch covering the buffer, ``coop_fit_steps`` times —
    bitwise :func:`fit_mse_full_batch` through the minibatch body."""
    return FitSchedule(
        epochs=cfg.coop_fit_steps, batch_size=capacity, shuffle=False
    )


def adv_fit_schedule(cfg: Config) -> FitSchedule:
    """The adversary minibatch flavors' shared schedule shape."""
    return FitSchedule(
        epochs=cfg.adv_fit_epochs, batch_size=cfg.adv_fit_batch, shuffle=True
    )


def fitstack_impl(cfg: Config) -> str:
    """The fitstack scan's execution backend: ``'pallas'`` /
    ``'pallas_interpret'`` when :attr:`Config.fitstack` names the
    fit-scan kernel (:mod:`rcmarl_tpu.ops.pallas_fit` — parameters
    VMEM-resident across the whole schedule), ``'xla'`` for every other
    truthy fitstack value (the lax.scan arm)."""
    from rcmarl_tpu.config import FITSTACK_IMPLS

    return cfg.fitstack if cfg.fitstack in FITSTACK_IMPLS else "xla"


def fused_fit_rows(keys_rows, params_rows, x_rows, targets_rows, mask,
                   schedule: FitSchedule, cfg: Config):
    """One fused (row, agent)-vmapped fit launch over stacked
    (flavor·net) rows — the fitstack twin of :func:`coop_pair_fit` /
    :func:`adv_pair_fit`, sharing their forward and learning rate.
    Under ``Config.fitstack in FITSTACK_IMPLS`` the launch is the
    fit-scan Pallas kernel instead of the XLA scan (fitted rows pinned
    leaf-for-leaf — tests/test_fused_epoch.py).
    Returns (fitted rows, (R, N) losses)."""
    impl = fitstack_impl(cfg)
    if impl != "xla":
        from rcmarl_tpu.ops.pallas_fit import pallas_fit_scan

        return pallas_fit_scan(
            keys_rows, params_rows, _fwd(cfg), x_rows, targets_rows,
            mask, schedule, cfg.fast_lr, cfg.fit_clip,
            interpret=impl == "pallas_interpret",
        )
    return fused_fit_scan(
        keys_rows, params_rows, _fwd(cfg), x_rows, targets_rows, mask,
        schedule, cfg.fast_lr, cfg.fit_clip,
    )


def coop_fused_fit(critic, tr, x2, targets2, mask, cfg: Config):
    """The full-batch group (cooperative critic + TR) as ONE fused
    launch. Keys are zeros: the identity-plan schedule never reads
    them. Returns (stacked (2, N, ...) fitted rows, (2, N) losses)."""
    N = jax.tree.leaves(critic)[0].shape[0]
    return fused_fit_rows(
        jnp.zeros((2, N, 2), jnp.uint32),
        netstack_stack(critic, tr),
        x2,
        targets2,
        mask,
        coop_fit_schedule(cfg, x2.shape[1]),
        cfg,
    )


def adv_fused_row_block(
    cfg: Config,
    critic,
    tr,
    critic_local,
    x2,
    ns,
    r_agents,
    r_coop,
    keys5,
    v_ns=None,
    has_greedy: bool = True,
    has_mal: bool = True,
):
    """Assemble the minibatch-group row block: every adversary fit
    flavor present as stacked (flavor·net) rows with the dual arm's
    exact per-flavor key streams.

    THE single source of truth for the fused adversary rows — shared by
    the epoch (``training/update.py:_phase1_fits_fused``) and the
    consensus-micro profiler, so the arm the profiler measures can
    never silently drift from the arm the epoch runs.

    Args:
      keys5: the ``(5, ...)`` key block ``split(ekey, 5)`` — rows
        ``(k_gc, k_gt, k_ml, k_mc, k_mt)``, the dual arm's exact split.
      v_ns: optional precomputed pre-fit critic bootstrap ``V(ns)``
        (the netstack sharing recipe); None recomputes it inside the
        pair targets, bitwise either way.

    Returns ``(keys_rows, params_rows, x_rows, targets_rows, in_dims)``
    ready for :func:`fused_fit_rows`, or None when neither adversary
    flavor is live.
    """
    k_gc, k_gt, k_ml, k_mc, k_mt = keys5
    N = jax.tree.leaves(critic)[0].shape[0]
    in2 = (cfg.obs_dim, cfg.sa_dim)

    def pair_targets(r):
        return pair_bootstrap_targets(cfg, critic, ns, r, v=v_ns)

    rows, keys, xs, tgts, in_dims = [], [], [], [], []
    if has_greedy:
        tg = pair_targets(r_agents)
        rows += [critic, tr]
        keys += [jax.random.split(k_gc, N), jax.random.split(k_gt, N)]
        xs += [x2[0], x2[1]]
        tgts += [tg[0], tg[1]]
        in_dims += list(in2)
    if has_mal:
        neg = jnp.broadcast_to(-r_coop[None], (N, *r_coop.shape))
        tgm = pair_targets(neg)
        # private critic on own reward (adversarial_CAC_agents.py:137-152),
        # bootstrapped with its OWN pre-fit weights
        v_loc = jax.vmap(
            lambda p: mlp_forward(p, ns, dtype=cfg.dot_dtype)
        )(critic_local)
        rows += [critic, tr, critic_local]
        keys += [
            jax.random.split(k_mc, N),
            jax.random.split(k_mt, N),
            jax.random.split(k_ml, N),
        ]
        xs += [x2[0], x2[1], x2[0]]
        tgts += [tgm[0], tgm[1], r_agents + cfg.gamma * v_loc]
        in_dims += [in2[0], in2[1], in2[0]]
    if not rows:
        return None
    return (
        jnp.stack(keys),
        netstack_stack_rows(rows),
        jnp.stack(xs),
        jnp.stack(tgts),
        tuple(in_dims),
    )


# --------------------------------------------------------------------------
# Phase II: resilient consensus (cooperative agents)
# --------------------------------------------------------------------------


def consensus_update_one(
    own: MLPParams,
    nbr_msgs: MLPParams,
    x: jnp.ndarray,
    mask: jnp.ndarray,
    cfg: Config,
    valid: jnp.ndarray | None = None,
    H=None,
) -> MLPParams:
    """Full Phase-II update for ONE cooperative agent's critic or TR net.

    Args:
      own: the agent's current net (pre-consensus; its head is the
        pre-phase-I head thanks to restore semantics).
      nbr_msgs: gathered neighbor messages, leaves (n_in, ...), own
        message at index 0 (in_nodes convention).
      x: (B, ...) the net's input batch (s for critic, sa for TR).
      valid: optional (n_in,) edge-validity mask when the graph has
        heterogeneous in-degrees and neighborhoods are padded (see
        :func:`rcmarl_tpu.ops.aggregation.resilient_aggregate`).

    Steps b-d of reference train_agents.py:125-145:
      b) hidden consensus (resilient_CAC_agents.py:142-166): clip-mean
         each trunk array over neighbors (trim bounds by dual
         top-(H+1) selection on the default impl — ops/aggregation.py);
         write trunk only.
      c) projection (resilient_CAC_agents.py:168-206): evaluate each
         neighbor's head on the agent's NEW trunk features over the whole
         batch; clip-mean over neighbors -> per-sample targets.
      d) team update (resilient_CAC_agents.py:60-84): one SGD step of the
         head (trunk frozen) toward the aggregated targets with weights
         1/(2*fast_lr*(||phi||^2+1)) — the paper's normalized projected
         update; with Keras MSE + SUM_OVER_BATCH_SIZE the fast_lr cancels.
    """
    n_trunk = len(own) - 1
    # traced H (the fused-matrix path) is XLA-only; the aggregation layer
    # resolves 'auto' to an impl that can lower and RAISES on an explicit
    # pallas choice rather than silently downgrading (ops/aggregation.py).
    # Both aggregation calls below carry everything the 3-way 'auto'
    # policy keys on — H (static here, traced on the matrix path), the
    # leading neighbor-axis size, and n_agents for the gathered volume —
    # so sort-vs-select-vs-pallas resolution happens at trace time with
    # no extra plumbing at this layer.
    H = cfg.H if H is None else H
    impl = cfg.consensus_impl
    # cfg.consensus_sanitize hardens BOTH aggregation calls against
    # non-finite neighbor payloads (transport faults, diverged peers):
    # bombs become exclusions, degree deficits keep the own value.
    sanitize = cfg.consensus_sanitize
    # b) hidden-layer consensus over trunk arrays: under the default
    # cfg.consensus_layout='flat' the whole trunk tree is raveled into
    # ONE (n_in, P_total) block, so the epoch issues a single
    # select/clip/mean op sequence per message tree instead of one per
    # weight array (bitwise identical either way).
    trunk_agg = resilient_aggregate_tree(
        tuple(nbr_msgs[i] for i in range(n_trunk)),
        H,
        impl,
        valid=valid,
        n_agents=cfg.n_agents,
        sanitize=sanitize,
        layout=cfg.consensus_layout,
    )
    new_params: MLPParams = tuple(trunk_agg) + (own[-1],)
    # c) projection: phi with aggregated trunk, all neighbor heads at once
    phi = trunk_forward(new_params, x, cfg.leaky_alpha, cfg.dot_dtype)  # (B, h)
    W_nbr, b_nbr = nbr_msgs[-1]  # (n_in, h, 1), (n_in, 1)
    proj = einsum("bh,nho->nbo", phi, W_nbr, dtype=cfg.dot_dtype)
    vals = proj + b_nbr[:, None, :]  # (n_in, B, 1)
    agg = resilient_aggregate(
        vals, H, impl, valid=valid, n_agents=cfg.n_agents, sanitize=sanitize
    )  # (B, 1)
    agg = jax.lax.stop_gradient(agg)
    # d) normalized team update of the head only
    new_head = team_head_update(new_params[-1], phi, agg, cfg, mask=mask)
    return tuple(trunk_agg) + (new_head,)


def _unravel_cols(vec: jnp.ndarray, tree):
    """Split a flat (P,) column vector back into a pytree of leaves
    (shapes taken from ``tree``; no leading neighbor axis)."""
    leaves, treedef = jax.tree.flatten(tree)
    out, off = [], 0
    for l in leaves:
        out.append(vec[off : off + l.size].reshape(l.shape))
        off += l.size
    return jax.tree.unflatten(treedef, out)


def consensus_update_pair(
    own_c: MLPParams,
    own_t: MLPParams,
    blk: jnp.ndarray,
    x2: jnp.ndarray,
    mask: jnp.ndarray,
    cfg: Config,
    valid: jnp.ndarray | None = None,
    H=None,
    impl: str | None = None,
) -> Tuple[MLPParams, MLPParams]:
    """Full Phase-II update for ONE agent's critic AND TR nets from one
    COMBINED raveled neighbor block (the netstack mode twin of two
    :func:`consensus_update_one` calls).

    Args:
      own_c/own_t: the agent's current critic / team-reward nets.
      blk: (n_in, P_critic + P_tr) gathered neighbor messages, own at
        index 0, columns laid out trunks-first:
        ``[trunk_c | trunk_t | head_c | head_t]`` (the ravel order of
        ``((trunk_c, trunk_t), (head_c, head_t))`` —
        ``training/update.py`` builds it with
        :func:`~rcmarl_tpu.ops.aggregation.ravel_neighbor_tree`).
      x2: (2, B, sa_dim) stacked flattened net inputs (net 0 = padded
        critic input, net 1 = TR input) — :func:`netstack_pair_inputs`.
      impl: aggregation backend override (default: the config's). The
        fused-epoch fallback paths pass ``'xla'`` here so the stacked
        XLA arm stays the bitwise reference whatever the config names.

    Steps b-d of the reference's Phase II, each launched ONCE for both
    trees: (b) one trim/clip/mean over the combined trunk columns, (c)
    one stacked trunk forward + one projection einsum over both head
    families, (d) one (net,)-vmapped normalized team head step — (c)
    and (d) shared with the one-kernel epoch as
    :func:`consensus_pair_tail`. Bitwise column-equal to the two
    per-tree aggregations (aggregation is elementwise along the
    trailing axis).
    """
    H = cfg.H if H is None else H
    impl = cfg.consensus_impl if impl is None else impl
    sanitize = cfg.consensus_sanitize
    trunk_c, trunk_t = own_c[:-1], own_t[:-1]
    P_c = sum(l.size for l in jax.tree.leaves(trunk_c))
    P_t = sum(l.size for l in jax.tree.leaves(trunk_t))
    # b) hidden consensus: ONE clip-mean over the combined trunk columns
    if P_c + P_t:
        agg = resilient_aggregate(
            blk[:, : P_c + P_t],
            H,
            impl,
            valid=valid,
            n_agents=cfg.n_agents,
            sanitize=sanitize,
        )
    else:  # head-only (hidden=()) nets: nothing to aggregate
        agg = None
    return consensus_pair_tail(
        own_c,
        own_t,
        agg,
        blk[:, P_c + P_t :],
        x2,
        mask,
        cfg,
        valid=valid,
        H=H,
        impl=impl,
    )


def consensus_pair_tail(
    own_c: MLPParams,
    own_t: MLPParams,
    agg_trunk: jnp.ndarray | None,
    head_blk: jnp.ndarray,
    x2: jnp.ndarray,
    mask: jnp.ndarray,
    cfg: Config,
    valid: jnp.ndarray | None = None,
    H=None,
    impl: str | None = None,
) -> Tuple[MLPParams, MLPParams]:
    """Steps c-d of the pair Phase II — the part of the epoch that
    STAYS XLA under the one-kernel arm (``consensus_impl=
    'pallas_fused'``): the per-net trunk forward, the projection einsum
    over both head families, ONE aggregation of the stacked per-sample
    estimates, and the normalized team head step.

    Args:
      agg_trunk: (P_critic + P_tr,) post-consensus trunk columns (the
        XLA aggregation's output, or the fused kernel's emitted tile);
        None for head-only (hidden=()) nets.
      head_blk: (n_in, 2(h+1)) gathered (and transport-faulted) head
        columns — ``[W_c | b_c | W_t | b_t]``, own row at index 0.
        Slicing them from a separately gathered head block is bitwise
        slicing them from the full pair block (gather commutes with the
        column slice), which is how the two arms share this tail.
    """
    H = cfg.H if H is None else H
    impl = cfg.consensus_impl if impl is None else impl
    sanitize = cfg.consensus_sanitize
    trunk_c, trunk_t = own_c[:-1], own_t[:-1]
    P_c = sum(l.size for l in jax.tree.leaves(trunk_c))
    P_t = sum(l.size for l in jax.tree.leaves(trunk_t))
    n_in = head_blk.shape[0]
    if agg_trunk is not None and P_c + P_t:
        new_trunk_c = _unravel_cols(agg_trunk[:P_c], trunk_c)
        new_trunk_t = _unravel_cols(agg_trunk[P_c:], trunk_t)
    else:  # head-only (hidden=()) nets: nothing was aggregated
        new_trunk_c, new_trunk_t = trunk_c, trunk_t
    # c) projection: per-net trunk features (each at its own unpadded
    # first-layer width — bitwise the dual arm's phi, no padding FLOPs),
    # then ONE einsum over both head families and ONE aggregation of the
    # stacked per-sample estimates
    h_c = own_c[-1][0].shape[0]
    h_t = own_t[-1][0].shape[0]
    h_max = max(h_c, h_t)
    x_c = x2[0, :, : own_c[0][0].shape[-2]]  # un-pad: zeros are appended
    if P_c + P_t:
        phi2 = jnp.stack([
            trunk_apply(new_trunk_c, x_c, cfg.leaky_alpha, cfg.dot_dtype),
            trunk_apply(new_trunk_t, x2[1], cfg.leaky_alpha, cfg.dot_dtype),
        ])  # (2, B, h)
    else:  # head-only nets: the flattened inputs ARE the features
        phi2 = jnp.stack([pad_features(x_c, h_max), x2[1]])
    off = 0
    W_c_nbr = head_blk[:, off : off + h_c].reshape(n_in, h_c, 1)
    b_c_nbr = head_blk[:, off + h_c : off + h_c + 1]
    off += h_c + 1
    W_t_nbr = head_blk[:, off : off + h_t].reshape(n_in, h_t, 1)
    b_t_nbr = head_blk[:, off + h_t : off + h_t + 1]
    W2_nbr = jnp.stack(
        [pad_rows(W_c_nbr, h_max), pad_rows(W_t_nbr, h_max)]
    )  # (2, n_in, h_max, 1)
    b2_nbr = jnp.stack([b_c_nbr, b_t_nbr])  # (2, n_in, 1)
    proj = einsum("kbh,knho->knbo", phi2, W2_nbr, dtype=cfg.dot_dtype)
    vals = proj + b2_nbr[:, :, None, :]  # (2, n_in, B, 1)
    agg2 = resilient_aggregate(
        jnp.moveaxis(vals, 0, 1),  # (n_in, 2, B, 1): neighbor axis leads
        H,
        impl,
        valid=valid,
        n_agents=cfg.n_agents,
        sanitize=sanitize,
    )  # (2, B, 1)
    agg2 = jax.lax.stop_gradient(agg2)
    # d) normalized team update of both heads in one (net,)-vmapped step
    head2 = (
        jnp.stack(
            [pad_rows(own_c[-1][0], h_max),
             pad_rows(own_t[-1][0], h_max)]
        ),
        jnp.stack([own_c[-1][1], own_t[-1][1]]),
    )
    new_W2, new_b2 = jax.vmap(
        lambda hd, ph, tg: team_head_update(hd, ph, tg, cfg, mask=mask)
    )(head2, phi2, agg2)
    new_c = tuple(new_trunk_c) + ((new_W2[0, :h_c], new_b2[0]),)
    new_t = tuple(new_trunk_t) + ((new_W2[1, :h_t], new_b2[1]),)
    return new_c, new_t


def team_head_update(head, phi, targets, cfg: Config, mask=None):
    """The paper's normalized projected head step (reference
    ``critic_update_team``/``TR_update_team``,
    ``resilient_CAC_agents.py:60-84``): one SGD step of the output layer
    on frozen trunk features ``phi`` toward the aggregated ``targets``,
    sample-weighted 1/(2*fast_lr*(||phi||^2+1)) — with Keras MSE's
    SUM_OVER_BATCH_SIZE reduction the fast_lr cancels."""
    phi_sg = jax.lax.stop_gradient(phi)
    phi_norm = jnp.sum(phi_sg**2, axis=1) + 1.0  # (B,)
    weights = 1.0 / (2.0 * cfg.fast_lr * phi_norm)

    def head_loss(head_params):
        pred = head_forward(head_params, phi_sg, cfg.dot_dtype)
        return weighted_mse(pred, targets, sample_weight=weights, mask=mask)

    g = jax.grad(head_loss)(head)
    return jax.tree.map(lambda p, gg: p - cfg.fast_lr * gg, head, g)


# --------------------------------------------------------------------------
# Phase III: actor updates
# --------------------------------------------------------------------------


def coop_actor_update(
    actor: MLPParams,
    opt: AdamState,
    critic: MLPParams,
    tr: MLPParams,
    s,
    ns,
    sa,
    a_own,
    cfg: Config,
) -> Tuple[MLPParams, AdamState]:
    """Cooperative actor step (resilient_CAC_agents.py:86-101): sample
    weights = team TD error r_bar(sa) + gamma*V(ns) - V(s) (own TR/critic,
    post-consensus), ONE full-batch Adam step of weighted sparse CE over
    the fresh on-policy window (always fully valid). Returns
    (new_actor, new_opt, pre_update_loss) — the loss mirrors the
    reference's ``train_on_batch`` return value."""
    delta = (
        mlp_forward(tr, sa, dtype=cfg.dot_dtype)
        + cfg.gamma * mlp_forward(critic, ns, dtype=cfg.dot_dtype)
        - mlp_forward(critic, s, dtype=cfg.dot_dtype)
    )
    delta = jax.lax.stop_gradient(delta[:, 0])  # (B,)

    def loss(p):
        return weighted_sparse_ce(
            actor_probs(p, s, cfg.leaky_alpha, cfg.dot_dtype), a_own, delta
        )

    loss_val, g = jax.value_and_grad(loss)(actor)
    new_actor, new_opt = adam_update(actor, g, opt, cfg.slow_lr)
    return new_actor, new_opt, loss_val


def adv_actor_update(
    key,
    actor: MLPParams,
    opt: AdamState,
    critic: MLPParams,
    s,
    ns,
    r_own,
    a_own,
    cfg: Config,
) -> Tuple[MLPParams, AdamState, jnp.ndarray]:
    """Adversary actor step (adversarial_CAC_agents.py:28-43,102-119,
    211-226): sample weights = LOCAL TD error from own reward and own
    critic (malicious: its private local critic), then
    fit(batch_size=200, epochs=1) = shuffled minibatch Adam steps.
    Returns (new_actor, new_opt, first_epoch_mean_loss)."""
    delta = (
        r_own
        + cfg.gamma * mlp_forward(critic, ns, dtype=cfg.dot_dtype)
        - mlp_forward(critic, s, dtype=cfg.dot_dtype)
    )
    delta = jax.lax.stop_gradient(delta[:, 0])  # (B,)
    B = s.shape[0]
    mask = jnp.ones((B,), jnp.float32)

    def batch_loss(p, idx, bval):
        return weighted_sparse_ce(
            actor_probs(p, s[idx], cfg.leaky_alpha, cfg.dot_dtype),
            a_own[idx], delta[idx], mask=bval,
        )

    return fit_minibatch(
        key,
        actor,
        batch_loss,
        capacity=B,
        mask=mask,
        epochs=1,
        batch_size=cfg.batch_size,
        opt_state=opt,
        opt_update=lambda p, g, s_: adam_update(p, g, s_, cfg.slow_lr),
        # the on-policy window is always full: the shuffle can skip the
        # valid-first penalty work (bitwise-identical plan — pinned in
        # tests/test_fitstack_properties.py)
        assume_valid=True,
    )


# --------------------------------------------------------------------------
# Role-masked select helpers
# --------------------------------------------------------------------------


def select_tree(pred_per_agent: jnp.ndarray, if_true, if_false, axis: int = 0):
    """Per-agent masked select over stacked pytrees: leaves carry the
    agent dimension on ``axis`` (0 for the usual (N, ...) stacks, 1 for
    netstacked (2, N, ...) leaves)."""

    def sel(a, b):
        shape = [1] * a.ndim
        shape[axis] = -1
        return jnp.where(pred_per_agent.reshape(shape), a, b)

    return jax.tree.map(sel, if_true, if_false)
