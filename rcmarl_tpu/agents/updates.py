"""Per-role update rules as pure functions over stacked parameters.

This module is the algorithmic heart: the TPU-native twin of the
reference's four agent classes (``agents/resilient_CAC_agents.py`` and
``agents/adversarial_CAC_agents.py``), re-expressed as pure functions of
``(stacked params, batch, masks)`` so a whole heterogeneous network of
agents updates inside one jitted XLA program (SURVEY.md §7 "Design
stance"). Object-per-agent method dispatch becomes compute-per-role +
masked select; role composition is STATIC (from Config), so absent roles
cost nothing at trace time.

Phase structure per update block (reference ``train_agents.py:100-153``):

  for epoch in range(n_epochs):
    I)  local critic/TR fits, ALL agents -> "messages" (transmitted
        weights); cooperative agents RESTORE their own nets
        (resilient_CAC_agents.py:120,138) — the local step produces the
        message, not a state change.
    II) resilient consensus, cooperative agents only:
        a) gather neighbor messages over in_nodes,
        b) hidden-layer clip-mean consensus -> new trunk,
        c) projection: evaluate every neighbor's HEAD on the agent's own
           (just-aggregated) trunk features, clip-mean over neighbors,
        d) normalized team update of the head toward the aggregate.
  III) actor updates, once per block: cooperative = one weighted
       train_on_batch step; adversaries = 5 shuffled minibatch Adam steps
       (fit(batch_size=200, epochs=1), adversarial_CAC_agents.py:41).

All batch tensors live in fixed-capacity buffers with validity masks so
shapes stay static under jit (see ops/losses.py).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.config import Config
from rcmarl_tpu.models.mlp import (
    MLPParams,
    actor_probs,
    einsum,
    head_forward,
    mlp_forward,
    trunk_forward,
)
from rcmarl_tpu.ops.aggregation import (
    resilient_aggregate,
    resilient_aggregate_tree,
)
from rcmarl_tpu.ops.fit import fit_full_batch, fit_minibatch
from rcmarl_tpu.ops.losses import weighted_mse, weighted_sparse_ce
from rcmarl_tpu.ops.optim import AdamState, adam_update


class AgentParams(NamedTuple):
    """All agents' learnable state, every leaf with leading agent axis N.

    ``critic_local`` is the malicious agent's PRIVATE critic
    (adversarial_CAC_agents.py:99): trained on its own reward and used for
    its actor updates, while ``critic`` holds the compromised critic it
    transmits. For non-malicious agents ``critic_local`` is an unused
    mirror (kept dense for vmap-ability; tiny at these model sizes).
    """

    actor: MLPParams
    critic: MLPParams
    tr: MLPParams
    critic_local: MLPParams
    actor_opt: AdamState


class CellSpec(NamedTuple):
    """One experiment cell's behavioral knobs as TRACED data.

    The solo trainer specializes its program on ``Config`` at trace time
    (roles/H/common_reward are compile-time constants; absent roles cost
    nothing). This pytree is the alternative used by the fused-matrix
    path (:mod:`rcmarl_tpu.parallel.matrix`): every field is an array,
    so replicas with DIFFERENT scenarios — the reference's whole
    scenario x H experiment matrix (``simulation_results/raw_data``
    layout) — share ONE compiled program, vmapped over the cell axis.
    Heterogeneous behavior then costs compute-all-then-mask across roles,
    the trade SURVEY.md §7 endorses at these model sizes.

    coop/greedy/malicious: (N,) bool role masks (faulty = none of the
    three: it transmits frozen nets and needs no branch of its own).
    H: () int32 trim parameter. common_reward: () bool.
    """

    coop: jnp.ndarray
    greedy: jnp.ndarray
    malicious: jnp.ndarray
    H: jnp.ndarray
    common_reward: jnp.ndarray


class Batch(NamedTuple):
    """Fixed-capacity update batch (the replay window).

    s/ns: (B, N, n_states) scaled states; a: (B, N, 1) float actions;
    r: (B, N, 1) scaled rewards; mask: (B,) validity.
    """

    s: jnp.ndarray
    ns: jnp.ndarray
    a: jnp.ndarray
    r: jnp.ndarray
    mask: jnp.ndarray

    @property
    def sa(self) -> jnp.ndarray:
        """concat(s, a) on the feature axis (train_agents.py:93)."""
        return jnp.concatenate([self.s, self.a], axis=-1)


# --------------------------------------------------------------------------
# Phase I: local fits
# --------------------------------------------------------------------------


def coop_local_critic_fit(
    critic: MLPParams, s, ns, r, mask, cfg: Config
) -> Tuple[MLPParams, jnp.ndarray]:
    """Cooperative local critic fit -> transmitted message
    (resilient_CAC_agents.py:103-122): TD target computed ONCE with
    current weights, then ``coop_fit_steps`` full-batch SGD steps; the
    caller keeps the agent's own critic unchanged (restore semantics).
    Returns (message_params, first_step_loss) — the loss mirrors the
    reference's ``history['loss'][0]`` second return value."""
    target = r + cfg.gamma * mlp_forward(critic, ns, dtype=cfg.dot_dtype)
    target = jax.lax.stop_gradient(target)

    def loss(p):
        return weighted_mse(mlp_forward(p, s, dtype=cfg.dot_dtype), target, mask=mask)

    return fit_full_batch(critic, loss, cfg.coop_fit_steps, cfg.fast_lr)


def coop_local_tr_fit(
    tr: MLPParams, sa, r, mask, cfg: Config
) -> Tuple[MLPParams, jnp.ndarray]:
    """Cooperative local team-reward fit (resilient_CAC_agents.py:124-140):
    same 5-step full-batch SGD, target = local reward (no bootstrap).
    Returns (message_params, first_step_loss)."""

    def loss(p):
        return weighted_mse(mlp_forward(p, sa, dtype=cfg.dot_dtype), r, mask=mask)

    return fit_full_batch(tr, loss, cfg.coop_fit_steps, cfg.fast_lr)


def adv_critic_fit(
    key, critic: MLPParams, s, ns, r_target, mask, cfg: Config
) -> Tuple[MLPParams, jnp.ndarray]:
    """Adversary critic fit (greedy local / malicious local+compromised):
    TD target with pre-fit weights, then fit(epochs=10, batch_size=32)
    shuffled minibatch SGD (adversarial_CAC_agents.py:131-133,146-151,
    237-239). The update PERSISTS (no restore). Returns
    (params, first_epoch_mean_loss) — the reference's
    ``history['loss'][0]`` second return value."""
    target = r_target + cfg.gamma * mlp_forward(critic, ns, dtype=cfg.dot_dtype)
    target = jax.lax.stop_gradient(target)

    def batch_loss(p, idx, bval):
        return weighted_mse(mlp_forward(p, s[idx], dtype=cfg.dot_dtype), target[idx], mask=bval)

    out, _, loss = fit_minibatch(
        key,
        critic,
        batch_loss,
        capacity=s.shape[0],
        mask=mask,
        epochs=cfg.adv_fit_epochs,
        batch_size=cfg.adv_fit_batch,
        lr=cfg.fast_lr,
    )
    return out, loss


def adv_tr_fit(
    key, tr: MLPParams, sa, r_target, mask, cfg: Config
) -> Tuple[MLPParams, jnp.ndarray]:
    """Adversary team-reward fit: fit(epochs=10, batch_size=32) toward the
    (possibly compromised) reward (adversarial_CAC_agents.py:154-165,
    243-253). Returns (params, first_epoch_mean_loss)."""

    def batch_loss(p, idx, bval):
        return weighted_mse(mlp_forward(p, sa[idx], dtype=cfg.dot_dtype), r_target[idx], mask=bval)

    out, _, loss = fit_minibatch(
        key,
        tr,
        batch_loss,
        capacity=sa.shape[0],
        mask=mask,
        epochs=cfg.adv_fit_epochs,
        batch_size=cfg.adv_fit_batch,
        lr=cfg.fast_lr,
    )
    return out, loss


# --------------------------------------------------------------------------
# Phase II: resilient consensus (cooperative agents)
# --------------------------------------------------------------------------


def consensus_update_one(
    own: MLPParams,
    nbr_msgs: MLPParams,
    x: jnp.ndarray,
    mask: jnp.ndarray,
    cfg: Config,
    valid: jnp.ndarray | None = None,
    H=None,
) -> MLPParams:
    """Full Phase-II update for ONE cooperative agent's critic or TR net.

    Args:
      own: the agent's current net (pre-consensus; its head is the
        pre-phase-I head thanks to restore semantics).
      nbr_msgs: gathered neighbor messages, leaves (n_in, ...), own
        message at index 0 (in_nodes convention).
      x: (B, ...) the net's input batch (s for critic, sa for TR).
      valid: optional (n_in,) edge-validity mask when the graph has
        heterogeneous in-degrees and neighborhoods are padded (see
        :func:`rcmarl_tpu.ops.aggregation.resilient_aggregate`).

    Steps b-d of reference train_agents.py:125-145:
      b) hidden consensus (resilient_CAC_agents.py:142-166): clip-mean
         each trunk array over neighbors (trim bounds by dual
         top-(H+1) selection on the default impl — ops/aggregation.py);
         write trunk only.
      c) projection (resilient_CAC_agents.py:168-206): evaluate each
         neighbor's head on the agent's NEW trunk features over the whole
         batch; clip-mean over neighbors -> per-sample targets.
      d) team update (resilient_CAC_agents.py:60-84): one SGD step of the
         head (trunk frozen) toward the aggregated targets with weights
         1/(2*fast_lr*(||phi||^2+1)) — the paper's normalized projected
         update; with Keras MSE + SUM_OVER_BATCH_SIZE the fast_lr cancels.
    """
    n_trunk = len(own) - 1
    # traced H (the fused-matrix path) is XLA-only; the aggregation layer
    # resolves 'auto' to an impl that can lower and RAISES on an explicit
    # pallas choice rather than silently downgrading (ops/aggregation.py).
    # Both aggregation calls below carry everything the 3-way 'auto'
    # policy keys on — H (static here, traced on the matrix path), the
    # leading neighbor-axis size, and n_agents for the gathered volume —
    # so sort-vs-select-vs-pallas resolution happens at trace time with
    # no extra plumbing at this layer.
    H = cfg.H if H is None else H
    impl = cfg.consensus_impl
    # cfg.consensus_sanitize hardens BOTH aggregation calls against
    # non-finite neighbor payloads (transport faults, diverged peers):
    # bombs become exclusions, degree deficits keep the own value.
    sanitize = cfg.consensus_sanitize
    # b) hidden-layer consensus over trunk arrays: under the default
    # cfg.consensus_layout='flat' the whole trunk tree is raveled into
    # ONE (n_in, P_total) block, so the epoch issues a single
    # select/clip/mean op sequence per message tree instead of one per
    # weight array (bitwise identical either way).
    trunk_agg = resilient_aggregate_tree(
        tuple(nbr_msgs[i] for i in range(n_trunk)),
        H,
        impl,
        valid=valid,
        n_agents=cfg.n_agents,
        sanitize=sanitize,
        layout=cfg.consensus_layout,
    )
    new_params: MLPParams = tuple(trunk_agg) + (own[-1],)
    # c) projection: phi with aggregated trunk, all neighbor heads at once
    phi = trunk_forward(new_params, x, cfg.leaky_alpha, cfg.dot_dtype)  # (B, h)
    W_nbr, b_nbr = nbr_msgs[-1]  # (n_in, h, 1), (n_in, 1)
    proj = einsum("bh,nho->nbo", phi, W_nbr, dtype=cfg.dot_dtype)
    vals = proj + b_nbr[:, None, :]  # (n_in, B, 1)
    agg = resilient_aggregate(
        vals, H, impl, valid=valid, n_agents=cfg.n_agents, sanitize=sanitize
    )  # (B, 1)
    agg = jax.lax.stop_gradient(agg)
    # d) normalized team update of the head only
    new_head = team_head_update(new_params[-1], phi, agg, cfg, mask=mask)
    return tuple(trunk_agg) + (new_head,)


def team_head_update(head, phi, targets, cfg: Config, mask=None):
    """The paper's normalized projected head step (reference
    ``critic_update_team``/``TR_update_team``,
    ``resilient_CAC_agents.py:60-84``): one SGD step of the output layer
    on frozen trunk features ``phi`` toward the aggregated ``targets``,
    sample-weighted 1/(2*fast_lr*(||phi||^2+1)) — with Keras MSE's
    SUM_OVER_BATCH_SIZE reduction the fast_lr cancels."""
    phi_sg = jax.lax.stop_gradient(phi)
    phi_norm = jnp.sum(phi_sg**2, axis=1) + 1.0  # (B,)
    weights = 1.0 / (2.0 * cfg.fast_lr * phi_norm)

    def head_loss(head_params):
        pred = head_forward(head_params, phi_sg, cfg.dot_dtype)
        return weighted_mse(pred, targets, sample_weight=weights, mask=mask)

    g = jax.grad(head_loss)(head)
    return jax.tree.map(lambda p, gg: p - cfg.fast_lr * gg, head, g)


# --------------------------------------------------------------------------
# Phase III: actor updates
# --------------------------------------------------------------------------


def coop_actor_update(
    actor: MLPParams,
    opt: AdamState,
    critic: MLPParams,
    tr: MLPParams,
    s,
    ns,
    sa,
    a_own,
    cfg: Config,
) -> Tuple[MLPParams, AdamState]:
    """Cooperative actor step (resilient_CAC_agents.py:86-101): sample
    weights = team TD error r_bar(sa) + gamma*V(ns) - V(s) (own TR/critic,
    post-consensus), ONE full-batch Adam step of weighted sparse CE over
    the fresh on-policy window (always fully valid). Returns
    (new_actor, new_opt, pre_update_loss) — the loss mirrors the
    reference's ``train_on_batch`` return value."""
    delta = (
        mlp_forward(tr, sa, dtype=cfg.dot_dtype)
        + cfg.gamma * mlp_forward(critic, ns, dtype=cfg.dot_dtype)
        - mlp_forward(critic, s, dtype=cfg.dot_dtype)
    )
    delta = jax.lax.stop_gradient(delta[:, 0])  # (B,)

    def loss(p):
        return weighted_sparse_ce(
            actor_probs(p, s, cfg.leaky_alpha, cfg.dot_dtype), a_own, delta
        )

    loss_val, g = jax.value_and_grad(loss)(actor)
    new_actor, new_opt = adam_update(actor, g, opt, cfg.slow_lr)
    return new_actor, new_opt, loss_val


def adv_actor_update(
    key,
    actor: MLPParams,
    opt: AdamState,
    critic: MLPParams,
    s,
    ns,
    r_own,
    a_own,
    cfg: Config,
) -> Tuple[MLPParams, AdamState, jnp.ndarray]:
    """Adversary actor step (adversarial_CAC_agents.py:28-43,102-119,
    211-226): sample weights = LOCAL TD error from own reward and own
    critic (malicious: its private local critic), then
    fit(batch_size=200, epochs=1) = shuffled minibatch Adam steps.
    Returns (new_actor, new_opt, first_epoch_mean_loss)."""
    delta = (
        r_own
        + cfg.gamma * mlp_forward(critic, ns, dtype=cfg.dot_dtype)
        - mlp_forward(critic, s, dtype=cfg.dot_dtype)
    )
    delta = jax.lax.stop_gradient(delta[:, 0])  # (B,)
    B = s.shape[0]
    mask = jnp.ones((B,), jnp.float32)

    def batch_loss(p, idx, bval):
        return weighted_sparse_ce(
            actor_probs(p, s[idx], cfg.leaky_alpha, cfg.dot_dtype),
            a_own[idx], delta[idx], mask=bval,
        )

    return fit_minibatch(
        key,
        actor,
        batch_loss,
        capacity=B,
        mask=mask,
        epochs=1,
        batch_size=cfg.batch_size,
        opt_state=opt,
        opt_update=lambda p, g, s_: adam_update(p, g, s_, cfg.slow_lr),
    )


# --------------------------------------------------------------------------
# Role-masked select helpers
# --------------------------------------------------------------------------


def select_tree(pred_per_agent: jnp.ndarray, if_true, if_false):
    """Per-agent masked select over stacked pytrees: leaves (N, ...)."""

    def sel(a, b):
        shape = (-1,) + (1,) * (a.ndim - 1)
        return jnp.where(pred_per_agent.reshape(shape), a, b)

    return jax.tree.map(sel, if_true, if_false)
