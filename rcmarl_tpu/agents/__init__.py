from rcmarl_tpu.agents.reference_api import ReferenceRPBCACAgent  # noqa: F401
