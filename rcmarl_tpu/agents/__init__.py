from rcmarl_tpu.agents.reference_api import (  # noqa: F401
    ReferenceFaultyAgent,
    ReferenceGreedyAgent,
    ReferenceMaliciousAgent,
    ReferenceRPBCACAgent,
)
