"""Transport-fault injection for the consensus exchange.

The paper's threat model (RPBCAC, arXiv:2111.06776) is *behavioral*:
greedy / faulty / malicious neighbors that send well-formed but wrong
weights. Real decentralized training adds a second, *transport-level*
threat model — gossip links drop, replay stale payloads, or deliver
corrupted bytes (gossip actor-learners, arXiv:1906.04585; preemption-
tolerant Podracer pods, arXiv:2104.06272) — that the scripted
adversaries never exercise. This module makes those faults a
first-class, reproducible experiment knob:

- :class:`FaultPlan`: a frozen, hashable description of per-link fault
  probabilities. It lives inside :class:`~rcmarl_tpu.config.Config`
  (``cfg.fault_plan``), so a faulted run is as pinned and resumable as a
  clean one.
- :func:`apply_link_faults`: a pure PRNG-driven transform on the
  GATHERED neighbor block — leaves ``(N, n_in, ...)``, own payload at
  slot 0 — applied between the exchange and the aggregation
  (``training/update.py``). Because it only sees the post-gather block,
  it traces identically under vmap (per-agent and per-replica), the
  fused experiment matrix (traced :class:`CellSpec`), and both gather
  lowerings (rotation-symmetric rolls and the general advanced-index
  path).
- :func:`fault_diagnostics`: per-block counters (non-finite payload
  entries; elementwise degree-deficit events where fewer than ``2H+1``
  finite values survive) surfaced by the trainer instead of silently
  undefined clipping.
- :func:`tree_all_finite`: the trainer guard's per-block detector.

Fault semantics, per directed link = (receiving agent ``i``, neighbor
slot ``j >= 1``) — slot 0 is the agent's own payload and is NEVER
faulted (there is no transport hop to itself):

1. ``stale_p``   — the link replays the sender's stale pre-fit weights
                   (the epoch-carry nets) instead of the fresh message.
2. ``corrupt_p`` — additive Gaussian corruption of the payload
                   (scale ``corrupt_scale``), elementwise noise.
3. ``flip_p``    — sign-flip corruption (the whole payload negated).
4. ``drop_p``    — the link delivers nothing; the receiver sees a NaN
                   payload (with ``sanitize`` consensus the row is
                   excluded; without it, this is the NaN poisoning the
                   guard rails exist for).
5. ``nan_p`` / ``inf_p`` — adversarial payload bombs: all-NaN, or ±Inf
                   with a per-link random sign.

Stages compose in that order (a stale payload can still be corrupted
and then bombed), each drawn independently per link per epoch from a
dedicated fault stream (``jax.random.fold_in`` off the epoch key — the
clean run's RNG stream is untouched, so ``fault_plan=None`` reproduces
the seed behavior bit-for-bit).

jax is imported inside the functions that trace, not at module level,
so ``rcmarl_tpu.config`` (which owns a :class:`FaultPlan` field) stays
importable without pulling in jax — the CLI's fast ``--help`` path.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import NamedTuple, Tuple


@dataclass(frozen=True)
class FaultPlan:
    """Per-link transport-fault probabilities (see module docstring).

    Frozen + scalar fields only, so it is hashable and can live inside
    the jit-static :class:`~rcmarl_tpu.config.Config`. ``seed``
    namespaces the fault stream: two plans differing only in ``seed``
    draw independent fault patterns over the same training run.
    """

    drop_p: float = 0.0
    stale_p: float = 0.0
    corrupt_p: float = 0.0
    corrupt_scale: float = 1.0
    flip_p: float = 0.0
    nan_p: float = 0.0
    inf_p: float = 0.0
    seed: int = 0

    def __post_init__(self):
        for name in ("drop_p", "stale_p", "corrupt_p", "flip_p", "nan_p", "inf_p"):
            p = getattr(self, name)
            if not 0.0 <= float(p) <= 1.0:
                raise ValueError(f"FaultPlan.{name}={p} must be in [0, 1]")
        if not float(self.corrupt_scale) >= 0.0:
            raise ValueError(
                f"FaultPlan.corrupt_scale={self.corrupt_scale} must be >= 0"
            )

    @property
    def active(self) -> bool:
        """True when any fault can actually fire (an all-zero plan is a
        no-op and callers skip the transform entirely)."""
        return any(
            float(getattr(self, n)) > 0.0
            for n in ("drop_p", "stale_p", "corrupt_p", "flip_p", "nan_p", "inf_p")
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


#: Valid always-adversarial payload modes of a :class:`ReplicaFaultPlan`.
BYZANTINE_MODES = ("nan", "sign_flip", "inf")


@dataclass(frozen=True)
class ReplicaFaultPlan:
    """Replica-level gossip-link fault plan (:mod:`rcmarl_tpu.parallel.gossip`).

    The transport threat model of :class:`FaultPlan`, lifted one level
    up the stack: the links here are the REPLICA gossip graph's directed
    edges (receiving learner replica, sending learner replica), and the
    payloads are whole parameter trees exchanged at a gossip round
    instead of per-epoch consensus messages. The probabilistic fields
    have exactly the :class:`FaultPlan` semantics (same composition
    order, same per-link-per-round draws; ``stale_p`` replays the
    sender's LAST-ROUND post-mix parameters), and the fault chain is the
    same code (:func:`_fault_payload`), so the two threat models cannot
    drift apart.

    On top of the probabilistic links, ``byzantine_replicas`` names
    ALWAYS-adversarial replicas deterministically: every payload they
    send (never their own slot-0 row) is replaced according to
    ``byzantine_mode`` — ``'nan'`` (all-NaN bomb), ``'sign_flip'`` (the
    negation of their current parameters), or ``'inf'`` (+Inf bomb).
    This is the infra-level twin of the paper's H scripted adversaries:
    the trimmed-mean gossip mix must keep the healthy replicas training
    for any ≤ ``Config.gossip_H`` Byzantine replicas per neighborhood.

    Frozen + hashable (scalars and an int tuple), so it lives inside the
    jit-static :class:`~rcmarl_tpu.config.Config`
    (``cfg.replica_fault_plan``); ``None`` keeps the gossip exchange
    bitwise the fault-free behavior.
    """

    drop_p: float = 0.0
    stale_p: float = 0.0
    corrupt_p: float = 0.0
    corrupt_scale: float = 1.0
    flip_p: float = 0.0
    nan_p: float = 0.0
    inf_p: float = 0.0
    byzantine_replicas: Tuple[int, ...] = ()
    byzantine_mode: str = "nan"
    seed: int = 0

    def __post_init__(self):
        for name in ("drop_p", "stale_p", "corrupt_p", "flip_p", "nan_p", "inf_p"):
            p = getattr(self, name)
            if not 0.0 <= float(p) <= 1.0:
                raise ValueError(f"ReplicaFaultPlan.{name}={p} must be in [0, 1]")
        if not float(self.corrupt_scale) >= 0.0:
            raise ValueError(
                f"ReplicaFaultPlan.corrupt_scale={self.corrupt_scale} must be >= 0"
            )
        if self.byzantine_mode not in BYZANTINE_MODES:
            raise ValueError(
                f"ReplicaFaultPlan.byzantine_mode={self.byzantine_mode!r}: "
                f"expected one of {BYZANTINE_MODES}"
            )
        byz = tuple(self.byzantine_replicas)
        if any(int(b) < 0 for b in byz):
            raise ValueError(
                f"ReplicaFaultPlan.byzantine_replicas={byz} must be "
                "non-negative replica indices"
            )
        if len(set(byz)) != len(byz):
            raise ValueError(
                f"ReplicaFaultPlan.byzantine_replicas={byz} carries "
                "duplicate indices"
            )
        # normalize to a sorted tuple so plans that differ only in the
        # listing order hash (and trace) identically
        object.__setattr__(
            self, "byzantine_replicas", tuple(sorted(int(b) for b in byz))
        )

    @property
    def active(self) -> bool:
        """True when any fault can fire: a probabilistic link fault or a
        standing Byzantine replica."""
        return bool(self.byzantine_replicas) or any(
            float(getattr(self, n)) > 0.0
            for n in ("drop_p", "stale_p", "corrupt_p", "flip_p", "nan_p", "inf_p")
        )

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def apply_replica_faults(key, fresh, stale, plan: ReplicaFaultPlan, in_nodes):
    """Apply a :class:`ReplicaFaultPlan` to a gathered replica block.

    Args:
      key: PRNG key for this gossip round's fault draw. Derive it by
        ``fold_in`` from a dedicated gossip stream so the training
        replicas' RNG streams are untouched (the same discipline as
        :func:`apply_link_faults`; ``plan.seed`` is folded in here).
      fresh: the gathered parameter payloads, ``(R, n_in, P)`` — one
        raveled parameter vector per directed gossip link, own payload
        at slot 0.
      stale: the same gather over the LAST round's post-mix parameters
        (what a stale link replays); pass ``fresh`` again when
        ``stale_p == 0``.
      plan: the replica fault plan; an inactive plan returns ``fresh``
        unchanged (bitwise).
      in_nodes: the static replica gossip graph as nested tuples
        (``rcmarl_tpu.parallel.gossip.replica_in_nodes``) — maps each
        link back to its SENDER for the Byzantine mask.

    The probabilistic chain is :func:`_fault_payload` — identical
    composition order and key structure as the agent-level transform.
    Byzantine senders are applied LAST and deterministically: whatever
    the link drew, a payload from a ``byzantine_replicas`` member is the
    adversarial one (slot 0 exempt — a replica never attacks its own
    mix row).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    if not plan.active:
        return fresh
    shape = fresh.shape[:2]
    key = jax.random.fold_in(key, plan.seed)
    masks = _link_masks(key, plan, shape)
    v = _fault_payload(key, masks, 0, fresh, stale, plan)
    if plan.byzantine_replicas:
        in_arr = np.asarray(in_nodes)
        byz = np.isin(in_arr, np.asarray(plan.byzantine_replicas))
        byz[:, 0] = False  # own slot is never a transport hop
        bmask = jnp.asarray(byz)[:, :, None]
        if plan.byzantine_mode == "nan":
            v = jnp.where(bmask, jnp.nan, v)
        elif plan.byzantine_mode == "sign_flip":
            v = jnp.where(bmask, -v, v)
        else:  # 'inf'
            v = jnp.where(bmask, jnp.inf, v)
    return v


def adaptive_payload_tree(tree, coop_mask, adaptive_mask, scale):
    """Colluding omniscient-adversary payloads optimized against the
    trimmed mean (the ``Roles.ADAPTIVE`` label's message transform).

    For EVERY parameter coordinate, all colluding adversaries replace
    their transmitted message with the same crafted value::

        payload = mean_coop + scale * (max_coop - min_coop)

    computed over the CURRENT epoch's cooperative messages — the
    "little is enough" placement family: at small ``scale`` the payload
    sits at (or just past) the edge of the healthy values' spread, so
    an ``H``-trimming neighborhood clips it back to the cooperative
    range and the residual influence is bounded by the healthy spread
    itself; at large ``scale`` it is the unbounded coordinated-mean
    attack that an untrimmed (``H=0``) clip-and-average neighborhood
    has no defense against (its clip bounds are the min/max of the
    gathered block, which the adversaries themselves set). All
    adversaries transmitting the SAME payload is what makes the
    collusion maximal: their ≤H copies stack on one side of every
    coordinate's order statistics.

    Deterministic (no RNG) and computed from the messages alone, so it
    traces identically on both netstack arms and leaves the clean-run
    key streams untouched.

    Args:
      tree: the epoch's message pytree, leaves ``(N, ...)``.
      coop_mask / adaptive_mask: ``(N,)`` bools (disjoint).
      scale: the static payload magnitude (``Config.adaptive_scale``).

    Returns the tree with adaptive rows replaced; all other rows are
    bitwise untouched.
    """
    import jax
    import jax.numpy as jnp

    coop = jnp.asarray(coop_mask)
    adaptive = jnp.asarray(adaptive_mask)
    n_coop = jnp.maximum(jnp.sum(coop.astype(jnp.int32)), 1).astype(
        jnp.float32
    )

    def craft(leaf):
        m = coop.reshape((-1,) + (1,) * (leaf.ndim - 1))
        mean_c = jnp.sum(jnp.where(m, leaf, 0.0), axis=0) / n_coop
        max_c = jnp.max(jnp.where(m, leaf, -jnp.inf), axis=0)
        min_c = jnp.min(jnp.where(m, leaf, jnp.inf), axis=0)
        payload = mean_c + jnp.asarray(scale, leaf.dtype) * (max_c - min_c)
        a = adaptive.reshape((-1,) + (1,) * (leaf.ndim - 1))
        return jnp.where(a, payload[None], leaf)

    return jax.tree.map(craft, tree)


class FaultDiag(NamedTuple):
    """Per-block degradation counters (int32 scalars, summable across
    epochs/trees): ``nonfinite`` = NaN/±Inf payload entries seen in the
    gathered blocks; ``deficit`` = elementwise aggregation slots where
    fewer than ``2H+1`` finite values survive, i.e. where the sanitize
    kernel fell back to the agent's own value."""

    nonfinite: object
    deficit: object


def zero_diag() -> FaultDiag:
    import jax.numpy as jnp

    z = jnp.zeros((), jnp.int32)
    return FaultDiag(nonfinite=z, deficit=z)


def sum_diags(diags: FaultDiag) -> FaultDiag:
    """Collapse a stacked (e.g. per-epoch scanned) FaultDiag to scalars."""
    import jax.numpy as jnp

    return FaultDiag(
        nonfinite=jnp.sum(diags.nonfinite).astype(jnp.int32),
        deficit=jnp.sum(diags.deficit).astype(jnp.int32),
    )


def _link_masks(key, plan: FaultPlan, shape):
    """Draw the per-link (N, n_in) fault masks for one epoch. Slot 0
    (self) is structurally exempt from every fault."""
    import jax
    import jax.numpy as jnp

    k_drop, k_stale, k_cor, k_flip, k_nan, k_inf, k_sign = jax.random.split(key, 7)
    not_self = (jnp.arange(shape[1]) != 0)[None, :]

    def bern(k, p):
        if float(p) <= 0.0:
            return jnp.zeros(shape, bool)
        return jax.random.bernoulli(k, p, shape) & not_self

    inf_sign = jnp.where(
        jax.random.bernoulli(k_sign, 0.5, shape), jnp.inf, -jnp.inf
    )
    return {
        "drop": bern(k_drop, plan.drop_p),
        "stale": bern(k_stale, plan.stale_p),
        "corrupt": bern(k_cor, plan.corrupt_p),
        "flip": bern(k_flip, plan.flip_p),
        "nan": bern(k_nan, plan.nan_p),
        "inf": bern(k_inf, plan.inf_p),
        "inf_sign": inf_sign,
    }


def _fault_payload(key, masks, i, fresh, stale, plan: FaultPlan):
    """The per-payload fault chain shared by the tree and flat entry
    points: ``fresh``/``stale`` are one leaf's ``(N, n_in, ...)`` block,
    ``i`` its index in the ORIGINAL tree's flatten order (the corruption
    noise stream is keyed on it), ``masks`` the tree's per-link draws."""
    import jax
    import jax.numpy as jnp

    shape = fresh.shape[:2]

    def bcast(m, leaf):
        return m.reshape(shape + (1,) * (leaf.ndim - 2))

    v = fresh
    if float(plan.stale_p) > 0.0:
        v = jnp.where(bcast(masks["stale"], v), stale, v)
    if float(plan.corrupt_p) > 0.0:
        noise = jax.random.normal(
            jax.random.fold_in(key, i + 1), v.shape, v.dtype
        )
        v = jnp.where(
            bcast(masks["corrupt"], v),
            v + jnp.asarray(plan.corrupt_scale, v.dtype) * noise,
            v,
        )
    if float(plan.flip_p) > 0.0:
        v = jnp.where(bcast(masks["flip"], v), -v, v)
    if float(plan.drop_p) > 0.0 or float(plan.nan_p) > 0.0:
        bomb = masks["drop"] | masks["nan"]
        v = jnp.where(bcast(bomb, v), jnp.nan, v)
    if float(plan.inf_p) > 0.0:
        v = jnp.where(
            bcast(masks["inf"], v),
            bcast(masks["inf_sign"], v).astype(v.dtype),
            v,
        )
    return v


def apply_link_faults(key, fresh_tree, stale_tree, plan: FaultPlan):
    """Apply ``plan`` to a gathered neighbor-message pytree.

    Args:
      key: PRNG key for this (epoch, tree) fault draw. Derive it by
        ``fold_in`` from the epoch key so the clean-run stream is
        untouched (see module docstring).
      fresh_tree: gathered messages, leaves ``(N, n_in, ...)``, own
        payload at slot 0.
      stale_tree: the same gather over the sender's PRE-FIT weights
        (the epoch carry) — what a stale link replays. Pass
        ``fresh_tree`` again to disable replay content-wise.
      plan: the fault plan; an inactive plan returns ``fresh_tree``
        unchanged (bitwise).

    Returns the faulted pytree, same structure/shapes/dtypes. A fault
    hits a LINK: the same (agent, slot) draw applies to every leaf
    (whole payloads drop/replay/flip together), while additive
    corruption noise is drawn per element per leaf.
    """
    import jax
    import jax.numpy as jnp

    if not plan.active:
        return fresh_tree

    leaves = jax.tree.leaves(fresh_tree)
    if not leaves:
        return fresh_tree
    shape = leaves[0].shape[:2]  # (N, n_in), shared by every leaf
    key = jax.random.fold_in(key, plan.seed)
    masks = _link_masks(key, plan, shape)

    fresh_leaves, treedef = jax.tree.flatten(fresh_tree)
    stale_leaves = jax.tree.leaves(stale_tree)
    if len(stale_leaves) != len(fresh_leaves):
        raise ValueError(
            "fresh_tree and stale_tree must share a structure: "
            f"{len(fresh_leaves)} vs {len(stale_leaves)} leaves"
        )
    out = [
        _fault_payload(key, masks, i, f, s, plan)
        for i, (f, s) in enumerate(zip(fresh_leaves, stale_leaves))
    ]
    return jax.tree.unflatten(treedef, out)


def apply_link_faults_flat(key, fresh, stale, plan: FaultPlan, segments):
    """Apply ``plan`` to a COMBINED raveled gathered block (the netstack
    consensus layout: BOTH message trees as one ``(N, n_in, P_total)``
    array).

    Args:
      key: the epoch fault key (pre per-tree fold_in — this function
        derives ``fold_in(key, tree_id)`` itself, matching the dual
        arm's two ``apply_link_faults(fold_in(key, k), ...)`` calls).
      fresh/stale: the combined gathered block and its stale-replay
        twin, shapes ``(N, n_in, P_total)``.
      segments: static tuple of ``(tree_id, leaf_idx, offset, size)``
        mapping column ranges back to the original trees' leaves
        (``training/update.py`` derives it from the pair ravel order).

    Per-tree link masks and per-leaf corruption noise are drawn with
    EXACTLY the key structure of two separate :func:`apply_link_faults`
    calls — ``jax.random`` fills arrays in row-major counter order, so a
    ``(N, n_in, size)`` noise draw is bitwise the reshaped
    ``(N, n_in, *leaf_dims)`` draw — making the faulted combined block
    the exact ravel of the dual-arm faulted trees.
    """
    import jax
    import jax.numpy as jnp

    if not plan.active:
        return fresh
    if sum(s[3] for s in segments) != fresh.shape[-1]:
        raise ValueError(
            f"segments cover {sum(s[3] for s in segments)} columns but the "
            f"block has {fresh.shape[-1]}"
        )
    shape = fresh.shape[:2]
    tree_ids = sorted({t for t, *_ in segments})
    keys = {
        t: jax.random.fold_in(jax.random.fold_in(key, t), plan.seed)
        for t in tree_ids
    }
    masks = {t: _link_masks(keys[t], plan, shape) for t in tree_ids}
    cols = []
    for tree_id, leaf_idx, off, size in segments:
        cols.append(
            _fault_payload(
                keys[tree_id],
                masks[tree_id],
                leaf_idx,
                fresh[:, :, off : off + size],
                stale[:, :, off : off + size],
                plan,
            )
        )
    return jnp.concatenate(cols, axis=-1)


def fault_diagnostics(tree, H, valid=None) -> FaultDiag:
    """Count degradation events in a gathered neighbor block.

    ``nonfinite``: NaN/±Inf entries across all leaves (padded-invalid
    slots excluded when ``valid`` is given — pad garbage is not a
    fault). ``deficit``: elementwise slots where fewer than ``2H+1``
    finite values survive — exactly the condition under which the
    sanitize kernel keeps the agent's own value
    (:func:`rcmarl_tpu.ops.aggregation.resilient_aggregate`). ``H`` may
    be a traced scalar (the fused-matrix path); ``valid`` is the
    (N, n_in) or (n_in,) edge-validity mask of padded ragged graphs.
    """
    import jax
    import jax.numpy as jnp

    need = 2 * jnp.asarray(H, jnp.int32) + 1
    nonfinite = jnp.zeros((), jnp.int32)
    deficit = jnp.zeros((), jnp.int32)
    for leaf in jax.tree.leaves(tree):
        finite = jnp.isfinite(leaf)
        if valid is not None:
            vb = (jnp.asarray(valid) > 0).reshape(
                valid.shape + (1,) * (leaf.ndim - valid.ndim)
            )
            finite = finite & vb
            bad = ~finite & vb
        else:
            bad = ~finite
        nonfinite = nonfinite + jnp.sum(bad).astype(jnp.int32)
        count = jnp.sum(finite.astype(jnp.int32), axis=1)  # drop n_in axis
        deficit = deficit + jnp.sum(count < need).astype(jnp.int32)
    return FaultDiag(nonfinite=nonfinite, deficit=deficit)


# --------------------------------------------------------------------------
# The finite-predicate family — ONE contract, three granularities
# --------------------------------------------------------------------------
#
# Every health decision in the repo (trainer guard, gossip per-replica
# guard, serve/publish candidate gates, the chaos campaign's outcome
# classifier) reduces to the same question asked at one of three
# granularities, and the three predicates below share one contract so
# they can never drift apart (docs/api.md "finite-predicate family"):
#
# - FLOATING LEAVES ONLY: integer/bool leaves (actions, counters, RNG
#   keys, block indices) are vacuously finite and never inspected — a
#   predicate that looked at them would reject every healthy tree the
#   moment a uint32 key rode along.
# - FINITE means ``isfinite``: NaN AND ±Inf both fail (an Inf-bombed
#   tree is as unservable as a NaN one).
# - An all-non-floating tree is healthy by definition for the scalar
#   forms; the per-replica form REFUSES it loudly (an (R,)-verdict over
#   nothing would silently pass every replica).
#
# ``tree_all_finite`` is the traced form (safe inside jit, one fused
# reduction); ``params_finite`` the host-bool wrapper every swap chain
# gates on; ``tree_finite_per_replica`` the host-side factored form.


def _float_leaves(tree, xp):
    """The leaves the finite-predicate contract inspects: floating
    dtypes only, under either array namespace (``jnp`` for the traced
    predicate, ``np`` for the host-side ones)."""
    import jax

    return [
        l
        for l in jax.tree.leaves(tree)
        if xp.issubdtype(xp.asarray(l).dtype, xp.floating)
    ]


def tree_all_finite(tree):
    """() bool: every FLOATING leaf of ``tree`` is fully finite — the
    trainer guard's per-block health check (cheap: one fused
    reduction; traced-safe). See the family contract above."""
    import jax.numpy as jnp

    leaves = [jnp.all(jnp.isfinite(l)) for l in _float_leaves(tree, jnp)]
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack(leaves).all()


def params_finite(params) -> bool:
    """Host bool: :func:`tree_all_finite` fetched — THE publish/hot-swap
    candidate guard, shared by every chain that swaps a policy into a
    running consumer (the serving engine's constructor and checkpoint
    watcher, :mod:`rcmarl_tpu.serve`, and the pipeline's in-memory
    publisher, :mod:`rcmarl_tpu.pipeline.publish`). A poisoned-but-
    well-formed candidate (the transport threat model above, landed in
    a parameter tree) must be rejected BEFORE the swap, with the
    consumer kept on its last good tree. Host-syncing — callers that
    need block-free handoff only validate when a guard is active. Same
    contract as the family (floating leaves, NaN/±Inf both fail)."""
    return bool(tree_all_finite(params))


def tree_finite_per_replica(tree):
    """(R,) numpy bool: :func:`tree_all_finite` factored per LEADING index.

    Every floating leaf must carry a shared leading replica axis; entry
    ``r`` is True iff replica ``r``'s slice of every floating leaf is
    fully finite (the family contract above — non-floating leaves are
    never inspected, but an all-non-floating tree raises loudly: an
    (R,) verdict over nothing would silently pass every replica). This
    is the per-replica guard predicate of the gossip trainer
    (:mod:`rcmarl_tpu.parallel.gossip`): one poisoned replica rolls
    back alone instead of forcing a global rollback of the healthy
    ones. Computed HOST-SIDE on fetched leaves — the verdict feeds a
    host control decision anyway, and a plain device-to-host copy stays
    collective-free however the replica axis is sharded.
    """
    import numpy as np

    leaves = _float_leaves(tree, np)
    if not leaves:
        raise ValueError(
            "tree_finite_per_replica: no floating leaves to health-check"
        )
    oks = None
    for l in leaves:
        a = np.asarray(l)
        fin = np.isfinite(a.reshape(a.shape[0], -1)).all(axis=1)
        oks = fin if oks is None else (oks & fin)
    return oks
