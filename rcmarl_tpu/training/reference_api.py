"""Drop-in twin of the reference's training entry point.

``train_RPBCAC(env, agents, args, exp_buffer=None)`` is the reference's
only trainer API (``training/train_agents.py:15-184``); together with
the environment twin (:class:`rcmarl_tpu.envs.ReferenceGridWorld`) and
the four agent-object twins (:mod:`rcmarl_tpu.agents.reference_api`)
this completes the compat surface: the reference's ENTIRE program —
``main.py``'s wiring included — can run unchanged on this framework's
numerics.

Semantics mirrored exactly (SURVEY.md §3.2-§3.3): per-step ε-mixed
actions from each agent in node order (global-NumPy draws), growing
replay lists warm-startable via ``exp_buffer``, the
``i == n_ep_fixed-1 and j == max_ep_len`` update trigger, the
I→II→III→IV schedule with synchronous same-epoch weight exchange over
``in_nodes``, actor updates on the fresh ``max_ep_len * n_ep_fixed``
on-policy window, FIFO buffer trim AFTER updates, and the reference's
per-episode metrics row (True/adv/Estimated returns).

This path runs the object protocol eagerly — it exists for migration
fidelity and is golden-tested against the reference loop run under TF;
:func:`rcmarl_tpu.training.trainer.train` is the fused TPU path.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from rcmarl_tpu.models.mlp import mlp_forward

__all__ = ["train_RPBCAC"]


def train_RPBCAC(env, agents, args, exp_buffer=None):
    """Train a mixed cooperative/adversarial network of agent twins.

    Args:
      env: a :class:`~rcmarl_tpu.envs.ReferenceGridWorld` (or any object
        with the reference env protocol).
      agents: list of agent twins matching ``args['agent_label']``.
      args: the reference's parameter dict (``train_agents.py:28-33``
        reads n_states, gamma, in_nodes, max_ep_len, n_episodes,
        n_ep_fixed, n_epochs, batch_size, buffer_size, agent_label,
        common_reward).
      exp_buffer: optional (states, nstates, actions, rewards) lists to
        warm-start the replay buffer (``train_agents.py:36-40``).

    Returns:
      (weights, sim_data): per-agent ``get_parameters()`` lists and the
      reference-layout pandas DataFrame.
    """
    labels = args["agent_label"]
    n_agents = env.n_agents
    n_coop = labels.count("Cooperative")
    gamma = args["gamma"]
    in_nodes = args["in_nodes"]
    max_ep_len, n_episodes = args["max_ep_len"], args["n_episodes"]
    n_ep_fixed, n_epochs = args["n_ep_fixed"], args["n_epochs"]
    buffer_size = args["buffer_size"]
    common_reward = args.get("common_reward", False)
    verbose = args.get("verbose", True)

    if exp_buffer:
        states, nstates, actions, rewards = exp_buffer
    else:
        states, nstates, actions, rewards = [], [], [], []

    coop_idx = [i for i, l in enumerate(labels) if l == "Cooperative"]
    paths = []
    for t in range(n_episodes):
        i = t % n_ep_fixed
        env.reset()
        state, _ = env.get_data()
        # cooperative critics' value estimate at s0 (train_agents.py:60-62)
        est_returns = [
            float(mlp_forward(agents[node].critic, np.asarray(state)[None])[0, 0])
            for node in coop_idx
        ]

        ep_returns = np.zeros(n_agents)
        action = np.zeros(n_agents)
        actor_loss = np.zeros(n_agents)
        critic_loss = np.zeros(n_agents)
        tr_loss = np.zeros(n_agents)
        for j in range(max_ep_len):
            obs = np.asarray(state)[None]
            for node in range(n_agents):
                action[node] = agents[node].get_action(obs)
            env.step(action)
            nstate, reward = env.get_data()
            ep_returns = ep_returns + reward * (gamma**j)
            states.append(np.array(state))
            nstates.append(np.array(nstate))
            actions.append(np.array(action).reshape(-1, 1))
            rewards.append(np.array(reward).reshape(-1, 1))
            state = np.array(nstate)

        if i == n_ep_fixed - 1:
            s = np.asarray(states, np.float32)
            ns = np.asarray(nstates, np.float32)
            a = np.asarray(actions, np.float32)
            r = np.asarray(rewards, np.float32)
            sa = np.concatenate([s, a], axis=-1)
            # (T, 1) even with zero cooperative agents (the reference
            # builds tf.zeros and accumulates, train_agents.py:96-98)
            r_coop = np.zeros((r.shape[0], r.shape[2]), np.float32)
            for node in coop_idx:
                r_coop += r[:, node] / n_coop

            for _ in range(n_epochs):
                # I) local updates -> the transmitted messages
                critic_weights, tr_weights = [], []
                for node in range(n_agents):
                    ag, lab = agents[node], labels[node]
                    r_applied = r_coop if common_reward else r[:, node]
                    if lab == "Cooperative":
                        x, tr_loss[node] = ag.TR_update_local(sa, r_applied)
                        y, critic_loss[node] = ag.critic_update_local(
                            s, ns, r_applied
                        )
                    elif lab == "Greedy":
                        x, tr_loss[node] = ag.TR_update_local(sa, r[:, node])
                        y, critic_loss[node] = ag.critic_update_local(
                            s, ns, r[:, node]
                        )
                    elif lab == "Malicious":
                        ag.critic_update_local(s, ns, r[:, node])
                        x, tr_loss[node] = ag.TR_update_compromised(sa, -r_coop)
                        y, critic_loss[node] = ag.critic_update_compromised(
                            s, ns, -r_coop
                        )
                    else:  # Faulty: frozen messages
                        x = ag.get_TR_weights()
                        y = ag.get_critic_weights()
                    tr_weights.append(x)
                    critic_weights.append(y)
                # II) resilient consensus, cooperative agents only —
                # synchronous exchange of THIS epoch's messages
                for node in coop_idx:
                    ag = agents[node]
                    c_in = [critic_weights[k] for k in in_nodes[node]]
                    t_in = [tr_weights[k] for k in in_nodes[node]]
                    ag.resilient_consensus_critic_hidden(c_in)
                    ag.resilient_consensus_TR_hidden(t_in)
                    critic_agg = ag.resilient_consensus_critic(s, c_in)
                    tr_agg = ag.resilient_consensus_TR(sa, t_in)
                    ag.critic_update_team(s, critic_agg)
                    ag.TR_update_team(sa, tr_agg)

            # III) actor updates over the fresh on-policy window
            w = max_ep_len * n_ep_fixed
            for node in range(n_agents):
                if labels[node] == "Cooperative":
                    actor_loss[node] = agents[node].actor_update(
                        s[-w:], ns[-w:], sa[-w:], a[-w:, node]
                    )
                else:
                    actor_loss[node] = agents[node].actor_update(
                        s[-w:], ns[-w:], r[-w:, node], a[-w:, node]
                    )

            # IV) FIFO trim AFTER the updates (train_agents.py:158-163)
            if len(states) > buffer_size:
                q = len(states) - buffer_size
                del states[:q]
                del nstates[:q]
                del actions[:q]
                del rewards[:q]

        n_adv = n_agents - n_coop
        mean_true = sum(ep_returns[k] for k in coop_idx) / max(n_coop, 1)
        mean_true_adv = (
            sum(ep_returns[k] for k in range(n_agents) if k not in coop_idx)
            / n_adv
            if n_adv
            else 0.0
        )
        if verbose:
            print(
                f"| Episode: {t} | Est. returns: {est_returns} "
                f"| Returns: {mean_true} | Average critic loss: {critic_loss} "
                f"| Average TR loss: {tr_loss} | Average actor loss: {actor_loss} "
            )
        paths.append(
            {
                "True_team_returns": mean_true,
                "True_adv_returns": mean_true_adv,
                "Estimated_team_returns": float(np.mean(est_returns)),
            }
        )

    sim_data = pd.DataFrame.from_dict(paths)
    weights = [agent.get_parameters() for agent in agents]
    return weights, sim_data
