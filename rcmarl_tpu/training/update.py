"""The update block: phases I-III as one jitted XLA program.

Rebuild of the reference's update schedule (``train_agents.py:86-163``,
SURVEY.md §3.3): per epoch, (I) local critic/TR fits for every agent
produce the transmitted messages, (II) cooperative agents run resilient
consensus over their in-neighborhoods, then (III) once per block, actor
updates over the fresh on-policy window. The reference dispatches on
agent-label strings in Python loops; here heterogeneous behavior is
compute-per-role + masked select over stacked parameters, with role
composition STATIC (from Config) so absent roles are never traced.

Epoch-loop semantics preserved exactly (SURVEY.md §7 trap 2): consensus
inputs are the SAME epoch's phase-I messages (synchronous simultaneous
exchange); cooperative agents' own nets are restored after the local fit
(the local step produces the message, not a state change); hidden-layer
consensus mutates the trunk BEFORE the projection step evaluates neighbor
heads on it.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.agents.updates import (
    AgentParams,
    Batch,
    CellSpec,
    adv_actor_update,
    adv_critic_fit,
    adv_fit_schedule,
    adv_fused_row_block,
    adv_pair_fit,
    adv_tr_fit,
    consensus_pair_tail,
    consensus_update_one,
    consensus_update_pair,
    coop_actor_update,
    coop_fused_fit,
    coop_local_critic_fit,
    coop_local_tr_fit,
    coop_pair_fit,
    fused_fit_rows,
    netstack_pair_inputs,
    pair_bootstrap_targets,
    select_tree,
)
from rcmarl_tpu.config import FUSED_CONSENSUS_IMPLS, Config, Roles
from rcmarl_tpu.faults import (
    FaultDiag,
    adaptive_payload_tree,
    apply_link_faults,
    apply_link_faults_flat,
    fault_diagnostics,
    sum_diags,
    zero_diag,
)
from rcmarl_tpu.models.mlp import (
    init_stacked_mlp,
    mlp_forward,
    netstack_split,
    netstack_split_rows,
    netstack_stack,
)
from rcmarl_tpu.ops.aggregation import ravel_neighbor_tree
from rcmarl_tpu.ops.optim import adam_init

#: fold_in tag deriving the transport-fault stream from the epoch key —
#: a DEDICATED stream, so the clean run's split structure (and therefore
#: every golden-pinned trajectory) is untouched when fault_plan is None.
_FAULT_STREAM = 0xFA17

#: Per-tree fault sub-stream tags off the fault stream. These are the
#: SAME ids :func:`_pair_segments` stamps on the combined netstack
#: block's segments (``apply_link_faults_flat`` folds them internally),
#: so the dual-launch arm's two ``fold_in`` calls and the stacked arm's
#: one flat call draw bitwise-identical per-tree fault patterns.
_FAULT_TREE_CRITIC = 0
_FAULT_TREE_TR = 1


def init_agent_params(key: jax.Array, cfg: Config) -> AgentParams:
    """All-agent learnable state; each agent draws an independent
    Glorot-uniform init, as the reference builds N Keras models in a loop
    (``main.py:56-82``). ``critic_local`` (the malicious agent's private
    critic, ``adversarial_CAC_agents.py:99``) gets its own draw."""
    k_a, k_c, k_t, k_l = jax.random.split(key, 4)
    actor = init_stacked_mlp(k_a, cfg.n_agents, cfg.obs_dim, cfg.hidden, cfg.n_actions)
    critic = init_stacked_mlp(k_c, cfg.n_agents, cfg.obs_dim, cfg.hidden, 1)
    tr = init_stacked_mlp(k_t, cfg.n_agents, cfg.sa_dim, cfg.hidden, 1)
    critic_local = init_stacked_mlp(k_l, cfg.n_agents, cfg.obs_dim, cfg.hidden, 1)
    actor_opt = jax.vmap(adam_init)(actor)
    return AgentParams(actor, critic, tr, critic_local, actor_opt)


def _role_mask(cfg: Config, role: int) -> jnp.ndarray:
    return jnp.asarray(np.array(cfg.agent_roles) == role)


def netstack_enabled(cfg: Config) -> bool:
    """Resolve ``Config.netstack`` at trace time: explicit booleans pass
    through; ``'auto'`` is the measured backend policy — the stacked
    one-block epoch on TPU (the batching win the stacking buys), the
    dual-launch arm elsewhere (measured slower on a serial CPU host:
    the zero-padding FLOPs have no parallel headroom to hide in —
    PERF.md "netstack"). The one-kernel consensus arms consume the
    combined pair block, so they force the stacked epoch whatever the
    policy resolves to (Config rejects an explicit netstack=False with
    them) — bench/profile rows then honestly report the layout that
    actually ran."""
    if cfg.consensus_impl in FUSED_CONSENSUS_IMPLS:
        return True
    if cfg.netstack == "auto":
        return jax.default_backend() == "tpu"
    return bool(cfg.netstack)


def consensus_fused_impl(cfg: Config) -> "str | None":
    """Resolve the one-kernel-epoch arm at trace time: the concrete
    fused impl name when :attr:`Config.consensus_impl` names it AND the
    fault plan is kernel-compatible, else None.

    ``corrupt_p > 0`` plans return None — the documented fallback to
    the stacked XLA reference arm: the corruption noise draw's bits are
    fusion-context-dependent (the erfinv tail FMA-fuses into whatever
    consumes it) and the ``(N, n_in, P)`` noise is n_in-fold the block,
    so the kernel's traffic win is structurally halved there anyway
    (ops/pallas_consensus.py). Time-varying graphs are first-class:
    the scheduled ``(N, degree)`` indices ride the kernel as a
    scalar-prefetch operand (the SPARSE one-kernel epoch).
    """
    if cfg.consensus_impl not in FUSED_CONSENSUS_IMPLS:
        return None
    from rcmarl_tpu.ops.pallas_consensus import kernel_compatible_plan

    if not kernel_compatible_plan(cfg.fault_plan):
        return None
    return cfg.consensus_impl


def fitstack_enabled(cfg: Config) -> bool:
    """Resolve ``Config.fitstack`` at trace time: explicit booleans
    pass through; ``'auto'`` is the measured backend policy, exactly
    the ``netstack='auto'`` precedent — the cross-flavor fused fit
    scan on TPU (batching every same-scheduled flavor into one
    device-resident launch is the Podracer win the MXU-underfilling
    20-wide gemms are waiting for), the PR-4 per-flavor arms elsewhere
    (measured on the 1-core CPU host: the critic rows' sa_dim padding
    costs FLOPs a serial core cannot hide — PERF.md "fitstack /
    bf16"). Outputs are pinned leaf-for-leaf bitwise either way
    (tests/test_fitstack_properties.py), so the policy is purely a
    speed choice. The fit-scan kernel values ('pallas' /
    'pallas_interpret', config.FITSTACK_IMPLS) are truthy — they imply
    the fused row stacking and additionally route the scan through
    ops/pallas_fit (``agents.updates.fitstack_impl``)."""
    if cfg.fitstack == "auto":
        return jax.default_backend() == "tpu"
    return bool(cfg.fitstack)


def spec_from_config(cfg: Config) -> CellSpec:
    """The config's static role/H/common_reward knobs as a concrete
    :class:`CellSpec` pytree — the bridge between the solo trainer's
    trace-time specialization and the fused-matrix path (stack these
    across cells and vmap). The ADAPTIVE role has no spec mask (its
    payload crafting is a static-path feature), so adaptive casts are
    rejected here rather than silently degraded to Faulty."""
    if cfg.has_role(Roles.ADAPTIVE):
        raise ValueError(
            "the fused-matrix path (CellSpec) does not model the "
            "ADAPTIVE colluding adversary; run adaptive casts through "
            "the solo trainer / per-cell sweep"
        )
    return CellSpec(
        coop=_role_mask(cfg, Roles.COOPERATIVE),
        greedy=_role_mask(cfg, Roles.GREEDY),
        malicious=_role_mask(cfg, Roles.MALICIOUS),
        H=jnp.asarray(cfg.H, jnp.int32),
        common_reward=jnp.asarray(cfg.common_reward, bool),
        task_scale=jnp.asarray(1.0, jnp.float32),
    )


def gather_neighbor_messages(cfg: Config, tree, in_arr=None):
    """Stack each agent's in-neighborhood of messages: (N, ...) leaves ->
    (N, n_in, ...) leaves, own message at neighbor index 0.

    ``in_arr`` (optional) is a TRACED ``(N, degree)`` int32 index array
    — the time-varying communication graph
    (:func:`rcmarl_tpu.config.scheduled_in_nodes`): gather indices are
    data, not program structure, so per-block resampling re-dispatches
    one compiled program. ``None`` (default) compiles the static
    ``cfg.in_nodes`` topology exactly as always.

    This is the framework's "communication backend" (reference
    ``train_agents.py:129-130`` — list indexing of weight lists). Two
    static lowerings:

    - rotation-symmetric graphs (circulant / fully-connected,
      :attr:`Config.uniform_shifts`): ``n_in`` static rolls. Under an
      agent-sharded mesh each sharded roll becomes a ring
      collective-permute of only the halo rows — measured at N=64 deg 4
      over 8 shards: 6 halo rows moved per leaf vs 64 with the general
      path (PARALLELISM.md). Safe because aggregation is
      permutation-invariant past index 0 (its trim bounds are order
      statistics of the gathered block — dual top-(H+1) selection or a
      full sort, ops/aggregation.py).
    - arbitrary graphs: advanced indexing ``l[in_arr]`` (rows padded to
      max degree for ragged graphs), which XLA lowers to an all-gather
      of the full stacked params when sharded.
    """
    if in_arr is not None:
        # the sparse O(n·deg·P) mega-population exchange — ONE shared
        # primitive (ops/exchange.py) for both netstack arms, pinned
        # bitwise against the static gather on matching indices and
        # cost-gated sparse-below-dense in AUDIT.jsonl (lint --cost)
        from rcmarl_tpu.ops.exchange import sparse_gather

        return sparse_gather(tree, in_arr)
    shifts = cfg.uniform_shifts
    if shifts is not None:
        return jax.tree.map(
            lambda l: jnp.stack(
                [jnp.roll(l, -s, axis=0) for s in shifts], axis=1
            ),
            tree,
        )
    in_pad, _ = cfg.padded_in_nodes()
    in_arr = jnp.asarray(np.array(in_pad))  # (N, n_in)
    return jax.tree.map(lambda l: l[in_arr], tree)


def team_average_reward(
    cfg: Config, r: jnp.ndarray, spec: CellSpec | None = None
) -> jnp.ndarray:
    """r_coop: mean reward of cooperative agents (``train_agents.py:96-98``).

    r: (B, N, 1) -> (B, 1). With a ``spec`` the cooperative mask (and so
    the divisor) is traced data.
    """
    if spec is None:
        coop = jnp.asarray(cfg.coop_mask, jnp.float32)[None, :, None]
        return jnp.sum(r * coop, axis=1) / max(cfg.n_coop, 1)
    coop = spec.coop.astype(jnp.float32)[None, :, None]
    return jnp.sum(r * coop, axis=1) / jnp.maximum(jnp.sum(coop), 1.0)


def _phase1_fits_fused(
    cfg: Config,
    critic,
    tr,
    critic_local,
    batch: Batch,
    r_coop: jnp.ndarray,
    ekey: jax.Array,
    spec: CellSpec | None = None,
):
    """Phase I for EVERY role as at most two cross-flavor fused scans
    (``Config.fitstack``) — the rung above PR 4's per-flavor pair fits.

    All flavors sharing a schedule shape stack into one
    (flavor·net, agent) row block and launch as ONE
    :func:`~rcmarl_tpu.agents.updates.fused_fit_rows` scan:

    - full-batch group: cooperative critic + cooperative TR (2 rows),
      run through the unified minibatch body on the identity plan;
    - minibatch group: greedy critic/TR, malicious compromised
      critic/TR, and the malicious PRIVATE critic (up to 5 rows), each
      row drawing the valid-first shuffles from the dual arm's exact
      per-flavor keys.

    A homogeneous cast therefore launches exactly ONE scan for all its
    flavors; a mixed cast launches two (the shapes cannot share a
    launch without ruinous width padding). The critic's TD bootstrap
    V(ns) is computed once at the unpadded width and shared across the
    coop/greedy/malicious pair targets (the PR-4 netstack recipe);
    the private critic's own bootstrap runs once more on
    ``critic_local``. Returns ``(msg_critic, msg_tr, new_critic,
    new_tr, new_critic_local)`` — plain per-tree results, pinned
    leaf-for-leaf bitwise against both PR-4 phase-I arms
    (tests/test_fitstack_properties.py).
    """
    s, ns, sa, mask = batch.s, batch.ns, batch.sa, batch.mask
    r_agents = jnp.moveaxis(batch.r, 1, 0)  # (N, B, 1)
    N = cfg.n_agents
    traced = spec is not None
    in2 = (cfg.obs_dim, cfg.sa_dim)
    x2 = netstack_pair_inputs(cfg, s, sa)  # (2, B, sa_dim)

    has_coop = traced or bool(cfg.n_coop)
    has_greedy = traced or cfg.has_role(Roles.GREEDY)
    has_mal = traced or cfg.has_role(Roles.MALICIOUS)

    # the shared TD bootstrap with the PRE-FIT critic, once
    v_ns = None
    if has_coop or has_greedy or has_mal:
        v_ns = jax.vmap(lambda p: mlp_forward(p, ns, dtype=cfg.dot_dtype))(
            critic
        )

    def pair_targets(r):
        return pair_bootstrap_targets(cfg, critic, ns, r, v=v_ns)

    msg_critic, msg_tr = critic, tr  # Faulty default: transmit frozen nets
    new_critic, new_tr, new_critic_local = critic, tr, critic_local

    # ---- full-batch group: cooperative critic + TR
    if has_coop:
        r_team = jnp.broadcast_to(r_coop[None], (N, *r_coop.shape))
        if traced:
            r_applied = jnp.where(spec.common_reward, r_team, r_agents)
        elif cfg.common_reward:
            r_applied = r_team
        else:
            r_applied = r_agents
        coop2, _ = coop_fused_fit(
            critic, tr, x2, pair_targets(r_applied), mask, cfg
        )
        coop_c, coop_t = netstack_split(coop2, in2)
        m = spec.coop if traced else _role_mask(cfg, Roles.COOPERATIVE)
        msg_critic = select_tree(m, coop_c, msg_critic)
        msg_tr = select_tree(m, coop_t, msg_tr)
        # own nets restored (resilient_CAC_agents.py:120,138): new_* unchanged

    # ---- minibatch group: every adversary flavor in one row block
    # (adv_fused_row_block is the single source of truth for the rows,
    # shared with the consensus-micro profiler)
    block = adv_fused_row_block(
        cfg, critic, tr, critic_local, x2, ns, r_agents, r_coop,
        jax.random.split(ekey, 5), v_ns=v_ns,
        has_greedy=has_greedy, has_mal=has_mal,
    )
    if block is not None:
        keys_rows, params_rows, x_rows, targets_rows, in_dims = block
        fitted, _ = fused_fit_rows(
            keys_rows, params_rows, x_rows, targets_rows, mask,
            adv_fit_schedule(cfg), cfg,
        )
        parts = netstack_split_rows(fitted, in_dims)
        i = 0
        if has_greedy:
            g_c, g_t = parts[i], parts[i + 1]
            i += 2
            m = spec.greedy if traced else _role_mask(cfg, Roles.GREEDY)
            msg_critic = select_tree(m, g_c, msg_critic)
            msg_tr = select_tree(m, g_t, msg_tr)
            new_critic = select_tree(m, g_c, new_critic)  # persists
            new_tr = select_tree(m, g_t, new_tr)
        if has_mal:
            mal_c, mal_t, mal_local = parts[i], parts[i + 1], parts[i + 2]
            m = spec.malicious if traced else _role_mask(cfg, Roles.MALICIOUS)
            msg_critic = select_tree(m, mal_c, msg_critic)
            msg_tr = select_tree(m, mal_t, msg_tr)
            new_critic = select_tree(m, mal_c, new_critic)  # persists
            new_tr = select_tree(m, mal_t, new_tr)
            new_critic_local = select_tree(m, mal_local, new_critic_local)
    return msg_critic, msg_tr, new_critic, new_tr, new_critic_local


def _fit_block(cfg: Config, carry, batch: Batch, r_coop, ekey,
               spec: CellSpec | None = None):
    """The fused phase-I fit program over one carry
    ``(critic, tr, critic_local)`` — the standalone jitted form of
    :func:`_phase1_fits_fused` (registered in
    ``utils/profiling.py:jit_entry_points`` so the retrace/cost audits
    cover the fused arm at both compute dtypes)."""
    critic, tr, critic_local = carry
    return _phase1_fits_fused(
        cfg, critic, tr, critic_local, batch, r_coop, ekey, spec
    )


#: The fused cross-flavor fit scan as its own jitted entry point (the
#: consensus-micro profiler and the lint audits drive it standalone;
#: inside ``update_block`` the same program is inlined into the epoch).
fit_block = partial(jax.jit, static_argnums=0)(_fit_block)


def _consensus_block(cfg: Config, carry, batch: Batch, ekey: jax.Array,
                     graph=None):
    """The phase-II consensus as a standalone jitted program on the
    stacked pair layout: the carry nets double as the transmitted
    messages AND the stale-replay source (message content never changes
    the compiled program, so the cost/retrace view is exact). Runs
    whichever arm the config resolves — the one-kernel Pallas program
    or the stacked XLA reference — through the same
    :func:`_pair_phase2` the epoch inlines; registered in
    ``utils/profiling.py:jit_entry_points`` so the lint cost/retrace
    audits and ``profile --consensus_micro`` drive the fused phase II
    standalone (the one-kernel analogue of :data:`fit_block`).
    ``graph`` (optional traced ``(N, degree)`` int32) drives the
    scheduled sparse exchange — the sparse one-kernel arm or the
    ``sparse_gather`` XLA arm, per the resolved impl."""
    critic, tr, _ = carry
    x2 = netstack_pair_inputs(cfg, batch.s, batch.sa)
    cons_c, cons_t, _ = _pair_phase2(
        cfg, critic, tr, critic, tr, critic, tr, x2, batch.mask, ekey,
        graph=graph,
    )
    return cons_c, cons_t


#: Standalone jitted phase-II entry point (fused or XLA per config).
consensus_block = partial(jax.jit, static_argnums=0)(_consensus_block)


def critic_tr_epoch(
    cfg: Config,
    carry,
    batch: Batch,
    r_coop: jnp.ndarray,
    ekey: jax.Array,
    spec: CellSpec | None = None,
    with_diag: bool = False,
    graph=None,
):
    """One epoch of phases I+II over stacked params.

    carry = (critic, tr, critic_local), each leaf (N, ...).
    ``graph`` (optional traced ``(N, degree)`` int32) switches the
    phase-II exchange onto the time-varying communication graph —
    indices as data, regular by construction (no validity masking),
    the static topology otherwise untouched.

    Without ``spec``, role composition / H / common_reward come from the
    static Config and absent roles are never traced (the solo path).
    With a ``spec`` they are TRACED data: every role branch is computed
    and masked, so cells with different scenarios share one program (the
    fused-matrix path). Identical RNG stream structure in both modes —
    the epoch key is split the same way regardless of which branches
    run — so a spec replica reproduces its solo twin exactly.

    With ``cfg.fault_plan`` active, the gathered neighbor blocks pass
    through :func:`rcmarl_tpu.faults.apply_link_faults` between the
    exchange and the aggregation (the transport boundary); the fault
    stream is folded off ``ekey`` under a dedicated tag so the clean-run
    RNG is untouched. ``with_diag`` (static) additionally returns a
    :class:`~rcmarl_tpu.faults.FaultDiag` of degradation counters for
    this epoch.
    """
    if netstack_enabled(cfg):
        # True for the one-kernel consensus impls regardless of the
        # netstack policy: the fused epoch consumes the combined pair
        # block (netstack_enabled docstring; Config rejects
        # netstack=False with them)
        return _critic_tr_epoch_netstack(
            cfg, carry, batch, r_coop, ekey, spec, with_diag, graph
        )
    critic, tr, critic_local = carry
    s, ns, sa, mask = batch.s, batch.ns, batch.sa, batch.mask
    r_agents = jnp.moveaxis(batch.r, 1, 0)  # (N, B, 1) per-agent rewards
    N = cfg.n_agents
    traced = spec is not None

    # ---- Phase I: local fits -> messages (+ persisted adversary updates)
    if fitstack_enabled(cfg):
        # cross-flavor fused scans (Config.fitstack): phase I is
        # orthogonal to the consensus layout, so the dual phase II
        # below applies unchanged
        (
            msg_critic, msg_tr, new_critic, new_tr, new_critic_local,
        ) = _phase1_fits_fused(
            cfg, critic, tr, critic_local, batch, r_coop, ekey, spec
        )
    else:
        msg_critic, msg_tr = critic, tr  # Faulty default: frozen nets
        new_critic, new_tr, new_critic_local = critic, tr, critic_local

        if traced or cfg.n_coop:
            # common_reward applies to cooperative local fits ONLY
            # (train_agents.py:106)
            r_team = jnp.broadcast_to(r_coop[None], (N, *r_coop.shape))
            if traced:
                r_applied = jnp.where(spec.common_reward, r_team, r_agents)
            elif cfg.common_reward:
                r_applied = r_team
            else:
                r_applied = r_agents
            coop_c, _ = jax.vmap(
                lambda p, r: coop_local_critic_fit(p, s, ns, r, mask, cfg)
            )(critic, r_applied)
            coop_t, _ = jax.vmap(
                lambda p, r: coop_local_tr_fit(p, sa, r, mask, cfg)
            )(tr, r_applied)
            m = spec.coop if traced else _role_mask(cfg, Roles.COOPERATIVE)
            msg_critic = select_tree(m, coop_c, msg_critic)
            msg_tr = select_tree(m, coop_t, msg_tr)
            # own nets restored (resilient_CAC_agents.py:120,138):
            # new_* unchanged

        k_gc, k_gt, k_ml, k_mc, k_mt = jax.random.split(ekey, 5)

        if traced or cfg.has_role(Roles.GREEDY):
            greedy_c, _ = jax.vmap(
                lambda k, p, r: adv_critic_fit(k, p, s, ns, r, mask, cfg)
            )(jax.random.split(k_gc, N), critic, r_agents)
            greedy_t, _ = jax.vmap(
                lambda k, p, r: adv_tr_fit(k, p, sa, r, mask, cfg)
            )(jax.random.split(k_gt, N), tr, r_agents)
            m = spec.greedy if traced else _role_mask(cfg, Roles.GREEDY)
            msg_critic = select_tree(m, greedy_c, msg_critic)
            msg_tr = select_tree(m, greedy_t, msg_tr)
            new_critic = select_tree(m, greedy_c, new_critic)  # persists
            new_tr = select_tree(m, greedy_t, new_tr)

        if traced or cfg.has_role(Roles.MALICIOUS):
            # private critic on own reward (adversarial_CAC_agents.py:137-152)
            mal_local, _ = jax.vmap(
                lambda k, p, r: adv_critic_fit(k, p, s, ns, r, mask, cfg)
            )(jax.random.split(k_ml, N), critic_local, r_agents)
            # compromised critic/TR toward -r_coop (adversarial:121-135,154-165)
            neg = jnp.broadcast_to(-r_coop[None], (N, *r_coop.shape))
            mal_c, _ = jax.vmap(
                lambda k, p, r: adv_critic_fit(k, p, s, ns, r, mask, cfg)
            )(jax.random.split(k_mc, N), critic, neg)
            mal_t, _ = jax.vmap(
                lambda k, p, r: adv_tr_fit(k, p, sa, r, mask, cfg)
            )(jax.random.split(k_mt, N), tr, neg)
            m = spec.malicious if traced else _role_mask(cfg, Roles.MALICIOUS)
            msg_critic = select_tree(m, mal_c, msg_critic)
            msg_tr = select_tree(m, mal_t, msg_tr)
            new_critic = select_tree(m, mal_c, new_critic)  # persists
            new_tr = select_tree(m, mal_t, new_tr)
            new_critic_local = select_tree(m, mal_local, new_critic_local)

    # ---- Phase II: resilient consensus, cooperative agents only
    diag = zero_diag() if with_diag else None
    if traced or cfg.n_coop:
        # Adaptive colluding adversaries (Roles.ADAPTIVE) replace their
        # transmitted messages with a payload crafted from THIS epoch's
        # cooperative messages against the trimmed mean (omniscient
        # collusion — rcmarl_tpu.faults.adaptive_payload_tree). Static
        # path only: the fused-matrix spec has no adaptive mask
        # (spec_from_config rejects the role).
        if not traced and cfg.has_role(Roles.ADAPTIVE):
            amask = _role_mask(cfg, Roles.ADAPTIVE)
            cmask = _role_mask(cfg, Roles.COOPERATIVE)
            msg_critic = adaptive_payload_tree(
                msg_critic, cmask, amask, cfg.adaptive_scale
            )
            msg_tr = adaptive_payload_tree(
                msg_tr, cmask, amask, cfg.adaptive_scale
            )
        # Heterogeneous in-degree graphs (reference main.py:28 accepts
        # arbitrary adjacency lists): rows padded to max degree with the
        # agent's own index; padded slots masked out of the aggregation.
        # (The fused-matrix path requires a uniform graph: traced H and
        # the padded-validity mask are mutually exclusive. A time-
        # varying graph is regular by construction: no masking.)
        _, valid_pad = cfg.padded_in_nodes()
        if graph is not None:
            valid_pad = None
        H = spec.H if traced else None
        nbr_c = gather_neighbor_messages(cfg, msg_critic, graph)  # (N, n_in, ...)
        nbr_t = gather_neighbor_messages(cfg, msg_tr, graph)
        plan = cfg.fault_plan
        if plan is not None and plan.active:
            # Transport boundary: fault the gathered blocks. A stale
            # link replays the sender's PRE-FIT epoch-carry weights —
            # gather the carry nets as the replay payload, but ONLY when
            # the stale branch can actually fire: a drop/NaN-only plan
            # must not pay a second full gather for replay content that
            # is never read. Pure PRNG transform on (N, n_in, ...)
            # blocks, so it traces the same under vmap, the fused
            # matrix, and both gather lowerings.
            fkey = jax.random.fold_in(ekey, _FAULT_STREAM)
            if float(plan.stale_p) > 0.0:
                stale_c = gather_neighbor_messages(cfg, critic, graph)
                stale_t = gather_neighbor_messages(cfg, tr, graph)
            else:
                stale_c, stale_t = nbr_c, nbr_t
            nbr_c = apply_link_faults(
                jax.random.fold_in(fkey, _FAULT_TREE_CRITIC), nbr_c,
                stale_c, plan,
            )
            nbr_t = apply_link_faults(
                jax.random.fold_in(fkey, _FAULT_TREE_TR), nbr_t,
                stale_t, plan,
            )
        if with_diag:
            H_diag = H if traced else cfg.H
            valid_diag = (
                None if valid_pad is None else jnp.asarray(np.array(valid_pad))
            )
            d_c = fault_diagnostics(nbr_c, H_diag, valid_diag)
            d_t = fault_diagnostics(nbr_t, H_diag, valid_diag)
            diag = FaultDiag(
                nonfinite=d_c.nonfinite + d_t.nonfinite,
                deficit=d_c.deficit + d_t.deficit,
            )
        if valid_pad is None:
            cons = jax.vmap(
                lambda own, nbr, x: consensus_update_one(
                    own, nbr, x, mask, cfg, H=H
                ),
                in_axes=(0, 0, None),
            )
        else:
            if traced:
                raise ValueError(
                    "the fused-matrix path (traced CellSpec) requires a "
                    "uniform-degree graph; this config pads ragged "
                    "neighborhoods"
                )
            valid_arr = jnp.asarray(np.array(valid_pad))  # (N, n_in)
            cons_v = jax.vmap(
                lambda own, nbr, x, v: consensus_update_one(
                    own, nbr, x, mask, cfg, valid=v
                ),
                in_axes=(0, 0, None, 0),
            )
            cons = lambda own, nbr, x: cons_v(own, nbr, x, valid_arr)
        m = spec.coop if traced else _role_mask(cfg, Roles.COOPERATIVE)
        new_critic = select_tree(m, cons(new_critic, nbr_c, s), new_critic)
        new_tr = select_tree(m, cons(new_tr, nbr_t, sa), new_tr)

    if with_diag:
        return (new_critic, new_tr, new_critic_local), diag
    return new_critic, new_tr, new_critic_local


def _pair_segments(msg_c, msg_t):
    """Static ``(tree_id, leaf_idx, offset, size)`` rows mapping the
    trunks-first pair ravel (``((trunk_c, trunk_t), (head_c, head_t))``)
    back to the two original trees' leaves — what
    :func:`~rcmarl_tpu.faults.apply_link_faults_flat` needs to draw the
    dual-arm fault streams on the combined block. Leaf sizes strip the
    leading agent axis (the gathered block is (N, n_in, P_total))."""
    lc, lt = jax.tree.leaves(msg_c), jax.tree.leaves(msg_t)
    C, T = _FAULT_TREE_CRITIC, _FAULT_TREE_TR
    order = (
        [(C, i) for i in range(len(lc) - 2)]
        + [(T, i) for i in range(len(lt) - 2)]
        + [(C, len(lc) - 2), (C, len(lc) - 1)]
        + [(T, len(lt) - 2), (T, len(lt) - 1)]
    )
    segs, off = [], 0
    for t, i in order:
        size = int(np.prod((lc, lt)[t][i].shape[1:], dtype=np.int64))
        segs.append((t, i, off, size))
        off += size
    return tuple(segs)


def _pair_trunk_split(segments):
    """(n_trunk, tree_split) of a :func:`_pair_segments` tuple: the
    column where the four head rows begin (the kernel/tail boundary)
    and the column where the TR trunk begins (the per-tree fault-mask
    boundary; equals ``n_trunk`` for head-only nets). THE one place
    that owns the 'four head segments last' layout invariant — the
    fused epoch, the cost-gate programs, and the tests all read it
    here."""
    n_trunk = segments[-4][2]
    split = next(
        (off for t, _, off, _ in segments[:-4] if t == _FAULT_TREE_TR),
        n_trunk,
    )
    return n_trunk, split


def _pair_block(msg_c, msg_t):
    """Ravel the two message trees into ONE (N, P_critic + P_tr) block,
    columns trunks-first (the layout
    :func:`~rcmarl_tpu.agents.updates.consensus_update_pair` slices)."""
    pair = ((msg_c[:-1], msg_t[:-1]), (msg_c[-1], msg_t[-1]))
    flat, _ = ravel_neighbor_tree(pair)
    return flat


def _pair_phase2(
    cfg: Config,
    own_c,
    own_t,
    msg_c,
    msg_t,
    carry_c,
    carry_t,
    x2,
    mask,
    ekey: jax.Array,
    spec: CellSpec | None = None,
    with_diag: bool = False,
    graph=None,
):
    """Phase II on the combined pair layout, for ALL agents: gather ->
    transport faults -> trunk consensus -> projection -> team head
    step, returning ``(cons_c, cons_t, diag)`` (role masking stays with
    the caller). TWO arms share this entry:

    - the stacked XLA arm (the bitwise reference): one combined
      ``(N, n_in, P_critic + P_tr)`` gathered block through
      ``apply_link_faults_flat`` and the vmapped
      :func:`~rcmarl_tpu.agents.updates.consensus_update_pair`;
    - the ONE-KERNEL arm (``consensus_impl='pallas_fused'`` /
      ``'..._interpret'``, resolved by :func:`consensus_fused_impl`):
      the trunk columns never materialize a gathered block — the
      VMEM-resident kernel
      (:func:`rcmarl_tpu.ops.pallas_consensus.fused_pair_consensus`)
      reads the stacked messages once and emits the post-consensus
      trunk tile; only the tiny ``2(h+1)``-column head block is
      gathered and faulted XLA-side (bitwise: per-segment fault streams
      are independent, and gather commutes with the column slice), and
      the projection/head tail runs as XLA with ``impl='xla'``
      (:func:`~rcmarl_tpu.agents.updates.consensus_pair_tail`).

    ``with_diag`` on the fused arm materializes the gathered block ONCE
    for the fault counters alone — the guarded trainer is a diagnostic
    mode and pays the reference arm's gather traffic for its per-link
    view; the hot path never does.

    Also the body of the standalone :data:`consensus_block` entry point
    (the lint cost/retrace arms and ``profile --consensus_micro`` drive
    the exact phase-II program of the active arm through it).
    """
    traced = spec is not None
    _, valid_pad = cfg.padded_in_nodes()
    if graph is not None:
        valid_pad = None  # time-varying graphs are regular
    if traced and valid_pad is not None:
        raise ValueError(
            "the fused-matrix path (traced CellSpec) requires a "
            "uniform-degree graph; this config pads ragged "
            "neighborhoods"
        )
    H = spec.H if traced else None
    plan = cfg.fault_plan
    active = plan is not None and plan.active
    diag = zero_diag() if with_diag else None
    fused = consensus_fused_impl(cfg)
    fused_family = cfg.consensus_impl in FUSED_CONSENSUS_IMPLS
    valid_arr = (
        None if valid_pad is None else jnp.asarray(np.array(valid_pad))
    )

    def xla_gathered_block():
        """The reference arm's faulted gathered block (also the fused
        arm's diagnostics-only view)."""
        nbr = gather_neighbor_messages(cfg, _pair_block(msg_c, msg_t), graph)
        if active:
            fkey = jax.random.fold_in(ekey, _FAULT_STREAM)
            if float(plan.stale_p) > 0.0:
                stale = gather_neighbor_messages(
                    cfg, _pair_block(carry_c, carry_t), graph
                )
            else:
                stale = nbr
            nbr = apply_link_faults_flat(
                fkey, nbr, stale, plan, _pair_segments(msg_c, msg_t)
            )
        return nbr

    if fused is not None:
        from rcmarl_tpu.ops.pallas_consensus import (
            draw_fault_fields,
            fused_pair_consensus,
            head_segments,
        )

        segs = _pair_segments(msg_c, msg_t)
        n_trunk, split = _pair_trunk_split(segs)
        pair = _pair_block(msg_c, msg_t)
        if graph is None:
            in_src, _ = cfg.padded_in_nodes()
            n_link = cfg.n_in
        else:
            # the SPARSE one-kernel epoch: the scheduled (N, degree)
            # indices ride the kernel as a scalar-prefetch operand;
            # the fault draw's link axis is the scheduled degree —
            # exactly the gathered width apply_link_faults_flat draws
            # on in the XLA sparse arm, so the arms stay bitwise
            in_src = graph
            n_link = cfg.resolved_graph_degree
        fkey = fields = stale_pair = None
        if active:
            fkey = jax.random.fold_in(ekey, _FAULT_STREAM)
            fields = draw_fault_fields(
                fkey, plan, cfg.n_agents, n_link, segs
            )
            if float(plan.stale_p) > 0.0:
                stale_pair = _pair_block(carry_c, carry_t)
        H_k = H if traced else cfg.H
        agg = None
        if n_trunk:
            agg = fused_pair_consensus(
                pair[:, :n_trunk],
                H_k,
                in_nodes=in_src,
                tree_split=split,
                valid=valid_pad,
                sanitize=cfg.consensus_sanitize,
                plan=plan if active else None,
                stale=None if stale_pair is None else stale_pair[:, :n_trunk],
                fields=fields,
                interpret=fused == "pallas_fused_interpret",
            )
        head = gather_neighbor_messages(cfg, pair[:, n_trunk:], graph)
        if active:
            stale_head = (
                head
                if stale_pair is None
                else gather_neighbor_messages(
                    cfg, stale_pair[:, n_trunk:], graph
                )
            )
            head = apply_link_faults_flat(
                fkey, head, stale_head, plan, head_segments(segs, n_trunk)
            )
        if with_diag:
            diag = fault_diagnostics(
                xla_gathered_block(), H if traced else cfg.H, valid_arr
            )
        if valid_pad is None:
            cons = jax.vmap(
                lambda oc, ot, at, hb: consensus_pair_tail(
                    oc, ot, at, hb, x2, mask, cfg, H=H, impl="xla"
                ),
                in_axes=(0, 0, None if agg is None else 0, 0),
            )
        else:
            cons_v = jax.vmap(
                lambda oc, ot, at, hb, va: consensus_pair_tail(
                    oc, ot, at, hb, x2, mask, cfg, valid=va, H=H, impl="xla"
                ),
                in_axes=(0, 0, None if agg is None else 0, 0, 0),
            )
            cons = lambda oc, ot, at, hb: cons_v(oc, ot, at, hb, valid_arr)
        cons_c, cons_t = cons(own_c, own_t, agg, head)
        return cons_c, cons_t, diag

    nbr = xla_gathered_block()
    if with_diag:
        diag = fault_diagnostics(nbr, H if traced else cfg.H, valid_arr)
    # the fused-family fallback (corrupt_p > 0) stays on the stacked
    # XLA reference arm explicitly, whatever name the config carries
    impl_override = "xla" if fused_family else None
    if valid_pad is None:
        cons = jax.vmap(
            lambda oc, ot, blk: consensus_update_pair(
                oc, ot, blk, x2, mask, cfg, H=H, impl=impl_override
            ),
            in_axes=(0, 0, 0),
        )
    else:
        cons_v = jax.vmap(
            lambda oc, ot, blk, v: consensus_update_pair(
                oc, ot, blk, x2, mask, cfg, valid=v, H=H, impl=impl_override
            ),
            in_axes=(0, 0, 0, 0),
        )
        cons = lambda oc, ot, blk: cons_v(oc, ot, blk, valid_arr)
    cons_c, cons_t = cons(own_c, own_t, nbr)
    return cons_c, cons_t, diag


def _critic_tr_epoch_netstack(
    cfg: Config,
    carry,
    batch: Batch,
    r_coop: jnp.ndarray,
    ekey: jax.Array,
    spec: CellSpec | None,
    with_diag: bool,
    graph=None,
):
    """The netstack twin of :func:`critic_tr_epoch` (``cfg.netstack``;
    on TPU under the default ``'auto'`` policy): identical math and RNG
    stream structure, but every hot launch happens ONCE for the
    critic+TR pair instead of twice —

    - phase I: each fit flavor is one (net, agent)-vmapped scan over the
      stacked parameter block (:func:`coop_pair_fit` /
      :func:`adv_pair_fit`; the malicious PRIVATE critic fit stays
      unpaired — it has no TR twin);
    - phase II: both message trees ravel into one
      (N, P_critic + P_tr) block, so the neighbor gather, the
      transport-fault transform, the trim/clip/mean, the projection
      einsum, and the team head step each launch once
      (:func:`consensus_update_pair`).

    Outputs are pinned equivalent to the dual-launch arm leaf for leaf
    (tests/test_netstack.py); the zero-padding that makes the two net
    families stackable is exactly gradient-neutral
    (tests/test_netstack_properties.py).
    """
    critic, tr, critic_local = carry
    s, ns, sa, mask = batch.s, batch.ns, batch.sa, batch.mask
    r_agents = jnp.moveaxis(batch.r, 1, 0)  # (N, B, 1) per-agent rewards
    N = cfg.n_agents
    traced = spec is not None
    in_dims = (cfg.obs_dim, cfg.sa_dim)

    x2 = netstack_pair_inputs(cfg, s, sa)

    # ---- Phase I: local fits -> messages (+ persisted adversary updates)
    if fitstack_enabled(cfg):
        # cross-flavor fused scans (Config.fitstack): same fused phase I
        # as the dual epoch; phase II below still runs on the combined
        # netstack block
        (
            msg_c, msg_t, new_critic, new_tr, new_critic_local,
        ) = _phase1_fits_fused(
            cfg, critic, tr, critic_local, batch, r_coop, ekey, spec
        )
    else:
        stack2 = netstack_stack(critic, tr)  # leaves (2, N, ...)
        # The critic's TD bootstrap V(ns) with the pre-fit weights,
        # computed ONCE at the unpadded width and reused by every fit
        # pair below (the dual arm recomputes the identical forward
        # inside each flavor).
        v_ns = None
        if traced or cfg.n_coop or cfg.has_role(Roles.GREEDY) or cfg.has_role(
            Roles.MALICIOUS
        ):
            v_ns = jax.vmap(lambda p: mlp_forward(p, ns, dtype=cfg.dot_dtype))(
                critic
            )

        def targets2(r):
            return pair_bootstrap_targets(cfg, critic, ns, r, v=v_ns)

        msg2 = stack2  # Faulty default: transmit frozen nets
        new2, new_critic_local = stack2, critic_local

        if traced or cfg.n_coop:
            r_team = jnp.broadcast_to(r_coop[None], (N, *r_coop.shape))
            if traced:
                r_applied = jnp.where(spec.common_reward, r_team, r_agents)
            elif cfg.common_reward:
                r_applied = r_team
            else:
                r_applied = r_agents
            coop2, _ = coop_pair_fit(stack2, x2, targets2(r_applied), mask, cfg)
            m = spec.coop if traced else _role_mask(cfg, Roles.COOPERATIVE)
            msg2 = select_tree(m, coop2, msg2, axis=1)
            # own nets restored (resilient_CAC_agents.py:120,138): new2 unchanged

        k_gc, k_gt, k_ml, k_mc, k_mt = jax.random.split(ekey, 5)

        if traced or cfg.has_role(Roles.GREEDY):
            keys2 = jnp.stack(
                [jax.random.split(k_gc, N), jax.random.split(k_gt, N)]
            )
            greedy2, _ = adv_pair_fit(
                keys2, stack2, x2, targets2(r_agents), mask, cfg
            )
            m = spec.greedy if traced else _role_mask(cfg, Roles.GREEDY)
            msg2 = select_tree(m, greedy2, msg2, axis=1)
            new2 = select_tree(m, greedy2, new2, axis=1)  # persists

        if traced or cfg.has_role(Roles.MALICIOUS):
            # private critic on own reward (adversarial_CAC_agents.py:137-152)
            mal_local, _ = jax.vmap(
                lambda k, p, r: adv_critic_fit(k, p, s, ns, r, mask, cfg)
            )(jax.random.split(k_ml, N), critic_local, r_agents)
            # compromised critic/TR toward -r_coop (adversarial:121-135,154-165)
            neg = jnp.broadcast_to(-r_coop[None], (N, *r_coop.shape))
            keys2 = jnp.stack(
                [jax.random.split(k_mc, N), jax.random.split(k_mt, N)]
            )
            mal2, _ = adv_pair_fit(keys2, stack2, x2, targets2(neg), mask, cfg)
            m = spec.malicious if traced else _role_mask(cfg, Roles.MALICIOUS)
            msg2 = select_tree(m, mal2, msg2, axis=1)
            new2 = select_tree(m, mal2, new2, axis=1)  # persists
            new_critic_local = select_tree(m, mal_local, new_critic_local)

        new_critic, new_tr = netstack_split(new2, in_dims)
        msg_c, msg_t = netstack_split(msg2, in_dims)

    # ---- Phase II: resilient consensus, cooperative agents only — on
    # ONE combined (N, n_in, P_critic + P_tr) gathered block
    diag = zero_diag() if with_diag else None
    if traced or cfg.n_coop:
        # Adaptive colluding payloads — identical math to the dual arm
        # (applied per tree AFTER the phase-I split, so the arms stay
        # pinned leaf-for-leaf).
        if not traced and cfg.has_role(Roles.ADAPTIVE):
            amask = _role_mask(cfg, Roles.ADAPTIVE)
            cmask = _role_mask(cfg, Roles.COOPERATIVE)
            msg_c = adaptive_payload_tree(
                msg_c, cmask, amask, cfg.adaptive_scale
            )
            msg_t = adaptive_payload_tree(
                msg_t, cmask, amask, cfg.adaptive_scale
            )
        # Transport boundary + consensus on the combined block, shared
        # with the standalone ``consensus_block`` entry — the stacked
        # XLA arm or the one-kernel Pallas arm per the resolved impl
        # (:func:`_pair_phase2`; per-tree fault streams identical to
        # the dual arm's two calls either way).
        cons_c, cons_t, diag2 = _pair_phase2(
            cfg, new_critic, new_tr, msg_c, msg_t, critic, tr,
            x2, mask, ekey, spec, with_diag, graph,
        )
        if with_diag:
            diag = diag2
        m = spec.coop if traced else _role_mask(cfg, Roles.COOPERATIVE)
        new_critic = select_tree(m, cons_c, new_critic)
        new_tr = select_tree(m, cons_t, new_tr)

    if with_diag:
        return (new_critic, new_tr, new_critic_local), diag
    return new_critic, new_tr, new_critic_local


def actor_phase(
    cfg: Config,
    params: AgentParams,
    fresh: Batch,
    key: jax.Array,
    spec: CellSpec | None = None,
) -> Tuple[object, object]:
    """Phase III: actor updates over the fresh on-policy window
    (``train_agents.py:149-153``). Returns (new_actor, new_actor_opt).
    With a ``spec``, role membership is traced (see
    :func:`critic_tr_epoch`)."""
    s, ns, sa = fresh.s, fresh.ns, fresh.sa
    a_own = jnp.moveaxis(fresh.a[..., 0], 1, 0).astype(jnp.int32)  # (N, B)
    r_own = jnp.moveaxis(fresh.r, 1, 0)  # (N, B, 1)
    N = cfg.n_agents
    traced = spec is not None

    new_actor, new_opt = params.actor, params.actor_opt
    if traced or cfg.n_coop:
        coop_a, coop_o, _ = jax.vmap(
            lambda ac, op, cr, t, a: coop_actor_update(
                ac, op, cr, t, s, ns, sa, a, cfg
            )
        )(params.actor, params.actor_opt, params.critic, params.tr, a_own)
        m = spec.coop if traced else _role_mask(cfg, Roles.COOPERATIVE)
        new_actor = select_tree(m, coop_a, new_actor)
        new_opt = select_tree(m, coop_o, new_opt)

    if traced or cfg.n_adv:
        # Malicious agents drive their actor with the PRIVATE local critic
        # (adversarial_CAC_agents.py:102-119); greedy/faulty use their own.
        mal = spec.malicious if traced else _role_mask(cfg, Roles.MALICIOUS)
        critic_in = select_tree(mal, params.critic_local, params.critic)
        adv_a, adv_o, _ = jax.vmap(
            lambda k, ac, op, cr, r, a: adv_actor_update(
                k, ac, op, cr, s, ns, r, a, cfg
            )
        )(
            jax.random.split(key, N),
            params.actor,
            params.actor_opt,
            critic_in,
            r_own,
            a_own,
        )
        m = ~spec.coop if traced else jnp.asarray(~np.array(cfg.coop_mask))
        new_actor = select_tree(m, adv_a, new_actor)
        new_opt = select_tree(m, adv_o, new_opt)

    return new_actor, new_opt


def _update_block(
    cfg: Config,
    params: AgentParams,
    batch: Batch,
    fresh: Batch,
    key: jax.Array,
    spec: CellSpec | None = None,
    with_diag: bool = False,
    graph=None,
) -> AgentParams:
    """Full update block: ``n_epochs`` x (phase I + II) then phase III.

    Jitted as :data:`update_block` (the default) and
    :data:`update_block_donated` (same program, ``params`` donated).

    Args:
      params: stacked agent state.
      batch: replay window (kept buffer + fresh block), masked.
      fresh: the on-policy actor window (fully valid).
      key: PRNG key for adversary fit shuffles and actor minibatching.
      spec: optional traced scenario knobs (roles/H/common_reward) —
        the fused-matrix path; None = static-Config specialization.
      with_diag: (static) also return a block-summed
        :class:`~rcmarl_tpu.faults.FaultDiag` of transport-degradation
        counters — ``(params, diag)`` instead of ``params``.
      graph: optional traced (N, degree) int32 gather indices — the
        block's time-varying communication graph (constant across the
        block's epochs; data, so resampling never recompiles).
    """
    r_coop = team_average_reward(cfg, batch.r, spec)
    k_epochs, k_actor = jax.random.split(key)

    def epoch(carry, ekey):
        if with_diag:
            return critic_tr_epoch(
                cfg, carry, batch, r_coop, ekey, spec, with_diag=True,
                graph=graph,
            )
        return (
            critic_tr_epoch(cfg, carry, batch, r_coop, ekey, spec, graph=graph),
            None,
        )

    (critic, tr, critic_local), diags = jax.lax.scan(
        epoch,
        (params.critic, params.tr, params.critic_local),
        jax.random.split(k_epochs, cfg.n_epochs),
    )
    params = params._replace(critic=critic, tr=tr, critic_local=critic_local)
    actor, actor_opt = actor_phase(cfg, params, fresh, k_actor, spec)
    params = params._replace(actor=actor, actor_opt=actor_opt)
    if with_diag:
        return params, sum_diags(diags)
    return params


#: The standard jitted update block: inputs stay alive after the call
#: (tests and the guard/retry path re-run blocks from the same state).
update_block = partial(
    jax.jit, static_argnums=0, static_argnames=("with_diag",)
)(_update_block)

#: Same program with the ``params`` carry DONATED: XLA reuses the input
#: parameter/optimizer buffers for the outputs, so the largest stacked
#: arrays update in place instead of allocating a second copy per call
#: (PERF.md "buffer donation"). The caller's ``params`` is consumed —
#: reusing it afterwards raises. Nested calls (e.g. from inside another
#: jit) leave donation to the outer program, where XLA aliases buffers
#: on its own.
update_block_donated = jax.jit(
    _update_block,
    static_argnums=0,
    static_argnames=("with_diag",),
    donate_argnums=(1,),
)
