"""Top-level trainer: blocks of rollout + update, host- or device-looped.

TPU-native twin of ``train_RPBCAC`` (reference ``training/train_agents.py:
15-184``). A *block* is ``n_ep_fixed`` episodes followed by one update
(phases I-IV); the whole block is a single jitted program. Two drivers:

- :func:`train` — host loop over blocks (jit per block): supports
  checkpointing, logging and warm-start, compiles once, and matches the
  reference's observable behavior episode-for-episode.
- :func:`train_scanned` — the entire run as ONE ``lax.scan`` over blocks
  (used by the benchmark and by seed-parallel sharding, where the host
  must stay out of the loop entirely).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rcmarl_tpu.agents.updates import AgentParams
from rcmarl_tpu.config import Config, scheduled_in_nodes
from rcmarl_tpu.envs.api import env_reset, env_task
from rcmarl_tpu.envs.api import make_env as _registry_make_env
from rcmarl_tpu.faults import tree_all_finite, tree_finite_per_replica
from rcmarl_tpu.training.buffer import (
    ReplayBuffer,
    buffer_init,
    buffer_push_block,
    update_batch,
)
from rcmarl_tpu.training.rollout import EpisodeMetrics, rollout_block
from rcmarl_tpu.training.update import init_agent_params, update_block


class TrainState(NamedTuple):
    """Everything that evolves across blocks (one checkpointable pytree)."""

    params: AgentParams
    buffer: ReplayBuffer
    desired: jnp.ndarray  # (N, 2) int32 goal layout, fixed for the run
    initial: jnp.ndarray  # (N, 2) int32 reset layout (randomize_state=False)
    key: jax.Array
    block: jnp.ndarray  # () int32 completed-block counter


def make_env(cfg: Config):
    """The env-zoo registry dispatch (``Config.env`` -> static world,
    :func:`rcmarl_tpu.envs.api.make_env`). Kept as a trainer-level name
    because every layer above (serving, pipeline, profiling, CLI)
    historically imports it from here; ``env='grid_world'`` (default)
    builds exactly the world this function always built."""
    return _registry_make_env(cfg)


def init_train_state(
    cfg: Config,
    key: jax.Array,
    desired: Optional[jnp.ndarray] = None,
    params: Optional[AgentParams] = None,
    buffer: Optional[ReplayBuffer] = None,
) -> TrainState:
    """Fresh run state. The goal and initial layouts are drawn once at
    startup from the run's RNG, as the reference draws ``s_desired`` and
    ``s_initial`` before training (``main.py:48-49``); pass ``desired``/
    ``params`` to resume."""
    k_desired, k_initial, k_params, k_run = jax.random.split(key, 4)
    env = make_env(cfg)
    if desired is None:
        # the env's TASK layout (goals / landmarks / evader start); for
        # the grid world this is env_reset — bit-for-bit the seed draw
        desired = env_task(env, k_desired)
    initial = env_reset(env, k_initial)
    if params is None:
        params = init_agent_params(k_params, cfg)
    if buffer is None:
        buffer = buffer_init(cfg.buffer_size, cfg.n_agents, cfg.n_states)
    return TrainState(
        params=params,
        buffer=buffer,
        desired=jnp.asarray(desired, jnp.int32),
        initial=jnp.asarray(initial, jnp.int32),
        key=k_run,
        block=jnp.zeros((), jnp.int32),
    )


def _train_block(
    cfg: Config, state: TrainState, spec=None, with_diag: bool = False,
    graph=None,
) -> Tuple[TrainState, EpisodeMetrics]:
    """One block: rollout ``n_ep_fixed`` episodes, update, push to buffer.

    Jitted once per (frozen, hashable) Config — repeated ``train`` calls
    with the same config reuse the compiled program. ``spec`` (a traced
    :class:`~rcmarl_tpu.agents.updates.CellSpec`) switches the scenario
    knobs (roles/H/common_reward) from trace-time constants to data —
    the fused-matrix path (:mod:`rcmarl_tpu.parallel.matrix`).
    ``with_diag`` (static) additionally returns the block's
    :class:`~rcmarl_tpu.faults.FaultDiag` degradation counters.
    ``graph`` (optional DATA, an ``(N, degree)`` int32 gather-index
    array) overrides the static communication topology for this block —
    the time-varying graph schedule
    (:func:`rcmarl_tpu.config.scheduled_in_nodes`); indices being data
    is what makes per-block resampling free of recompiles. ``None``
    (default) keeps the compiled static topology, bit-for-bit.

    Exposed as :data:`train_block` (inputs stay alive) and
    :data:`train_block_donated` (``state`` donated — the host training
    loop's allocation saver).
    """
    env = make_env(cfg)
    key, k_roll, k_upd = jax.random.split(state.key, 3)
    fresh, metrics = rollout_block(
        cfg, env, state.params, state.desired, k_roll, state.initial, spec
    )
    batch = update_batch(state.buffer, fresh)
    if with_diag:
        params, diag = update_block(
            cfg, state.params, batch, fresh, k_upd, spec, with_diag=True,
            graph=graph,
        )
    else:
        params = update_block(
            cfg, state.params, batch, fresh, k_upd, spec, graph=graph
        )
    buffer = buffer_push_block(state.buffer, fresh)
    out_state = TrainState(
        params, buffer, state.desired, state.initial, key, state.block + 1
    )
    if with_diag:
        return out_state, metrics, diag
    return out_state, metrics


#: The standard jitted block: inputs stay alive after the call — what
#: the guard/retry path, the fused-matrix/seed-parallel vmaps, and every
#: test that re-runs a block from the same state need.
train_block = partial(
    jax.jit, static_argnums=0, static_argnames=("with_diag",)
)(_train_block)

#: Same program with ``state`` DONATED: XLA writes the new params /
#: optimizer moments / replay buffer into the input buffers instead of
#: allocating a second full copy per block — the steady-state host loop
#: (:func:`train` with the guard off) runs with one live TrainState
#: instead of two (PERF.md "buffer donation"). The passed ``state`` is
#: consumed; reusing it afterwards raises.
train_block_donated = jax.jit(
    _train_block,
    static_argnums=0,
    static_argnames=("with_diag",),
    donate_argnums=(1,),
)


def train_scanned(
    cfg: Config, state: TrainState, n_blocks: int, spec=None, graphs=None
) -> Tuple[TrainState, EpisodeMetrics]:
    """``n_blocks`` blocks as one ``lax.scan`` — zero host round-trips.

    Returned metrics leaves have shape (n_blocks * n_ep_fixed,) == one row
    per episode, flattened in episode order.

    ``graphs`` is the STACKED-SCHEDULE operand for time-varying
    ``graph_schedule`` configs: the ``(n_blocks, N, degree)`` int32
    window of per-block gather indices
    (:func:`rcmarl_tpu.config.schedule_window` — bitwise the host
    loop's ``scheduled_in_nodes`` sequence by construction), consumed
    as plain scan data so S scheduled blocks run as ONE launch instead
    of S host dispatches. The window is host data the device scan
    cannot regenerate, so scheduled configs must pass it; static
    configs must not (a silently ignored window would be a schedule
    bug). Concrete host-side validation (shape / self-first / range /
    duplicates / 2H+1, per block) runs here exactly when the operand
    is concrete; traced operands — inside a caller's jit, e.g. the
    donated window entry — were validated where they were built.
    """

    if cfg.graph_schedule != "static":
        if graphs is None:
            raise ValueError(
                "train_scanned needs the stacked-schedule window for a "
                "time-varying graph_schedule: the per-block resample is "
                "host-side data the device scan cannot regenerate — "
                "pass graphs=schedule_window(cfg, start_block, n_blocks)"
            )
    elif graphs is not None:
        raise ValueError(
            "graphs is the time-varying stacked-schedule operand; "
            "graph_schedule='static' compiles its topology into the "
            "program and would silently ignore it"
        )

    if graphs is not None:
        if isinstance(graphs, np.ndarray):
            from rcmarl_tpu.ops.exchange import validate_graph_window

            graphs = validate_graph_window(
                graphs, cfg.n_agents, degree=cfg.resolved_graph_degree,
                H=cfg.H,
            )
        graphs = jnp.asarray(graphs, jnp.int32)
        if graphs.shape[0] != n_blocks:
            raise ValueError(
                f"stacked-schedule window covers {graphs.shape[0]} "
                f"blocks but the scan runs n_blocks={n_blocks}"
            )

        def body(s, g):
            return train_block(cfg, s, spec, graph=g)

        state, metrics = jax.lax.scan(body, state, graphs)
    else:

        def body(s, _):
            return train_block(cfg, s, spec)

        state, metrics = jax.lax.scan(body, state, None, length=n_blocks)
    return state, jax.tree.map(lambda x: x.reshape(-1), metrics)


def _train_window(cfg: Config, state: TrainState, n_blocks: int, graphs,
                  spec=None):
    return train_scanned(cfg, state, n_blocks, spec=spec, graphs=graphs)


#: The scheduled-config scan as ONE DONATED device launch:
#: ``train_window_donated(cfg, state, S, graphs)`` runs S scheduled
#: blocks per dispatch with the ``(S, N, degree)`` stacked-schedule
#: window as scan data and the carried ``state`` donated (XLA reuses
#: the params/moments/replay buffers across the launch — the
#: steady-state driver for scheduled/sparse configs, replacing S
#: host-looped dispatches). Successive windows re-dispatch the SAME
#: executable — window content is data, shapes are fixed by
#: (n_agents, degree, S) — proven by the ``lint --retrace``
#: scanned-window case. The passed ``state`` is consumed.
train_window_donated = jax.jit(
    _train_window,
    static_argnums=(0, 2),
    donate_argnums=(1,),
)


def metrics_to_dataframe(metrics: EpisodeMetrics):
    """Per-episode metrics -> the reference's sim_data DataFrame layout
    (columns ``True_team_returns`` / ``True_adv_returns`` /
    ``Estimated_team_returns``, one row per episode;
    ``train_agents.py:175-183``) so the reference's plotting pipeline works
    unchanged on our outputs."""
    import pandas as pd

    return pd.DataFrame(
        {
            "True_team_returns": np.asarray(metrics.true_team_returns),
            "True_adv_returns": np.asarray(metrics.true_adv_returns),
            "Estimated_team_returns": np.asarray(metrics.est_team_returns),
        }
    )


def _replica_block_healthy(states: TrainState, metrics):
    """(R,) bool: the guard predicate factored PER REPLICA over a
    leading replica axis — params and metric rows of replica ``r`` are
    fully finite. The gossip trainer
    (:mod:`rcmarl_tpu.parallel.gossip`) rolls back and excludes exactly
    the poisoned replicas, so one NaN-bombed replica can never force a
    global rollback/retry of the healthy ones."""
    return tree_finite_per_replica((states.params, metrics))


def _block_healthy(state: TrainState, metrics) -> bool:
    """Guard predicate: params AND the block's metric rows are fully
    finite (one fused device reduction, one host bool). The solo-state
    scalar form of :func:`_replica_block_healthy`."""
    return bool(tree_all_finite((state.params, metrics)))


def train(
    cfg: Config,
    n_episodes: Optional[int] = None,
    state: Optional[TrainState] = None,
    verbose: bool = False,
    block_callback=None,
    guard: Optional[bool] = None,
    max_retries: int = 1,
):
    """Host-looped training run (the ``train_RPBCAC`` equivalent).

    Args:
      n_episodes: override cfg.n_episodes; must be a multiple of
        ``n_ep_fixed`` (the reference silently never updates on a trailing
        partial block; we reject it instead).
      state: resume from a prior TrainState (warm-started buffer included,
        the ``exp_buffer`` feature of ``train_agents.py:15``).
      block_callback: called as ``f(state, block_idx)`` after each block
        (checkpoint hook).
      guard: per-block non-finite guard rails — after each block, params
        and metrics are checked for NaN/±Inf; an unhealthy block ROLLS
        BACK to the last good state and retries with a perturbed RNG
        stream (up to ``max_retries`` times), then SKIPS: the run keeps
        the last good parameters, records the degraded metrics row, and
        moves on. An injected (or real) fault therefore degrades the
        run's metrics instead of destroying its parameters. ``None``
        (default) auto-enables exactly when ``cfg.fault_plan`` is set,
        so clean runs keep the seed behavior bit-for-bit.
      max_retries: bounded retry budget per block under ``guard``.

    Returns (state, sim_data DataFrame with one row per episode). The
    frame's ``.attrs['guard']`` records the guard/diagnostic counters
    (retries, skipped blocks, non-finite payload entries, degree-deficit
    fallbacks) when the guard or a fault plan is active.

    Allocation: with the guard off the loop runs :data:`train_block_donated`
    — each block's new TrainState reuses the old one's buffers (one live
    copy of params/moments/replay instead of two). A caller-passed
    ``state`` is copied once up front so it survives the run; guarded
    runs use the undonated entry because rollback/retry re-runs blocks
    from the same pre-block state.
    """
    n_eps = cfg.n_episodes if n_episodes is None else n_episodes
    if n_eps % cfg.n_ep_fixed != 0:
        raise ValueError(
            f"n_episodes={n_eps} must be a multiple of n_ep_fixed={cfg.n_ep_fixed}"
        )
    if max_retries < 0:
        raise ValueError(f"max_retries={max_retries} must be >= 0")
    n_blocks = n_eps // cfg.n_ep_fixed
    if guard is None:
        guard = cfg.fault_plan is not None
    if state is None:
        state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
    elif not guard:
        # The donated block entry below CONSUMES its input state; work on
        # a one-time copy so the caller's resume state stays alive (the
        # copy is one block's worth of allocation, paid once per run —
        # donation then keeps the whole loop at a single live TrainState).
        state = jax.tree.map(jnp.copy, state)
    # Guarded runs keep the undonated entry: rollback/retry re-runs a
    # block from the SAME pre-block state, which donation would consume.
    step = train_block if guard else train_block_donated
    with_diag = cfg.fault_plan is not None and cfg.fault_plan.active
    stats = {"retries": 0, "skipped": 0, "nonfinite": 0, "deficit": 0}

    # Time-varying communication graphs (Config.graph_schedule): the
    # block's gather indices are regenerated host-side — deterministic
    # in (graph_seed, GLOBAL block number), so resumed runs replay
    # their exact graph sequence — and passed to the jitted block as
    # DATA (same shape every block: one compile, zero steady-state
    # recompiles, proven by the lint retrace case).
    dynamic_graph = cfg.graph_schedule != "static"
    start_block = (
        int(np.asarray(state.block).reshape(-1)[0]) if dynamic_graph else 0
    )

    all_metrics = []
    for b in range(n_blocks):
        graph = None
        if dynamic_graph:
            # guard rail at the host/device boundary: every resampled
            # graph the device gather consumes is regular, self-first,
            # in-range, duplicate-free, and wide enough for the trim
            # (ops/exchange.py — the sparse-exchange invariants the
            # hypothesis twins pin)
            from rcmarl_tpu.ops.exchange import validate_graph

            graph = validate_graph(
                scheduled_in_nodes(cfg, start_block + b),
                cfg.n_agents,
                degree=cfg.resolved_graph_degree,
                H=cfg.H,
            )
        attempt = 0
        while True:
            base = state
            if attempt:
                # Perturbed RNG stream for the retry: different rollout,
                # adversary-shuffle, and fault draws — deterministic in
                # (key, block, attempt), so guarded runs stay replayable.
                base = base._replace(
                    key=jax.random.fold_in(base.key, attempt)
                )
            diag = None
            if with_diag:
                new_state, m, diag = step(
                    cfg, base, with_diag=True, graph=graph
                )
            else:
                new_state, m = step(cfg, base, graph=graph)
            if not guard or _block_healthy(new_state, m):
                state = new_state
                break
            if attempt < max_retries:
                attempt += 1
                stats["retries"] += 1
                if verbose:
                    print(
                        f"| Block {b + 1} | non-finite params/metrics — "
                        f"rolling back (retry {attempt}/{max_retries})"
                    )
                continue
            # Retries exhausted: SKIP. Keep the last good parameters and
            # buffer, record the degraded metrics row, advance the RNG
            # (folded on the block index so the next block does not
            # replay the failing draw) and the block counter.
            stats["skipped"] += 1
            if verbose:
                print(
                    f"| Block {b + 1} | still non-finite after "
                    f"{max_retries} retries — skipping (params rolled back)"
                )
            state = state._replace(
                key=jax.random.fold_in(state.key, 0x5C1B + b),
                block=state.block + 1,
            )
            break
        if diag is not None:
            # Count the RECORDED attempt only (the accepted block, or the
            # final skipped attempt whose degraded metrics row is kept):
            # discarded retry attempts must not inflate the per-run fault
            # rates QUALITY.md derives from these counters.
            stats["nonfinite"] += int(diag.nonfinite)
            stats["deficit"] += int(diag.deficit)
        all_metrics.append(m)
        if verbose:
            tt = float(jnp.mean(m.true_team_returns))
            et = float(jnp.mean(m.est_team_returns))
            print(
                f"| Block {int(state.block)} | episodes {(b + 1) * cfg.n_ep_fixed}"
                f" | team return {tt:.3f} | est return {et:.3f}"
            )
        if block_callback is not None:
            block_callback(state, b)

    metrics = jax.tree.map(lambda *xs: jnp.concatenate(xs), *all_metrics)
    df = metrics_to_dataframe(metrics)
    if guard or with_diag:
        df.attrs["guard"] = stats
    return state, df
