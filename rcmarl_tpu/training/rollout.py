"""On-device episode rollout as a jitted ``lax.scan``.

TPU-native rebuild of the reference's rollout hot loop
(``train_agents.py:46-80``), which issues n_agents x max_ep_len Keras
``predict`` calls on batches of 1 per episode (~2.5 env-steps/s). Here a
whole update block — ``n_ep_fixed`` episodes x ``max_ep_len`` steps — is
one XLA program: vmapped policy forward for all agents at once, the pure
env step, and metric accumulation, scanned over steps and episodes
with zero host round-trips.

Generic over the env-zoo protocol (:mod:`rcmarl_tpu.envs.api`): the env
is a static world description dispatched at trace time, the task array
(goals / landmarks / evader — TrainState's ``desired``) rides the step
scan carry so task-evolving envs (pursuit) share this exact program
shape with static-task envs, for which the carried task is unchanged
data and the compiled program's arithmetic is bit-for-bit the
historical grid-world rollout (the ``Config.env='grid_world'`` pin).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.agents.updates import AgentParams, Batch, CellSpec
from rcmarl_tpu.config import Config
from rcmarl_tpu.envs.api import (
    env_obs,
    env_reset,
    env_reward_scaled,
    env_transition,
    env_transition_scaled,
)
from rcmarl_tpu.models.mlp import actor_probs, mlp_forward


class EpisodeMetrics(NamedTuple):
    """Per-episode scalars matching the reference's sim_data columns
    (``train_agents.py:175-179``)."""

    true_team_returns: jnp.ndarray  # mean discounted return, cooperative agents
    true_adv_returns: jnp.ndarray  # mean discounted return, adversaries
    est_team_returns: jnp.ndarray  # mean cooperative critic V(s0)


def sample_actions(
    cfg: Config, actor: object, state_scaled: jnp.ndarray, key: jax.Array
) -> jnp.ndarray:
    """eps-mixed policy sampling for all agents at once.

    Reference ``get_action`` (resilient_CAC_agents.py:208-219): sample the
    softmax policy, then with prob. ``mu`` replace by a uniform random
    action. Every agent observes the same GLOBAL state; each applies its
    own actor. Returns (N,) int32.
    """
    n = cfg.n_agents
    # (N, batch=1, N, n_states): same global state for every agent
    obs = jnp.broadcast_to(state_scaled[None], (n, *state_scaled.shape))[:, None]
    probs = jax.vmap(lambda p, x: actor_probs(p, x, cfg.leaky_alpha, cfg.dot_dtype))(
        actor, obs
    )
    k_pol, k_rand, k_mix = jax.random.split(key, 3)
    policy_a = jax.vmap(jax.random.categorical)(
        jax.random.split(k_pol, n), jnp.log(probs[:, 0, :])
    )
    random_a = jax.random.randint(k_rand, (n,), 0, cfg.n_actions)
    use_random = jax.random.bernoulli(k_mix, cfg.eps_explore, (n,))
    return jnp.where(use_random, random_a, policy_a).astype(jnp.int32)


def rollout_episode(
    cfg: Config,
    env,
    params: AgentParams,
    desired: jnp.ndarray,
    key: jax.Array,
    initial: jnp.ndarray = None,
    spec: CellSpec | None = None,
) -> Tuple[Batch, EpisodeMetrics]:
    """One episode: reset, ``max_ep_len`` steps, per-episode metrics
    evaluated with the CURRENT (episode-start) parameters, exactly as the
    reference interleaves metric evaluation with training
    (``train_agents.py:55-71``).

    ``env`` is any registered env-zoo world; ``desired`` is its task
    array (episode-START layout — a task-evolving env restarts from it
    every episode). Reset honors ``cfg.randomize_state`` (reference
    ``grid_world.py:39-43``): random positions by default, else the
    fixed ``initial`` layout drawn at startup (reference ``main.py:49``).
    Rollout dynamics are role-independent; ``spec`` (the fused-matrix
    path) only redefines which agents count as cooperative in the
    METRICS.
    """
    k_reset, k_steps = jax.random.split(key)
    if cfg.randomize_state:
        pos0 = env_reset(env, k_reset)
    elif initial is None:
        raise ValueError(
            "randomize_state=False requires a fixed `initial` layout "
            "(drawn at startup; see TrainState.initial)"
        )
    else:
        pos0 = initial

    # Estimated team returns at s0 (train_agents.py:60-62)
    s0 = env_obs(env, pos0)
    if spec is None:
        coop = jnp.asarray(cfg.coop_mask)
        n_coop = max(cfg.n_coop, 1)
        n_adv = max(cfg.n_adv, 1)
    else:
        coop = spec.coop
        n_coop = jnp.maximum(jnp.sum(coop), 1)
        n_adv = jnp.maximum(jnp.sum(~coop), 1)
    v0 = jax.vmap(
        lambda p: mlp_forward(p, s0[None].reshape(1, -1), dtype=cfg.dot_dtype)[
            0, 0
        ]
    )(params.critic)  # (N,)
    est = jnp.sum(jnp.where(coop, v0, 0.0)) / n_coop

    def step(carry, k):
        pos, task, ret, j = carry
        s_scaled = env_obs(env, pos)
        actions = sample_actions(cfg, params.actor, s_scaled, k)
        if spec is None:
            npos, ntask, reward = env_transition(env, pos, task, actions)
        else:
            # traced Diff-DAC task level (Config.task_axis); 1.0 keeps
            # every non-task spec cell bitwise on the plain transition
            npos, ntask, reward = env_transition_scaled(
                env, pos, task, actions, spec.task_scale
            )
        r_scaled = env_reward_scaled(env, reward)  # (N,)
        ret = ret + r_scaled * cfg.gamma**j
        out = (
            s_scaled,
            env_obs(env, npos),
            actions.astype(jnp.float32)[:, None],
            r_scaled[:, None],
        )
        return (npos, ntask, ret, j + 1.0), out

    (_, _, ep_returns, _), (s, ns, a, r) = jax.lax.scan(
        step,
        (pos0, desired, jnp.zeros((cfg.n_agents,)), 0.0),
        jax.random.split(k_steps, cfg.max_ep_len),
    )

    true_team = jnp.sum(jnp.where(coop, ep_returns, 0.0)) / n_coop
    true_adv = jnp.sum(jnp.where(coop, 0.0, ep_returns)) / n_adv
    batch = Batch(s=s, ns=ns, a=a, r=r, mask=jnp.ones((cfg.max_ep_len,), jnp.float32))
    return batch, EpisodeMetrics(true_team, true_adv, est)


def rollout_block(
    cfg: Config,
    env,
    params: AgentParams,
    desired: jnp.ndarray,
    key: jax.Array,
    initial: jnp.ndarray = None,
    spec: CellSpec | None = None,
) -> Tuple[Batch, EpisodeMetrics]:
    """``n_ep_fixed`` consecutive episodes under frozen parameters (the
    reference only updates at block boundaries, ``train_agents.py:86``).

    Returns the fresh on-policy window as a flat (block_steps, N, ...)
    batch — exactly the ``s[-max_ep_len*n_ep_fixed:]`` actor window of
    ``train_agents.py:149-153`` — plus per-episode metrics (n_ep_fixed,).
    """

    def one_ep(_, k):
        return None, rollout_episode(
            cfg, env, params, desired, k, initial, spec
        )

    _, (ep_batch, metrics) = jax.lax.scan(
        one_ep, None, jax.random.split(key, cfg.n_ep_fixed)
    )
    flat = jax.tree.map(
        lambda x: x.reshape(cfg.block_steps, *x.shape[2:]), ep_batch
    )
    return flat, metrics
