from rcmarl_tpu.training.buffer import (  # noqa: F401
    ReplayBuffer,
    buffer_init,
    buffer_push_block,
    update_batch,
)
from rcmarl_tpu.training.rollout import (  # noqa: F401
    EpisodeMetrics,
    rollout_block,
    rollout_episode,
    sample_actions,
)
from rcmarl_tpu.training.trainer import (  # noqa: F401
    TrainState,
    init_train_state,
    make_env,
    metrics_to_dataframe,
    train,
    train_block,
    train_block_donated,
    train_scanned,
)
from rcmarl_tpu.training.update import (  # noqa: F401
    init_agent_params,
    spec_from_config,
    team_average_reward,
    update_block,
    update_block_donated,
)
from rcmarl_tpu.training.reference_api import train_RPBCAC  # noqa: F401
