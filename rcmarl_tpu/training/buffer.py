"""Fixed-capacity experience replay as an on-device ring buffer.

The reference keeps growing Python lists, FIFO-trimmed to ``buffer_size``
after each update block (``train_agents.py:36-42,76-80,158-163``), so the
update batch is 1000 rows after block 0, 2000 after block 1, and 3000 at
steady state. Growing shapes are hostile to XLA, so here the kept buffer is
a static ``(buffer_size, ...)`` ring in HBM with a validity count; the
update batch is the (static-shape) concatenation of the kept ring and the
fresh block, masked to the valid rows — numerically identical to the
reference's growing window because every consumer is order-independent
(full-batch fits, shuffled mini-batch fits, per-row TD targets) and the
on-policy actor window is passed separately.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from rcmarl_tpu.agents.updates import Batch


class ReplayBuffer(NamedTuple):
    """Ring of transitions, every array (capacity, n_agents, ...)."""

    s: jnp.ndarray  # (C, N, n_states) scaled states
    ns: jnp.ndarray  # (C, N, n_states)
    a: jnp.ndarray  # (C, N, 1) float action indices
    r: jnp.ndarray  # (C, N, 1) scaled rewards
    ptr: jnp.ndarray  # () int32 next write position
    count: jnp.ndarray  # () int32 number of valid rows

    @property
    def capacity(self) -> int:
        return self.s.shape[0]

    @property
    def mask(self) -> jnp.ndarray:
        """(C,) float32 validity. Ring order is irrelevant to consumers."""
        return (jnp.arange(self.capacity) < self.count).astype(jnp.float32)


def buffer_init(capacity: int, n_agents: int, n_states: int) -> ReplayBuffer:
    return ReplayBuffer(
        s=jnp.zeros((capacity, n_agents, n_states), jnp.float32),
        ns=jnp.zeros((capacity, n_agents, n_states), jnp.float32),
        a=jnp.zeros((capacity, n_agents, 1), jnp.float32),
        r=jnp.zeros((capacity, n_agents, 1), jnp.float32),
        ptr=jnp.zeros((), jnp.int32),
        count=jnp.zeros((), jnp.int32),
    )


def buffer_push_block(buf: ReplayBuffer, fresh: Batch) -> ReplayBuffer:
    """Insert a block of transitions with wraparound (the post-update FIFO
    trim of ``train_agents.py:158-163``: once full, each push overwrites the
    oldest rows)."""
    block = fresh.s.shape[0]
    if block >= buf.capacity:
        # Block alone overflows the ring: keep its LAST `capacity` rows
        # (reference trim keeps the newest buffer_size rows). A modular
        # scatter would have duplicate indices with unspecified winners.
        keep = jax.tree.map(lambda x: x[block - buf.capacity :], fresh)
        return ReplayBuffer(
            s=keep.s,
            ns=keep.ns,
            a=keep.a,
            r=keep.r,
            ptr=jnp.zeros((), jnp.int32),
            count=jnp.full((), buf.capacity, jnp.int32),
        )
    idx = (buf.ptr + jnp.arange(block)) % buf.capacity
    return ReplayBuffer(
        s=buf.s.at[idx].set(fresh.s),
        ns=buf.ns.at[idx].set(fresh.ns),
        a=buf.a.at[idx].set(fresh.a),
        r=buf.r.at[idx].set(fresh.r),
        ptr=(buf.ptr + block) % buf.capacity,
        count=jnp.minimum(buf.count + block, buf.capacity),
    )


def update_batch(buf: ReplayBuffer, fresh: Batch) -> Batch:
    """The batch an update block sees: kept rows + the fresh block
    (reference semantics: updates run BEFORE the trim, over up to
    buffer_size + block rows)."""
    return Batch(
        s=jnp.concatenate([buf.s, fresh.s], axis=0),
        ns=jnp.concatenate([buf.ns, fresh.ns], axis=0),
        a=jnp.concatenate([buf.a, fresh.a], axis=0),
        r=jnp.concatenate([buf.r, fresh.r], axis=0),
        mask=jnp.concatenate([buf.mask, fresh.mask], axis=0),
    )
