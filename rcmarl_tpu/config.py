"""Experiment configuration.

Replaces the reference's argparse-only flag system (reference
``main.py:25-44``) with a structured, hashable dataclass whose topology
(``in_nodes``) and per-agent role labels are first-class values instead of
unoverridable argparse defaults (SURVEY.md §5 "Config / flag system").

The config is static with respect to JAX tracing: everything here is a
Python scalar/tuple, so it can be closed over by jitted functions without
triggering retraces, and role composition is resolved at trace time
(compute only the update branches for roles actually present).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional, Tuple

from rcmarl_tpu.faults import FaultPlan, ReplicaFaultPlan


#: Valid consensus aggregation backends (see ops/aggregation.py):
#: 'xla' / 'pallas' compute the trim bounds by dual top-(H+1) selection
#: (running min/max registers — the measured-faster default), the
#: '*_sort' twins keep the original full-sort strategy as the
#: measured-comparison arm, 'pallas_interpret' runs the selection kernel
#: in the Pallas interpreter (CPU tests), and 'auto' is the 3-way
#: measured-crossover policy keyed on (H, n_in, volume).
#: 'pallas_fused' / 'pallas_fused_interpret' are the ONE-KERNEL EPOCH
#: arms (ops/pallas_consensus.py): phase-II gather -> link-fault
#: injection -> trim/clip/mean runs as a single VMEM-resident Pallas
#: program over the combined (n_in, P_critic + P_tr) pair block (the
#: stacked layout is therefore forced), with the projection einsum and
#: team head step staying XLA; at the leaf-aggregation level the fused
#: names alias the plain kernel ('pallas'/'pallas_interpret') — the
#: extra fusion is an epoch-level property. 'auto' never resolves to
#: the fused arms until the queued TPU session measures them
#: (scripts/tpu_session.sh).
CONSENSUS_IMPLS = (
    "xla",
    "xla_sort",
    "pallas",
    "pallas_sort",
    "pallas_interpret",
    "pallas_fused",
    "pallas_fused_interpret",
    "auto",
)

#: The one-kernel-epoch members of CONSENSUS_IMPLS (the fused phase-II
#: arms training/update.py routes onto the stacked pair layout).
FUSED_CONSENSUS_IMPLS = ("pallas_fused", "pallas_fused_interpret")

#: Valid Config.fitstack values beyond the bool/'auto' policy shared
#: with netstack: the fit-scan Pallas kernel arms (ops/pallas_fit.py)
#: — phase-I parameters VMEM-resident across the whole minibatch
#: schedule instead of round-tripping HBM per scan step. 'pallas' is
#: the real lowering (queued for the TPU session),
#: 'pallas_interpret' the CPU test arm; both imply the fused
#: cross-flavor row stacking (fitstack on).
FITSTACK_IMPLS = ("pallas", "pallas_interpret")


#: Valid environment names — the keys of the env-zoo registry
#: (``rcmarl_tpu.envs.api.make_env``). Kept here (jax-free) so Config
#: validation and the CLI ``--env`` choices never drift from the
#: registry; tests pin the registry's keys to this tuple.
ENV_NAMES = ("grid_world", "pursuit", "coverage", "congestion")

#: Valid communication-graph schedules: 'static' = the fixed
#: ``in_nodes`` topology compiled into the program (the seed behavior,
#: bit-for-bit), 'random_geometric' = the in-neighborhoods are
#: REGENERATED every ``graph_every`` blocks as a deterministic
#: random-geometric digraph (``random_geometric_in_nodes`` — the same
#: builder the replica gossip layer uses, applied at the agent level)
#: and passed to the jitted block as DATA (gather indices, not program
#: structure), so resampling never recompiles.
GRAPH_SCHEDULES = ("static", "random_geometric")

#: Mega-population guard rail: the widest STATIC in-neighborhood the
#: framework will compile. A static dense graph gathers an
#: ``(N, n_in, P)`` block whose cost is quadratic in the population
#: once ``n_in`` tracks ``N``; past this degree the time-varying
#: random-geometric schedule (``graph_schedule='random_geometric'`` +
#: ``graph_degree``) is MANDATORY — its sparse data-indexed exchange
#: (rcmarl_tpu.ops.exchange) costs ``O(n · graph_degree · P)`` instead,
#: the scaling the AUDIT.jsonl ``consensus_exchange`` ledger rows pin.
#: The limit equals the largest measured dense cell (n64_full), so
#: every historical config compiles unchanged.
DENSE_DEGREE_LIMIT = 64


#: Valid replica gossip graphs (parallel/gossip.py:replica_in_nodes):
#: 'ring' = directed circulant of in-degree ``gossip_degree`` (incl.
#: self), 'full' = fully connected, 'random_geometric' = deterministic
#: unit-square positions from ``gossip_seed``, each replica wired to its
#: ``gossip_degree - 1`` nearest others.
GOSSIP_GRAPHS = ("ring", "full", "random_geometric")

#: Valid gossip mixing operators: 'trimmed' = the repo's resilient
#: clip-and-average (sanitized, H = gossip_H — the hardened default),
#: 'mean' = plain arithmetic mean (the unhardened comparison arm a
#: single NaN replica poisons).
GOSSIP_MIXES = ("trimmed", "mean")


class Roles:
    """Integer role codes for the agent behaviors. The first four are
    the reference's labels (``main.py:88-104``); ADAPTIVE is this
    framework's colluding omniscient adversary — it transmits a payload
    crafted against the trimmed mean from the CURRENT epoch's
    cooperative messages (``rcmarl_tpu.faults.adaptive_payload_tree``)
    instead of any fitted net, the natural stress test for ``H``."""

    COOPERATIVE = 0
    GREEDY = 1
    FAULTY = 2
    MALICIOUS = 3
    ADAPTIVE = 4

    BY_NAME = {
        "Cooperative": COOPERATIVE,
        "Greedy": GREEDY,
        "Faulty": FAULTY,
        "Malicious": MALICIOUS,
        "Adaptive": ADAPTIVE,
    }
    NAMES = {v: k for k, v in BY_NAME.items()}


def circulant_in_nodes(n_agents: int, degree: int) -> Tuple[Tuple[int, ...], ...]:
    """Directed circulant communication graph with self first.

    Generalizes the reference default
    ``[[0,1,2,3],[1,2,3,4],[2,3,4,0],[3,4,0,1],[4,0,1,2]]``
    (reference ``main.py:28``): agent i receives from
    ``(i, i+1, ..., i+degree-1) mod n``. ``degree`` counts the agent
    itself, matching the reference convention that the agent's own index
    appears first in its in-neighborhood.
    """
    if not 1 <= degree <= n_agents:
        raise ValueError(f"degree must be in [1, {n_agents}], got {degree}")
    return tuple(
        tuple((i + k) % n_agents for k in range(degree)) for i in range(n_agents)
    )


def full_in_nodes(n_agents: int) -> Tuple[Tuple[int, ...], ...]:
    """Fully-connected graph, self first (BASELINE.json config 3)."""
    return tuple(
        (i,) + tuple(j for j in range(n_agents) if j != i) for i in range(n_agents)
    )


def random_geometric_in_nodes(n: int, degree: int, seed) -> Tuple[Tuple[int, ...], ...]:
    """Deterministic random-geometric digraph, self first.

    ``n`` nodes get positions ~ U[0,1)^2 from ``default_rng(seed)``;
    each node is wired to itself plus its ``degree - 1`` nearest others
    (stable tie-break), so every row has exactly ``degree`` entries —
    a REGULAR graph, no padding/masking needed. ``seed`` may be an int
    or a tuple (e.g. ``(graph_seed, round)`` for per-round resampling).

    This is THE random-geometric builder of the framework: the replica
    gossip layer (:func:`rcmarl_tpu.parallel.gossip.replica_in_nodes`)
    and the agent-level time-varying communication schedule
    (:func:`scheduled_in_nodes`) both call it, so the two levels of the
    stack cannot drift apart.
    """
    import numpy as np

    if not 1 <= degree <= n:
        raise ValueError(
            f"random_geometric degree must be in [1, {n}], got {degree}"
        )
    rng = np.random.default_rng(seed)
    pos = rng.random((n, 2))
    out = []
    for i in range(n):
        d = np.linalg.norm(pos - pos[i], axis=1)
        d[i] = -1.0  # self sorts first
        order = np.argsort(d, kind="stable")
        out.append(tuple(int(j) for j in order[:degree]))
    return tuple(out)


def scheduled_in_nodes(cfg: "Config", block: int):
    """The (N, degree) int32 gather-index array of the time-varying
    communication graph active at training block ``block``.

    Host-side and deterministic in ``(graph_seed, block // graph_every)``
    alone, so a resumed run replays its exact graph sequence. The array
    is DATA to the jitted train block (``train_block(..., graph=...)``):
    every resample re-dispatches the same executable — the lint retrace
    case proves zero steady-state recompiles across resampled blocks.
    Rows are self-first with exactly ``cfg.resolved_graph_degree``
    entries (every neighborhood keeps ``n_in >= 2H+1`` by the Config
    validation), matching the static-graph gather layout.
    """
    import numpy as np

    if cfg.graph_schedule == "static":
        raise ValueError(
            "scheduled_in_nodes is only defined for a time-varying "
            "graph_schedule; the static topology is cfg.in_nodes"
        )
    rnd = int(block) // cfg.graph_every
    nodes = random_geometric_in_nodes(
        cfg.n_agents, cfg.resolved_graph_degree, (cfg.graph_seed, rnd)
    )
    return np.asarray(nodes, dtype=np.int32)


def schedule_window(cfg: "Config", start_block: int, n_blocks: int):
    """The stacked-schedule operand: the ``(S, N, degree)`` int32 block
    of the scheduled graphs active at blocks ``[start_block,
    start_block + n_blocks)`` — BITWISE the per-block
    :func:`scheduled_in_nodes` sequence by construction (it IS that
    sequence, stacked), which is what lets ``train_scanned`` run S
    scheduled blocks as one device launch with the window as plain
    scan data. Every slice passes the same host/device guard rails the
    host loop applies per block
    (:func:`rcmarl_tpu.ops.exchange.validate_graph`); resuming
    mid-sequence is just a different ``start_block`` — the window
    replays the global schedule bitwise (the hypothesis twins pin
    both properties, tests/test_sparse_fused.py)."""
    import numpy as np

    from rcmarl_tpu.ops.exchange import validate_graph

    if n_blocks < 1:
        raise ValueError(f"n_blocks={n_blocks} must be >= 1 (window length)")
    if start_block < 0:
        raise ValueError(f"start_block={start_block} must be >= 0")
    return np.stack(
        [
            np.asarray(
                validate_graph(
                    scheduled_in_nodes(cfg, start_block + b),
                    cfg.n_agents,
                    degree=cfg.resolved_graph_degree,
                    H=cfg.H,
                ),
                dtype=np.int32,
            )
            for b in range(n_blocks)
        ]
    )


@dataclass(frozen=True)
class Config:
    """Hyperparameters; defaults mirror reference ``main.py:25-44``.

    Note the reference's published runs (BASELINE.md) override
    ``slow_lr=0.002`` and ``n_episodes=4000`` (per phase); the code
    defaults here match the reference snapshot's code defaults.
    """

    # --- topology / cast ---
    n_agents: int = 5
    agent_roles: Tuple[int, ...] = (Roles.COOPERATIVE,) * 5
    in_nodes: Tuple[Tuple[int, ...], ...] = circulant_in_nodes(5, 4)
    # --- spaces ---
    n_actions: int = 5
    n_states: int = 2
    nrow: int = 5
    ncol: int = 5
    # --- schedule ---
    n_episodes: int = 7000
    max_ep_len: int = 20
    n_ep_fixed: int = 50
    n_epochs: int = 10
    # --- optimization ---
    slow_lr: float = 0.01
    fast_lr: float = 0.01
    batch_size: int = 200  # adversarial actor minibatch (reference adversarial_CAC_agents.py:41)
    buffer_size: int = 2000
    gamma: float = 0.9
    # --- resilience ---
    H: int = 0
    common_reward: bool = False
    # --- exploration (reference hardcodes mu=0.1: resilient_CAC_agents.py:208) ---
    eps_explore: float = 0.1
    # --- model ---
    hidden: Tuple[int, ...] = (20, 20)
    leaky_alpha: float = 0.1
    # --- environment selection (the env-zoo registry, rcmarl_tpu.envs) ---
    # Which environment the trainer/evaluator rolls: 'grid_world' (the
    # default — bit-for-bit the seed behavior, pinned), 'pursuit'
    # (cooperative pursuit of a fleeing evader), 'coverage' (spread to
    # cover a landmark layout), 'congestion' (goal navigation where
    # shared cells carry a literal per-step load cost). All envs are
    # pure-functional and JAX-native behind the same protocol
    # (envs/api.py), so every trainer/serving/bench path is
    # env-agnostic.
    env: str = "grid_world"
    # --- env behavior ---
    collision_physics: bool = False  # opt-in *intended* collision semantics
    scaling: bool = True
    randomize_state: bool = True
    # Congestion-world toll per OTHER agent sharing a cell (the load
    # price of envs/congestion.py; 1.0 = the env's historical default,
    # bit-for-bit). The Diff-DAC task axis scales this per task level
    # at trace-free runtime (CellSpec.task_scale), so one compiled
    # program trains over a whole load-level family.
    congestion_weight: float = 1.0
    # --- time-varying communication graphs ---
    # graph_schedule: 'static' (default) keeps the fixed `in_nodes`
    # topology compiled into the program — bit-for-bit the seed
    # behavior. 'random_geometric' REGENERATES the in-neighborhoods
    # every `graph_every` blocks as a deterministic random-geometric
    # digraph of in-degree `graph_degree` (incl. self; 0 = reuse the
    # static graph's n_in), seeded by (`graph_seed`, round). The
    # resampled indices are DATA to the jitted block (gather indices,
    # not program structure), so resampling causes ZERO recompiles
    # (lint --retrace case). Solo-trainer feature: rejected with
    # replicas / pipeline_depth; the device-scanned parallel trainers
    # raise loudly.
    graph_schedule: str = "static"
    graph_every: int = 1
    graph_degree: int = 0
    graph_seed: int = 0
    # --- adaptive (colluding) adversary ---
    # Payload magnitude of Roles.ADAPTIVE agents, in units of the
    # cooperative messages' per-coordinate spread: all colluding
    # adversaries transmit mean_coop + adaptive_scale * (max_coop -
    # min_coop) for every parameter coordinate
    # (rcmarl_tpu.faults.adaptive_payload_tree). Small values sit just
    # inside the trim bounds (the residual-influence stress test for
    # H); large values are the unbounded mean attack that destroys
    # H=0 consensus while H>=#adversaries-per-neighborhood absorbs it
    # (QUALITY.md "Adaptive colluding adversary").
    adaptive_scale: float = 10.0
    #: Reference-exact move clipping (both coordinates bounded by nrow-1,
    #: reference grid_world.py:55) — only differs from the default
    #: per-axis clip on non-square grids; see envs/grid_world.py.
    reference_clip: bool = False
    # --- adversary fit schedule (reference adversarial_CAC_agents.py:133,150,163,239,251) ---
    adv_fit_epochs: int = 10
    adv_fit_batch: int = 32
    # --- cooperative local fit (reference resilient_CAC_agents.py:118,136) ---
    coop_fit_steps: int = 5
    # Global-gradient-norm ceiling for the phase-I critic/TR SGD fits
    # (every arm: dual, netstack, fitstack XLA scan, fitstack Pallas
    # kernel — the clip lives in ops/fit + ops/pallas_fit so the
    # arm-vs-arm bitwise pins carry any value). 0.0 (default) traces no
    # clip ops at all — bit-for-bit the reference program. The
    # mega-population rail: the full-batch MSE gradient's Lipschitz
    # constant grows with the joint state-action width (~3*n_agents for
    # the TR net, unnormalized actions), so past n~64 the fixed
    # ``fast_lr`` exceeds the SGD stability bound 2/L and the raw
    # 5-step fit diverges to NaN on CLEAN runs; the n>=256 bench/chaos
    # cells set ``fit_clip=1.0`` (step norm <= fast_lr * fit_clip).
    fit_clip: float = 0.0
    seed: int = 300
    # --- consensus kernel implementation ---
    # 'xla' (default): log-depth tournament selection bounds + clip/mean
    # — bitwise-equal to the sort, and the measured epoch winner at
    # EVERY scale on CPU, including the dense n_in=64 graphs where the
    # earlier register-chain selection lost 0.64x (tournament: ref5_ring
    # 2.5x, n16_full 2.2x, n64_full 4.8x — PERF.md "sort vs select").
    # 'xla_sort': the original full jnp.sort bounds (the measured-
    # comparison arm for crossover refits, see ops/aggregation.py).
    # 'pallas': fused VMEM-resident selection kernel
    # (ops/pallas_aggregation.py), for large-N/large-model scale-out on
    # TPU. 'pallas_sort': the kernel's sorting-network arm.
    # 'pallas_interpret': selection kernel in interpreter mode (CPU
    # tests only).
    # 'pallas_fused' / 'pallas_fused_interpret': the ONE-KERNEL EPOCH
    # (ops/pallas_consensus.py) — phase-II gather + link-fault
    # injection + trim/clip/mean as a single VMEM-resident Pallas
    # program over the combined (n_in, P_critic + P_tr) pair block
    # (forces the stacked netstack layout; the projection einsum +
    # team head step stay XLA). Bitwise vs the XLA arm across the
    # sanitize matrix; corrupt_p > 0 plans route back to the XLA
    # reference arm (documented in ops/pallas_consensus.py).
    # Time-varying graph schedules run the SPARSE one-kernel epoch:
    # the scheduled (N, degree) indices ride the kernel as a
    # scalar-prefetch operand, bitwise vs the sparse_gather XLA arm.
    # Gated on the AUDIT.jsonl bytes_accessed ledger (lint --cost).
    # 'auto': 3-way measured-crossover choice keyed on (H, n_in,
    # volume) — pallas on TPU from volume >= 256 up, xla vs xla_sort by
    # the CPU-measured selection crossover elsewhere (currently: xla
    # everywhere — SELECT_MAX_N_IN is None); never the fused arms until
    # the queued TPU session measures them
    # (ops/aggregation.py:resolve_impl, BENCH_SCALING.md, PERF.md).
    consensus_impl: str = "xla"
    # --- consensus message-tree layout ---
    # 'flat' (default): every parameter leaf of a message tree is raveled
    # into ONE (n_in, P_total) block so each consensus epoch issues a
    # single select/clip/mean op sequence per tree (the layout the Pallas
    # kernel always used; now shared by the XLA paths). 'per_leaf': the
    # historical leaf-by-leaf dispatch, kept as the measured-comparison
    # arm. Bitwise identical — raveling is elementwise-neutral
    # (ops/aggregation.py:resilient_aggregate_tree).
    consensus_layout: str = "flat"
    # --- netstack: critic+TR as ONE stacked program ---
    # True: the whole critic+TR epoch operates on one stacked parameter
    # block — phase-I fits run as a single (net, agent)-vmapped scan
    # (critic inputs/first-layer rows zero-padded to the TR width;
    # exactly gradient-neutral), and phase-II consensus gathers, faults,
    # trims, clips and projects BOTH message trees as one combined
    # (n_in, P_critic + P_tr) block — every hot launch happens once per
    # epoch instead of twice. False: the historical dual-launch path,
    # kept as the measured comparison arm (it is also the only arm
    # `consensus_layout` affects; the netstack always uses the combined
    # flat block). 'auto' (default): a measured BACKEND policy, like
    # consensus_impl='auto' — stacked on TPU (where doubling the batch
    # of the MXU-underfilling 20-wide gemms is the win the stacking
    # buys), dual-launch elsewhere (measured on the 1-core CPU host: the
    # zero-padding widens the critic's dominant first-layer contraction
    # obs_dim -> sa_dim, ~+20% FLOPs, and a serial core has no batching
    # headroom to pay for it — PERF.md "netstack"). Outputs are pinned
    # leaf-for-leaf equivalent either way (tests/test_netstack.py), so
    # the policy is purely a speed choice.
    netstack: "bool | str" = "auto"
    # --- fitstack: ALL phase-I fit flavors as one fused scan ---
    # True: every fit flavor the scenario runs (cooperative critic+TR
    # full-batch fits, greedy critic+TR minibatch fits, malicious
    # compromised critic+TR minibatch fits, the malicious PRIVATE
    # critic minibatch fit) is stacked along a leading (flavor·net) row
    # axis and launched through ONE unified scan body per schedule
    # shape (ops/fit.py:fused_fit_scan): the full-batch flavor is
    # expressed as one identity-plan minibatch covering the whole
    # batch, the minibatch flavors draw their valid-first shuffles with
    # the dual arm's exact key structure, so the fused rows are pinned
    # leaf-for-leaf BITWISE against the PR-4 pair-fit arm
    # (tests/test_fitstack_properties.py). A mixed coop+adversary cast
    # has two schedule shapes (full-batch vs minibatch) and therefore
    # two fused launches — down from four; a homogeneous cast launches
    # exactly ONE scan for all its flavors. False: the PR-4 phase-I
    # arms (pair fits under netstack, per-tree fits on the dual arm).
    # 'auto' (default): the measured backend policy, netstack-style —
    # fused on TPU (batching the MXU-underfilling 20-wide gemms across
    # flavor rows is the Podracer win), the PR-4 arms elsewhere (the
    # serial-CPU measurement keeps the dual arm: padding the critic
    # rows to sa_dim costs FLOPs a single core cannot hide — PERF.md
    # "fitstack / bf16"). Orthogonal to `netstack`: fitstack owns
    # phase I, netstack then only governs the phase-II consensus
    # layout. 'pallas' / 'pallas_interpret' (FITSTACK_IMPLS): the
    # fit-scan Pallas kernel (ops/pallas_fit.py) — the fused rows'
    # parameters live VMEM-resident across the whole epochs x batches
    # schedule instead of round-tripping HBM as the XLA scan's carry
    # every step; fitted rows pinned leaf-for-leaf vs the XLA scan
    # (interpret on CPU, real lowering queued for the TPU session).
    fitstack: "bool | str" = "auto"
    # --- transport faults / graceful degradation ---
    # fault_plan: per-link transport-fault injection on the consensus
    # exchange (drop / stale replay / corruption / NaN-Inf bombs —
    # rcmarl_tpu.faults.FaultPlan), applied between the neighbor gather
    # and the aggregation. None (default) = clean transport, bit-for-bit
    # the seed behavior. consensus_sanitize: harden the aggregation
    # against non-finite payloads (NaN/±Inf entries become per-element
    # exclusions; < 2H+1 finite survivors keep the agent's own value) —
    # the defense arm for fault_plan, and for genuinely diverged
    # neighbors in clean runs.
    fault_plan: Optional[FaultPlan] = None
    consensus_sanitize: bool = False
    # --- gossip-replicated learners (parallel/gossip.py) ---
    # replicas: number of learner replicas trained as one vmapped
    # seed-axis program (0, the default, disables the replica layer
    # entirely — the solo trainer path is untouched). gossip_every:
    # mix the replicas' parameter trees every K blocks through the
    # trimmed-mean block (0 = never mix: independent replicas, bitwise
    # the parallel/seeds.py behavior). gossip_graph/gossip_degree: the
    # replica communication graph (GOSSIP_GRAPHS). gossip_H: the
    # replica-level trim parameter — up to gossip_H Byzantine/corrupted
    # replicas per gossip neighborhood are trimmed away exactly as H
    # adversarial agents are trimmed in-graph. gossip_mix: 'trimmed'
    # (hardened default) or 'mean' (unhardened comparison arm).
    # gossip_seed namespaces the gossip streams (random-geometric
    # positions, replica fault draws) independently of the training
    # seeds. replica_fault_plan: the replica-level threat model
    # (rcmarl_tpu.faults.ReplicaFaultPlan); None = clean gossip links.
    replicas: int = 0
    gossip_every: int = 1
    gossip_graph: str = "ring"
    gossip_degree: int = 3
    gossip_H: int = 1
    gossip_mix: str = "trimmed"
    gossip_seed: int = 0
    replica_fault_plan: Optional[ReplicaFaultPlan] = None
    # --- Diff-DAC multitask axis (parallel/gossip.py) ---
    # task_axis=True turns the vmapped replica/seed axis into a TASK
    # axis (Diff-DAC, PAPERS.md 1710.10363): replica r trains on the
    # congestion world at load level task_levels[r] (the level scales
    # the congestion toll as traced CellSpec.task_scale data — one
    # compiled program for the whole task family), and the existing
    # gossip mix doubles as the cross-task consensus step Diff-DAC
    # prescribes — the trimmed mean over tasks' parameter blocks.
    # task_levels: one positive toll multiplier per replica; () =
    # linspace(0.5, 2.0, replicas) (resolved_task_levels). Requires
    # replicas >= 2, env='congestion', a static graph schedule, no
    # pipeline tier, no ADAPTIVE cast, and the XLA consensus family
    # (the traced-spec program shares the fused-matrix constraints).
    task_axis: bool = False
    task_levels: Tuple[float, ...] = ()
    # --- async actor-learner pipeline (rcmarl_tpu.pipeline) ---
    # pipeline_depth: how many rollout blocks the actor tier runs AHEAD
    # of the learner tier (the Podracer/TorchBeast split). 0 (default) =
    # synchronous handoff: the fused one-launch train block, bit-for-bit
    # the historical train() behavior — the pinned reference arm.
    # 1 = decoupled actor/learner programs with a direct (staleness-0)
    # handoff; >= 2 = genuinely pipelined: rollout block b+depth is
    # dispatched while epoch b+1 runs, so rollout cost hides in the
    # epoch's shadow at the price of acting on parameters
    # depth-1 (+ publish lag) updates stale. Staleness is COUNTED per
    # block (df.attrs['pipeline'], train summary line), never silent.
    # publish_every: the learner publishes its parameters to the actor
    # tier every K blocks (the in-memory twin of the serving
    # checkpoint hot-swap chain — validate fully, then swap the single
    # acting-params reference wholesale). K > 1 adds up to K-1 blocks
    # of staleness on top of the depth: the measured off-policy axis
    # the staleness quality cell sweeps (QUALITY.md).
    pipeline_depth: int = 0
    publish_every: int = 1
    # --- composed pipelined gossip fleet (parallel/gala.py) ---
    # Setting replicas > 0 AND pipeline_depth > 0 selects the composed
    # GALA topology: R gossiping learner replicas, each fed by its own
    # actor tier running pipeline_depth blocks ahead, trimmed gossip
    # mixes at segment boundaries, and (optionally) the winning
    # replica's policy admitted into serving through a CanaryGate.
    # canary_band: relative return band for the composed run's canary
    # admission gate (0.0, the default, disables the gate — every
    # finite winner publishes). canary_blocks: frozen-policy evaluation
    # blocks per canary decision. Both are composed-topology knobs:
    # canary_band > 0 outside replicas>0 && pipeline_depth>0 is
    # rejected loudly (solo serving has its own --canary_band on the
    # serve parser; this one gates the TRAINING-side deploy publisher).
    canary_band: float = 0.0
    canary_blocks: int = 1
    # --- matmul compute precision ---
    # 'float32' (default): true-fp32 dots, the reference-parity path.
    # 'bfloat16': opt-in scale-out mode — matmul inputs in the MXU's
    # native bf16, f32 accumulation; params/activations/optimizer stay
    # f32 (models/mlp.py:dot). For the 256-wide BASELINE config, not for
    # parity runs.
    compute_dtype: str = "float32"

    def __post_init__(self):
        if len(self.agent_roles) != self.n_agents:
            raise ValueError("agent_roles length must equal n_agents")
        if len(self.in_nodes) != self.n_agents:
            raise ValueError("in_nodes length must equal n_agents")
        for i, nbrs in enumerate(self.in_nodes):
            if nbrs[0] != i:
                raise ValueError(
                    f"in_nodes[{i}] must list the agent itself first "
                    "(reference convention, main.py:28)"
                )
            # H must be valid in EVERY neighborhood (heterogeneous
            # in-degrees allowed, as the reference accepts arbitrary
            # adjacency lists — main.py:28)
            if not 0 <= 2 * self.H <= len(nbrs) - 1:
                raise ValueError(
                    f"H={self.H} too large for in_nodes[{i}] of degree "
                    f"{len(nbrs)}: need 2H <= degree-1"
                )
        if self.env not in ENV_NAMES:
            raise ValueError(
                f"env={self.env!r}: expected one of {ENV_NAMES} "
                "(the rcmarl_tpu.envs registry keys)"
            )
        if self.env != "grid_world" and (
            self.collision_physics or self.reference_clip
        ):
            # grid-world-only semantics; silently ignoring them would
            # let a user believe they are active (loud-rejection
            # convention, like graph_schedule vs replicas)
            raise ValueError(
                f"collision_physics/reference_clip are grid_world-only "
                f"knobs; env={self.env!r} does not implement them"
            )
        if self.graph_schedule not in GRAPH_SCHEDULES:
            raise ValueError(
                f"graph_schedule={self.graph_schedule!r}: expected one "
                f"of {GRAPH_SCHEDULES}"
            )
        if self.graph_every < 1:
            raise ValueError(
                f"graph_every={self.graph_every} must be >= 1 "
                "(resample cadence in blocks)"
            )
        if not 0 <= self.graph_degree <= self.n_agents:
            raise ValueError(
                f"graph_degree={self.graph_degree} must be in "
                f"[0, n_agents={self.n_agents}] (0 = reuse the static "
                "graph's n_in; degree counts the agent itself)"
            )
        if self.graph_schedule != "static":
            deg = self.resolved_graph_degree
            if not 0 <= 2 * self.H <= deg - 1:
                raise ValueError(
                    f"H={self.H} too large for a resampled "
                    f"random_geometric graph of in-degree {deg}: need "
                    "2H <= degree-1 in EVERY neighborhood (rows are "
                    "regular by construction)"
                )
            if self.replicas or self.pipeline_depth:
                raise ValueError(
                    "graph_schedule='random_geometric' is a "
                    "solo-trainer feature (the per-block resample "
                    "lives in the host loop); run with replicas=0 and "
                    "pipeline_depth=0"
                )
        if self.graph_schedule == "static" and self.n_in > DENSE_DEGREE_LIMIT:
            # mega-population guard rail: a static dense neighborhood
            # compiles an (N, n_in, P) exchange quadratic in the
            # population — past the largest measured dense cell the
            # sparse scheduled exchange is mandatory
            raise ValueError(
                f"static in-neighborhoods of degree {self.n_in} exceed "
                f"DENSE_DEGREE_LIMIT={DENSE_DEGREE_LIMIT}: the dense "
                "(N, n_in, P) exchange is quadratic at this scale. Use "
                "graph_schedule='random_geometric' with a bounded "
                "graph_degree (the sparse O(n*deg*P) exchange, "
                "rcmarl_tpu.ops.exchange)"
            )
        if not float(self.adaptive_scale) >= 0.0:
            raise ValueError(
                f"adaptive_scale={self.adaptive_scale} must be >= 0"
            )
        if not float(self.congestion_weight) >= 0.0:
            raise ValueError(
                f"congestion_weight={self.congestion_weight} must be >= 0"
            )
        if not float(self.fit_clip) >= 0.0:
            raise ValueError(f"fit_clip={self.fit_clip} must be >= 0")
        if self.task_levels and not self.task_axis:
            raise ValueError(
                "task_levels without task_axis=True would be silently "
                "ignored; set task_axis=True (the Diff-DAC arm) or drop "
                "the levels"
            )
        if self.task_axis:
            if self.replicas < 2:
                raise ValueError(
                    "task_axis=True needs replicas >= 2 (the replica "
                    "axis IS the task axis; one task is just train())"
                )
            if self.env != "congestion":
                raise ValueError(
                    f"task_axis=True varies the congestion toll per task "
                    f"level; env={self.env!r} has no load knob (use "
                    "env='congestion')"
                )
            if self.pipeline_depth:
                raise ValueError(
                    "task_axis=True rides the gossip replica program; "
                    "the composed pipeline tier (pipeline_depth > 0) "
                    "does not thread per-replica task specs"
                )
            if self.task_levels and len(self.task_levels) != self.replicas:
                raise ValueError(
                    f"task_levels has {len(self.task_levels)} entries "
                    f"for replicas={self.replicas}; need one level per "
                    "replica (or () for the linspace default)"
                )
            if self.task_levels and not all(
                float(l) > 0.0 for l in self.task_levels
            ):
                raise ValueError(
                    f"task_levels={self.task_levels} must all be > 0 "
                    "(toll multipliers)"
                )
            if Roles.ADAPTIVE in self.agent_roles:
                raise ValueError(
                    "task_axis=True traces the scenario as CellSpec "
                    "data, which does not model the ADAPTIVE colluding "
                    "adversary (the fused-matrix constraint)"
                )
            if self.consensus_impl not in ("xla", "xla_sort", "auto"):
                raise ValueError(
                    "task_axis=True runs consensus with a traced "
                    "CellSpec (the XLA family); consensus_impl="
                    f"{self.consensus_impl!r} cannot apply"
                )
        if self.consensus_impl not in CONSENSUS_IMPLS:
            raise ValueError(
                f"consensus_impl={self.consensus_impl!r}: expected one of "
                f"{CONSENSUS_IMPLS}"
            )
        if self.consensus_layout not in ("flat", "per_leaf"):
            raise ValueError(
                f"consensus_layout={self.consensus_layout!r}: expected "
                "'flat' or 'per_leaf'"
            )
        if not (isinstance(self.netstack, bool) or self.netstack == "auto"):
            raise ValueError(
                f"netstack={self.netstack!r}: expected True, False, or "
                "'auto' (the measured backend policy)"
            )
        if not (
            isinstance(self.fitstack, bool)
            or self.fitstack == "auto"
            or self.fitstack in FITSTACK_IMPLS
        ):
            raise ValueError(
                f"fitstack={self.fitstack!r}: expected True, False, "
                f"'auto' (the measured backend policy), or one of "
                f"{FITSTACK_IMPLS} (the fit-scan Pallas kernel arms)"
            )
        if self.consensus_impl in FUSED_CONSENSUS_IMPLS:
            # the one-kernel epoch consumes the stacked pair layout;
            # contradictory knobs are rejected loudly rather than
            # silently overridden. Time-varying graph schedules are
            # first-class here: the scheduled (N, degree) indices ride
            # the kernel as a scalar-prefetch operand (the SPARSE
            # one-kernel epoch, ops/pallas_consensus.py) — gather
            # indices stay data, so resampling never recompiles.
            if self.netstack is False:
                raise ValueError(
                    f"consensus_impl={self.consensus_impl!r} runs phase II "
                    "on the combined (n_in, P_critic + P_tr) pair block; "
                    "netstack=False contradicts it (use True or 'auto' — "
                    "the fused epoch forces the stacked layout)"
                )
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"compute_dtype={self.compute_dtype!r}: expected "
                "'float32' or 'bfloat16'"
            )
        if self.fault_plan is not None and not isinstance(
            self.fault_plan, FaultPlan
        ):
            raise ValueError(
                "fault_plan must be a rcmarl_tpu.faults.FaultPlan "
                f"(got {type(self.fault_plan).__name__}); dicts don't "
                "hash and would break jit-staticness"
            )
        if self.pipeline_depth < 0:
            raise ValueError(
                f"pipeline_depth={self.pipeline_depth} must be >= 0 "
                "(0 = synchronous handoff, the reference arm)"
            )
        if self.publish_every < 1:
            raise ValueError(
                f"publish_every={self.publish_every} must be >= 1 "
                "(the learner publishes at least every K blocks; an "
                "actor that never refreshes is not an experiment arm)"
            )
        if self.replicas < 0:
            raise ValueError(f"replicas={self.replicas} must be >= 0")
        if self.canary_band < 0:
            raise ValueError(
                f"canary_band={self.canary_band} must be >= 0 "
                "(0 = composed deploy gate off)"
            )
        if self.canary_blocks < 1:
            raise ValueError(
                f"canary_blocks={self.canary_blocks} must be >= 1 "
                "(frozen-policy evaluation blocks per canary decision)"
            )
        if self.canary_band and not (self.replicas and self.pipeline_depth):
            raise ValueError(
                f"canary_band={self.canary_band} gates the composed "
                "pipelined-gossip deploy publisher (parallel/gala.py); "
                "it requires replicas > 0 AND pipeline_depth > 0 "
                "(solo serving has its own serve-parser --canary_band)"
            )
        if self.replicas and self.pipeline_depth and self.gossip_every:
            # The composed topology drains each replica's in-flight
            # actor windows before a mix round (mixed params would
            # otherwise race queued windows rolled under pre-mix
            # policies with no counter owning the skew). A segment
            # shorter than the pipeline depth would drain the queue
            # every round and never reach steady state.
            if self.pipeline_depth > self.gossip_every:
                raise ValueError(
                    f"pipeline_depth={self.pipeline_depth} > "
                    f"gossip_every={self.gossip_every}: composed "
                    "pipelined-gossip segments must be at least as long "
                    "as the pipeline depth (the actor tier drains at "
                    "each mix boundary; a shorter segment never "
                    "pipelines). Raise gossip_every or lower the depth."
                )
        if self.gossip_every < 0:
            raise ValueError(
                f"gossip_every={self.gossip_every} must be >= 0 "
                "(0 = never mix)"
            )
        if self.gossip_graph not in GOSSIP_GRAPHS:
            raise ValueError(
                f"gossip_graph={self.gossip_graph!r}: expected one of "
                f"{GOSSIP_GRAPHS}"
            )
        if self.gossip_mix not in GOSSIP_MIXES:
            raise ValueError(
                f"gossip_mix={self.gossip_mix!r}: expected one of "
                f"{GOSSIP_MIXES}"
            )
        if self.replica_fault_plan is not None and not isinstance(
            self.replica_fault_plan, ReplicaFaultPlan
        ):
            raise ValueError(
                "replica_fault_plan must be a "
                "rcmarl_tpu.faults.ReplicaFaultPlan "
                f"(got {type(self.replica_fault_plan).__name__})"
            )
        if self.replicas:
            if self.gossip_graph != "full" and not (
                1 <= self.gossip_degree <= self.replicas
            ):
                raise ValueError(
                    f"gossip_degree={self.gossip_degree} must be in "
                    f"[1, replicas={self.replicas}] (degree counts the "
                    "replica itself, like in_nodes; 'full' ignores it)"
                )
            # The trimmed mix needs 2*gossip_H <= n_in - 1 in every
            # gossip neighborhood, exactly like the in-graph H check.
            if not 0 <= 2 * self.gossip_H <= self.gossip_n_in - 1:
                raise ValueError(
                    f"gossip_H={self.gossip_H} too large for a "
                    f"{self.gossip_graph!r} replica graph of in-degree "
                    f"{self.gossip_n_in}: need 2*gossip_H <= degree-1"
                )
            if self.replica_fault_plan is not None:
                bad = [
                    b
                    for b in self.replica_fault_plan.byzantine_replicas
                    if b >= self.replicas
                ]
                if bad:
                    raise ValueError(
                        f"replica_fault_plan.byzantine_replicas={bad} "
                        f"out of range for replicas={self.replicas}"
                    )

    # ---- derived (static) quantities ----

    @property
    def n_in(self) -> int:
        """Max in-degree (the padded neighbor-axis size for irregular
        graphs; for regular graphs, THE in-degree)."""
        return max(len(nbrs) for nbrs in self.in_nodes)

    @property
    def in_degrees(self) -> Tuple[int, ...]:
        return tuple(len(nbrs) for nbrs in self.in_nodes)

    @property
    def resolved_graph_degree(self) -> int:
        """In-degree (incl. self) of the resampled time-varying graph:
        ``graph_degree`` when set, else the static graph's
        :attr:`n_in` (so switching the schedule on keeps the gather
        shape — and therefore the compiled program's input avals —
        unchanged)."""
        return self.graph_degree if self.graph_degree else self.n_in

    @property
    def resolved_task_levels(self) -> Tuple[float, ...]:
        """The Diff-DAC toll multiplier per replica when
        :attr:`task_axis` is set: ``task_levels`` verbatim when given,
        else an even spread over [0.5, 2.0] — one load level per
        replica, the family the single compiled program trains over."""
        if not self.task_axis:
            return ()
        if self.task_levels:
            return tuple(float(l) for l in self.task_levels)
        r = self.replicas
        return tuple(0.5 + 1.5 * i / (r - 1) for i in range(r))

    @property
    def gossip_n_in(self) -> int:
        """In-degree (incl. self) of the replica gossip graph — the
        neighbor-axis size of the replica-level trimmed-mean mix."""
        return self.replicas if self.gossip_graph == "full" else self.gossip_degree

    @property
    def regular_graph(self) -> bool:
        """True when every agent has the same in-degree — the fast path
        with no edge-validity masking."""
        return len(set(self.in_degrees)) == 1

    @property
    def uniform_shifts(self) -> "Tuple[int, ...] | None":
        """Shift set S (with S[0] == 0) such that every agent's
        in-neighborhood is ``{(i + s) % N for s in S}`` as a multiset —
        i.e. the graph is vertex-transitive under rotation (circulant
        graphs of any degree, including the fully-connected graph).

        When present, the consensus gather can be expressed as ``n_in``
        static rolls of the stacked message arrays instead of a fancy
        index: under an agent-sharded mesh, XLA lowers a sharded roll to
        a ring collective-permute of just the (shift)-row halo, where the
        general gather all-gathers ALL N agents' parameters to every
        shard (measured: 64-row all-gather vs 1-3-row permutes at N=64,
        degree 4 — see PARALLELISM.md). Returns None for graphs without
        this structure (they use the general gather).

        The reordering is safe because resilient aggregation is
        permutation-invariant in the non-self neighbors (the kernel
        sorts); only index 0 (self, shift 0) is positional.
        """
        if not self.regular_graph:
            return None
        N = self.n_agents
        base = tuple(sorted((j - 0) % N for j in self.in_nodes[0]))
        for i, nbrs in enumerate(self.in_nodes):
            if tuple(sorted((j - i) % N for j in nbrs)) != base:
                return None
        return base  # 0 first: self is always present, shifts in [0, N)

    def padded_in_nodes(self):
        """(in_arr, valid) as nested tuples, each row padded to
        :attr:`n_in`: padded slots repeat the agent's own index (a
        harmless gather target) and are zero in ``valid``. ``valid`` is
        None for regular graphs (fast path, no masking)."""
        n_in = self.n_in
        in_arr = tuple(
            nbrs + (i,) * (n_in - len(nbrs))
            for i, nbrs in enumerate(self.in_nodes)
        )
        if self.regular_graph:
            return in_arr, None
        valid = tuple(
            (1.0,) * len(nbrs) + (0.0,) * (n_in - len(nbrs))
            for nbrs in self.in_nodes
        )
        return in_arr, valid

    @property
    def dot_dtype(self) -> "str | None":
        """Matmul compute dtype for :func:`rcmarl_tpu.models.mlp.dot`:
        ``None`` = exact f32 (parity default), ``'bfloat16'`` = MXU-native
        inputs with f32 accumulation (kept a string so Config stays
        jax-free and hashable)."""
        return "bfloat16" if self.compute_dtype == "bfloat16" else None

    @property
    def obs_dim(self) -> int:
        """Flattened global-state input dim of actor/critic (N * n_states)."""
        return self.n_agents * self.n_states

    @property
    def sa_dim(self) -> int:
        """Flattened state-action input dim of the team-reward net."""
        return self.n_agents * (self.n_states + 1)

    @property
    def buffer_capacity(self) -> int:
        """Steady-state sample count seen by an update block: kept buffer
        plus one fresh block (reference train_agents.py:86,158-163)."""
        return self.buffer_size + self.n_ep_fixed * self.max_ep_len

    @property
    def block_steps(self) -> int:
        """Env steps collected between update blocks."""
        return self.n_ep_fixed * self.max_ep_len

    @property
    def coop_mask(self) -> Tuple[bool, ...]:
        return tuple(r == Roles.COOPERATIVE for r in self.agent_roles)

    @property
    def n_coop(self) -> int:
        return sum(self.coop_mask)

    @property
    def n_adv(self) -> int:
        return self.n_agents - self.n_coop

    def has_role(self, role: int) -> bool:
        return role in self.agent_roles

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)

    @classmethod
    def from_labels(cls, labels, **kw) -> "Config":
        """Build from reference-style string labels, e.g.
        ``['Cooperative']*4 + ['Malicious']``."""
        roles = tuple(Roles.BY_NAME[l] for l in labels)
        return cls(agent_roles=roles, n_agents=len(roles), **kw)
