"""rcmarl_tpu — TPU-native resilient consensus multi-agent RL.

A from-scratch JAX/XLA framework with the capabilities of the RPBCAC
reference implementation (mfigura/Resilient-consensus-based-MARL):
decentralized actor-critic training of N networked agents reaching
Byzantine-resilient consensus on critic and team-reward estimates via
clip-and-average (trimmed-mean) aggregation and projection-based updates
over a directed communication graph, with first-class injection of
greedy / faulty / malicious adversaries and an H-trimming defense.

Design (see SURVEY.md §7): all agents' parameters are stacked along a
leading agent axis; heterogeneous agent behavior is expressed through
static role partitions and masked updates so every phase — rollout,
local TD fits, neighbor exchange, sort/clip/mean consensus, projection,
actor updates — runs as vmapped/jitted XLA programs. Independent
training seeds are vmapped/sharded across TPU cores.
"""

__version__ = "0.5.0"

from rcmarl_tpu.config import (  # noqa: F401
    Config,
    Roles,
    circulant_in_nodes,
    full_in_nodes,
)
from rcmarl_tpu.faults import (  # noqa: F401
    FaultDiag,
    FaultPlan,
    ReplicaFaultPlan,
    apply_link_faults,
    apply_replica_faults,
    fault_diagnostics,
    tree_all_finite,
    tree_finite_per_replica,
)

# Heavier layers (jax-compiled trainers, the reference compat twins) are
# imported lazily so `import rcmarl_tpu` stays cheap; the canonical entry
# points are re-exported here for discoverability:
#   rcmarl_tpu.training.train / train_RPBCAC
#   rcmarl_tpu.parallel.train_parallel
#   rcmarl_tpu.serve.ServeEngine / serve_block / CheckpointWatcher
#   rcmarl_tpu.agents.Reference{RPBCAC,Faulty,Greedy,Malicious}Agent
#   rcmarl_tpu.envs.GridWorld / ReferenceGridWorld
