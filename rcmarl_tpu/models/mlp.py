"""Stacked-agent MLP actor/critic/team-reward networks.

TPU-native rebuild of the reference's per-agent Keras ``Sequential`` models
(reference ``main.py:56-82``): instead of N independent Keras objects, a
model family is ONE pytree whose leaves carry a leading agent axis, so all
N forward/backward passes run as a single vmapped XLA program (SURVEY.md §7
"Design stance").

Architecture (parity with reference ``main.py:60-82``):
  input -> flatten -> Dense(h1, LeakyReLU alpha=0.1) -> ... -> Dense(out)
with the actor adding a softmax head. The parameter pytree is a tuple of
``(W, b)`` layer pairs; the split ``trunk = layers[:-1]`` / ``head =
layers[-1]`` mirrors the reference's ``critic_features`` sub-model cut at
``layers[-2].output`` (``resilient_CAC_agents.py:39-40``) — load-bearing
for consensus, which treats hidden layers and the output layer differently.

Initialization matches Keras defaults (SURVEY.md §7 contract 5): Glorot
uniform kernels, zero biases.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

# An MLP's parameters: ((W1, b1), (W2, b2), ..., (Wk, bk)).
MLPParams = Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]

# This JAX build's default matmul precision is bf16-class even on CPU
# (~1e-3 relative error). The reference is pure fp32; curve parity and the
# golden tests require true fp32 dots. These models are tiny (20-wide), so
# HIGHEST costs nothing at reference scale; the 256-wide BASELINE config
# opts into MXU-native inputs via Config(compute_dtype='bfloat16') (the
# dtype parameter below).
PRECISION = jax.lax.Precision.HIGHEST


def dot(a: jnp.ndarray, b: jnp.ndarray, dtype=None) -> jnp.ndarray:
    """Model contraction at the configured compute precision.

    ``dtype=None`` (the default everywhere): true-fp32 matmul — the
    reference-parity path. With ``dtype=jnp.bfloat16``
    (``Config(compute_dtype='bfloat16')``, the opt-in scale-out mode for
    the 256-wide BASELINE config): both operands are cast to bf16 — the
    MXU's native input width — and accumulated in f32
    (``preferred_element_type``), the standard mixed-precision recipe.
    Parameters, activations, and optimizer state stay f32 either way;
    only the matmul inputs narrow.
    """
    if dtype is None:
        return jnp.matmul(a, b, precision=PRECISION)
    return jnp.matmul(
        a.astype(dtype), b.astype(dtype), preferred_element_type=jnp.float32
    )


def einsum(spec: str, *operands: jnp.ndarray, dtype=None) -> jnp.ndarray:
    """General contraction under the same precision policy as :func:`dot`
    (one place owns the mixed-precision recipe)."""
    if dtype is None:
        return jnp.einsum(spec, *operands, precision=PRECISION)
    return jnp.einsum(
        spec,
        *(o.astype(dtype) for o in operands),
        preferred_element_type=jnp.float32,
    )


def glorot_uniform(key: jax.Array, fan_in: int, fan_out: int) -> jnp.ndarray:
    """Keras default kernel init: U(-l, l), l = sqrt(6/(fan_in+fan_out))."""
    limit = jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(
        key, (fan_in, fan_out), minval=-limit, maxval=limit, dtype=jnp.float32
    )


def init_mlp(
    key: jax.Array, in_dim: int, hidden: Sequence[int], out_dim: int
) -> MLPParams:
    """Initialize one MLP: Glorot-uniform kernels, zero biases."""
    dims = [in_dim, *hidden, out_dim]
    keys = jax.random.split(key, len(dims) - 1)
    return tuple(
        (glorot_uniform(k, d_in, d_out), jnp.zeros((d_out,), jnp.float32))
        for k, d_in, d_out in zip(keys, dims[:-1], dims[1:])
    )


def init_stacked_mlp(
    key: jax.Array, n_agents: int, in_dim: int, hidden: Sequence[int], out_dim: int
) -> MLPParams:
    """Initialize N independent MLPs stacked on a leading agent axis
    (each agent draws its own init, as the reference builds N separate
    Keras models in a loop, ``main.py:59``)."""
    keys = jax.random.split(key, n_agents)
    return jax.vmap(lambda k: init_mlp(k, in_dim, hidden, out_dim))(keys)


def leaky_relu(x: jnp.ndarray, alpha: float = 0.1) -> jnp.ndarray:
    """LeakyReLU with the reference's alpha=0.1 (``main.py:63``)."""
    return jnp.where(x >= 0, x, alpha * x)


def trunk(params: MLPParams) -> MLPParams:
    """Hidden-layer parameters — the consensus 'hidden' block."""
    return params[:-1]


def head(params: MLPParams) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Output-layer parameters — the consensus 'estimate' block."""
    return params[-1]


def flatten_input(x: jnp.ndarray) -> jnp.ndarray:
    """Flatten all but the leading batch axis (Keras Flatten layer)."""
    return x.reshape(x.shape[0], -1)


def trunk_apply(
    trunk_params: MLPParams, x: jnp.ndarray, alpha: float = 0.1, dtype=None
) -> jnp.ndarray:
    """Apply a TRUNK-ONLY parameter tuple (no head layer) to an already
    flattened ``(batch, features)`` input. The netstacked consensus path
    uses this directly on the stacked (net, ...) trunk; everything else
    goes through :func:`trunk_forward`."""
    h = x
    for W, b in trunk_params:
        h = leaky_relu(dot(h, W, dtype) + b, alpha)
    return h


def trunk_forward(
    params: MLPParams, x: jnp.ndarray, alpha: float = 0.1, dtype=None
) -> jnp.ndarray:
    """Features phi(x) after the last hidden layer (the reference's
    ``critic_features`` / ``TR_features`` sub-models).

    Args:
      params: single-agent MLP pytree (no agent axis).
      x: (batch, ...) input; flattened internally.
      dtype: matmul compute dtype (see :func:`dot`).
    """
    return trunk_apply(params[:-1], flatten_input(x), alpha, dtype)


def head_forward(
    head_params: Tuple[jnp.ndarray, jnp.ndarray], phi: jnp.ndarray, dtype=None
) -> jnp.ndarray:
    W, b = head_params
    return dot(phi, W, dtype) + b


def mlp_forward(
    params: MLPParams, x: jnp.ndarray, alpha: float = 0.1, dtype=None
) -> jnp.ndarray:
    """Full forward pass -> (batch, out_dim) linear output."""
    return head_forward(params[-1], trunk_forward(params, x, alpha, dtype), dtype)


def actor_probs(
    params: MLPParams, x: jnp.ndarray, alpha: float = 0.1, dtype=None
) -> jnp.ndarray:
    """Softmax policy probabilities (reference actor, ``main.py:65``)."""
    return jax.nn.softmax(mlp_forward(params, x, alpha, dtype), axis=-1)


def agent_slice(params: MLPParams, i) -> MLPParams:
    """Select agent i's parameters from a stacked pytree."""
    return jax.tree.map(lambda a: a[i], params)


# --------------------------------------------------------------------------
# Netstack: critic + TR as ONE stacked parameter block
# --------------------------------------------------------------------------
#
# The critic (input obs_dim) and team-reward net (input sa_dim) share
# every dimension except the first-layer input width. Zero-padding the
# narrower net's first-layer rows (and its input columns) to the common
# width makes the two nets stackable along a leading net axis, so one
# (net, agent)-vmapped program fits/evaluates BOTH families at once —
# and the padding is exactly neutral: padded input columns are exact
# zeros, so padded first-layer rows receive bitwise-zero gradients and
# stay zero through any number of SGD steps
# (tests/test_netstack_properties.py pins this).


def pad_features(x: jnp.ndarray, width: int) -> jnp.ndarray:
    """Zero-pad the trailing feature axis of ``x`` up to ``width``."""
    d = x.shape[-1]
    if d == width:
        return x
    if d > width:
        raise ValueError(f"cannot pad feature dim {d} down to {width}")
    pad = [(0, 0)] * (x.ndim - 1) + [(0, width - d)]
    return jnp.pad(x, pad)


def pad_rows(W: jnp.ndarray, rows: int) -> jnp.ndarray:
    """Zero-pad the input-row axis (``-2``) of a kernel up to ``rows``."""
    d = W.shape[-2]
    if d == rows:
        return W
    pad = [(0, 0)] * W.ndim
    pad[-2] = (0, rows - d)
    return jnp.pad(W, pad)


def netstack_stack_rows(nets: Sequence[MLPParams]) -> MLPParams:
    """Stack ANY number of MLP families along a NEW leading row axis.

    All families must agree in depth and in every layer shape except the
    first-layer input width, which is zero-padded up to the widest (both
    for kernels with and without a leading agent axis — only the ``-2``
    axis of the first kernel is padded). Leaves of the result are
    ``(len(nets), ...)``-leading; recover the originals with
    :func:`netstack_split_rows`. The fitstack fused scan stacks one row
    per (flavor, net) here; :func:`netstack_stack` is the 2-row case.
    """
    nets = tuple(nets)
    if not nets:
        raise ValueError("netstack_stack_rows needs at least one net")
    depths = {len(n) for n in nets}
    if len(depths) != 1:
        raise ValueError(
            f"netstack requires equal depth, got {sorted(depths)} layers"
        )
    width = max(n[0][0].shape[-2] for n in nets)
    padded = tuple(
        ((pad_rows(n[0][0], width), n[0][1]),) + tuple(n[1:]) for n in nets
    )
    return jax.tree.map(lambda *ls: jnp.stack(ls), *padded)


def netstack_split_rows(
    stacked: MLPParams, in_dims: Sequence[int]
) -> Tuple[MLPParams, ...]:
    """Inverse of :func:`netstack_stack_rows`: slice each family back
    out, trimming each first-layer kernel to its own input width (the
    padded rows carry exact zeros, so the trim is lossless)."""

    def unstack(net: int, rows: int) -> MLPParams:
        p = jax.tree.map(lambda l: l[net], stacked)
        W1 = p[0][0]
        sl = (slice(None),) * (W1.ndim - 2) + (slice(0, rows), slice(None))
        return ((W1[sl], p[0][1]),) + tuple(p[1:])

    return tuple(unstack(i, rows) for i, rows in enumerate(in_dims))


def netstack_stack(a: MLPParams, b: MLPParams) -> MLPParams:
    """Stack two MLP families along a NEW leading net axis (the 2-row
    case of :func:`netstack_stack_rows`; the critic+TR netstack pair)."""
    return netstack_stack_rows((a, b))


def netstack_split(
    stacked: MLPParams, in_dims: Tuple[int, int]
) -> Tuple[MLPParams, MLPParams]:
    """Inverse of :func:`netstack_stack` (the 2-row case of
    :func:`netstack_split_rows`)."""
    a, b = netstack_split_rows(stacked, in_dims)
    return a, b
