"""Stacked-agent MLP actor/critic/team-reward networks.

TPU-native rebuild of the reference's per-agent Keras ``Sequential`` models
(reference ``main.py:56-82``): instead of N independent Keras objects, a
model family is ONE pytree whose leaves carry a leading agent axis, so all
N forward/backward passes run as a single vmapped XLA program (SURVEY.md §7
"Design stance").

Architecture (parity with reference ``main.py:60-82``):
  input -> flatten -> Dense(h1, LeakyReLU alpha=0.1) -> ... -> Dense(out)
with the actor adding a softmax head. The parameter pytree is a tuple of
``(W, b)`` layer pairs; the split ``trunk = layers[:-1]`` / ``head =
layers[-1]`` mirrors the reference's ``critic_features`` sub-model cut at
``layers[-2].output`` (``resilient_CAC_agents.py:39-40``) — load-bearing
for consensus, which treats hidden layers and the output layer differently.

Initialization matches Keras defaults (SURVEY.md §7 contract 5): Glorot
uniform kernels, zero biases.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

# An MLP's parameters: ((W1, b1), (W2, b2), ..., (Wk, bk)).
MLPParams = Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]

# This JAX build's default matmul precision is bf16-class even on CPU
# (~1e-3 relative error). The reference is pure fp32; curve parity and the
# golden tests require true fp32 dots. These models are tiny (20-wide), so
# HIGHEST costs nothing at reference scale; the 256-wide BASELINE config
# opts into MXU-native inputs via Config(compute_dtype='bfloat16') (the
# dtype parameter below).
PRECISION = jax.lax.Precision.HIGHEST


def dot(a: jnp.ndarray, b: jnp.ndarray, dtype=None) -> jnp.ndarray:
    """Model contraction at the configured compute precision.

    ``dtype=None`` (the default everywhere): true-fp32 matmul — the
    reference-parity path. With ``dtype=jnp.bfloat16``
    (``Config(compute_dtype='bfloat16')``, the opt-in scale-out mode for
    the 256-wide BASELINE config): both operands are cast to bf16 — the
    MXU's native input width — and accumulated in f32
    (``preferred_element_type``), the standard mixed-precision recipe.
    Parameters, activations, and optimizer state stay f32 either way;
    only the matmul inputs narrow.
    """
    if dtype is None:
        return jnp.matmul(a, b, precision=PRECISION)
    return jnp.matmul(
        a.astype(dtype), b.astype(dtype), preferred_element_type=jnp.float32
    )


def einsum(spec: str, *operands: jnp.ndarray, dtype=None) -> jnp.ndarray:
    """General contraction under the same precision policy as :func:`dot`
    (one place owns the mixed-precision recipe)."""
    if dtype is None:
        return jnp.einsum(spec, *operands, precision=PRECISION)
    return jnp.einsum(
        spec,
        *(o.astype(dtype) for o in operands),
        preferred_element_type=jnp.float32,
    )


def glorot_uniform(key: jax.Array, fan_in: int, fan_out: int) -> jnp.ndarray:
    """Keras default kernel init: U(-l, l), l = sqrt(6/(fan_in+fan_out))."""
    limit = jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(
        key, (fan_in, fan_out), minval=-limit, maxval=limit, dtype=jnp.float32
    )


def init_mlp(
    key: jax.Array, in_dim: int, hidden: Sequence[int], out_dim: int
) -> MLPParams:
    """Initialize one MLP: Glorot-uniform kernels, zero biases."""
    dims = [in_dim, *hidden, out_dim]
    keys = jax.random.split(key, len(dims) - 1)
    return tuple(
        (glorot_uniform(k, d_in, d_out), jnp.zeros((d_out,), jnp.float32))
        for k, d_in, d_out in zip(keys, dims[:-1], dims[1:])
    )


def init_stacked_mlp(
    key: jax.Array, n_agents: int, in_dim: int, hidden: Sequence[int], out_dim: int
) -> MLPParams:
    """Initialize N independent MLPs stacked on a leading agent axis
    (each agent draws its own init, as the reference builds N separate
    Keras models in a loop, ``main.py:59``)."""
    keys = jax.random.split(key, n_agents)
    return jax.vmap(lambda k: init_mlp(k, in_dim, hidden, out_dim))(keys)


def leaky_relu(x: jnp.ndarray, alpha: float = 0.1) -> jnp.ndarray:
    """LeakyReLU with the reference's alpha=0.1 (``main.py:63``)."""
    return jnp.where(x >= 0, x, alpha * x)


def trunk(params: MLPParams) -> MLPParams:
    """Hidden-layer parameters — the consensus 'hidden' block."""
    return params[:-1]


def head(params: MLPParams) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Output-layer parameters — the consensus 'estimate' block."""
    return params[-1]


def flatten_input(x: jnp.ndarray) -> jnp.ndarray:
    """Flatten all but the leading batch axis (Keras Flatten layer)."""
    return x.reshape(x.shape[0], -1)


def trunk_forward(
    params: MLPParams, x: jnp.ndarray, alpha: float = 0.1, dtype=None
) -> jnp.ndarray:
    """Features phi(x) after the last hidden layer (the reference's
    ``critic_features`` / ``TR_features`` sub-models).

    Args:
      params: single-agent MLP pytree (no agent axis).
      x: (batch, ...) input; flattened internally.
      dtype: matmul compute dtype (see :func:`dot`).
    """
    h = flatten_input(x)
    for W, b in params[:-1]:
        h = leaky_relu(dot(h, W, dtype) + b, alpha)
    return h


def head_forward(
    head_params: Tuple[jnp.ndarray, jnp.ndarray], phi: jnp.ndarray, dtype=None
) -> jnp.ndarray:
    W, b = head_params
    return dot(phi, W, dtype) + b


def mlp_forward(
    params: MLPParams, x: jnp.ndarray, alpha: float = 0.1, dtype=None
) -> jnp.ndarray:
    """Full forward pass -> (batch, out_dim) linear output."""
    return head_forward(params[-1], trunk_forward(params, x, alpha, dtype), dtype)


def actor_probs(
    params: MLPParams, x: jnp.ndarray, alpha: float = 0.1, dtype=None
) -> jnp.ndarray:
    """Softmax policy probabilities (reference actor, ``main.py:65``)."""
    return jax.nn.softmax(mlp_forward(params, x, alpha, dtype), axis=-1)


def agent_slice(params: MLPParams, i) -> MLPParams:
    """Select agent i's parameters from a stacked pytree."""
    return jax.tree.map(lambda a: a[i], params)
